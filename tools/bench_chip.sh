#!/usr/bin/env bash
# One-command chip capture (round-17 satellite, ROADMAP bench item): run
# the FULL-config bench tiers on the real TPU and emit BENCH_r06.json in
# the same wrapper shape as the existing BENCH_r0*.json artifacts
# ({n, cmd, rc, tail, parsed}), plus a "rows" list with every parsed
# metric row — so the long-owed chip refresh (stale since PR 5) is a
# single command on real hardware.
#
# Round 18 adds the ann tier (bench_ann): IVF-ANN search vs the exact
# kneighbors ring — recall@10 >= 0.95 AND >= 3x speedup, one dispatch /
# zero transfers counter-asserted (DSLIB_ANN_RECALL_MIN /
# DSLIB_ANN_SPEEDUP_MIN override the floors).
#
# Round 19 adds the dcn tier (bench_dcn): the hierarchical DCN-aware
# rechunk under the DSLIB_MOCK_HOSTS overlay (the function sets it
# itself, scoped) — inter-host messages per step <= hosts-1 (coalesced,
# O(hosts) not O(panels)), dcn_bytes_moved == the deviceput floor,
# bit-equal to the flat panel exchange, rechunk_dcn schedule-counted.
# On a multi-PROCESS rig the same code path runs real host maps; see
# tools/run_multihost.sh for the two-process dryrun.
#
# Usage:  tools/bench_chip.sh [OUT_JSON] [ROUND_N]
#         OUT_JSON defaults to BENCH_r06.json, ROUND_N to the digits in
#         OUT_JSON's name.
#
# Must run on a rig with the TPU visible (bench.py's device probe aborts
# fast on a dead tunnel and replays the latest local capture as an
# explicit stale carryover — rc stays non-zero, so this script will NOT
# overwrite a previous fresh artifact with carryover rows).
set -u
set -o pipefail
cd "$(dirname "$0")/.." || exit 1
OUT="${1:-BENCH_r06.json}"
N="${2:-$(basename "$OUT" | tr -cd '0-9' | sed 's/^0*//')}"
# same persistent compile cache bench.py's children use: repeat captures
# skip the 20-40 s TPU compiles of unchanged configs
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
LOG="$(mktemp)"
CMD="python bench.py"
# full mode: BENCH_SMOKE must NOT be set — guard against an inherited one
unset BENCH_SMOKE
echo "=== chip capture -> $OUT (round $N): $CMD ===" >&2
$CMD 2> >(tail -40 >&2) | tee "$LOG"
RC=$?
python - "$LOG" "$OUT" "$N" "$CMD" "$RC" <<'EOF'
import json
import sys

log, out, n, cmd, rc = sys.argv[1:6]
rows = []
with open(log) as f:
    for line in f:
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
tail = open(log).read()[-4000:]
stale = [r["metric"] for r in rows if r.get("stale") and r.get("metric")]
doc = {"n": int(n), "cmd": cmd, "rc": int(rc), "tail": tail,
       "parsed": rows[-1] if rows else None, "rows": rows,
       "fresh_rows": sum(1 for r in rows if r.get("fresh")),
       "stale_rows": len(stale)}
with open(out, "w") as f:
    json.dump(doc, f, indent=1)
    f.write("\n")
print(f"wrote {out}: {len(rows)} rows "
      f"({doc['fresh_rows']} fresh, {len(stale)} stale), rc={rc}",
      file=sys.stderr)
if stale:
    print("WARNING: stale rows present (device probe fell back?) — this "
          "artifact is NOT a fresh chip capture:", file=sys.stderr)
    for m in stale[:10]:
        print(f"  stale: {m}", file=sys.stderr)
EOF
rm -f "$LOG"
exit "$RC"
