#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command plus `-rs` (report skip reasons:
# env-gated skips must be VISIBLE, not silent — round-8 satellite).  The
# extra flag only appends a "short test summary info" section, so the
# DOTS_PASSED green-dot count and the exit code are exactly the ROADMAP
# command's.  Run from the repo root:
#   tools/run_tier1.sh
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -rs -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo "-- env-gated skips (reasons) --"
grep -a "^SKIPPED" /tmp/_t1.log || echo "(none)"
# the multihost tier rots silently unless someone runs it: when this rig
# CAN host two jax.distributed CPU processes but the dryrun wasn't part
# of this invocation, say so in one line (round-19 satellite)
if [ -z "${DSLIB_MULTIHOST_TIER:-}" ] && python - <<'EOF' >/dev/null 2>&1
import jax.distributed  # the coordination service import, cheap
EOF
then
  echo "hint: jax.distributed is importable here — the two-process" \
       "multihost dryrun (rechunk parity, bundle load barrier, capacity" \
       "ledger) was NOT run; try: tools/run_multihost.sh"
fi
exit $rc
