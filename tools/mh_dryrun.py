#!/usr/bin/env python
"""Multi-host proof harness: the round-19 DCN data-plane dryrun AND the
round-20 process-killing chaos driver.

**Dryrun mode** (``mh_dryrun.py <rank> <nprocs> <port> <workdir>``) —
one rank of the two-process DCN data-plane dryrun (round 19).

**Chaos mode** (``mh_dryrun.py --chaos [workdir]``) — the round-20
survival drill: a parent driver spawns two REAL rank processes that
coordinate through the shared-directory ``FileCoordinator``
(``DSLIB_COORD_DIR``) with heartbeat leases, then

1. SIGKILLs rank 1 mid-fit (the rank kills ITSELF right after its first
   snapshot lands — a real, uncatchable ``SIGKILL`` at a deterministic
   point in the work stream); the survivor's lease keeper confirms the
   death, publishes the shrunk capacity target, and the survivor's fit
   shrinks (2,1)→(1,1) mid-fit and lands on the shrunk-fleet oracle;
2. RESTARTS rank 1: it rejoins under a bumped epoch (asserted), its
   stale pre-crash posts are fenced out of gathers (asserted), and the
   survivor's in-flight fit GROWS BACK to the home mesh;
3. delays heartbeats past the lease (the flap): the survivor counts a
   death AND a rejoin with no process restart;
4. tears coordination files and the capacity ledger mid-write: readers
   classify TRANSIENT, retry, and heal — never a fleet kill;
5. kills rank 1 again and drives the sharded-bundle load-barrier seam:
   the survivor aborts typed (``load barrier ABORTED``) within
   ``DSLIB_BARRIER_TIMEOUT`` — with membership the abort is immediate
   (attributed ``RankDead``), without it the deadline holds.  Zero
   hangs anywhere: every wait in the harness carries a hard deadline
   and the parent bounds every child.

Why the file transport and not ``jax.distributed``: probed on this
rig's jaxlib (0.4.36), SIGKILLing one rank of a ``jax.distributed``
fleet tears down the SURVIVORS too (the coordination-service disconnect
propagates as a fatal error), and overriding the missed-heartbeat
callback crashes in native code — so no survivable kill drill exists on
that transport here.  The membership/lease layer rides the coordinator
dslib owns; the chaos scenarios therefore run on the documented
shared-filesystem rig transport, and the round-19 dryrun below keeps
covering the ``jax.distributed`` KV path for healthy fleets.

**Dryrun mode** details — launched (twice) by
``tools/run_multihost.sh``: two REAL OS processes, each owning 2
virtual CPU devices, joined through ``jax.distributed.initialize`` — 4
global devices, the 'rows' mesh axis spanning the process (DCN)
boundary.  Each rank proves, for real:

1. **rechunk parity** — the hierarchical ``dcn`` schedule relays a
   deterministic global array across mesh shapes; every rank checks its
   addressable output shards bit-for-bit against the host-side oracle,
   and the analytic accounting invariants (messages/step ≤ hosts−1,
   bytes == deviceput floor) hold;
2. **sharded-bundle load barrier** — ``export_bundle(hosts=2)`` (each
   rank writes its own shard, rank 0 the manifest), a coordinated
   ``load_bundle`` where both ranks serve bit-correct predictions; then
   the poisoned episode: rank 1 corrupts ITS shard, and BOTH ranks
   raise the same typed ``BundleShardCorrupt`` — zero hosts serve;
3. **coherent capacity episode** — rank 0 publishes shrink(2) then
   grow(4) through the shared ``CapacityLedger``; both ranks observe
   the same level at each step (asserted by exchanging observations),
   with the ledger epoch strictly increasing.

Usage: ``mh_dryrun.py <rank> <nprocs> <port> <workdir>`` (dryrun),
``mh_dryrun.py --chaos [workdir]`` (chaos driver), or
``mh_dryrun.py --chaos-rank <rank> <phase> <workdir>`` (one chaos rank —
spawned by the driver, not by hand).  Exit 0 = green.
"""

import json
import os
import signal
import subprocess
import sys
import time


def log(rank, msg):
    print(f"[dryrun r{rank}] {msg}", flush=True)


def main():
    rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
    port, workdir = int(sys.argv[3]), sys.argv[4]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DSLIB_PROC_ID"] = str(rank)
    os.environ["DSLIB_CAPACITY_LEDGER"] = os.path.join(workdir,
                                                       "cap.ledger")
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import dislib_tpu as ds
    from dislib_tpu.ops import rechunk as rc
    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.runtime import BundleShardCorrupt, CapacityLedger
    from dislib_tpu.runtime.coord import get_coordinator
    from dislib_tpu.runtime.preemption import capacity_target
    from dislib_tpu.serving import ServePipeline, export_bundle, load_bundle

    ds.parallel.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nprocs, process_id=rank)
    assert jax.process_count() == nprocs, "distributed join failed"
    assert len(jax.local_devices()) == 2
    ds.init()                           # (4, 1): rows axis spans DCN
    coord = get_coordinator()
    log(rank, f"joined: {jax.device_count()} global devices, "
              f"coordinator={type(coord).__name__}")

    # ---- phase 1: hierarchical rechunk parity --------------------------
    # The only phase needing cross-process COLLECTIVES (the coordination
    # service used by phases 2/3 is platform-independent): jaxlib < 0.6
    # CPU backends raise "Multiprocess computations aren't implemented",
    # so the parity run is version-gated here — tier-1 still proves the
    # schedule bit-equal on every run through the DSLIB_MOCK_HOSTS
    # overlay (tests/test_multihost_dataplane.py).
    src = _mesh.get_mesh()
    m, n = 50, 6
    x = (np.arange(m * n, dtype=np.float32).reshape(m, n) * 0.5 - 7.0)
    pr = src.shape[_mesh.ROWS]
    mp = -(-m // pr) * pr
    xp = np.zeros((mp, n), np.float32)
    xp[:m] = x
    sh = _mesh.data_sharding(src)
    data = jax.make_array_from_callback((mp, n), sh, lambda idx: xp[idx])
    dst = Mesh(np.asarray(list(src.devices.flat)).reshape(2, 2),
               _mesh.AXIS_NAMES)
    assert rc.dcn_supported(data, dst), "hierarchical layout not detected"
    acct = rc.dcn_accounting(data, (m, n), dst)
    assert acct["hosts"] == nprocs
    assert acct["messages_per_step_max"] <= acct["hosts"] - 1
    assert acct["dcn_bytes_moved"] == acct["deviceput_bytes"]
    from dislib_tpu.runtime.xla_flags import _jaxlib_version
    v = _jaxlib_version()
    collectives_ok = (v is not None and v >= (0, 6, 0)) or \
        os.environ.get("DSLIB_FORCE_MP_TESTS") == "1"
    if collectives_ok:
        out, sched = rc.reshard(data, (m, n), dst, schedule="dcn")
        assert sched == "dcn"
        # oracle: the relayout is a pure re-partition of the logical array
        mp2 = -(-m // 2) * 2
        np2 = -(-n // 2) * 2
        oracle = np.zeros((mp2, np2), np.float32)
        oracle[:m, :n] = x
        for s in out.addressable_shards:
            np.testing.assert_array_equal(np.asarray(s.data),
                                          oracle[s.index],
                                          err_msg="dcn shard mismatch")
        log(rank, f"rechunk parity OK ({acct['dcn_messages']} DCN "
                  f"messages, {acct['dcn_bytes_moved']} bytes)")
    else:
        log(rank, "rechunk parity SKIPPED (this jaxlib's CPU backend "
                  "lacks multiprocess collectives) — accounting + "
                  "support gates checked; mock-host tier-1 carries "
                  "bit-equality")
    votes = coord.exchange("dryrun-rechunk", rank, True, n=nprocs)
    assert all(votes.values())

    # ---- phase 2: sharded bundle + load barrier ------------------------
    # Serving topology: each host serves ITS shard on ITS local devices
    # (the per-host serving mesh — what the sharded bundle's mesh
    # contract describes).  Everything below is collective-free: the
    # cross-process protocol rides the coordination service, compute
    # stays intra-host — so this phase runs for real on every rig.
    ds.init(mesh_shape=(len(jax.local_devices()), 1),
            devices=jax.local_devices())
    jax.clear_caches()
    NF = 4
    lr = ds.LinearRegression()
    lr.coef_ = np.arange(NF, dtype=np.float32).reshape(NF, 1)
    lr.intercept_ = np.full(1, 2.5, np.float32)
    pipe = ServePipeline(lr, n_features=NF)
    state = {"coef": lr.coef_, "intercept": lr.intercept_}
    good = os.path.join(workdir, "good.dsb.npz")
    export_bundle(pipe, good, buckets=(1, 8), state=state, hosts=nprocs)
    lb = load_bundle(good)
    assert not lb.fallback and lb.host == rank and lb.hosts == nprocs
    xq = np.linspace(0, 1, 3 * NF, dtype=np.float32).reshape(3, NF)
    got = lb.pipeline.predict_bucket(xq, 8)
    np.testing.assert_allclose(got, xq @ lr.coef_ + 2.5, atol=1e-5)
    log(rank, "sharded bundle served bit-correct after the barrier")

    bad = os.path.join(workdir, "bad.dsb.npz")
    export_bundle(pipe, bad, buckets=(1,), state=state, hosts=nprocs)
    if rank == 1:
        with open(bad + ".shard1", "r+b") as f:
            f.seek(64)
            f.write(b"\xde\xad\xbe\xef")
    coord.exchange("dryrun-corrupted", rank, True, n=nprocs)
    try:
        load_bundle(bad)
        raise AssertionError("corrupt shard served — barrier failed")
    except BundleShardCorrupt as e:
        assert e.host == 1, f"wrong host blamed: {e.host}"
    coord.exchange("dryrun-abort-seen", rank, True, n=nprocs)
    log(rank, "poisoned shard → typed abort on BOTH ranks, zero served")

    # ---- phase 3: coherent shrink→grow capacity episode ----------------
    ledger = CapacityLedger(os.environ["DSLIB_CAPACITY_LEDGER"])
    episodes = []
    for step, target in (("shrink", 2), ("grow", 4)):
        if rank == 0:
            ds.runtime.request_capacity(target)   # publishes to the ledger
        deadline = time.time() + 20
        seen, epoch = None, 0
        while time.time() < deadline:
            seen, epoch = ledger.read()
            if seen == target:
                break
            time.sleep(0.02)
        assert seen == target, f"{step}: rank {rank} saw {seen}"
        # the consumer-side view agrees (override on the writer, ledger
        # on everyone else — one coherent level either way)
        assert capacity_target() == target
        episodes.append((step, target, epoch))
        # every rank observed the same level AT the same ledger epoch —
        # the rank-0 writer publishes the next step only after this
        # barrier, so the recorded epochs are comparable fleet-wide
        obs = coord.exchange(f"dryrun-cap-{step}", rank, [seen, epoch],
                             n=nprocs)
        vals = {tuple(v) for v in obs.values()}
        assert vals == {(target, epoch)}, f"incoherent fleet: {obs}"
    assert episodes[0][2] < episodes[1][2], "ledger epoch not monotonic"
    log(rank, f"capacity episode coherent: {episodes}")

    with open(os.path.join(workdir, f"result.{rank}.json"), "w") as f:
        json.dump({"rank": rank, "rechunk": acct,
                   "episodes": episodes}, f)
    coord.exchange("dryrun-done", rank, True, n=nprocs)
    ds.parallel.shutdown()
    log(rank, "ALL PHASES GREEN")


# ===========================================================================
# round-20 chaos harness
# ===========================================================================

CHAOS_LEASE_MS = "1000"                 # short lease: deaths confirm fast
CHAOS_BARRIER_S = "6"                   # DSLIB_BARRIER_TIMEOUT for the drill


def clog(rank, msg):
    print(f"[chaos r{rank} +{time.monotonic() % 1e4:8.2f}] {msg}",
          flush=True)


def _wait_for(pred, deadline_s, what, poll=0.05):
    """Bounded wait — EVERY wait in the chaos harness goes through here,
    so 'zero hangs' is structural, not luck."""
    end = time.monotonic() + float(deadline_s)
    while True:
        v = pred()
        if v:
            return v
        if time.monotonic() >= end:
            raise AssertionError(f"HANG GUARD: {what} not observed "
                                 f"within {deadline_s}s")
        time.sleep(poll)


def _chaos_env_setup(workdir, rank):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DSLIB_PROC_ID"] = str(rank)
    os.environ["DSLIB_COORD_DIR"] = os.path.join(workdir, "coord")
    os.environ["DSLIB_CAPACITY_LEDGER"] = os.path.join(workdir,
                                                       "cap.ledger")
    os.environ.setdefault("DSLIB_COORD_LEASE_MS", CHAOS_LEASE_MS)
    os.environ.setdefault("DSLIB_BARRIER_TIMEOUT", CHAOS_BARRIER_S)
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _chaos_fit_setup():
    """The chaos fit: same KMeans shape as the tier-1 elastic scenarios
    (chunk results are mesh-size-independent, so ONE oracle serves every
    device set the fit lands on)."""
    import numpy as np
    rng = np.random.RandomState(0)
    centers = rng.rand(3, 4) * 10
    x_np = np.vstack([centers[i] + 0.3 * rng.randn(66, 4)
                      for i in range(3)]).astype(np.float32)
    init = np.ascontiguousarray(x_np[[0, 70, 140]])
    kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
    return x_np, kw


def chaos_rank0(workdir):
    """The SURVIVOR: observes the death, shrinks mid-fit, matches the
    shrunk-fleet oracle, grows back on the rejoin, survives the flap and
    the torn files, and aborts the load barrier typed when the peer dies
    at it."""
    _chaos_env_setup(workdir, 0)
    import numpy as np
    import jax

    import dislib_tpu as ds
    from dislib_tpu.cluster import KMeans
    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.runtime.coord import (CapacityLedger,
                                          CoordinationTimeout,
                                          FileCoordinator, LeaseKeeper,
                                          Membership, RankDead,
                                          barrier_timeout,
                                          get_coordinator,
                                          resilient_exchange,
                                          set_membership)
    from dislib_tpu.runtime.health import ChunkGuard, HealthPolicy
    from dislib_tpu.runtime.preemption import (capacity_target,
                                               clear_capacity)
    from dislib_tpu.serving.bundle import _barrier_exchange
    from dislib_tpu.utils import profiling as _prof
    from dislib_tpu.utils.checkpoint import FitCheckpoint
    from dislib_tpu.utils.faults import TornCoordWrite

    coord = get_coordinator()
    assert isinstance(coord, FileCoordinator), type(coord).__name__
    res = {"counters": None, "timings": {}}
    x_np, kw = _chaos_fit_setup()

    # the shrunk-fleet oracle: the SAME fit, clean, on one device —
    # computed before any membership machinery so no counter is touched
    ds.init((1, 1), devices=jax.devices()[:1])
    oracle = KMeans(**kw).fit(ds.array(x_np)).centers_
    clog(0, "shrunk-fleet oracle computed on (1,1)")

    class _GateAtChunk(HealthPolicy):
        """Admit-seam gate (the NaNAtChunk idiom): chunk ``at_chunk``
        does not dispatch until ``ready()`` — deterministic phasing for
        the rejoin-mid-fit scenario, through the production guard."""

        def __init__(self, at_chunk, ready, on_arm, **hkw):
            super().__init__(**hkw)
            self.at_chunk, self.ready = int(at_chunk), ready
            self.on_arm, self.fired = on_arm, 0

        def make_guard(self, name, checkpoint=None):
            pol = self

            class _G(ChunkGuard):
                def admit(self, *carries):
                    carries = super().admit(*carries)
                    if self.chunk_index >= pol.at_chunk and not pol.fired:
                        pol.fired = 1
                        pol.on_arm()
                        _wait_for(pol.ready, 180,
                                  "capacity heal after the rejoin")
                    return carries

            return _G(name, pol, checkpoint)

    _prof.reset_counters()
    m = Membership(0, 2, devices=2)
    assert m.join() == 1
    set_membership(m)
    keeper = LeaseKeeper(m, watch=True)
    keeper.start()
    try:
        resilient_exchange(coord, "chaos-ready", 0, True, 2, timeout=120)
        clog(0, "fleet up (2 ranks, file transport) — waiting for the "
                "SIGKILL")

        # -- scenario 1: death → capacity shrink → fit on the survivors -
        t0 = time.monotonic()
        _wait_for(lambda: capacity_target() == 1, 240,
                  "death → shrunk capacity target")
        res["timings"]["death_to_capacity_s"] = time.monotonic() - t0
        r = _prof.resilience_counters()
        assert r.get("rank_deaths") == 1, r
        assert m.stats()["dead_ranks"] == [1]
        clog(0, f"rank 1 death confirmed and published "
                f"(capacity → 1, {res['timings']['death_to_capacity_s']:.2f}s "
                f"after the fleet barrier)")

        ds.init((2, 1), devices=jax.devices()[:2])
        fit1 = KMeans(**kw).fit(
            ds.array(x_np),
            checkpoint=FitCheckpoint(os.path.join(workdir, "ck1.npz"),
                                     every=2))
        assert fit1.fit_info_["mesh_shrinks"] == 1, fit1.fit_info_
        assert _mesh.mesh_shape(_mesh.get_mesh()) == (1, 1)
        np.testing.assert_allclose(fit1.centers_, oracle,
                                   rtol=1e-4, atol=1e-5)
        clog(0, "fit 1: shrank (2,1)→(1,1) mid-fit, resumed from the "
                "committed snapshot, MATCHES the shrunk-fleet oracle")

        # -- scenario 2: restart → rejoin (epoch 2) → grow back mid-fit -
        def _ask_rejoin():
            open(os.path.join(workdir, "want-rejoin"), "w").close()
            clog(0, "fit 2 gated at chunk 2 — asking the driver to "
                    "restart rank 1")

        ds.init((2, 1), devices=jax.devices()[:2])
        pol = _GateAtChunk(2, lambda: capacity_target() is None,
                           _ask_rejoin)
        fit2 = KMeans(**kw).fit(
            ds.array(x_np),
            checkpoint=FitCheckpoint(os.path.join(workdir, "ck2.npz"),
                                     every=2),
            health=pol)
        assert fit2.fit_info_["mesh_shrinks"] == 1, fit2.fit_info_
        assert fit2.fit_info_["mesh_grows"] == 1, fit2.fit_info_
        assert _mesh.mesh_shape(_mesh.get_mesh()) == (2, 1)
        np.testing.assert_allclose(fit2.centers_, oracle,
                                   rtol=1e-4, atol=1e-5)
        r = _prof.resilience_counters()
        assert r.get("rank_rejoins") == 1, r
        clog(0, "fit 2: shrank while alone, GREW BACK to (2,1) when "
                "rank 1 rejoined, matches the oracle")

        # the rejoiner runs under a bumped epoch; its pre-crash post is
        # fenced out of gathers until it re-posts under the new lease
        assert m.lease_of(1)["epoch"] == 2
        assert m.gather("fence-probe") == {}, "stale epoch-1 post leaked"
        coord.post("mark-fence-checked", 0, True)
        _wait_for(lambda: coord.peek("mark-fence-reposted", 1), 120,
                  "rank 1's re-post under epoch 2")
        assert m.gather("fence-probe") == {1: "fresh"}
        resilient_exchange(coord, "rejoin-ready", 0, True, 2, timeout=120)
        clog(0, "epoch fencing held: stale post invisible, epoch-2 "
                "re-post visible")

        # -- scenario 3: delayed heartbeats (the flap) ------------------
        coord.post("mark-flap", 0, True)
        _wait_for(lambda: (
            _prof.resilience_counters().get("rank_deaths", 0) >= 2
            and _prof.resilience_counters().get("rank_rejoins", 0) >= 2
            and capacity_target() is None), 120,
            "flap: death + rejoin with no restart")
        clog(0, "heartbeat-delay flap observed: death AND rejoin "
                "counted, capacity healed, no process restart")

        # -- scenario 4: torn files are transient -----------------------
        TornCoordWrite(coord, failures=1).post("torn-own", 0, "x")
        assert coord.peek("torn-own", 0) is None     # degraded, typed
        assert _prof.resilience_counters().get("coord_torn_reads", 0) >= 1
        ledger = CapacityLedger(os.environ["DSLIB_CAPACITY_LEDGER"])
        with open(os.environ["DSLIB_CAPACITY_LEDGER"], "wb") as f:
            f.write(b'{"torn mid-wri')     # non-atomic, unparseable
        ledger.read()                      # survives: last-coherent-wins
        # a cross-process exchange whose FIRST post is torn: the peer's
        # read retries, the clean re-post heals, both sides complete
        TornCoordWrite(coord, failures=1, name="torn-x").post(
            "torn-x", 0, {"from": 0})
        time.sleep(0.3)                    # let the peer see the tear
        votes = coord.exchange("torn-x", 0, {"from": 0}, 2, timeout=90)
        assert votes[1] == {"from": 1}, votes
        clog(0, "torn coord file + torn ledger survived as TRANSIENT "
                "(retried/healed), cross-process exchange completed")

        # -- scenario 5: dead host at the load barrier ------------------
        coord.post("mark-fits-done", 0, True)      # rank 1 self-kills
        _wait_for(lambda: capacity_target() == 1, 120,
                  "second death confirmed")
        bt = barrier_timeout()
        t0 = time.monotonic()
        try:
            _barrier_exchange(coord, "chaos-load-dead", 0, {"ok": True},
                              2, bt, "chaos.dsb.npz")
            raise AssertionError("barrier passed with a dead host")
        except CoordinationTimeout as e:
            took = time.monotonic() - t0
            assert isinstance(e, RankDead), type(e).__name__
            assert "load barrier ABORTED" in str(e)
            assert took < bt, f"attributed abort burned the deadline: " \
                              f"{took:.2f}s"
        res["timings"]["barrier_abort_attributed_s"] = took
        set_membership(None)               # and WITHOUT membership:
        t0 = time.monotonic()              # the deadline still holds
        try:
            _barrier_exchange(coord, "chaos-load-deadline", 0,
                              {"ok": True}, 2, bt, "chaos.dsb.npz")
            raise AssertionError("barrier passed with a dead host")
        except CoordinationTimeout as e:
            took = time.monotonic() - t0
            assert "load barrier ABORTED" in str(e)
            assert took <= bt + 5.0, f"deadline overrun: {took:.2f}s"
        res["timings"]["barrier_abort_deadline_s"] = took
        r = _prof.resilience_counters()
        assert r.get("bundle_barrier_abort", 0) >= 2, r
        clog(0, f"load barrier: typed abort twice (attributed "
                f"{res['timings']['barrier_abort_attributed_s']:.2f}s, "
                f"deadline {took:.2f}s vs budget {bt:.0f}s) — never a "
                f"hang")
    finally:
        set_membership(None)
        keeper.stop()
        clear_capacity()

    res["counters"] = _prof.resilience_counters()
    res["pass"] = True
    with open(os.path.join(workdir, "chaos_result.json"), "w") as f:
        json.dump(res, f, indent=1)
    clog(0, f"counters: {res['counters']}")
    clog(0, "CHAOS ALL SCENARIOS GREEN")


def chaos_rank1(workdir, phase):
    """The VICTIM.  Phase 'a': join, post a fence probe, then SIGKILL
    itself right after its first snapshot lands (a real kill, mid-fit).
    Phase 'b' (the restart): rejoin under a bumped epoch, serve the
    fencing and flap scenarios, then die again at the load barrier."""
    _chaos_env_setup(workdir, 1)
    import dislib_tpu as ds                          # noqa: F401
    import jax

    from dislib_tpu.runtime.coord import (LeaseKeeper, Membership,
                                          get_coordinator,
                                          resilient_exchange)
    from dislib_tpu.utils.faults import (CallbackCheckpoint, KillRankAt)

    coord = get_coordinator()
    m = Membership(1, 2, devices=2, heal_capacity=False)
    epoch = m.join()
    keeper = LeaseKeeper(m, watch=False)
    keeper.start()

    if phase == "a":
        assert epoch == 1, f"fresh fleet should start at epoch 1: {epoch}"
        from dislib_tpu.cluster import KMeans
        m.post("fence-probe", "stale")   # epoch-1 payload, must be fenced
        resilient_exchange(coord, "chaos-ready", 1, True, 2, timeout=120)
        clog(1, "fitting — SIGKILL lands right after snapshot 1")
        x_np, kw = _chaos_fit_setup()
        ds.init((2, 1), devices=jax.devices()[:2])
        KMeans(**kw).fit(
            ds.array(x_np),
            checkpoint=CallbackCheckpoint(
                os.path.join(workdir, "ck-victim.npz"), every=2, after=1,
                callback=KillRankAt(at_call=1)))
        clog(1, "survived my own SIGKILL — impossible")
        sys.exit(7)

    assert phase == "b", phase
    assert epoch == 2, f"rejoin must bump past the dead lease: {epoch}"
    clog(1, "rejoined under epoch 2 — heartbeating")
    _wait_for(lambda: coord.peek("mark-fence-checked", 0), 300,
              "rank 0's fence check")
    m.post("fence-probe", "fresh")       # epoch-2 re-post: visible again
    coord.post("mark-fence-reposted", 1, True)
    resilient_exchange(coord, "rejoin-ready", 1, True, 2, timeout=120)

    _wait_for(lambda: coord.peek("mark-flap", 0), 180, "flap go-signal")
    clog(1, f"flapping: heartbeats delayed {2.8 * m.lease_s:.1f}s "
            f"(lease {m.lease_s:.1f}s)")
    keeper.stop()
    time.sleep(2.8 * m.lease_s)          # the delayed-heartbeat window
    keeper = LeaseKeeper(m, watch=False)
    keeper.start()

    votes = coord.exchange("torn-x", 1, {"from": 1}, 2, timeout=90)
    assert votes[0] == {"from": 0}, votes    # healed through the tear
    clog(1, "torn-first exchange completed after the writer re-posted")

    _wait_for(lambda: coord.peek("mark-fits-done", 0), 300,
              "rank 0 done with the fits")
    clog(1, "dying at the load barrier (SIGKILL self)")
    os.kill(os.getpid(), signal.SIGKILL)


def chaos_parent(workdir=None):
    """The chaos driver: spawns the ranks, delivers the restart, bounds
    every child with a hard deadline, and prints the verdict."""
    import tempfile
    own_work = workdir is None
    if own_work:
        workdir = tempfile.mkdtemp(prefix="dslib-chaos-")
    os.makedirs(os.path.join(workdir, "coord"), exist_ok=True)
    here = os.path.abspath(__file__)
    base = dict(os.environ)
    base.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "DSLIB_COORD_DIR": os.path.join(workdir, "coord"),
        "DSLIB_CAPACITY_LEDGER": os.path.join(workdir, "cap.ledger"),
        "DSLIB_COORD_LEASE_MS": os.environ.get("DSLIB_COORD_LEASE_MS",
                                               CHAOS_LEASE_MS),
        "DSLIB_BARRIER_TIMEOUT": os.environ.get("DSLIB_BARRIER_TIMEOUT",
                                                CHAOS_BARRIER_S),
    })
    procs, logs = {}, {}

    def spawn(rank, phase):
        env = dict(base)
        env["DSLIB_PROC_ID"] = str(rank)
        name = f"r{rank}{phase}"
        logs[name] = os.path.join(workdir, f"chaos.{name}.log")
        f = open(logs[name], "w")
        procs[name] = subprocess.Popen(
            [sys.executable, here, "--chaos-rank", str(rank), phase,
             workdir],
            env=env, stdout=f, stderr=subprocess.STDOUT)
        print(f"[chaos driver] spawned {name} (pid "
              f"{procs[name].pid})", flush=True)
        return procs[name]

    def reap(name, deadline_s, want):
        try:
            rc = procs[name].wait(timeout=deadline_s)
        except subprocess.TimeoutExpired:
            procs[name].kill()
            raise AssertionError(f"HANG GUARD: {name} still running "
                                 f"after {deadline_s}s")
        assert rc == want, f"{name}: exit {rc}, wanted {want}"
        print(f"[chaos driver] {name} exited {rc} (expected)", flush=True)

    verdict = 1
    try:
        p0 = spawn(0, "x")
        spawn(1, "a")
        # phase a ends in a REAL SIGKILL delivered mid-fit
        reap("r1a", 300, -signal.SIGKILL)
        marker = os.path.join(workdir, "want-rejoin")
        _wait_for(lambda: os.path.exists(marker), 300,
                  "survivor's restart request")
        spawn(1, "b")
        reap("r1b", 600, -signal.SIGKILL)  # dies again, at the barrier
        reap("r0x", 600, 0)
        with open(os.path.join(workdir, "chaos_result.json")) as f:
            result = json.load(f)
        assert result.get("pass") is True
        print(f"[chaos driver] counters: {result['counters']}",
              flush=True)
        print(f"[chaos driver] timings: "
              f"{ {k: round(v, 2) for k, v in result['timings'].items()} }",
              flush=True)
        print("MULTIHOST CHAOS: PASS", flush=True)
        verdict = 0
    except BaseException as e:   # noqa: BLE001 — verdict + logs, typed
        print(f"[chaos driver] FAILED: {type(e).__name__}: {e}",
              flush=True)
        for name, p in procs.items():
            if p.poll() is None:
                p.kill()
        for name, path in logs.items():
            print(f"---- {name} log ----", flush=True)
            try:
                with open(path) as f:
                    print(f.read(), flush=True)
            except OSError:
                pass
        print("MULTIHOST CHAOS: FAIL", flush=True)
    finally:
        if own_work and verdict == 0:
            import shutil
            shutil.rmtree(workdir, ignore_errors=True)
    sys.exit(verdict)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        chaos_parent(sys.argv[2] if len(sys.argv) > 2 else None)
    elif len(sys.argv) > 1 and sys.argv[1] == "--chaos-rank":
        rank, phase, wd = int(sys.argv[2]), sys.argv[3], sys.argv[4]
        (chaos_rank0 if rank == 0 else
         lambda w, p=phase: chaos_rank1(w, p))(wd)
    else:
        main()
