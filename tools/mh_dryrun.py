#!/usr/bin/env python
"""One rank of the two-process DCN data-plane dryrun (round 19).

Launched (twice) by ``tools/run_multihost.sh``: two REAL OS processes,
each owning 2 virtual CPU devices, joined through
``jax.distributed.initialize`` — 4 global devices, the 'rows' mesh axis
spanning the process (DCN) boundary.  Each rank proves, for real:

1. **rechunk parity** — the hierarchical ``dcn`` schedule relays a
   deterministic global array across mesh shapes; every rank checks its
   addressable output shards bit-for-bit against the host-side oracle,
   and the analytic accounting invariants (messages/step ≤ hosts−1,
   bytes == deviceput floor) hold;
2. **sharded-bundle load barrier** — ``export_bundle(hosts=2)`` (each
   rank writes its own shard, rank 0 the manifest), a coordinated
   ``load_bundle`` where both ranks serve bit-correct predictions; then
   the poisoned episode: rank 1 corrupts ITS shard, and BOTH ranks
   raise the same typed ``BundleShardCorrupt`` — zero hosts serve;
3. **coherent capacity episode** — rank 0 publishes shrink(2) then
   grow(4) through the shared ``CapacityLedger``; both ranks observe
   the same level at each step (asserted by exchanging observations),
   with the ledger epoch strictly increasing.

Usage: ``mh_dryrun.py <rank> <nprocs> <port> <workdir>``.
Exit 0 = this rank passed every phase.
"""

import json
import os
import sys
import time


def log(rank, msg):
    print(f"[dryrun r{rank}] {msg}", flush=True)


def main():
    rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
    port, workdir = int(sys.argv[3]), sys.argv[4]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DSLIB_PROC_ID"] = str(rank)
    os.environ["DSLIB_CAPACITY_LEDGER"] = os.path.join(workdir,
                                                       "cap.ledger")
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import dislib_tpu as ds
    from dislib_tpu.ops import rechunk as rc
    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.runtime import BundleShardCorrupt, CapacityLedger
    from dislib_tpu.runtime.coord import get_coordinator
    from dislib_tpu.runtime.preemption import capacity_target
    from dislib_tpu.serving import ServePipeline, export_bundle, load_bundle

    ds.parallel.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nprocs, process_id=rank)
    assert jax.process_count() == nprocs, "distributed join failed"
    assert len(jax.local_devices()) == 2
    ds.init()                           # (4, 1): rows axis spans DCN
    coord = get_coordinator()
    log(rank, f"joined: {jax.device_count()} global devices, "
              f"coordinator={type(coord).__name__}")

    # ---- phase 1: hierarchical rechunk parity --------------------------
    # The only phase needing cross-process COLLECTIVES (the coordination
    # service used by phases 2/3 is platform-independent): jaxlib < 0.6
    # CPU backends raise "Multiprocess computations aren't implemented",
    # so the parity run is version-gated here — tier-1 still proves the
    # schedule bit-equal on every run through the DSLIB_MOCK_HOSTS
    # overlay (tests/test_multihost_dataplane.py).
    src = _mesh.get_mesh()
    m, n = 50, 6
    x = (np.arange(m * n, dtype=np.float32).reshape(m, n) * 0.5 - 7.0)
    pr = src.shape[_mesh.ROWS]
    mp = -(-m // pr) * pr
    xp = np.zeros((mp, n), np.float32)
    xp[:m] = x
    sh = _mesh.data_sharding(src)
    data = jax.make_array_from_callback((mp, n), sh, lambda idx: xp[idx])
    dst = Mesh(np.asarray(list(src.devices.flat)).reshape(2, 2),
               _mesh.AXIS_NAMES)
    assert rc.dcn_supported(data, dst), "hierarchical layout not detected"
    acct = rc.dcn_accounting(data, (m, n), dst)
    assert acct["hosts"] == nprocs
    assert acct["messages_per_step_max"] <= acct["hosts"] - 1
    assert acct["dcn_bytes_moved"] == acct["deviceput_bytes"]
    from dislib_tpu.runtime.xla_flags import _jaxlib_version
    v = _jaxlib_version()
    collectives_ok = (v is not None and v >= (0, 6, 0)) or \
        os.environ.get("DSLIB_FORCE_MP_TESTS") == "1"
    if collectives_ok:
        out, sched = rc.reshard(data, (m, n), dst, schedule="dcn")
        assert sched == "dcn"
        # oracle: the relayout is a pure re-partition of the logical array
        mp2 = -(-m // 2) * 2
        np2 = -(-n // 2) * 2
        oracle = np.zeros((mp2, np2), np.float32)
        oracle[:m, :n] = x
        for s in out.addressable_shards:
            np.testing.assert_array_equal(np.asarray(s.data),
                                          oracle[s.index],
                                          err_msg="dcn shard mismatch")
        log(rank, f"rechunk parity OK ({acct['dcn_messages']} DCN "
                  f"messages, {acct['dcn_bytes_moved']} bytes)")
    else:
        log(rank, "rechunk parity SKIPPED (this jaxlib's CPU backend "
                  "lacks multiprocess collectives) — accounting + "
                  "support gates checked; mock-host tier-1 carries "
                  "bit-equality")
    votes = coord.exchange("dryrun-rechunk", rank, True, n=nprocs)
    assert all(votes.values())

    # ---- phase 2: sharded bundle + load barrier ------------------------
    # Serving topology: each host serves ITS shard on ITS local devices
    # (the per-host serving mesh — what the sharded bundle's mesh
    # contract describes).  Everything below is collective-free: the
    # cross-process protocol rides the coordination service, compute
    # stays intra-host — so this phase runs for real on every rig.
    ds.init(mesh_shape=(len(jax.local_devices()), 1),
            devices=jax.local_devices())
    jax.clear_caches()
    NF = 4
    lr = ds.LinearRegression()
    lr.coef_ = np.arange(NF, dtype=np.float32).reshape(NF, 1)
    lr.intercept_ = np.full(1, 2.5, np.float32)
    pipe = ServePipeline(lr, n_features=NF)
    state = {"coef": lr.coef_, "intercept": lr.intercept_}
    good = os.path.join(workdir, "good.dsb.npz")
    export_bundle(pipe, good, buckets=(1, 8), state=state, hosts=nprocs)
    lb = load_bundle(good)
    assert not lb.fallback and lb.host == rank and lb.hosts == nprocs
    xq = np.linspace(0, 1, 3 * NF, dtype=np.float32).reshape(3, NF)
    got = lb.pipeline.predict_bucket(xq, 8)
    np.testing.assert_allclose(got, xq @ lr.coef_ + 2.5, atol=1e-5)
    log(rank, "sharded bundle served bit-correct after the barrier")

    bad = os.path.join(workdir, "bad.dsb.npz")
    export_bundle(pipe, bad, buckets=(1,), state=state, hosts=nprocs)
    if rank == 1:
        with open(bad + ".shard1", "r+b") as f:
            f.seek(64)
            f.write(b"\xde\xad\xbe\xef")
    coord.exchange("dryrun-corrupted", rank, True, n=nprocs)
    try:
        load_bundle(bad)
        raise AssertionError("corrupt shard served — barrier failed")
    except BundleShardCorrupt as e:
        assert e.host == 1, f"wrong host blamed: {e.host}"
    coord.exchange("dryrun-abort-seen", rank, True, n=nprocs)
    log(rank, "poisoned shard → typed abort on BOTH ranks, zero served")

    # ---- phase 3: coherent shrink→grow capacity episode ----------------
    ledger = CapacityLedger(os.environ["DSLIB_CAPACITY_LEDGER"])
    episodes = []
    for step, target in (("shrink", 2), ("grow", 4)):
        if rank == 0:
            ds.runtime.request_capacity(target)   # publishes to the ledger
        deadline = time.time() + 20
        seen, epoch = None, 0
        while time.time() < deadline:
            seen, epoch = ledger.read()
            if seen == target:
                break
            time.sleep(0.02)
        assert seen == target, f"{step}: rank {rank} saw {seen}"
        # the consumer-side view agrees (override on the writer, ledger
        # on everyone else — one coherent level either way)
        assert capacity_target() == target
        episodes.append((step, target, epoch))
        # every rank observed the same level AT the same ledger epoch —
        # the rank-0 writer publishes the next step only after this
        # barrier, so the recorded epochs are comparable fleet-wide
        obs = coord.exchange(f"dryrun-cap-{step}", rank, [seen, epoch],
                             n=nprocs)
        vals = {tuple(v) for v in obs.values()}
        assert vals == {(target, epoch)}, f"incoherent fleet: {obs}"
    assert episodes[0][2] < episodes[1][2], "ledger epoch not monotonic"
    log(rank, f"capacity episode coherent: {episodes}")

    with open(os.path.join(workdir, f"result.{rank}.json"), "w") as f:
        json.dump({"rank": rank, "rechunk": acct,
                   "episodes": episodes}, f)
    coord.exchange("dryrun-done", rank, True, n=nprocs)
    ds.parallel.shutdown()
    log(rank, "ALL PHASES GREEN")


if __name__ == "__main__":
    main()
