"""Patch BASELINE.md's measurement table from a bench.py output capture.

Usage:
    python bench.py | tee /tmp/bench.jsonl
    python tools/fill_baseline.py /tmp/bench.jsonl [hardware-label] [peak-tflops]

Replaces the benchmark-matrix table wholesale with the measured rows
(value + vs_baseline against the NumPy single-node proxy, labeled as
BASELINE.md's measurement rules require), keeping the prose around it
untouched.  Matmul rows additionally get an MFU column: GFLOPS / (peak
TFLOP/s × 1000), against the per-chip peak for the matmul's input dtype
— pass the right peak for the hardware actually used (default 197, TPU
v5e bf16; the f32 row's MFU is then vs the bf16 peak and understates a
true-f32 ceiling, which the column header states).
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# EXACT bench metric first token → (BASELINE.md row name, config text,
# is_matmul).  Exact keys, not prefixes: matmul_16384_f32 would otherwise
# swallow matmul_16384_f32x3.
ROWS = [
    ("dispatch_rtt_trivial_op_ms", "Dispatch RTT (informational)",
     "8×8 jitted add + 1-elt fetch", False),
    ("kmeans_10000x100_k8_iter_per_sec", "KMeans",
     "k=8, 10000×100 ds-array", False),
    ("matmul_4096_f32_gflops_per_chip", "Blocked matmul (f32)",
     "4096×4096 @ 4096×4096", True),
    ("matmul_mp_4096_bf16_vs_f32_speedup",
     "Matmul mixed-precision A/B (bf16 policy vs f32)",
     "4096×4096, 12-GEMM chains, roofline-normalized gate", False),
    ("polar_16384x1024_gflops_sustained",
     "Polar (Newton–Schulz, roofline row)",
     "16384×1024, one dispatch per call", True),
    ("summa_8192_gflops_per_chip", "SUMMA matmul (2-D mesh)",
     "8192×8192, explicit panel broadcasts", True),
    ("tsqr_65536x256_wall_s", "tsQR", "65536×256 tall-skinny", False),
    ("randomsvd_32768x1024_nsv64_wall_s", "RandomizedSVD",
     "32768×1024, nsv=64", False),
    ("svd_4096x512_wall_s", "SVD (block Jacobi, informational)",
     "4096×512", False),
    ("gmm_1000000x50_k16_5it_wall_s", "GaussianMixture EM",
     "1M×50, k=16, 5 iter", False),
    ("csvm_20000x20_rbf_3it_fit_wall_s", "CascadeSVM (irregular tier)",
     "20000×20 rbf, 3 global iters", False),
    ("gridsearch_kmeans_200000x20_3x3fits_wall_s",
     "GridSearchCV (async trials)", "KMeans 200k×20, 3 cand × 3 folds",
     False),
    ("dbscan_200000x10_wall_s", "DBSCAN (tiled tier)",
     "200k×10, ε-stream + label propagation", False),
    ("daura_50000x15_wall_s", "Daura (greedy GROMOS, tiled)",
     "50k×15 (5 atoms), RMSD ε-graph + greedy extraction", False),
    ("forest_100000x20_16t_fit_predict_wall_s", "RandomForest (vmapped)",
     "100k×20, 16 trees, fit+predict", False),
    ("knn_1000000x10_q10000_k10_queries_per_sec", "kNN query throughput",
     "1M fit rows, 10k queries, k=10", False),
    ("als_sparse_100000x10000_nnz100_f16_3it_wall_s", "ALS (sparse BCOO)",
     "100k×10k, 100 nnz/user, f=16, 3 iter", False),
    ("shuffle_2097152x64_gb_per_sec", "Shuffle (all_to_all)",
     "2M×64 f32 (512 MB)", False),
    ("matmul_16384_f32_gflops_per_chip", "Matmul north star ★ (f32)",
     "16384×16384", True),
    ("matmul_16384_bf16_gflops_per_chip", "Matmul north star ★ (bf16)",
     "16384×16384", True),
    ("matmul_16384_f32x3_gflops_per_chip",
     "Matmul (f32x3 3-pass, informational)", "16384×16384", True),
    ("kmeans_1Mx100_k10_sustained_iter_per_sec",
     "KMeans ★ sustained (500 it/dispatch)", "1M×100, k=10", False),
    ("kmeans_1Mx100_k10_fastdist_iter_per_sec",
     "KMeans ★ (bf16 assignment)", "1M×100, k=10", False),
    ("kmeans_1Mx100_k10_iter_per_sec", "KMeans north star ★",
     "1M×100, k=10", False),
]


def main():
    jsonl = sys.argv[1]
    hw = sys.argv[2] if len(sys.argv) > 2 else "TPU v5e (1 chip, axon)"
    peak_tflops = float(sys.argv[3]) if len(sys.argv) > 3 else 197.0
    results = {}
    with open(jsonl) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            if rec.get("stale"):
                # stale-fallback rows are a wedge-day courtesy copy of an
                # older capture — never let them overwrite the table as if
                # they were this run's measurements
                continue
            results[rec["metric"].split(" ")[0]] = rec

    out_rows = [f"| Workload | Config | Measured | Unit | raw (1 RTT/disp) "
                f"| vs NumPy proxy | MFU (vs {peak_tflops:.0f} TF/s peak) "
                f"| Hardware |",
                "|---|---|---|---|---|---|---|---|"]
    for key, name, cfg, is_matmul in ROWS:
        # exact first, then a one-directional legacy fallback: an old-style
        # error record is keyed by a SHORTER config name, so only
        # key.startswith(k) applies, the match must end at an underscore
        # token boundary (so 'matmul_16384_f32' cannot land on the
        # 'matmul_16384_f32x3...' row), and the longest such k wins
        rec = results.get(key)
        if rec is None:
            legacy = [k for k in results
                      if key.startswith(k) and key[len(k):len(k) + 1] == "_"]
            if legacy:
                rec = results[max(legacy, key=len)]
        if rec is None:
            out_rows.append(f"| {name} | {cfg} | (not run) | — | — | — | — "
                            f"| {hw} |")
        elif rec.get("error"):
            out_rows.append(f"| {name} | {cfg} | ERROR: "
                            f"{rec['error'][:60]} | — | — | — | — | {hw} |")
        else:
            mfu = "—"
            if is_matmul:
                mfu = f"{100.0 * rec['value'] / (peak_tflops * 1000):.1f}%"
            vsb = "—" if rec.get("vs_baseline") is None \
                else f"{rec['vs_baseline']}×"
            raw = rec.get("raw_value")
            raw = "—" if raw is None else f"{raw}"
            out_rows.append(
                f"| {name} | {cfg} | {rec['value']} | {rec['unit']} | "
                f"{raw} | {vsb} | {mfu} | {hw} |")

    # FILL_BASELINE_PATH: test hook — point at a COPY so harness tests
    # never rewrite the checked-in file (a SIGKILL mid-test would leave it
    # wiped with no restore)
    path = os.environ.get("FILL_BASELINE_PATH") \
        or os.path.join(ROOT, "BASELINE.md")
    text = open(path).read()
    table = "\n".join(out_rows)
    block = ("## Measured results\n\n"
             "Per BASELINE.md measurement rules: median of ≥5 timed runs "
             "after warmup, compile excluded, correctness gate before "
             "timing. The baseline column is the in-process NumPy "
             "single-node proxy of the same algorithm (no dislib+COMPSs "
             "install exists in this environment — labeled per the rules "
             "above).\n\n" + table + "\n")
    marker = "## Measured results"
    if marker in text:
        pre, rest = text.split(marker, 1)
        # replace only this section: resume at the next '## ' heading
        nxt = re.search(r"^## (?!Measured results)", rest, re.MULTILINE)
        tail = rest[nxt.start():] if nxt else ""
        text = pre + block + ("\n" + tail if tail else "")
    else:
        text = text.rstrip() + "\n\n" + block
    open(path, "w").write(text)
    n_ok = sum(1 for r in out_rows[2:]
               if "not run" not in r and "ERROR:" not in r)
    print(f"BASELINE.md updated with {n_ok} measured rows "
          f"({len(out_rows) - 2 - n_ok} missing/error)")


if __name__ == "__main__":
    main()
