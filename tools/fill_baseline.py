"""Patch BASELINE.md's measurement table from a bench.py output capture.

Usage:
    python bench.py | tee /tmp/bench.jsonl
    python tools/fill_baseline.py /tmp/bench.jsonl [hardware-label]

Replaces the benchmark-matrix table wholesale with the measured rows
(value + vs_baseline against the NumPy single-node proxy, labeled as BASELINE.md's
measurement rules require), keeping the prose around it untouched.
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# bench metric prefix → (BASELINE.md row name, config text)
ROWS = [
    ("kmeans_10000x100_k8", "KMeans", "k=8, 10000×100 ds-array"),
    ("matmul_4096", "Blocked matmul", "4096×4096 @ 4096×4096"),
    ("tsqr_65536x256", "tsQR", "65536×256 tall-skinny"),
    ("randomsvd_32768x1024", "RandomizedSVD", "32768×1024, nsv=64"),
    ("gmm_1000000x50", "GaussianMixture EM", "1M×50, k=16, 5 iter"),
    ("matmul_16384", "Matmul north star ★", "16384×16384"),
    ("kmeans_1Mx100_k10", "KMeans north star ★", "1M×100, k=10"),
]


def main():
    jsonl = sys.argv[1]
    hw = sys.argv[2] if len(sys.argv) > 2 else "TPU v5e (1 chip, axon)"
    results = {}
    with open(jsonl) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            results[rec["metric"].split(" ")[0]] = rec

    out_rows = ["| Workload | Config | Measured | Unit | vs NumPy proxy | Hardware |",
                "|---|---|---|---|---|---|"]
    for prefix, name, cfg in ROWS:
        rec = next((r for k, r in results.items() if k.startswith(prefix)),
                   None)
        if rec is None:
            out_rows.append(f"| {name} | {cfg} | (not run) | — | — | {hw} |")
        elif rec.get("error"):
            out_rows.append(f"| {name} | {cfg} | ERROR: "
                            f"{rec['error'][:60]} | — | — | {hw} |")
        else:
            out_rows.append(
                f"| {name} | {cfg} | {rec['value']} | {rec['unit']} | "
                f"{rec['vs_baseline']}× | {hw} |")

    path = os.path.join(ROOT, "BASELINE.md")
    text = open(path).read()
    table = "\n".join(out_rows)
    block = ("## Measured results\n\n"
             "Per BASELINE.md measurement rules: median of ≥5 timed runs "
             "after warmup, compile excluded, correctness gate before "
             "timing. The baseline column is the in-process NumPy "
             "single-node proxy of the same algorithm (no dislib+COMPSs "
             "install exists in this environment — labeled per the rules "
             "above).\n\n" + table + "\n")
    marker = "## Measured results"
    if marker in text:
        pre, rest = text.split(marker, 1)
        # replace only this section: resume at the next '## ' heading
        nxt = re.search(r"^## (?!Measured results)", rest, re.MULTILINE)
        tail = rest[nxt.start():] if nxt else ""
        text = pre + block + ("\n" + tail if tail else "")
    else:
        text = text.rstrip() + "\n\n" + block
    open(path, "w").write(text)
    n_ok = sum(1 for r in out_rows[2:]
               if "not run" not in r and "ERROR:" not in r)
    print(f"BASELINE.md updated with {n_ok} measured rows "
          f"({len(out_rows) - 2 - n_ok} missing/error)")


if __name__ == "__main__":
    main()
