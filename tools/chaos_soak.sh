#!/usr/bin/env bash
# Chaos soak (round-8 satellite): N randomized-schedule fault-injection
# fit runs — preemption + NaN-in-carry + hung chunk + snapshot corruption
# combined — asserting the resilience+health invariant (self-heal or a
# typed diagnostic, then a clean resume equals the unfaulted model).
#
# Usage:  tools/chaos_soak.sh [RUNS] [SEED]
#         tools/chaos_soak.sh --matrix [SEED] [OUT_JSONL]
#         tools/chaos_soak.sh --oscillate [SEED]
#         tools/chaos_soak.sh --trainer [SEED] [OUT_JSONL]
#         tools/chaos_soak.sh --multihost [SEED] [OUT_JSONL]
#
# Default mode runs the `slow`-marked tests/test_chaos_soak.py (excluded
# from tier-1) and echoes the machine-readable summary line; append it to
# the current BENCH_local_*.jsonl when recording a capture.
#
# --matrix (round-12) runs the seeded chaos MATRIX instead — every
# chunked estimator × every fault injector incl. the tier-targeted
# FaultAtTier (tests/test_chaos_matrix.py) — and APPENDS its
# machine-readable summary (per-cell verdicts + resilience counters) to
# OUT_JSONL (default BENCH_local_matrix.jsonl) as one JSON line.
#
# --oscillate (round-16) runs the oscillating-CAPACITY tier: a seeded
# shrink → heal → grow device-availability walk across every chunked
# estimator family, asserting zero consumed rollback budget and an
# oracle-matching model after every swing (bidirectional elasticity).
#
# --trainer (round-17) runs the CONTINUOUS-LEARNING soak: one
# ContinuousTrainer driven train → bundle → canary → promote through six
# generations with a fault at every seam (torn export, corrupt bundle,
# canary gate trip, preemption, capacity shrink/grow, explicit rollback)
# while client threads decode (tenant, generation) from every response —
# and APPENDS the summary to OUT_JSONL (default BENCH_local_r15.jsonl).
# --multihost (round-20) runs the MULTI-HOST SURVIVAL soak: repeated
# kill → resume → rejoin → grow-back episodes (lease-based membership
# over a FileCoordinator, death published as a capacity level, the
# head-home grow on rejoin) under live retrieval client traffic — every
# dead-window failure must be TYPED (ShardDrained), the healed model
# must equal the unfaulted oracle, and the rank_deaths/rank_rejoins
# counters are asserted per episode.  APPENDS the summary to OUT_JSONL
# (default BENCH_local_r19.jsonl).
set -o pipefail
cd "$(dirname "$0")/.." || exit 1
if [ "$1" = "--multihost" ]; then
    SEED="${2:-0}"
    OUT="${3:-BENCH_local_r19.jsonl}"
    LOG="$(mktemp)"
    env JAX_PLATFORMS=cpu DSLIB_SOAK_SEED="$SEED" \
        timeout -k 10 900 \
        python -m pytest tests/test_chaos_soak.py::test_chaos_mh_soak \
        -q -m slow -s -p no:cacheprovider 2>&1 | tee "$LOG"
    rc=${PIPESTATUS[0]}
    echo "-- multihost soak summary --"
    grep -a "^CHAOS_MH_SUMMARY" "$LOG" | sed 's/^CHAOS_MH_SUMMARY //'
    if [ "$rc" -eq 0 ]; then
        grep -a "^CHAOS_MH_SUMMARY" "$LOG" \
            | sed 's/^CHAOS_MH_SUMMARY //' >> "$OUT"
        echo "appended to $OUT"
    fi
    rm -f "$LOG"
    exit $rc
fi
if [ "$1" = "--trainer" ]; then
    SEED="${2:-0}"
    OUT="${3:-BENCH_local_r15.jsonl}"
    LOG="$(mktemp)"
    env JAX_PLATFORMS=cpu DSLIB_SOAK_SEED="$SEED" \
        python -m pytest tests/test_chaos_soak.py::test_chaos_trainer_soak \
        -q -m slow -s -p no:cacheprovider 2>&1 | tee "$LOG"
    rc=${PIPESTATUS[0]}
    echo "-- trainer soak summary --"
    grep -a "^CHAOS_TRAINER_SUMMARY" "$LOG" | sed 's/^CHAOS_TRAINER_SUMMARY //'
    if [ "$rc" -eq 0 ]; then
        grep -a "^CHAOS_TRAINER_SUMMARY" "$LOG" \
            | sed 's/^CHAOS_TRAINER_SUMMARY //' >> "$OUT"
        echo "appended to $OUT"
    fi
    rm -f "$LOG"
    exit $rc
fi
if [ "$1" = "--oscillate" ]; then
    SEED="${2:-0}"
    LOG="$(mktemp)"
    env JAX_PLATFORMS=cpu DSLIB_SOAK_SEED="$SEED" \
        python -m pytest \
        tests/test_chaos_soak.py::test_chaos_oscillation_soak \
        -q -m slow -s -p no:cacheprovider 2>&1 | tee "$LOG"
    rc=${PIPESTATUS[0]}
    echo "-- oscillation summary --"
    grep -a "^CHAOS_OSC_SUMMARY" "$LOG" | sed 's/^CHAOS_OSC_SUMMARY //'
    rm -f "$LOG"
    exit $rc
fi
if [ "$1" = "--matrix" ]; then
    SEED="${2:-0}"
    OUT="${3:-BENCH_local_matrix.jsonl}"
    LOG="$(mktemp)"
    env JAX_PLATFORMS=cpu DSLIB_MATRIX_SEED="$SEED" \
        python -m pytest tests/test_chaos_matrix.py::test_chaos_matrix_full \
        -q -m slow -s -p no:cacheprovider 2>&1 | tee "$LOG"
    rc=${PIPESTATUS[0]}
    echo "-- matrix summary --"
    grep -a "^CHAOS_MATRIX_SUMMARY" "$LOG" | sed 's/^CHAOS_MATRIX_SUMMARY //'
    if [ "$rc" -eq 0 ]; then
        grep -a "^CHAOS_MATRIX_SUMMARY" "$LOG" \
            | sed 's/^CHAOS_MATRIX_SUMMARY //' >> "$OUT"
        echo "appended to $OUT"
    fi
    rm -f "$LOG"
    exit $rc
fi
RUNS="${1:-10}"
SEED="${2:-0}"
LOG="$(mktemp)"
env JAX_PLATFORMS=cpu DSLIB_SOAK_RUNS="$RUNS" DSLIB_SOAK_SEED="$SEED" \
    python -m pytest tests/test_chaos_soak.py -q -m slow -s \
    -p no:cacheprovider 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "-- soak summary --"
grep -a "^CHAOS_SOAK_SUMMARY" "$LOG" | sed 's/^CHAOS_SOAK_SUMMARY //'
rm -f "$LOG"
exit $rc
