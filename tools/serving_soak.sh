#!/usr/bin/env bash
# Serving soak: a sustained concurrent request stream across live
# checkpoint hot-swaps (slow tier — excluded from tier-1; the fast
# handoff coverage lives in tests/test_serving.py).
#
#   tools/serving_soak.sh [GENS] [SECONDS] [CLIENTS]    # hot-swap soak
#   tools/serving_soak.sh --fleet [SECONDS]             # round-15 fleet
#
# Hot-swap invariants (tests/test_serving_soak.py): zero failed
# requests, zero torn responses, zero stale-after-adoption responses,
# >= 2 swaps under load, one fused dispatch per warm batch, and a
# mid-stream corrupted generation neither failing a request nor serving
# garbage.
#
# Fleet invariants (--fleet): three tenants with distinct models on one
# ModelRouter under mixed-shape load, one mid-stream canary promotion —
# zero cross-tenant leakage (every prediction decodes to the right
# (tenant, generation)), generation 1 never served to beta after its
# promotion, one fused dispatch per batch fleet-wide, zero shed.
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--fleet" ]]; then
    export DSLIB_SOAK_SECONDS="${2:-6}"
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_serving_soak.py \
        -q -m slow -k fleet -p no:cacheprovider -rs
fi

export DSLIB_SOAK_GENS="${1:-6}"
export DSLIB_SOAK_SECONDS="${2:-6}"
export DSLIB_SOAK_CLIENTS="${3:-3}"

exec env JAX_PLATFORMS=cpu python -m pytest tests/test_serving_soak.py \
    -q -m slow -k "not fleet" -p no:cacheprovider -rs
