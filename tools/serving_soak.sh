#!/usr/bin/env bash
# Serving soak: a sustained concurrent request stream across live
# checkpoint hot-swaps (slow tier — excluded from tier-1; the fast
# handoff coverage lives in tests/test_serving.py).
#
#   tools/serving_soak.sh [GENS] [SECONDS] [CLIENTS]
#
# Asserted invariants (see tests/test_serving_soak.py): zero failed
# requests, zero torn responses, zero stale-after-adoption responses,
# >= 2 swaps under load, one fused dispatch per warm batch, and a
# mid-stream corrupted generation neither failing a request nor serving
# garbage.
set -euo pipefail
cd "$(dirname "$0")/.."

export DSLIB_SOAK_GENS="${1:-6}"
export DSLIB_SOAK_SECONDS="${2:-6}"
export DSLIB_SOAK_CLIENTS="${3:-3}"

exec env JAX_PLATFORMS=cpu python -m pytest tests/test_serving_soak.py \
    -q -m slow -p no:cacheprovider -rs
