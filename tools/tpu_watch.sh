#!/bin/bash
# TPU tunnel recovery watcher.  The axon tunnel wedges for hours at a time
# (rounds 2-4, and again mid-round-5 at ~09:45 UTC after a 70-minute live
# window that captured the full bench matrix + 18/24 suite files); this
# loop probes cheaply and the moment the chip answers it captures whatever
# round-5 evidence is still missing, in priority order:
#   1. the five NEW estimator-tier bench rows  -> tools/BENCH_watch_r05.jsonl
#   2. the resumed test suite (remaining files; greens are skipped via the
#      results log)                            -> tools/TPU_SUITE_watch.txt
#   3. the CholeskyQR2 breakdown-band probe    -> tools/CHOLQR_BAND_r05.txt
# then exits.  Run in the background; polls every PERIOD seconds (default
# 300) for up to MAX_HOURS (default 11).
set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-300}
MAX_HOURS=${MAX_HOURS:-11}
SUITE_LOG=${SUITE_LOG:-/tmp/tpu_suite_r05.log}
# shared persistent compile cache for every capture step (bench --one
# children and pytest don't set it themselves)
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
log() { echo "[tpu_watch $(date -u +%H:%M:%S)] $*" >> tools/tpu_watch.log; }

log "watcher started (period=${PERIOD}s)"
while [ "$(date +%s)" -lt "$deadline" ]; do
    if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        log "TPU PROBE OK — capturing the round-5 remainder"
        # -k 30 everywhere: a wedged device claim ignores TERM (round-2
        # post-mortem), so bare `timeout` would hang the watcher itself.
        # Two consecutive row timeouts = the tunnel wedged again mid-
        # window; go back to probing rather than burning the rest of the
        # recovery window on guaranteed timeouts.
        : > tools/BENCH_watch_r05.jsonl
        wedged=0
        consec=0
        for row in dbscan_200000x10_wall_s \
                   daura_50000x15_wall_s \
                   forest_100000x20_16t_fit_predict_wall_s \
                   knn_1000000x10_q10000_k10_queries_per_sec \
                   als_sparse_100000x10000_nnz100_f16_3it_wall_s \
                   shuffle_2097152x64_gb_per_sec; do
            timeout -k 30 1200 python bench.py --one "$row" \
                >> tools/BENCH_watch_r05.jsonl 2>> tools/BENCH_watch.err
            rc=$?
            log "bench row $row rc=$rc"
            if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
                consec=$((consec + 1))
                if [ "$consec" -ge 2 ]; then wedged=1; break; fi
            else
                consec=0
            fi
        done
        if [ "$wedged" -eq 1 ]; then
            log "tunnel wedged mid-capture — resuming probe loop"
            sleep "$PERIOD"
            continue
        fi
        # fill BASELINE.md from the merged capture (r05 matrix + the new
        # rows) so even a post-session recovery lands the updated table
        # for the driver's end-of-round auto-commit
        cat BENCH_local_r05.jsonl tools/BENCH_watch_r05.jsonl \
            > /tmp/bench_merged_r05.jsonl 2>/dev/null
        python tools/fill_baseline.py /tmp/bench_merged_r05.jsonl \
            "TPU v5 lite (1 chip, axon), 2026-08-01" 197 \
            >> tools/tpu_watch.log 2>&1 || log "fill_baseline failed"
        # drop stale FAILs so those files retry (greens stay skipped)
        grep "^PASS " "$SUITE_LOG" > "$SUITE_LOG.tmp" || true
        mv "$SUITE_LOG.tmp" "$SUITE_LOG"
        timeout -k 30 14400 bash tools/run_tpu_suite.sh "$SUITE_LOG" 1500 \
            > tools/TPU_SUITE_watch_r05.txt 2>&1
        log "suite rc=$?"; cp "$SUITE_LOG" tools/tpu_suite_r05_results.log
        DSLIB_TEST_TPU=1 timeout -k 30 1500 python -m pytest \
            "tests/test_math.py::TestCholQR2::test_cholqr_breakdown_band_on_chip" \
            -q > tools/CHOLQR_BAND_r05.txt 2>&1
        log "cholqr band rc=$? — watcher done"
        exit 0
    fi
    log "probe failed; sleeping ${PERIOD}s"
    sleep "$PERIOD"
done
log "deadline reached without TPU recovery"
exit 1
