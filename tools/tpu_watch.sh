#!/bin/bash
# TPU tunnel recovery watcher (round 3).  The axon tunnel wedged for all of
# round 2 and is wedged at round-3 start; this loop probes cheaply and the
# moment the chip answers it captures the round's on-chip evidence:
#   1. python bench.py            -> tools/BENCH_watch.jsonl
#   2. the unmodified test suite  -> tools/TPU_SUITE_watch.txt
# then exits.  Run it in the background; it polls every PERIOD seconds
# (default 600) for up to MAX_HOURS (default 11).
set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-600}
MAX_HOURS=${MAX_HOURS:-11}
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
log() { echo "[tpu_watch $(date -u +%H:%M:%S)] $*" >> tools/tpu_watch.log; }

log "watcher started (period=${PERIOD}s)"
while [ "$(date +%s)" -lt "$deadline" ]; do
    if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        log "TPU PROBE OK — capturing bench"
        timeout 9000 python bench.py > tools/BENCH_watch.jsonl 2> tools/BENCH_watch.err
        log "bench rc=$? — running TPU test suite (per-file, resumable)"
        timeout 10800 bash tools/run_tpu_suite.sh /tmp/tpu_suite_results.log \
            > tools/TPU_SUITE_watch.txt 2>&1
        log "suite rc=$? — watcher done"
        exit 0
    fi
    log "probe failed; sleeping ${PERIOD}s"
    sleep "$PERIOD"
done
log "deadline reached without TPU recovery"
exit 1
