"""Generate docs/api.md from the package's public surface.

Deterministic introspection dump: every SURVEY §8 parity name plus the
estimator submodules, with signatures and first docstring paragraphs.
Run: python tools/gen_api_docs.py  (CPU; does not touch the TPU).
"""

import inspect
import os
import sys

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import dislib_tpu as ds  # noqa: E402

SECTIONS = [
    ("Mesh / parallel", "dislib_tpu",
     ["init", "get_mesh", "set_mesh"]),
    ("ds-array construction", "dislib_tpu",
     ["array", "random_array", "zeros", "full", "ones", "identity", "eye",
      "apply_along_axis", "concat_rows", "concat_cols"]),
    ("Rechunk / redistribution", "dislib_tpu",
     ["rechunk", "ensure_canonical"]),
    ("DCN-aware hierarchical rechunk (multi-host)", "dislib_tpu.ops.rechunk",
     ["dcn_accounting", "dcn_supported", "pick_schedule"]),
    ("Host topology (real map or DSLIB_MOCK_HOSTS overlay)",
     "dislib_tpu.parallel.hosts",
     ["host_of", "host_map", "n_hosts", "mock_hosts", "host_blocks"]),
    ("I/O", "dislib_tpu",
     ["load_txt_file", "load_svmlight_file", "load_npy_file",
      "load_mdcrd_file", "save_txt"]),
    ("Array / SparseArray", "dislib_tpu", ["Array", "SparseArray"]),
    ("Sharded sparse fast path", "dislib_tpu.data.sparse",
     ["ShardedSparse", "nse_quantum"]),
    ("Sparse matmul (masked-psum SpMM)", "dislib_tpu.ops.spmm",
     ["spmm", "spmm_steps", "spmm_memory_analysis", "spmm_masking_work"]),
    ("Blocked linear algebra", "dislib_tpu",
     ["matmul", "kron", "svd", "qr", "polar", "tsqr", "random_svd",
      "lanczos_svd"]),
    ("Precision policy (mixed-precision linalg)", "dislib_tpu.ops.precision",
     ["Policy", "resolve", "to_compute", "f32", "pdot", "peinsum",
      "precise"]),
    ("Overlap schedules (comm–compute pipelining)", "dislib_tpu.ops.overlap",
     ["resolve", "overlapped", "panel_pipeline", "host_pipeline"]),
    ("Pallas fallback kernels", "dislib_tpu.ops.pallas_kernels",
     ["available", "panel_gemm", "distances_sq", "node_histogram",
      "hist_available"]),
    ("Decomposition", "dislib_tpu", ["PCA"]),
    ("Clustering", "dislib_tpu.cluster",
     ["KMeans", "MiniBatchKMeans", "GaussianMixture", "DBSCAN", "Daura"]),
    ("Classification", "dislib_tpu.classification",
     ["CascadeSVM", "KNeighborsClassifier"]),
    ("Trees", "dislib_tpu.trees",
     ["RandomForestClassifier", "RandomForestRegressor",
      "DecisionTreeClassifier", "DecisionTreeRegressor"]),
    ("Neighbors", "dislib_tpu.neighbors", ["NearestNeighbors"]),
    ("Regression / optimization", "dislib_tpu",
     ["LinearRegression", "Lasso", "ADMM"]),
    ("Recommendation", "dislib_tpu", ["ALS"]),
    ("Preprocessing", "dislib_tpu", ["StandardScaler", "MinMaxScaler"]),
    ("Model selection", "dislib_tpu.model_selection",
     ["KFold", "GridSearchCV", "RandomizedSearchCV"]),
    ("Utilities", "dislib_tpu",
     ["shuffle", "train_test_split", "save_model", "load_model"]),
    ("Checkpointing", "dislib_tpu.utils.checkpoint",
     ["FitCheckpoint", "SnapshotCorrupt"]),
    ("Resilience runtime", "dislib_tpu.runtime",
     ["Preempted", "PreemptionWatcher", "preemption_requested",
      "request_preemption", "clear_preemption", "raise_if_preempted",
      "capacity_target", "request_capacity", "clear_capacity",
      "Retry", "retry_call", "is_transient_error", "repad_rows", "fetch",
      "AsyncFetch"]),
    ("Health runtime (self-healing fits)", "dislib_tpu.runtime.health",
     ["HealthPolicy", "ChunkGuard", "Verdict", "Remediation",
      "NumericalDivergence", "WatchdogTimeout", "guard", "health_vec",
      "check_snapshot"]),
    ("Chunked fit-loop driver (resilient-by-construction estimators)",
     "dislib_tpu.runtime",
     ["ChunkedFitLoop", "LoopState", "ChunkOutcome", "EscalationLadder",
      "Escalation"]),
    ("Checkpoint adoption (hot-swap read gate)", "dislib_tpu.runtime",
     ["Adoption", "AdoptionRejected", "adopt_latest", "generation_token"]),
    ("Serving", "dislib_tpu.serving",
     ["ServePipeline", "PredictServer", "ServeResponse", "ModelPool",
      "ProgramCache", "bucket_ladder", "bucket_for", "split_rows",
      "SparseFoldInPipeline", "pack_sparse_rows",
      "BucketLadderError", "QueueFull", "ShardDrained"]),
    ("Deployment bundles (AOT serving artifacts)", "dislib_tpu.serving",
     ["export_bundle", "load_bundle", "runtime_fingerprint",
      "BundlePipeline", "LoadedBundle"]),
    ("Bundle I/O (checksummed artifact seam)", "dislib_tpu.runtime",
     ["write_bundle", "read_bundle", "BundleIncompatible",
      "BundleShardCorrupt"]),
    ("Coordination service (multi-host control plane)", "dislib_tpu.runtime",
     ["get_coordinator", "LocalCoordinator", "FileCoordinator",
      "KVCoordinator", "CoordinationTimeout", "CapacityLedger"]),
    ("Membership & lease-based fault tolerance", "dislib_tpu.runtime",
     ["Membership", "LeaseKeeper", "RankDead", "TornCoordFile",
      "resilient_exchange", "set_membership", "current_membership"]),
    ("Multi-tenant routing", "dislib_tpu.serving",
     ["ModelRouter", "TenantQuotaExceeded", "DeadlineShed"]),
    ("Vector retrieval (IVF-ANN search tier)", "dislib_tpu.retrieval",
     ["IVFIndex", "RetrievalPipeline"]),
    ("Continuous-learning trainer (train → bundle → canary → promote)",
     "dislib_tpu.runtime",
     ["ContinuousTrainer", "PromotionFailed"]),
    ("Ingest quarantine", "dislib_tpu",
     ["QuarantineReport", "QuarantineLedger", "last_quarantine_report",
      "quarantine_ledger", "quarantine_batch"]),
    ("Fault injection", "dislib_tpu.utils.faults",
     ["CallbackCheckpoint", "SigtermAtNthSave", "corrupt_snapshot",
      "FlakyCall", "FlakyOpen",
      "NaNAtChunk", "DivergenceRamp", "HangAtChunk", "TripAtChunk",
      "FaultAtTier", "CapacityAtSave", "oscillation_schedule",
      "TornBundleWrite", "CanaryGateTrip",
      "KillRankAt", "LeaseExpiry", "TornCoordWrite"]),
    ("Profiling", "dislib_tpu.utils.profiling",
     ["trace", "annotate", "op_graph", "profiled_jit", "dispatch_count",
      "trace_count", "transfer_count", "counters", "reset_counters",
      "count_resilience", "resilience_counters",
      "count_schedule", "schedule_counters"]),
    ("Distributed (multi-host)", "dislib_tpu.parallel.distributed",
     ["initialize", "is_initialized", "process_info", "shutdown"]),
]


def first_para(doc):
    if not doc:
        return "(no docstring)"
    out = []
    for line in inspect.cleandoc(doc).splitlines():
        if not line.strip():
            break
        out.append(line.strip())
    return " ".join(out)


def sig_of(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(…)"


def methods_of(cls):
    """Public methods, including ones inherited from intermediate bases
    (mixins / shared ensemble bases) — but not the BaseEstimator plumbing
    every estimator shares (get_params/set_params)."""
    from dislib_tpu.base import BaseEstimator
    rows = {}
    for klass in reversed(cls.__mro__):
        if klass in (object, BaseEstimator):
            continue
        for name, fn in vars(klass).items():
            if name.startswith("_") or not callable(fn):
                continue
            rows[name] = (name, sig_of(fn), first_para(fn.__doc__))
    return [rows[k] for k in sorted(rows)]


def main():
    import importlib
    lines = ["# dislib_tpu API reference",
             "",
             "Generated by `tools/gen_api_docs.py` — regenerate after "
             "changing public signatures. Reference-parity contract: "
             "SURVEY.md §8.", ""]
    for title, modname, names in SECTIONS:
        mod = importlib.import_module(modname)
        lines.append(f"## {title}")
        lines.append("")
        for n in names:
            obj = getattr(mod, n)
            if inspect.isclass(obj):
                init_sig = sig_of(obj.__init__).replace("(self, ", "(") \
                    .replace("(self)", "()")
                lines.append(f"### `{modname}.{n}{init_sig}`")
                lines.append("")
                lines.append(first_para(obj.__doc__))
                meths = methods_of(obj)
                if meths:
                    lines.append("")
                    for m, s, d in meths:
                        sig = s.replace('(self, ', '(').replace('(self)', '()')
                        # sklearn-convention methods are documented by the
                        # class docstring; suppress the no-docstring note
                        if d == "(no docstring)":
                            lines.append(f"- `.{m}{sig}`")
                        else:
                            lines.append(f"- `.{m}{sig}` — {d}")
                lines.append("")
            else:
                lines.append(f"### `{modname}.{n}{sig_of(obj)}`")
                lines.append("")
                lines.append(first_para(obj.__doc__))
                lines.append("")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "api.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out} ({len(lines)} lines)")


if __name__ == "__main__":
    main()
