#!/bin/bash
# Run the test suite on the real TPU one file at a time, resumably.
#
# Round-2 post-mortem: a single monolithic `DSLIB_TEST_TPU=1 pytest tests/`
# through the axon tunnel ran >60 min without finishing one batch and its
# kill wedged the device claim.  Per-file invocations bound each process's
# claim lifetime, record per-file results as they land, and skip files
# already marked green in the results log, so the run resumes after any
# interruption.
#
# Usage: tools/run_tpu_suite.sh [results_log] [per-file timeout seconds]
set -u
LOG="${1:-/tmp/tpu_suite_results.log}"
TMO="${2:-900}"
cd "$(dirname "$0")/.."
# persistent compile cache (same one bench.py uses): dispatch-heavy files
# (ring, property) otherwise burn their whole budget on repeated 20-40 s
# TPU compiles of tiny shapes — round-5 rc-124 post-mortem
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"
touch "$LOG"
overall=0
consec_tmo=0
for f in tests/test_*.py; do
  if grep -q "^PASS $f$" "$LOG"; then
    echo "skip (already green): $f"
    continue
  fi
  echo "=== $f ==="
  # -k: a wedged device claim can leave python unkillable by TERM; KILL
  # 30s later so `timeout` itself can never hang (rc 137 = KILL path,
  # counted as a timeout below alongside 124)
  tmpout=$(mktemp)
  DSLIB_TEST_TPU=1 timeout -k 30 "$TMO" python -m pytest "$f" -q --no-header \
    > "$tmpout" 2>&1
  rc=$?
  # greens stay terse; failures keep enough context to diagnose without a
  # re-run (round-5: the GMM loglik delta was lost to tail -3)
  if [ "$rc" -eq 0 ]; then tail -3 "$tmpout"; else tail -40 "$tmpout"; fi
  rm -f "$tmpout"
  grep -v " $f$" "$LOG" > "$LOG.tmp" || true   # one line per file
  mv "$LOG.tmp" "$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "PASS $f" >> "$LOG"
    consec_tmo=0
  else
    echo "FAIL($rc) $f" >> "$LOG"
    overall=1
    # rc 124 = the per-file timeout fired.  Two in a row is the mid-suite
    # tunnel-wedge signature (rounds 2-3): every later file would burn the
    # full timeout too.  Abort; the log keeps the greens, so a re-run
    # after recovery resumes where this one died.
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
      consec_tmo=$((consec_tmo + 1))
      if [ "$consec_tmo" -ge 2 ]; then
        echo "=== two consecutive per-file timeouts — tunnel wedged, aborting (resumable) ==="
        break
      fi
    else
      consec_tmo=0
    fi
  fi
done
echo "=== results ==="
cat "$LOG"
exit $overall
