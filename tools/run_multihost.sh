#!/usr/bin/env bash
# Two-process DCN data-plane dryrun (round 19): two REAL JAX CPU
# processes under jax.distributed — rechunk parity on the hierarchical
# `dcn` schedule, the sharded-bundle load barrier (including the
# poisoned-shard typed abort), and a coherent cross-process
# shrink→grow capacity episode.  See tools/mh_dryrun.py for the phases.
#
# The coordination service (jax.distributed KV) is platform-independent,
# so the bundle-barrier and capacity phases run for real everywhere.
# Only the rechunk COLLECTIVE phase needs multiprocess CPU support
# (jaxlib >= 0.6); on older rigs the worker skips that one phase loudly
# — its bit-equality is still proven on every tier-1 run through the
# single-process DSLIB_MOCK_HOSTS overlay
# (tests/test_multihost_dataplane.py).  DSLIB_FORCE_MP_TESTS=1 forces
# the collective phase regardless.
#
# --chaos (round 20) runs the process-killing survival drill instead:
# ``tools/mh_dryrun.py --chaos`` SIGKILLs one of two real coordinated
# processes mid-fit, restarts it, delays heartbeats, tears coordination/
# ledger files, and kills it again at the load barrier — green means the
# survivor's resumed model matches the shrunk-fleet oracle, the rejoin
# grows back under a bumped epoch, every abort is typed, and nothing
# hangs (the driver hard-bounds every wait).
#
#   tools/run_multihost.sh [--chaos]
cd "$(dirname "$0")/.." || exit 1

if [ "$1" = "--chaos" ]; then
  LOG=$(mktemp)
  env JAX_PLATFORMS=cpu timeout -k 10 600 \
      python tools/mh_dryrun.py --chaos 2>&1 | tee "$LOG"
  rc=${PIPESTATUS[0]}
  if [ "$rc" -eq 0 ] && grep -q "MULTIHOST CHAOS: PASS" "$LOG"; then
    rm -f "$LOG"; exit 0
  fi
  rm -f "$LOG"
  echo "MULTIHOST CHAOS: FAIL (rc=$rc)"
  exit 1
fi

WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT
PORT=$(python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0))
print(s.getsockname()[1]); s.close()
EOF
)

echo "-- launching 2 ranks (coordinator 127.0.0.1:$PORT, work $WORK) --"
pids=()
for r in 0 1; do
  env -u XLA_FLAGS -u JAX_PLATFORMS \
      timeout -k 10 300 \
      python tools/mh_dryrun.py "$r" 2 "$PORT" "$WORK" \
      > "$WORK/rank$r.log" 2>&1 &
  pids+=($!)
done

rc=0
for i in 0 1; do
  if ! wait "${pids[$i]}"; then rc=1; fi
done
for r in 0 1; do
  echo "-- rank $r --"
  cat "$WORK/rank$r.log"
done
if [ $rc -eq 0 ] && grep -q "ALL PHASES GREEN" "$WORK/rank0.log" \
   && grep -q "ALL PHASES GREEN" "$WORK/rank1.log"; then
  echo "MULTIHOST DRYRUN: PASS"
else
  echo "MULTIHOST DRYRUN: FAIL"
  exit 1
fi
