"""Benchmark harness — the full BASELINE.md matrix.

Prints ONE JSON line per config, most-important (north-star KMeans ★) LAST so
a driver that parses the final stdout line records the headline metric.
Every config is isolated: a failure prints a JSON line with an "error" field
and the harness moves on — one bad kernel can never zero a round's evidence
again (round-1 post-mortem).

Measurement rules (BASELINE.md):
- median of >= 5 timed runs after a warmup/compile run; compile excluded;
- correctness gate before timing (device result vs NumPy oracle);
- vs_baseline is measured against a NumPy single-node proxy of the same
  algorithm run in-process (no dislib+COMPSs install exists here; the proxy
  is labeled in the metric string);
- results are synced by fetching a small slice of each terminal output
  (device_get). `block_until_ready` alone is NOT trusted for timing through
  the axon TPU tunnel — measured in round 2 returning ~1000x too fast.
"""

import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

# Watchdog architecture (round-3 rework of the round-2 thread watchdog):
# a wedged device tunnel blocks the Python main thread inside a C call, so
# no in-process mechanism can skip past it.  Each config therefore runs in
# its OWN subprocess; the parent (which never imports jax) enforces
# timeouts, forwards the child's JSON lines, and keeps going after a
# timeout — one slow config no longer zeroes the rest of the round's
# evidence.  Two consecutive timeouts mean the backend itself is wedged
# (every later config would hang too) and abort with rc 2.  A cheap
# 60-second `jax.devices()` probe child runs first so a dead tunnel costs
# one minute, not fifteen.
_CONFIG_TIMEOUT_S = int(os.environ.get("DSLIB_BENCH_CONFIG_S", "900"))
_PROBE_TIMEOUT_S = int(os.environ.get("DSLIB_BENCH_PROBE_S", "60"))


def _smoke_wants_cpu() -> bool:
    """True when smoke mode should force the CPU platform: BENCH_SMOKE is
    on and the caller did not EXPLICITLY request a different platform.
    ``JAX_PLATFORMS=axon`` is this box's session-wide default export (the
    TPU tunnel), not a caller request — honouring it would make
    `BENCH_SMOKE=1 python bench.py` hang on a wedged tunnel, which smoke
    mode exists to avoid.  Test hooks inject probe failures by setting a
    non-axon platform."""
    return bool(os.environ.get("BENCH_SMOKE")) and \
        os.environ.get("JAX_PLATFORMS", "axon") == "axon"


def _median_time(fn, repeats=5):
    """Median wall seconds of fn(), which must internally sync its outputs."""
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _sync(*arrays):
    """Force completion by fetching a tiny dependent slice of each output."""
    for a in arrays:
        data = a._data if hasattr(a, "_data") else a
        np.asarray(data[:1, :1] if data.ndim == 2 else data[:1])


def _emit(payload):
    print(json.dumps(payload), flush=True)


def _measure_rtt(repeats=7):
    """Median wall seconds of a trivial dispatch + 1-element fetch — the
    fixed per-call latency floor every timed region pays exactly once.
    Measured in-config so amortized rows can emit an RTT-corrected value
    next to the raw one (round-3 verdict: correction must be in the JSON,
    not prose)."""
    import jax
    import jax.numpy as jnp

    x = jax.device_put(jnp.ones((8, 8), jnp.float32))
    f = jax.jit(lambda a: a + 1.0)
    np.asarray(f(x)[:1, :1])  # warmup/compile
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(f(x)[:1, :1])
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _guard(name, fn):
    try:
        _emit(fn())
    except Exception as e:  # noqa: BLE001 — resilience is the whole point
        _emit({"metric": name, "value": None, "unit": None, "vs_baseline": None,
               "error": f"{type(e).__name__}: {e}",
               "trace": traceback.format_exc(limit=3)})


# ---------------------------------------------------------------------------
# roofline: measured per-dtype peak + the vs_peak gate (round-10 perf PR)
# ---------------------------------------------------------------------------

_PEAK_CACHE: dict = {}


def _policy_of(dtype_tag):
    from dislib_tpu.ops import precision as px
    return {"f32": px.FLOAT32, "bf16": px.BFLOAT16}[dtype_tag]


def _peak_gflops(dtype_tag):
    """Measured per-chip GEMM peak for one compute dtype — the roofline
    denominator every ``vs_peak`` row divides by.

    ``DSLIB_PEAK_GFLOPS_F32`` / ``_BF16`` override with a datasheet value
    when the platform's peak is known; otherwise a dedicated probe runs a
    deep dependent-GEMM chain (the library's own ``precision.pdot``
    formulation) at an MXU-friendly square size and takes the BEST of 3
    regions — peak wants the minimum wall, not the median.  The probe is
    a proxy: a benched workload whose shape outruns the probe's can read
    ``vs_peak`` slightly above 1; the gate direction (a floor) only cares
    about collapses."""
    env = os.environ.get(f"DSLIB_PEAK_GFLOPS_{dtype_tag.upper()}")
    if env:
        return float(env)
    if dtype_tag in _PEAK_CACHE:
        return _PEAK_CACHE[dtype_tag]
    dim = 512 if os.environ.get("BENCH_SMOKE") else 4096
    # FILE-backed like the matmul setup cache: every config runs in its
    # own subprocess (watchdog architecture), so without it each
    # roofline-gated sibling would re-measure the identical probe; the
    # parent clears these at run start so a previous invocation's machine
    # load never leaks into this run's vs_peak ratios
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp")
    cache_f = os.path.join(cache_dir, f"bench_peak_{dtype_tag}_{dim}.json")
    if os.path.exists(cache_f):
        try:
            with open(cache_f) as f:
                peak = float(json.load(f)["peak_gflops"])
            _PEAK_CACHE[dtype_tag] = peak
            return peak
        except (OSError, ValueError, KeyError):
            pass                        # unreadable cache: re-measure
    import jax
    import jax.numpy as jnp
    import dislib_tpu as ds  # noqa: F401 — mesh init side effect
    chain = 8
    x = jax.device_put(jnp.asarray(
        np.random.RandomState(0).rand(dim, dim).astype(np.float32)))
    fn = _policy_chain_fn(_policy_of(dtype_tag), chain)
    np.asarray(fn(x)[:1, :1])                       # warmup/compile
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(fn(x)[:1, :1])
        walls.append(time.perf_counter() - t0)
    peak = 2.0 * dim ** 3 * chain / min(walls) / 1e9
    _PEAK_CACHE[dtype_tag] = peak
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with open(cache_f, "w") as f:
            json.dump({"peak_gflops": peak}, f)
    except OSError:
        pass                            # cache is best-effort
    return peak


def _policy_chain_fn(policy, chain):
    """One dispatch of ``chain`` dependent GEMMs through the library's
    policy-routed contraction (`ops/precision.pdot`) — the same dependency
    trick as ``bench_matmul``'s chain (stops XLA hoisting), but measuring
    the policy path the library actually ships."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from dislib_tpu.ops import precision as px
    from dislib_tpu.parallel import mesh as _mesh_mod

    def _body(x):
        eps = jnp.float32(1.0 / (x.shape[0] * x.shape[0]))

        def step(i, c):
            out = px.pdot(x, x + eps * c, policy)
            return lax.with_sharding_constraint(out,
                                                _mesh_mod.data_sharding())
        return lax.fori_loop(0, chain, step,
                             jnp.zeros(x.shape, jnp.float32))

    return jax.jit(px.precise(_body))


def _apply_roofline(res, sustained_gflops, dtype_tag, floor):
    """Attach ``peak_gflops`` / ``vs_peak`` to a row and enforce the
    roofline floor — the regression gate: sustained GFLOPS falling below
    ``floor`` x measured peak FAILS the config loudly (stderr + an error
    row via ``_guard``) instead of shipping a quietly-slower number.
    ``DSLIB_VS_PEAK_MIN`` overrides every floor (noisy-rig escape)."""
    peak = _peak_gflops(dtype_tag)
    vs_peak = sustained_gflops / peak
    res["peak_gflops"] = round(peak, 1)
    res["vs_peak"] = round(vs_peak, 3)
    # record the floor the gate ACTUALLY enforces (env override included)
    # — a row must never read as having cleared a floor it was not held to
    floor = float(os.environ.get("DSLIB_VS_PEAK_MIN", floor))
    res["vs_peak_floor"] = floor
    if vs_peak < floor:
        msg = (f"ROOFLINE GATE FAILED: {res['metric']}: sustained "
               f"{sustained_gflops:.1f} GFLOPS is {vs_peak:.1%} of the "
               f"measured {dtype_tag} peak {peak:.1f} GFLOPS — below the "
               f"{floor:.0%} floor (regression in sustained throughput)")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


# ---------------------------------------------------------------------------
# NumPy proxies (single-node, labeled as such in metric strings)
# ---------------------------------------------------------------------------

def _numpy_kmeans_iter(x, centers):
    d = (x * x).sum(1)[:, None] - 2.0 * (x @ centers.T) \
        + (centers * centers).sum(1)[None]
    labels = d.argmin(1)
    onehot = np.zeros((x.shape[0], centers.shape[0]), x.dtype)
    onehot[np.arange(x.shape[0]), labels] = 1.0
    counts = onehot.sum(0)
    sums = onehot.T @ x
    return np.where(counts[:, None] > 0,
                    sums / np.maximum(counts, 1)[:, None], centers)


def _numpy_gmm_iter(x, weights, means, covs, reg=1e-6):
    """One full-covariance EM iteration (log-domain responsibilities)."""
    m, n = x.shape
    k = means.shape[0]
    log_prob = np.empty((m, k), np.float32)
    for j in range(k):
        chol = np.linalg.cholesky(covs[j])
        dev = np.linalg.solve(chol, (x - means[j]).T)
        log_det = 2.0 * np.log(np.diag(chol)).sum()
        log_prob[:, j] = -0.5 * (n * np.log(2 * np.pi) + log_det
                                 + (dev * dev).sum(0))
    wlp = log_prob + np.log(weights)[None]
    norm = wlp.max(1, keepdims=True)
    resp = np.exp(wlp - norm)
    resp /= resp.sum(1, keepdims=True)
    nk = resp.sum(0) + 1e-10
    means = resp.T @ x / nk[:, None]
    covs = np.empty_like(covs)
    for j in range(k):
        diff = x - means[j]
        covs[j] = (resp[:, j, None] * diff).T @ diff / nk[j] \
            + reg * np.eye(n, dtype=np.float32)
    return nk / m, means, covs


def _numpy_random_svd(x, sketch, iters, seed=0):
    rng = np.random.RandomState(seed)
    omega = rng.standard_normal((x.shape[1], sketch)).astype(np.float32)
    q, _ = np.linalg.qr(x @ omega)
    for _ in range(iters):
        qz, _ = np.linalg.qr(x.T @ q)
        q, _ = np.linalg.qr(x @ qz)
    b = q.T @ x
    ub, s, vt = np.linalg.svd(b, full_matrices=False)
    return q @ ub, s, vt


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------

def bench_kmeans(m, n, k, iters, tag, amortize=None):
    """KMeans iteration rate.  ``amortize``: additionally time a region of
    that many iterations per dispatch and report it as the headline value —
    the per-dispatch tunnel RTT (~69 ms) otherwise dominates any config
    whose ``iters``-iteration compute is comparable to one round trip
    (round-3 verdict weak #1: 541.9 it/s "2.41×" on config 1 was a latency
    artifact).  The spec-``iters`` rate is kept in ``raw_value`` and the
    RTT-subtracted rate in ``rtt_corrected_value`` so raw, amortized and
    corrected are all machine-readable."""
    import jax.numpy as jnp
    import dislib_tpu as ds
    from dislib_tpu.cluster.kmeans import _kmeans_fit

    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    init = x_host[rng.choice(m, k, replace=False)].copy()

    t0 = time.perf_counter()
    c = init.copy()
    for _ in range(2):
        c = _numpy_kmeans_iter(x_host, c)
    cpu_iter_sec = 2.0 / (time.perf_counter() - t0)

    a = ds.array(x_host, block_size=(m, n))
    c0 = jnp.asarray(init)
    fast = tag.endswith("fastdist")
    # correctness gate: 1 device iteration vs the NumPy oracle.  The bf16-
    # assignment variant legitimately flips near-tied argmins, so its gate
    # is inertia-relative vs the full-precision device result (centers
    # averaged over ~m/k points absorb a handful of boundary flips; the
    # objective must agree to 0.1%)
    got_state = _kmeans_fit(a._data, a.shape, c0, 1, 0.0, fast=fast)
    got = np.asarray(got_state[0])
    if fast:
        exact = _kmeans_fit(a._data, a.shape, c0, 1, 0.0, fast=False)
        np.testing.assert_allclose(float(got_state[2]), float(exact[2]),
                                   rtol=1e-3)
        np.testing.assert_allclose(got, np.asarray(exact[0]),
                                   rtol=2e-2, atol=2e-2)
    else:
        np.testing.assert_allclose(got, _numpy_kmeans_iter(x_host, init),
                                   rtol=2e-3, atol=2e-3)
    np.asarray(_kmeans_fit(a._data, a.shape, c0, iters, 0.0,
                           fast=fast)[0])  # warmup
    t = _median_time(
        lambda: np.asarray(_kmeans_fit(a._data, a.shape, c0, iters, 0.0,
                                       fast=fast)[0]))
    tpu_iter_sec = iters / t
    res = {"metric": f"kmeans_{tag}_iter_per_sec (baseline: numpy single-node proxy)",
           "value": round(tpu_iter_sec, 3), "unit": "iter/s",
           "vs_baseline": round(tpu_iter_sec / cpu_iter_sec, 2)}
    # dispatch accounting (round-7 fusion PR): how many XLA dispatches one
    # estimator-level fit/predict costs, from the utils.profiling counters
    # — the "one program per result, not per op" claim as a number
    from dislib_tpu.cluster import KMeans as _KMeans
    from dislib_tpu.utils import profiling as _prof
    kw = dict(n_clusters=k, init=init, max_iter=iters, tol=0.0,
              fast_distance=fast)
    warm = _KMeans(**kw).fit(a)                 # compile both paths
    warm.predict(a).force()
    _prof.reset_counters()
    est = _KMeans(**kw).fit(a)
    res["dispatches_per_fit"] = _prof.dispatch_count()
    _prof.reset_counters()
    est.predict(a).force()
    res["dispatches_per_predict"] = _prof.dispatch_count()
    if amortize:
        np.asarray(_kmeans_fit(a._data, a.shape, c0, amortize, 0.0,
                               fast=fast)[0])  # compile for the new max_iter
        wall = _median_time(
            lambda: np.asarray(_kmeans_fit(a._data, a.shape, c0, amortize,
                                           0.0, fast=fast)[0]))
        rtt = _measure_rtt()
        sustained = amortize / wall
        res.update({
            "raw_value": res["value"],
            "raw_vs_baseline": res["vs_baseline"],
            "value": round(sustained, 3),
            "vs_baseline": round(sustained / cpu_iter_sec, 2),
            "rtt_ms": round(1e3 * rtt, 2),
            "rtt_corrected_value": round(amortize / max(wall - rtt, 1e-9), 3),
            "iters_per_dispatch": amortize,
            "note": f"value = sustained rate ({amortize} iters/dispatch); "
                    f"raw_value = spec rate ({iters} iters/dispatch, "
                    "one RTT per dispatch)"})
    return res


def bench_matmul(dim, tag, proxy_dim=None, bf16=False, chain=None,
                 precision=None, peak_floor=None):
    """GEMM GFLOPS/chip — f32-faithful, or the library's bfloat16 policy
    (bf16-compute / f32-accumulate via ``ds.matmul(precision='bfloat16')``)
    when ``bf16``; pre-round-10 captures measured bf16-STORAGE operands
    instead (same MXU passes, so rows compare).  proxy_dim: run the NumPy
    proxy at a smaller size and scale analytically (labeled) when the
    full size is too slow.  ``peak_floor``: when set (library rows only),
    the sustained value must reach that fraction of the measured
    per-dtype peak — the roofline regression gate (round-10 perf PR).

    ``chain``: additionally time ONE dispatch containing that many
    *dependent* GEMMs (``c_{i+1} = x @ (x + eps*c_i)``, same dot + sharding
    constraint + f32-faithful precision scope as the library kernel,
    ``math/base.py::_matmul_kernel``) and report the sustained GFLOPS as
    the headline value — a single dispatch's wall includes the fixed
    tunnel RTT, which at 4096³ f32 swamped the compute 4:1 in round 3
    (verdict weak #1/#2).  The dependency chain stops XLA hoisting the
    loop-invariant product; eps ~ 1/dim² keeps the iterate bounded (the
    perturbation contracts since eps·‖x‖₂ ≈ 1/(2·dim) ≪ 1).  Single-
    dispatch GFLOPS stays in ``raw_value``; RTT-subtracted sustained in
    ``rtt_corrected_value``.

    ``precision``: INFORMATIONAL precision override — "high" is the TPU
    3-pass bf16x3 algorithm (~2⁻²¹ relative error vs f32's 2⁻²⁴;
    theoretical ceiling ≈ peak/3 vs 'highest''s peak/6).  The library's
    own kernels stay at 'highest'; this row exists so a future round can
    decide from measured on-chip data whether the f32-faithful scope can
    drop to 3-pass (measurably-better rule).  Uses a direct jitted dot
    (the library has no 'high' path to measure)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    import dislib_tpu as ds
    from dislib_tpu.ops.base import precise
    from dislib_tpu.parallel import mesh as _mesh_mod

    # setup cache — FILE-backed, because every config runs in its own
    # subprocess (the watchdog architecture), so the f32 and bf16 siblings
    # of a dim would otherwise each re-measure the slow NumPy proxy and
    # gate stripe.  Data is deterministic (RandomState(0)), so the cached
    # gate reference is exact across children.
    rng = np.random.RandomState(0)
    pdim = proxy_dim or dim
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp")
    cache_f = os.path.join(cache_dir, f"bench_matmul_setup_{dim}_{pdim}.npz")
    if os.path.exists(cache_f):
        with np.load(cache_f) as z:
            cpu_gflops, ref = float(z["cpu_gflops"]), z["ref"]
        rng.rand(pdim, pdim)            # keep the stream position identical
        x_host = rng.rand(dim, dim).astype(np.float32)
    else:
        xp = rng.rand(pdim, pdim).astype(np.float32)
        t0 = time.perf_counter()
        xp @ xp
        cpu_gflops = 2.0 * pdim ** 3 / (time.perf_counter() - t0) / 1e9
        x_host = rng.rand(dim, dim).astype(np.float32)
        ref = x_host @ x_host[:, :64]
        try:
            os.makedirs(cache_dir, exist_ok=True)
            np.savez(cache_f, cpu_gflops=cpu_gflops, ref=ref)
        except OSError:
            pass                        # cache is best-effort

    a = ds.array(x_host, block_size=(dim // 4, dim // 4))
    # the bf16 row measures the LIBRARY precision policy (bf16-compute /
    # f32-accumulate, operands stored f32 and rounded in-kernel) — the
    # surface users actually call; pre-round-10 captures measured
    # bf16-STORAGE operands instead (same MXU passes, so rows compare)
    lib_precision = "bfloat16" if bf16 else None
    # correctness gate on a 64-column stripe (cheap on host at any dim);
    # bf16 operand rounding is ~2^-9 relative, so a 3% relative bound has
    # ample headroom while still catching mis-scaled accumulation (entries
    # are sums of positive products — nothing near zero, rtol-only works);
    # the 3-pass f32x3 variant is ~2^-21 relative — 0.5% bound
    if precision is None:
        c = ds.matmul(a, a, precision=lib_precision)
        got = np.asarray(c._data[:dim, :64], dtype=np.float32)
        np.testing.assert_allclose(got, ref, rtol=3e-2 if bf16 else 2e-2,
                                   atol=0)

        def run():
            out = ds.matmul(a, a, precision=lib_precision)
            _sync(out)
    else:
        xd = a._data
        mm = jax.jit(lambda u, v: jnp.dot(
            u, v, precision=precision,
            preferred_element_type=jnp.float32))
        got = np.asarray(mm(xd, xd)[:dim, :64], dtype=np.float32)
        np.testing.assert_allclose(got, ref, rtol=5e-3, atol=0)

        def run():
            np.asarray(mm(xd, xd)[:1, :1])
    run()  # warmup (already compiled above, keeps parity with rules)
    t = _median_time(run)
    gflops = 2.0 * dim ** 3 / t / 1e9
    label = "numpy single-node proxy" + \
        (f" measured at {pdim}^3" if proxy_dim else "")
    dt = "bf16" if bf16 else \
        ("f32x3" if precision == "high" else "f32")
    res = {"metric": f"matmul_{tag}_{dt}_gflops_per_chip (baseline: {label})",
           "value": round(gflops, 1), "unit": "GFLOPS",
           "vs_baseline": round(gflops / cpu_gflops, 2)}
    if precision is None:
        # dispatch accounting (round-7 fusion PR): a library matmul is ONE
        # dispatch — the fused expression forced, or the eager kernel
        from dislib_tpu.utils import profiling as _prof
        _prof.reset_counters()
        ds.matmul(a, a, precision=lib_precision).force()
        res["dispatches_per_op"] = _prof.dispatch_count()
    if chain:
        x = a._data
        eps = np.float32(1.0 / (float(dim) * float(dim)))

        if precision is None:
            # library rows: the policy-routed pdot chain — what ships
            chain_fn = _policy_chain_fn(
                _policy_of("bf16" if bf16 else "f32"), chain)
        else:
            def _chain_body(x):
                def body(i, c):
                    y = x + eps * c
                    # the informational f32x3 row passes "high" explicitly
                    out = jnp.dot(x, y, precision=precision,
                                  preferred_element_type=jnp.float32)
                    return lax.with_sharding_constraint(
                        out, _mesh_mod.data_sharding())
                return lax.fori_loop(0, chain, body,
                                     jnp.zeros(x.shape, jnp.float32))

            chain_fn = jax.jit(precise(_chain_body))
        np.asarray(chain_fn(x)[:1, :1])  # warmup/compile
        wall = _median_time(lambda: np.asarray(chain_fn(x)[:1, :1]))
        rtt = _measure_rtt()
        sustained = 2.0 * dim ** 3 * chain / wall / 1e9
        res.update({
            "raw_value": res["value"],
            "raw_vs_baseline": res["vs_baseline"],
            "value": round(sustained, 1),
            "vs_baseline": round(sustained / cpu_gflops, 2),
            "rtt_ms": round(1e3 * rtt, 2),
            "rtt_corrected_value": round(
                2.0 * dim ** 3 * chain / max(wall - rtt, 1e-9) / 1e9, 1),
            "gemms_per_dispatch": chain,
            "note": f"value = sustained rate ({chain} dependent GEMMs in one "
                    "dispatch); raw_value = single-GEMM dispatch incl. one "
                    "RTT"})
        if precision is None and peak_floor is not None:
            _apply_roofline(res, sustained, "bf16" if bf16 else "f32",
                            peak_floor)
    return res


def bench_matmul_mp(dim, tag, chain, min_speedup=1.5, peak_floors=(0.15, 0.15)):
    """Mixed-precision matmul A/B — the round-10 acceptance row: the
    bfloat16 policy's sustained GEMM throughput must reach
    ``min_speedup`` x the f32-faithful policy's on the same operand, with
    the measured error inside the documented bound
    (``ops/precision.ERROR_BOUNDS``), both library paths at exactly ONE
    dispatch per op, and both sustained rates above their per-dtype
    roofline floors.  Every one of those is an in-config ASSERT — a
    regression fails the row loudly instead of shipping a quieter number.
    """
    import dislib_tpu as ds
    from dislib_tpu.ops import precision as px
    from dislib_tpu.utils import profiling as _prof

    rng = np.random.RandomState(0)
    x_host = rng.rand(dim, dim).astype(np.float32)
    a = ds.array(x_host, block_size=(dim // 4, dim // 4)).force()

    # accuracy gate: normalized entry error of the bf16 policy vs the
    # in-library f32 path, against the documented bound
    ref = np.asarray(ds.matmul(a, a)._data[:dim, :64], dtype=np.float32)
    got = np.asarray(ds.matmul(a, a, precision="bfloat16")
                     ._data[:dim, :64], dtype=np.float32)
    scale = np.abs(ref).max()
    err = float(np.abs(got - ref).max() / scale)
    bound = px.ERROR_BOUNDS[("matmul", "bfloat16")]
    assert err <= bound, \
        f"bf16 matmul error {err:.2e} outside documented bound {bound:.0e}"

    # dispatch gate: one fused/eager program per op, BOTH policies
    disp = {}
    for name, prec in (("f32", None), ("bf16", "bfloat16")):
        ds.matmul(a, a, precision=prec).force()          # warm
        _prof.reset_counters()
        ds.matmul(a, a, precision=prec).force()
        disp[name] = _prof.dispatch_count()
        assert disp[name] == 1, \
            f"{name} matmul cost {disp[name]} dispatches, expected 1"

    # sustained throughput per policy (dependent-GEMM chain, one dispatch)
    walls = {}
    for name in ("f32", "bf16"):
        fn = _policy_chain_fn(_policy_of(name), chain)
        np.asarray(fn(a._data)[:1, :1])                  # warmup/compile
        walls[name] = _median_time(lambda: np.asarray(fn(a._data)[:1, :1]))
    gflops = {name: 2.0 * dim ** 3 * chain / walls[name] / 1e9
              for name in walls}
    speedup = gflops["bf16"] / gflops["f32"]
    res = {"metric": f"matmul_mp_{tag}_bf16_vs_f32_speedup (baseline: the "
                     "f32-faithful policy, same operand/chain)",
           "value": round(speedup, 2), "unit": "x",
           "vs_baseline": round(speedup, 2),
           "f32_gflops": round(gflops["f32"], 1),
           "bf16_gflops": round(gflops["bf16"], 1),
           "bf16_rel_err": round(err, 6), "err_bound": bound,
           "dispatches_per_op": disp,
           "gemms_per_dispatch": chain, "min_speedup": min_speedup,
           "note": "bf16 = bf16-compute/f32-accumulate policy; gates: "
                   "speedup >= min_speedup, error <= documented bound, "
                   "1 dispatch/op, vs_peak floors per dtype"}
    _apply_roofline(res, gflops["f32"], "f32", peak_floors[0])
    f32_peak, f32_vs = res["peak_gflops"], res["vs_peak"]
    f32_floor = res["vs_peak_floor"]
    _apply_roofline(res, gflops["bf16"], "bf16", peak_floors[1])
    res.update({"f32_peak_gflops": f32_peak, "f32_vs_peak": f32_vs,
                "f32_vs_peak_floor": f32_floor,
                "bf16_peak_gflops": res.pop("peak_gflops"),
                "bf16_vs_peak": res.pop("vs_peak"),
                "bf16_vs_peak_floor": res.pop("vs_peak_floor")})
    # The speedup gate is roofline-NORMALIZED with a platform-class
    # deadband.  MXU-class platforms (measured bf16 peak >= 1.5x f32 —
    # the r05 chip capture shows ~2.6x) must deliver the full
    # ``min_speedup`` expectation: floor = min(min_speedup,
    # 0.8 x peak_ratio), i.e. 1.5x on chip.  Parity-class platforms
    # (this rig's CPU: bf16 GEMMs upcast, peak ratio jitters ~0.9-1.15
    # between probes — the r08 smoke capture's 2.27x was a
    # host-contention artifact) get a fixed 0.7x floor: "bf16 may not be
    # MATERIALLY slower than f32" — a double-cast/upcast regression
    # (~2x slower) still fails loudly, but probe noise around parity
    # cannot flip the gate (a 0.8 x ratio floor measured 0.88-0.92 here,
    # a coin flip against an equally-noisy 0.84-0.96 speedup).
    peak_ratio = res["bf16_peak_gflops"] / res["f32_peak_gflops"]
    if peak_ratio >= 1.5:
        floor = min(float(min_speedup), 0.8 * peak_ratio)
    else:
        floor = 0.7
    floor = float(os.environ.get("DSLIB_BF16_SPEEDUP_MIN", floor))
    res["peak_ratio"] = round(peak_ratio, 2)
    res["speedup_floor"] = round(floor, 2)
    if speedup < floor:
        msg = (f"MIXED-PRECISION GATE FAILED: bf16 sustained "
               f"{gflops['bf16']:.1f} GFLOPS is only {speedup:.2f}x the "
               f"f32 policy's {gflops['f32']:.1f} — below the "
               f"{floor:.2f}x floor (min_speedup={min_speedup}, measured "
               f"peak ratio {peak_ratio:.2f})")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


def bench_polar(m, n, tag, max_iter=30, peak_floor=0.1):
    """Newton–Schulz polar — the canonical sustained-GFLOPS workload
    (pure dependent GEMMs, zero factorisations on the critical path;
    round-10 tentpole).  Gates, all asserted in-config: U orthonormal +
    reconstruction vs the f32 SVD oracle, ONE dispatch per polar call
    REGARDLESS of iteration count (the whole loop is one program), and
    sustained GFLOPS ≥ ``peak_floor`` x the measured f32 peak.  The bf16
    policy's wall/GFLOPS ride along as fields (its iteration count can
    differ, so the ratio is informational here — the hard bf16-vs-f32
    gate lives in the matmul_mp row)."""
    import dislib_tpu as ds
    from dislib_tpu.ops import precision as px
    from dislib_tpu.utils import profiling as _prof

    rng = np.random.RandomState(0)
    x_host = rng.standard_normal((m, n)).astype(np.float32)
    a = ds.array(x_host, block_size=(max(1, m // 8), n))

    # correctness gate vs the SVD-based oracle
    u, h, info = ds.polar(a, max_iter=max_iter, info=True)
    uh = np.asarray(u.collect())
    orth = float(np.abs(uh.T @ uh - np.eye(n)).max())
    recon = float(np.linalg.norm(uh @ np.asarray(h.collect()) - x_host)
                  / np.linalg.norm(x_host))
    assert orth <= px.ERROR_BOUNDS[("polar_orth", "float32")] * 10, \
        f"polar gate: ||U'U - I|| = {orth}"
    assert recon <= 1e-4, f"polar gate: reconstruction {recon}"

    # dispatch gate: the WHOLE iteration loop is one program
    for iters in (1, max_iter):
        ds.polar(a, max_iter=iters)                     # warm
        _prof.reset_counters()
        ds.polar(a, max_iter=iters)
        d = _prof.dispatch_count()
        assert d == 1, f"polar(max_iter={iters}) cost {d} dispatches"

    def run(prec):
        _, _, nfo = ds.polar(a, precision=prec, max_iter=max_iter,
                             info=True)
        return nfo

    run(None)                                           # warmed above
    t = _median_time(lambda: run(None))
    iters = info["iterations"]
    # 2 GEMMs/iter + final-err Gram + H
    flops = 4.0 * m * n * n * iters + 4.0 * m * n * n
    gflops = flops / t / 1e9
    info_bf = run("bfloat16")                           # warmup bf16
    t_bf = _median_time(lambda: run("bfloat16"))
    gflops_bf = (4.0 * m * n * n * info_bf["iterations"]
                 + 4.0 * m * n * n) / t_bf / 1e9
    res = {"metric": f"polar_{tag}_gflops_sustained (baseline: measured "
                     "f32 GEMM peak — roofline row)",
           "value": round(gflops, 1), "unit": "GFLOPS",
           "vs_baseline": None,
           "wall_s": round(t, 4), "iterations": iters,
           "ortho_err": info["ortho_err"], "recon_err": round(recon, 8),
           "dispatches_per_op": 1,
           "bf16_gflops": round(gflops_bf, 1),
           "bf16_wall_s": round(t_bf, 4),
           "bf16_iterations": info_bf["iterations"],
           "note": "one dispatch per polar call at ANY iteration count "
                   "(asserted); flops = (4*iters + 4)*m*n^2"}
    _apply_roofline(res, gflops, "f32", peak_floor)
    res["vs_baseline"] = res["vs_peak"]
    return res


def _mesh_2d_shapes(what):
    """Near-square 2-D factorisation of the device count — (src, dst)
    mesh shapes for the tiers that need a genuine 2-D mesh (summa,
    rechunk, overlap).  Rejects < 4 devices and prime counts (whose only
    factorisation is 1-D) loudly; ONE copy of the sqrt-descend loop so a
    policy fix propagates to every tier."""
    import jax
    devs = len(jax.devices())
    if devs < 4:
        raise RuntimeError(
            f"{what} bench needs >= 4 devices for a 2-D mesh, have {devs}")
    r = int(np.sqrt(devs))
    while devs % r:
        r -= 1
    if r == 1:
        raise RuntimeError(
            f"{what} bench needs a composite device count for a 2-D mesh, "
            f"have {devs} (prime)")
    return (devs // r, r), (r, devs // r)


def bench_summa(dim, tag, peak_floor=0.05):
    """SUMMA matmul on a genuinely 2-D mesh — the explicit panel-broadcast
    schedule (`ops/summa`) vs the XLA-partitioned dot on the SAME mesh.
    Gates: values match the XLA path, ONE dispatch per op, vs_peak floor.
    The vs_xla ratio is informational: on real multi-chip ICI the panel
    schedule's bounded broadcasts are the point; on a host-core rig the
    partitioner's fused schedule usually wins wall clock."""
    import jax
    import dislib_tpu as ds

    src, _ = _mesh_2d_shapes("summa")
    ds.init(src)
    from dislib_tpu.utils import profiling as _prof

    rng = np.random.RandomState(0)
    x_host = rng.rand(dim, dim).astype(np.float32)
    a = ds.array(x_host, block_size=(dim // 4, dim // 4)).force()
    ref = np.asarray(ds.matmul(a, a, algorithm="xla")
                     ._data[:dim, :64], dtype=np.float32)
    got = np.asarray(ds.matmul(a, a, algorithm="summa")
                     ._data[:dim, :64], dtype=np.float32)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5 * ref.max())

    ds.matmul(a, a, algorithm="summa").force()          # warm
    _prof.reset_counters()
    ds.matmul(a, a, algorithm="summa").force()
    d = _prof.dispatch_count()
    assert d == 1, f"summa matmul cost {d} dispatches, expected 1"

    def run(algo):
        out = ds.matmul(a, a, algorithm=algo)
        _sync(out)

    # steady-state A/B (round-13 satellite): BOTH schedules are warmed
    # before EITHER timed region, and the regions are trace-asserted
    # compile-free — a first-call recompile inside _median_time would
    # poison the vs_xla ratio with one-off compile wall (the peak
    # probe's file-cached-setup precedent).  The hoist makes the
    # guarantee structural; the assert makes a regression loud.
    run("summa")
    run("xla")
    traces_before = _prof.trace_count()
    t = _median_time(lambda: run("summa"))
    t_xla = _median_time(lambda: run("xla"))
    assert _prof.trace_count() == traces_before, \
        "summa/xla timed region recompiled — the A/B ratio is not " \
        "steady-state"
    gflops = 2.0 * dim ** 3 / t / 1e9
    res = {"metric": f"summa_{tag}_gflops_per_chip (baseline: XLA-"
                     "partitioned dot, same 2-D mesh)",
           "value": round(gflops, 1), "unit": "GFLOPS",
           "vs_baseline": round(t_xla / t, 2),
           "wall_s": round(t, 4), "xla_wall_s": round(t_xla, 4),
           "mesh": list(ds.get_mesh().devices.shape),
           "dispatches_per_op": 1,
           "note": "vs_baseline = xla_wall / summa_wall on this mesh "
                   "(informational); gates: values == xla path, 1 "
                   "dispatch, vs_peak floor"}
    _apply_roofline(res, gflops, "f32", peak_floor)
    return res


def bench_rechunk(m, n, tag, panels=4, min_gbps=0.02, peak_ratio_max=1.5):
    """On-device collective rechunk (round-11 perf PR, ROADMAP item 4):
    the explicit masked-psum panel-exchange schedule resharding an (m, n)
    ds-array between two 2-D mesh layouts of the same devices.

    Gates (all fail the config loudly):
    - result BIT-EQUAL to the host `repad_rows` oracle, pads exactly zero;
    - ONE dispatch per reshard, ZERO host transfers (counters);
    - peak-live-buffer proxy ((out + temp) / in from XLA's own memory
      analysis of the compiled program) <= ``peak_ratio_max`` — a
      schedule that gathered a full copy sits >= 2.0, the panel schedule
      at ~1 + 1/panels (``DSLIB_RECHUNK_PEAK_RATIO_MAX`` overrides);
    - sustained bytes/s ((in + out) / wall) >= ``min_gbps``
      (``DSLIB_RECHUNK_GBPS_MIN`` overrides);
    - a mid-chain rechunk in a fused op chain costs ZERO extra
      dispatches (the chain still forces as ONE program).
    The deviceput (runtime-copy) schedule is timed alongside as the
    baseline ratio — informational, like summa's vs_xla."""
    import jax
    import dislib_tpu as ds
    from dislib_tpu.ops import rechunk as _rc
    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.utils import profiling as _prof

    src, dst = _mesh_2d_shapes("rechunk")
    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    ds.init(src)
    a = ds.array(x_host).force()
    ds.init(dst)
    q = _mesh.pad_quantum()
    pshape = (-(-m // q) * q, -(-n // q) * q)

    # correctness gate: a reshard is pure data movement — BIT-equal
    out = ds.rechunk(a, schedule="panels", panels=panels)
    got = np.asarray(out._data)
    from dislib_tpu.runtime import repad_rows
    oracle = repad_rows(repad_rows(x_host, m, pshape[0], axis=0),
                        n, pshape[1], axis=1)
    np.testing.assert_array_equal(got, oracle)

    # dispatch / transfer gate
    _prof.reset_counters()
    ds.rechunk(a, schedule="panels", panels=panels)
    d, tr = _prof.dispatch_count(), _prof.transfer_count()
    assert d == 1, f"panel rechunk cost {d} dispatches, expected 1"
    assert tr == 0, f"panel rechunk cost {tr} host transfers, expected 0"

    # peak-live-buffer proxy gate (XLA memory analysis; analytic bound as
    # the fallback on backends without it)
    ma = _rc.panel_memory_analysis(a._data, a.shape, _mesh.get_mesh(),
                                   panels)
    ratio = ma["peak_live_ratio"] if ma["peak_live_ratio"] is not None \
        else ma["analytic_ratio"]
    ratio_max = float(os.environ.get("DSLIB_RECHUNK_PEAK_RATIO_MAX",
                                     peak_ratio_max))
    if ratio > ratio_max:
        msg = (f"RECHUNK MEMORY GATE FAILED: peak-live proxy {ratio:.2f}x "
               f"the array footprint exceeds the {ratio_max:.2f}x bound "
               f"(panels={ma['panels']}) — the schedule is materialising "
               "a gathered copy")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)

    # fused mid-chain gate: a rechunk NODE adds no dispatch to a chain.
    # schedule="xla" forces the node onto the graph — the auto path's
    # metadata fast-path would make this gate vacuous (review-found)
    b = ds.array(x_host).force()          # canonical under dst mesh
    def _chain():
        mid = ds.rechunk(b * 1.0001, (max(1, m // 8), n), schedule="xla")
        assert mid.is_lazy, "mid-chain rechunk left the fusion graph"
        (mid + 0.0001).force()
    _chain()                              # warm
    _prof.reset_counters()
    _chain()
    dc = _prof.dispatch_count()
    assert dc == 1, f"fused chain with mid-chain rechunk cost {dc} dispatches"

    def run(schedule):
        y = ds.rechunk(a, schedule=schedule, panels=panels)
        _sync(y._data)

    run("panels")
    t = _median_time(lambda: run("panels"))
    run("deviceput")
    t_dput = _median_time(lambda: run("deviceput"))
    moved = (int(np.prod(a._pshape)) + int(np.prod(pshape))) * 4
    gbps = moved / t / 1e9
    floor = float(os.environ.get("DSLIB_RECHUNK_GBPS_MIN", min_gbps))
    res = {"metric": f"rechunk_{tag}_gb_per_sec (baseline: deviceput "
                     "runtime copy, same relayout)",
           "value": round(gbps, 3), "unit": "GB/s",
           "vs_baseline": round(t_dput / t, 2),
           "wall_s": round(t, 5), "deviceput_wall_s": round(t_dput, 5),
           "mesh_src": list(src), "mesh_dst": list(dst),
           "dispatches_per_op": 1, "host_transfers": 0,
           "peak_live_ratio": ratio, "peak_live_ratio_max": ratio_max,
           "panel_temp_bytes": ma["temp_bytes"],
           "analytic_ratio": ma["analytic_ratio"], "panels": ma["panels"],
           "gbps_floor": floor,
           "note": "gates: bit-equal to host repad oracle, 1 dispatch / 0 "
                   "transfers, peak-live proxy, mid-chain rechunk fuses "
                   "at 0 extra dispatches; vs_baseline = deviceput_wall / "
                   "panels_wall (informational)"}
    if gbps < floor:
        msg = (f"RECHUNK THROUGHPUT GATE FAILED: {gbps:.3f} GB/s below "
               f"the {floor:.3f} GB/s floor")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


def bench_dcn(m, n, tag, mock_hosts=4, panels=4):
    """Hierarchical DCN-aware rechunk tier (round 19, ROADMAP item 2):
    the ``dcn`` schedule resharding an (m, n) ds-array between two
    hierarchical 2-D layouts of the same devices, judged on its ANALYTIC
    inter-host accounting (the ``spmm_masking_work`` exposure pattern) —
    counters and bytes, not prose.  ``DSLIB_MOCK_HOSTS`` partitions this
    process's devices into ``mock_hosts`` fake hosts so the whole
    protocol runs single-process (chip runs use real ``process_index``
    host maps and take the same code path).

    Gates (all fail the config loudly):
    - result BIT-EQUAL to the flat ``panels`` schedule (same relayout);
    - coalesced: inter-host messages per step <= hosts-1 — O(hosts),
      NOT O(panels) — and strictly fewer total DCN messages than the
      flat panel exchange on the same topology
      (``dcn_messages < flat_messages``);
    - no write amplification: ``dcn_bytes_moved`` <= the deviceput
      floor (the rows-whose-host-changes bytes ANY schedule must move);
    - the router actually ran the hierarchical tier (schedule counter
      ``rechunk_dcn``) and auto-routing picks it on a multi-host mesh;
    - the relayout genuinely crosses hosts (``dcn_messages > 0``) — a
      config whose padded row intervals align proves nothing.
    """
    import jax
    import dislib_tpu as ds
    from dislib_tpu.ops import rechunk as _rc
    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.utils import profiling as _prof

    prev = os.environ.get("DSLIB_MOCK_HOSTS")
    os.environ["DSLIB_MOCK_HOSTS"] = str(mock_hosts)
    try:
        ndev = len(jax.devices())
        if ndev % (2 * mock_hosts):
            raise RuntimeError(
                f"dcn bench needs a device count divisible by "
                f"2*mock_hosts={2 * mock_hosts}, have {ndev}")
        src, dst = (ndev, 1), (ndev // 2, 2)
        rng = np.random.RandomState(0)
        x_host = rng.rand(m, n).astype(np.float32)
        ds.init(src)
        a = ds.array(x_host).force()
        ds.init(dst)

        acct = _rc.dcn_accounting(a._data, a.shape, _mesh.get_mesh(),
                                  panels=panels)
        hosts = acct["hosts"]
        assert hosts == mock_hosts, \
            f"mock host map bled: {hosts} hosts, wanted {mock_hosts}"
        assert acct["dcn_messages"] > 0, (
            f"vacuous config: m={m} pads identically under {src} and "
            f"{dst} — no rows change host, pick a misaligning m")
        assert acct["messages_per_step_max"] <= hosts - 1, (
            f"NOT coalesced: {acct['messages_per_step_max']} messages in "
            f"one step exceeds hosts-1={hosts - 1} — O(panels) leak")
        assert acct["dcn_messages"] < acct["flat_messages"], (
            f"hierarchical schedule sends {acct['dcn_messages']} DCN "
            f"messages, the flat exchange only {acct['flat_messages']}")
        assert acct["dcn_bytes_moved"] <= acct["deviceput_bytes"], (
            f"write amplification: {acct['dcn_bytes_moved']} DCN bytes "
            f"exceed the {acct['deviceput_bytes']} deviceput floor")

        # correctness gate: bit-equal to the flat panel schedule, and the
        # router counted the hierarchical tier (+ auto picks it here)
        _prof.reset_counters()
        out_dcn = ds.rechunk(a, schedule="dcn", panels=panels)
        scheds = _prof.schedule_counters()
        ran = sum(v for k, v in scheds.items()
                  if k.startswith("rechunk_dcn:"))
        assert ran == 1, f"rechunk_dcn not counted exactly once: {scheds}"
        out_flat = ds.rechunk(a, schedule="panels", panels=panels)
        np.testing.assert_array_equal(np.asarray(out_dcn._data),
                                      np.asarray(out_flat._data),
                                      err_msg="dcn != panels (bit-equal "
                                              "gate)")
        auto = _rc.pick_schedule(a._data, _mesh.get_mesh(), "auto")
        assert auto == "dcn", \
            f"auto-routing picked {auto!r} on a {hosts}-host mesh"

        def run(schedule):
            y = ds.rechunk(a, schedule=schedule, panels=panels)
            _sync(y._data)

        run("dcn")
        t = _median_time(lambda: run("dcn"))
        t_flat = _median_time(lambda: run("panels"))
        moved = (int(np.prod(a._pshape))
                 + int(np.prod(out_dcn._pshape))) * 4
        return {"metric": f"dcn_rechunk_{tag}_gb_per_sec (baseline: flat "
                          "panel exchange, same relayout)",
                "value": round(moved / t / 1e9, 3), "unit": "GB/s",
                "vs_baseline": round(t_flat / t, 2),
                "wall_s": round(t, 5), "flat_wall_s": round(t_flat, 5),
                "mesh_src": list(src), "mesh_dst": list(dst),
                "hosts": hosts,
                "dcn_messages": acct["dcn_messages"],
                "flat_messages": acct["flat_messages"],
                "messages_per_step_max": acct["messages_per_step_max"],
                "messages_per_step_bound": hosts - 1,
                "dcn_bytes_moved": acct["dcn_bytes_moved"],
                "deviceput_bytes": acct["deviceput_bytes"],
                "steps": acct["steps"], "panels": acct["panels"],
                "note": "gates: bit-equal to the flat panel schedule, "
                        "messages/step <= hosts-1 (coalesced, O(hosts) "
                        "not O(panels)), dcn_messages < flat_messages, "
                        "dcn_bytes <= deviceput floor, rechunk_dcn "
                        "counted, auto-routing picks dcn multi-host; "
                        "mock-host overlay (DSLIB_MOCK_HOSTS) — wall "
                        "clock is intra-process, accounting is the "
                        "evidence"}
    finally:
        if prev is None:
            os.environ.pop("DSLIB_MOCK_HOSTS", None)
        else:
            os.environ["DSLIB_MOCK_HOSTS"] = prev


def bench_overlap(kind, m, n, tag, hidden_floor=0.0, panels=4, repeats=9):
    """Comm–compute overlap tier (round-13 PR): how much of the panel
    collective does the double-buffered schedule actually hide under
    compute, per schedule family (``kind`` = summa | rechunk | ring).

    ``comm_hidden_frac`` = (t_seq − t_db) / t_comm_alone, where
    t_comm_alone comes from a BROADCAST-ONLY variant of the same program
    (identical collectives, the compute replaced by a (1, 1) touch per
    panel — ``comm_only=True`` on the kernel), so the fraction is
    normalized by the comm the pipeline could possibly hide: 1.0 = the
    whole collective disappeared under compute, 0 = no overlap, < 0 =
    the pipelined program is slower (a scheduling regression).

    Gates, all failing the config loudly:
    - db and seq results BIT-EQUAL (same panel order, identical ops);
    - ONE dispatch under the db schedule (dispatch counters), and the
      router observably ran it (schedule counters);
    - ``comm_hidden_frac`` >= ``hidden_floor``
      (``DSLIB_OVERLAP_HIDDEN_MIN`` overrides — the vs_peak noisy-rig
      escape.  On host-core rigs the collectives are memcpys through
      shared caches, so the honest floor is "no pathological slowdown";
      real ICI is where the hidden fraction is the roofline claim);
    - double-buffer memory bound via ``compiled.memory_analysis()``:
      the db program's peak-live stays within the documented
      one-extra-panel budget — rechunk (out + temp)/in <= min(1 + 2/k,
      the tier's 1.5x ceiling) (``DSLIB_OVERLAP_PEAK_RATIO_MAX``
      overrides); summa/ring: temp(db) − temp(seq) <= one in-flight
      panel set (+1/2 panel slack for scheduler variance) — the double
      buffer must cost ONE panel of live memory, never an operand copy.
    Rows carry ``fresh: true`` — the stale-fallback machinery flips it
    (and stamps ``stale_origin``) on any replay."""
    import jax
    import dislib_tpu as ds
    from dislib_tpu.utils import profiling as _prof

    src, dst = _mesh_2d_shapes("overlap")
    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)

    extra = {}
    if kind == "summa":
        from dislib_tpu.ops import precision as px
        from dislib_tpu.ops.summa import summa_matmul
        ds.init(src)
        mesh = ds.get_mesh()
        a = ds.array(x_host).force()
        b = ds.array(rng.rand(n, m).astype(np.float32)).force()
        ad, bd = a._data, b._data
        policy = px.FLOAT32

        def run(sched, comm_only=False):
            _sync(summa_matmul(ad, bd, mesh, policy, overlap=sched,
                               comm_only=comm_only))

        def lower(sched):
            return summa_matmul.lower(ad, bd, mesh, policy, overlap=sched)

        out_db = np.asarray(summa_matmul(ad, bd, mesh, policy,
                                         overlap="db"))
        out_seq = np.asarray(summa_matmul(ad, bd, mesh, policy,
                                          overlap="seq"))
        # the kernel's own step-count formula — keeps the one-extra-panel
        # memory gate anchored to ops/summa's schedule.  PER-DEVICE
        # bytes (memory_analysis accounts one device): the broadcast A
        # panel lives (M/rows, kb) on each device, the B panel (kb,
        # N/cols) (review-found: global bytes made the bound ~mesh-
        # factor too loose)
        from dislib_tpu.ops.summa import summa_steps
        steps = summa_steps(mesh)
        panel_set = (ad.size // src[0]
                     + bd.size // src[1]) * ad.dtype.itemsize // steps
        counter_key, expect = "summa_matmul", 1
        # the routed entry (math.matmul) must counter-visibly run the
        # schedule the env selects
        ds.matmul(a, b, algorithm="summa").force()
        sched_counts = _prof.schedule_counters()
        assert any(k.startswith("summa_matmul:") for k in sched_counts), \
            f"summa route left no schedule counter: {sched_counts}"
    elif kind == "rechunk":
        from dislib_tpu.ops import rechunk as _rc
        from dislib_tpu.parallel import mesh as _mesh_mod
        ds.init(src)
        a = ds.array(x_host).force()
        ds.init(dst)
        dst_mesh = _mesh_mod.get_mesh()

        def run(sched, comm_only=False):
            if comm_only:
                _sync(_rc.panel_comm_probe(a._data, a.shape, dst_mesh,
                                           panels, overlap=sched))
            else:
                _sync(_rc.panel_rechunk(a._data, a.shape, dst_mesh, panels,
                                        overlap=sched))

        out_db = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst_mesh,
                                              panels, overlap="db"))
        out_seq = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst_mesh,
                                               panels, overlap="seq"))
        ma_db = _rc.panel_memory_analysis(a._data, a.shape, dst_mesh,
                                          panels, overlap="db")
        ratio = ma_db["peak_live_ratio"] if ma_db["peak_live_ratio"] \
            is not None else ma_db["analytic_ratio"]
        ratio_max = float(os.environ.get(
            "DSLIB_OVERLAP_PEAK_RATIO_MAX", min(1.0 + 2.0 / panels, 1.5)))
        if ratio > ratio_max:
            msg = (f"OVERLAP MEMORY GATE FAILED: double-buffered rechunk "
                   f"peak-live {ratio:.3f}x exceeds the {ratio_max:.3f}x "
                   "bound (1 + 2/k against the tier's 1.5x ceiling) — the "
                   "extra in-flight panel must cost one panel, not a copy")
            print(msg, file=sys.stderr, flush=True)
            raise AssertionError(msg)
        extra.update({"peak_live_ratio_db": ratio,
                      "peak_live_ratio_max": ratio_max,
                      "panels": ma_db["panels"]})
        steps = ma_db["panels"]
        panel_set = ma_db["analytic_temp_bytes"]
        counter_key, expect = "rechunk_panels", 1
        lower = None
    elif kind == "ring":
        from dislib_tpu.ops.ring import ring_kneighbors
        from dislib_tpu.parallel import mesh as _mesh_mod
        ds.init(src)
        mesh = _mesh_mod.get_mesh()
        k_nn = 5
        # asymmetric shapes: FEW query rows against the full fitted set,
        # so the rotated shard (the hideable comm) is a meaningful share
        # of each step — the fold at square shapes dwarfs the rotation
        # and the hidden fraction would measure pure scheduler noise
        mq = max(64, m // 16)
        q = ds.array(x_host[:mq]).force()
        f = ds.array(x_host).force()
        qd, fd = q._data, f._data

        def run(sched, comm_only=False):
            out = ring_kneighbors(qd, fd, mesh, k_nn, m, overlap=sched,
                                  comm_only=comm_only)
            _sync(*(out if isinstance(out, tuple) else (out,)))

        def lower(sched):
            return ring_kneighbors.lower(qd, fd, mesh, k_nn, m,
                                         overlap=sched)

        d_db, i_db = ring_kneighbors(qd, fd, mesh, k_nn, m, overlap="db")
        d_seq, i_seq = ring_kneighbors(qd, fd, mesh, k_nn, m, overlap="seq")
        out_db = np.concatenate([np.asarray(d_db),
                                 np.asarray(i_db, np.float32)], axis=1)
        out_seq = np.concatenate([np.asarray(d_seq),
                                  np.asarray(i_seq, np.float32)], axis=1)
        steps = src[0]
        # rotated set per hop, PER-DEVICE (memory_analysis accounts one
        # device): the (rows_loc, n/cols) fitted block + its norms + ids
        # (review-found: the global feature dim made the bound too loose)
        rows_loc = fd.shape[0] // src[0]
        panel_set = rows_loc * (fd.shape[1] // src[1] + 2) \
            * fd.dtype.itemsize
        # counter-assert the PUBLIC path: one profiled ring dispatch per
        # kneighbors call (the estimator boundary)
        nn = ds.NearestNeighbors(n_neighbors=k_nn, ring=True).fit(f)
        nn.kneighbors(q)                    # warm
        _prof.reset_counters()
        nn.kneighbors(q)
        got = _prof.counters()["dispatch_by"].get("ring_kneighbors")
        assert got == 1, \
            f"ring kneighbors path cost {got} ring dispatches, expected 1"
    else:
        raise ValueError(f"unknown overlap bench kind {kind!r}")

    # bit-equality gate: the two schedules consume panels in identical
    # order with identical ops
    np.testing.assert_array_equal(out_db, out_seq)

    # dispatch gate under the db schedule (the ring KERNEL is counted at
    # its estimator boundary — asserted in the ring branch above)
    run("db")                               # warm
    if kind != "ring":
        _prof.reset_counters()
        run("db")
        d = _prof.counters()["dispatch_by"].get(counter_key, 0)
        assert d == expect, \
            f"{kind} db schedule cost {d} dispatches, expected {expect}"

    # summa/ring memory bound: the db program's temp may exceed seq's by
    # at most one in-flight panel set (+50% scheduler slack) — XLA's own
    # accounting of "the double buffer costs one panel, not a copy"
    if kind in ("summa", "ring") and lower is not None:
        try:
            t_db = int(lower("db").compile().memory_analysis()
                       .temp_size_in_bytes)
            t_seq = int(lower("seq").compile().memory_analysis()
                        .temp_size_in_bytes)
        except Exception:   # noqa: BLE001 — backend without the analysis
            t_db = t_seq = None
        if t_db is not None:
            slack = max(panel_set // 2, 65536)
            assert t_db <= t_seq + panel_set + slack, (
                f"OVERLAP MEMORY GATE FAILED: {kind} db temp {t_db} vs seq "
                f"{t_seq} — the double buffer costs more than one "
                f"in-flight panel set ({panel_set} B)")
            extra.update({"temp_bytes_db": t_db, "temp_bytes_seq": t_seq,
                          "panel_set_bytes": panel_set})

    # timing: both schedules + the broadcast-only probe, all steady-state.
    # INTERLEAVED rounds + BEST-of wall (the _peak_gflops precedent):
    # the hidden fraction is a DIFFERENCE of two walls divided by a
    # small third — on a cpu-shares-throttled container, (a) measuring
    # the schedules in separate blocks lets throttle drift bias the
    # difference, so each round times db, seq and the probe back to
    # back, and (b) median contention noise swamps the delta, while the
    # min wall estimates each schedule's uncontended cost
    run("seq")
    run("seq", comm_only=True)
    walls = {"db": [], "seq": [], "comm": []}
    for _ in range(repeats):
        for key, fn in (("db", lambda: run("db")),
                        ("seq", lambda: run("seq")),
                        ("comm", lambda: run("seq", comm_only=True))):
            t0 = time.perf_counter()
            fn()
            walls[key].append(time.perf_counter() - t0)
    t_db = float(min(walls["db"]))
    t_seq = float(min(walls["seq"]))
    t_comm = float(min(walls["comm"]))
    hidden = (t_seq - t_db) / t_comm if t_comm > 0 else 0.0
    floor = float(os.environ.get("DSLIB_OVERLAP_HIDDEN_MIN", hidden_floor))
    res = {"metric": f"overlap_{kind}_{tag}_comm_hidden_frac (baseline: "
                     "sequential-phase schedule, same program)",
           "value": round(hidden, 3), "unit": "frac",
           "vs_baseline": round(t_seq / t_db, 3) if t_db > 0 else None,
           "db_wall_s": round(t_db, 5), "seq_wall_s": round(t_seq, 5),
           "comm_alone_wall_s": round(t_comm, 5),
           "comm_hidden_floor": floor, "steps": steps,
           "dispatches_per_op": 1, "fresh": True,
           "note": "comm_hidden = (t_seq - t_db) / t_comm_alone; "
                   "t_comm_alone = broadcast-only variant of the same "
                   "program; gates: db==seq bit-equal, 1 dispatch, "
                   "peak-live within one extra in-flight panel",
           **extra}
    if hidden < floor:
        msg = (f"OVERLAP GATE FAILED: {kind} comm-hidden fraction "
               f"{hidden:.3f} below the {floor:.3f} floor — the "
               "double-buffered schedule is not hiding comm on this rig")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


def bench_fused_chain(dim, n_ops, tag):
    """Fused-chain microbench (round-7 fusion PR): ONE user-visible op
    chain — scale/add/transpose rounds ending in a matmul — forced as a
    single XLA dispatch, vs the same chain under DSLIB_EAGER=1 paying one
    dispatch per op.  The chain is rebuilt inside the timed region (graph
    construction is part of the fused path's cost); results are gated
    bit-identical between the two modes.  `value` is the speedup — the
    measured answer to "what did the fusion layer buy on this rig"."""
    import dislib_tpu as ds
    from dislib_tpu.utils import profiling as prof

    rng = np.random.RandomState(0)
    x_host = rng.rand(dim, dim).astype(np.float32)
    a = ds.array(x_host, block_size=(dim, dim)).force()

    def chain():
        y = a
        for i in range(n_ops // 4):
            y = ((y * 1.0001 + 0.0001).T - 0.0001).T
        y = ds.matmul(y, a, transpose_a=True)
        return y

    def run():
        y = chain()
        y.force()
        _sync(y._data)

    old = os.environ.pop("DSLIB_EAGER", None)
    try:
        run()                                   # fused warmup/compile
        prof.reset_counters()
        run()
        fused_disp = prof.dispatch_count()
        fused_ref = chain().collect()
        t_fused = _median_time(run)

        os.environ["DSLIB_EAGER"] = "1"
        run()                                   # eager warmup/compile
        prof.reset_counters()
        run()
        eager_disp = prof.dispatch_count()
        # correctness gate: shared op bodies ⇒ identical rounding per op;
        # the one permitted divergence is XLA's in-program FMA contraction
        # (≤ 1 ulp per mul→add round — see data/array.py::_exec_program),
        # so the bound scales with the chain's contraction count
        eager_ref = chain().collect()
        np.testing.assert_allclose(fused_ref, eager_ref,
                                   rtol=n_ops * 3e-7, atol=1e-6)
        t_eager = _median_time(run)
    finally:
        if old is None:
            os.environ.pop("DSLIB_EAGER", None)
        else:
            os.environ["DSLIB_EAGER"] = old
    speedup = t_eager / t_fused
    return {"metric": f"fused_chain_{tag}_{n_ops}ops_speedup_vs_eager "
                      "(baseline: same chain, DSLIB_EAGER=1 per-op "
                      "dispatch)",
            "value": round(speedup, 2), "unit": "x",
            "vs_baseline": round(speedup, 2),
            "fused_wall_s": round(t_fused, 5),
            "eager_wall_s": round(t_eager, 5),
            "dispatches_fused": fused_disp,
            "dispatches_eager": eager_disp,
            "note": "one forced chain per region; dispatches_* from the "
                    "utils.profiling counters"}


def _predict_dispatches(est, a) -> int:
    """``dispatches_per_predict`` from the utils.profiling counters: warm
    the predict program, then count one fresh end-to-end call (force
    included) — the "one program per result, not per op" claim as a
    number, now measured for every counted estimator (round-9 satellite:
    the counters are what caught the CSVM/forest host-sync hops)."""
    from dislib_tpu.utils import profiling as _prof
    est.predict(a).force()                  # warm/compile
    _prof.reset_counters()
    est.predict(a).force()
    return _prof.dispatch_count()


def bench_serving(m, n, k, n_requests, tag, buckets=(1, 8, 64, 512),
                  deadline_ms=2):
    """Serving-layer bench (round-9 tentpole): warm request p50/p99/QPS
    through the micro-batching server vs the per-call COLD
    ``predict().force()`` path — each cold call hits a padded shape the
    jit cache has never seen, which is exactly what an unbucketed request
    loop pays (every new batch size = a fresh trace+compile).

    Hard asserts (regression gates, not just reported numbers):
    - every warm served batch is EXACTLY one fused XLA dispatch
      (profiling counters through the server's per-batch accounting);
    - served labels bit-match the direct pipeline's labels.
    """
    import dislib_tpu as ds
    from dislib_tpu.parallel import mesh as _mesh_mod
    from dislib_tpu.serving import PredictServer, ServePipeline

    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    a = ds.array(x_host, block_size=(m, n))
    scaler = ds.StandardScaler().fit(a)
    est = ds.KMeans(n_clusters=k, max_iter=5, random_state=0).fit(a)
    pipe = ServePipeline(est, transforms=(scaler,), n_features=n)

    # correctness gate: the served bucket path == the direct pipeline
    probe = x_host[: buckets[1]]
    direct = np.asarray(
        est.predict(scaler.transform(ds.array(probe))).collect())
    np.testing.assert_array_equal(pipe.predict_bucket(probe, buckets[1]),
                                  direct)

    # COLD path: per-call predict at FRESH padded shapes (each row count
    # below lands on a padded shape no earlier call compiled)
    q = _mesh_mod.pad_quantum()
    cold = []
    for i in range(1, 8):
        rows = x_host[: q * i + 1]
        t0 = time.perf_counter()
        out = est.predict(scaler.transform(ds.array(rows))).force()
        _sync(out._data)
        cold.append(time.perf_counter() - t0)
    cold_p50 = float(np.median(cold))

    # WARM path: the server (buckets AOT-warmed at start()) under a
    # burst-submitted request stream of mixed sizes
    sizes = rng.randint(1, min(buckets[-2], 64) + 1, n_requests)
    starts = rng.randint(0, m - int(sizes.max()), n_requests)
    reqs = [x_host[s:s + sz] for s, sz in zip(starts, sizes)]
    with PredictServer(pipeline=pipe, buckets=buckets,
                       deadline_ms=deadline_ms) as srv:
        futs = [srv.submit(r) for r in reqs]
        outs = [f.result(timeout=120) for f in futs]
        st = srv.stats()
    assert st["dispatches_per_batch_max"] == 1, \
        f"serving dispatch invariant broken: {st}"
    for r, o in zip(reqs, outs):
        assert o.values.shape == (len(r), 1) \
            and np.all(np.isfinite(o.values)), "bad served response"
    p50 = st["p50_ms"]
    return {"metric": f"serving_{tag}_warm_p50_ms (baseline: per-call "
                      "cold predict().force() at fresh shapes)",
            "value": p50, "unit": "ms",
            "vs_baseline": round(cold_p50 * 1e3 / p50, 2),
            "p99_ms": st["p99_ms"], "qps": st["qps"],
            "rows_per_s": st["rows_per_s"],
            "requests": st["requests"], "batches": st["batches"],
            "dispatches_per_batch_max": st["dispatches_per_batch_max"],
            "cold_p50_ms": round(cold_p50 * 1e3, 3),
            "deadline_ms": deadline_ms, "buckets": list(buckets),
            "note": "warm batches asserted 1 fused dispatch each; cold = "
                    "scaler+predict+force per call, fresh padded shape "
                    "(trace+compile on the request path); vs_baseline = "
                    "cold_p50 / warm_p50"}


def bench_serving_fleet(m, n, k, n_requests, tag, buckets=(1, 8, 64),
                        deadline_ms=2, coldstart_min=None):
    """Round-15 tentpole tier: AOT deployment bundles + multi-tenant
    routing.

    Leg 1 — COLD START: time-to-first-response-for-the-whole-ladder in a
    cache-cleared process, with vs without the bundle.  Without: every
    bucket pays its trace+compile (``jax.clear_caches()`` reproduces the
    fresh-process state in-process; the subprocess twin lives in
    ``tests/test_serving_fleet.py``).  With: ``load_bundle`` deserializes
    the compiled executables and serves — gated ZERO traces.

    Leg 2 — FLEET: three tenants on ONE shared server serving the
    bundle pipeline under a mixed-shape burst; QPS and per-tenant p99
    come from the server's OWN per-tenant accounting (round-15
    satellite), not from timing wrapped around it.

    Hard gates: cold/bundle ratio >= ``coldstart_min``
    (``DSLIB_BUNDLE_COLDSTART_MIN``, default 10 — calibrated ~16x on the
    reference rig), zero traces on the bundle path AND under tenant
    load, zero shed, one fused dispatch per warm batch, bundle
    predictions bit-equal to the in-process pipeline's.
    """
    import tempfile
    import jax
    import dislib_tpu as ds
    from dislib_tpu.serving import (ModelRouter, PredictServer,
                                    ServePipeline, export_bundle,
                                    load_bundle)
    from dislib_tpu.utils import profiling as _prof

    if coldstart_min is None:
        coldstart_min = float(os.environ.get("DSLIB_BUNDLE_COLDSTART_MIN",
                                             "10"))
    # the harness's persistent compilation cache (main() sets
    # JAX_COMPILATION_CACHE_DIR for every child) would let the "cold" leg
    # replay its compiles from disk and understate what a genuinely fresh
    # process pays — this config measures cold start, so it opts out (it
    # runs in its own child process; no other config is affected)
    try:
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:  # noqa: BLE001 — older jaxlib: flag absent, cache off
        pass
    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    a = ds.array(x_host, block_size=(m, n))
    scaler = ds.StandardScaler().fit(a)
    est = ds.KMeans(n_clusters=k, max_iter=5, random_state=0).fit(a)
    pipe = ServePipeline(est, transforms=(scaler,), n_features=n)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "model.dsb.npz")
        export_bundle(pipe, path, buckets=buckets)
        ref = {b: pipe.predict_bucket(x_host[: min(b, 16)], b)
               for b in buckets}

        # cold start WITHOUT the bundle: first response for every ladder
        # bucket pays trace+compile
        jax.clear_caches()
        t0 = time.perf_counter()
        for b in buckets:
            pipe.predict_bucket(x_host[:1], b)
        cold_s = time.perf_counter() - t0

        # cold start WITH the bundle: deserialize + first batch, and not
        # one trace anywhere
        jax.clear_caches()
        tr0 = _prof.trace_count()
        t0 = time.perf_counter()
        loaded = load_bundle(path)
        for b in buckets:
            loaded.pipeline.predict_bucket(x_host[:1], b)
        bundle_s = time.perf_counter() - t0
        bundle_traces = _prof.trace_count() - tr0
        ratio = cold_s / bundle_s
        for b in buckets:
            np.testing.assert_array_equal(
                loaded.pipeline.predict_bucket(x_host[: min(b, 16)], b),
                ref[b])
        if bundle_traces:
            raise AssertionError(
                f"bundle path traced {bundle_traces}x — the zero-retrace "
                "cold-start claim is broken")
        if ratio < coldstart_min:
            raise AssertionError(
                f"bundle cold-start speedup {ratio:.2f}x < gate "
                f"{coldstart_min}x (cold {cold_s * 1e3:.1f} ms, bundle "
                f"{bundle_s * 1e3:.1f} ms; override via "
                "DSLIB_BUNDLE_COLDSTART_MIN)")

        # fleet leg: 3 tenants x mixed shapes on one shared server
        tenants = ("alpha", "beta", "gamma")
        srv = PredictServer(pipeline=loaded.pipeline, buckets=buckets,
                            deadline_ms=deadline_ms, name="fleet")
        router = ModelRouter(name="fleet")
        for t in tenants:
            router.add_tenant(t, srv)
        sizes = rng.randint(1, min(buckets[-1], 64) + 1, n_requests)
        starts = rng.randint(0, m - int(sizes.max()), n_requests)
        tr0 = _prof.trace_count()
        with router:
            futs = [router.submit(x_host[s:s + sz], tenants[i % 3],
                                  key=str(i))
                    for i, (s, sz) in enumerate(zip(starts, sizes))]
            outs = [f.result(timeout=120) for f in futs]
            st = srv.stats()
        if _prof.trace_count() != tr0:
            raise AssertionError("multi-tenant load compiled something — "
                                 "executable sharing is broken")
        if st["dispatches_per_batch_max"] != 1:
            raise AssertionError(f"serving dispatch invariant broken: {st}")
        if st["shed"] or any(v["shed"] for v in st["tenants"].values()):
            raise AssertionError(f"requests shed under fleet load: {st}")
        for o in outs:
            if not np.all(np.isfinite(o.values)):
                raise AssertionError("bad served response")
        per_tenant = {t: {"requests": st["tenants"][t]["requests"],
                          "p50_ms": st["tenants"][t]["p50_ms"],
                          "p99_ms": st["tenants"][t]["p99_ms"]}
                      for t in tenants}

    return {"metric": f"serving_fleet_{tag}_coldstart_ratio (baseline: "
                      "fresh-process trace+compile of the whole ladder)",
            "value": round(ratio, 2), "unit": "x",
            "vs_baseline": round(ratio, 2),
            "coldstart_min_gate": coldstart_min,
            "cold_ms": round(cold_s * 1e3, 3),
            "bundle_ms": round(bundle_s * 1e3, 3),
            "bundle_traces": bundle_traces,
            "fleet_qps": st["qps"], "fleet_p99_ms": st["p99_ms"],
            "tenants": per_tenant,
            "requests": st["requests"], "batches": st["batches"],
            "dispatches_per_batch_max": st["dispatches_per_batch_max"],
            "shed": st["shed"],
            "deadline_ms": deadline_ms, "buckets": list(buckets),
            "fresh": True,
            "note": "leg 1: cold = clear_caches + per-bucket "
                    "trace+compile; bundle = load_bundle + first batch, "
                    "zero traces gated.  leg 2: 3 tenants share one "
                    "server/executable set; per-tenant p50/p99 read from "
                    "the server's own stats()"}


def bench_trainer(rows, n, k, generations, tag, batches_per_generation=4,
                  buckets=(1, 8, 64), deadline_ms=2):
    """Round-17 tier: the continuous-learning loop end-to-end —
    ``ContinuousTrainer`` drives train → bundle → canary → promote for
    ``generations`` cadences of a streaming ``MiniBatchKMeans`` against
    a live ``ModelRouter`` tenant, and the row reads the cadence the
    loop sustains plus where the wall goes (train vs export vs promote,
    per-phase from the promotion ledger's own timings).

    Hard gates: every generation promotes (the canary health gate passes
    a clean stream), the served generation lands on the last one, the
    post-promotion burst through the router performs ZERO traces (the
    canary serves deserialized AOT executables — promotion never
    recompiles the predict path), every response finite, and the on-disk
    ``ledger.jsonl`` replays the in-memory promotion ledger exactly."""
    import tempfile
    import dislib_tpu as ds
    from dislib_tpu.runtime import ContinuousTrainer
    from dislib_tpu.serving import ModelRouter, ServePipeline
    from dislib_tpu.utils import FitCheckpoint
    from dislib_tpu.utils import profiling as _prof

    rng = np.random.RandomState(0)
    centers = (rng.rand(k, n) * 10).astype(np.float32)

    def stream():
        while True:
            lab = rng.randint(0, k, rows)
            yield (centers[lab]
                   + 0.3 * rng.randn(rows, n)).astype(np.float32)

    probe = (centers[rng.randint(0, k, 16)]
             + 0.3 * rng.randn(16, n)).astype(np.float32)
    with tempfile.TemporaryDirectory() as td:
        router = ModelRouter(name="trainer-bench")
        tr = ContinuousTrainer(
            ds.MiniBatchKMeans(n_clusters=k, random_state=0), stream(),
            FitCheckpoint(os.path.join(td, "ck.npz"), every=2, keep=2),
            lambda est, g: ServePipeline(est, n_features=n),
            os.path.join(td, "bundles"), router=router, tenant="alpha",
            buckets=buckets, batches_per_generation=batches_per_generation,
            probe=probe, deadline_ms=deadline_ms, name="bench-trainer")
        t_train = 0.0
        burst_traces = 0
        with router:
            t_all = time.perf_counter()
            for _ in range(generations):
                t0 = time.perf_counter()
                if not tr.train_generation():
                    raise AssertionError("infinite stream exhausted?!")
                t_train += time.perf_counter() - t0
                rec = tr.publish_generation()
                if rec["verdict"] != "promoted":
                    raise AssertionError(
                        f"clean generation {rec['generation']} not "
                        f"promoted: {rec}")
                # post-promotion burst: mixed shapes through the router,
                # zero traces gated — promotion must never recompile the
                # predict path
                tr0 = _prof.trace_count()
                futs = [router.submit(probe[: 1 + (i % len(probe))],
                                      "alpha",
                                      key=f"g{rec['generation']}:{i}")
                        for i in range(16)]
                outs = [f.result(timeout=120) for f in futs]
                burst_traces += _prof.trace_count() - tr0
                for o in outs:
                    if not np.all(np.isfinite(o.values)):
                        raise AssertionError("bad served response")
            wall = time.perf_counter() - t_all
            stats = tr.stats()
            tr.close()
        if burst_traces:
            raise AssertionError(
                f"promotion bursts traced {burst_traces}x — the "
                "zero-retrace promotion claim is broken")
        if stats["promotions"] != generations \
                or stats["served_generation"] != generations:
            raise AssertionError(f"promotion ledger off: {stats}")
        with open(os.path.join(td, "bundles", "ledger.jsonl")) as f:
            disk = [json.loads(line) for line in f]
        if disk != tr.ledger:
            raise AssertionError("ledger.jsonl does not replay the "
                                 "in-memory promotion ledger")
        exp = [r["export_s"] for r in tr.ledger if "export_s" in r]
        pro = [r["promote_s"] for r in tr.ledger if "promote_s" in r]

    return {"metric": f"trainer_{tag}_generations_per_min (train -> "
                      "bundle -> canary -> promote cadence, all promoted)",
            "value": round(generations / (wall / 60.0), 2),
            "unit": "gen/min", "vs_baseline": None,
            "generations": generations,
            "batches_per_generation": batches_per_generation,
            "train_s_per_gen": round(t_train / generations, 4),
            "export_s_per_gen": round(float(np.mean(exp)), 4),
            "export_s_max": round(float(np.max(exp)), 4),
            "promote_s_per_gen": round(float(np.mean(pro)), 4),
            "burst_traces": burst_traces,
            "batches": stats["batches"],
            "quarantined_rows": stats["quarantine"]["n_quarantined"],
            "buckets": list(buckets), "fresh": True,
            "note": "per-phase walls from the promotion ledger's own "
                    "export_s/promote_s; gates: all generations promoted, "
                    "zero traces on the post-promotion burst, finite "
                    "responses, ledger.jsonl == in-memory ledger"}


def bench_resilience(m, n, k, iters, tag, every=2):
    """Resilience-layer row (round-12): a NaN-poisoned chunked KMeans fit
    heals through the fit-loop driver's rollback ladder.  Three gates,
    all hard: (1) the healed model equals the unfaulted checkpointed fit;
    (2) dispatch parity — the resilience counters are host-side integers,
    so the ONLY extra device work of the healed fit is the one re-run
    chunk (PR-2/PR-3 counter baseline + exactly 1); (3) the counters
    actually recorded the rollback.  ``value`` is the healed fit's wall —
    informational; the gates are the point."""
    import tempfile
    import dislib_tpu as ds
    from dislib_tpu.cluster import KMeans
    from dislib_tpu.utils import FitCheckpoint, faults
    from dislib_tpu.utils import profiling as _prof

    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    init = x_host[rng.choice(m, k, replace=False)].copy()
    a = ds.array(x_host, block_size=(m, n))
    kw = dict(n_clusters=k, init=init, max_iter=iters, tol=0.0)
    with tempfile.TemporaryDirectory() as td:
        ck = FitCheckpoint(os.path.join(td, "w.npz"), every=every)
        KMeans(**kw).fit(a, checkpoint=ck)          # warm the compiles
        ck.delete()
        _prof.reset_counters()
        ref = KMeans(**kw).fit(
            a, checkpoint=FitCheckpoint(os.path.join(td, "r.npz"),
                                        every=every))
        clean = _prof.counters()["dispatch_by"].get("kmeans_fit", 0)
        pol = faults.NaNAtChunk(at_chunk=2)
        _prof.reset_counters()
        t0 = time.perf_counter()
        res = KMeans(**kw).fit(
            a, checkpoint=FitCheckpoint(os.path.join(td, "f.npz"),
                                        every=every),
            health=pol)
        heal_wall = time.perf_counter() - t0
        faulted = _prof.counters()
    np.testing.assert_allclose(res.centers_, ref.centers_, rtol=1e-5)
    extra = faulted["dispatch_by"].get("kmeans_fit", 0) - clean
    r = faulted["resilience"]
    if pol.fired != 1:
        raise AssertionError("fault was never injected")
    if extra != 1:
        raise AssertionError(
            f"healed fit cost {extra} extra fit dispatches — the counters "
            "or the driver added device work beyond the 1 re-run chunk")
    if r.get("rollbacks") != 1 or r.get("chunk_retries") != 1:
        raise AssertionError(f"resilience counters did not record the "
                             f"rollback: {r}")
    return {"metric": f"resilience_{tag}_heal_wall_s",
            "value": round(heal_wall, 4), "unit": "s", "vs_baseline": None,
            "fault": f"NaNAtChunk(at_chunk=2) over {iters} iters, "
                     f"every={every}",
            "rollbacks": r["rollbacks"], "chunk_retries": r["chunk_retries"],
            "escalations_retry": r.get("escalations_retry", 0),
            "extra_fit_dispatches": extra,
            "clean_fit_dispatches": clean,
            "healed_equals_unfaulted": True}


def bench_mh_resilience(tag, max_wall_s=480.0, recovery_max_s=30.0):
    """Round-20 multi-host survival tier: the REAL process-killing chaos
    drill (``tools/mh_dryrun.py --chaos``) as a gated bench row.  Two
    coordinated CPU processes; one is SIGKILLed mid-fit, restarted,
    heartbeat-delayed, fed torn coordination/ledger writes, and killed
    again at the sharded-bundle load barrier.  Gates, all hard:

    - the drill PASSES — typed attributed ``RankDead``, the survivor's
      resumed model equals the shrunk-fleet oracle, the restart rejoins
      under a bumped epoch (stale writes fenced) and grows back, torn
      files heal as TRANSIENT, and BOTH barrier-abort modes are typed;
    - zero hangs — the whole episode is bounded by ``max_wall_s`` (the
      drill additionally hard-bounds every internal wait);
    - recovery wall — death → published shrunk capacity under
      ``recovery_max_s``;
    - the rank_deaths / rank_rejoins / mesh_shrinks / mesh_grows /
      bundle_barrier_abort counters all actually recorded.

    ``value`` is the full-episode wall — informational; the gates are
    the point (the ``bench_resilience`` precedent)."""
    import shutil
    import tempfile
    here = os.path.dirname(os.path.abspath(__file__))
    driver = os.path.join(here, "tools", "mh_dryrun.py")
    workdir = tempfile.mkdtemp(prefix="dslib-bench-mh-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.perf_counter()
    try:
        try:
            proc = subprocess.run(
                [sys.executable, driver, "--chaos", workdir],
                env=env, capture_output=True, text=True,
                timeout=max_wall_s)
        except subprocess.TimeoutExpired:
            raise AssertionError(
                f"HANG: the chaos drill exceeded {max_wall_s}s")
        wall = time.perf_counter() - t0
        out = proc.stdout + proc.stderr
        if proc.returncode != 0 or "MULTIHOST CHAOS: PASS" not in out:
            raise AssertionError(
                f"chaos drill failed (rc={proc.returncode}): "
                f"{out[-2000:]}")
        with open(os.path.join(workdir, "chaos_result.json")) as f:
            result = json.load(f)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    c, t = result["counters"], result["timings"]
    for key, want in (("rank_deaths", 3), ("rank_rejoins", 2),
                      ("mesh_shrinks", 2), ("mesh_grows", 1),
                      ("bundle_barrier_abort", 2)):
        if c.get(key, 0) < want:
            raise AssertionError(
                f"counter {key}={c.get(key, 0)} < {want}: {c}")
    if t["death_to_capacity_s"] > recovery_max_s:
        raise AssertionError(
            f"recovery wall {t['death_to_capacity_s']:.2f}s exceeds "
            f"{recovery_max_s}s")
    return {"metric": f"mh_resilience_{tag}_episode_wall_s",
            "value": round(wall, 2), "unit": "s", "vs_baseline": None,
            "death_to_capacity_s": round(t["death_to_capacity_s"], 2),
            "barrier_abort_attributed_s":
                round(t["barrier_abort_attributed_s"], 2),
            "barrier_abort_deadline_s":
                round(t["barrier_abort_deadline_s"], 2),
            "counters": c, "healed_equals_shrunk_oracle": True,
            "rejoin_epoch_fenced": True, "hangs": 0}


def bench_rtt(repeats=21):
    """Fixed per-dispatch round-trip floor of this backend (informational).

    Times a trivial jitted op (8×8 add) plus a 1-element fetch — the same
    dispatch+sync structure every timed config pays exactly once per run.
    On the axon tunnel this is ~69 ms (2026-07-31), which dominates every
    short-wall-clock row; BASELINE.md's interpretation section uses this
    number to separate tunnel latency from on-chip compute."""
    return {"metric": "dispatch_rtt_trivial_op_ms "
                      "(informational: per-call latency floor)",
            "value": round(1e3 * _measure_rtt(repeats), 2), "unit": "ms",
            "vs_baseline": None}


def bench_tsqr(m, n):
    """tsQR wall clock — measures BOTH local-factorisation policies
    (Householder tree and CholeskyQR2) and reports the auto policy's
    number as the headline, so one on-chip capture IS the A/B that
    decides whether the TPU-gated CholeskyQR2 path stays (round-4
    measurably-better rule; the flag is a static retrace key, so flipping
    it between timed regions is sound)."""
    import dislib_tpu as ds

    rng = np.random.RandomState(0)
    x_host = rng.standard_normal((m, n)).astype(np.float32)
    t0 = time.perf_counter()
    np.linalg.qr(x_host)
    cpu_wall = time.perf_counter() - t0

    a = ds.array(x_host, block_size=(m // max(1, len(__import__("jax").devices())), n))
    q, r = ds.tsqr(a)  # warmup + correctness gate (auto policy)
    qh, rh = q.collect(), r.collect()
    np.testing.assert_allclose(qh @ rh, x_host, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(qh.T @ qh, np.eye(n), atol=1e-2)

    def run():
        q, r = ds.tsqr(a)
        _sync(q, r)

    variants = {}
    old = os.environ.get("DSLIB_TSQR_CHOLQR")
    try:
        for name, flag in (("tree", "0"), ("cholqr2", "1")):
            os.environ["DSLIB_TSQR_CHOLQR"] = flag
            run()                                   # warmup/compile
            variants[name] = _median_time(run)
    finally:
        if old is None:
            os.environ.pop("DSLIB_TSQR_CHOLQR", None)
        else:
            os.environ["DSLIB_TSQR_CHOLQR"] = old
    # the headline is whichever variant the ambient policy selects — no
    # third timed region (it would duplicate one of the two, and label it
    # 'auto' even when the caller forced the env)
    from dislib_tpu.decomposition.tsqr import _use_cholqr
    policy = "cholqr2" if _use_cholqr() else "tree"
    t = variants[policy]
    return {"metric": f"tsqr_{m}x{n}_wall_s (baseline: numpy qr single-node)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "tree_wall_s": round(variants["tree"], 4),
            "cholqr2_wall_s": round(variants["cholqr2"], 4),
            "note": f"value = the active policy's ({policy}) measurement; "
                    "tree/cholqr2 fields are the explicit A/B"}


def bench_randomsvd(m, n, nsv=64, iters=2):
    import dislib_tpu as ds
    from dislib_tpu.decomposition import random_svd

    rng = np.random.RandomState(0)
    # Spectral decay (0.95^j column scaling) makes the 1% gate well-posed:
    # on a FLAT Gaussian spectrum, sketch-and-project with oversample=10
    # leaves ~6% error vs the exact values for BOTH the device path and
    # the proxy, and since the two draw DIFFERENT test matrices Ω (jax vs
    # numpy RNG) their estimates differ by up to ~1.5% from each other —
    # the pre-round-8 smoke-gate flake, reproduced back to PR 1.  With
    # decay (the workload truncated SVD exists for) both land within
    # ~0.2% of the exact spectrum; the timed GEMMs are value-independent,
    # so the wall-clock metric is unaffected.  Regression-pinned by
    # tests/test_math.py::test_randomsvd_smoke_gate_margin.
    x_host = (rng.standard_normal((m, n))
              * 0.95 ** np.arange(n)).astype(np.float32)
    sketch = nsv + 10
    t0 = time.perf_counter()
    _, s_proxy, _ = _numpy_random_svd(x_host, sketch, iters)
    cpu_wall = time.perf_counter() - t0

    a = ds.array(x_host, block_size=(m // 8, n))
    u, s, v = random_svd(a, iters=iters, nsv=nsv, oversample=10,
                         random_state=0)  # warmup
    # correctness gate: top singular values match the proxy to 1%
    s_dev = np.asarray(s.collect()).ravel()[:nsv]
    np.testing.assert_allclose(s_dev[:16], s_proxy[:16], rtol=1e-2)

    def run():
        u, s, v = random_svd(a, iters=iters, nsv=nsv, oversample=10,
                             random_state=0)
        _sync(u, s, v)
    t = _median_time(run)
    return {"metric": f"randomsvd_{m}x{n}_nsv{nsv}_wall_s "
                      "(baseline: numpy same-algorithm single-node proxy)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2)}


def bench_svd(m, n):
    """One-sided block-Jacobi SVD wall clock (informational config — the
    column-BLOCK pair tier, reference's own pairing, MXU-shaped)."""
    import dislib_tpu as ds

    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)
    t0 = time.perf_counter()
    s_ref = np.linalg.svd(x_host, compute_uv=False)
    cpu_wall = time.perf_counter() - t0

    a = ds.array(x_host, block_size=(m // 4, n))
    u, s, v = ds.svd(a)  # warmup + correctness gate
    s_dev = np.asarray(s.collect()).ravel()
    np.testing.assert_allclose(s_dev, s_ref, rtol=1e-3, atol=1e-3 * s_ref[0])

    def run():
        u, s, v = ds.svd(a)
        _sync(u, s, v)
    t = _median_time(run)
    return {"metric": f"svd_{m}x{n}_wall_s (baseline: numpy lapack svd "
                      "single-node)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2)}


def bench_gmm(m, n, k, iters=5):
    import dislib_tpu as ds
    from dislib_tpu.cluster import GaussianMixture

    rng = np.random.RandomState(0)
    x_host = rng.standard_normal((m, n)).astype(np.float32)
    means0 = x_host[rng.choice(m, k, replace=False)].copy()

    w = np.full(k, 1.0 / k, np.float32)
    covs = np.tile(np.eye(n, dtype=np.float32)[None], (k, 1, 1))
    t0 = time.perf_counter()
    w2, mu2, covs2 = _numpy_gmm_iter(x_host, w, means0.copy(), covs)
    cpu_iter_wall = time.perf_counter() - t0
    cpu_wall = cpu_iter_wall * iters

    a = ds.array(x_host, block_size=(m, n))
    gm = GaussianMixture(n_components=k, max_iter=iters, tol=0.0,
                         init_params="random", random_state=0)
    gm.fit(a)  # warmup/compile
    assert np.isfinite(gm.lower_bound_)

    t = _median_time(lambda: GaussianMixture(
        n_components=k, max_iter=iters, tol=0.0, init_params="random",
        random_state=0).fit(a))
    return {"metric": f"gmm_{m}x{n}_k{k}_{iters}it_wall_s "
                      "(baseline: numpy full-cov EM single-node proxy x iters)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "dispatches_per_predict": _predict_dispatches(gm, a)}


def _numpy_csvm_fit(x, y_pm, part, c, gamma, max_iter, arity=2):
    """Same-algorithm cascade proxy: K+1-augmented boxed dual solved by
    projected gradient ascent (Gershgorin step, ≤500 steps, 1e-6 delta —
    the device solver's exact loop), SV merge up an arity tree, global SV
    feedback.  Mirrors classification/csvm.py with NumPy GEMVs."""
    m = x.shape[0]

    def solve(idx):
        xs = x[idx]
        sq = (xs * xs).sum(1)
        d = np.maximum(sq[:, None] - 2.0 * (xs @ xs.T) + sq[None, :], 0.0)
        k = np.exp(-gamma * d) + 1.0
        q = k * np.outer(y_pm[idx], y_pm[idx])
        eta = 1.0 / max(np.abs(q).sum(1).max(), 1e-12)
        a = np.zeros(len(idx), np.float32)
        for _ in range(500):
            new = np.clip(a + eta * (1.0 - q @ a), 0.0, c).astype(np.float32)
            delta = np.abs(new - a).max()
            a = new
            if delta <= 1e-6:
                break
        return a, a.sum() - 0.5 * a @ (q @ a)

    sv = alpha = None
    for _ in range(max_iter):
        nodes = [np.arange(s, min(s + part, m)) for s in range(0, m, part)]
        if sv is not None and len(sv):
            nodes = [np.unique(np.r_[nd, sv]) for nd in nodes]
        while True:
            res = [solve(nd) for nd in nodes]
            if len(nodes) == 1:
                break
            merged = []
            for i in range(0, len(nodes), arity):
                grp = []
                for j in range(i, min(i + arity, len(nodes))):
                    grp.extend(nodes[j][res[j][0] > 1e-8].tolist())
                merged.append(np.unique(grp) if grp else nodes[i][:1])
            nodes = merged
        a, _ = res[0]
        keep = a > 1e-8
        sv, alpha = nodes[0][keep], a[keep]
    return sv, alpha


def bench_csvm(m, n, tag, max_iter=3, part=1024):
    """CascadeSVM fit wall clock — the first irregular-tier row (round-3
    verdict #8): cascades of masked fixed-capacity dual solves, nothing
    like the dense-linalg tier's single fused program."""
    import dislib_tpu as ds
    from dislib_tpu.classification import CascadeSVM

    rng = np.random.RandomState(0)
    half = m // 2
    x_host = np.vstack([rng.randn(half, n) + 2.0,
                        rng.randn(m - half, n) - 2.0]).astype(np.float32)
    y_host = np.r_[np.ones(half), -np.ones(m - half)].astype(np.float32)
    perm = rng.permutation(m)
    x_host, y_host = x_host[perm], y_host[perm]
    gamma = 1.0 / n

    t0 = time.perf_counter()
    sv, alpha = _numpy_csvm_fit(x_host, y_host, part, 1.0, gamma, max_iter)
    cpu_wall = time.perf_counter() - t0
    # proxy correctness gate: its SV model must classify the blobs
    k_dec = np.exp(-gamma * np.maximum(
        ((x_host * x_host).sum(1)[:, None] - 2.0 * x_host @ x_host[sv].T
         + (x_host[sv] * x_host[sv]).sum(1)[None]), 0.0)) + 1.0
    proxy_acc = float(np.mean(np.sign(k_dec @ (alpha * y_host[sv])) == y_host))
    assert proxy_acc > 0.95, f"proxy cascade degenerate: acc={proxy_acc}"

    a = ds.array(x_host, block_size=(part, n))
    ya = ds.array(y_host.reshape(-1, 1), block_size=(part, 1))

    def fit_once():
        est = CascadeSVM(kernel="rbf", c=1.0, gamma=gamma,
                         max_iter=max_iter, check_convergence=False)
        est.fit(a, ya)
        return est

    # explicit solver A/B (the tsqr tree/cholqr2 precedent): time BOTH
    # dual solvers; `value` stays the active policy's measurement so the
    # row is comparable across rounds, and the fista field is the
    # evidence for flipping the auto policy (round-5: PG's 1/k rate often
    # hits the 500-step cap; FISTA converges in fewer sequential steps —
    # the cascade's latency driver)
    from dislib_tpu.classification.csvm import _use_fista
    walls = {}
    accs = {}
    ests = {}
    old = os.environ.get("DSLIB_CSVM_SOLVER")
    try:
        for sv in ("pg", "fista"):
            os.environ["DSLIB_CSVM_SOLVER"] = sv
            est = fit_once()  # warmup/compile (per-solver trace)
            ests[sv] = est
            accs[sv] = est.score(a, ya)
            assert accs[sv] > 0.95 and accs[sv] > proxy_acc - 0.02, \
                f"device cascade ({sv}) acc {accs[sv]} vs proxy {proxy_acc}"
            walls[sv] = _median_time(lambda: fit_once())
    finally:
        if old is None:
            os.environ.pop("DSLIB_CSVM_SOLVER", None)
        else:
            os.environ["DSLIB_CSVM_SOLVER"] = old
    # the headline value is whatever THIS environment's policy ships —
    # one source of truth (_use_fista), so a future auto-flip or an
    # operator override keeps the row comparable to production
    active = "fista" if _use_fista() else "pg"
    t = walls[active]
    acc = accs[active]
    return {"metric": f"csvm_{tag}_rbf_{max_iter}it_fit_wall_s "
                      "(baseline: numpy same-algorithm cascade proxy)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "device_train_acc": round(acc, 4),
            "proxy_train_acc": round(proxy_acc, 4),
            "dispatches_per_predict": _predict_dispatches(ests[active], a),
            "pg_wall_s": round(walls["pg"], 4),
            "fista_wall_s": round(walls["fista"], 4),
            "fista_train_acc": round(accs["fista"], 4),
            "note": f"value = the active policy's ({active}) measurement; "
                    "pg/fista fields are the explicit solver A/B"}


def bench_gridsearch(m, n, cands, folds, kmeans_iters, tag):
    """GridSearchCV wall clock over KMeans candidates — the first measured
    search-throughput row; on TPU it exercises the pipelined async-trial
    protocol (all fits of a fold in flight before any host read), which
    the cpu rig deliberately serializes (round-3 verdict weak #3)."""
    import dislib_tpu as ds
    from dislib_tpu.cluster import KMeans
    from dislib_tpu.model_selection import GridSearchCV

    rng = np.random.RandomState(0)
    x_host = rng.rand(m, n).astype(np.float32)

    # proxy: same folds (contiguous KFold splits), same fixed-iteration
    # Lloyd's per candidate, NumPy single-node
    t0 = time.perf_counter()
    bounds = np.linspace(0, m, folds + 1).astype(int)
    for k in cands:
        for f in range(folds):
            tr = np.concatenate([x_host[: bounds[f]], x_host[bounds[f + 1]:]])
            c = tr[:k].copy()
            for _ in range(kmeans_iters):
                c = _numpy_kmeans_iter(tr, c)
    cpu_wall = time.perf_counter() - t0

    a = ds.array(x_host, block_size=(max(1, m // 8), n))

    def search_once():
        gs = GridSearchCV(KMeans(random_state=0, max_iter=kmeans_iters,
                                 tol=0.0),
                          {"n_clusters": list(cands)}, cv=folds, refit=False)
        gs.fit(a)
        return gs

    gs = search_once()  # warmup/compile + gate
    scores = gs.cv_results_["mean_test_score"]
    assert np.all(np.isfinite(scores)) and len(scores) == len(cands)
    assert gs.best_index_ == int(np.argmax(scores))
    t = _median_time(lambda: search_once())
    return {"metric": f"gridsearch_kmeans_{tag}_{len(cands)}x{folds}fits_"
                      "wall_s (baseline: numpy same-folds kmeans proxy)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2)}


# --- round-5 rows: the estimator tier (VERDICT r4 missing #3) --------------

def _blobs(m, n, k, seed=0, std=0.08):
    """k well-separated gaussian blobs on the unit cube — shared synthetic
    for the estimator-tier rows (labels = blob id)."""
    rng = np.random.RandomState(seed)
    centers = rng.rand(k, n).astype(np.float32)
    lab = rng.randint(0, k, m)
    x = centers[lab] + std * rng.standard_normal((m, n)).astype(np.float32)
    return x.astype(np.float32), lab.astype(np.int64)


def _numpy_dbscan(x, eps, min_samples, chunk=4096):
    """Same-algorithm DBSCAN: chunked ε-graph, connected components of the
    core-core graph, border points joined to their first core neighbor.
    Returns (labels, eps_wall) — the ε-pass wall is the O(m²) part and is
    reported separately so the caller can scale it quadratically and the
    graph/relabel tail sub-quadratically."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import connected_components
    m = x.shape[0]
    eps2 = eps * eps
    xsq = (x * x).sum(1)
    # ONE chunked ε-pass: all neighbor pairs are kept (counts via
    # bincount, core-core edges and border targets filtered afterwards) —
    # a second distance pass would double eps_wall and overstate the
    # baseline this proxy exists to understate
    t_eps = time.perf_counter()
    pr, pc = [], []
    for s in range(0, m, chunk):
        d = xsq[s:s + chunk, None] - 2.0 * (x[s:s + chunk] @ x.T) + xsq[None]
        r, c = np.nonzero(d <= eps2)
        pr.append(r + s)
        pc.append(c)
    pr = np.concatenate(pr)
    pc = np.concatenate(pc)
    eps_wall = time.perf_counter() - t_eps
    counts = np.bincount(pr, minlength=m)
    core = counts >= min_samples
    to_core = core[pc]
    rows = pr[to_core & core[pr]]
    cols = pc[to_core & core[pr]]
    # border target: first core neighbor of each non-core point
    border_to = np.full(m, -1, np.int64)
    bsel = to_core & ~core[pr]
    # reversed so the FIRST core neighbor (lowest col per row) wins
    border_to[pr[bsel][::-1]] = pc[bsel][::-1]
    g = sp.csr_matrix((np.ones(len(rows), np.int8), (rows, cols)),
                      shape=(m, m))
    n_comp, comp = connected_components(g, directed=False)
    labels = np.full(m, -1, np.int64)
    labels[core] = comp[core]
    join = (~core) & (border_to >= 0)
    labels[join] = comp[border_to[join]]
    # renumber compactly over the labels that survived (vectorised)
    used, inv = np.unique(labels[labels >= 0], return_inverse=True)
    labels[labels >= 0] = inv
    return labels, eps_wall


def _same_partition_on_core(lab_a, lab_b, core_mask):
    """True iff the two labelings induce the SAME partition of the core
    points (bijective label correspondence — border ties may legally
    differ between schedules)."""
    a, b = lab_a[core_mask], lab_b[core_mask]
    if (a < 0).any() or (b < 0).any():
        return False
    pairs = set(zip(a.tolist(), b.tolist()))
    return len(pairs) == len(set(p[0] for p in pairs)) == \
        len(set(p[1] for p in pairs))


def bench_dbscan(m, n, tag, proxy_m=None):
    """DBSCAN on the tiled-streamed tier (m > dense-max on a 1-row mesh).
    Proxy: same-algorithm NumPy at ``proxy_m`` rows (the matmul proxy_dim
    precedent): its ε-pass wall scales by (m/proxy)², the graph/label tail
    by (m/proxy) — a conservative under-statement of the true baseline.
    Gate: device labels at the proxy shape induce the proxy's exact core
    partition."""
    import dislib_tpu as ds
    from dislib_tpu.cluster import DBSCAN

    proxy_m = proxy_m or m
    eps, min_samples = 0.35, 5
    xp_host, _ = _blobs(proxy_m, n, k=16, seed=3)
    t0 = time.perf_counter()
    lab_proxy, eps_wall = _numpy_dbscan(xp_host, eps, min_samples)
    total_wall = time.perf_counter() - t0
    ratio = m / proxy_m
    # only the ε-pass is O(m²); the graph/label tail scales with the edge
    # count — super-linear for fixed eps but below m², so scaling it by
    # ratio (not ratio²) UNDER-states the proxy and keeps vs_baseline
    # conservative
    cpu_wall = eps_wall * ratio ** 2 + (total_wall - eps_wall) * ratio

    # correctness gate at the proxy shape
    fit_small = DBSCAN(eps=eps, min_samples=min_samples) \
        .fit(ds.array(xp_host, block_size=(4096, n)))
    core_mask = np.zeros(proxy_m, bool)
    core_mask[fit_small.core_sample_indices_] = True
    assert _same_partition_on_core(fit_small.labels_, lab_proxy, core_mask), \
        "dbscan gate: device core partition != numpy proxy"
    noise_dev = int((fit_small.labels_ < 0).sum())
    noise_prx = int((lab_proxy < 0).sum())
    assert abs(noise_dev - noise_prx) <= max(5, 0.01 * proxy_m), \
        f"dbscan gate: noise count {noise_dev} vs proxy {noise_prx}"

    x_host, _ = _blobs(m, n, k=16, seed=4)
    a = ds.array(x_host, block_size=(8192, n))
    DBSCAN(eps=eps, min_samples=min_samples).fit(a)     # warmup/compile
    t = _median_time(lambda: DBSCAN(eps=eps, min_samples=min_samples).fit(a))
    return {"metric": f"dbscan_{tag}_wall_s (baseline: numpy same-algorithm "
                      f"proxy at {proxy_m} rows; eps-pass x(m/proxy)^2, "
                      "graph tail x(m/proxy))",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2)}


def _numpy_daura(x, cutoff, chunk=2048):
    """Same-algorithm greedy GROMOS clustering: RMSD ε-adjacency
    (RMSD² = ‖xi − xj‖²/n_atoms, rows are 3·n_atoms coords), then repeat
    {pick the active frame with the most active neighbors, extract it and
    its neighbors as one cluster}."""
    m = x.shape[0]
    eps2 = cutoff * cutoff * (x.shape[1] // 3)
    xsq = (x * x).sum(1)
    adj = np.zeros((m, m), bool)
    for s in range(0, m, chunk):
        d = xsq[s:s + chunk, None] - 2.0 * (x[s:s + chunk] @ x.T) + xsq[None]
        adj[s:s + chunk] = d <= eps2
    active = np.ones(m, bool)
    labels = np.full(m, -1, np.int64)
    cid = 0
    while active.any():
        counts = (adj & active[None, :]).sum(1)
        counts[~active] = -1
        medoid = int(np.argmax(counts))
        members = active & adj[medoid]
        members[medoid] = True
        labels[members] = cid
        active &= ~members
        cid += 1
    return labels


def bench_daura(m, n, tag, proxy_m=None):
    """Daura (greedy GROMOS) on the tiled tier.  Proxy: same-algorithm
    NumPy at ``proxy_m`` rows scaled by (m/proxy)² — BOTH phases (ε-pass
    and per-cluster neighbor recounts) are quadratic.  Gate: identical
    partition at the proxy shape (well-separated blobs → the greedy order
    is unambiguous)."""
    import dislib_tpu as ds
    from dislib_tpu.cluster import Daura

    proxy_m = proxy_m or m
    cutoff = 0.3
    xp_host, _ = _blobs(proxy_m, n, k=12, seed=6, std=0.05)
    t0 = time.perf_counter()
    lab_proxy = _numpy_daura(xp_host, cutoff)
    cpu_wall = (time.perf_counter() - t0) * (m / proxy_m) ** 2

    # the gate must exercise the SAME tier the timed run takes (the
    # dbscan precedent): full-mode proxy_m sits above daura's dense-max
    # (16384) so both gate and timed fit stream tiles; smoke stays dense
    fit_small = Daura(cutoff=cutoff).fit(ds.array(xp_host,
                                                  block_size=(4096, n)))
    assert fit_small.labels_.min() >= 0
    assert _same_partition_on_core(fit_small.labels_, lab_proxy,
                                   np.ones(proxy_m, bool)), \
        "daura gate: device partition != numpy greedy proxy"

    x_host, _ = _blobs(m, n, k=12, seed=7, std=0.05)
    a = ds.array(x_host, block_size=(8192, n))
    warm = Daura(cutoff=cutoff).fit(a)                  # warmup/compile
    # sanity on the RESULT being timed, not just the gate shape
    n_clusters = int(warm.labels_.max()) + 1
    assert 1 < n_clusters < m // 10, \
        f"daura full-size result degenerate: {n_clusters} clusters"
    t = _median_time(lambda: Daura(cutoff=cutoff).fit(a))
    return {"metric": f"daura_{tag}_wall_s (baseline: numpy same-algorithm "
                      f"greedy proxy at {proxy_m} rows x (m/proxy)^2)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "n_clusters": n_clusters}


def _numpy_hist_tree_level(bx, node, w, y_onehot, n_nodes, n_bins):
    """One level of the same histogram-tree algorithm (gini), NumPy."""
    m, n = bx.shape
    k = y_onehot.shape[1]
    hist = np.zeros((n_nodes, n, n_bins, k), np.float32)
    np.add.at(hist, (node[:, None], np.arange(n)[None, :], bx),
              (w[:, None] * y_onehot)[:, None, :])
    left = np.cumsum(hist, axis=2)
    total = left[:, :, -1:, :]
    right = total - left

    def gini(s):
        wts = s.sum(-1)
        p = s / np.maximum(wts[..., None], 1e-12)
        return wts * (1.0 - (p * p).sum(-1))

    gain = gini(total) - gini(left) - gini(right)
    gain[:, :, -1] = -np.inf
    wl, wr = left.sum(-1), right.sum(-1)
    gain[~((wl > 0) & (wr > 0))] = -np.inf
    flat = gain.reshape(n_nodes, -1)
    best = flat.argmax(1)
    feat = (best // n_bins).astype(np.int64)
    tbin = best % n_bins
    is_split = flat[np.arange(n_nodes), best] > 0.0
    feat[~is_split] = 0
    tbin[~is_split] = n_bins - 1
    go_right = (bx[np.arange(m), feat[node]] > tbin[node]) & is_split[node]
    return node * 2 + go_right.astype(node.dtype)


def bench_forest(m, n, n_trees, tag, depth=8):
    """RandomForest fit + predict.  Proxy: the same histogram-tree
    algorithm in NumPy, ONE tree's growth × n_trees (per-tree scaling —
    the trees are independent).  Gate: device train accuracy ≥ 0.95 on
    separable blobs AND ≥ proxy-tree accuracy − 5 pts."""
    import dislib_tpu as ds
    from dislib_tpu.trees import RandomForestClassifier

    n_bins = 32
    x_host, lab = _blobs(m, n, k=8, seed=5)
    y_host = (lab % 2).astype(np.float32)[:, None]

    # numpy proxy: one bootstrap tree, same binning + level loop
    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    qs = np.linspace(0, 100, n_bins + 1)[1:-1]
    edges = np.percentile(x_host, qs, axis=0).T
    bx = (x_host[:, :, None] > edges[None]).sum(2)
    w = rng.poisson(1.0, m).astype(np.float32)
    y1 = np.zeros((m, 2), np.float32)
    y1[np.arange(m), y_host.ravel().astype(np.int64)] = 1.0
    node = np.zeros(m, np.int64)
    for lvl in range(depth):
        node = _numpy_hist_tree_level(bx, node, w, y1, 2 ** lvl, n_bins)
    leaf_stats = np.zeros((2 ** depth, 2), np.float32)
    np.add.at(leaf_stats, node, w[:, None] * y1)
    proxy_tree_wall = time.perf_counter() - t0
    cpu_wall = proxy_tree_wall * n_trees
    pred_proxy = leaf_stats.argmax(1)[node]
    proxy_acc = float((pred_proxy == y_host.ravel()).mean())

    a = ds.array(x_host, block_size=(8192, n))
    yb = ds.array(y_host, block_size=(8192, 1))

    def fit_predict():
        rf = RandomForestClassifier(n_estimators=n_trees, max_depth=depth,
                                    random_state=0)
        rf.fit(a, yb)
        return rf, np.asarray(rf.predict(a).collect()).ravel()

    rf0, pred0 = fit_predict()                          # warmup/compile
    acc = float((pred0 == y_host.ravel()).mean())
    assert acc >= 0.95 and acc >= proxy_acc - 0.05, \
        f"forest gate: device {acc} vs proxy tree {proxy_acc}"
    t = _median_time(lambda: fit_predict())
    return {"metric": f"forest_{tag}_{n_trees}t_fit_predict_wall_s "
                      "(baseline: numpy same-algorithm histogram tree "
                      "x n_trees)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "device_train_acc": round(acc, 4),
            "proxy_train_acc": round(proxy_acc, 4),
            "dispatches_per_predict": _predict_dispatches(rf0, a)}


def bench_knn(m_fit, n, mq, k, tag):
    """kNN query throughput over a streamed (chunked) fit set.  Proxy:
    chunked NumPy brute force, same algorithm.  Gate: device distances ==
    NumPy on a query subset."""
    import dislib_tpu as ds
    from dislib_tpu.neighbors import NearestNeighbors

    rng = np.random.RandomState(1)
    fit_host = rng.rand(m_fit, n).astype(np.float32)
    q_host = rng.rand(mq, n).astype(np.float32)

    def numpy_knn(q):
        out = np.empty((len(q), k), np.float32)
        fsq = (fit_host * fit_host).sum(1)
        for s in range(0, len(q), 1024):
            d = ((q[s:s + 1024] ** 2).sum(1)[:, None]
                 - 2.0 * q[s:s + 1024] @ fit_host.T + fsq[None])
            # partition, not sort: O(m) top-k is what any reasonable
            # brute-force baseline does (review: a full row sort would
            # inflate the proxy wall several-fold)
            top = np.partition(d, k - 1, axis=1)[:, :k]
            out[s:s + 1024] = np.sort(top, axis=1)
        return np.sqrt(np.maximum(out, 0.0))

    t0 = time.perf_counter()
    d_proxy = numpy_knn(q_host)
    cpu_wall = time.perf_counter() - t0

    nn = NearestNeighbors(n_neighbors=k).fit(
        ds.array(fit_host, block_size=(8192, n)))
    qa = ds.array(q_host, block_size=(8192, n))
    d_dev, _ = nn.kneighbors(qa)                        # warmup/compile
    d_dev_h = np.asarray(d_dev.collect())
    gate = np.abs(np.sort(d_dev_h, 1) - np.sort(d_proxy[: mq], 1)).max()
    assert gate < 1e-2, f"knn gate: max distance error {gate}"

    def run():
        d, i = nn.kneighbors(qa)
        _sync(d, i)
    t = _median_time(run)
    return {"metric": f"knn_{tag}_k{k}_queries_per_sec "
                      "(baseline: numpy chunked brute force)",
            "value": round(mq / t, 1), "unit": "queries/s",
            "vs_baseline": round(cpu_wall / t, 2),
            "wall_s": round(t, 4)}


def bench_ann(m, d, mq, k, nlist, nprobe, tag, kmeans_max_iter=2):
    """Round-18 IVF-ANN retrieval tier vs the EXACT kneighbors ring at
    the same scale on the same backend.  Gates: recall@k ≥
    ``DSLIB_ANN_RECALL_MIN`` (0.95, tie-tolerant: a found id counts if
    its true distance is within the k-th oracle distance + eps) and
    speedup ≥ ``DSLIB_ANN_SPEEDUP_MIN`` (3×) over the exact ring scan,
    with the warm search counter-asserted as ONE fused dispatch / 0
    transfers / 0 traces.  QPS, p99, and pad waste are informational."""
    import dislib_tpu as ds
    from dislib_tpu.neighbors import NearestNeighbors
    from dislib_tpu.retrieval import IVFIndex
    from dislib_tpu.utils import profiling as prof

    rng = np.random.RandomState(3)
    # clustered catalog — the regime IVF exists for (uniform data has no
    # list structure to exploit); blob count = nlist so the quantizer has
    # a natural partition to find even at tiny max_iter
    centers = rng.standard_normal((nlist, d)).astype(np.float32) * 4.0
    x = (centers[rng.randint(0, nlist, m)]
         + rng.standard_normal((m, d))).astype(np.float32)
    q = (centers[rng.randint(0, nlist, mq)]
         + rng.standard_normal((mq, d))).astype(np.float32)

    # exact oracle (host, f64, query-chunked so the distance slab never
    # materializes at mq×m) with the tie band
    xf = x.astype(np.float64)
    xsq = (xf ** 2).sum(1)
    kth = np.empty(mq)
    for s in range(0, mq, 256):
        qc = q[s:s + 256].astype(np.float64)
        d2c = (qc ** 2).sum(1)[:, None] - 2.0 * qc @ xf.T + xsq[None]
        kth[s:s + 256] = np.partition(d2c, k - 1, axis=1)[:, k - 1]

    ix = IVFIndex(n_lists=nlist, nprobe=nprobe,
                  kmeans_max_iter=kmeans_max_iter, random_state=0).fit(x)
    qa = ds.array(q)
    _, idx = ix.search(qa, k=k, nprobe=nprobe)          # warmup/compile
    found = np.asarray(idx.collect()).astype(np.int64)
    d_found = ((q[:, None, :].astype(np.float64)
                - xf[found]) ** 2).sum(-1)              # (mq, k) only
    hit = (d_found <= kth[:, None] + 1e-4) & (found >= 0)
    recall = float(hit.mean())
    recall_min = float(os.environ.get("DSLIB_ANN_RECALL_MIN", "0.95"))
    assert recall >= recall_min, (
        f"ann recall@{k} {recall:.4f} < {recall_min} "
        "(DSLIB_ANN_RECALL_MIN)")

    # the one-dispatch contract on the warm hot path
    prof.reset_counters()
    dist, idx = ix.search(qa, k=k, nprobe=nprobe)
    _sync(dist, idx)
    c = prof.counters()
    assert c["dispatch_by"].get("ivf_search") == 1, c["dispatch_by"]
    assert c["transfers"] == 0 and c["traces"] == 0, c

    nn = NearestNeighbors(n_neighbors=k).fit(
        ds.array(x, block_size=(8192, d)))
    de, ie = nn.kneighbors(qa)                          # warmup/compile
    _sync(de, ie)

    def run_exact():
        dd, ii = nn.kneighbors(qa)
        _sync(dd, ii)

    def run_ann():
        dd, ii = ix.search(qa, k=k, nprobe=nprobe)
        _sync(dd, ii)

    t_exact = _median_time(run_exact)
    walls = []
    for _ in range(9):
        t0 = time.perf_counter()
        run_ann()
        walls.append(time.perf_counter() - t0)
    t_ann = float(np.median(walls))
    speedup = t_exact / t_ann
    speedup_min = float(os.environ.get("DSLIB_ANN_SPEEDUP_MIN", "3"))
    assert speedup >= speedup_min, (
        f"ann speedup {speedup:.2f}x < {speedup_min}x vs the exact ring "
        f"(exact {t_exact:.4f}s, ann {t_ann:.4f}s; "
        "DSLIB_ANN_SPEEDUP_MIN)")
    return {"metric": f"ann_{tag}_k{k}_nprobe{nprobe}_queries_per_sec "
                      "(baseline: exact kneighbors ring, same backend)",
            "value": round(mq / t_ann, 1), "unit": "queries/s",
            "vs_baseline": round(speedup, 2),
            "recall_at_k": round(recall, 4),
            "p99_ms": round(1e3 * float(np.percentile(walls, 99)), 2),
            "pad_waste_frac": round(ix.pad_waste["waste_frac"], 4),
            "wall_s": round(t_ann, 4)}


def bench_als_sparse(n_users, n_items, nnz_per_user, tag, n_f=16, iters=3):
    """Sparse ALS (BCOO segment-sum path).  Proxy: same-algorithm NumPy —
    batched per-user/item normal equations from the triplets, ONE
    iteration × iters.  Gate: device training RMSE ≤ 1.3×proxy + 0.05
    (see the inline note on independent-init spread)."""
    import scipy.sparse as sp

    import dislib_tpu as ds  # noqa: F401  (package init = mesh init)
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.recommendation import ALS

    rng = np.random.RandomState(2)
    rows = np.repeat(np.arange(n_users), nnz_per_user)
    cols = rng.randint(0, n_items, rows.shape[0])
    u0 = rng.standard_normal((n_users, n_f)).astype(np.float32)
    v0 = rng.standard_normal((n_items, n_f)).astype(np.float32)
    vals = (u0[rows] * v0[cols]).sum(1) + \
        0.1 * rng.standard_normal(rows.shape[0]).astype(np.float32)
    csr = sp.csr_matrix((vals, (rows, cols)), shape=(n_users, n_items),
                        dtype=np.float32)
    lam = 0.065

    def numpy_als_half(fixed, rows_ix, cols_ix, v):
        """Solve one side's normal equations from the triplets (batched)."""
        nn_ = fixed.shape[1]
        g = np.zeros((int(rows_ix.max()) + 1, nn_, nn_), np.float32)
        b = np.zeros((int(rows_ix.max()) + 1, nn_), np.float32)
        f = fixed[cols_ix]
        np.add.at(g, rows_ix, f[:, :, None] * f[:, None, :])
        np.add.at(b, rows_ix, f * v[:, None])
        cnt = np.bincount(rows_ix, minlength=g.shape[0]).astype(np.float32)
        g += lam * np.maximum(cnt, 1.0)[:, None, None] * \
            np.eye(nn_, dtype=np.float32)[None]
        return np.linalg.solve(g, b[..., None])[..., 0]

    # proxy init is a FRESH random draw (not the generating factors u0/v0
    # — that would hand the proxy a converged start the device never gets)
    rng_p = np.random.RandomState(7)
    v_p = rng_p.standard_normal((n_items, n_f)).astype(np.float32)
    t0 = time.perf_counter()
    u_np = numpy_als_half(v_p, rows, cols, vals)
    _ = numpy_als_half(u_np, cols, rows, vals)
    cpu_wall = (time.perf_counter() - t0) * iters

    s_arr = SparseArray.from_scipy(csr)
    als = ALS(n_f=n_f, lambda_=lam, max_iter=iters, tol=0.0, random_state=0)
    als.fit(s_arr)                                      # warmup/compile
    pred = (als.users_[rows] * als.items_[cols]).sum(1)
    rmse_dev = float(np.sqrt(np.mean((pred - vals) ** 2)))
    # proxy RMSE after the same number of alternations from its random init
    for _ in range(iters):
        u_p = numpy_als_half(v_p, rows, cols, vals)
        v_p = numpy_als_half(u_p, cols, rows, vals)
    rmse_prx = float(np.sqrt(np.mean(
        ((u_p[rows] * v_p[cols]).sum(1) - vals) ** 2)))
    # gate width: device and proxy descend from INDEPENDENT random inits,
    # so after few iterations they sit in different basins — 1.3x + 0.05
    # catches a broken solver (rmse ~ O(1) garbage) without flaking on
    # legitimate init-to-init spread; both values are emitted for audit
    assert rmse_dev <= rmse_prx * 1.3 + 0.05, \
        f"als gate: device rmse {rmse_dev} vs proxy {rmse_prx}"

    t = _median_time(lambda: ALS(n_f=n_f, lambda_=lam, max_iter=iters,
                                 tol=0.0, random_state=0).fit(s_arr))
    return {"metric": f"als_sparse_{tag}_f{n_f}_{iters}it_wall_s "
                      "(baseline: numpy same-algorithm batched normal "
                      "equations x iters)",
            "value": round(t, 4), "unit": "s",
            "vs_baseline": round(cpu_wall / t, 2),
            "device_rmse": round(rmse_dev, 4),
            "proxy_rmse": round(rmse_prx, 4)}


def bench_sparse(m, n, k, density, tag, panels=4, min_speedup=2.0,
                 temp_ratio_max=1.0):
    """Round-14 sparse fast path: the sharded masked-psum SpMM vs the
    densify route (to_dense + dense GEMM — what every sparse matmul paid
    before this round), at recommender density, plus the fold-in serving
    dispatch.

    Gates (all fail the config loudly):
    - SpMM ≈ the densify oracle (the two contraction orders differ, so
      allclose at f32 tolerance), and db/seq overlap schedules BIT-equal;
    - ONE dispatch per SpMM, ZERO host transfers (counters);
    - O(nnz)-scaled peak-live: XLA's own memory analysis of the compiled
      SpMM — temporaries ≤ ``temp_ratio_max`` × one densified-A
      allocation (``DSLIB_SPMM_TEMP_RATIO_MAX`` overrides; the densify
      route's floor IS that allocation);
    - speedup = densify_wall / spmm_wall ≥ ``min_speedup``
      (``DSLIB_SPMM_SPEEDUP_MIN`` overrides) at ≤1% density.
    ``panels`` is recorded in the row.  Round 17: the default moved to 4
    — the col-partitioned slot-range layout collapsed per-entry masking
    work from O(steps·nse) to O(nse + steps·quantum), so the panel count
    is now a pure memory knob; the row carries the masking-work
    accounting (``spmm_masking_work``) as the evidence."""
    import scipy.sparse as sp

    import dislib_tpu as ds
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.ops.spmm import (spmm, spmm_masking_work,
                                     spmm_memory_analysis)
    from dislib_tpu.utils import profiling as _prof

    assert density <= 0.01 + 1e-9, "the headline gate is the ≤1% regime"
    rng = np.random.RandomState(0)
    ds.init()
    mat = sp.random(m, n, density=density, random_state=0,
                    dtype=np.float32).tocsr()
    xs = SparseArray.from_scipy(mat)
    b = ds.array(rng.rand(n, k).astype(np.float32)).force()
    xs.sharded()                                    # ingest outside timing

    # correctness gates: vs the densify route, and across schedules
    got = np.asarray(spmm(xs, b, panels=panels).collect())
    oracle = np.asarray(ds.matmul(xs, b, algorithm="densify").collect())
    np.testing.assert_allclose(got, oracle, rtol=2e-4, atol=2e-4)
    got_seq = np.asarray(spmm(xs, b, overlap="seq", panels=panels)
                         .collect())
    got_db = np.asarray(spmm(xs, b, overlap="db", panels=panels).collect())
    assert (got_db == got_seq).all(), "db/seq schedules not bit-equal"

    # dispatch / transfer gate
    _prof.reset_counters()
    y = spmm(xs, b, panels=panels)
    _sync(y._data)
    d, tr = (_prof.counters()["dispatch_by"].get("spmm_panels", 0),
             _prof.transfer_count())
    assert d == 1, f"spmm cost {d} dispatches, expected 1"
    assert tr == 0, f"spmm cost {tr} host transfers, expected 0"

    # O(nnz) peak-live gate: temporaries vs ONE densified-A allocation
    ma = spmm_memory_analysis(xs, b, panels=panels)
    ratio_max = float(os.environ.get("DSLIB_SPMM_TEMP_RATIO_MAX",
                                     temp_ratio_max))
    if ma["temp_vs_dense"] is not None and ma["temp_vs_dense"] > ratio_max:
        msg = (f"SPMM MEMORY GATE FAILED: temporaries at "
               f"{ma['temp_vs_dense']:.2f}x a densified operand exceed "
               f"the {ratio_max:.2f}x bound — the kernel is densifying")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)

    # the A/B: each densify call honestly pays the dense materialisation
    # (that IS the route's cost; it holds no cache)
    def run_spmm():
        _sync(spmm(xs, b, panels=panels)._data)

    def run_densify():
        _sync(ds.matmul(xs, b, algorithm="densify")._data)

    run_spmm()
    run_densify()
    # interleaved rounds + best-of walls (the bench_overlap precedent):
    # block-sequential medians let cpu-shares throttle drift bias the
    # ratio on this 2-vCPU rig — alternating the two arms and taking
    # each arm's best puts both under the same load profile
    t_sp, t_dn = [], []
    for _ in range(7):
        t0 = time.perf_counter()
        run_spmm()
        t_sp.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_densify()
        t_dn.append(time.perf_counter() - t0)
    t_sp, t_dn = min(t_sp), min(t_dn)
    speedup = t_dn / t_sp
    floor = float(os.environ.get("DSLIB_SPMM_SPEEDUP_MIN", min_speedup))

    # fold-in serving dispatch wall (informational): one padded sparse
    # batch of 8 users scored against n-item factors — the serve-side
    # unit of the recommender pipeline
    from dislib_tpu.recommendation import ALS
    from dislib_tpu.serving import SparseFoldInPipeline
    als = ALS(n_f=8, max_iter=2, tol=0.0, random_state=0)
    als.items_ = rng.rand(n, 8).astype(np.float32)
    als.users_ = rng.rand(1, 8).astype(np.float32)
    pipe = SparseFoldInPipeline(als, nse_cap=max(64, int(8 * density * n)))
    batch = pipe.pack(mat[:8])
    pipe.predict_bucket(batch, 8)                   # warm
    t_fold = _median_time(lambda: pipe.predict_bucket(batch, 8))

    # masking-work accounting: what the slot-range layout saves per
    # dispatch vs the legacy re-mask-everything layout at this panel
    # count — the "panels is a pure memory knob now" evidence
    mw = spmm_masking_work(xs, b, panels=panels)

    res = {"metric": f"sparse_{tag}_spmm_speedup_vs_densify (baseline: "
                     "to_dense + dense GEMM per product)",
           "value": round(speedup, 2), "unit": "x",
           "spmm_wall_s": round(t_sp, 4),
           "densify_wall_s": round(t_dn, 4),
           "shape": [m, n, k], "density": density, "nnz": int(mat.nnz),
           "panels": panels, "steps": ma["steps"],
           "masked_layout_work": mw["masked_work"],
           "slots_layout_work": mw["slots_work"],
           "masking_inflation_removed": mw["inflation"],
           "dispatches_per_op": 1, "host_transfers": 0,
           "temp_vs_dense": ma["temp_vs_dense"],
           "temp_ratio_max": ratio_max,
           "spmm_temp_bytes": ma["temp_bytes"],
           "dense_a_bytes": ma["dense_a_bytes"],
           "sparse_in_bytes": ma["sparse_in_bytes"],
           "foldin_serve_batch8_wall_s": round(t_fold, 4),
           "speedup_floor": floor, "fresh": True,
           "note": "gates: allclose vs densify oracle, db==seq bit-equal, "
                   "1 dispatch / 0 transfers, temp <= ratio_max x "
                   "densified-A bytes, speedup >= floor at <=1% density; "
                   "fold-in row is the serve-side dispatch wall "
                   "(informational)"}
    if speedup < floor:
        msg = (f"SPMM SPEEDUP GATE FAILED: {speedup:.2f}x below the "
               f"{floor:.2f}x floor vs the densify route")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


def bench_trees(m, n_feat, n_nodes, n_bins, tag, s=3, min_speedup=1.2):
    """Round-17 Pallas tier two: the forest level histogram — the fit
    loop's scatter-shaped hot op — as the one-hot-GEMM Pallas kernel
    (``ops/pallas_kernels.node_histogram``) vs the XLA scatter-add it
    replaces, at the routed ``trees/decision_tree._node_histogram``
    surface.

    Gates (all fail the config loudly):
    - BIT-equality: pallas == xla == a NumPy scatter oracle (the forest's
      contributions — Poisson weights × count/target stats — are
      integer-representable, so both summation orders are exact);
    - the routed forest fit is counter-observable (``hist:pallas``) and a
      warm same-shape refit compiles ZERO new programs;
    - speedup = xla_wall / pallas_wall >= the floor.  MXU-class backends
      (real TPUs, where the one-hot GEMM is dense MXU work against a
      serialized scatter loop) get ``min_speedup``; interpret-mode rigs
      (this CPU box) get 0.0 — Pallas interpret mode is a correctness
      rig, not wall-clock evidence (the bf16 parity-class-floor
      precedent).  ``DSLIB_HIST_SPEEDUP_MIN`` overrides either floor."""
    import warnings

    import jax
    import jax.numpy as jnp
    import dislib_tpu as ds
    from dislib_tpu.ops import pallas_kernels as _pk
    from dislib_tpu.trees import RandomForestClassifier
    from dislib_tpu.trees.decision_tree import _node_histogram
    from dislib_tpu.utils import profiling as _prof

    if not _pk.hist_available():
        raise RuntimeError("pallas histogram kernel unavailable on this "
                           "backend (hist_available probe failed)")
    rng = np.random.RandomState(0)
    node_h = rng.randint(0, n_nodes, m).astype(np.int32)
    bx_h = rng.randint(0, n_bins, (m, n_feat)).astype(np.int32)
    w_h = rng.poisson(1.0, m).astype(np.float32)
    stats_h = rng.randint(0, 3, (m, s)).astype(np.float32)
    node, bx = jnp.asarray(node_h), jnp.asarray(bx_h)
    w, stats = jnp.asarray(w_h), jnp.asarray(stats_h)

    fns = {sched: jax.jit(
        lambda nd, b, ww, st, _s=sched: _node_histogram(
            nd, b, ww, st, n_nodes, n_bins, hist=_s))
        for sched in ("xla", "pallas")}

    # correctness gate: both routes vs each other AND a host oracle
    outs = {k: np.asarray(f(node, bx, w, stats)) for k, f in fns.items()}
    np.testing.assert_array_equal(outs["xla"], outs["pallas"])
    want = np.zeros((n_nodes, n_feat, n_bins, s), np.float32)
    contrib = w_h[:, None] * stats_h
    for f_i in range(n_feat):
        np.add.at(want, (node_h, f_i, bx_h[:, f_i]), contrib)
    np.testing.assert_array_equal(outs["xla"], want)

    # interleaved best-of walls (the bench_sparse precedent: alternating
    # arms under the same load profile, best per arm)
    t_x, t_p = [], []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(fns["xla"](node, bx, w, stats)[:1])
        t_x.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(fns["pallas"](node, bx, w, stats)[:1])
        t_p.append(time.perf_counter() - t0)
    t_x, t_p = min(t_x), min(t_p)
    speedup = t_x / t_p

    # routed-fit evidence: the hist:<sched> counter at the fit boundary,
    # and zero new programs on a warm same-shape refit
    x_fit = rng.rand(512, 8).astype(np.float32)
    y_fit = (x_fit[:, 0] > 0.5).astype(np.float32)[:, None]
    prev = os.environ.get("DSLIB_OVERLAP")
    os.environ["DSLIB_OVERLAP"] = "pallas"
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")     # pallas warns off-TPU
            RandomForestClassifier(n_estimators=2, random_state=0).fit(
                ds.array(x_fit), ds.array(y_fit))       # warm
            _prof.reset_counters()
            RandomForestClassifier(n_estimators=2, random_state=0).fit(
                ds.array(x_fit), ds.array(y_fit))
        sc = _prof.schedule_counters()
        assert sc.get("hist:pallas", 0) >= 1, \
            f"routed forest fit left no hist:pallas counter: {sc}"
        traces = _prof.trace_count()
        assert traces == 0, \
            f"warm same-shape refit compiled {traces} new programs"
    finally:
        if prev is None:
            os.environ.pop("DSLIB_OVERLAP", None)
        else:
            os.environ["DSLIB_OVERLAP"] = prev

    interpret = jax.default_backend() != "tpu"
    floor = float(os.environ.get("DSLIB_HIST_SPEEDUP_MIN",
                                 0.0 if interpret else min_speedup))
    res = {"metric": f"trees_{tag}_hist_speedup_vs_scatter (baseline: "
                     "the XLA scatter-add histogram, same shapes)",
           "value": round(speedup, 2), "unit": "x",
           "vs_baseline": round(speedup, 2),
           "xla_wall_s": round(t_x, 5), "pallas_wall_s": round(t_p, 5),
           "shape": [m, n_feat], "n_nodes": n_nodes, "n_bins": n_bins,
           "stats_width": s, "interpret_mode": interpret,
           "speedup_floor": floor, "fresh": True,
           "note": "gates: pallas == xla == numpy oracle BIT-equal, "
                   "hist:pallas counter on a routed fit, 0 traces on a "
                   "warm refit, speedup >= floor (floor 0.0 on "
                   "interpret-mode rigs — wall clock there is a "
                   "correctness rig, not MXU evidence; "
                   "DSLIB_HIST_SPEEDUP_MIN overrides)"}
    if speedup < floor:
        msg = (f"HIST SPEEDUP GATE FAILED: one-hot-GEMM histogram at "
               f"{speedup:.2f}x the XLA scatter is below the "
               f"{floor:.2f}x floor")
        print(msg, file=sys.stderr, flush=True)
        raise AssertionError(msg)
    return res


def bench_shuffle(m, n, tag, chain=8):
    """Global all_to_all shuffle throughput.  Proxy: NumPy permutation
    take of the same matrix.  Gate: the row multiset is preserved.
    ``chain`` shuffles per timed region amortize the dispatch RTT."""
    import dislib_tpu as ds
    from dislib_tpu.utils import shuffle

    rng = np.random.RandomState(3)
    x_host = rng.rand(m, n).astype(np.float32)
    perm = rng.permutation(m)
    t0 = time.perf_counter()
    _ = x_host[perm]
    cpu_wall = time.perf_counter() - t0

    a = ds.array(x_host, block_size=(8192, n))
    out = shuffle(a, random_state=0)                    # warmup/compile
    small = ds.array(x_host[:2048], block_size=(512, n))
    sm = np.asarray(shuffle(small, random_state=1).collect())
    assert sorted(map(tuple, sm.tolist())) == \
        sorted(map(tuple, x_host[:2048].tolist())), \
        "shuffle gate: row multiset not preserved"

    rtt = _measure_rtt()

    def run():
        y = a
        for i in range(chain):
            y = shuffle(y, random_state=i)
        _sync(y._data)
    run()                                               # chain warmup
    t = _median_time(run)
    gb = m * n * 4 / 1e9
    raw_gbps = gb * chain / t
    # the correction is only meaningful when the RTTs are a MINORITY of
    # the wall; when t ≲ chain·rtt the subtraction degenerates (divide by
    # ~0 → absurd GB/s), so emit null rather than poison the artifact
    corr = t - chain * rtt
    corr_gbps = round(gb * chain / corr, 2) if corr > 0.2 * t else None
    return {"metric": f"shuffle_{tag}_gb_per_sec (baseline: numpy "
                      "permutation take)",
            "value": round(raw_gbps, 2), "unit": "GB/s",
            "vs_baseline": round((cpu_wall * chain) / t, 2),
            "rtt_ms": round(rtt * 1e3, 2),
            "rtt_corrected_value": corr_gbps,
            "shuffles_per_region": chain,
            "note": "each chained shuffle pays one host-planning RTT; "
                    "rtt_corrected_value subtracts them (null when RTT "
                    "dominates the region)"}


def _configs():
    """Ordered (name, thunk) list.  BENCH_SMOKE=1: every config at ~1/100
    scale — validates the whole harness (gates, proxies, JSON, watchdog
    orchestration) on CPU without the chip.  Full mode: BASELINE.md
    configs 1-5, then the two north stars (KMeans ★ LAST so a driver that
    parses the final stdout line records the headline)."""
    if os.environ.get("BENCH_SMOKE"):
        return [
            ("dispatch_rtt", bench_rtt),
            ("kmeans_smoke",
             lambda: bench_kmeans(1000, 20, 4, 5, "smoke", amortize=25)),
            ("matmul_smoke", lambda: bench_matmul(512, "smoke", chain=3,
                                                  peak_floor=0.15)),
            ("matmul_smoke_bf16",
             lambda: bench_matmul(512, "smoke", bf16=True, chain=3,
                                  peak_floor=0.15)),
            ("matmul_smoke_f32x3",
             lambda: bench_matmul(512, "smoke", chain=3, precision="high")),
            # round-10 mixed-precision tier: bf16-policy >= 1.5x f32
            # sustained + error-bound + 1-dispatch + roofline, all gated
            ("matmul_smoke_mp",
             lambda: bench_matmul_mp(512, "smoke", chain=3)),
            ("polar_smoke", lambda: bench_polar(2048, 96, "smoke",
                                                peak_floor=0.1)),
            ("summa_smoke", lambda: bench_summa(512, "smoke",
                                                peak_floor=0.1)),
            # round-11 rechunk tier: collective reshard, memory-bounded
            ("rechunk_smoke", lambda: bench_rechunk(2048, 256, "smoke",
                                                    min_gbps=0.02)),
            # round-19 DCN tier: hierarchical rechunk under the mock
            # host map — coalesced messages O(hosts) + bytes == deviceput
            # floor + bit-equal to the flat exchange, all counter-gated
            ("dcn_smoke", lambda: bench_dcn(2050, 96, "smoke")),
            # round-13 overlap tier: comm-hidden fraction per panel
            # schedule, db==seq bit-equal + 1-dispatch + memory-bounded
            # gated in-config.  Floors are rig-calibrated (the bf16
            # roofline-normalization precedent): rechunk/ring measure
            # +0.2-0.4 / +0.1-0.4 hidden on these host cores (thunk
            # concurrency), while summa's double buffer is CACHE-BOUND
            # here (two live panel pairs vs one: measured -0.3±0.1, no
            # ICI to win back) — its smoke floor is the documented
            # bounded-regression -1.0 and the full/chip config arms 0.0
            ("overlap_smoke_summa",
             lambda: bench_overlap("summa", 512, 512, "smoke",
                                   hidden_floor=-1.0)),
            ("overlap_smoke_rechunk",
             lambda: bench_overlap("rechunk", 2048, 256, "smoke",
                                   hidden_floor=0.02)),
            # ring floor −0.05, not 0: measured 0.38–0.67 hidden here,
            # but one run in ~5 TIES (−0.01) when the container is
            # throttled mid-region — the floor tolerates the tie, the
            # chip config arms 0.0
            ("overlap_smoke_ring",
             lambda: bench_overlap("ring", 8192, 128, "smoke",
                                   hidden_floor=-0.05, repeats=15)),
            ("kmeans_smoke_fastdist",
             lambda: bench_kmeans(1000, 20, 4, 5, "smoke_fastdist")),
            # round-12 fit-loop driver: heal == unfaulted, +1 dispatch only
            ("resilience_smoke",
             lambda: bench_resilience(1000, 20, 4, 8, "smoke")),
            # round-20 multi-host survival: the real SIGKILL chaos drill,
            # all counters + recovery wall + zero-hang gated
            ("mh_resilience_smoke",
             lambda: bench_mh_resilience("smoke")),
            ("fused_chain_smoke",
             lambda: bench_fused_chain(256, 32, "smoke")),
            ("tsqr_smoke", lambda: bench_tsqr(2048, 64)),
            ("randomsvd_smoke", lambda: bench_randomsvd(1024, 128, nsv=16)),
            ("svd_smoke", lambda: bench_svd(256, 130)),
            ("csvm_smoke", lambda: bench_csvm(600, 8, "smoke", max_iter=2,
                                              part=128)),
            ("gridsearch_smoke",
             lambda: bench_gridsearch(2000, 8, (2, 3), 2, 4, "smoke")),
            ("gmm_smoke", lambda: bench_gmm(2000, 8, 3, 2)),
            ("dbscan_smoke", lambda: bench_dbscan(3000, 6, "smoke",
                                                  proxy_m=1500)),
            ("daura_smoke", lambda: bench_daura(2000, 6, "smoke",
                                                proxy_m=1000)),
            ("forest_smoke", lambda: bench_forest(2000, 8, 4, "smoke",
                                                  depth=5)),
            ("knn_smoke", lambda: bench_knn(4000, 8, 512, 5, "smoke")),
            ("serving_smoke",
             lambda: bench_serving(2000, 8, 4, 200, "smoke",
                                   buckets=(1, 8, 64), deadline_ms=2)),
            # round-15 bundle + fleet tier: cold-start ratio gated >= 10x
            # (DSLIB_BUNDLE_COLDSTART_MIN), zero traces on the bundle
            # path and under 3-tenant mixed-shape load
            ("serving_fleet_smoke",
             lambda: bench_serving_fleet(2000, 8, 4, 300, "smoke",
                                         buckets=(1, 8, 64),
                                         deadline_ms=2)),
            # round-17 continuous-learning tier: train -> bundle ->
            # canary -> promote cadence, all promoted, zero-retrace
            # post-promotion bursts gated
            ("trainer_smoke",
             lambda: bench_trainer(512, 8, 4, 4, "smoke",
                                   batches_per_generation=3,
                                   buckets=(1, 8, 64), deadline_ms=2)),
            ("als_smoke", lambda: bench_als_sparse(1000, 400, 10, "smoke",
                                                   n_f=8, iters=2)),
            # round-14 sparse fast path (round-17: default panels=4 under
            # the slot-range layout — a pure memory knob now, masking-work
            # accounting in the row): SpMM >= 2x the densify A/B at 1%
            # density, 1 dispatch, O(nnz) peak-live, db==seq bit-equal
            ("sparse_smoke",
             lambda: bench_sparse(4096, 2048, 64, 0.01, "smoke")),
            # round-17 Pallas tier two: the forest level histogram as a
            # one-hot GEMM vs the XLA scatter — bit-equal gated; the
            # speedup floor arms on MXU-class backends only
            ("trees_smoke",
             lambda: bench_trees(2048, 8, 16, 32, "smoke")),
            # round-18 IVF-ANN retrieval tier: recall@10 >= 0.95 AND
            # >= 3x the exact kneighbors ring, 1 dispatch / 0 transfers
            ("ann_smoke",
             lambda: bench_ann(262_144, 32, 512, 10, 2048, 8, "smoke",
                               kmeans_max_iter=2)),
            ("shuffle_smoke", lambda: bench_shuffle(4096, 16, "smoke",
                                                    chain=3)),
            ("kmeans_smoke_star",
             lambda: bench_kmeans(4000, 20, 4, 5, "smoke_star")),
        ]
    return [
        # full-mode config names MATCH each metric's first token, so a
        # failure/timeout record (emitted under the config name) lands on
        # the same BASELINE.md row as a success would (fill_baseline.py)
        ("dispatch_rtt_trivial_op_ms", bench_rtt),
        # amortize/chain sizes pick sustained regions ≥ 10× the ~69 ms RTT
        # (per-unit costs measured in round 3: kmeans-cfg1 ~0.46 ms/iter,
        # kmeans-1M ~1.25 ms/iter, 4096³ f32 ~19 ms, 16384³ f32 ~290 ms,
        # 16384³ bf16 ~46 ms)
        ("kmeans_10000x100_k8_iter_per_sec",
         lambda: bench_kmeans(10_000, 100, 8, 50, "10000x100_k8",
                              amortize=2000)),
        ("matmul_4096_f32_gflops_per_chip",
         lambda: bench_matmul(4096, "4096", chain=36, peak_floor=0.3)),
        # round-10 mixed-precision / paper-scale linalg tier
        ("matmul_mp_4096_bf16_vs_f32_speedup",
         lambda: bench_matmul_mp(4096, "4096", chain=12,
                                 peak_floors=(0.3, 0.3))),
        ("polar_16384x1024_gflops_sustained",
         lambda: bench_polar(16384, 1024, "16384x1024", peak_floor=0.15)),
        ("summa_8192_gflops_per_chip",
         lambda: bench_summa(8192, "8192", peak_floor=0.1)),
        # round-11 rechunk tier: collective reshard of a paper-scale
        # operand between 2-D layouts, peak-live proxy <= 1.5x gated
        ("rechunk_16384x2048_gb_per_sec",
         lambda: bench_rechunk(16384, 2048, "16384x2048", min_gbps=0.2)),
        # round-19 DCN tier at paper scale: the hierarchical schedule's
        # accounting gates (messages O(hosts), bytes == deviceput floor)
        # under the mock host map; m chosen so the two layouts pad
        # DIFFERENTLY (aligned pads would mean zero cross-host rows and
        # a vacuous run — the tier rejects that loudly)
        ("dcn_rechunk_16500x2048_gb_per_sec",
         lambda: bench_dcn(16500, 2048, "16500x2048")),
        # round-13 overlap tier at paper scale: on real ICI the
        # double-buffered schedule must hide a strictly positive
        # fraction of the panel collective (floor 0.0, armed) —
        # DSLIB_OVERLAP_HIDDEN_MIN is the noisy-rig escape
        ("overlap_summa_4096_comm_hidden_frac",
         lambda: bench_overlap("summa", 4096, 4096, "4096",
                               hidden_floor=0.0)),
        ("overlap_rechunk_16384x2048_comm_hidden_frac",
         lambda: bench_overlap("rechunk", 16384, 2048, "16384x2048",
                               hidden_floor=0.0)),
        ("overlap_ring_65536x128_comm_hidden_frac",
         lambda: bench_overlap("ring", 65536, 128, "65536x128",
                               hidden_floor=0.0)),
        # round-7 fusion PR: one forced op chain vs per-op eager dispatch —
        # at 512² the per-dispatch RTT dominates both modes' compute, so
        # the ratio reads the dispatch savings directly
        ("fused_chain_512_32ops_speedup_vs_eager",
         lambda: bench_fused_chain(512, 32, "512")),
        ("tsqr_65536x256_wall_s", lambda: bench_tsqr(65536, 256)),
        ("randomsvd_32768x1024_nsv64_wall_s",
         lambda: bench_randomsvd(32768, 1024)),
        ("svd_4096x512_wall_s", lambda: bench_svd(4096, 512)),
        ("gmm_1000000x50_k16_5it_wall_s",
         lambda: bench_gmm(1_000_000, 50, 16, 5)),
        ("csvm_20000x20_rbf_3it_fit_wall_s",
         lambda: bench_csvm(20_000, 20, "20000x20")),
        ("gridsearch_kmeans_200000x20_3x3fits_wall_s",
         lambda: bench_gridsearch(200_000, 20, (4, 8, 12), 3, 10,
                                  "200000x20")),
        # round-5: the estimator tier (r4 VERDICT missing #3) — DBSCAN on
        # the tiled-streamed tier, forest fit+predict, kNN streamed query
        # throughput, sparse ALS, and the all_to_all shuffle
        # round-12 fit-loop driver: rollback heal at paper-ish scale —
        # gates equality with the unfaulted fit and the +1-dispatch cost
        ("resilience_100000x50_k8_heal_wall_s",
         lambda: bench_resilience(100_000, 50, 8, 20,
                                  "100000x50_k8")),
        # round-20 multi-host survival: the process-killing chaos drill
        # (always CPU-coordinated — the jax.distributed CPU service is
        # platform-independent; see tools/mh_dryrun.py)
        ("mh_resilience_episode_wall_s",
         lambda: bench_mh_resilience("full")),
        ("dbscan_200000x10_wall_s",
         lambda: bench_dbscan(200_000, 10, "200000x10", proxy_m=20_000)),
        ("daura_50000x15_wall_s",
         lambda: bench_daura(50_000, 15, "50000x15", proxy_m=20_000)),
        ("forest_100000x20_16t_fit_predict_wall_s",
         lambda: bench_forest(100_000, 20, 16, "100000x20")),
        ("knn_1000000x10_q10000_k10_queries_per_sec",
         lambda: bench_knn(1_000_000, 10, 10_000, 10, "1000000x10_q10000")),
        # round-18 IVF-ANN retrieval tier at the million-item scale the
        # subsystem exists for: recall@10 >= 0.95 AND >= 3x the exact
        # ring, ONE dispatch / 0 transfers counter-asserted in-config
        ("ann_1000000x64_q4096_k10_queries_per_sec",
         lambda: bench_ann(1_000_000, 64, 4096, 10, 1024, 32,
                           "1000000x64_q4096", kmeans_max_iter=5)),
        ("als_sparse_100000x10000_nnz100_f16_3it_wall_s",
         lambda: bench_als_sparse(100_000, 10_000, 100,
                                  "100000x10000_nnz100")),
        # round-14 sparse fast path at paper scale (round-17: default
        # panels=4 under the slot-range layout): the sharded SpMM vs
        # the densify route on this rig, same gates as the smoke tier
        ("sparse_16384x8192_spmm_speedup_vs_densify",
         lambda: bench_sparse(16_384, 8_192, 64, 0.01, "16384x8192")),
        # round-17 Pallas tier two at paper-ish shape: one-hot-GEMM
        # histogram vs the XLA scatter, bit-equal + hist:<sched> routing
        # gated; speedup floor arms on MXU-class backends
        ("trees_16384x8_hist_speedup_vs_scatter",
         lambda: bench_trees(16_384, 8, 32, 32, "16384x8")),
        # round-9 serving layer: warm micro-batched p50 vs per-call cold
        # predict, 1-dispatch-per-batch asserted in-config
        ("serving_1000000x100_k10_warm_p50_ms",
         lambda: bench_serving(1_000_000, 100, 10, 2000, "1000000x100_k10",
                               buckets=(1, 8, 64, 512), deadline_ms=5)),
        # round-15 bundle + fleet tier at paper scale: on chip the cold
        # side is tens of seconds of ladder compiles, the bundle side is
        # a deserialize — the >= 10x gate has enormous headroom there
        ("serving_fleet_1000000x100_k10_coldstart_ratio",
         lambda: bench_serving_fleet(1_000_000, 100, 10, 2000,
                                     "1000000x100_k10",
                                     buckets=(1, 8, 64, 512),
                                     deadline_ms=5)),
        # round-17 continuous-learning loop at paper-ish scale: 8k-row
        # batches through train -> bundle -> canary -> promote, same
        # all-promoted / zero-retrace-burst / ledger-replay gates
        ("trainer_8192x100_k10_generations_per_min",
         lambda: bench_trainer(8192, 100, 10, 5, "8192x100_k10",
                               batches_per_generation=6,
                               buckets=(1, 8, 64, 512), deadline_ms=5)),
        ("shuffle_2097152x64_gb_per_sec",
         lambda: bench_shuffle(2_097_152, 64, "2097152x64")),
        ("matmul_16384_f32_gflops_per_chip",
         lambda: bench_matmul(16384, "16384", proxy_dim=8192, chain=6,
                              peak_floor=0.3)),
        # informational variants — headline ★ stays the full-precision path
        ("matmul_16384_bf16_gflops_per_chip",
         lambda: bench_matmul(16384, "16384", proxy_dim=8192, bf16=True,
                              chain=15, peak_floor=0.3)),
        # 3-pass bf16x3 "f32-ish": ceiling ≈ peak/3 (~65 TF/s) vs
        # 'highest''s peak/6 — data for a future precision-policy decision
        ("matmul_16384_f32x3_gflops_per_chip",
         lambda: bench_matmul(16384, "16384", proxy_dim=8192, chain=10,
                              precision="high")),
        # sustained rate: 500 iters/dispatch amortizes the per-call RTT the
        # 10-iter headline pays once per 10 iterations (BASELINE.md
        # interpretation section)
        ("kmeans_1Mx100_k10_sustained_iter_per_sec",
         lambda: bench_kmeans(1_000_000, 100, 10, 500,
                              "1Mx100_k10_sustained")),
        ("kmeans_1Mx100_k10_fastdist_iter_per_sec",
         lambda: bench_kmeans(1_000_000, 100, 10, 10, "1Mx100_k10_fastdist",
                              amortize=500)),
        ("kmeans_1Mx100_k10_iter_per_sec",
         lambda: bench_kmeans(1_000_000, 100, 10, 10, "1Mx100_k10",
                              amortize=500)),
    ]


def _run_one(name):
    """Child entry: bring up the backend and run exactly one config."""
    # test hook: comma-separated config names that should hang (exercises
    # the parent's skip-and-continue and two-timeouts-abort paths)
    if name in os.environ.get("DSLIB_BENCH_FAKE_HANG", "").split(","):
        time.sleep(10_000)
    if name.startswith(("summa", "rechunk", "overlap", "sparse", "ann",
                        "dcn")) \
            and os.environ.get("BENCH_SMOKE") \
            and (_smoke_wants_cpu()
                 or "cpu" in os.environ.get("JAX_PLATFORMS", "")):
        # the SUMMA/rechunk/sparse tiers need a sharded mesh; smoke mode
        # fakes one with virtual host devices — must land in XLA_FLAGS
        # BEFORE the backend initialises (the conftest precedent).  Chip
        # runs use the real device grid and never take this branch.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        if _smoke_wants_cpu():
            # smoke mode validates the harness WITHOUT the chip; the platform
            # must be forced in-process before backend init (JAX_PLATFORMS is
            # ignored by the axon sitecustomize — round-1 post-mortem).
            import jax
            jax.config.update("jax_platforms", "cpu")
        import dislib_tpu as ds
        ds.init()
    except Exception as e:  # noqa: BLE001
        _emit({"metric": "backend_init", "value": None, "unit": None,
               "vs_baseline": None, "error": f"{type(e).__name__}: {e}"})
        sys.exit(2)
    fn = dict(_configs())[name]
    _guard(name, fn)


def _emit_stale_fallback():
    """On a wedged/failed device probe, re-emit the most recent green
    local capture (BENCH_local_r*.jsonl) with ``stale: true`` on every row
    — rc stays non-zero for the driver, but the artifact remains
    monotonically informative instead of one error line (round-4 VERDICT
    weak #8: the round-4 wedge cost the round its entire measurement
    record).

    Round-9 satellite (ROADMAP item 5 follow-up): BENCH_r05.json carried
    EVERY chip metric as a stale replay and still read like fresh
    evidence to a skimming reviewer.  The fallback now also (a) emits one
    leading ``{"stale_carryover": true, ...}`` record so a consumer that
    only scans top-level flags sees the carryover before any number, (b)
    marks every replayed row ``stale_carryover: true`` alongside the
    existing per-row ``stale`` flag, and (c) prints a LOUD stderr warning
    — stale chip numbers can no longer masquerade as a fresh capture."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    captures = sorted(glob.glob(os.path.join(here, "BENCH_local_r*.jsonl")))
    for path in reversed(captures):
        rows = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line.startswith("{"):
                        rec = json.loads(line)
                        # CPU smoke captures (tagged via "capture", or
                        # smoke-config metric names) are never evidence
                        # for the on-chip trajectory — skip the whole
                        # tier so the fallback only replays real captures
                        if rec.get("capture", "").startswith("cpu_smoke") \
                                or "smoke" in rec.get("metric", ""):
                            continue
                        if not rec.get("error"):
                            rows.append(rec)
        except (OSError, ValueError):
            continue
        if rows:
            src = os.path.basename(path)
            print(f"WARNING: device probe failed — the {len(rows)} metric "
                  f"rows that follow are a STALE CARRYOVER replayed from "
                  f"{src}, NOT fresh measurements of this code state",
                  file=sys.stderr, flush=True)
            # round-10 satellite: the leading record NAMES every carried
            # metric, so a consumer can see exactly which rows of a round
            # artifact (the BENCH_r05.json chip metrics, e.g.) are
            # replays without scanning per-row flags
            _emit({"metric": "stale_carryover", "stale_carryover": True,
                   "stale_source": src, "rows": len(rows),
                   "metrics": [r.get("metric") for r in rows],
                   "value": None, "unit": None, "vs_baseline": None,
                   "note": "every following row is replayed from an old "
                           "capture; treat nothing below as fresh "
                           "evidence"})
            for rec in rows:
                # a replayed row that was ITSELF a replay keeps its
                # deepest origin: stale_origin always names the capture
                # the number was actually measured in, however many
                # fallback hops it has survived
                rec["stale_origin"] = rec.get("stale_origin") \
                    or rec.get("stale_source") or src
                rec["stale"] = True
                rec["stale_carryover"] = True
                rec["stale_source"] = src
                rec["fresh"] = False
                _emit(rec)
            return


def main():
    # persistent compilation cache for all config children: repeat runs (and
    # the f32/bf16 siblings of a config) skip the 20-40 s TPU compiles, so
    # more of each 900 s budget goes to measurement
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(os.path.dirname(os.path.abspath(
                              __file__)), ".jax_cache"))
    # the matmul setup cache (NumPy-proxy GFLOPS + gate stripe) exists to
    # share work between the f32/bf16 sibling CHILDREN of one run; a proxy
    # measured under a previous invocation's machine load must not leak
    # into this run's vs_baseline ratios (round-3 advisor) — the parent
    # clears it before spawning any child
    import glob
    for f in glob.glob(os.path.join(os.environ["JAX_COMPILATION_CACHE_DIR"],
                                    "bench_matmul_setup_*.npz")) \
            + glob.glob(os.path.join(os.environ["JAX_COMPILATION_CACHE_DIR"],
                                     "bench_peak_*.json")):
        try:
            os.remove(f)
        except OSError:
            pass
    # fast probe: a dead tunnel is detected in _PROBE_TIMEOUT_S, not per-
    # config watchdog time.  The parent process never imports jax, so it
    # can always report and exit cleanly.
    if _smoke_wants_cpu():
        probe_src = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                     "jax.devices()")
    else:
        probe_src = "import jax; jax.devices()"
    try:
        subprocess.run([sys.executable, "-c", probe_src],
                       timeout=_PROBE_TIMEOUT_S, check=True,
                       stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
                       text=True)
    except subprocess.TimeoutExpired:
        _emit({"metric": "backend_init", "value": None, "unit": None,
               "vs_baseline": None,
               "error": f"device probe hung past {_PROBE_TIMEOUT_S}s "
                        "(wedged tunnel?)"})
        _emit_stale_fallback()
        sys.exit(2)
    except subprocess.CalledProcessError as e:
        _emit({"metric": "backend_init", "value": None, "unit": None,
               "vs_baseline": None,
               "error": f"device probe failed (rc={e.returncode})",
               "stderr_tail": (e.stderr or "")[-400:]})
        _emit_stale_fallback()
        sys.exit(2)

    consecutive_timeouts = 0
    for name, _ in _configs():
        try:
            res = subprocess.run([sys.executable, __file__, "--one", name],
                                 timeout=_CONFIG_TIMEOUT_S,
                                 capture_output=True, text=True)
        except subprocess.TimeoutExpired as e:
            # forward whatever the child printed before wedging
            if e.stdout:
                print(e.stdout.decode() if isinstance(e.stdout, bytes)
                      else e.stdout, end="", flush=True)
            _emit({"metric": name, "value": None, "unit": None,
                   "vs_baseline": None,
                   "error": f"watchdog: exceeded {_CONFIG_TIMEOUT_S}s "
                            "(skipped, continuing)"})
            consecutive_timeouts += 1
            if consecutive_timeouts >= 2:
                _emit({"metric": "abort", "value": None, "unit": None,
                       "vs_baseline": None,
                       "error": "two consecutive config timeouts — backend "
                                "wedged, aborting"})
                sys.exit(2)
            continue
        consecutive_timeouts = 0
        print(res.stdout, end="", flush=True)
        if '"metric": "backend_init"' in res.stdout:
            # the child's backend bring-up failed fast: every later config
            # would fail identically — record once and abort with evidence
            _emit({"metric": "abort", "value": None, "unit": None,
                   "vs_baseline": None,
                   "error": "child backend_init failed — aborting"})
            sys.exit(2)
        if res.returncode != 0 and not res.stdout.strip():
            _emit({"metric": name, "value": None, "unit": None,
                   "vs_baseline": None,
                   "error": f"config subprocess rc={res.returncode}",
                   "stderr_tail": res.stderr[-400:]})


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--one":
        _run_one(sys.argv[2])
    else:
        main()
