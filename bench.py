"""North-star benchmark (BASELINE.md ★): KMeans iter/sec on 1M×100, k=10.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured against a NumPy single-node implementation of the
same blocked Lloyd iteration, run in-process — the CPU-proxy rule from
BASELINE.md "Measurement rules" (no dislib+COMPSs install exists in this
environment; the proxy is labeled as such in the metric string).
Correctness is gated first: device centers after 1 iteration must match the
NumPy oracle.
"""

import json
import time

import numpy as np


M, N, K = 1_000_000, 100, 10
ITERS = 10


def _numpy_iter(x, centers):
    d = (x * x).sum(1)[:, None] - 2.0 * (x @ centers.T) + (centers * centers).sum(1)[None]
    labels = d.argmin(1)
    onehot = np.zeros((x.shape[0], centers.shape[0]), x.dtype)
    onehot[np.arange(x.shape[0]), labels] = 1.0
    counts = onehot.sum(0)
    sums = onehot.T @ x
    return np.where(counts[:, None] > 0, sums / np.maximum(counts, 1)[:, None], centers)


def main():
    rng = np.random.RandomState(0)
    x_host = rng.rand(M, N).astype(np.float32)
    init = x_host[rng.choice(M, K, replace=False)].copy()

    # --- CPU proxy baseline (NumPy blocked Lloyd, single node) ---
    t0 = time.perf_counter()
    c = init.copy()
    for _ in range(2):
        c = _numpy_iter(x_host, c)
    cpu_iter_sec = 2.0 / (time.perf_counter() - t0)

    # --- TPU path ---
    import jax
    import dislib_tpu as ds
    from dislib_tpu.cluster import KMeans
    from dislib_tpu.cluster.kmeans import _kmeans_fit

    ds.init()
    a = ds.array(x_host, block_size=(M // max(1, len(jax.devices())), N))

    # correctness gate: 1 iteration vs the NumPy oracle
    km_check = KMeans(n_clusters=K, init=init.copy(), max_iter=1, tol=0.0)
    km_check.fit(a)
    oracle = _numpy_iter(x_host, init.copy())
    np.testing.assert_allclose(km_check.centers_, oracle, rtol=2e-3, atol=2e-3)

    centers0 = __import__("jax.numpy", fromlist=["asarray"]).asarray(init)
    # warmup/compile (excluded from timing)
    _kmeans_fit(a._data, a.shape, centers0, ITERS, 0.0)[0].block_until_ready()
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        _kmeans_fit(a._data, a.shape, centers0, ITERS, 0.0)[0].block_until_ready()
        times.append(time.perf_counter() - t0)
    tpu_iter_sec = ITERS / float(np.median(times))

    print(json.dumps({
        "metric": "kmeans_1Mx100_k10_iter_per_sec (baseline: numpy single-node proxy)",
        "value": round(tpu_iter_sec, 3),
        "unit": "iter/s",
        "vs_baseline": round(tpu_iter_sec / cpu_iter_sec, 2),
    }))


if __name__ == "__main__":
    main()
