"""Chaos soak (round-8 satellite, `slow` tier — run via
``tools/chaos_soak.sh``, excluded from tier-1): N randomized-SCHEDULE but
seeded-and-reproducible fit runs, each drawing a random combination of
every fault family the runtime defends against —

- preemption requested mid-fit (PR-1),
- snapshot corruption between the faulted fit and the resume (PR-1),
- NaN poisoned into a chunk carry (round-8),
- a hung chunk force point under a watchdog deadline (round-8),

— and asserts the ONE invariant the whole resilience+health stack
promises: a fit either completes with a finite model (self-healed), or
raises a TYPED diagnostic (``Preempted`` / ``NumericalDivergence`` /
``WatchdogTimeout`` / ``SnapshotCorrupt``), and a clean resume from
whatever snapshot survives lands on the unfaulted model.  Never a silent
bad model, a hang, or a corrupted-over-good snapshot.

``DSLIB_SOAK_RUNS`` (default 10) and ``DSLIB_SOAK_SEED`` (default 0)
parameterize the sweep; every run's schedule derives from the seed, so a
failure reproduces with the printed seed alone.
"""

import json
import os
import warnings
from collections import Counter

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import GaussianMixture, KMeans
from dislib_tpu.recommendation import ALS
from dislib_tpu.runtime import (NumericalDivergence, Preempted,
                                WatchdogTimeout, clear_preemption,
                                request_preemption)
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils.checkpoint import SnapshotCorrupt

TYPED = (Preempted, NumericalDivergence, WatchdogTimeout, SnapshotCorrupt)


def _estimator(kind, rng):
    """(fresh estimator factory, ds-array data, model-vector extractor)."""
    if kind == "kmeans":
        c = rng.rand(3, 4) * 10
        x_np = np.vstack([c[i] + 0.3 * rng.randn(60, 4)
                          for i in range(3)]).astype(np.float32)
        init = np.ascontiguousarray(x_np[[0, 60, 120]])
        make = lambda: KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0)  # noqa: E731
        return make, ds.array(x_np), lambda e: e.centers_
    if kind == "gmm":
        x_np = np.vstack([rng.rand(60, 3),
                          rng.rand(60, 3) + 4]).astype(np.float32)
        make = lambda: GaussianMixture(n_components=2, max_iter=10, tol=0.0,  # noqa: E731
                                       random_state=0)
        return make, ds.array(x_np), lambda e: e.means_
    u, v = rng.rand(30, 4), rng.rand(20, 4)
    r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)
    make = lambda: ALS(n_f=4, max_iter=8, tol=1e-9, random_state=0)  # noqa: E731
    return make, ds.array(r), lambda e: e.users_


def _one_run(i, seed, tmp_path, monkeypatch):
    rng = np.random.RandomState(seed)
    kind = ("kmeans", "gmm", "als")[rng.randint(3)]
    make, x, model_of = _estimator(kind, rng)
    full = make().fit(x)
    ref = model_of(full)

    path = str(tmp_path / f"soak{i}.npz")
    want_nan = bool(rng.randint(2))
    want_hang = bool(rng.randint(2))
    want_preempt = bool(rng.randint(2))
    want_corrupt = bool(rng.randint(2))
    at_chunk = 1 + int(rng.randint(3))
    if want_hang:
        pol = faults.HangAtChunk(at_chunk=at_chunk, hang_s=0.3,
                                 deadline_s=0.05,
                                 times=int(rng.randint(1, 3)))
    elif want_nan:
        pol = faults.NaNAtChunk(at_chunk=at_chunk)
    else:
        pol = None
    ck = faults.CallbackCheckpoint(path, every=2, after=1 + int(rng.randint(2)),
                                   callback=request_preemption) \
        if want_preempt else FitCheckpoint(path, every=2)

    outcome = "healed"
    try:
        est = make().fit(x, checkpoint=ck, health=pol)
    except TYPED as e:
        outcome = f"typed:{type(e).__name__}"
    else:
        m = model_of(est)
        assert np.isfinite(np.asarray(m)).all(), \
            f"seed {seed}: silent non-finite model ({kind})"
    finally:
        clear_preemption()

    if want_corrupt and os.path.exists(path):
        faults.corrupt_snapshot(
            path, mode=("flip", "truncate", "foreign")[rng.randint(3)])

    # clean resume from whatever snapshot state survives must land on the
    # unfaulted model (corrupt newest generation falls back one)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        try:
            res = make().fit(x, checkpoint=FitCheckpoint(path, every=2))
        except SnapshotCorrupt:
            # every generation damaged: restart from scratch is the
            # documented operator action — and must still work
            for j in range(3):
                p = path if j == 0 else f"{path}.{j}"
                if os.path.exists(p):
                    os.remove(p)
            res = make().fit(x, checkpoint=FitCheckpoint(path, every=2))
            outcome += "+restart"
    np.testing.assert_allclose(model_of(res), ref, rtol=1e-4, atol=1e-5)
    return kind, outcome


@pytest.mark.slow
def test_chaos_soak(tmp_path, monkeypatch):
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    runs = int(os.environ.get("DSLIB_SOAK_RUNS", "10"))
    base = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    tally = Counter()
    for i in range(runs):
        kind, outcome = _one_run(i, base + i, tmp_path, monkeypatch)
        tally[f"{kind}:{outcome}"] += 1
        clear_preemption()
    from dislib_tpu.utils import profiling as prof
    summary = {"metric": "chaos_soak", "runs": runs, "seed": base,
               "outcomes": dict(sorted(tally.items())),
               "resilience": prof.resilience_counters()}
    print("CHAOS_SOAK_SUMMARY " + json.dumps(summary))
    assert sum(tally.values()) == runs


@pytest.mark.slow
@pytest.mark.filterwarnings("ignore::RuntimeWarning")
def test_chaos_trainer_soak(tmp_path, monkeypatch):
    """Round-17 continuous-learning soak (``tools/chaos_soak.sh
    --trainer``): ONE ContinuousTrainer driven through six generations
    with a deterministic fault at every seam —

    - gen 2: crash-mid-export TORN bundle (truncated artifact; the CRC
      read-back catches it and the export retries),
    - gen 3: canary health-gate trip (rejected, traffic stays on
      last-good),
    - gen 5: preemption mid-stream (typed ``Preempted``, snapshot
      flushed, stream resumes),
    - gen 6: corrupt-on-disk bundle (bit flip) PLUS a capacity
      shrink → grow oscillation during training,
    - finale: an EXPLICIT rollback of the served generation

    — while client threads hammer the router continuously.  Invariants:
    every response decodes to a (tenant, generation) that was actually
    serving (no torn responses, no unsanctioned generation), the served
    generation never moves backwards except via the explicit rollback,
    promoted-generation quality (holdout MSE) is monotone non-increasing,
    quarantine totals accumulate across generations, and the predict
    path performs ZERO traces in quiescent windows after warmup."""
    import threading
    import time

    from test_trainer import (BASE, BUCKETS, NF, STEP, TENANT, StreamLR,
                              _pipeline_of, _stream)
    from dislib_tpu.runtime import ContinuousTrainer, Retry
    from dislib_tpu.serving import ModelRouter
    from dislib_tpu.utils import profiling as prof

    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    seed = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    ds.init()
    home = int(np.prod(list(ds.get_mesh().shape.values())))
    rng = np.random.RandomState(seed)
    hold_x = rng.rand(256, NF).astype(np.float32)
    hold_y = hold_x.sum(axis=1)

    def dirty_stream():
        """Noisy [x|y] batches; every 3rd batch carries one NaN row the
        quarantine seam must strip (totals audited at the end)."""
        for i, b in enumerate(_stream(seed=seed + 1, rows=32, sigma=0.05)):
            if i % 3 == 0:
                b[0, 0] = np.nan
            yield b

    ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1, keep=2)
    router = ModelRouter(name="soak-router")
    # capacity walk keyed on STREAM-WIDE save counts (2 saves/generation;
    # gen 5 spends an extra save on the preempted batch): save 11 is gen
    # 5's last — its dip makes gen 6's first batch shrink; the grow-back
    # lands on gen 6's second batch; then the override clears
    cap = faults.CapacityAtSave({11: max(1, home // 2), 12: home, 13: None})
    trainer = ContinuousTrainer(
        StreamLR(NF), dirty_stream(), ck, _pipeline_of(0),
        str(tmp_path / "bundles"), router=router, tenant=TENANT,
        buckets=BUCKETS, batches_per_generation=2, canary_fraction=0.5,
        promote_budget=3, health=cap,
        retry=Retry(attempts=4, backoff=0.0,
                    classify=ContinuousTrainer._classify_export),
        probe=rng.rand(4, NF).astype(np.float32), name="soak-trainer")

    lock = threading.Lock()
    valid, promoted = set(), set()
    epoch = [0]
    stop_evt = threading.Event()
    errors: list[str] = []
    n_requests = [0]

    def publish():
        g = trainer.generation
        with lock:
            valid.add(g)
        rec = trainer.publish_generation()
        with lock:
            if rec["verdict"].startswith("promoted"):
                promoted.add(g)
            else:
                valid.discard(g)        # canary aborted AND drained
        return rec

    def mse():
        w = np.asarray(trainer.estimator.coef_, np.float64).ravel()
        yhat = hold_x @ w + float(trainer.estimator.intercept_)
        return float(np.mean((yhat - hold_y) ** 2))

    def client(cid):
        crng = np.random.RandomState(100 + cid)
        last_g, last_epoch = -1, 0
        i = 0
        while not stop_evt.is_set():
            i += 1
            k = int(crng.randint(1, BUCKETS[0] + 1))
            rows = crng.rand(k, NF).astype(np.float32)
            with lock:
                allowed = set(valid)
            try:
                r = router.submit(rows, TENANT,
                                  key=f"c{cid}:{i}").result(timeout=60)
            except Exception as e:  # noqa: BLE001 — any failure fails soak
                errors.append(f"client {cid}: {type(e).__name__}: {e}")
                return
            vals = np.asarray(r.values).ravel() - rows.sum(axis=1) - BASE
            dec = np.unique(np.round(vals / STEP).astype(int))
            if len(dec) != 1:
                errors.append(f"client {cid}: TORN response {vals}")
                return
            g = int(dec[0])
            with lock:
                ok = g in allowed or g in valid
                is_promoted = g in promoted
                ep = epoch[0]
            n_requests[0] += 1
            if not ok:
                errors.append(f"client {cid}: unsanctioned generation {g}")
                return
            # served generation monotone per client — checked strictly
            # before the explicit rollback; afterwards (epoch 1) the
            # old-primary drain legitimately interleaves, so the steady
            # state is asserted by the main thread's decode burst
            if is_promoted and ep == 0:
                if last_epoch == 0 and g < last_g:
                    errors.append(f"client {cid}: served generation went "
                                  f"backwards ({g} after {last_g}) without "
                                  "an explicit rollback")
                    return
                last_g, last_epoch = g, ep

    def burst(expect, n=8):
        got = set()
        brng = np.random.RandomState(7)
        for i in range(n):
            k = int(brng.randint(1, BUCKETS[0] + 1))
            rows = brng.rand(k, NF).astype(np.float32)
            r = router.submit(rows, TENANT, key=f"b{i}").result(timeout=60)
            vals = np.asarray(r.values).ravel() - rows.sum(axis=1) - BASE
            got.update(np.round(vals / STEP).astype(int).tolist())
        assert got == expect, f"steady-state decode {got}, want {expect}"

    quality: dict[int, float] = {}
    seams: dict[str, object] = {}
    traces_quiescent = []
    threads: list[threading.Thread] = []
    from dislib_tpu.runtime.preemption import clear_capacity

    with router:
        try:
            # -- gen 1: clean initial deploy ------------------------------
            assert trainer.train_generation()
            quality[1] = mse()
            assert publish()["verdict"] == "promoted"
            threads.extend(threading.Thread(target=client, args=(c,))
                           for c in range(2))
            for t in threads:
                t.start()

            # -- gen 2: crash-mid-export torn bundle ----------------------
            assert trainer.train_generation()
            quality[2] = mse()
            torn = faults.TornBundleWrite(failures=1, mode="truncate")
            with monkeypatch.context() as m:
                m.setattr("dislib_tpu.serving.bundle.write_bundle", torn)
                assert publish()["verdict"] == "promoted"
            assert torn.calls == 2      # torn once, rewritten clean
            seams["torn_export"] = "retried+promoted"

            # -- gen 3: canary health-gate trip ---------------------------
            assert trainer.train_generation()
            quality[3] = mse()
            trip = faults.CanaryGateTrip(times=1)
            trainer.health_gate = trip
            rec = publish()
            trainer.health_gate = None
            assert rec["verdict"] == "rejected" and trip.checks == 1
            assert trainer.served_generation == 2    # stayed on last-good
            seams["canary_trip"] = "rejected+stayed_on_last_good"

            # -- gen 4: clean promote (budget reset proven) ---------------
            assert trainer.train_generation()
            quality[4] = mse()
            assert publish()["verdict"] == "promoted"
            t0 = prof.trace_count()
            time.sleep(0.4)             # clients hammer; training idle
            traces_quiescent.append(prof.trace_count() - t0)

            # -- gen 5: preemption mid-stream -----------------------------
            request_preemption()
            with pytest.raises(Preempted):
                trainer.train_generation()
            clear_preemption()
            assert trainer.stats()["preemptions"] == 1
            assert trainer.train_generation()        # stream resumes
            quality[5] = mse()
            assert publish()["verdict"] == "promoted"
            seams["preemption"] = "typed+resumed"

            # -- gen 6: corrupt-on-disk bundle + capacity oscillation -----
            assert trainer.train_generation()
            quality[6] = mse()
            info = trainer.stats()["stream"]
            assert info["mesh_shrinks"] == 1, info
            assert info["mesh_grows"] == 1, info
            seams["capacity"] = {"shrinks": info["mesh_shrinks"],
                                 "grows": info["mesh_grows"]}
            flip = faults.TornBundleWrite(failures=1, mode="flip")
            with monkeypatch.context() as m:
                m.setattr("dislib_tpu.serving.bundle.write_bundle", flip)
                assert publish()["verdict"] == "promoted"
            assert flip.calls == 2
            seams["corrupt_bundle"] = "retried+promoted"
            t0 = prof.trace_count()
            time.sleep(0.4)
            traces_quiescent.append(prof.trace_count() - t0)

            # -- finale: the EXPLICIT rollback ----------------------------
            with lock:
                epoch[0] += 1
            assert trainer.rollback()["generation"] == 5
            time.sleep(0.3)             # old primary drains under load
            t0 = prof.trace_count()
            burst({5})                  # steady state: rollback target only
            traces_quiescent.append(prof.trace_count() - t0)
        finally:
            stop_evt.set()
            clear_capacity()
            clear_preemption()
            for t in threads:
                t.join()
            trainer.close()

    assert not errors, "trainer soak failures:\n  " + "\n  ".join(errors)
    stats = trainer.stats()
    served_path = [r["served"] for r in trainer.ledger]
    assert served_path == [1, 2, 2, 4, 5, 6, 5]
    assert stats["promotions"] == 5 and stats["canary_rejections"] == 1
    assert stats["export_retries"] == 2
    assert stats["rollbacks_of_served"] == 1
    assert traces_quiescent == [0, 0, 0], traces_quiescent
    assert n_requests[0] > 50, n_requests
    q = stats["quarantine"]
    assert q["n_quarantined"] >= 4      # every 3rd batch carried poison
    promoted_q = [quality[g] for g in sorted(promoted)]
    for a, b in zip(promoted_q, promoted_q[1:]):
        assert b <= a * 1.25 + 1e-6, (promoted_q, quality)
    assert promoted_q[-1] < promoted_q[0], promoted_q

    summary = {"metric": "chaos_trainer", "seed": seed,
               "seams": seams, "served_path": served_path,
               "promotions": stats["promotions"],
               "canary_rejections": stats["canary_rejections"],
               "export_retries": stats["export_retries"],
               "rollbacks_of_served": stats["rollbacks_of_served"],
               "preemptions": stats["preemptions"],
               "quarantine": q, "client_requests": n_requests[0],
               "traces_quiescent": traces_quiescent,
               "quality_mse": {str(g): round(v, 8)
                               for g, v in sorted(quality.items())},
               "resilience": prof.resilience_counters()}
    print("CHAOS_TRAINER_SUMMARY " + json.dumps(summary))


@pytest.mark.slow
def test_chaos_oscillation_soak(tmp_path, monkeypatch):
    """Round-16 oscillating-capacity tier (``tools/chaos_soak.sh
    --oscillate``): a seeded shrink → heal → grow capacity walk
    (``faults.oscillation_schedule``) across EVERY chunked estimator
    family.  Capacity swings are re-layouts, not failures, so the
    invariant is stronger than heal-or-typed: every run must COMPLETE,
    spend zero rollback budget on the resizes, and land on its unfaulted
    oracle (bit-for-bit on integral models; policy-precision otherwise).
    """
    from test_chaos_matrix import _estimators
    from dislib_tpu.runtime.preemption import clear_capacity
    from dislib_tpu.utils import profiling as prof

    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    base = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    names = ("kmeans", "gmm", "als", "forest", "csvm", "dbscan", "daura")
    tally = Counter()
    shrinks = grows = 0
    prof.reset_counters()
    for i, name in enumerate(names):
        seed = base + i
        ds.init()
        home = int(np.prod(list(ds.get_mesh().shape.values())))
        fit, model_of = _estimators()[name](np.random.RandomState(seed))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            oracle = fit(
                FitCheckpoint(str(tmp_path / f"o{i}.npz"), every=2), None)
        ref = np.asarray(model_of(oracle), np.float64)

        ds.init()
        pol = faults.CapacityAtSave(
            faults.oscillation_schedule(home, seed))
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                est = fit(
                    FitCheckpoint(str(tmp_path / f"c{i}.npz"), every=2),
                    pol)
        finally:
            clear_capacity()
        info = est.fit_info_
        shrinks += info["mesh_shrinks"]
        grows += info["mesh_grows"]
        assert info["rollbacks"] == 0, \
            f"{name} seed {seed}: a capacity resize consumed rollback budget"
        got = np.asarray(model_of(est), np.float64)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"{name} seed {seed}")
        exact = bool(np.array_equal(got, ref))
        tally[f"{name}:{'bitexact' if exact else 'close'}"] += 1
        ds.init()
    summary = {"metric": "chaos_oscillation", "seed": base,
               "outcomes": dict(sorted(tally.items())),
               "mesh_shrinks": shrinks, "mesh_grows": grows,
               "resilience": prof.resilience_counters()}
    print("CHAOS_OSC_SUMMARY " + json.dumps(summary))
    assert sum(tally.values()) == len(names)
    assert shrinks >= len(names), "every run must shrink at least once"
    assert grows >= 1, "the sweep never exercised grow-back"


@pytest.mark.slow
def test_chaos_mh_soak(tmp_path, monkeypatch):
    """Round-20 multi-host survival soak (``tools/chaos_soak.sh
    --multihost``): repeated kill → resume → rejoin → grow-back episodes
    under live client traffic.  Two membership ranks share a
    FileCoordinator; each episode stops rank 1's heartbeats (the process
    is gone), waits for the survivor's lease watcher to publish the
    shrunk capacity level, then runs a checkpointed KMeans fit that must
    shrink onto the survivor device set, absorb rank 1's RESTART
    mid-fit (rejoin → pressure lifts → the head-home rung grows back),
    and land on the unfaulted oracle.  Throughout, a client thread
    hammers a membership-aware retrieval ``PredictServer``: while the
    peer is dead every request fails TYPED (``ShardDrained``) — never a
    torn result — and serving resumes after the rejoin.

    ``DSLIB_SOAK_EPISODES`` (default 2) and ``DSLIB_SOAK_SEED``
    parameterize; the summary line is ``CHAOS_MH_SUMMARY``.
    """
    import threading
    import time

    from dislib_tpu.parallel import mesh as _mesh
    from dislib_tpu.retrieval import IVFIndex, RetrievalPipeline
    from dislib_tpu.runtime.coord import LeaseKeeper, Membership
    from dislib_tpu.runtime.preemption import capacity_target, clear_capacity
    from dislib_tpu.serving import PredictServer, ShardDrained
    from dislib_tpu.utils import profiling as prof

    episodes = int(os.environ.get("DSLIB_SOAK_EPISODES", "2"))
    seed = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    monkeypatch.setenv("DSLIB_COORD_DIR", str(tmp_path / "coord"))
    monkeypatch.setenv("DSLIB_CAPACITY_LEDGER", str(tmp_path / "cap.ledger"))
    monkeypatch.setenv("DSLIB_COORD_LEASE_MS", "500")
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")

    def wait_for(pred, deadline_s, what):
        t0 = time.monotonic()
        while not pred():
            assert time.monotonic() - t0 < deadline_s, f"{what}: hang"
            time.sleep(0.02)
        return time.monotonic() - t0

    ds.init((8, 1))
    rng = np.random.RandomState(seed)
    centers = rng.rand(3, 4) * 10
    x_np = np.vstack([centers[i] + 0.3 * rng.randn(66, 4)
                      for i in range(3)]).astype(np.float32)
    kw = dict(n_clusters=3, init=np.ascontiguousarray(x_np[[0, 70, 140]]),
              max_iter=12, tol=0.0)
    oracle = KMeans(**kw).fit(
        ds.array(x_np),
        checkpoint=FitCheckpoint(str(tmp_path / "oracle.npz"),
                                 every=2)).centers_

    ix = IVFIndex(n_lists=3, nprobe=3, kmeans_max_iter=8, random_state=0)
    ix.fit(ds.array(x_np))
    pipe = RetrievalPipeline(ix, k=3)

    prof.reset_counters()
    m0 = Membership(0, 2, devices=8, heal_capacity=True)
    m1 = Membership(1, 2, devices=8, heal_capacity=False)
    m0.join(), m1.join()
    k0 = LeaseKeeper(m0, watch=True)
    k0.start()
    k1 = LeaseKeeper(m1, watch=False)
    k1.start()

    stop = threading.Event()
    client = {"ok": 0, "drained": 0, "other": 0}
    q = x_np[:8]

    def traffic():
        while not stop.is_set():
            for attempt in (0, 1):
                try:
                    srv.predict(q)
                    client["ok"] += 1
                except ShardDrained:
                    client["drained"] += 1
                except Exception as e:      # noqa: BLE001 — torn = fail
                    # one retry: a request can land on the very instant
                    # the fit thread flips the global mesh — that race
                    # heals by the next batch.  A PERSISTENT failure
                    # (e.g. a stale bucket canvas after the index
                    # re-stripes) fails the retry too and fails the soak.
                    if attempt == 0:
                        time.sleep(0.1)
                        continue
                    client["other"] += 1
                    client.setdefault("errs", []).append(
                        f"{type(e).__name__}: {e}"[:160])
                break
            time.sleep(0.03)

    recovery = []
    srv = PredictServer(pipeline=pipe, buckets=(1, 8), membership=m0,
                        name="mh-soak")
    srv.start()
    thr = threading.Thread(target=traffic, daemon=True)
    thr.start()
    try:
        for ep in range(episodes):
            base = dict(prof.resilience_counters())
            k1.stop()                       # the KILL: heartbeats stop
            recovery.append(round(wait_for(
                lambda: capacity_target() == 4, 30.0,
                f"ep{ep}: death -> shrunk capacity"), 2))

            restarted = threading.Event()

            def resume():
                # the RESTART, delivered mid-fit: heartbeats come back,
                # the watcher counts the rejoin and clears the pressure
                nonlocal k1
                k1 = LeaseKeeper(m1, watch=False)
                k1.start()
                wait_for(lambda: capacity_target() is None, 30.0,
                         "rejoin heal")
                restarted.set()

            ck = faults.CallbackCheckpoint(
                str(tmp_path / f"ep{ep}.npz"), every=2, after=2,
                callback=resume)
            est = KMeans(**kw).fit(ds.array(x_np), checkpoint=ck)
            info = est.fit_info_
            assert restarted.is_set()
            assert info["mesh_shrinks"] >= 1, (ep, info)
            assert info["mesh_grows"] >= 1, (ep, info)
            assert _mesh.mesh_shape(_mesh.get_mesh()) == (8, 1)
            np.testing.assert_allclose(est.centers_, oracle,
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=f"ep{ep} healed != oracle")
            now = prof.resilience_counters()
            assert now.get("rank_deaths", 0) - base.get("rank_deaths", 0) \
                == 1, (ep, now)
            assert now.get("rank_rejoins", 0) \
                - base.get("rank_rejoins", 0) == 1, (ep, now)
            wait_for(lambda: not srv.stats()["draining"], 30.0,
                     f"ep{ep}: serving resume")
    finally:
        stop.set()
        thr.join(10.0)
        srv.stop()
        k1.stop(), k0.stop()
        clear_capacity()
    counters = prof.resilience_counters()
    summary = {"metric": "chaos_mh", "seed": seed, "episodes": episodes,
               "oracle_match": True, "recovery_s": recovery,
               "client": dict(client),
               "resilience": {k: counters[k] for k in sorted(counters)}}
    print("CHAOS_MH_SUMMARY " + json.dumps(summary))
    assert client["ok"] > 0, "client traffic never served"
    assert client["drained"] >= 1, \
        "no request ever failed typed during a dead window"
    assert client["other"] == 0, f"untyped client failure: {client}"
    assert counters.get("serve_shard_drains", 0) >= 1
