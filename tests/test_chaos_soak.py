"""Chaos soak (round-8 satellite, `slow` tier — run via
``tools/chaos_soak.sh``, excluded from tier-1): N randomized-SCHEDULE but
seeded-and-reproducible fit runs, each drawing a random combination of
every fault family the runtime defends against —

- preemption requested mid-fit (PR-1),
- snapshot corruption between the faulted fit and the resume (PR-1),
- NaN poisoned into a chunk carry (round-8),
- a hung chunk force point under a watchdog deadline (round-8),

— and asserts the ONE invariant the whole resilience+health stack
promises: a fit either completes with a finite model (self-healed), or
raises a TYPED diagnostic (``Preempted`` / ``NumericalDivergence`` /
``WatchdogTimeout`` / ``SnapshotCorrupt``), and a clean resume from
whatever snapshot survives lands on the unfaulted model.  Never a silent
bad model, a hang, or a corrupted-over-good snapshot.

``DSLIB_SOAK_RUNS`` (default 10) and ``DSLIB_SOAK_SEED`` (default 0)
parameterize the sweep; every run's schedule derives from the seed, so a
failure reproduces with the printed seed alone.
"""

import json
import os
import warnings
from collections import Counter

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import GaussianMixture, KMeans
from dislib_tpu.recommendation import ALS
from dislib_tpu.runtime import (NumericalDivergence, Preempted,
                                WatchdogTimeout, clear_preemption,
                                request_preemption)
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils.checkpoint import SnapshotCorrupt

TYPED = (Preempted, NumericalDivergence, WatchdogTimeout, SnapshotCorrupt)


def _estimator(kind, rng):
    """(fresh estimator factory, ds-array data, model-vector extractor)."""
    if kind == "kmeans":
        c = rng.rand(3, 4) * 10
        x_np = np.vstack([c[i] + 0.3 * rng.randn(60, 4)
                          for i in range(3)]).astype(np.float32)
        init = np.ascontiguousarray(x_np[[0, 60, 120]])
        make = lambda: KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0)  # noqa: E731
        return make, ds.array(x_np), lambda e: e.centers_
    if kind == "gmm":
        x_np = np.vstack([rng.rand(60, 3),
                          rng.rand(60, 3) + 4]).astype(np.float32)
        make = lambda: GaussianMixture(n_components=2, max_iter=10, tol=0.0,  # noqa: E731
                                       random_state=0)
        return make, ds.array(x_np), lambda e: e.means_
    u, v = rng.rand(30, 4), rng.rand(20, 4)
    r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)
    make = lambda: ALS(n_f=4, max_iter=8, tol=1e-9, random_state=0)  # noqa: E731
    return make, ds.array(r), lambda e: e.users_


def _one_run(i, seed, tmp_path, monkeypatch):
    rng = np.random.RandomState(seed)
    kind = ("kmeans", "gmm", "als")[rng.randint(3)]
    make, x, model_of = _estimator(kind, rng)
    full = make().fit(x)
    ref = model_of(full)

    path = str(tmp_path / f"soak{i}.npz")
    want_nan = bool(rng.randint(2))
    want_hang = bool(rng.randint(2))
    want_preempt = bool(rng.randint(2))
    want_corrupt = bool(rng.randint(2))
    at_chunk = 1 + int(rng.randint(3))
    if want_hang:
        pol = faults.HangAtChunk(at_chunk=at_chunk, hang_s=0.3,
                                 deadline_s=0.05,
                                 times=int(rng.randint(1, 3)))
    elif want_nan:
        pol = faults.NaNAtChunk(at_chunk=at_chunk)
    else:
        pol = None
    ck = faults.CallbackCheckpoint(path, every=2, after=1 + int(rng.randint(2)),
                                   callback=request_preemption) \
        if want_preempt else FitCheckpoint(path, every=2)

    outcome = "healed"
    try:
        est = make().fit(x, checkpoint=ck, health=pol)
    except TYPED as e:
        outcome = f"typed:{type(e).__name__}"
    else:
        m = model_of(est)
        assert np.isfinite(np.asarray(m)).all(), \
            f"seed {seed}: silent non-finite model ({kind})"
    finally:
        clear_preemption()

    if want_corrupt and os.path.exists(path):
        faults.corrupt_snapshot(
            path, mode=("flip", "truncate", "foreign")[rng.randint(3)])

    # clean resume from whatever snapshot state survives must land on the
    # unfaulted model (corrupt newest generation falls back one)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        try:
            res = make().fit(x, checkpoint=FitCheckpoint(path, every=2))
        except SnapshotCorrupt:
            # every generation damaged: restart from scratch is the
            # documented operator action — and must still work
            for j in range(3):
                p = path if j == 0 else f"{path}.{j}"
                if os.path.exists(p):
                    os.remove(p)
            res = make().fit(x, checkpoint=FitCheckpoint(path, every=2))
            outcome += "+restart"
    np.testing.assert_allclose(model_of(res), ref, rtol=1e-4, atol=1e-5)
    return kind, outcome


@pytest.mark.slow
def test_chaos_soak(tmp_path, monkeypatch):
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    runs = int(os.environ.get("DSLIB_SOAK_RUNS", "10"))
    base = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    tally = Counter()
    for i in range(runs):
        kind, outcome = _one_run(i, base + i, tmp_path, monkeypatch)
        tally[f"{kind}:{outcome}"] += 1
        clear_preemption()
    from dislib_tpu.utils import profiling as prof
    summary = {"metric": "chaos_soak", "runs": runs, "seed": base,
               "outcomes": dict(sorted(tally.items())),
               "resilience": prof.resilience_counters()}
    print("CHAOS_SOAK_SUMMARY " + json.dumps(summary))
    assert sum(tally.values()) == runs


@pytest.mark.slow
def test_chaos_oscillation_soak(tmp_path, monkeypatch):
    """Round-16 oscillating-capacity tier (``tools/chaos_soak.sh
    --oscillate``): a seeded shrink → heal → grow capacity walk
    (``faults.oscillation_schedule``) across EVERY chunked estimator
    family.  Capacity swings are re-layouts, not failures, so the
    invariant is stronger than heal-or-typed: every run must COMPLETE,
    spend zero rollback budget on the resizes, and land on its unfaulted
    oracle (bit-for-bit on integral models; policy-precision otherwise).
    """
    from test_chaos_matrix import _estimators
    from dislib_tpu.runtime.preemption import clear_capacity
    from dislib_tpu.utils import profiling as prof

    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    base = int(os.environ.get("DSLIB_SOAK_SEED", "0"))
    names = ("kmeans", "gmm", "als", "forest", "csvm", "dbscan", "daura")
    tally = Counter()
    shrinks = grows = 0
    prof.reset_counters()
    for i, name in enumerate(names):
        seed = base + i
        ds.init()
        home = int(np.prod(list(ds.get_mesh().shape.values())))
        fit, model_of = _estimators()[name](np.random.RandomState(seed))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            oracle = fit(
                FitCheckpoint(str(tmp_path / f"o{i}.npz"), every=2), None)
        ref = np.asarray(model_of(oracle), np.float64)

        ds.init()
        pol = faults.CapacityAtSave(
            faults.oscillation_schedule(home, seed))
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                est = fit(
                    FitCheckpoint(str(tmp_path / f"c{i}.npz"), every=2),
                    pol)
        finally:
            clear_capacity()
        info = est.fit_info_
        shrinks += info["mesh_shrinks"]
        grows += info["mesh_grows"]
        assert info["rollbacks"] == 0, \
            f"{name} seed {seed}: a capacity resize consumed rollback budget"
        got = np.asarray(model_of(est), np.float64)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"{name} seed {seed}")
        exact = bool(np.array_equal(got, ref))
        tally[f"{name}:{'bitexact' if exact else 'close'}"] += 1
        ds.init()
    summary = {"metric": "chaos_oscillation", "seed": base,
               "outcomes": dict(sorted(tally.items())),
               "mesh_shrinks": shrinks, "mesh_grows": grows,
               "resilience": prof.resilience_counters()}
    print("CHAOS_OSC_SUMMARY " + json.dumps(summary))
    assert sum(tally.values()) == len(names)
    assert shrinks >= len(names), "every run must shrink at least once"
    assert grows >= 1, "the sweep never exercised grow-back"
