"""Native (C++) fastio layer: parity with the pure-NumPy parsers.

The native module is a performance component with a mandatory fallback, so
these tests assert BOTH that the native parse (when buildable) matches the
NumPy oracle and that the io.py entry points give identical results with the
native layer disabled (DSLIB_NO_NATIVE)."""

import io as _io
import os

import numpy as np
import pytest

from dislib_tpu import native


def _native_available():
    return native.get_lib() is not None


pytestmark = pytest.mark.skipif(
    not _native_available(), reason="native toolchain unavailable (fallback "
    "paths are covered by tests/test_io.py)")


class TestParseText:
    def test_matches_loadtxt(self):
        rng = np.random.RandomState(0)
        a = rng.standard_normal((500, 13)).astype(np.float64)
        buf = "\n".join(",".join(f"{v:.9e}" for v in row) for row in a)
        buf = buf.encode()
        got = native.parse_text(buf)
        ref = np.loadtxt(_io.BytesIO(buf), delimiter=",", dtype=np.float32,
                         ndmin=2)
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, ref, rtol=2e-7, atol=1e-30)

    def test_plain_decimals_and_blank_lines(self):
        buf = b"1.5,2,-3.25\n\n4,5.125,6\n   \n7,8,9\n"
        got = native.parse_text(buf)
        np.testing.assert_allclose(
            got, [[1.5, 2, -3.25], [4, 5.125, 6], [7, 8, 9]])

    def test_inf_nan_fallback_tokens(self):
        got = native.parse_text(b"1.0,inf,-inf\nnan,2.5e-3,3\n")
        assert np.isinf(got[0, 1]) and got[0, 1] > 0
        assert np.isinf(got[0, 2]) and got[0, 2] < 0
        assert np.isnan(got[1, 0])
        np.testing.assert_allclose(got[1, 1:], [2.5e-3, 3.0])

    def test_ragged_raises(self):
        with pytest.raises(native.NativeUnavailable):
            native.parse_text(b"1,2,3\n4,5\n")

    def test_malformed_token_raises(self):
        # np.loadtxt raises on these; the native layer must defer, not guess
        with pytest.raises(native.NativeUnavailable):
            native.parse_text(b"a1,2\n3,4\n")
        with pytest.raises(native.NativeUnavailable):
            native.parse_text(b"1,,3\n")          # empty field
        with pytest.raises(native.NativeUnavailable):
            native.parse_text(b"1,2,\n")          # trailing delimiter

    def test_comments_match_loadtxt(self):
        buf = b"# h1,h2\n1,2 # trailing\n3,4\n"
        got = native.parse_text(buf)
        ref = np.loadtxt(_io.BytesIO(buf), delimiter=",", dtype=np.float32,
                         ndmin=2)
        np.testing.assert_array_equal(got, ref)

    def test_empty(self):
        assert native.parse_text(b"").shape == (0, 0)

    def test_threaded_equals_single(self):
        rng = np.random.RandomState(1)
        a = rng.rand(997, 7).astype(np.float32)   # odd row count: uneven split
        buf = "\n".join(",".join(f"{v:.6f}" for v in row) for row in a)
        buf = buf.encode()
        np.testing.assert_array_equal(native.parse_text(buf, nthreads=1),
                                      native.parse_text(buf, nthreads=5))


class TestParseSvmlight:
    def test_csr_roundtrip(self):
        sv = b"1 1:0.5 3:2.0\n-1 2:1.5\n# comment line\n1 1:1.0 4:2.5e-1\n"
        labels, indptr, indices, data, nfeat = native.parse_svmlight(sv)
        np.testing.assert_allclose(labels, [1, -1, 1])
        assert nfeat == 4
        import scipy.sparse as sp
        csr = sp.csr_matrix((data, indices, indptr), shape=(3, nfeat))
        dense = csr.toarray()
        np.testing.assert_allclose(dense[0], [0.5, 0, 2.0, 0])
        np.testing.assert_allclose(dense[1], [0, 1.5, 0, 0])
        np.testing.assert_allclose(dense[2], [1.0, 0, 0, 0.25])

    def test_malformed_raises(self):
        with pytest.raises(native.NativeUnavailable):
            native.parse_svmlight(b"1 nonsense\n")

    def test_duplicate_indices_sum_both_paths(self, tmp_path):
        p = str(tmp_path / "dup.svm")
        with open(p, "w") as f:
            f.write("1 2:1.0 2:2.0\n-1 1:0.5\n")
        from dislib_tpu.data.io import load_svmlight_file
        x1, _ = load_svmlight_file(p, store_sparse=False)
        os.environ["DSLIB_NO_NATIVE"] = "1"
        try:
            x2, _ = load_svmlight_file(p, store_sparse=False)
        finally:
            del os.environ["DSLIB_NO_NATIVE"]
        np.testing.assert_allclose(x1.collect(), x2.collect())
        assert np.asarray(x1.collect())[0, 1] == 3.0   # 1.0 + 2.0 summed


class TestParseMdcrdErrors:
    def test_overflow_field_raises(self):
        # AMBER writes ******** on overflow; dropping the field would shift
        # every later coordinate — must defer to the Python path (raises)
        buf = b"title\n   1.000********   3.000\n"
        with pytest.raises(native.NativeUnavailable):
            native.parse_mdcrd(buf)


class TestParseMdcrd:
    def test_fixed_width(self):
        vals = np.arange(24, dtype=np.float32) * 1.5
        body = "".join(f"{v:8.3f}" for v in vals)
        lines = "\n".join(body[i:i + 80] for i in range(0, len(body), 80))
        buf = ("title\n" + lines + "\n").encode()
        got = native.parse_mdcrd(buf)
        np.testing.assert_allclose(got, vals, atol=1e-3)


class TestIoIntegration:
    """io.py entry points: native and fallback paths agree."""

    def test_load_txt_file_paths_agree(self, tmp_path):
        rng = np.random.RandomState(2)
        a = rng.rand(64, 5).astype(np.float32)
        p = str(tmp_path / "m.csv")
        np.savetxt(p, a, delimiter=",")
        from dislib_tpu.data.io import load_txt_file
        x_native = load_txt_file(p, block_size=(16, 5)).collect()
        os.environ["DSLIB_NO_NATIVE"] = "1"
        try:
            x_py = load_txt_file(p, block_size=(16, 5)).collect()
        finally:
            del os.environ["DSLIB_NO_NATIVE"]
        np.testing.assert_allclose(x_native, x_py, rtol=1e-6)

    def test_load_svmlight_paths_agree(self, tmp_path):
        p = str(tmp_path / "d.svm")
        with open(p, "w") as f:
            f.write("1 1:0.5 3:2.0\n-1 2:1.5\n1 1:1.0 4:0.25\n2 3:1.0\n")
        from dislib_tpu.data.io import load_svmlight_file
        x1, y1 = load_svmlight_file(p, store_sparse=False)
        os.environ["DSLIB_NO_NATIVE"] = "1"
        try:
            x2, y2 = load_svmlight_file(p, store_sparse=False)
        finally:
            del os.environ["DSLIB_NO_NATIVE"]
        np.testing.assert_allclose(x1.collect(), x2.collect(), rtol=1e-6)
        np.testing.assert_allclose(y1.collect(), y2.collect(), rtol=1e-6)
