"""Mixed-precision distributed linear algebra (round-10 perf PR).

Four pillars, every assertion against single sources of truth:

1. **Accuracy bounds** — a parametrized grid comparing each policy's
   result against the f32/f64 reference across shapes AND condition
   numbers, asserted against the DOCUMENTED bounds in
   ``ops/precision.ERROR_BOUNDS`` (the user-guide table quotes the same
   dict, so docs and tests cannot drift apart).
2. **SUMMA** — the explicit panel-broadcast schedule on a genuinely 2-D
   mesh: oracle equivalence (irregular shapes, transposes, bf16), the
   algorithm-routing rule, and the ONE-dispatch contract.
3. **Newton–Schulz polar** — factorisation properties vs the SVD oracle
   and the one-dispatch-at-any-iteration-count contract (each iteration
   adds ZERO dispatches — the PR-2/PR-4 counter-pinning pattern).
4. **Pad-tail hygiene** — the shared grow/crop helpers must keep a
   padded tail out of every reduced-precision accumulation even when the
   backing's zero-pad invariant has been violated upstream.
"""

import os

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.ops import precision as px
from dislib_tpu.utils import profiling as prof


def _conditioned(m, n, cond, seed=0):
    """Deterministic (m, n) float32 matrix with condition number ~cond and
    unit largest singular value."""
    rng = np.random.RandomState(seed)
    k = min(m, n)
    u, _ = np.linalg.qr(rng.standard_normal((m, k)))
    v, _ = np.linalg.qr(rng.standard_normal((n, k)))
    s = np.logspace(0, -np.log10(cond), k)
    return (u * s) @ v.T


# ---------------------------------------------------------------------------
# policy resolution
# ---------------------------------------------------------------------------

class TestPolicyResolution:
    def test_aliases(self):
        for name in ("float32", "f32", "fp32", "highest"):
            assert px.resolve(name) is px.FLOAT32
        for name in ("bfloat16", "bf16", "BF16"):
            assert px.resolve(name) is px.BFLOAT16
        assert px.resolve(px.BFLOAT16) is px.BFLOAT16

    def test_env_default(self, monkeypatch):
        monkeypatch.delenv("DSLIB_MATMUL_PRECISION", raising=False)
        assert px.resolve(None) is px.FLOAT32
        monkeypatch.setenv("DSLIB_MATMUL_PRECISION", "bf16")
        assert px.resolve(None) is px.BFLOAT16
        # explicit kwarg beats the env
        assert px.resolve("float32") is px.FLOAT32

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="unknown precision policy"):
            px.resolve("float16")

    def test_policy_is_static_cache_key(self):
        """Same operand, different policy → different jit trace (the env
        flip cannot be silently ignored)."""
        rng = np.random.RandomState(0)
        a = ds.array(rng.rand(24, 16).astype(np.float32)).force()
        b = ds.array(rng.rand(16, 8).astype(np.float32)).force()
        ds.matmul(a, b).force()
        ds.matmul(a, b, precision="bf16").force()
        prof.reset_counters()
        f32 = np.asarray(ds.matmul(a, b).force().collect())
        bf16 = np.asarray(ds.matmul(a, b, precision="bf16").force()
                          .collect())
        # both warm (no retrace), and genuinely different numerics
        assert prof.trace_count() == 0
        assert np.abs(f32 - bf16).max() > 0

    def test_f64_passthrough_under_float32_floor(self):
        """x64-mode data must not be narrowed by the DEFAULT policy (the
        ds.array dtype-policy precedent: narrowing is never implicit)."""
        import jax.numpy as jnp
        x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
        assert px.to_compute(x, px.FLOAT32).dtype == jnp.float32
        assert px.to_compute(x, px.BFLOAT16).dtype == jnp.bfloat16
        # f32 policy upcasts bf16 (faithful floor), bf16 policy rounds
        assert px.to_compute(x.astype(jnp.bfloat16),
                             px.FLOAT32).dtype == jnp.float32


# ---------------------------------------------------------------------------
# accuracy bounds — the documented table IS the assertion
# ---------------------------------------------------------------------------

POLICIES = ("float32", "bfloat16")
CONDS = (10.0, 1e4)


class TestAccuracyBounds:
    @pytest.mark.parametrize("policy", POLICIES)
    @pytest.mark.parametrize("cond", CONDS)
    @pytest.mark.parametrize("shape", [(64, 48, 32), (96, 40, 56)])
    def test_matmul(self, policy, cond, shape):
        m, k, n = shape
        a_host = _conditioned(m, k, cond, seed=1).astype(np.float32)
        b_host = _conditioned(k, n, cond, seed=2).astype(np.float32)
        ref = a_host.astype(np.float64) @ b_host.astype(np.float64)
        got = np.asarray(ds.matmul(ds.array(a_host), ds.array(b_host),
                                   precision=policy).collect(),
                         dtype=np.float64)
        err = np.abs(got - ref).max() / np.abs(ref).max()
        assert err <= px.ERROR_BOUNDS[("matmul", policy)], \
            f"matmul {policy} cond={cond}: {err:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    @pytest.mark.parametrize("cond", CONDS)
    def test_tsqr(self, policy, cond):
        x = _conditioned(512, 48, cond, seed=3).astype(np.float32)
        q, r = ds.tsqr(ds.array(x, block_size=(64, 48)), precision=policy)
        qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
        orth = np.abs(qh.T @ qh - np.eye(48)).max()
        resid = np.linalg.norm(qh @ rh - x) / np.linalg.norm(x)
        assert orth <= px.ERROR_BOUNDS[("tsqr_orth", policy)], \
            f"tsqr {policy} cond={cond}: orth {orth:.2e}"
        assert resid <= px.ERROR_BOUNDS[("tsqr_resid", policy)], \
            f"tsqr {policy} cond={cond}: resid {resid:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    @pytest.mark.parametrize("cond", CONDS)
    def test_blocked_qr(self, policy, cond, monkeypatch):
        import importlib
        qrmod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qrmod, "_PANEL", 16)   # blocked path, cheaply
        x = _conditioned(256, 40, cond, seed=4).astype(np.float32)
        a = ds.array(x, block_size=(32, 40))
        q, r = ds.qr(a, mode="economic", precision=policy)
        qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
        orth = np.abs(qh.T @ qh - np.eye(40)).max()
        resid = np.linalg.norm(qh @ rh - x) / np.linalg.norm(x)
        assert orth <= px.ERROR_BOUNDS[("qr_orth", policy)], \
            f"qr {policy} cond={cond}: orth {orth:.2e}"
        assert resid <= px.ERROR_BOUNDS[("qr_resid", policy)], \
            f"qr {policy} cond={cond}: resid {resid:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    def test_randomsvd(self, policy):
        rng = np.random.RandomState(5)
        x = (rng.standard_normal((768, 96))
             * 0.9 ** np.arange(96)).astype(np.float32)
        s_ref = np.linalg.svd(x, compute_uv=False)
        _, s, _ = ds.random_svd(ds.array(x, block_size=(96, 96)), nsv=12,
                                random_state=0, precision=policy)
        sd = np.asarray(s.collect()).ravel()
        err = np.abs(sd - s_ref[:12]).max() / s_ref[0]
        assert err <= px.ERROR_BOUNDS[("randomsvd_values", policy)], \
            f"randomsvd {policy}: {err:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    def test_lanczos(self, policy):
        rng = np.random.RandomState(6)
        x = (rng.standard_normal((384, 64))
             * 0.9 ** np.arange(64)).astype(np.float32)
        s_ref = np.linalg.svd(x, compute_uv=False)
        _, s, _ = ds.lanczos_svd(ds.array(x), k=6, random_state=0,
                                 precision=policy)
        sd = np.asarray(s.collect()).ravel()
        err = np.abs(sd - s_ref[:6]).max() / s_ref[0]
        assert err <= px.ERROR_BOUNDS[("lanczos_values", policy)], \
            f"lanczos {policy}: {err:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    @pytest.mark.parametrize("cond", CONDS)
    def test_polar(self, policy, cond):
        x = _conditioned(192, 40, cond, seed=7).astype(np.float32)
        u, h = ds.polar(ds.array(x), precision=policy, max_iter=60)
        uh, hh = np.asarray(u.collect()), np.asarray(h.collect())
        orth = np.abs(uh.T @ uh - np.eye(40)).max()
        resid = np.linalg.norm(uh @ hh - x) / np.linalg.norm(x)
        assert orth <= px.ERROR_BOUNDS[("polar_orth", policy)], \
            f"polar {policy} cond={cond}: orth {orth:.2e}"
        assert resid <= px.ERROR_BOUNDS[("polar_resid", policy)], \
            f"polar {policy} cond={cond}: resid {resid:.2e}"

    @pytest.mark.parametrize("policy", POLICIES)
    @pytest.mark.parametrize("cond", CONDS)
    def test_svd_block_tier(self, policy, cond):
        """Round-11 satellite (ROADMAP item 5 follow-up (b)): the
        block-Jacobi pair-update GEMMs follow the policy; values and the
        full-factor residual hold the documented bounds on the block
        tier (n >= 2*64 engages the column-block pairing)."""
        m, n = 256, 160
        x = _conditioned(m, n, cond, seed=11).astype(np.float32)
        s_ref = np.linalg.svd(x.astype(np.float64), compute_uv=False)
        u, s, v = ds.svd(ds.array(x), precision=policy)
        sv = np.asarray(s.collect()).ravel()
        uh, vh = np.asarray(u.collect()), np.asarray(v.collect())
        val_err = np.max(np.abs(sv - s_ref) / s_ref[0])
        resid = np.linalg.norm(x - (uh * sv) @ vh.T) / np.linalg.norm(x)
        assert val_err <= px.ERROR_BOUNDS[("svd_values", policy)], \
            f"svd {policy} cond={cond}: values {val_err:.2e}"
        assert resid <= px.ERROR_BOUNDS[("svd_resid", policy)], \
            f"svd {policy} cond={cond}: resid {resid:.2e}"

    def test_svd_scalar_tier_pinned_f32(self):
        """Below the block threshold there is no FLOP-dominant GEMM: the
        scalar tier ignores the policy (documented), so bf16 and f32
        requests return bit-identical factors."""
        x = _conditioned(48, 24, 10.0, seed=12).astype(np.float32)
        s32 = np.asarray(ds.svd(ds.array(x), compute_uv=False,
                                precision="float32").collect())
        sbf = np.asarray(ds.svd(ds.array(x), compute_uv=False,
                                precision="bfloat16").collect())
        np.testing.assert_array_equal(s32, sbf)

    def test_svd_bf16_eps_floor_converges(self):
        """The per-policy eps floor (5e-3) keeps a default-eps bf16 call
        from burning max_sweeps chasing unreachable 1e-6 orthogonality:
        the sweep loop must terminate well inside the budget and still
        meet the documented bounds."""
        x = _conditioned(256, 160, 10.0, seed=13).astype(np.float32)
        s_ref = np.linalg.svd(x.astype(np.float64), compute_uv=False)
        s = np.asarray(ds.svd(ds.array(x), compute_uv=False,
                              precision="bfloat16").collect()).ravel()
        err = np.max(np.abs(s - s_ref) / s_ref[0])
        assert err <= px.ERROR_BOUNDS[("svd_values", "bfloat16")]

    def test_pca_policy_close_to_f32(self):
        rng = np.random.RandomState(8)
        x = (rng.standard_normal((512, 32))
             * 0.9 ** np.arange(32)).astype(np.float32)
        a = ds.array(x)
        var32 = np.asarray(ds.PCA(n_components=4).fit(a)
                           .explained_variance_.collect())
        var16 = np.asarray(ds.PCA(n_components=4, precision="bf16").fit(a)
                           .explained_variance_.collect())
        assert np.abs(var16 - var32).max() / var32.max() <= 2e-2

    def test_composed_randomsvd_ignores_ambient_env(self, monkeypatch):
        """The composed (non-fused) random_svd path pins its tsqr
        orthonormalisations to f32 EXPLICITLY — an ambient
        DSLIB_MATMUL_PRECISION must not leak into an explicit
        precision='float32' call (review-found; m < sketch forces the
        composed path)."""
        rng = np.random.RandomState(11)
        x = rng.standard_normal((24, 64)).astype(np.float32)  # m < sketch
        a = ds.array(x)
        _, s_clean, _ = ds.random_svd(a, nsv=4, random_state=0,
                                      precision="float32")
        monkeypatch.setenv("DSLIB_MATMUL_PRECISION", "bfloat16")
        _, s_env, _ = ds.random_svd(a, nsv=4, random_state=0,
                                    precision="float32")
        np.testing.assert_array_equal(np.asarray(s_clean.collect()),
                                      np.asarray(s_env.collect()))

    def test_polar_info_err_describes_returned_factor(self, rng):
        """On a max_iter exit the reported ortho_err must measure the
        RETURNED U, not the pre-update iterate (review-found off-by-one-
        contraction)."""
        x = rng.standard_normal((96, 12)).astype(np.float32)
        u, _, info = ds.polar(ds.array(x), max_iter=3, info=True)
        uh = np.asarray(u.collect())
        true_err = np.abs(uh.T @ uh - np.eye(12)).max()
        assert abs(info["ortho_err"] - true_err) <= 1e-5 + 0.05 * true_err

    def test_env_var_routes_the_default(self, monkeypatch):
        """DSLIB_MATMUL_PRECISION=bfloat16 flips the kwarg-less path — the
        result must match the explicit precision='bfloat16' call exactly
        (same policy object → same traced program)."""
        rng = np.random.RandomState(9)
        x = rng.rand(48, 32).astype(np.float32)
        y = rng.rand(32, 24).astype(np.float32)
        a, b = ds.array(x), ds.array(y)
        explicit = np.asarray(ds.matmul(a, b, precision="bfloat16")
                              .collect())
        monkeypatch.setenv("DSLIB_MATMUL_PRECISION", "bfloat16")
        via_env = np.asarray(ds.matmul(a, b).collect())
        np.testing.assert_array_equal(explicit, via_env)


# ---------------------------------------------------------------------------
# SUMMA
# ---------------------------------------------------------------------------

class TestSumma:
    @pytest.fixture(autouse=True)
    def _mesh2d(self):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init((4, 2))
        yield
        ds.init()

    @pytest.mark.parametrize("shapes", [((64, 64), (64, 64)),
                                        ((33, 65), (65, 12)),
                                        ((17, 5), (5, 9))])
    def test_oracle(self, rng, shapes):
        (m, k), (_, n) = shapes
        x, y = (rng.rand(m, k).astype(np.float32),
                rng.rand(k, n).astype(np.float32))
        got = ds.matmul(ds.array(x), ds.array(y),
                        algorithm="summa").collect()
        np.testing.assert_allclose(got, x @ y, rtol=1e-4, atol=1e-5)

    def test_auto_picks_summa_on_2d_mesh(self, monkeypatch):
        import dislib_tpu.math.base as mb
        monkeypatch.setattr(mb, "_SUMMA_MIN_DIM", 16)    # paper-scale gate
        rng = np.random.RandomState(0)
        a = ds.array(rng.rand(32, 32).astype(np.float32)).force()
        ds.matmul(a, a).force()                          # warm
        prof.reset_counters()
        ds.matmul(a, a).force()
        assert prof.counters()["dispatch_by"].get("summa_matmul") == 1
        # transposed operands stay on the XLA fusion path under auto
        ds.matmul(a, a, transpose_a=True).force()
        prof.reset_counters()
        ds.matmul(a, a, transpose_a=True).force()
        assert "summa_matmul" not in prof.counters()["dispatch_by"]

    def test_auto_preserves_fusion_for_lazy_and_small_operands(self,
                                                               monkeypatch):
        """Auto-SUMMA must not steal a GEMM out of a pending fusion chain
        (the chain would force and gain dispatches) nor grab sub-scale
        products; both stay one fused dispatch on a 2-D mesh."""
        import dislib_tpu.math.base as mb
        rng = np.random.RandomState(0)
        a = ds.array(rng.rand(32, 32).astype(np.float32)).force()
        # small concrete operands: below _SUMMA_MIN_DIM → xla fusion node
        ds.matmul(a, a).force()
        prof.reset_counters()
        ds.matmul(a, a).force()
        assert "summa_matmul" not in prof.counters()["dispatch_by"]
        # lazy chain ending in a matmul: even at SUMMA-eligible sizes the
        # whole chain is ONE fused dispatch
        monkeypatch.setattr(mb, "_SUMMA_MIN_DIM", 16)
        y = (a * 2.0 + 1.0)                              # pending chain
        out = ds.matmul(y, a)
        assert out.is_lazy
        prof.reset_counters()
        out.force()
        assert prof.dispatch_count() == 1
        assert "summa_matmul" not in prof.counters()["dispatch_by"]

    def test_auto_picks_xla_on_1d_mesh(self):
        ds.init()                                        # (8, 1)
        rng = np.random.RandomState(0)
        a = ds.array(rng.rand(32, 32).astype(np.float32)).force()
        ds.matmul(a, a).force()
        prof.reset_counters()
        ds.matmul(a, a).force()
        assert "summa_matmul" not in prof.counters()["dispatch_by"]

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("DSLIB_MATMUL_ALGO", "xla")
        rng = np.random.RandomState(0)
        a = ds.array(rng.rand(32, 32).astype(np.float32)).force()
        ds.matmul(a, a).force()
        prof.reset_counters()
        ds.matmul(a, a).force()
        assert "summa_matmul" not in prof.counters()["dispatch_by"]

    def test_transposes_match_oracle(self, rng):
        x, y = (rng.rand(12, 40).astype(np.float32),
                rng.rand(9, 40).astype(np.float32))
        got = ds.matmul(ds.array(x), ds.array(y), transpose_b=True,
                        algorithm="summa").collect()
        np.testing.assert_allclose(got, x @ y.T, rtol=1e-4, atol=1e-5)

    def test_bf16_policy_within_bound(self, rng):
        x = rng.rand(64, 48).astype(np.float32)
        y = rng.rand(48, 40).astype(np.float32)
        ref = x.astype(np.float64) @ y.astype(np.float64)
        got = np.asarray(ds.matmul(ds.array(x), ds.array(y),
                                   algorithm="summa",
                                   precision="bf16").collect(),
                         dtype=np.float64)
        err = np.abs(got - ref).max() / np.abs(ref).max()
        assert 0 < err <= px.ERROR_BOUNDS[("matmul", "bfloat16")]

    def test_one_dispatch(self, rng):
        a = ds.array(rng.rand(64, 64).astype(np.float32)).force()
        for prec in (None, "bf16"):
            ds.matmul(a, a, algorithm="summa", precision=prec).force()
            prof.reset_counters()
            ds.matmul(a, a, algorithm="summa", precision=prec).force()
            assert prof.dispatch_count() == 1, prof.counters()

    def test_cross_mesh_operands_repad(self, rng):
        """An operand built under an older mesh quantum (here: unpadded,
        from a (1,1) mesh) must repad to the current grid instead of the
        panel loop silently dropping the K tail."""
        x = rng.rand(33, 65).astype(np.float32)
        y = rng.rand(65, 12).astype(np.float32)
        ds.init((1, 1))
        a, b = ds.array(x).force(), ds.array(y).force()
        ds.init((4, 2))
        got = ds.matmul(a, b, algorithm="summa").collect()
        np.testing.assert_allclose(got, x @ y, rtol=1e-4, atol=1e-5)

    def test_matches_xla_path_closely(self, rng):
        """Same operands, both schedules, near bit-equality (both are
        f32-faithful dots over the same zero-padded data; only the
        reduction ORDER differs, so the bound is a few ulps scaled)."""
        x = rng.rand(96, 80).astype(np.float32)
        y = rng.rand(80, 72).astype(np.float32)
        s_got = np.asarray(ds.matmul(ds.array(x), ds.array(y),
                                     algorithm="summa").collect())
        x_got = np.asarray(ds.matmul(ds.array(x), ds.array(y),
                                     algorithm="xla").collect())
        np.testing.assert_allclose(s_got, x_got, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# polar: dispatch contract + API edges
# ---------------------------------------------------------------------------

class TestPolar:
    def test_one_dispatch_at_any_iteration_count(self, rng):
        """A full Newton–Schulz run is ONE fused dispatch — iterating adds
        ZERO dispatches (the loop lives inside the program)."""
        a = ds.array(rng.standard_normal((128, 24)).astype(np.float32))
        a.force()
        for iters in (1, 8, 30):
            ds.polar(a, max_iter=iters)                  # warm this trace
            prof.reset_counters()
            ds.polar(a, max_iter=iters)
            assert prof.dispatch_count() == 1, \
                (iters, prof.counters())
            assert prof.counters()["dispatch_by"].get("polar_ns") == 1

    def test_info_and_convergence(self, rng):
        x = rng.standard_normal((96, 16)).astype(np.float32)
        u, h, info = ds.polar(ds.array(x), info=True)
        assert info["iterations"] < 30
        assert info["ortho_err"] <= 1e-5
        # H symmetric PSD
        hh = np.asarray(h.collect())
        np.testing.assert_allclose(hh, hh.T, atol=1e-6)
        assert np.linalg.eigvalsh(hh).min() > -1e-4

    def test_matches_svd_oracle(self, rng):
        x = rng.standard_normal((80, 12)).astype(np.float32)
        u, _ = ds.polar(ds.array(x))
        uo, _, vto = np.linalg.svd(x, full_matrices=False)
        np.testing.assert_allclose(np.asarray(u.collect()), uo @ vto,
                                   rtol=1e-3, atol=1e-4)

    def test_wide_raises(self, rng):
        with pytest.raises(ValueError, match="tall or square"):
            ds.polar(ds.array(rng.rand(4, 9).astype(np.float32)))

    def test_tol_clamp_warns(self, rng):
        a = ds.array(rng.standard_normal((64, 8)).astype(np.float32))
        with pytest.warns(RuntimeWarning, match="orthogonality floor"):
            ds.polar(a, precision="bf16", tol=1e-9)

    def test_irregular_pad_shapes(self, rng):
        """Quantum-padded rows/cols stay exactly zero through the iterates
        (σ = 0 fixed point) — the logical factors are pad-independent."""
        x = rng.standard_normal((37, 11)).astype(np.float32)
        u, h = ds.polar(ds.array(x))
        uh = np.asarray(u.collect())
        assert uh.shape == (37, 11)
        assert np.abs(uh.T @ uh - np.eye(11)).max() < 1e-4
        # the padded backing outside the logical block is still zero
        backing = np.asarray(u._data)
        assert np.all(backing[37:, :] == 0) and np.all(backing[:, 11:] == 0)


# ---------------------------------------------------------------------------
# pad-tail hygiene: the shared grow/crop helpers under a violated invariant
# ---------------------------------------------------------------------------

class TestPadTailHygiene:
    def _poisoned_col_tail(self, x):
        """An Array whose padded COLUMN tail is garbage — the invariant
        violation the shared helpers must be robust to."""
        a = ds.array(x)
        data = a._data
        m, n = a.shape
        if data.shape[1] == n:
            pytest.skip("no column padding at this shape/mesh")
        bad = data.at[:, n:].set(1e6)
        return ds.Array(bad, (m, n), a.block_size, False)

    def test_poisoned_pad_tail_cannot_leak_into_svd(self, rng):
        """Both Jacobi tiers re-assert the zero-pad invariant through the
        shared grow_canvas helper at ingest — a garbage tail (which at
        bf16 scales would swamp every small singular value) changes
        NOTHING."""
        x = rng.standard_normal((40, 10)).astype(np.float32)
        clean = np.asarray(ds.svd(ds.array(x), compute_uv=False).collect())
        poisoned = np.asarray(ds.svd(self._poisoned_col_tail(x),
                                     compute_uv=False).collect())
        np.testing.assert_array_equal(clean, poisoned)

    def test_poisoned_pad_tail_cannot_leak_into_blocked_qr(self, rng,
                                                           monkeypatch):
        import importlib
        qrmod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qrmod, "_PANEL", 16)
        x = rng.standard_normal((256, 20)).astype(np.float32)
        a_clean = ds.array(x, block_size=(32, 20))
        r_clean = np.asarray(ds.qr(a_clean, mode="r").collect())
        data = a_clean._data
        if data.shape[1] == 20:
            pytest.skip("no column padding at this shape/mesh")
        bad = ds.Array(data.at[:, 20:].set(1e6), (256, 20),
                       a_clean.block_size, False)
        r_bad = np.asarray(ds.qr(bad, mode="r", precision="bf16").collect())
        r_bad32 = np.asarray(ds.qr(bad, mode="r").collect())
        # the f32 run of the POISONED array must equal the clean run
        # exactly (the tail is masked before any accumulation)...
        np.testing.assert_array_equal(r_clean, r_bad32)
        # ...and the bf16 run must stay within its documented residual
        # bound of the clean reference rather than being 1e6-swamped
        assert np.abs(np.abs(r_bad) - np.abs(r_clean)).max() \
            / np.abs(r_clean).max() <= px.ERROR_BOUNDS[("qr_resid",
                                                        "bfloat16")]

    def test_block_jacobi_tier_masks_tail(self, rng):
        """The ≥128-column block tier routes its canvas through
        grow_canvas(valid=...) — poisoned tail, identical spectrum."""
        x = rng.standard_normal((160, 130)).astype(np.float32)
        a = ds.array(x)
        data = a._data
        if data.shape[1] == 130:
            pytest.skip("no column padding at this shape/mesh")
        bad = ds.Array(data.at[:, 130:].set(1e6), (160, 130),
                       a.block_size, False)
        s_clean = np.asarray(ds.svd(a, compute_uv=False).collect())
        s_bad = np.asarray(ds.svd(bad, compute_uv=False).collect())
        np.testing.assert_array_equal(s_clean, s_bad)
