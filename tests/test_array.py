"""ds-array tests — mirrors the reference's `tests/test_array.py` strategy
(SURVEY.md §5): small arrays, deliberately irregular block sizes, dense and
(later) sparse variants, NumPy as the oracle, determinism via random_state."""

import numpy as np
import pytest

import dislib_tpu as ds


def _mk(rng, shape, bs=None):
    x = rng.rand(*shape)
    return ds.array(x, block_size=bs), x.astype(np.float32)


class TestConstruction:
    def test_from_numpy(self, rng):
        a, x = _mk(rng, (25, 13), (4, 5))
        assert a.shape == (25, 13)
        assert a.block_size == (4, 5)
        np.testing.assert_allclose(a.collect(), x)

    def test_from_list(self):
        a = ds.array([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(a.collect(), [[1, 2], [3, 4]])

    def test_1d_promotes_to_row(self):
        a = ds.array(np.arange(5.0))
        assert a.shape == (1, 5)

    def test_3d_rejected(self):
        with pytest.raises(ValueError):
            ds.array(np.zeros((2, 2, 2)))

    def test_irregular_blocks(self, rng):
        # shapes that don't divide the mesh or block size evenly
        for shape in [(1, 1), (7, 3), (17, 19), (8, 64), (100, 1)]:
            a, x = _mk(rng, shape, (3, 2))
            np.testing.assert_allclose(a.collect(), x)

    def test_zeros_full_identity_eye(self):
        np.testing.assert_allclose(ds.zeros((5, 3)).collect(), np.zeros((5, 3)))
        np.testing.assert_allclose(ds.full((4, 6), 2.5).collect(), np.full((4, 6), 2.5))
        np.testing.assert_allclose(ds.identity(7).collect(), np.eye(7))
        np.testing.assert_allclose(ds.eye(5, 9).collect(), np.eye(5, 9))
        np.testing.assert_allclose(ds.eye(9, 5).collect(), np.eye(9, 5))

    def test_random_array_deterministic(self):
        a = ds.random_array((20, 10), random_state=7).collect()
        b = ds.random_array((20, 10), random_state=7).collect()
        c = ds.random_array((20, 10), random_state=8).collect()
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert a.min() >= 0.0 and a.max() < 1.0


class TestElementwise:
    def test_binary_ops(self, rng):
        a, x = _mk(rng, (9, 11))
        b, y = _mk(rng, (9, 11))
        np.testing.assert_allclose((a + b).collect(), x + y, rtol=1e-6)
        np.testing.assert_allclose((a - b).collect(), x - y, rtol=1e-6)
        np.testing.assert_allclose((a * b).collect(), x * y, rtol=1e-6)
        np.testing.assert_allclose((a / (b + 1.0)).collect(), x / (y + 1), rtol=1e-5)

    def test_scalar_ops(self, rng):
        a, x = _mk(rng, (6, 5))
        np.testing.assert_allclose((a + 3).collect(), x + 3, rtol=1e-6)
        np.testing.assert_allclose((3 + a).collect(), x + 3, rtol=1e-6)
        np.testing.assert_allclose((a - 1.5).collect(), x - 1.5, rtol=1e-6)
        np.testing.assert_allclose((2.0 - a).collect(), 2 - x, rtol=1e-6)
        np.testing.assert_allclose((a * 2).collect(), x * 2, rtol=1e-6)
        np.testing.assert_allclose((a / 2).collect(), x / 2, rtol=1e-6)
        np.testing.assert_allclose((2.0 / (a + 1)).collect(), 2 / (x + 1), rtol=1e-5)
        np.testing.assert_allclose((a ** 2).collect(), x ** 2, rtol=1e-5)
        np.testing.assert_allclose((-a).collect(), -x, rtol=1e-6)
        np.testing.assert_allclose(abs(a - 0.5).collect(), abs(x - 0.5), rtol=1e-5)

    def test_broadcast_row(self, rng):
        a, x = _mk(rng, (12, 5))
        m = a.mean(axis=0)
        np.testing.assert_allclose((a - m).collect(), x - x.mean(0, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_shape_mismatch_raises(self, rng):
        a, _ = _mk(rng, (4, 5))
        b, _ = _mk(rng, (5, 4))
        with pytest.raises(ValueError):
            a + b


class TestReductions:
    @pytest.mark.parametrize("axis", [0, 1, None])
    @pytest.mark.parametrize("kind", ["sum", "mean", "min", "max"])
    def test_reductions(self, rng, axis, kind):
        a, x = _mk(rng, (23, 17), (5, 5))
        got = getattr(a, kind)(axis=axis).collect()
        want = getattr(x, kind)(axis=axis, keepdims=True)
        if axis is None:
            want = want.reshape(1, 1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_norm(self, rng):
        a, x = _mk(rng, (14, 9))
        np.testing.assert_allclose(a.norm(axis=0).collect().ravel(),
                                   np.linalg.norm(x, axis=0), rtol=1e-5)
        np.testing.assert_allclose(a.norm(axis=1).collect().ravel(),
                                   np.linalg.norm(x, axis=1), rtol=1e-5)


class TestIndexing:
    def test_int_row(self, rng):
        a, x = _mk(rng, (10, 6))
        np.testing.assert_allclose(a[3].collect(), x[3:4])
        np.testing.assert_allclose(a[-1].collect(), x[-1:])

    def test_single_element(self, rng):
        a, x = _mk(rng, (10, 6))
        assert a[2, 4].shape == (1, 1)
        np.testing.assert_allclose(a[2, 4].collect()[0, 0], x[2, 4])

    def test_slices(self, rng):
        a, x = _mk(rng, (20, 15))
        np.testing.assert_allclose(a[2:9, :].collect(), x[2:9])
        np.testing.assert_allclose(a[:, 3:11].collect(), x[:, 3:11])
        np.testing.assert_allclose(a[5:, 10:].collect(), x[5:, 10:])
        np.testing.assert_allclose(a[::2, ::3].collect(), x[::2, ::3])
        np.testing.assert_allclose(a[18:200, :].collect(), x[18:200])

    def test_fancy(self, rng):
        a, x = _mk(rng, (20, 15))
        np.testing.assert_allclose(a[[1, 5, 5, 19], :].collect(), x[[1, 5, 5, 19]])
        np.testing.assert_allclose(a[:, [0, 14, 7]].collect(), x[:, [0, 14, 7]])
        mask = np.zeros(20, bool); mask[[2, 4]] = True
        np.testing.assert_allclose(a[mask, :].collect(), x[mask])

    def test_out_of_bounds(self, rng):
        a, _ = _mk(rng, (5, 5))
        with pytest.raises(IndexError):
            a[7]
        with pytest.raises(IndexError):
            a[:, [9]]


class TestLayoutOps:
    def test_transpose(self, rng):
        a, x = _mk(rng, (13, 7))
        np.testing.assert_allclose(a.T.collect(), x.T)
        np.testing.assert_allclose(a.transpose().collect(), x.T)
        assert a.T.shape == (7, 13)

    def test_rechunk_metadata_only(self, rng):
        a, x = _mk(rng, (16, 16), (4, 4))
        b = a.rechunk((8, 2))
        assert b.block_size == (8, 2)
        np.testing.assert_allclose(b.collect(), x)

    def test_astype_copy(self, rng):
        a, x = _mk(rng, (6, 6))
        assert a.astype(np.float32).dtype == np.float32
        np.testing.assert_allclose(a.copy().collect(), x)

    def test_iterator(self, rng):
        a, x = _mk(rng, (11, 8), (4, 3))
        rows = list(a.iterator(axis=0))
        assert len(rows) == 3
        np.testing.assert_allclose(np.vstack([r.collect() for r in rows]), x)
        cols = list(a.iterator(axis=1))
        assert len(cols) == 3
        np.testing.assert_allclose(np.hstack([c.collect() for c in cols]), x)

    def test_concat(self, rng):
        a, x = _mk(rng, (5, 4))
        b, y = _mk(rng, (3, 4))
        np.testing.assert_allclose(ds.concat_rows([a, b]).collect(), np.vstack([x, y]))
        c, z = _mk(rng, (5, 6))
        np.testing.assert_allclose(ds.concat_cols([a, c]).collect(), np.hstack([x, z]))


class TestApplyAlongAxis:
    def test_jax_traceable(self, rng):
        import jax.numpy as jnp
        a, x = _mk(rng, (9, 6))
        got = ds.apply_along_axis(jnp.sum, 0, a).collect()
        np.testing.assert_allclose(got, x.sum(0, keepdims=True), rtol=1e-5)
        got = ds.apply_along_axis(jnp.mean, 1, a).collect()
        np.testing.assert_allclose(got, x.mean(1, keepdims=True), rtol=1e-5)

    def test_host_fallback_warns(self, rng):
        import pytest
        a, x = _mk(rng, (6, 4))

        def untraceable(row):
            return float(np.asarray(row).sum())  # forces concrete values

        with pytest.warns(UserWarning, match="not JAX-traceable"):
            got = ds.apply_along_axis(untraceable, 1, a).collect()
        np.testing.assert_allclose(got.ravel(), x.sum(1), rtol=1e-5)


class TestMeshes:
    def test_2d_mesh(self, rng):
        ds.init((4, 2))
        a, x = _mk(rng, (19, 23), (5, 5))
        np.testing.assert_allclose(a.collect(), x)
        b = ds.matmul(a, a, transpose_b=True)
        np.testing.assert_allclose(b.collect(), x @ x.T, rtol=1e-4)

    def test_1x1_mesh(self, rng):
        ds.init((1, 1))
        a, x = _mk(rng, (9, 4))
        np.testing.assert_allclose((a + a).collect(), 2 * x, rtol=1e-6)


class TestDeviceInput:
    def test_array_accepts_jax_array_without_host_roundtrip(self, rng,
                                                            monkeypatch):
        import importlib
        import jax
        import jax.numpy as jnp
        arr_mod = importlib.import_module("dislib_tpu.data.array")
        x_np = rng.rand(20, 5).astype(np.float32)
        xd = jnp.asarray(x_np)
        # the host round-trip this guards against was `np.asarray(x)` on
        # the device input (transfer_guard cannot catch it — __array__
        # counts as an explicit transfer), so spy on the module's np
        calls = {"n": 0}
        real_asarray = np.asarray

        def spy(obj, *a, **k):
            if isinstance(obj, jax.Array):
                calls["n"] += 1
            return real_asarray(obj, *a, **k)

        monkeypatch.setattr(arr_mod.np, "asarray", spy)
        a = ds.array(xd, block_size=(5, 5))
        monkeypatch.setattr(arr_mod.np, "asarray", real_asarray)
        assert calls["n"] == 0, "device input took a host round-trip"
        np.testing.assert_allclose(a.collect(), x_np, rtol=1e-6)
        assert a.dtype == np.float32

    def test_device_f64_input_warns_and_narrows(self, rng):
        import jax
        import jax.numpy as jnp
        with jax.enable_x64(True):
            xd = jnp.asarray(rng.rand(6, 3))          # float64 device array
            assert xd.dtype == np.float64
            with pytest.warns(UserWarning, match="narrowing"):
                a = ds.array(xd)
        assert a.dtype == np.float32
