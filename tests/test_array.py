"""ds-array tests — mirrors the reference's `tests/test_array.py` strategy
(SURVEY.md §5): small arrays, deliberately irregular block sizes, dense and
(later) sparse variants, NumPy as the oracle, determinism via random_state."""

import numpy as np
import pytest

import dislib_tpu as ds


def _mk(rng, shape, bs=None):
    x = rng.rand(*shape)
    return ds.array(x, block_size=bs), x.astype(np.float32)


class TestConstruction:
    def test_from_numpy(self, rng):
        a, x = _mk(rng, (25, 13), (4, 5))
        assert a.shape == (25, 13)
        assert a.block_size == (4, 5)
        np.testing.assert_allclose(a.collect(), x)

    def test_from_list(self):
        a = ds.array([[1.0, 2.0], [3.0, 4.0]])
        np.testing.assert_allclose(a.collect(), [[1, 2], [3, 4]])

    def test_1d_promotes_to_row(self):
        a = ds.array(np.arange(5.0))
        assert a.shape == (1, 5)

    def test_3d_rejected(self):
        with pytest.raises(ValueError):
            ds.array(np.zeros((2, 2, 2)))

    def test_irregular_blocks(self, rng):
        # shapes that don't divide the mesh or block size evenly
        for shape in [(1, 1), (7, 3), (17, 19), (8, 64), (100, 1)]:
            a, x = _mk(rng, shape, (3, 2))
            np.testing.assert_allclose(a.collect(), x)

    def test_zeros_full_identity_eye(self):
        np.testing.assert_allclose(ds.zeros((5, 3)).collect(), np.zeros((5, 3)))
        np.testing.assert_allclose(ds.full((4, 6), 2.5).collect(), np.full((4, 6), 2.5))
        np.testing.assert_allclose(ds.identity(7).collect(), np.eye(7))
        np.testing.assert_allclose(ds.eye(5, 9).collect(), np.eye(5, 9))
        np.testing.assert_allclose(ds.eye(9, 5).collect(), np.eye(9, 5))

    def test_random_array_deterministic(self):
        a = ds.random_array((20, 10), random_state=7).collect()
        b = ds.random_array((20, 10), random_state=7).collect()
        c = ds.random_array((20, 10), random_state=8).collect()
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert a.min() >= 0.0 and a.max() < 1.0


class TestElementwise:
    def test_binary_ops(self, rng):
        a, x = _mk(rng, (9, 11))
        b, y = _mk(rng, (9, 11))
        np.testing.assert_allclose((a + b).collect(), x + y, rtol=1e-6)
        np.testing.assert_allclose((a - b).collect(), x - y, rtol=1e-6)
        np.testing.assert_allclose((a * b).collect(), x * y, rtol=1e-6)
        np.testing.assert_allclose((a / (b + 1.0)).collect(), x / (y + 1), rtol=1e-5)

    def test_scalar_ops(self, rng):
        a, x = _mk(rng, (6, 5))
        np.testing.assert_allclose((a + 3).collect(), x + 3, rtol=1e-6)
        np.testing.assert_allclose((3 + a).collect(), x + 3, rtol=1e-6)
        np.testing.assert_allclose((a - 1.5).collect(), x - 1.5, rtol=1e-6)
        np.testing.assert_allclose((2.0 - a).collect(), 2 - x, rtol=1e-6)
        np.testing.assert_allclose((a * 2).collect(), x * 2, rtol=1e-6)
        np.testing.assert_allclose((a / 2).collect(), x / 2, rtol=1e-6)
        np.testing.assert_allclose((2.0 / (a + 1)).collect(), 2 / (x + 1), rtol=1e-5)
        np.testing.assert_allclose((a ** 2).collect(), x ** 2, rtol=1e-5)
        np.testing.assert_allclose((-a).collect(), -x, rtol=1e-6)
        np.testing.assert_allclose(abs(a - 0.5).collect(), abs(x - 0.5), rtol=1e-5)

    def test_broadcast_row(self, rng):
        a, x = _mk(rng, (12, 5))
        m = a.mean(axis=0)
        np.testing.assert_allclose((a - m).collect(), x - x.mean(0, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_shape_mismatch_raises(self, rng):
        a, _ = _mk(rng, (4, 5))
        b, _ = _mk(rng, (5, 4))
        with pytest.raises(ValueError):
            a + b


class TestReductions:
    @pytest.mark.parametrize("axis", [0, 1, None])
    @pytest.mark.parametrize("kind", ["sum", "mean", "min", "max"])
    def test_reductions(self, rng, axis, kind):
        a, x = _mk(rng, (23, 17), (5, 5))
        got = getattr(a, kind)(axis=axis).collect()
        want = getattr(x, kind)(axis=axis, keepdims=True)
        if axis is None:
            want = want.reshape(1, 1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_norm(self, rng):
        a, x = _mk(rng, (14, 9))
        np.testing.assert_allclose(a.norm(axis=0).collect().ravel(),
                                   np.linalg.norm(x, axis=0), rtol=1e-5)
        np.testing.assert_allclose(a.norm(axis=1).collect().ravel(),
                                   np.linalg.norm(x, axis=1), rtol=1e-5)


class TestIndexing:
    def test_int_row(self, rng):
        a, x = _mk(rng, (10, 6))
        np.testing.assert_allclose(a[3].collect(), x[3:4])
        np.testing.assert_allclose(a[-1].collect(), x[-1:])

    def test_single_element(self, rng):
        a, x = _mk(rng, (10, 6))
        assert a[2, 4].shape == (1, 1)
        np.testing.assert_allclose(a[2, 4].collect()[0, 0], x[2, 4])

    def test_slices(self, rng):
        a, x = _mk(rng, (20, 15))
        np.testing.assert_allclose(a[2:9, :].collect(), x[2:9])
        np.testing.assert_allclose(a[:, 3:11].collect(), x[:, 3:11])
        np.testing.assert_allclose(a[5:, 10:].collect(), x[5:, 10:])
        np.testing.assert_allclose(a[::2, ::3].collect(), x[::2, ::3])
        np.testing.assert_allclose(a[18:200, :].collect(), x[18:200])

    def test_fancy(self, rng):
        a, x = _mk(rng, (20, 15))
        np.testing.assert_allclose(a[[1, 5, 5, 19], :].collect(), x[[1, 5, 5, 19]])
        np.testing.assert_allclose(a[:, [0, 14, 7]].collect(), x[:, [0, 14, 7]])
        mask = np.zeros(20, bool); mask[[2, 4]] = True
        np.testing.assert_allclose(a[mask, :].collect(), x[mask])

    def test_out_of_bounds(self, rng):
        a, _ = _mk(rng, (5, 5))
        with pytest.raises(IndexError):
            a[7]
        with pytest.raises(IndexError):
            a[:, [9]]


class TestLayoutOps:
    def test_transpose(self, rng):
        a, x = _mk(rng, (13, 7))
        np.testing.assert_allclose(a.T.collect(), x.T)
        np.testing.assert_allclose(a.transpose().collect(), x.T)
        assert a.T.shape == (7, 13)

    def test_rechunk_metadata_only(self, rng):
        a, x = _mk(rng, (16, 16), (4, 4))
        b = a.rechunk((8, 2))
        assert b.block_size == (8, 2)
        np.testing.assert_allclose(b.collect(), x)

    def test_iterator_after_rechunk(self, rng):
        """Pins the documented rechunk contract (migration.md): rechunk is
        metadata-only, but the ITERATOR honours the new stripe size both
        row- and col-wise — the observable behavior the reference's
        data-movement rechunk produced, without the movement."""
        a, x = _mk(rng, (16, 12), (4, 12))
        b = a.rechunk((8, 3))
        rows = list(b.iterator(axis=0))
        assert [blk.shape for blk in rows] == [(8, 12), (8, 12)]
        np.testing.assert_allclose(
            np.vstack([blk.collect() for blk in rows]), x)
        cols = list(b.iterator(axis=1))
        assert [blk.shape for blk in cols] == [(16, 3)] * 4
        np.testing.assert_allclose(
            np.hstack([blk.collect() for blk in cols]), x)
        # uneven trailing stripe after rechunk
        c = a.rechunk((5, 12))
        assert [blk.shape[0] for blk in c.iterator(axis=0)] == [5, 5, 5, 1]

    def test_astype_copy(self, rng):
        a, x = _mk(rng, (6, 6))
        assert a.astype(np.float32).dtype == np.float32
        np.testing.assert_allclose(a.copy().collect(), x)

    def test_iterator(self, rng):
        a, x = _mk(rng, (11, 8), (4, 3))
        rows = list(a.iterator(axis=0))
        assert len(rows) == 3
        np.testing.assert_allclose(np.vstack([r.collect() for r in rows]), x)
        cols = list(a.iterator(axis=1))
        assert len(cols) == 3
        np.testing.assert_allclose(np.hstack([c.collect() for c in cols]), x)

    def test_concat(self, rng):
        a, x = _mk(rng, (5, 4))
        b, y = _mk(rng, (3, 4))
        np.testing.assert_allclose(ds.concat_rows([a, b]).collect(), np.vstack([x, y]))
        c, z = _mk(rng, (5, 6))
        np.testing.assert_allclose(ds.concat_cols([a, c]).collect(), np.hstack([x, z]))


class TestApplyAlongAxis:
    def test_jax_traceable(self, rng):
        import jax.numpy as jnp
        a, x = _mk(rng, (9, 6))
        got = ds.apply_along_axis(jnp.sum, 0, a).collect()
        np.testing.assert_allclose(got, x.sum(0, keepdims=True), rtol=1e-5)
        got = ds.apply_along_axis(jnp.mean, 1, a).collect()
        np.testing.assert_allclose(got, x.mean(1, keepdims=True), rtol=1e-5)

    def test_host_fallback_warns(self, rng):
        import pytest
        a, x = _mk(rng, (6, 4))

        def untraceable(row):
            return float(np.asarray(row).sum())  # forces concrete values

        with pytest.warns(UserWarning, match="not JAX-traceable"):
            got = ds.apply_along_axis(untraceable, 1, a).collect()
        np.testing.assert_allclose(got.ravel(), x.sum(1), rtol=1e-5)

    def test_traceable_map_is_one_fused_dispatch(self, rng):
        """Round-11 satellite: a traceable func is a fusion-graph node —
        the whole map (and any chain feeding it) is ONE dispatch and
        ZERO host transfers, pinned by the counters."""
        import jax.numpy as jnp
        from dislib_tpu.utils import profiling as prof
        a, x = _mk(rng, (12, 7))
        a.force()
        prof.reset_counters()
        got = ds.apply_along_axis(jnp.sort, 0, a * 2.0)
        got.force()
        assert prof.dispatch_count() == 1, prof.counters()
        assert prof.transfer_count() == 0
        np.testing.assert_allclose(got.collect(), np.sort(x * 2.0, axis=0),
                                   rtol=1e-5)

    def test_extra_args_thread_through(self, rng):
        import jax.numpy as jnp
        a, x = _mk(rng, (8, 5))
        got = ds.apply_along_axis(jnp.quantile, 0, a, 0.5).collect()
        np.testing.assert_allclose(got.ravel(), np.quantile(x, 0.5, axis=0),
                                   rtol=1e-5)


class TestMeshes:
    def test_2d_mesh(self, rng):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init((4, 2))
        a, x = _mk(rng, (19, 23), (5, 5))
        np.testing.assert_allclose(a.collect(), x)
        b = ds.matmul(a, a, transpose_b=True)
        np.testing.assert_allclose(b.collect(), x @ x.T, rtol=1e-4)

    def test_1x1_mesh(self, rng):
        ds.init((1, 1))
        a, x = _mk(rng, (9, 4))
        np.testing.assert_allclose((a + a).collect(), 2 * x, rtol=1e-6)


class TestDeviceInput:
    def test_array_accepts_jax_array_without_host_roundtrip(self, rng,
                                                            monkeypatch):
        import importlib
        import jax
        import jax.numpy as jnp
        arr_mod = importlib.import_module("dislib_tpu.data.array")
        x_np = rng.rand(20, 5).astype(np.float32)
        xd = jnp.asarray(x_np)
        # the host round-trip this guards against was `np.asarray(x)` on
        # the device input (transfer_guard cannot catch it — __array__
        # counts as an explicit transfer), so spy on the module's np
        calls = {"n": 0}
        real_asarray = np.asarray

        def spy(obj, *a, **k):
            if isinstance(obj, jax.Array):
                calls["n"] += 1
            return real_asarray(obj, *a, **k)

        monkeypatch.setattr(arr_mod.np, "asarray", spy)
        a = ds.array(xd, block_size=(5, 5))
        monkeypatch.setattr(arr_mod.np, "asarray", real_asarray)
        assert calls["n"] == 0, "device input took a host round-trip"
        np.testing.assert_allclose(a.collect(), x_np, rtol=1e-6)
        assert a.dtype == np.float32

    def test_device_f64_input_warns_and_narrows(self, rng):
        import jax
        import jax.numpy as jnp
        with jax.enable_x64(True):
            xd = jnp.asarray(rng.rand(6, 3))          # float64 device array
            assert xd.dtype == np.float64
            with pytest.warns(UserWarning, match="narrowing"):
                a = ds.array(xd)
        assert a.dtype == np.float32


# ---------------------------------------------------------------------------
# round-4 systematic matrix (verdict #6): {dense, sparse} ×
# {regular, irregular, 1×n, n×1, block>shape} ×
# {int / bool / fancy / slice / negative-step} ×
# {mixed-dtype elementwise, broadcast corners} — results oracle'd against
# NumPy/SciPy, error contracts pinned crisply.
# ---------------------------------------------------------------------------

SHAPE_TIERS = [
    ("regular", (12, 8), (3, 4)),
    ("irregular", (17, 19), (5, 7)),
    ("one_by_n", (1, 16), (1, 5)),
    ("n_by_one", (16, 1), (5, 1)),
    ("block_gt_shape", (6, 4), (10, 10)),
]


def _index_cases(m, n):
    bm_r = np.zeros(m, bool)
    bm_r[:: max(1, m // 3)] = True
    bm_c = np.zeros(n, bool)
    bm_c[:: max(1, n // 2)] = True
    return [
        ("int_row", (min(m - 1, 2), slice(None))),
        ("int_neg_row", (-1, slice(None))),
        ("int_both", (0, n - 1)),
        ("slice_rows", (slice(1, max(2, m - 1)), slice(None))),
        ("slice_cols", (slice(None), slice(0, max(1, n - 1)))),
        ("slice_step", (slice(0, m, 2), slice(0, n, 3))),
        ("slice_open", (slice(m // 2, None), slice(None, None))),
        ("slice_past_end", (slice(0, m + 100), slice(None))),
        ("slice_empty", (slice(m, m), slice(None))),
        ("fancy_rows", ([0, m - 1, m // 2, 0], slice(None))),
        ("fancy_neg", ([-1, 0], slice(None))),
        ("fancy_cols", (slice(None), [n - 1, 0])),
        ("fancy_both_outer", ([0, m - 1], [0, n - 1])),
        ("bool_rows", (bm_r, slice(None))),
        ("bool_cols", (slice(None), bm_c)),
        ("bool_both", (bm_r, bm_c)),
    ]


def _oracle(x, rows, cols):
    """NumPy oracle under the ds-array contract: each axis is selected
    INDEPENDENTLY (fancy×fancy = outer/cross product, np.ix_ semantics,
    matching the reference's block-wise selection), and integer indices
    keep the axis (2-D in, 2-D out)."""
    def norm(idx, dim):
        if isinstance(idx, (int, np.integer)):
            i = int(idx) + (dim if idx < 0 else 0)
            return [i]
        if isinstance(idx, slice):
            return list(range(*idx.indices(dim)))
        arr = np.asarray(idx)
        if arr.dtype == bool:
            return list(np.nonzero(arr)[0])
        return [int(v) + (dim if v < 0 else 0) for v in arr]
    r = norm(rows, x.shape[0])
    c = norm(cols, x.shape[1])
    return x[np.ix_(r, c)] if r and c else \
        np.zeros((len(r), len(c)), x.dtype)


class TestIndexingMatrixDense:
    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    def test_all_indexers(self, rng, tier, shape, bs):
        a, x = _mk(rng, shape, bs)
        for name, (rows, cols) in _index_cases(*shape):
            got = a[rows, cols]
            want = _oracle(x, rows, cols)
            assert got.shape == want.shape, \
                f"{tier}/{name}: shape {got.shape} != {want.shape}"
            if want.size:
                np.testing.assert_allclose(got.collect(), want, rtol=1e-6,
                                           err_msg=f"{tier}/{name}")


class TestIndexingMatrixSparse:
    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    def test_all_indexers(self, rng, tier, shape, bs):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        x = (rng.rand(*shape) * (rng.rand(*shape) > 0.4)).astype(np.float32)
        if not x.any():
            x[0, 0] = 1.0                 # keep at least one nonzero
        a = SparseArray.from_scipy(sp.csr_matrix(x), block_size=bs)
        for name, (rows, cols) in _index_cases(*shape):
            got = a[rows, cols]
            want = _oracle(x, rows, cols)
            assert isinstance(got, SparseArray), \
                f"{tier}/{name}: indexing densified"
            assert got.shape == want.shape, \
                f"{tier}/{name}: shape {got.shape} != {want.shape}"
            if want.size:
                np.testing.assert_allclose(got.collect().toarray(), want,
                                           rtol=1e-6,
                                           err_msg=f"{tier}/{name}")


class TestIndexingErrorContracts:
    def _both(self, rng, shape=(10, 6)):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        x = rng.rand(*shape).astype(np.float32)
        return [ds.array(x), SparseArray.from_scipy(sp.csr_matrix(x))]

    def test_negative_step_raises(self, rng):
        for a in self._both(rng):
            with pytest.raises(IndexError, match="negative slice step"):
                a[::-1, :]
            with pytest.raises(IndexError, match="negative slice step"):
                a[:, 5:1:-1]

    def test_three_axes_raises(self, rng):
        for a in self._both(rng):
            with pytest.raises(IndexError, match="2-D"):
                a[1, 2, 3]

    def test_out_of_bounds_int_and_fancy(self, rng):
        for a in self._both(rng):
            with pytest.raises(IndexError):
                a[10, :]
            with pytest.raises(IndexError):
                a[-11, :]
            with pytest.raises(IndexError):
                a[[0, 10], :]
            with pytest.raises(IndexError):
                a[:, [-7]]

    def test_bool_length_mismatch(self, rng):
        for a in self._both(rng):
            with pytest.raises(IndexError, match="boolean"):
                a[np.ones(3, bool), :]

    def test_float_fancy_raises(self, rng):
        for a in self._both(rng):
            with pytest.raises(IndexError, match="integer or boolean"):
                a[[0.5, 1.2], :]


class TestMixedDtypeElementwise:
    def test_int_construction_narrows_to_i32(self):
        assert ds.array(np.arange(6, dtype=np.int64).reshape(2, 3)).dtype \
            == np.int32
        assert ds.array(np.arange(6, dtype=np.int32).reshape(2, 3)).dtype \
            == np.int32

    def test_int_plus_float_promotes_f32_exact(self, rng):
        xi = np.arange(12, dtype=np.int32).reshape(3, 4)
        xf = rng.rand(3, 4).astype(np.float32)
        out = ds.array(xi) + ds.array(xf)
        assert out.dtype == np.float32
        np.testing.assert_allclose(out.collect(), xi + xf, rtol=1e-6)

    def test_bf16_f32_promotes_f32(self, rng):
        import jax.numpy as jnp
        a, x = _mk(rng, (6, 5))
        b16 = a.astype(jnp.bfloat16)
        out = b16 + a
        assert out.dtype == np.float32
        np.testing.assert_allclose(out.collect(), x.astype(jnp.bfloat16)
                                   .astype(np.float32) + x, rtol=1e-6)

    def test_int_arithmetic_stays_exact(self):
        xi = np.arange(1, 13, dtype=np.int32).reshape(3, 4)
        a = ds.array(xi)
        got = (a * 3 - a).collect()
        np.testing.assert_array_equal(got, xi * 3 - xi)


class TestBroadcastCorners:
    def test_row_col_scalar_broadcasts(self, rng):
        m, x = _mk(rng, (7, 5))
        r, xr = _mk(rng, (1, 5))
        c, xc = _mk(rng, (7, 1))
        s, xs = _mk(rng, (1, 1))
        np.testing.assert_allclose((m + r).collect(), x + xr, rtol=1e-6)
        np.testing.assert_allclose((m - c).collect(), x - xc, rtol=1e-6)
        np.testing.assert_allclose((m * s).collect(), x * xs, rtol=1e-6)
        np.testing.assert_allclose((r + c).collect(), xr + xc, rtol=1e-6)
        np.testing.assert_allclose((c / r).collect(), xc / xr, rtol=1e-5)

    def test_broadcast_on_irregular_blocks(self, rng):
        m, x = _mk(rng, (17, 9), (5, 4))
        r, xr = _mk(rng, (1, 9), (1, 4))
        np.testing.assert_allclose((m * r).collect(), x * xr, rtol=1e-6)

    def test_incompatible_broadcast_raises(self, rng):
        a, _ = _mk(rng, (3, 4))
        for other_shape in [(1, 5), (2, 1), (4, 4), (2, 4)]:
            b, _ = _mk(rng, other_shape)
            with pytest.raises(ValueError):
                a + b


class TestOpsAcrossShapeTiers:
    """Elementwise / reduction / layout ops over the same shape tiers as
    the indexing matrix — degenerate shapes (1×n, n×1, block>shape) stress
    the pad-and-mask invariant in every op's mask arithmetic."""

    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    def test_elementwise_chain(self, rng, tier, shape, bs):
        a, x = _mk(rng, shape, bs)
        b, y = _mk(rng, shape, bs)
        got = ((a + b) * 2.0 - a / (b + 1.0)).collect()
        np.testing.assert_allclose(got, (x + y) * 2.0 - x / (y + 1.0),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    @pytest.mark.parametrize("kind", ["sum", "mean", "min", "max"])
    def test_reductions_all_axes(self, rng, tier, shape, bs, kind):
        a, x = _mk(rng, shape, bs)
        for axis in (0, 1, None):
            got = getattr(a, kind)(axis=axis).collect()
            want = getattr(x, kind)(axis=axis, keepdims=True)
            if axis is None:
                want = np.asarray(want).reshape(1, 1)
            np.testing.assert_allclose(
                got, want, rtol=1e-5, atol=1e-6,
                err_msg=f"{tier}/{kind}/axis={axis}")

    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    def test_transpose_roundtrip(self, rng, tier, shape, bs):
        a, x = _mk(rng, shape, bs)
        np.testing.assert_allclose(a.T.collect(), x.T)
        np.testing.assert_allclose(a.T.T.collect(), x)
        assert a.T.shape == (shape[1], shape[0])

    @pytest.mark.parametrize("tier,shape,bs", SHAPE_TIERS,
                             ids=[t[0] for t in SHAPE_TIERS])
    def test_iterator_both_axes(self, rng, tier, shape, bs):
        a, x = _mk(rng, shape, bs)
        rows = [r.collect() for r in a.iterator(axis=0)]
        np.testing.assert_allclose(np.vstack(rows), x)
        cols = [c.collect() for c in a.iterator(axis=1)]
        np.testing.assert_allclose(np.hstack(cols), x)

    def test_norm_degenerate_shapes(self, rng):
        for shape in [(1, 1), (1, 9), (9, 1)]:
            a, x = _mk(rng, shape)
            np.testing.assert_allclose(a.norm(axis=0).collect().ravel(),
                                       np.linalg.norm(x, axis=0), rtol=1e-5)
            np.testing.assert_allclose(a.norm(axis=1).collect().ravel(),
                                       np.linalg.norm(x, axis=1), rtol=1e-5)

    def test_concat_error_contracts(self, rng):
        a, _ = _mk(rng, (4, 5))
        b, _ = _mk(rng, (4, 6))
        with pytest.raises(ValueError):
            ds.concat_rows([a, b])       # column mismatch
        c, _ = _mk(rng, (3, 5))
        with pytest.raises(ValueError):
            ds.concat_cols([a, c])       # row mismatch

    def test_rechunk_preserves_values_all_tiers(self, rng):
        for tier, shape, bs in SHAPE_TIERS:
            a, x = _mk(rng, shape, bs)
            b = a.rechunk((2, 2))
            np.testing.assert_allclose(b.collect(), x,
                                       err_msg=f"{tier}")


class TestEmptySelection:
    def test_empty_list_index_valid(self, rng):
        """NumPy accepts x[[]] — a computed-empty selection must not trip
        the float-dtype fancy-index guard (round-4 review)."""
        a, x = _mk(rng, (8, 5))
        got = a[[], :]
        assert got.shape == (0, 5)
        got2 = a[:, []]
        assert got2.shape == (8, 0)
