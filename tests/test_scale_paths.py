"""Scale-path tests: the streamed/chunked implementations the quadratic
estimators switch to past single-chip memory limits (VERDICT round-1 #4)
must be oracle-equal to the dense paths, and must provably not allocate the
m×m buffer."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import DBSCAN, Daura
from dislib_tpu.cluster import dbscan as dbscan_mod
from dislib_tpu.cluster import daura as daura_mod
from dislib_tpu.neighbors import NearestNeighbors
from dislib_tpu.neighbors import base as nb
from dislib_tpu.ops import tiled as tiled_mod


class TestChunkedKNeighbors:
    def test_chunked_matches_direct(self, rng):
        x = rng.rand(150, 5).astype(np.float32)
        q = rng.rand(40, 5).astype(np.float32)
        xa, qa = ds.array(x, block_size=(32, 5)), ds.array(q, block_size=(16, 5))
        nn = NearestNeighbors(n_neighbors=4).fit(xa)
        d_ref, i_ref = (a.collect() for a in nn.kneighbors(qa))
        d_ch, i_ch = nb._kneighbors(qa._data, xa._data, qa.shape, xa.shape,
                                    4, chunk=16)
        np.testing.assert_allclose(np.asarray(d_ch)[:40], d_ref, rtol=1e-5,
                                   atol=1e-5)
        assert np.array_equal(np.asarray(i_ch)[:40], i_ref.astype(np.int32))

    def test_chunked_tie_break_matches(self, rng):
        # duplicated fitted rows: equal distances must keep the lowest index
        base = rng.rand(8, 3).astype(np.float32)
        x = np.vstack([base, base, base])
        q = base + 0.0
        xa, qa = ds.array(x), ds.array(q)
        d_dir, i_dir = nb._kneighbors(qa._data, xa._data, qa.shape, xa.shape,
                                      3, chunk=1024)
        d_ch, i_ch = nb._kneighbors(qa._data, xa._data, qa.shape, xa.shape,
                                    3, chunk=4)
        assert np.array_equal(np.asarray(i_dir)[:8], np.asarray(i_ch)[:8])

    def test_no_quadratic_buffer(self):
        """Memory-shape assertion: the chunked lowering's temporaries stay
        far below the mq x mf distance matrix the direct path allocates."""
        import jax.numpy as jnp
        mq, mf, d, k, chunk = 256, 8192, 8, 5, 512
        qp = jnp.zeros((mq, d), jnp.float32)
        fp = jnp.zeros((mf, d), jnp.float32)
        compiled = nb._kneighbors.lower(qp, fp, (mq, d), (mf, d), k,
                                        chunk=chunk).compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("backend reports no memory analysis")
        quadratic = mq * mf * 4
        assert mem.temp_size_in_bytes < quadratic, \
            f"temp {mem.temp_size_in_bytes} >= m^2 buffer {quadratic}"


def _blob_data(rng, n=120):
    t = rng.rand(n // 2) * 2 * np.pi
    c1 = np.c_[np.cos(t), np.sin(t)] + 0.05 * rng.randn(n // 2, 2)
    c2 = np.c_[np.cos(t) + 6.0, np.sin(t)] + 0.05 * rng.randn(n // 2, 2)
    noise = rng.rand(6, 2) * 2 + np.array([2.5, 4.0])
    return np.vstack([c1, c2, noise]).astype(np.float32)


class TestTiledDBSCAN:
    def test_tiled_matches_dense(self, rng, monkeypatch):
        x = _blob_data(rng)
        dense = DBSCAN(eps=0.4, min_samples=5).fit(ds.array(x))
        monkeypatch.setattr(dbscan_mod, "_DENSE_MAX", 0)
        monkeypatch.setattr(tiled_mod, "TILE", 32)
        tiled = DBSCAN(eps=0.4, min_samples=5).fit(ds.array(x))
        assert np.array_equal(dense.labels_, tiled.labels_)
        assert dense.n_clusters_ == tiled.n_clusters_
        assert np.array_equal(dense.core_sample_indices_,
                              tiled.core_sample_indices_)

    def test_tiled_chain(self, rng, monkeypatch):
        # 1-D chain spanning many tiles: worst case for propagation depth
        monkeypatch.setattr(dbscan_mod, "_DENSE_MAX", 0)
        monkeypatch.setattr(tiled_mod, "TILE", 16)
        x = np.c_[np.arange(100) * 0.5, np.zeros(100)].astype(np.float32)
        est = DBSCAN(eps=0.6, min_samples=2).fit(ds.array(x))
        assert est.n_clusters_ == 1
        assert np.all(est.labels_ == 0)


class TestTiledDaura:
    def test_tiled_matches_dense(self, rng, monkeypatch):
        n_atoms = 4
        x = (rng.randn(70, 3 * n_atoms) * 2).astype(np.float32)
        dense = Daura(cutoff=3.0).fit(ds.array(x))
        monkeypatch.setattr(daura_mod, "_DENSE_MAX", 0)
        monkeypatch.setattr(tiled_mod, "TILE", 16)
        tiled = Daura(cutoff=3.0).fit(ds.array(x))
        assert np.array_equal(dense.labels_, tiled.labels_)
        assert [c[0] for c in dense.clusters_] == [c[0] for c in tiled.clusters_]


class TestCSVMDegenerate:
    def test_empty_sv_fallback_warns(self, rng):
        from dislib_tpu.classification import CascadeSVM
        x = rng.randn(24, 3).astype(np.float32)
        y = (rng.rand(24) > 0.5).astype(np.float32)
        xa, ya = ds.array(x), ds.array(y[:, None])
        with pytest.warns(RuntimeWarning, match="no support vector"):
            est = CascadeSVM(c=1e-12, max_iter=1, kernel="linear").fit(xa, ya)
        assert est.support_vectors_count_ == 1
        # decision function is usable (finite), not identically broken
        dec = est.decision_function(xa).collect()
        assert np.isfinite(dec).all()
