"""Densify lint (round-14 sparse PR satellite; the host-sync / precision
lint pattern): estimator and serving code may not densify a sparse
operand — ``.to_dense()`` / ``.toarray()`` is O(rows·cols) memory and
FLOPs for O(nnz) information, exactly the escape hatch the sparse fast
path (sharded SpMM, sparse rechunk, fold-in serving) exists to retire.

A new ``.to_dense()`` in estimator/serving code is a test failure unless
the site is consciously allowlisted with a reason (each entry is a
HOST-side staging/triage boundary, never the ratings/feature matrix on
the fit or serve path).  The ``math.matmul`` ``algorithm="densify"``
route lives in ``dislib_tpu/math`` — deliberate, budget-guarded, and
outside this lint's scanned set by design (it is the one blessed
densify entry)."""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCANNED_DIRS = (
    "dislib_tpu/cluster",
    "dislib_tpu/classification",
    "dislib_tpu/recommendation",
    "dislib_tpu/trees",
    "dislib_tpu/regression",
    "dislib_tpu/decomposition",
    "dislib_tpu/neighbors",
    "dislib_tpu/optimization",
    "dislib_tpu/model_selection",
    "dislib_tpu/preprocessing",
    "dislib_tpu/serving",
    # round-18: the IVF retrieval tier — its sharded list buffers are
    # the ShardedSparse pad discipline; densifying them would be the
    # exact regression this lint guards
    "dislib_tpu/retrieval",
)

# (file, enclosing function) pairs allowed to densify, with reasons:
ALLOWLIST = {
    # dense-path ALS accepting a SPARSE held-out test matrix: the dense
    # fit kernel needs the padded test canvas anyway (dense-with-mask),
    # and the conversion is host-side ingest of the small TEST ratings —
    # the sparse FIT path never touches this branch
    ("dislib_tpu/recommendation/als.py", "fit"),
    # cascade SVM stages its support-vector ROWS as host CSR→dense at
    # adoption time (SURVEY §3.3 host-planned tier) — a per-node subset,
    # never the full feature matrix
    ("dislib_tpu/classification/csvm.py", "fit"),
    # cascade SVM's per-node sub-Gram: (sub @ subᵀ).todense() is the
    # small (cap, cap) KERNEL BLOCK the dual solve needs dense anyway —
    # the full matrix stays CSR (the function's docstring contract)
    ("dislib_tpu/classification/csvm.py", "k_of"),
}

_DENSIFY_ATTRS = ("to_dense", "toarray", "todense")


def _densify_calls(path):
    tree = ast.parse(open(path, encoding="utf-8").read())

    def walk(node, fname):
        for child in ast.iter_child_nodes(node):
            cname = fname
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cname = child.name
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr in _DENSIFY_ATTRS:
                yield fname, child.lineno, child.func.attr
            yield from walk(child, cname)

    yield from walk(tree, "<module>")


def _scanned_files():
    for d in SCANNED_DIRS:
        full = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full)):
            if fn.endswith(".py"):
                yield f"{d}/{fn}", os.path.join(full, fn)


def test_no_densification_in_estimator_or_serving_code():
    offenders = []
    for rel, full in _scanned_files():
        for fname, lineno, attr in _densify_calls(full):
            if (rel, fname) not in ALLOWLIST:
                offenders.append(f"{rel}:{lineno} in {fname}(): .{attr}()")
    assert not offenders, (
        "sparse operand densified in estimator/serving code — route "
        "through the sparse fast path (ops/spmm, sharded buffers, the "
        "matmul densify router), or consciously extend the lint "
        "ALLOWLIST with a reason:\n  " + "\n  ".join(offenders))


def test_allowlist_entries_still_exist():
    """A refactor that renames or removes an allowlisted site must prune
    the list — dead entries would quietly bless future regressions."""
    live = set()
    for rel, full in _scanned_files():
        for fname, _, _ in _densify_calls(full):
            live.add((rel, fname))
    dead = {site for site in ALLOWLIST if site not in live}
    assert not dead, f"densify allowlist entries match no code: {dead}"


def test_sparse_fit_and_serve_paths_scanned():
    """The sparse fast path's own homes stay in the scanned set."""
    scanned = {rel for rel, _ in _scanned_files()}
    for f in ("dislib_tpu/recommendation/als.py",
              "dislib_tpu/serving/sparse.py",
              "dislib_tpu/cluster/kmeans.py",
              # round-18 retrieval tier
              "dislib_tpu/retrieval/ivf.py",
              "dislib_tpu/retrieval/serving.py"):
        assert f in scanned, f"{f} escaped the densify lint"


def test_device_staging_never_densifies():
    """The round-17 device staging views (ELL, row steps, the
    col-partitioned panel view) exist precisely so sparse fit entry is
    O(nnz) on device — none of them may densify or detour through the
    host triplet path.  data/sparse.py sits outside SCANNED_DIRS (it
    legitimately DEFINES to_dense), so the staging methods are pinned
    here by name."""
    path = os.path.join(REPO, "dislib_tpu/data/sparse.py")
    staging = {"ell", "ell_buffers", "row_steps", "row_step_buffers",
               "row_step_plan", "panel_view", "panel_counts",
               "_cols_stream"}
    hits = [f"{fname}:{lineno} .{attr}()"
            for fname, lineno, attr in _densify_calls(path)
            if fname in staging]
    assert not hits, ("sparse staging densified an operand:\n  "
                      + "\n  ".join(hits))
