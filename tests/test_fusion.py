"""Dispatch-fusion layer (`data/array.py` round-7 perf PR): chains of
Array ops build a deferred expression and run as ONE cached XLA program at
the first force point.

- correctness: fused chains bit-match the `DSLIB_EAGER=1` per-op path
  (same op bodies, so exact equality — including mixed padded canvases,
  sparse-flagged passthrough, unaries, reductions, distances);
- the acceptance claim: a >= 3-op chain is exactly 1 dispatch, asserted
  with the new `utils.profiling` counters;
- retrace guard: fitting twice with same-shape data and re-running a 3x3
  grid search add ZERO kernel traces — cache-key regressions (lost
  static_argnames, fusion-program instability) fail here, on CPU, not as
  a silent 20 s recompile on chip;
- donation: the donated fit-loop carries (ALS factors, forest nodes) are
  actually invalidated, and donated kernels survive `jax_debug_nans`.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.utils import profiling as prof


def _x(rng, m=37, n=11):
    return rng.rand(m, n).astype(np.float32)


class TestFusionCorrectness:
    def test_chain_bitmatches_eager(self, rng, monkeypatch):
        x = _x(rng)
        fused = ds.matmul((ds.array(x, block_size=(16, 8)) * 2.0 + 1.0).T,
                          ds.array(x, block_size=(16, 8)))[1:5, :3]
        assert fused.is_lazy
        got = fused.collect()
        monkeypatch.setenv("DSLIB_EAGER", "1")
        eager = ds.matmul((ds.array(x, block_size=(16, 8)) * 2.0 + 1.0).T,
                          ds.array(x, block_size=(16, 8)))[1:5, :3]
        assert not eager.is_lazy
        np.testing.assert_array_equal(got, eager.collect())

    def test_mixed_padded_shapes_broadcast(self, rng, monkeypatch):
        x = _x(rng, 21, 13)
        r = rng.rand(1, 13).astype(np.float32)

        def build():
            a, v = ds.array(x), ds.array(r)
            return ((a - v) / (v + 1.0)).sum(axis=0)

        got = build().collect()
        monkeypatch.setenv("DSLIB_EAGER", "1")
        np.testing.assert_array_equal(got, build().collect())

    def test_unaries_and_reductions_match_eager(self, rng, monkeypatch):
        x = _x(rng, 19, 7) + 0.25

        def build(op):
            a = ds.array(x)
            chain = {
                "abs": lambda: abs(-a),
                "sqrt": lambda: (a * 2.0).sqrt(),
                "exp": lambda: (a - 1.0).exp(),
                "sum0": lambda: (a * 3.0).sum(axis=0),
                "sum1": lambda: (a * 3.0).sum(axis=1),
                "sumN": lambda: (a * 3.0).sum(axis=None),
                "mean": lambda: (a + 1.0).mean(axis=1),
                "min": lambda: (a - 0.5).min(axis=0),
                "max": lambda: abs(a).max(axis=None),
                "norm": lambda: (a * a).norm(axis=0),
                "neg_pow": lambda: (-a) ** 2.0,
            }[op]()
            return chain

        for op in ("abs", "sqrt", "exp", "sum0", "sum1", "sumN", "mean",
                   "min", "max", "norm", "neg_pow"):
            monkeypatch.delenv("DSLIB_EAGER", raising=False)
            fused = build(op)
            got = fused.collect()
            fused_dtype = fused.dtype
            monkeypatch.setenv("DSLIB_EAGER", "1")
            eager = build(op)
            np.testing.assert_array_equal(got, eager.collect(), err_msg=op)
            assert fused_dtype == eager.dtype, op

    def test_fma_contraction_is_the_only_divergence(self, rng, monkeypatch):
        """A mul feeding an add on the same element may contract to one
        FMA inside the fused program (XLA excess precision; no barrier
        primitive stops the backend's fp-contract) — the ONE permitted
        divergence from eager, strictly bounded by 1 ulp per contraction.
        Everything else in this file asserts EXACT equality."""
        x = _x(rng, 16, 16)

        def build():
            return ds.array(x) * 1.0001 + 0.0001

        got = build().collect()
        monkeypatch.setenv("DSLIB_EAGER", "1")
        ref = build().collect()
        ulp = np.spacing(np.abs(ref).astype(np.float32))
        assert np.all(np.abs(got - ref) <= ulp), \
            "fused chain diverged from eager by more than 1 ulp"

    def test_sparse_passthrough(self, rng, monkeypatch):
        import scipy.sparse as sp
        x = _x(rng, 23, 9)
        x[x < 0.7] = 0.0

        def build():
            a = ds.array(sp.csr_matrix(x))
            return (a * 3.0).T

        fused = build()
        assert fused.is_lazy and fused._sparse
        got = fused.collect()
        assert sp.issparse(got)
        monkeypatch.setenv("DSLIB_EAGER", "1")
        ref = build().collect()
        np.testing.assert_array_equal(got.toarray(), ref.toarray())

    def test_distances_sq_is_a_graph_node(self, rng, monkeypatch):
        from dislib_tpu.ops import distances_sq
        xa, xb = _x(rng, 17, 6), _x(rng, 9, 6)

        def build():
            a, b = ds.array(xa), ds.array(xb)
            return distances_sq(a * 1.5, b, precision="highest") + 1.0

        fused = build()
        assert fused.is_lazy
        got = fused.collect()
        monkeypatch.setenv("DSLIB_EAGER", "1")
        np.testing.assert_array_equal(got, build().collect())
        ref = ((xa * 1.5)[:, None, :] - xb[None]) ** 2
        np.testing.assert_allclose(got, ref.sum(-1) + 1.0, atol=1e-4)

    def test_shared_prefix_across_arrays_runs_once(self, rng):
        """A lazy prefix consumed by SEVERAL Arrays materialises once:
        the first force emits it as an extra program output and caches
        it, so later consumers load it as a leaf (review finding — the
        naive version re-ran and re-compiled the prefix per fan-out)."""
        x = _x(rng, 20, 8)
        a = ds.array(x).force()
        shared = ds.matmul((a * 2.0 + 1.0).T, a)   # expensive prefix
        c = shared + 1.0
        d = shared * 3.0
        prof.reset_counters()
        c_host = c.collect()                       # runs prefix + its op
        d_host = d.collect()                       # prefix now a cached leaf
        s_host = shared.collect()                  # free: cached root value
        assert prof.counters()["dispatch_by"] == {"fused_chain": 2}
        base = (x * 2.0 + 1.0).T @ x
        np.testing.assert_allclose(c_host, base + 1.0, rtol=1e-5)
        np.testing.assert_allclose(d_host, base * 3.0, rtol=1e-5)
        np.testing.assert_allclose(s_host, base, rtol=1e-5)

    def test_float_of_sparse_flagged_scalar(self, rng):
        """float() on a (1, 1) slice of a sparse-flagged array reads the
        dense backing (collect() would wrap it in a csr_matrix)."""
        import scipy.sparse as sp
        x = np.zeros((6, 6), np.float32)
        x[2, 3] = 4.5
        a = ds.array(sp.csr_matrix(x))
        cell = a[2:3, 3:4]
        assert cell._sparse
        assert float(cell) == 4.5

    def test_int_scalar_div_dtype_metadata(self):
        """Lazy dtype metadata must match the forced result: int / scalar
        true-divides to float (review finding — it reported int32)."""
        a = ds.array(np.arange(12, dtype=np.int32).reshape(3, 4))
        y = a / 2.0
        lazy_dtype = y.dtype
        got = y.collect()
        assert lazy_dtype == got.dtype == np.float32

    def test_exp_drops_the_sparse_flag(self):
        """exp(0)=1 densifies — the result must not stay sparse-flagged
        (review finding: the dummy 0.0 operand slipped exp through the
        zero-preserving clause and collect() wrapped dense data in csr)."""
        import scipy.sparse as sp
        a = ds.array(sp.csr_matrix(np.eye(3, dtype=np.float32)))
        e = a.exp()
        assert not e._sparse
        out = e.collect()
        assert not sp.issparse(out)
        np.testing.assert_allclose(out, np.exp(np.eye(3, dtype=np.float32)),
                                   rtol=1e-6)

    def test_materialised_prefix_releases_its_subtree(self, rng):
        """Once a shared prefix is value-cached, its graph edges drop so
        the leaf device buffers are not pinned for the lifetime of other
        lazy consumers (review finding: an HBM leak on big leaves)."""
        x = _x(rng, 16, 8)
        a = ds.array(x).force()
        shared = (a * 2.0).T
        c = shared + 1.0
        d = shared * 3.0                 # stays lazy
        c.collect()
        assert d._lazy.args[0].args == ()   # d's prefix edge is cached+cut
        np.testing.assert_allclose(d.collect(), (x * 2.0).T * 3.0,
                                   rtol=1e-6)

    def test_diamond_tower_is_not_force_spammed(self, rng):
        """n_ops overcounts shared subexpressions exponentially; the cap
        must use the exact deduped count so a y = y + y tower stays ONE
        fused dispatch (review finding: it forced every ~7 ops)."""
        x = _x(rng, 8, 4)
        y = ds.array(x).force()
        for _ in range(20):
            y = y + y
        assert y.is_lazy, "diamond tower was forced early by the cap"
        prof.reset_counters()
        got = y.collect()
        assert prof.counters()["dispatch_by"] == {"fused_chain": 1}
        np.testing.assert_allclose(got, x * 2.0 ** 20, rtol=1e-6)

    def test_diamond_graph_evaluates_shared_node_once(self, rng):
        x = _x(rng, 12, 5)
        a = ds.array(x)
        shared = a * 2.0
        out = (shared + shared.T.T) - shared   # shared appears 3x
        prof.reset_counters()
        got = out.collect()
        assert prof.counters()["dispatch_by"] == {"fused_chain": 1}
        np.testing.assert_allclose(got, x * 2.0, rtol=1e-6)

    def test_fusion_cap_bounds_program_size(self, rng, monkeypatch):
        monkeypatch.setenv("DSLIB_FUSION_CAP", "8")
        x = _x(rng, 8, 4)
        b = ds.array(x)
        for _ in range(20):
            b = b + 1.0
        # the chain must have forced itself at least once on the way
        assert b._lazy is None or b._lazy.n_ops < 8
        np.testing.assert_allclose(b.collect(), x + 20.0, rtol=1e-5)


class TestSingleDispatch:
    def test_three_op_chain_is_one_dispatch(self, rng):
        a = ds.array(_x(rng, 24, 10)).force()     # concrete leaf
        prof.reset_counters()
        chain = ds.matmul((a * 0.5).T, a).T       # scale → T → matmul → T
        assert chain.is_lazy
        assert prof.dispatch_count() == 0, "building the chain dispatched"
        chain.collect()
        assert prof.counters()["dispatch_by"] == {"fused_chain": 1}

    def test_eager_escape_hatch_pays_per_op(self, rng, monkeypatch):
        monkeypatch.setenv("DSLIB_EAGER", "1")
        a = ds.array(_x(rng, 24, 10))
        prof.reset_counters()
        ds.matmul((a * 0.5).T, a).T
        assert prof.dispatch_count() >= 4

    def test_repeat_chain_hits_program_cache(self, rng):
        a = ds.array(_x(rng, 16, 16)).force()
        ds.matmul((a + 1.0).T, a).collect()       # compile
        prof.reset_counters()
        ds.matmul((a + 1.0).T, a).collect()
        c = prof.counters()
        assert c["dispatch_by"].get("fused_chain") == 1
        assert c["traces"] == 0, "same-structure chain retraced"

    def test_force_points(self, rng):
        from dislib_tpu.runtime import fetch
        x = _x(rng, 10, 10)
        a = ds.array(x)
        s = (a * 2.0).sum(axis=None)
        assert s.is_lazy
        assert float(s) == pytest.approx(2.0 * x.sum(), rel=1e-5)
        assert not s.is_lazy                       # float() forced it
        t = (a + 1.0).T
        v = fetch(t)                               # snapshot fetch forces
        assert not t.is_lazy
        np.testing.assert_array_equal(v[: 10, : 10], (x + 1.0).T)

    def test_metadata_does_not_force(self, rng):
        a = ds.array(_x(rng, 33, 9))
        chain = (a * 2.0).T
        assert chain.shape == (9, 33)
        assert chain.dtype == jnp.float32
        assert chain.block_size is not None
        repr(chain)
        assert chain.is_lazy, "metadata access forced the chain"


class TestRetraceGuard:
    def test_fit_twice_same_shape_adds_no_traces(self, rng):
        x = ds.array(_x(rng, 57, 7))
        kw = dict(n_clusters=3, max_iter=4, tol=0.0, random_state=0)
        KMeans(**kw).fit(x)
        before = prof.counters()["trace_by"]
        KMeans(**kw).fit(x)
        after = prof.counters()["trace_by"]
        assert after.get("kmeans_fit", 0) == before.get("kmeans_fit", 0), \
            "same-shape refit recompiled the fit kernel"
        assert after.get("fused_chain", 0) == before.get("fused_chain", 0)

    def test_grid_search_3x3_compiles_each_kernel_once(self, rng):
        from dislib_tpu.model_selection import GridSearchCV
        x = ds.array(_x(rng, 90, 6))   # 90 % 3 == 0: all folds same shape

        def search():
            gs = GridSearchCV(KMeans(random_state=0, max_iter=3, tol=0.0),
                              {"n_clusters": [2, 3, 4]}, cv=3, refit=False)
            gs.fit(x)
            return gs

        search()                                    # compile pass
        before = prof.counters()["trace_by"]
        gs = search()                               # every kernel cached
        after = prof.counters()["trace_by"]
        assert len(gs.cv_results_["mean_test_score"]) == 3
        for kernel in ("kmeans_fit", "kmeans_score", "fused_chain"):
            assert after.get(kernel, 0) == before.get(kernel, 0), \
                f"3x3 grid search recompiled {kernel} on the second run"


class TestDonation:
    def test_als_chunk_carry_is_donated(self, rng):
        """The chunked-fit path: chunk N's factor outputs feed chunk N+1
        as init_state and must be donated (their sharding matches the
        outputs, so XLA aliases them — a fresh host-built donor may not)."""
        from dislib_tpu.recommendation.als import _als_fit
        r = rng.rand(24, 12).astype(np.float32)
        r[r < 0.5] = 0.0
        a = ds.array(r)
        out1 = _als_fit(a._data, a._data, a.shape, 4, 0.1, 0.0, 2, 0)
        u1, v1 = out1[0], out1[1]
        rmse1 = float(out1[2])
        u1.block_until_ready()
        out2 = _als_fit(a._data, a._data, a.shape, 4, 0.1, 0.0, 2, 0,
                        init_state=(u1, v1, rmse1))
        out2[0].block_until_ready()
        assert u1.is_deleted() and v1.is_deleted(), \
            "init_state factors were not donated (HBM double-buffered)"

    def test_forest_node_carry_is_donated(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        import dislib_tpu.trees.decision_tree as dt
        seen = []
        real = dt._forest_level

        def spy(node, *args, **kwargs):
            out = real(node, *args, **kwargs)
            seen.append(node)
            return out

        x = ds.array(_x(rng, 60, 5))
        y = ds.array((rng.rand(60, 1) > 0.5).astype(np.float32))
        try:
            dt._forest_level = spy
            RandomForestClassifier(n_estimators=2, max_depth=3,
                                   random_state=0).fit(x, y)
        finally:
            dt._forest_level = real
        # the level-0 input is a freshly-built zeros buffer whose layout
        # may not alias the sharded output; every LATER level's input is
        # the previous level's output and must be donated in place
        assert len(seen) >= 2
        assert all(n.is_deleted() for n in seen[1:]), \
            "forest node arrays were not donated"

    def test_donated_fits_pass_debug_checks(self, rng, tmp_path):
        """The ISSUE's `jax.debug` gate: chunked (checkpointed) fits that
        exercise every donation path run clean under jax_debug_nans."""
        from dislib_tpu.cluster import GaussianMixture
        from dislib_tpu.recommendation import ALS
        from dislib_tpu.utils import FitCheckpoint
        jax.config.update("jax_debug_nans", True)
        try:
            x = ds.array(_x(rng, 60, 4))
            km = KMeans(n_clusters=3, max_iter=4, tol=0.0, random_state=0) \
                .fit(x, checkpoint=FitCheckpoint(
                    str(tmp_path / "km.npz"), every=2))
            assert np.isfinite(km.inertia_)
            gm = GaussianMixture(n_components=2, max_iter=4, tol=0.0,
                                 random_state=0) \
                .fit(x, checkpoint=FitCheckpoint(
                    str(tmp_path / "gm.npz"), every=2))
            assert np.isfinite(gm.lower_bound_)
            r = rng.rand(30, 15).astype(np.float32)
            r[r < 0.5] = 0.0
            als = ALS(n_f=4, max_iter=4, tol=0.0, random_state=0) \
                .fit(ds.array(r), checkpoint=FitCheckpoint(
                    str(tmp_path / "als.npz"), every=2))
            assert np.isfinite(als.rmse_)
        finally:
            jax.config.update("jax_debug_nans", False)


class TestEagerParityOfResults:
    def test_estimator_results_identical_with_and_without_fusion(
            self, rng, monkeypatch):
        """End-to-end: a KMeans fit produces identical centers whether the
        Array layer fuses or dispatches eagerly — the estimators' own
        kernels bypass the fusion layer, and the fusion layer's force
        points feed them identical buffers."""
        x = _x(rng, 80, 5)
        init = np.ascontiguousarray(x[[3, 40, 77]])
        fused = KMeans(n_clusters=3, init=init, max_iter=5, tol=0.0) \
            .fit(ds.array(x)).centers_
        monkeypatch.setenv("DSLIB_EAGER", "1")
        eager = KMeans(n_clusters=3, init=init, max_iter=5, tol=0.0) \
            .fit(ds.array(x)).centers_
        np.testing.assert_array_equal(fused, eager)
