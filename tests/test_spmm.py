"""Round-14 distributed-sparse fast path.

Pins the tentpole's claims: the masked-psum SpMM equals the densify
oracle over a (density × mesh × dtype incl. x64-f64 × overlap-schedule)
grid and is BIT-equal across overlap schedules; the sparse rechunk
schedules reproduce a host scipy relayout exactly and rebuild poisoned
nse pads from zero; the ``math.matmul`` spmm/densify router keys on
density × the densify budget; ALS ``fold_in`` matches the normal-
equation oracle in one dispatch; the sparse serving pipeline serves
padded sparse batches through the PredictServer bucket ladder; and the
fit → fold-in → serve pipeline runs with zero host transfers of the
ratings/factors and ZERO densifications (monkeypatch-banned).
"""

import warnings

import jax
import numpy as np
import pytest
import scipy.sparse as sp

import dislib_tpu as ds
from dislib_tpu.data.sparse import SparseArray, nse_quantum
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as prof

MESHES = [(8, 1), (4, 2), (2, 4)]


def _mk(rng, m, n, density, dtype=np.float32):
    dense = (rng.rand(m, n) * (rng.rand(m, n) < density)).astype(dtype)
    return dense, SparseArray.from_scipy(sp.csr_matrix(dense), dtype=dtype)


def _triplet_dense(sa):
    """Rebuild the logical dense matrix from the SHARDED buffers."""
    rep = sa.sharded()
    out = np.zeros(sa.shape, np.asarray(rep.data).dtype)
    rows, cols, vals = rep.host_triplets()
    np.add.at(out, (rows.astype(int), cols.astype(int)), vals)
    return out


def _poison_pads(sa):
    """Overwrite every pad slot of the sharded buffers with garbage
    (NaN values, in-range-but-wrong columns/rows) — the pads must stay
    non-load-bearing through every kernel and schedule."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = sa.sharded()
    d = np.asarray(rep.data).copy()
    lr = np.asarray(rep.lrows).copy()
    cc = np.asarray(rep.cols).copy()
    for s, k in enumerate(rep.counts):
        d[s, k:] = np.nan
        lr[s, k:] = (s + 1) % max(rep.m_local, 1)
        cc[s, k:] = min(rep.shape[1] - 1, 1)
    sh = NamedSharding(rep.mesh, P(_mesh.ROWS))
    rep.data = jax.device_put(jnp.asarray(d), sh)
    rep.lrows = jax.device_put(jnp.asarray(lr), sh)
    rep.cols = jax.device_put(jnp.asarray(cc), sh)
    # drop every derived view so it REBUILDS from the poisoned primaries
    # (a clean cached view would dodge the poison instead of masking it)
    rep._rowsq = None
    rep._pviews = {}
    rep._ell = None
    rep._rsteps = {}
    return sa


def _poison_panel_view(sa, steps, h):
    """Poison the PANEL VIEW's pad slots (between each panel's live count
    and nse_p) — the slot-range consume must mask them out per panel."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = sa.sharded()
    view = rep.panel_view(steps, h)
    d = np.asarray(view.data).copy()
    lr = np.asarray(view.lrows).copy()
    cc = np.asarray(view.cols).copy()
    pc = np.asarray(jax.device_get(view.counts_dev))
    for s in range(rep.p):
        for t in range(steps):
            lo = t * view.nse_p + pc[s, t]
            hi = (t + 1) * view.nse_p
            d[s, lo:hi] = np.nan
            lr[s, lo:hi] = (s + 1) % max(rep.m_local, 1)
            cc[s, lo:hi] = min(h - 1, 1)
    sh = NamedSharding(rep.mesh, P(_mesh.ROWS))
    rep._pviews[(int(steps), int(h))] = type(view)(
        jax.device_put(jnp.asarray(d), sh),
        jax.device_put(jnp.asarray(lr), sh),
        jax.device_put(jnp.asarray(cc), sh),
        view.counts_dev, view.nse_p, view.steps, view.h)
    return sa


# ---------------------------------------------------------------------------
# SpMM vs the densify oracle
# ---------------------------------------------------------------------------

class TestSpmmOracle:
    @pytest.mark.parametrize("mesh", MESHES)
    @pytest.mark.parametrize("density", [0.01, 0.3])
    def test_matches_densify_oracle(self, rng, mesh, density):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init(mesh)
        dense, xs = _mk(rng, 54, 37, density)
        b = rng.rand(37, 13).astype(np.float32)
        from dislib_tpu.ops.spmm import spmm
        out = np.asarray(spmm(xs, ds.array(b)).collect())
        np.testing.assert_allclose(out, dense @ b, rtol=1e-5, atol=1e-5)

    def test_f64_x64_mode(self, rng):
        with jax.enable_x64(True):
            ds.init((4, 2))
            dense = (np.asarray(rng.rand(40, 24) * (rng.rand(40, 24) < 0.1))
                     .astype(np.float64))
            xs = SparseArray.from_scipy(sp.csr_matrix(dense),
                                        dtype=np.float64)
            b = rng.rand(24, 8)
            from dislib_tpu.ops.spmm import spmm
            out = spmm(xs, ds.array(b, dtype=np.float64))
            assert out.dtype == np.float64
            np.testing.assert_allclose(np.asarray(out.collect()),
                                       dense @ b, rtol=1e-12)

    def test_overlap_schedules_bit_equal_and_counted(self, rng):
        """db / seq / pallas consume panels in identical order — outputs
        are BIT-equal, and each run is observable as a spmm:<sched>
        schedule counter (1 dispatch each)."""
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        dense, xs = _mk(rng, 48, 32, 0.1)
        b = ds.array(rng.rand(32, 8).astype(np.float32))
        outs = {}
        prof.reset_counters()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # pallas fallback warn off-TPU
            for sched in ("db", "seq", "pallas"):
                outs[sched] = np.asarray(spmm(xs, b, overlap=sched)
                                         .collect())
        assert (outs["db"] == outs["seq"]).all()
        assert (outs["db"] == outs["pallas"]).all()
        sc = prof.schedule_counters()
        assert sc.get("spmm:db", 0) >= 1 and sc.get("spmm:seq", 0) == 1

    def test_one_dispatch(self, rng):
        from dislib_tpu.ops.spmm import spmm
        ds.init((8, 1))
        _, xs = _mk(rng, 40, 16, 0.1)
        b = ds.array(rng.rand(16, 4).astype(np.float32)).force()
        xs.sharded()                        # ingest outside the window
        spmm(xs, b)                         # warm
        prof.reset_counters()
        spmm(xs, b)
        assert prof.counters()["dispatch_by"].get("spmm_panels") == 1
        assert prof.trace_count() == 0

    def test_poisoned_pads_are_inert(self, rng):
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        dense, xs = _mk(rng, 30, 20, 0.2)
        b = ds.array(rng.rand(20, 6).astype(np.float32))
        want = np.asarray(spmm(xs, b).collect())
        _poison_pads(xs)
        got = np.asarray(spmm(xs, b).collect())
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got, want)

    def test_memory_is_o_nnz_not_o_dense(self, rng):
        """XLA's own accounting: the compiled SpMM's temporaries stay
        below one densified-A allocation at low density."""
        from dislib_tpu.ops.spmm import spmm_memory_analysis
        ds.init((8, 1))
        _, xs = _mk(rng, 256, 256, 0.01)
        b = ds.array(rng.rand(256, 32).astype(np.float32))
        res = spmm_memory_analysis(xs, b)
        if res["temp_bytes"] is None:
            pytest.skip("backend exposes no memory analysis")
        assert res["temp_vs_dense"] < 1.0, res


# ---------------------------------------------------------------------------
# the col-partitioned slot-range layout (round-17 leg 2)
# ---------------------------------------------------------------------------

class TestColPartitionedLayout:
    def test_slots_vs_masked_match_oracle_and_counted(self, rng):
        """Both entry layouts equal the densify oracle (allclose, not
        bit: regrouping entries by panel reassociates each output's sum)
        and each run is observable via the spmm_layout:<layout>
        counter."""
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        dense, xs = _mk(rng, 52, 36, 0.15)
        b = rng.rand(36, 9).astype(np.float32)
        ba = ds.array(b)
        prof.reset_counters()
        for layout in ("slots", "masked"):
            out = np.asarray(spmm(xs, ba, layout=layout).collect())
            np.testing.assert_allclose(out, dense @ b, rtol=1e-5, atol=1e-5)
        sc = prof.schedule_counters()
        assert sc.get("spmm_layout:slots", 0) >= 1
        assert sc.get("spmm_layout:masked", 0) >= 1

    @pytest.mark.parametrize("sched", ["db", "seq"])
    def test_slots_bit_equal_across_schedules(self, rng, sched):
        """WITHIN the slots layout the overlap schedules stay bit-equal
        (the layout changes WHICH slots a panel reads, never the panel
        consume order)."""
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        _, xs = _mk(rng, 48, 32, 0.12)
        b = ds.array(rng.rand(32, 7).astype(np.float32))
        ref = np.asarray(spmm(xs, b, overlap="db", layout="slots").collect())
        got = np.asarray(spmm(xs, b, overlap=sched, layout="slots").collect())
        assert (ref == got).all()

    def test_default_layout_is_slots(self, rng):
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        _, xs = _mk(rng, 40, 24, 0.1)
        b = ds.array(rng.rand(24, 5).astype(np.float32))
        prof.reset_counters()
        spmm(xs, b)
        assert prof.schedule_counters().get("spmm_layout:slots", 0) == 1

    def test_masking_work_collapses(self, rng):
        """The locality claim, as a counter: slots masking work is
        O(nse + steps·quantum) while masked re-touches all nse per panel
        — at default panels=4 the inflation factor is the panel count
        (minus the slot-pad rounding)."""
        from dislib_tpu.ops.spmm import spmm_masking_work
        ds.init((8, 1))
        _, xs = _mk(rng, 128, 64, 0.1)
        w = spmm_masking_work(xs)
        assert w["masked_work"] == w["steps"] * w["nse"]
        assert w["slots_work"] == w["steps"] * w["nse_p"]
        assert w["inflation"] > 1.0, w

    @pytest.mark.parametrize("sched", ["db", "seq"])
    def test_poisoned_slot_pads_are_inert(self, rng, sched):
        """Poison BOTH pad tiers — the primary buffers' nse pads and the
        panel view's per-panel slot pads — per schedule: the slot-range
        consume must re-zero everything past each panel's live count."""
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        dense, xs = _mk(rng, 44, 28, 0.2)
        b = ds.array(rng.rand(28, 6).astype(np.float32))
        want = np.asarray(spmm(xs, b, overlap=sched, layout="slots")
                          .collect())
        _poison_pads(xs)                      # view rebuilds from these
        got = np.asarray(spmm(xs, b, overlap=sched, layout="slots")
                         .collect())
        np.testing.assert_array_equal(got, want)
        # now poison the REBUILT view's slot pads directly
        rep = xs.sharded()
        view_key = next(iter(rep._pviews))
        _poison_panel_view(xs, *view_key)
        got2 = np.asarray(spmm(xs, b, overlap=sched, layout="slots")
                          .collect())
        assert np.isfinite(got2).all()
        np.testing.assert_array_equal(got2, want)

    def test_slots_f64_x64_mode(self, rng):
        with jax.enable_x64(True):
            ds.init((4, 2))
            dense = (np.asarray(rng.rand(40, 24) * (rng.rand(40, 24) < 0.1))
                     .astype(np.float64))
            xs = SparseArray.from_scipy(sp.csr_matrix(dense),
                                        dtype=np.float64)
            b = rng.rand(24, 8)
            from dislib_tpu.ops.spmm import spmm
            out = spmm(xs, ds.array(b, dtype=np.float64), layout="slots")
            assert out.dtype == np.float64
            np.testing.assert_allclose(np.asarray(out.collect()),
                                       dense @ b, rtol=1e-12)

    def test_cols_host_survives_rechunk(self, rng):
        """The global column stream is layout-independent metadata: a
        reshard carries it through, so the rechunk PRODUCT's panel view
        rebuilds from host metadata with NO blessed cols fetch
        (transfer-counter pinned) and its slots SpMM still matches the
        oracle."""
        from dislib_tpu.ops.spmm import spmm
        ds.init((4, 2))
        dense, xs = _mk(rng, 48, 32, 0.1)
        bh = rng.rand(32, 8).astype(np.float32)
        b = ds.array(bh)
        rs = xs.resharded(nse=xs.sharded().nse + nse_quantum(),
                          schedule="xla")
        rep = rs._sharded_rep
        assert rep.cols_host is not None
        t0 = prof.transfer_count()
        rep.panel_view(4, max(1, -(-rs.shape[1] // 4)))
        assert prof.transfer_count() == t0   # no _cols_stream fetch
        out = np.asarray(spmm(rs, b, layout="slots").collect())
        np.testing.assert_allclose(out, dense @ bh, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# the matmul spmm/densify router
# ---------------------------------------------------------------------------

class TestMatmulRouter:
    def test_auto_low_density_routes_spmm(self, rng):
        ds.init((8, 1))
        dense, xs = _mk(rng, 64, 40, 0.02)
        bh = rng.rand(40, 8).astype(np.float32)
        b = ds.array(bh)
        prof.reset_counters()
        out = ds.matmul(xs, b)
        assert prof.counters()["dispatch_by"].get("spmm_panels") == 1
        np.testing.assert_allclose(np.asarray(out.collect()), dense @ bh,
                                   rtol=1e-5, atol=1e-5)

    def test_auto_high_density_routes_densify(self, rng):
        ds.init((8, 1))
        dense, xs = _mk(rng, 30, 20, 0.6)
        bh = rng.rand(20, 4).astype(np.float32)
        b = ds.array(bh)
        prof.reset_counters()
        out = ds.matmul(xs, b)
        assert "spmm_panels" not in prof.counters()["dispatch_by"]
        np.testing.assert_allclose(np.asarray(out.collect()), dense @ bh,
                                   rtol=1e-5, atol=1e-5)

    def test_densify_budget_forces_spmm(self, rng, monkeypatch):
        """Over the densify byte budget, auto takes spmm even at high
        density — O(nnz) always fits where the data fits."""
        ds.init((8, 1))
        dense, xs = _mk(rng, 30, 20, 0.6)
        bh = rng.rand(20, 4).astype(np.float32)
        b = ds.array(bh)
        monkeypatch.setenv("DSLIB_SPARSE_DENSIFY_BUDGET", "16")
        prof.reset_counters()
        out = ds.matmul(xs, b)
        assert prof.counters()["dispatch_by"].get("spmm_panels") == 1
        np.testing.assert_allclose(np.asarray(out.collect()), dense @ bh,
                                   rtol=1e-5, atol=1e-5)

    def test_explicit_algorithms_and_typed_errors(self, rng):
        ds.init((8, 1))
        dense, xs = _mk(rng, 24, 16, 0.3)
        b = ds.array(rng.rand(16, 4).astype(np.float32))
        a1 = np.asarray(ds.matmul(xs, b, algorithm="spmm").collect())
        a2 = np.asarray(ds.matmul(xs, b, algorithm="densify").collect())
        np.testing.assert_allclose(a1, a2, rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="algorithm"):
            ds.matmul(xs, b, algorithm="nope")
        with pytest.raises(TypeError, match="sparse @ dense"):
            ds.matmul(xs, b, transpose_a=True)
        with pytest.raises(TypeError, match="sparse @ dense"):
            ds.matmul(b, xs)

    def test_operator_still_routes(self, rng):
        ds.init((8, 1))
        dense, xs = _mk(rng, 24, 16, 0.05)
        b = rng.rand(16, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray((xs @ b).collect()),
                                   dense @ b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# sparse rechunk: schedules vs the host scipy relayout oracle
# ---------------------------------------------------------------------------

class TestSparseRechunk:
    @pytest.mark.parametrize("pair", [((8, 1), (4, 2)), ((4, 2), (2, 4)),
                                      ((2, 4), (8, 1))])
    def test_panel_exchange_equals_scipy_relayout(self, rng, pair):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        src, dst = pair
        ds.init(src)
        dense, xs = _mk(rng, 61, 23, 0.25)
        xs.sharded()                         # lay out under the SOURCE mesh
        dst_mesh = _mesh.init(dst)
        prof.reset_counters()
        out = ds.rechunk(xs, mesh=dst_mesh, schedule="panels")
        assert out._sharded_rep.mesh is dst_mesh
        # oracle: the host scipy matrix relaid out is ... the same matrix
        np.testing.assert_allclose(_triplet_dense(out), dense)
        assert any(k.startswith("rechunk_sparse_panels:")
                   for k in prof.schedule_counters())
        # and the fast path consumes the relaid buffers directly
        b = rng.rand(23, 5).astype(np.float32)
        from dislib_tpu.ops.spmm import spmm
        np.testing.assert_allclose(
            np.asarray(spmm(out, ds.array(b)).collect()), dense @ b,
            rtol=1e-5, atol=1e-5)

    def test_nse_requantize_fused(self, rng):
        ds.init((8, 1))
        dense, xs = _mk(rng, 40, 16, 0.2)
        xs.sharded()
        q = nse_quantum()
        out = ds.rechunk(xs, nse=3 * q, schedule="xla")
        assert out._sharded_rep.nse == 3 * q
        np.testing.assert_allclose(_triplet_dense(out), dense)
        # a too-small explicit nse is a typed error, not silent truncation
        with pytest.raises(ValueError, match="nse"):
            ds.rechunk(xs, nse=0, schedule="xla")

    def test_deviceput_device_set_change(self, rng):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init((8, 1))
        dense, xs = _mk(rng, 33, 17, 0.3)
        xs.sharded()
        half = _mesh.init((2, 2), devices=jax.devices()[:4])
        out = ds.rechunk(xs, mesh=half, schedule="deviceput")
        assert out._sharded_rep.p == 2
        np.testing.assert_allclose(_triplet_dense(out), dense)

    @pytest.mark.parametrize("sched", ["panels", "deviceput"])
    def test_poisoned_pads_rebuilt_per_schedule(self, rng, sched):
        ds.init((8, 1))
        dense, xs = _mk(rng, 29, 11, 0.3)
        _poison_pads(xs)
        dst = _mesh.init((4, 2))
        out = ds.rechunk(xs, mesh=dst, schedule=sched)
        rep = out._sharded_rep
        assert np.isfinite(np.asarray(rep.data)).all()
        np.testing.assert_allclose(_triplet_dense(out), dense)

    def test_sharded_ingest_guard_relands_on_device(self, rng):
        """`sharded(mesh)` on a backing laid out for ANOTHER mesh — the
        estimator ingest-guard path — reshards without a host hop."""
        ds.init((8, 1))
        dense, xs = _mk(rng, 26, 14, 0.3)
        xs.sharded()
        dst = _mesh.init((4, 2))
        with jax.transfer_guard("disallow"):
            rep = xs.sharded(dst)
        assert rep.mesh is dst and rep.p == 4

    def test_rechunk_dense_still_rejects_garbage(self):
        with pytest.raises(TypeError, match="ds-array or SparseArray"):
            ds.rechunk([[1, 2]])

    def test_panels_kwarg_rejected_for_sparse(self, rng):
        """panels= tunes the DENSE exchange only; silently ignoring it
        on sparse would read as a working memory knob (review-found) —
        nse= is the sparse knob, and the entry says so."""
        _, xs = _mk(rng, 16, 8, 0.3)
        with pytest.raises(ValueError, match="nse"):
            ds.rechunk(xs, panels=8)


# ---------------------------------------------------------------------------
# ALS fold-in
# ---------------------------------------------------------------------------

def _als_fixture(rng, m=30, n=20, f=4):
    u = rng.rand(m, f).astype(np.float32)
    v = rng.rand(n, f).astype(np.float32)
    full = u @ v.T
    r = np.where(rng.rand(m, n) < 0.4, full, 0.0).astype(np.float32)
    from dislib_tpu.recommendation import ALS
    als = ALS(n_f=f, lambda_=0.002, max_iter=30, tol=1e-7,
              random_state=0).fit(SparseArray.from_scipy(sp.csr_matrix(r)))
    return als, v, full


class TestFoldIn:
    def test_matches_normal_equation_oracle(self, rng):
        als, v, full = _als_fixture(rng)
        new = np.where(rng.rand(20) < 0.5,
                       rng.rand(4).astype(np.float32) @ v.T, 0.0) \
            .astype(np.float32)
        prof.reset_counters()
        pred = als.fold_in(new)
        assert prof.counters()["dispatch_by"].get("als_fold_in") == 1
        obs = new != 0
        vo = als.items_[obs]
        lam = als.lambda_ * max(obs.sum(), 1)
        fac = np.linalg.solve(vo.T @ vo + lam * np.eye(4),
                              vo.T @ new[obs])
        np.testing.assert_allclose(pred[0], fac @ als.items_.T,
                                   rtol=1e-4, atol=1e-4)
        # the folded-in user predicts its own observed ratings well
        assert np.abs(pred[0][obs] - new[obs]).mean() < 0.15

    def test_input_forms_agree(self, rng):
        als, v, _ = _als_fixture(rng)
        new = np.where(rng.rand(2, 20) < 0.5, 1.0, 0.0).astype(np.float32)
        a = als.fold_in(new)
        b = als.fold_in(sp.csr_matrix(new))
        c = als.fold_in(SparseArray.from_scipy(sp.csr_matrix(new)))
        np.testing.assert_allclose(a, b, atol=1e-6)
        np.testing.assert_allclose(a, c, atol=1e-6)

    def test_top_n_fused_matches_full_scores(self, rng):
        """fold_in(top_n=) ranks in the SAME dispatch (lax.top_k fused
        after the predict GEMM) and agrees with ranking the full score
        matrix on host."""
        als, v, _ = _als_fixture(rng)
        new = np.where(rng.rand(3, 20) < 0.5, 1.0, 0.0).astype(np.float32)
        full = als.fold_in(new)
        prof.reset_counters()
        ids, scores = als.fold_in(new, top_n=5)
        assert prof.counters()["dispatch_by"].get("als_fold_in") == 1
        assert ids.shape == scores.shape == (3, 5)
        for k in range(3):
            want = np.argsort(-full[k])[:5]
            np.testing.assert_array_equal(np.sort(ids[k]), np.sort(want))
            np.testing.assert_allclose(scores[k], full[k][ids[k]],
                                       atol=1e-6)

    def test_wrong_width_raises(self, rng):
        als, _, _ = _als_fixture(rng)
        with pytest.raises(ValueError, match="items"):
            als.fold_in(np.zeros((1, 7), np.float32))

    def test_unfitted_raises(self):
        from dislib_tpu.recommendation import ALS
        with pytest.raises(RuntimeError):
            ALS().fold_in(np.zeros(3))

    def test_float32_cols_tuple_form(self, rng):
        """The pre-padded (cols, vals) device form accepts float32 ids —
        the serving encoding's dtype (review-found: the gather needs an
        int cast the packed kernel had but the tuple form lacked)."""
        als, _, _ = _als_fixture(rng)
        cols = np.array([[1, 5, 0, 0]], np.float32)
        vals = np.array([[2.0, 3.0, 0, 0]], np.float32)
        a = als.fold_in((cols, vals))
        b = als.fold_in((cols.astype(np.int32), vals))
        np.testing.assert_array_equal(a, b)

    def test_out_of_range_id_is_a_no_op(self, rng):
        """A corrupt id past pack-time validation must not silently
        score against the clipped LAST item (review-found): the fold-in
        weight masks out-of-range entries to nothing."""
        als, _, _ = _als_fixture(rng)
        good = als.fold_in((np.array([[1, 5]], np.int32),
                            np.array([[2.0, 3.0]], np.float32)))
        with_bad = als.fold_in((np.array([[1, 5, 10_000]], np.int32),
                                np.array([[2.0, 3.0, 4.0]], np.float32)))
        np.testing.assert_allclose(with_bad, good, atol=1e-6)


# ---------------------------------------------------------------------------
# sparse serving: padded sparse batches through the bucket ladder
# ---------------------------------------------------------------------------

class TestSparseServing:
    def test_server_bucket_ladder_serves_padded_sparse(self, rng):
        from dislib_tpu.serving import PredictServer, SparseFoldInPipeline
        als, v, _ = _als_fixture(rng)
        pipe = SparseFoldInPipeline(als, nse_cap=16)
        new = np.where(rng.rand(5, 20) < 0.4,
                       rng.rand(5, 4).astype(np.float32) @ v.T, 0.0) \
            .astype(np.float32)
        packed = pipe.pack(new)
        assert packed.shape == (5, 32)
        with PredictServer(pipeline=pipe, buckets=(1, 8, 64)) as srv:
            prof.reset_counters()
            out = srv.predict(packed)
            stats = srv.stats()
        assert stats["dispatches_per_batch_max"] == 1
        np.testing.assert_allclose(out, als.fold_in(new), rtol=1e-5,
                                   atol=1e-5)

    def test_pipeline_top_n_serves_ranked_rows(self, rng):
        """A top_n pipeline serves [item_ids | scores] rows of width
        2·top_n from the same fused dispatch, agreeing with the full
        score matrix's ranking."""
        from dislib_tpu.serving import SparseFoldInPipeline
        als, v, _ = _als_fixture(rng)
        new = np.where(rng.rand(2, 20) < 0.4, 1.0, 0.0).astype(np.float32)
        full = SparseFoldInPipeline(als, nse_cap=16)
        ranked = SparseFoldInPipeline(als, nse_cap=16, top_n=4)
        out_full = full.predict_bucket(full.pack(new), 4)
        out = ranked.predict_bucket(ranked.pack(new), 4)
        assert out.shape == (2, 8) and ranked.out_cols == 8
        ids, scores = out[:, :4].astype(np.int64), out[:, 4:]
        for k in range(2):
            want = np.argsort(-out_full[k])[:4]
            np.testing.assert_array_equal(np.sort(ids[k]), np.sort(want))
            np.testing.assert_allclose(scores[k], out_full[k][ids[k]],
                                       atol=1e-5)

    def test_pack_guards(self, rng):
        from dislib_tpu.serving import SparseFoldInPipeline
        als, _, _ = _als_fixture(rng)
        pipe = SparseFoldInPipeline(als, nse_cap=2)
        dense_row = np.ones((1, 20), np.float32)     # 20 observed > cap 2
        with pytest.raises(ValueError, match="nse_cap"):
            pipe.pack(dense_row)
        with pytest.raises(ValueError, match="out of range"):
            pipe.pack([(np.array([25]), np.array([1.0]))])
        with pytest.raises(ValueError, match="pack"):
            pipe.predict_bucket(np.zeros((1, 7), np.float32), 8)

    def test_padded_rows_are_zero_observation_users(self, rng):
        """A pad row (all zeros) solves λI·u = 0 → zero predictions —
        it can never affect real rows (the bucket-pad contract)."""
        from dislib_tpu.serving import SparseFoldInPipeline
        als, v, _ = _als_fixture(rng)
        pipe = SparseFoldInPipeline(als, nse_cap=8)
        one = pipe.pack(np.where(rng.rand(1, 20) < 0.3, 1.0, 0.0)
                        .astype(np.float32))
        alone = pipe.predict_bucket(one, 8)
        assert alone.shape[0] == 1


# ---------------------------------------------------------------------------
# the pipeline proof: fit -> fold-in -> serve, zero densify, zero transfers
# ---------------------------------------------------------------------------

class TestZeroDensifyPipeline:
    def test_fit_foldin_serve_never_densifies(self, rng, monkeypatch):
        """The WHOLE sparse recommender pipeline under a densify ban:
        to_dense / the dense escape hatch raising proves zero
        densifications of the ratings matrix, end to end."""
        from dislib_tpu.recommendation import ALS
        from dislib_tpu.serving import PredictServer, SparseFoldInPipeline

        def boom(*a, **k):
            raise AssertionError("pipeline densified the ratings matrix")
        monkeypatch.setattr(SparseArray, "to_dense", boom)
        monkeypatch.setattr(SparseArray, "_data", property(boom))
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = np.where(rng.rand(30, 20) < 0.4, u @ v.T, 0.0) \
            .astype(np.float32)
        xs = SparseArray.from_scipy(sp.csr_matrix(r))
        als = ALS(n_f=4, lambda_=0.002, max_iter=20, tol=1e-7,
                  random_state=0).fit(xs)
        assert als.rmse_ < 0.1
        pipe = SparseFoldInPipeline(als, nse_cap=16)
        new = np.where(rng.rand(2, 20) < 0.4, 1.0, 0.0).astype(np.float32)
        with PredictServer(pipeline=pipe, buckets=(1, 8)) as srv:
            out = srv.predict(pipe.pack(new))
        assert out.shape == (2, 20) and np.isfinite(out).all()

    def test_model_boundary_crosses_at_zero_transfers(self, rng):
        """After warmup, the fit → fold-in → serve DEVICE boundary is
        transfer-free: counter-asserted AND under
        jax.transfer_guard('disallow') — the PR-6 pipeline-boundary
        discipline extended to the sparse recommender."""
        from dislib_tpu.recommendation import ALS
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = np.where(rng.rand(30, 20) < 0.4, u @ v.T, 0.0) \
            .astype(np.float32)
        xs = SparseArray.from_scipy(sp.csr_matrix(r))
        als = ALS(n_f=4, lambda_=0.002, max_iter=10, tol=1e-7,
                  random_state=0).fit(xs)
        from dislib_tpu.recommendation.als import _fold_in_pack
        cols, vals = _fold_in_pack(
            np.where(rng.rand(2, 20) < 0.4, 1.0, 0.0).astype(np.float32),
            20)
        jax.block_until_ready(als._fold_in_device((cols, vals)))  # warm
        prof.reset_counters()
        with jax.transfer_guard("disallow"):
            rep = xs.sharded()          # the fit's backing: already placed
            preds = als._fold_in_device((cols, vals))
            assert rep.nnz >= 0
        jax.block_until_ready(preds)
        assert prof.transfer_count() == 0
        assert np.isfinite(np.asarray(preds)).all()


# ---------------------------------------------------------------------------
# the sparse elastic rung (the PR-10 ladder's mesh-shrink tier)
# ---------------------------------------------------------------------------

class TestSparseElastic:
    def test_sparse_kmeans_mesh_shrink_heals_to_oracle(self, rng, tmp_path):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        from dislib_tpu.cluster import KMeans
        from dislib_tpu.utils import faults
        from dislib_tpu.utils.checkpoint import FitCheckpoint
        xm = rng.rand(200, 6).astype(np.float32)
        xm[xm < np.median(xm)] = 0
        init = np.ascontiguousarray(xm[[0, 70, 140]])
        kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
        ds.init((8, 1), devices=jax.devices()[:8])
        full = KMeans(**kw).fit(SparseArray.from_scipy(sp.csr_matrix(xm)))
        ds.init((8, 1), devices=jax.devices()[:8])
        pol = faults.FaultAtTier(tiers=2, at_chunk=2, max_restarts=3,
                                 elastic_attempts=1)
        res = KMeans(**kw).fit(
            SparseArray.from_scipy(sp.csr_matrix(xm)),
            checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert pol.healed and res.fit_info_["mesh_shrinks"] == 1
        assert ds.get_mesh().shape["rows"] == 4
        np.testing.assert_allclose(res.centers_, full.centers_,
                                   rtol=1e-4, atol=1e-5)

    def test_sparse_als_mesh_shrink_heals_to_oracle(self, rng, tmp_path):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        from dislib_tpu.recommendation import ALS
        from dislib_tpu.utils import faults
        from dislib_tpu.utils.checkpoint import FitCheckpoint
        u, v = rng.rand(30, 4), rng.rand(20, 4)
        r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)
        akw = dict(n_f=4, max_iter=8, tol=-1.0, random_state=0)
        ds.init((8, 1), devices=jax.devices()[:8])
        full = ALS(**akw).fit(SparseArray.from_scipy(sp.csr_matrix(r)))
        ds.init((8, 1), devices=jax.devices()[:8])
        pol = faults.FaultAtTier(tiers=2, at_chunk=2, max_restarts=3,
                                 elastic_attempts=1)
        res = ALS(**akw).fit(
            SparseArray.from_scipy(sp.csr_matrix(r)),
            checkpoint=FitCheckpoint(str(tmp_path / "a.npz"), every=2),
            health=pol)
        assert pol.healed and res.fit_info_["mesh_shrinks"] == 1
        assert res.fit_info_["escalations"]["elastic"] == 1
        np.testing.assert_allclose(res.users_, full.users_,
                                   rtol=2e-2, atol=2e-3)
