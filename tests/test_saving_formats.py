"""save_model/load_model format regressions (round-7 satellite — the PR 1
known-issue entry: npz crashing via numpy's implicit behaviors, cbor
failing on length decode).  One regression class per format:

- every format round-trips an estimator with LARGE fitted state (forest:
  multi-level split arrays force cbor 2- and 4-byte length arguments and
  a multi-MB npz payload);
- npz: `np.savez_compressed` silently APPENDS ".npz" to a bare path —
  save now writes through the file handle, so any extension round-trips;
  loads run with `allow_pickle=False` and reject foreign/pickled files
  with a clear error instead of numpy's allow_pickle crash;
- cbor: the in-tree decoder bounds-checks every length argument — a
  truncated/foreign file raises a clear ValueError at the exact offset
  instead of IndexError or a silently-misread length.
"""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.trees import RandomForestClassifier
from dislib_tpu.utils.saving import load_model, save_model


@pytest.fixture(scope="module")
def forest(rng_module):
    rng = rng_module
    x = rng.rand(600, 8).astype(np.float32)
    y = (x[:, 0] + x[:, 3] > 1.0).astype(np.float32)[:, None]
    a, ya = ds.array(x), ds.array(y)
    rf = RandomForestClassifier(n_estimators=3, max_depth=6,
                                random_state=0).fit(a, ya)
    return rf, a, rf.predict(a).collect()


@pytest.fixture(scope="module")
def rng_module():
    return np.random.RandomState(7)


@pytest.mark.parametrize("fmt", ["json", "cbor", "npz"])
def test_large_state_roundtrip_per_format(forest, tmp_path, fmt):
    rf, a, pred = forest
    path = str(tmp_path / f"forest.{fmt}")
    save_model(rf, path, save_format=fmt)
    rf2 = load_model(path)
    np.testing.assert_array_equal(rf2.predict(a).collect(), pred)


@pytest.mark.parametrize("fmt", ["json", "cbor", "npz"])
def test_extensionless_path_roundtrip(forest, tmp_path, fmt):
    """np.savez_compressed appends '.npz' to bare paths — the npz format
    used to save `model` as `model.npz` and fail its own load; every
    format must round-trip whatever path the caller names."""
    import os
    rf, a, pred = forest
    path = str(tmp_path / f"model_{fmt}_noext")
    save_model(rf, path, save_format=fmt)
    assert os.path.exists(path) and not os.path.exists(path + ".npz")
    rf2 = load_model(path, load_format=fmt)
    np.testing.assert_array_equal(rf2.predict(a).collect(), pred)


def test_npz_rejects_foreign_and_pickled_files(tmp_path):
    foreign = str(tmp_path / "foreign.npz")
    np.savez(foreign, junk=np.arange(3))             # no 'state' entry
    with pytest.raises(ValueError, match="not a dislib_tpu npz model"):
        load_model(foreign, load_format="npz")
    pickled = str(tmp_path / "pickled.npz")
    np.savez(pickled, state=np.asarray([{"a": 1}], dtype=object))
    with pytest.raises(ValueError, match="not a dislib_tpu npz model"):
        load_model(pickled, load_format="npz")       # allow_pickle stays off


def test_npz_rejects_truncated_file(forest, tmp_path):
    rf, _, _ = forest
    path = str(tmp_path / "trunc.npz")
    save_model(rf, path, save_format="npz")
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(ValueError, match="not a dislib_tpu npz model"):
        load_model(path)


def test_cbor_rejects_truncated_file(forest, tmp_path):
    rf, _, _ = forest
    path = str(tmp_path / "trunc.cbor")
    save_model(rf, path, save_format="cbor")
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 3])
    with pytest.raises(ValueError, match="not a dislib_tpu cbor model"):
        load_model(path)


def test_cbor_decoder_flags_truncation_not_indexerror():
    """Bounds checks at the decoder layer: every cut point of a valid
    encoding raises ValueError('truncated CBOR...') — never IndexError,
    never a silently-misread shorter length."""
    from dislib_tpu.utils import cbor_lite
    payload = {"k" * 30: [list(range(30)), "v" * 300, 2 ** 40, 1.25],
               "b": bytes(range(256))}
    enc = cbor_lite.dumps(payload)
    assert cbor_lite.loads(enc) == payload
    for cut in range(len(enc)):
        with pytest.raises(ValueError):
            cbor_lite.loads(enc[:cut])
