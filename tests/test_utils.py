"""shuffle / split / saving tests (reference: test_utils, test_saving*)."""

import os

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.base import clone
from dislib_tpu.cluster import KMeans


class TestShuffle:
    def test_permutes_rows(self, rng):
        x = rng.rand(40, 5)
        y = np.arange(40.0).reshape(-1, 1)
        xs, ys = ds.shuffle(ds.array(x), ds.array(y), random_state=0)
        xc, yc = xs.collect(), ys.collect()
        perm = yc.ravel().astype(int)
        assert not np.array_equal(perm, np.arange(40))
        assert sorted(perm) == list(range(40))
        np.testing.assert_allclose(xc, x[perm].astype(np.float32))

    def test_deterministic(self, rng):
        x = ds.array(rng.rand(20, 3))
        a = ds.shuffle(x, random_state=3).collect()
        b = ds.shuffle(x, random_state=3).collect()
        np.testing.assert_array_equal(a, b)

    def test_mismatched_rows_raise(self, rng):
        with pytest.raises(ValueError):
            ds.shuffle(ds.array(rng.rand(5, 2)), ds.array(rng.rand(4, 1)))


class TestTrainTestSplit:
    def test_sizes_and_content(self, rng):
        x = rng.rand(40, 3)
        y = np.arange(40.0).reshape(-1, 1)
        xtr, xte, ytr, yte = ds.train_test_split(ds.array(x), ds.array(y),
                                                 test_size=0.25, random_state=0)
        assert xtr.shape == (30, 3) and xte.shape == (10, 3)
        all_idx = np.concatenate([ytr.collect().ravel(), yte.collect().ravel()])
        assert sorted(all_idx.astype(int)) == list(range(40))


class TestSaving:
    @pytest.mark.parametrize("fmt,ext", [("json", "json"), ("npz", "npz")])
    def test_roundtrip_kmeans(self, rng, tmp_path, fmt, ext):
        x = rng.rand(60, 4).astype(np.float32)
        a = ds.array(x)
        km = KMeans(n_clusters=3, max_iter=10, random_state=0).fit(a)
        path = os.path.join(tmp_path, f"model.{ext}")
        ds.save_model(km, path, save_format=fmt)
        km2 = ds.load_model(path)
        assert isinstance(km2, KMeans)
        assert km2.n_clusters == 3
        np.testing.assert_allclose(km2.centers_, km.centers_)
        assert km2.n_iter_ == km.n_iter_
        np.testing.assert_array_equal(km2.predict(a).collect(),
                                      km.predict(a).collect())

    def test_roundtrip_private_state_estimators(self, rng, tmp_path):
        """Estimators whose predictive state lives in leading-underscore
        attrs (declared via _private_fitted_attrs) must predict identically
        after a save/load round trip."""
        from dislib_tpu.classification import CascadeSVM, KNeighborsClassifier
        from dislib_tpu.trees import RandomForestClassifier
        from dislib_tpu.neighbors import NearestNeighbors
        x = rng.randn(80, 3).astype(np.float32)
        x[40:] += 4.0
        y = np.r_[np.zeros(40), np.ones(40)].astype(np.float32)
        a, ya = ds.array(x), ds.array(y[:, None])
        for est in (CascadeSVM(max_iter=2, random_state=0),
                    RandomForestClassifier(n_estimators=3, random_state=0),
                    KNeighborsClassifier(n_neighbors=3)):
            est.fit(a, ya)
            path = os.path.join(tmp_path, f"{type(est).__name__}.json")
            ds.save_model(est, path)
            est2 = ds.load_model(path)
            np.testing.assert_array_equal(est2.predict(a).collect(),
                                          est.predict(a).collect())
        nn = NearestNeighbors(n_neighbors=2).fit(a)
        path = os.path.join(tmp_path, "nn.json")
        ds.save_model(nn, path)
        nn2 = ds.load_model(path)
        d1, i1 = nn.kneighbors(a)
        d2, i2 = nn2.kneighbors(a)
        np.testing.assert_allclose(d2.collect(), d1.collect(), atol=1e-5)
        np.testing.assert_array_equal(i2.collect(), i1.collect())

    def test_no_overwrite(self, rng, tmp_path):
        km = KMeans(n_clusters=2).fit(ds.array(rng.rand(10, 2)))
        path = os.path.join(tmp_path, "m.json")
        ds.save_model(km, path)
        with pytest.raises(FileExistsError):
            ds.save_model(km, path, overwrite=False)

    def test_refuses_foreign_module(self, tmp_path):
        import json
        path = os.path.join(tmp_path, "evil.json")
        with open(path, "w") as f:
            json.dump({"__estimator__": {"module": "os", "cls": "system",
                                         "params": {}, "fitted": {}}}, f)
        with pytest.raises(ValueError):
            ds.load_model(path)


class TestBaseEstimator:
    def test_get_set_params_clone(self):
        km = KMeans(n_clusters=5, tol=1e-3)
        p = km.get_params()
        assert p["n_clusters"] == 5 and p["tol"] == 1e-3
        km.set_params(n_clusters=7)
        assert km.n_clusters == 7
        with pytest.raises(ValueError):
            km.set_params(bogus=1)
        km2 = clone(km)
        assert km2.n_clusters == 7 and not hasattr(km2, "centers_")


class TestDataUtil:
    def test_pad_helpers(self, rng):
        from dislib_tpu.data import util as du
        x = rng.rand(10, 7)
        a = ds.array(x, block_size=(4, 4))
        p = du.pad(a, ((1, 2), (0, 3)), value=5.0)
        want = np.pad(x, ((1, 2), (0, 3)), constant_values=5.0)
        np.testing.assert_allclose(p.collect(), want.astype(np.float32))
        pz = du.pad_last_blocks_with_zeros(a)
        assert pz.shape == (12, 8)
        assert du.compute_bottom_right_shape(a) == (2, 3)
        np.testing.assert_allclose(du.remove_last_rows(a, 3).collect(), x[:7].astype(np.float32))
        np.testing.assert_allclose(du.remove_last_columns(a, 2).collect(), x[:, :5].astype(np.float32))


class TestShuffleScale:
    """Round-2 weak #6 follow-up: the global-permutation shuffle at a
    non-toy size stays a sharded gather — output balanced across shards,
    content an exact permutation."""

    def test_shuffle_large_stays_sharded_and_exact(self, rng):
        x_np = rng.rand(8192, 8).astype(np.float32)
        xs = ds.shuffle(ds.array(x_np), random_state=7)
        # output is still sharded evenly over the mesh rows
        ndev = len({s.device for s in xs._data.addressable_shards})
        total = xs._data.nbytes
        for s in xs._data.addressable_shards:
            assert s.data.nbytes <= total // ndev
        got = np.asarray(xs.collect())
        # exact permutation: same multiset of rows, not the identity
        key = rng.rand(8).astype(np.float32)
        np.testing.assert_allclose(np.sort(got @ key), np.sort(x_np @ key),
                                   rtol=1e-5)
        assert not np.allclose(got, x_np)


class TestMemoryStats:
    def test_reports_per_device(self):
        from dislib_tpu.utils import memory_stats
        import jax
        stats = memory_stats()
        assert len(stats) == len(jax.local_devices())
        for v in stats.values():
            assert v is None or isinstance(v, dict)


class TestShuffleScaling:
    def test_oracle_and_irregular(self, rng):
        import dislib_tpu as ds
        from dislib_tpu.utils import shuffle
        x = rng.rand(101, 7).astype(np.float32)   # ragged vs the 8-shard grid
        y = np.arange(101, dtype=np.float32).reshape(-1, 1)
        xs, ys = shuffle(ds.array(x), ds.array(y), random_state=3)
        got_x, got_y = xs.collect(), ys.collect()
        perm = np.random.RandomState(3).permutation(101)
        np.testing.assert_allclose(got_x, x[perm])
        np.testing.assert_allclose(got_y, y[perm])

    def test_all_to_all_not_gather(self, rng):
        """VERDICT r2 weak #6: the reshuffle must be an all-to-all exchange
        with bounded per-device buffers — never a gather of the full
        operand onto every device."""
        import re
        import jax.numpy as jnp
        import pytest
        import dislib_tpu as ds
        from dislib_tpu.utils import base as ub
        from dislib_tpu.parallel import mesh as _mesh

        if _mesh.get_mesh().shape[_mesh.ROWS] < 2:
            pytest.skip("needs a multi-device rows axis")
        m, n, p = 4096, 64, 8
        perm = np.random.RandomState(0).permutation(m)
        a = ds.array(np.zeros((m, n), np.float32))
        m_loc = a._data.shape[0] // p
        send_idx, dst_idx = ub._routing(perm, m_loc, p)
        # uniform permutation: exchange buffers concentrate at ~1 shard
        assert send_idx.shape[2] * p <= 2 * m_loc, "exchange cap blew up"
        compiled = ub._shuffle_exchange.lower(
            a._data, jnp.asarray(send_idx), jnp.asarray(dst_idx),
            _mesh.get_mesh(), p).compile()
        hlo = compiled.as_text()
        assert "all-to-all" in hlo
        full = m * n
        for mt in re.finditer(r"all-gather[^\n]*f32\[([\d,]+)\]", hlo):
            elems = int(np.prod([int(d) for d in mt.group(1).split(",")]))
            assert elems < full, f"all-gather of {elems} covers the operand"
        mem = compiled.memory_analysis()
        if mem is not None:
            assert mem.temp_size_in_bytes < full * 4, \
                f"per-device temp {mem.temp_size_in_bytes} ~ full operand"


class TestCborLite:
    def test_rfc8949_known_vectors(self):
        """Byte-exact against RFC 8949 appendix-A examples (the encodings
        cbor2 produces for the same values — interop is byte compatibility)."""
        from dislib_tpu.utils import cbor_lite as c
        vectors = [
            (0, "00"), (10, "0a"), (23, "17"), (24, "1818"), (100, "1864"),
            (1000, "1903e8"), (1000000, "1a000f4240"),
            (-1, "20"), (-10, "29"), (-100, "3863"),
            (1.1, "fb3ff199999999999a"), (-4.1, "fbc010666666666666"),
            (False, "f4"), (True, "f5"), (None, "f6"),
            ("", "60"), ("a", "6161"), ("IETF", "6449455446"),
            (b"\x01\x02\x03\x04", "4401020304"),
            ([1, 2, 3], "83010203"),
            ({"a": 1, "b": [2, 3]}, "a26161016162820203"),
            ([1, [2, 3], [4, 5]], "8301820203820405"),
        ]
        for val, hexs in vectors:
            assert c.dumps(val).hex() == hexs, val
            back = c.loads(bytes.fromhex(hexs))
            assert back == val and type(back) is type(val)

    def test_decoder_accepts_small_floats_rejects_indefinite(self):
        from dislib_tpu.utils import cbor_lite as c
        assert c.loads(bytes.fromhex("f93c00")) == 1.0       # float16
        assert c.loads(bytes.fromhex("fa47c35000")) == 100000.0   # float32
        with pytest.raises(ValueError):
            c.loads(bytes.fromhex("9f01ff"))                 # indefinite list
        with pytest.raises(ValueError):
            c.loads(bytes.fromhex("c074"))                   # tagged item

    def test_model_roundtrip_cbor(self, rng, tmp_path):
        import dislib_tpu as ds
        from dislib_tpu.cluster import KMeans
        from dislib_tpu.utils import save_model, load_model
        x = ds.array(rng.rand(60, 5).astype(np.float32))
        km = KMeans(n_clusters=3, random_state=0).fit(x)
        p = str(tmp_path / "model.cbor")
        save_model(km, p, save_format="cbor")
        km2 = load_model(p)
        np.testing.assert_allclose(km2.centers_, km.centers_)
        np.testing.assert_array_equal(km2.predict(x).collect(),
                                      km.predict(x).collect())

    def test_large_lengths_roundtrip(self):
        from dislib_tpu.utils import cbor_lite as c
        big = {"s": "x" * 70_000,                   # 4-byte text length
               "b": bytes(range(256)) * 300,        # 2-byte bytes length
               "l": list(range(700)),               # 2-byte array length
               "i": [2**40, -(2**40), 2**63 - 1, -(2**63)]}
        assert c.loads(c.dumps(big)) == big
