"""SURVEY §8 API parity contract, executable.

One integration test per public name: construct → minimal fit/op → sane
output. This is the judge's checklist in test form — if a name regresses
(import, signature, or basic behavior), this file fails before any deeper
suite does. Small shapes throughout; oracle checks live in the per-module
test files."""

import os

import numpy as np
import pytest

import dislib_tpu as ds


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    x = rng.rand(48, 6).astype(np.float32)
    y = (x[:, 0] > 0.5).astype(np.float32).reshape(-1, 1)
    return x, y


def _xy(data, bs=(12, 6)):
    x, y = data
    return ds.array(x, block_size=bs), ds.array(y, block_size=(bs[0], 1))


class TestConstructorsAndArray:
    def test_constructors(self):
        assert ds.array(np.ones((4, 3)), block_size=(2, 3)).shape == (4, 3)
        assert ds.random_array((5, 4), random_state=0).shape == (5, 4)
        assert ds.zeros((3, 3)).collect().sum() == 0
        assert ds.ones((3, 3)).collect().sum() == 9
        assert ds.full((2, 2), 7.0).collect().sum() == 28
        assert np.trace(np.asarray(ds.identity(4).collect())) == 4
        assert np.asarray(ds.eye(3, 5).collect()).sum() == 3

    def test_concat_and_sparse(self):
        a = ds.array(np.ones((4, 3)), block_size=(2, 3))
        assert ds.concat_rows([a, a]).shape == (8, 3)
        assert ds.concat_cols([a, a]).shape == (4, 6)
        import scipy.sparse as sp
        xs = ds.SparseArray.from_scipy(sp.eye(5, format="csr",
                                              dtype=np.float32))
        assert xs.shape == (5, 5) and xs.nnz == 5

    def test_mesh_accessors(self):
        m = ds.get_mesh()
        ds.set_mesh(m)              # idempotent round-trip
        assert ds.get_mesh() is m

    def test_apply_along_axis(self, data):
        x, _ = _xy(data)
        got = ds.apply_along_axis(lambda r: r.sum(), 0, x)
        np.testing.assert_allclose(np.asarray(got.collect()).ravel(),
                                   data[0].sum(0), rtol=1e-4)


class TestIO:
    def test_txt_npy_svmlight_mdcrd_save(self, data, tmp_path):
        x, _ = data
        p = str(tmp_path / "a.csv")
        np.savetxt(p, x, delimiter=",")
        assert ds.load_txt_file(p).shape == x.shape
        pn = str(tmp_path / "a.npy")
        np.save(pn, x)
        assert ds.load_npy_file(pn).shape == x.shape
        ps = str(tmp_path / "a.svm")
        with open(ps, "w") as f:
            f.write("1 1:0.5\n-1 2:1.5\n")
        xs, ys = ds.load_svmlight_file(ps)
        assert xs.shape[0] == 2 and ys.shape == (2, 1)
        pm = str(tmp_path / "a.mdcrd")
        with open(pm, "w") as f:
            f.write("t\n" + "".join(f"{v:8.3f}" for v in range(12)) + "\n")
        assert ds.load_mdcrd_file(pm, n_atoms=2).shape == (2, 6)
        pt = str(tmp_path / "out.txt")
        ds.save_txt(ds.array(x, block_size=(12, 6)), pt)
        assert os.path.exists(pt)


class TestLinalg:
    def test_matmul_kron_svd_qr_tsqr(self, data):
        x, _ = _xy(data)
        assert ds.matmul(x, x, transpose_b=True).shape == (48, 48)
        assert ds.kron(ds.identity(2), ds.identity(3)).shape == (6, 6)
        u, s, v = ds.svd(x)
        assert s.shape == (1, 6)
        q, r = ds.qr(x, mode="economic")
        np.testing.assert_allclose(np.asarray(ds.matmul(q, r).collect()),
                                   data[0], atol=1e-3)
        q2, r2 = ds.tsqr(x)
        assert q2.shape == (48, 6) and r2.shape == (6, 6)
        u3, s3, v3 = ds.random_svd(x, nsv=3, random_state=0)
        assert s3.shape[1] == 3
        u4, s4, v4 = ds.lanczos_svd(x, k=3, random_state=0)
        assert s4.shape == (1, 3)

    def test_pca(self, data):
        x, _ = _xy(data)
        p = ds.PCA(n_components=3)
        t = p.fit_transform(x)
        assert t.shape == (48, 3)
        assert p.components_.shape[0] == 3


ESTIMATOR_CASES = [
    ("KMeans", lambda: ds.KMeans(n_clusters=2, random_state=0, max_iter=3),
     "fit_predict"),
    ("GaussianMixture",
     lambda: ds.GaussianMixture(n_components=2, max_iter=3, random_state=0),
     "fit_predict"),
    ("DBSCAN", lambda: ds.DBSCAN(eps=0.6, min_samples=3), "fit_predict"),
    ("Daura", lambda: ds.Daura(cutoff=0.8), "fit_predict"),
]


class TestClustering:
    @pytest.mark.parametrize("name,make,meth", ESTIMATOR_CASES)
    def test_cluster_fit_predict(self, data, name, make, meth):
        x, _ = _xy(data)
        labels = getattr(make(), meth)(x)
        assert labels.shape == (48, 1)


class TestSupervised:
    def test_classifiers(self, data):
        x, y = _xy(data)
        for est in (ds.CascadeSVM(max_iter=2, random_state=0),
                    ds.KNeighborsClassifier(n_neighbors=3),
                    ds.RandomForestClassifier(n_estimators=3,
                                              random_state=0)):
            est.fit(x, y)
            assert est.predict(x).shape == (48, 1)
            assert 0.0 <= est.score(x, y) <= 1.0

    def test_regressors(self, data):
        x, y = _xy(data)
        for est in (ds.LinearRegression(),
                    ds.Lasso(lmbd=0.01, max_iter=20),
                    ds.RandomForestRegressor(n_estimators=3, random_state=0)):
            est.fit(x, y)
            assert est.predict(x).shape == (48, 1)

    def test_decision_trees(self, data):
        x, y = _xy(data)
        clf = ds.DecisionTreeClassifier(max_depth=3).fit(x, y)
        assert clf.predict(x).shape == (48, 1)
        reg = ds.DecisionTreeRegressor(max_depth=3).fit(x, y)
        assert reg.predict(x).shape == (48, 1)

    def test_neighbors_admm_als(self, data):
        x, y = _xy(data)
        d, i = ds.NearestNeighbors(n_neighbors=2).fit(x).kneighbors(x)
        assert d.shape == (48, 2) and i.shape == (48, 2)
        als = ds.ALS(n_f=2, max_iter=3, random_state=0)
        als.fit(ds.array(np.abs(data[0]), block_size=(12, 6)))
        assert als.predict_user(0).shape == (6,)
        admm = ds.ADMM(prox_kappa=0.01, max_iter=10).fit(x, y)
        assert np.isfinite(np.asarray(admm.z_)).all()

    def test_scalers_shuffle_split(self, data):
        x, y = _xy(data)
        xs = ds.StandardScaler().fit_transform(x)
        assert xs.shape == x.shape
        xm = ds.MinMaxScaler().fit_transform(x)
        assert np.asarray(xm.collect()).max() <= 1.0 + 1e-6
        xsh, ysh = ds.shuffle(x, y, random_state=0)
        assert xsh.shape == x.shape and ysh.shape == y.shape
        tr_x, te_x, tr_y, te_y = ds.train_test_split(x, y, test_size=0.25,
                                                     random_state=0)
        assert tr_x.shape[0] + te_x.shape[0] == 48


class TestMetaAndPersistence:
    def test_model_selection(self, data):
        x, y = _xy(data)
        folds = list(ds.KFold(n_splits=3).split(x, y))
        assert len(folds) == 3
        gs = ds.GridSearchCV(ds.KMeans(random_state=0, max_iter=3),
                             {"n_clusters": [2, 3]}, cv=2).fit(x)
        assert gs.best_params_["n_clusters"] in (2, 3)
        rs = ds.RandomizedSearchCV(ds.KMeans(random_state=0, max_iter=3),
                                   {"n_clusters": [2, 3, 4]}, n_iter=2,
                                   cv=2, random_state=0).fit(x)
        assert "mean_test_score" in rs.cv_results_

    def test_save_load(self, data, tmp_path):
        x, y = _xy(data)
        km = ds.KMeans(n_clusters=2, random_state=0, max_iter=3).fit(x)
        p = str(tmp_path / "m.json")
        ds.save_model(km, p)
        km2 = ds.load_model(p)
        np.testing.assert_allclose(km2.centers_, km.centers_)
