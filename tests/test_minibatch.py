"""MiniBatchKMeans — the ChunkedFitLoop recipe's acceptance estimator
(round-12): a streaming ``partial_fit`` with ZERO bespoke resilience code
(the driver lint enforces that structurally) that still passes the same
rollback / watchdog / preemption / quarantine fault grid as the seven
ported estimators.  One fused dispatch per batch, counter-asserted.
"""

import os

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans, MiniBatchKMeans
from dislib_tpu.data.io import QuarantineLedger, QuarantineReport
from dislib_tpu.runtime import (HealthPolicy, NumericalDivergence,
                                Preempted, WatchdogTimeout,
                                clear_preemption, request_preemption)
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils import profiling as prof


def _blobs(rng, n=192, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32), centers


def _stream(x_np, bs=64):
    return [ds.array(x_np[s: s + bs]) for s in range(0, len(x_np), bs)]


def _mbk(**kw):
    kw.setdefault("n_clusters", 3)
    kw.setdefault("random_state", 0)
    return MiniBatchKMeans(**kw)


@pytest.fixture
def fast_retry(monkeypatch):
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")


class TestStreaming:
    def test_partial_fit_stream_clusters_the_blobs(self, rng):
        x_np, _ = _blobs(rng)
        est = _mbk()
        for b in _stream(x_np):
            est.partial_fit(b)
        assert est.n_batches_ == 3
        assert np.isfinite(est.centers_).all()
        assert est.counts_.sum() == pytest.approx(len(x_np))
        x = ds.array(x_np)
        # the streamed model is a usable clustering: within 2x of a
        # full-batch Lloyd's inertia on the same data
        full = KMeans(n_clusters=3, random_state=0, max_iter=10).fit(x)
        assert -est.score(x) < 2.0 * -full.score(x)
        labels = np.asarray(est.predict(x).collect()).ravel()
        assert len(np.unique(labels)) == 3

    def test_fit_resumes_a_checkpointed_stream_without_reconsuming(
            self, rng, tmp_path):
        """A preempted `fit(x, checkpoint=...)` re-run must resume at the
        snapshot's batch position — re-streaming from 0 would apply the
        already-snapshotted batches twice and diverge from the unfaulted
        model (review-found, pinned)."""
        x_np, _ = _blobs(rng)
        x = ds.array(x_np)
        ref = _mbk(batch_size=64).fit(x)
        path = str(tmp_path / "r.npz")
        # simulate the preempted first run: 2 of 3 batches snapshotted
        part = _mbk(batch_size=64)
        for b in _stream(x_np)[:2]:
            part.partial_fit(b, checkpoint=FitCheckpoint(path, every=1))
        res = _mbk(batch_size=64).fit(x, checkpoint=FitCheckpoint(path,
                                                                  every=1))
        assert res.n_batches_ == 3
        assert res.counts_.sum() == pytest.approx(len(x_np)), \
            "resumed fit re-consumed snapshotted batches"
        np.testing.assert_array_equal(res.centers_, ref.centers_)
        # a re-run over a COMPLETED snapshot adopts it, zero re-dispatch
        again = _mbk(batch_size=64).fit(x, checkpoint=FitCheckpoint(path,
                                                                    every=1))
        assert again.n_batches_ == 3
        np.testing.assert_array_equal(again.centers_, ref.centers_)

    def test_fit_streams_row_slices_and_restarts_state(self, rng):
        x_np, _ = _blobs(rng)
        est = _mbk(batch_size=64, epochs=2).fit(ds.array(x_np))
        assert est.n_batches_ == 6
        assert est.counts_.sum() == pytest.approx(2 * len(x_np))
        est.fit(ds.array(x_np))            # fresh fit restarts the stream
        assert est.n_batches_ == 6

    def test_ndarray_batches_are_accepted(self, rng):
        x_np, _ = _blobs(rng)
        est = _mbk().partial_fit(x_np[:64])
        assert est.n_batches_ == 1

    def test_one_dispatch_per_batch(self, rng):
        x_np, _ = _blobs(rng)
        batches = _stream(x_np)
        _mbk().partial_fit(batches[0])     # warm the compile cache
        prof.reset_counters()
        est = _mbk()
        for b in batches:
            est.partial_fit(b)
        assert prof.counters()["dispatch_by"].get("mbkmeans_step") == 3


class TestFaultGrid:
    """The same grid the ported estimators pass — with zero resilience
    code in the estimator, every behavior below is the DRIVER's."""

    def _healed_stream(self, rng, tmp_path, pol, tag):
        x_np, _ = _blobs(rng)
        batches = _stream(x_np)
        ref = _mbk()
        for b in batches:
            ref.partial_fit(b)
        est = _mbk()
        ck = FitCheckpoint(str(tmp_path / f"{tag}.npz"), every=1)
        for b in batches:
            est.partial_fit(b, checkpoint=ck, health=pol)
        return ref, est

    def test_nan_poisoned_batch_rolls_back_and_heals(self, rng, tmp_path):
        pol = faults.NaNAtChunk(at_chunk=2)
        ref, est = self._healed_stream(rng, tmp_path, pol, "nan")
        assert pol.fired == 1, "fault was never injected"
        assert est.fit_info_["rollbacks"] == 1
        # rollback re-runs the SAME batch: the healed stream is bit-equal
        np.testing.assert_array_equal(est.centers_, ref.centers_)
        np.testing.assert_array_equal(est.counts_, ref.counts_)

    def test_escalation_ladder_runs_for_streams(self, rng, tmp_path):
        pol = faults.FaultAtTier(tiers=1, at_chunk=2)
        ref, est = self._healed_stream(rng, tmp_path, pol, "tier")
        assert pol.healed and pol.fired == 2
        assert est.fit_info_["escalations"]["remediate"] == 1
        np.testing.assert_array_equal(est.centers_, ref.centers_)

    def test_hung_batch_trips_watchdog_then_heals(self, rng, tmp_path,
                                                  fast_retry):
        pol = faults.HangAtChunk(at_chunk=2, hang_s=0.4, deadline_s=0.05,
                                 times=1)
        ref, est = self._healed_stream(rng, tmp_path, pol, "hang")
        assert pol.stalls == 1
        np.testing.assert_array_equal(est.centers_, ref.centers_)

    def test_hang_exhaustion_is_typed(self, rng, tmp_path, fast_retry,
                                      monkeypatch):
        monkeypatch.setenv("DSLIB_RETRY_ATTEMPTS", "2")
        x_np, _ = _blobs(rng)
        est = _mbk()
        with pytest.raises(WatchdogTimeout):
            est.partial_fit(
                _stream(x_np)[0],
                checkpoint=FitCheckpoint(str(tmp_path / "h.npz"), every=1),
                health=faults.HangAtChunk(at_chunk=1, hang_s=0.4,
                                          deadline_s=0.05, times=10))

    def test_no_checkpoint_nan_raises_typed(self, rng):
        x_np, _ = _blobs(rng)
        with pytest.raises(NumericalDivergence) as exc:
            _mbk().partial_fit(_stream(x_np)[0],
                               health=faults.NaNAtChunk(at_chunk=1))
        assert exc.value.estimator == "minibatch_kmeans"

    def test_preemption_lands_between_batches_and_stream_resumes(
            self, rng, tmp_path):
        x_np, _ = _blobs(rng)
        batches = _stream(x_np)
        ref = _mbk()
        for b in batches:
            ref.partial_fit(b)

        path = str(tmp_path / "p.npz")
        est = _mbk()
        try:
            est.partial_fit(batches[0],
                            checkpoint=FitCheckpoint(path, every=1))
            request_preemption()           # eviction notice mid-stream
            with pytest.raises(Preempted):
                # the batch COMMITS and SNAPSHOTS first, then the clean
                # raise lands at the chunk boundary — never mid-dispatch
                est.partial_fit(batches[1],
                                checkpoint=FitCheckpoint(path, every=1))
            clear_preemption()             # the replacement job's clean env
            # the snapshot on disk is the resume point: a FRESH estimator
            # (new process in production) reads the stream position from
            # it and continues exactly (the raise-after-snapshot contract)
            start = int(FitCheckpoint(path, every=1).load()["n_batches"])
            assert start == 2, "the preempted batch must have snapshot"
            res = _mbk()
            for b in batches[start:]:
                res.partial_fit(b, checkpoint=FitCheckpoint(path, every=1))
        finally:
            clear_preemption()
        assert res.n_batches_ == ref.n_batches_
        np.testing.assert_array_equal(res.centers_, ref.centers_)
        np.testing.assert_array_equal(res.counts_, ref.counts_)

    def test_armed_monotone_guard_does_not_false_trip_across_batches(
            self, rng, tmp_path):
        """Consecutive chunks of a STREAM see different data, so
        batch-to-batch inertia is not a monotone trajectory — the batch
        kernel keeps the loss history OUT of its health vector, and an
        armed `monotone_rtol` must not burn the fault budget on healthy
        batch-to-batch variation (review-found false-trip, pinned)."""
        x_np, _ = _blobs(rng)
        # batches sorted by distance from the mean: inertia RISES across
        # batches by construction
        order = np.argsort(np.linalg.norm(x_np - x_np.mean(0), axis=1))
        est = _mbk()
        ck = FitCheckpoint(str(tmp_path / "m.npz"), every=1)
        for b in _stream(x_np[order]):
            est.partial_fit(b, checkpoint=ck,
                            health=HealthPolicy(monotone_rtol=0.05))
        assert est.fit_info_["rollbacks"] == 0, \
            "healthy stream burned the fault budget on rising inertia"
        assert est.n_batches_ == 3

    def test_ledger_caps_retained_reports_but_keeps_exact_totals(self):
        led = QuarantineLedger(max_reports=2)
        for i in range(5):
            led.append(QuarantineReport(f"s{i}", [0], np.zeros((1, 2)), 9))
        assert len(led.reports) == 2, "retained reports must be capped"
        assert [r.source for r in led.reports] == ["s3", "s4"]
        assert led.n_quarantined == 5 and led.n_loaded == 45, \
            "count totals must stay exact past the cap"
        led.reset()
        assert led.n_quarantined == 0 and not led.reports

    def test_nonfinite_batch_is_typed_not_silent(self, rng, tmp_path):
        x_np, _ = _blobs(rng)
        bad = x_np[:64].copy()
        bad[5, 1] = np.nan
        est = _mbk()
        with pytest.raises(NumericalDivergence) as exc:
            est.partial_fit(bad,
                            checkpoint=FitCheckpoint(str(tmp_path / "b.npz"),
                                                     every=1))
        assert exc.value.guard == "input-nonfinite"

    def test_quarantined_ingest_accumulates_across_the_stream(self, rng,
                                                              tmp_path):
        """The streaming steady state the round-12 QuarantineLedger fix
        exists for: repeated load→partial_fit batches ACCUMULATE their
        quarantine reports instead of overwriting them."""
        ds.quarantine_ledger().reset()
        est = _mbk()
        kept = []
        for i in range(3):
            xb, _ = _blobs(rng, n=48)
            xb[4 + i, 0] = np.nan          # one poisoned row per batch
            p = str(tmp_path / f"b{i}.csv")
            np.savetxt(p, xb, delimiter=",")
            with pytest.warns(RuntimeWarning, match="quarantined 1"):
                got = ds.load_txt_file(p)
            kept.append(got.shape[0])
            est.partial_fit(got)
        ledger = ds.quarantine_ledger()
        assert len(ledger.reports) == 3, \
            "ledger must accumulate across repeated ingest calls"
        assert ledger.n_quarantined == 3 and ledger.n_loaded == sum(kept)
        assert [m.shape for m in ledger.keep_masks] == [(48,)] * 3
        assert ledger.keep_mask_all().shape == (144,)
        assert ledger.keep_mask_all().sum() == sum(kept)
        # last_quarantine_report keeps its most-recent-load contract
        assert ds.last_quarantine_report() is ledger.reports[-1]
        assert np.isfinite(est.centers_).all()
        ledger.reset()
        assert ledger.n_quarantined == 0 and not ledger.reports


class TestZeroBespokeResilience:
    def test_partial_fit_source_has_no_protocol_calls(self):
        """Belt over the lint's braces: the estimator's own methods never
        touch guard/checkpoint primitives — the driver is the only
        resilience surface."""
        import inspect
        src = inspect.getsource(MiniBatchKMeans)
        for needle in ("save_async", "remediate", ".admit(", ".check(",
                       "check_host", "raise_if_preempted",
                       "preemption_requested", "checkpoint.load"):
            assert needle not in src, f"bespoke resilience code: {needle}"
