"""Comm–compute overlap schedules (round-13 PR).

The library-wide panel-schedule contract: the double-buffered (``db``)
schedules of SUMMA, the panel rechunk and the ring kernels must be
BIT-EQUAL to their sequential (``seq``) counterparts — same panels, same
ops, same order — still exactly ONE dispatch, routed by ``DSLIB_OVERLAP``
(observable through the schedule counters), green under
``jax_debug_nans``, and the pipelined program must actually decouple the
next panel's collective from the current panel's compute (compiled-HLO
audit: in the db while body at least one all-reduce does NOT feed the
dot; in the seq body every one does).
"""

import re
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof

from conftest import skip_unless_devices


def _mk(shape, dtype=np.float32, seed=0):
    return np.random.RandomState(seed).rand(*shape).astype(dtype)


# ---------------------------------------------------------------------------
# 1. the DSLIB_OVERLAP router
# ---------------------------------------------------------------------------

class TestRouter:
    def test_default_is_double_buffered(self, monkeypatch):
        monkeypatch.delenv("DSLIB_OVERLAP", raising=False)
        assert _ov.resolve() == "db"

    @pytest.mark.parametrize("raw,want", [
        ("db", "db"), ("auto", "db"), ("1", "db"), ("on", "db"),
        ("seq", "seq"), ("0", "seq"), ("off", "seq"),
        ("sequential", "seq"),
    ])
    def test_aliases(self, raw, want):
        assert _ov.resolve(raw) == want

    def test_env_routes_the_default(self, monkeypatch):
        monkeypatch.setenv("DSLIB_OVERLAP", "seq")
        assert _ov.resolve() == "seq"
        monkeypatch.setenv("DSLIB_OVERLAP", "pallas")
        assert _ov.resolve() in ("pallas", "db")   # db iff pallas missing

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="unknown overlap schedule"):
            _ov.resolve("bogus")
        with pytest.raises(ValueError):
            _ov.overlapped("bogus")

    def test_overlapped_predicate(self):
        assert _ov.overlapped("db") and _ov.overlapped("pallas")
        assert not _ov.overlapped("seq")

    def test_pallas_degrades_to_db_when_unavailable(self, monkeypatch):
        from dislib_tpu.ops import pallas_kernels as _pk
        monkeypatch.setattr(_pk, "_AVAILABLE", False)
        monkeypatch.setattr(_ov, "_WARN_REGISTRY", {})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert _ov.resolve("pallas") == "db"
        assert any("falling back" in str(x.message) for x in w), \
            "the pallas→db degrade must warn (sequential stays explicit)"

    def test_pallas_degrade_warns_once_per_process(self, monkeypatch):
        """The degradation warning dedupes through the module registry:
        many dispatch sites resolve the schedule (spmm, forest, rechunk,
        the ring tiers), and even under an ``always`` warning filter the
        process must see the degrade exactly ONCE, not once per site."""
        from dislib_tpu.ops import pallas_kernels as _pk
        monkeypatch.setattr(_pk, "_AVAILABLE", False)
        monkeypatch.setattr(_ov, "_WARN_REGISTRY", {})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for _ in range(4):              # four "dispatch sites"
                assert _ov.resolve("pallas") == "db"
        hits = [x for x in w if "falling back" in str(x.message)]
        assert len(hits) == 1, f"expected one degrade warning, got {len(hits)}"

    def test_public_observability_entry(self, monkeypatch):
        monkeypatch.delenv("DSLIB_OVERLAP", raising=False)
        assert ds.overlap_schedule() == "db"


# ---------------------------------------------------------------------------
# 2. the shared pipeline helper: same folds, either order
# ---------------------------------------------------------------------------

class TestPanelPipeline:
    @pytest.mark.parametrize("steps", [1, 2, 3, 5])
    def test_bit_equal_and_order_preserving(self, steps):
        vals = jnp.asarray(np.random.RandomState(3).rand(8, 4)
                           .astype(np.float32))

        def fetch(t, prev):
            return vals[t]

        def consume(t, acc, pan):
            # non-commutative fold: order changes the bits, so equality
            # proves the schedules consume panels identically.  add THEN
            # scale — a mul+add chain could legally FMA-contract
            # differently in the two compiled programs (the fusion
            # layer's documented ±1-ulp divergence), which would test
            # XLA, not the pipeline
            return (acc + pan) * (1.0 + (t + 1) * 0.001)

        acc0 = jnp.zeros((4,), jnp.float32)
        seq = _ov.panel_pipeline(steps, vals[0], fetch, consume, acc0, False)
        db = _ov.panel_pipeline(steps, vals[0], fetch, consume, acc0, True)
        np.testing.assert_array_equal(np.asarray(seq), np.asarray(db))
        # oracle: explicit in-order fold
        acc = acc0
        for t in range(steps):
            acc = consume(t, acc, vals[t])
        np.testing.assert_array_equal(np.asarray(seq), np.asarray(acc))

    def test_zero_steps_is_identity(self):
        acc0 = jnp.ones((2,))
        for ov in (False, True):
            out = _ov.panel_pipeline(0, None, None, None, acc0, ov)
            assert out is acc0


# ---------------------------------------------------------------------------
# 2b. the host-loop pipeline (round 17: panel_pipeline's discipline
#     lifted to the fit drivers' dispatch→read sequences)
# ---------------------------------------------------------------------------

class TestHostPipeline:
    @pytest.mark.parametrize("steps", [0, 1, 2, 5])
    def test_same_pairs_same_order_both_schedules(self, steps):
        logs = {}
        for ov in (False, True):
            calls = []

            def fetch(t):
                calls.append(("fetch", t))
                return t * 10

            def consume(t, h):
                calls.append(("consume", t))
                assert h == t * 10, "handle paired with the wrong step"
                return h + t

            out = _ov.host_pipeline(steps, fetch, consume, overlap=ov)
            assert out == [t * 11 for t in range(steps)]
            logs[ov] = calls
        # both schedules evaluate the same consume(t, fetch(t)) pairs in
        # the same consume order (bit-equal by construction); what the
        # pipelined order changes is ONLY the issue point — fetch(t+1)
        # lands before consume(t), where the strict chain interleaves
        consumed = [c for c in logs[True] if c[0] == "consume"]
        assert consumed == [c for c in logs[False] if c[0] == "consume"]
        if steps >= 2:
            assert logs[True].index(("fetch", 1)) \
                < logs[True].index(("consume", 0))
            assert logs[False].index(("consume", 0)) \
                < logs[False].index(("fetch", 1))

    def test_exactly_one_extra_step_in_flight(self):
        for ov, want_peak in ((False, 1), (True, 2)):
            live = {"now": 0, "peak": 0}

            def fetch(t):
                live["now"] += 1
                live["peak"] = max(live["peak"], live["now"])
                return t

            def consume(t, h):
                live["now"] -= 1
                return h

            _ov.host_pipeline(6, fetch, consume, overlap=ov)
            assert live["now"] == 0, "a step was never drained"
            assert live["peak"] == want_peak, \
                (ov, live["peak"], "pipelined carry must hold exactly ONE "
                                   "extra in-flight step")

    def test_csvm_batched_level_routed_and_counted(self, monkeypatch):
        """A partition cap + tiny solve budget force the CSVM level solve
        into multiple batches — the batch loop must pipeline through the
        host-loop router (counter-observable) and both schedules must
        pick the same support vectors."""
        import scipy.sparse as sp
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.data.sparse import SparseArray
        rs = np.random.RandomState(7)
        m_sp = sp.random(200, 24, density=0.08, format="coo",
                         random_state=rs, dtype=np.float32)
        row_sum = np.asarray(m_sp.sum(axis=1)).ravel()
        y = ds.array((row_sum > np.median(row_sum))
                     .astype(np.float32).reshape(-1, 1))
        monkeypatch.setenv("DSLIB_CSVM_MAX_PARTITION", "64")
        monkeypatch.setenv("DSLIB_CSVM_SOLVE_BUDGET", str(1 << 16))
        svs = {}
        for sched in ("db", "seq"):
            monkeypatch.setenv("DSLIB_OVERLAP", sched)
            _prof.reset_counters()
            est = CascadeSVM(cascade_arity=2, max_iter=2, c=1.0,
                             gamma=0.1).fit(SparseArray.from_scipy(m_sp), y)
            sc = _prof.schedule_counters()
            assert sc.get(f"csvm_batches:{sched}", 0) >= 1, (sched, sc)
            svs[sched] = np.sort(np.asarray(est._sv_idx))
        np.testing.assert_array_equal(svs["db"], svs["seq"])

    def test_forest_snapshot_and_adopt_routed_and_counted(
            self, tmp_path, monkeypatch, rng):
        """A checkpointed forest fit drains its per-level snapshot fetches
        and the adoption reads through the host-loop router — both sites
        counter-observable, predictions bit-equal across schedules."""
        from dislib_tpu.trees import RandomForestClassifier
        from dislib_tpu.utils.checkpoint import FitCheckpoint
        x = rng.rand(200, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32).reshape(-1, 1)
        probs = {}
        for sched in ("db", "seq"):
            monkeypatch.setenv("DSLIB_OVERLAP", sched)
            _prof.reset_counters()
            f = RandomForestClassifier(n_estimators=2, random_state=0).fit(
                ds.array(x), ds.array(y),
                checkpoint=FitCheckpoint(
                    str(tmp_path / f"ck_{sched}"), every=1))
            probs[sched] = np.asarray(
                f.predict_proba(ds.array(x)).collect())
            sc = _prof.schedule_counters()
            assert sc.get(f"forest_snapshot:{sched}", 0) >= 1, (sched, sc)
            assert sc.get(f"forest_adopt:{sched}", 0) >= 1, (sched, sc)
        np.testing.assert_array_equal(probs["db"], probs["seq"])


# ---------------------------------------------------------------------------
# 3. schedule-equivalence grid: SUMMA
# ---------------------------------------------------------------------------

class TestSummaSchedules:
    @pytest.mark.parametrize("grid", [(4, 2), (2, 4)])
    @pytest.mark.parametrize("policy", ["float32", "bfloat16"])
    def test_db_bit_equals_seq(self, grid, policy):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        ds.init(grid)
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        pol = px.resolve(policy)
        db = np.asarray(summa_matmul(a._data, b._data, mesh, pol,
                                     overlap="db"))
        seq = np.asarray(summa_matmul(a._data, b._data, mesh, pol,
                                      overlap="seq"))
        np.testing.assert_array_equal(db, seq)
        # absolute correctness vs the host oracle
        oracle = _mk((96, 64)) @ _mk((64, 80), seed=1)
        tol = 2e-2 if policy == "bfloat16" else 1e-5
        np.testing.assert_allclose(db[:96, :80], oracle, rtol=tol,
                                   atol=tol * np.abs(oracle).max())

    def test_f64_x64_mode(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        with jax.enable_x64(True):
            x = _mk((32, 32)).astype(np.float64)
            ad = jax.device_put(
                np.pad(x, ((0, 0), (0, 0))), _mesh.data_sharding())
            db = np.asarray(summa_matmul(ad, ad, mesh, px.FLOAT32,
                                         overlap="db"))
            seq = np.asarray(summa_matmul(ad, ad, mesh, px.FLOAT32,
                                          overlap="seq"))
            assert db.dtype == np.float64   # f32 floor passes f64 through
            np.testing.assert_array_equal(db, seq)
            np.testing.assert_allclose(db, x @ x, rtol=1e-12)

    def test_one_dispatch_per_schedule(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        for ov in ("db", "seq"):
            summa_matmul(a._data, b._data, mesh, px.FLOAT32, overlap=ov)
            _prof.reset_counters()
            summa_matmul(a._data, b._data, mesh, px.FLOAT32, overlap=ov)
            assert _prof.dispatch_count() == 1, \
                f"summa overlap={ov} is not one dispatch"

    def test_env_routes_matmul_and_counts_schedule(self, monkeypatch):
        skip_unless_devices(8)
        ds.init((4, 2))
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        monkeypatch.setenv("DSLIB_OVERLAP", "seq")
        _prof.reset_counters()
        ds.matmul(a, b, algorithm="summa").force()
        assert _prof.schedule_counters().get("summa_matmul:seq") == 1
        monkeypatch.delenv("DSLIB_OVERLAP", raising=False)
        _prof.reset_counters()
        ds.matmul(a, b, algorithm="summa").force()
        assert _prof.schedule_counters().get("summa_matmul:db") == 1

    def test_db_green_under_debug_nans(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((32, 32))).force()
        jax.config.update("jax_debug_nans", True)
        try:
            out = summa_matmul(a._data, a._data, mesh, px.FLOAT32,
                               overlap="db")
            np.asarray(out)
        finally:
            jax.config.update("jax_debug_nans", False)


# ---------------------------------------------------------------------------
# 4. compiled-HLO overlap audit: the collective/compute dependence shape
# ---------------------------------------------------------------------------

def _while_body_def_use(hlo):
    """(def→operands map, all-reduce names, dot names) of the compiled
    while BODY computation that carries the panel loop (the one holding
    both an all-reduce and a dot)."""
    for m in re.finditer(r"body=%([\w\.\-]+)", hlo):
        name = m.group(1)
        start = hlo.index("%" + name + " ")
        block = hlo[start:hlo.index("\n}", start) + 2]
        if "all-reduce(" not in block or " dot(" not in block:
            continue
        defs, ars, dots = {}, [], []
        for line in block.splitlines():
            mm = re.match(r"\s*%([\w\.\-]+) = .*?\b([\w\-]+)\(", line)
            if not mm:
                continue
            dst, op = mm.group(1), mm.group(2)
            rhs = line.split("=", 1)[1]
            defs[dst] = [t for t in re.findall(r"%([\w\.\-]+)", rhs)
                         if t != dst]
            if op == "all-reduce":
                ars.append(dst)
            elif op == "dot":
                dots.append(dst)
        return defs, ars, dots
    raise AssertionError("no while body with all-reduce + dot in the HLO")


def _transitive_inputs(defs, roots):
    seen, stack = set(), list(roots)
    while stack:
        cur = stack.pop()
        for op in defs.get(cur, ()):
            if op not in seen:
                seen.add(op)
                stack.append(op)
    return seen


class TestCompiledOverlapAudit:
    """The tentpole's scheduling claim, verified on the compiled program:
    in the double-buffered body the prefetched panel's collectives feed
    the CARRY, not the dot — the dot and at least one all-reduce are
    data-independent, so the latency-hiding scheduler may overlap them.
    The sequential body is the contrast: every all-reduce feeds the dot
    (one strict chain), proving the audit is not vacuous."""

    def _hlo(self, overlap):
        from dislib_tpu.ops.summa import summa_matmul
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        return summa_matmul.lower(a._data, b._data, mesh, px.FLOAT32,
                                  overlap=overlap).compile().as_text()

    def test_db_decouples_collective_from_dot(self):
        skip_unless_devices(8)
        defs, ars, dots = _while_body_def_use(self._hlo("db"))
        assert ars and dots
        feeding = _transitive_inputs(defs, dots)
        free = [ar for ar in ars if ar not in feeding]
        assert free, (
            "double-buffered SUMMA body serialized every collective into "
            "the dot's chain — the pipeline structure did not survive "
            f"compilation (all-reduces: {ars})")

    def test_seq_is_a_strict_chain(self):
        skip_unless_devices(8)
        defs, ars, dots = _while_body_def_use(self._hlo("seq"))
        assert ars and dots
        feeding = _transitive_inputs(defs, dots)
        stray = [ar for ar in ars if ar not in feeding]
        assert not stray, (
            "sequential SUMMA body has a collective outside the dot "
            "chain — the seq baseline no longer is the strict-phase "
            f"schedule (stray: {stray})")


# ---------------------------------------------------------------------------
# 5. schedule-equivalence grid: panel rechunk
# ---------------------------------------------------------------------------

class TestRechunkSchedules:
    @pytest.mark.parametrize("dtype", [np.float32, np.int32])
    def test_db_bit_equals_seq(self, dtype):
        skip_unless_devices(8)
        from dislib_tpu.ops import rechunk as _rc
        ds.init((4, 2))
        x = (_mk((40, 12)) * 100).astype(dtype)
        a = ds.array(x).force()
        ds.init((2, 4))
        dst = _mesh.get_mesh()
        db = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst, 4,
                                          overlap="db"))
        seq = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst, 4,
                                           overlap="seq"))
        np.testing.assert_array_equal(db, seq)
        np.testing.assert_array_equal(db[:40, :12], x)

    def test_f64_x64_mode(self):
        skip_unless_devices(8)
        from dislib_tpu.ops import rechunk as _rc
        with jax.enable_x64(True):
            ds.init((4, 2))
            x = _mk((24, 8)).astype(np.float64)
            a = ds.array(x, dtype=np.float64).force()
            ds.init((2, 4))
            dst = _mesh.get_mesh()
            db = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst, 2,
                                              overlap="db"))
            seq = np.asarray(_rc.panel_rechunk(a._data, a.shape, dst, 2,
                                               overlap="seq"))
            assert db.dtype == np.float64
            np.testing.assert_array_equal(db, seq)

    def test_one_dispatch_and_schedule_counter(self):
        skip_unless_devices(8)
        from dislib_tpu.ops import rechunk as _rc
        ds.init((4, 2))
        a = ds.array(_mk((40, 12))).force()
        ds.init((2, 4))
        dst = _mesh.get_mesh()
        _rc.panel_rechunk(a._data, a.shape, dst, 4, overlap="db")  # warm
        _prof.reset_counters()
        _rc.panel_rechunk(a._data, a.shape, dst, 4, overlap="db")
        assert _prof.dispatch_count() == 1
        assert _prof.schedule_counters().get("rechunk_panels:db") == 1

    def test_db_poisoned_pad_rezeroes(self):
        """Poisoned-pad regression for the NEW schedule: the
        double-buffered exchange rebuilds pads from a zero canvas."""
        skip_unless_devices(8)
        ds.init((4, 2))
        x = _mk((20, 6), seed=7)
        a = ds.array(x).force()
        bad = a._data.at[20:, :].set(jnp.nan).at[:, 6:].set(jnp.inf)
        from dislib_tpu.data.array import Array
        a_bad = Array(bad, (20, 6))
        ds.init((2, 4))
        out = ds.rechunk(a_bad, schedule="panels", overlap="db")
        full = np.asarray(out._data)
        np.testing.assert_array_equal(full[:20, :6], x)
        assert np.all(full[20:] == 0) and np.all(full[:, 6:] == 0)

    def test_memory_analysis_reports_db_budget(self):
        skip_unless_devices(8)
        from dislib_tpu.ops import rechunk as _rc
        ds.init((4, 2))
        a = ds.array(_mk((64, 16))).force()
        ds.init((2, 4))
        dst = _mesh.get_mesh()
        ma_db = _rc.panel_memory_analysis(a._data, a.shape, dst, 4,
                                          overlap="db")
        ma_seq = _rc.panel_memory_analysis(a._data, a.shape, dst, 4,
                                           overlap="seq")
        assert ma_db["overlap"] == "db" and ma_seq["overlap"] == "seq"
        # the documented analytic budget: exactly one extra in-flight
        # panel for the double buffer
        panel = ma_db["in_bytes"] // ma_db["panels"]
        assert ma_db["analytic_temp_bytes"] \
            == ma_seq["analytic_temp_bytes"] + panel
        if ma_db["peak_live_ratio"] is not None:
            k = 4
            assert ma_db["peak_live_ratio"] <= min(1 + 2 / k, 1.5), \
                "double-buffered peak-live exceeds the documented bound"


# ---------------------------------------------------------------------------
# 6. schedule-equivalence grid: ring kernels + estimators
# ---------------------------------------------------------------------------

class TestRingSchedules:
    def test_kneighbors_db_bit_equals_seq(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.ring import ring_kneighbors
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        q = ds.array(_mk((37, 5))).force()
        f = ds.array(_mk((53, 5), seed=1)).force()
        d_db, i_db = ring_kneighbors(q._data, f._data, mesh, 5, 53,
                                     overlap="db")
        d_seq, i_seq = ring_kneighbors(q._data, f._data, mesh, 5, 53,
                                       overlap="seq")
        np.testing.assert_array_equal(np.asarray(d_db), np.asarray(d_seq))
        np.testing.assert_array_equal(np.asarray(i_db), np.asarray(i_seq))

    def test_neigh_count_min_db_bit_equals_seq(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.ring import ring_neigh_count_min
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((48, 5))).force()
        mp = a._data.shape[0]
        ids = jnp.arange(mp, dtype=jnp.int32)
        valid = ids < 48
        outs = {}
        for ov in ("db", "seq"):
            c, mn = ring_neigh_count_min(a._data, jnp.float32(0.3), ids,
                                         valid, jnp.int32(mp), mesh,
                                         overlap=ov)
            outs[ov] = (np.asarray(c), np.asarray(mn))
        np.testing.assert_array_equal(outs["db"][0], outs["seq"][0])
        np.testing.assert_array_equal(outs["db"][1], outs["seq"][1])

    def test_kneighbors_estimator_one_dispatch_and_env_routing(
            self, monkeypatch):
        skip_unless_devices(8)
        ds.init((4, 2))
        f = ds.array(_mk((64, 4))).force()
        q = ds.array(_mk((16, 4), seed=2)).force()
        nn = ds.NearestNeighbors(n_neighbors=3, ring=True).fit(f)
        nn.kneighbors(q)                                     # warm
        _prof.reset_counters()
        nn.kneighbors(q)
        assert _prof.counters()["dispatch_by"].get("ring_kneighbors") == 1
        assert _prof.schedule_counters().get("ring_kneighbors:db") == 1
        monkeypatch.setenv("DSLIB_OVERLAP", "seq")
        _prof.reset_counters()
        d_seq, i_seq = nn.kneighbors(q)
        assert _prof.schedule_counters().get("ring_kneighbors:seq") == 1
        monkeypatch.delenv("DSLIB_OVERLAP", raising=False)
        d_db, i_db = nn.kneighbors(q)
        np.testing.assert_array_equal(np.asarray(i_db.collect()),
                                      np.asarray(i_seq.collect()))
        np.testing.assert_array_equal(np.asarray(d_db.collect()),
                                      np.asarray(d_seq.collect()))

    def test_ring_dbscan_schedules_agree(self, monkeypatch):
        skip_unless_devices(8)
        from dislib_tpu.cluster import dbscan as dbmod
        ds.init((4, 2))
        monkeypatch.setattr(dbmod, "_RING", True)
        x = np.vstack([_mk((40, 4)), _mk((40, 4), seed=1) + 3.0]) \
            .astype(np.float32)
        labels = {}
        for ov in ("db", "seq"):
            monkeypatch.setenv("DSLIB_OVERLAP", ov)
            _prof.reset_counters()
            model = ds.DBSCAN(eps=0.8, min_samples=3).fit(ds.array(x))
            assert any(k == f"ring_neigh:{ov}"
                       for k in _prof.schedule_counters()), \
                f"dbscan ring tier did not record schedule {ov}"
            labels[ov] = model.labels_.copy()
        np.testing.assert_array_equal(labels["db"], labels["seq"])

    def test_ring_daura_schedules_agree(self, monkeypatch):
        skip_unless_devices(8)
        from dislib_tpu.cluster import daura as damod
        ds.init((4, 2))
        monkeypatch.setattr(damod, "_RING", True)
        x = _mk((60, 6), seed=5)
        labels = {}
        for ov in ("db", "seq"):
            monkeypatch.setenv("DSLIB_OVERLAP", ov)
            model = ds.Daura(cutoff=0.45).fit(ds.array(x))
            labels[ov] = model.labels_.copy()
        np.testing.assert_array_equal(labels["db"], labels["seq"])

    def test_db_poisoned_fit_pad_rows_stay_masked(self):
        """Poisoned-pad regression for the db ring schedule: garbage in
        the fitted backing's pad rows must never become a neighbor
        (the ids >= m_fit mask, preserved by the pipelined fold)."""
        skip_unless_devices(8)
        from dislib_tpu.ops.ring import ring_kneighbors
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        q = ds.array(_mk((16, 4))).force()
        f = ds.array(_mk((20, 4), seed=1)).force()
        clean = ring_kneighbors(q._data, f._data, mesh, 3, 20, overlap="db")
        # pad rows moved to the query cloud's center: unmasked, they
        # would beat most real rows into the top-k
        poisoned = f._data.at[20:, :].set(0.5)
        got = ring_kneighbors(q._data, poisoned, mesh, 3, 20, overlap="db")
        np.testing.assert_array_equal(np.asarray(clean[1]),
                                      np.asarray(got[1]))
        np.testing.assert_array_equal(np.asarray(clean[0]),
                                      np.asarray(got[0]))

    def test_db_green_under_debug_nans(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.ring import ring_neigh_count_min
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((24, 4))).force()
        mp = a._data.shape[0]
        ids = jnp.arange(mp, dtype=jnp.int32)
        jax.config.update("jax_debug_nans", True)
        try:
            c, _ = ring_neigh_count_min(a._data, jnp.float32(0.3), ids,
                                        ids < 24, jnp.int32(mp), mesh,
                                        overlap="db")
            np.asarray(c)
        finally:
            jax.config.update("jax_debug_nans", False)


# ---------------------------------------------------------------------------
# 7. the Pallas fallback route
# ---------------------------------------------------------------------------

class TestPallasRoute:
    def test_kernels_available_on_this_rig(self):
        from dislib_tpu.ops import pallas_kernels as _pk
        assert _pk.available(), \
            "pallas interpret mode should run on the CPU rig"

    def test_panel_gemm_matches_pdot(self):
        from dislib_tpu.ops import pallas_kernels as _pk
        a = jnp.asarray(_mk((48, 32)))
        b = jnp.asarray(_mk((32, 40), seed=1))
        got = np.asarray(_pk.panel_gemm(a, b, px.FLOAT32))
        want = np.asarray(px.pdot(a, b, px.FLOAT32))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
        assert got.dtype == want.dtype

    def test_distances_matches_xla_formulation(self):
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.ops.base import distances_sq
        a = jnp.asarray(_mk((24, 6)))
        b = jnp.asarray(_mk((20, 6), seed=1))
        got = np.asarray(_pk.distances_sq(a, b))
        want = np.asarray(distances_sq(np.asarray(a), np.asarray(b)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert (got >= 0).all()

    def test_summa_pallas_schedule_matches(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        db = np.asarray(summa_matmul(a._data, b._data, mesh, px.FLOAT32,
                                     overlap="db"))
        pl = np.asarray(summa_matmul(a._data, b._data, mesh, px.FLOAT32,
                                     overlap="pallas"))
        np.testing.assert_allclose(pl, db, rtol=1e-6,
                                   atol=1e-6 * np.abs(db).max())

    def test_ring_pallas_schedule_matches(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.ring import ring_neigh_count_min
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((48, 5))).force()
        mp = a._data.shape[0]
        ids = jnp.arange(mp, dtype=jnp.int32)
        valid = ids < 48
        c_db, m_db = ring_neigh_count_min(a._data, jnp.float32(0.3), ids,
                                          valid, jnp.int32(mp), mesh,
                                          overlap="db")
        c_pl, m_pl = ring_neigh_count_min(a._data, jnp.float32(0.3), ids,
                                          valid, jnp.int32(mp), mesh,
                                          overlap="pallas")
        np.testing.assert_array_equal(np.asarray(c_db), np.asarray(c_pl))
        np.testing.assert_array_equal(np.asarray(m_db), np.asarray(m_pl))

    def test_distances_threads_explicit_precision(self):
        """Regression: the pallas branch of ``ops/base.distances_sq`` must
        pass the caller's explicit MXU precision to the cross GEMM, not
        silently drop it (review-found)."""
        from dislib_tpu.ops.base import distances_sq
        a = jnp.asarray(_mk((24, 6)))
        b = jnp.asarray(_mk((20, 6), seed=1))
        got = np.asarray(distances_sq(a, b, precision="highest",
                                      use_pallas=True))
        want = np.asarray(distances_sq(a, b, precision="highest"))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("route", ["db", "pallas"])
    def test_tiled_dbscan_routes_and_matches(self, monkeypatch, route):
        """The single-device tiled tier has no collective to overlap, but
        ``DSLIB_OVERLAP=pallas`` must still pick the Pallas inner kernel
        (observable via the ``tiled_neigh`` schedule counter) and cluster
        identically (review-found: the knob used to be a silent no-op
        here)."""
        from dislib_tpu.cluster import dbscan as dbmod
        from dislib_tpu.ops import tiled as _tiled
        if route == "pallas" and _ov.resolve("pallas") != "pallas":
            pytest.skip("pallas unavailable on this backend")
        monkeypatch.setattr(dbmod, "_RING", False)
        monkeypatch.setattr(dbmod, "_DENSE_MAX", 0)
        monkeypatch.setattr(_tiled, "TILE", 64)
        x = np.vstack([_mk((25, 4)), _mk((25, 4), seed=1) + 3.0]) \
            .astype(np.float32)
        monkeypatch.setenv("DSLIB_OVERLAP", route)
        _prof.reset_counters()
        model = ds.DBSCAN(eps=0.8, min_samples=3).fit(ds.array(x))
        assert _prof.schedule_counters().get(f"tiled_neigh:{route}"), \
            f"dbscan tiled tier did not record schedule {route}"
        oracle = ds.DBSCAN(eps=0.8, min_samples=3)
        monkeypatch.setenv("DSLIB_OVERLAP", "seq")
        oracle.fit(ds.array(x))
        np.testing.assert_array_equal(model.labels_, oracle.labels_)

    def test_tiled_daura_routes_pallas(self, monkeypatch):
        from dislib_tpu.cluster import daura as damod
        from dislib_tpu.ops import tiled as _tiled
        if _ov.resolve("pallas") != "pallas":
            pytest.skip("pallas unavailable on this backend")
        monkeypatch.setattr(damod, "_RING", False)
        monkeypatch.setattr(damod, "_DENSE_MAX", 0)
        monkeypatch.setattr(_tiled, "TILE", 64)
        x = _mk((40, 6), seed=5)
        monkeypatch.setenv("DSLIB_OVERLAP", "pallas")
        _prof.reset_counters()
        model = ds.Daura(cutoff=0.45).fit(ds.array(x))
        assert _prof.schedule_counters().get("tiled_neigh:pallas"), \
            "daura tiled tier did not record the pallas schedule"
        oracle = ds.Daura(cutoff=0.45)
        monkeypatch.setenv("DSLIB_OVERLAP", "db")
        oracle.fit(ds.array(x))
        np.testing.assert_array_equal(model.labels_, oracle.labels_)


# ---------------------------------------------------------------------------
# 8. the DSLIB_SUMMA_MIN_DIM router knob
# ---------------------------------------------------------------------------

class TestSummaMinDimKnob:
    def test_env_knob_routes_small_dims_to_summa(self, monkeypatch):
        skip_unless_devices(8)
        ds.init((4, 2))
        a = ds.array(_mk((64, 64))).force()
        b = ds.array(_mk((64, 64), seed=1)).force()
        # default gate (256): a 64-dim CONCRETE product stays on the
        # fusion-graph XLA path
        monkeypatch.delenv("DSLIB_SUMMA_MIN_DIM", raising=False)
        out = ds.matmul(a, b)
        assert out.is_lazy, "small concrete product left the fusion graph"
        # knob lowered: the same product auto-routes to SUMMA
        monkeypatch.setenv("DSLIB_SUMMA_MIN_DIM", "16")
        _prof.reset_counters()
        out = ds.matmul(a, b)
        assert not out.is_lazy
        assert _prof.counters()["dispatch_by"].get("summa_matmul") == 1
        assert any(k.startswith("summa_matmul:")
                   for k in _prof.schedule_counters())

    def test_env_knob_respected_by_module_default(self, monkeypatch):
        from dislib_tpu.math import base as mb
        monkeypatch.delenv("DSLIB_SUMMA_MIN_DIM", raising=False)
        assert mb._summa_min_dim() == mb._SUMMA_MIN_DIM
        monkeypatch.setenv("DSLIB_SUMMA_MIN_DIM", "512")
        assert mb._summa_min_dim() == 512


# ---------------------------------------------------------------------------
# 9. comm-only probes: same collectives, no compute (bench denominator)
# ---------------------------------------------------------------------------

class TestCommOnlyProbes:
    def test_probes_run_and_shape(self):
        skip_unless_devices(8)
        from dislib_tpu.ops.summa import summa_matmul
        from dislib_tpu.ops.ring import ring_kneighbors
        from dislib_tpu.ops import rechunk as _rc
        ds.init((4, 2))
        mesh = _mesh.get_mesh()
        a = ds.array(_mk((96, 64))).force()
        b = ds.array(_mk((64, 80), seed=1)).force()
        out = summa_matmul(a._data, b._data, mesh, px.FLOAT32,
                           overlap="seq", comm_only=True)
        assert out.shape == (4, 2) and np.isfinite(np.asarray(out)).all()
        f = ds.array(_mk((40, 8), seed=2)).force()
        q = ds.array(_mk((16, 8), seed=3)).force()
        out = ring_kneighbors(q._data, f._data, mesh, 3, 40,
                              overlap="seq", comm_only=True)
        assert out.shape == (4, 2)
        ds.init((2, 4))
        dst = _mesh.get_mesh()
        probe = _rc.panel_comm_probe(a._data, a.shape, dst, 4)
        assert np.isfinite(np.asarray(probe)).all()
