"""Dense-estimator sweep tests (reference: test_gm, test_preprocessing,
test_linear_regression, test_lasso, test_admm, test_knn,
test_nearest_neighbors — SURVEY.md §5 oracle pattern vs sklearn/NumPy)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import GaussianMixture
from dislib_tpu.preprocessing import StandardScaler, MinMaxScaler
from dislib_tpu.regression import LinearRegression, Lasso
from dislib_tpu.neighbors import NearestNeighbors
from dislib_tpu.classification import KNeighborsClassifier


def _blobs(rng, n=300, d=4, k=3, spread=0.2):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + spread * rng.randn(n // k, d) for i in range(k)])
    labels = np.repeat(np.arange(k), n // k)
    return x.astype(np.float32), labels


class TestGaussianMixture:
    @pytest.mark.parametrize("cov_type", ["full", "tied", "diag", "spherical"])
    def test_recovers_blobs(self, rng, cov_type):
        x, true_labels = _blobs(rng, n=300, d=3, k=3)
        gm = GaussianMixture(n_components=3, covariance_type=cov_type,
                             max_iter=100, random_state=0)
        labels = gm.fit_predict(ds.array(x)).collect().ravel().astype(int)
        for c in range(3):
            assert len(np.unique(labels[true_labels == c])) == 1, cov_type
        assert gm.converged_
        assert np.isclose(gm.weights_.sum(), 1.0, atol=1e-5)

    def test_vs_sklearn_loglik(self, rng):
        from sklearn.mixture import GaussianMixture as SkGM
        x, _ = _blobs(rng, n=240, d=4, k=2)
        gm = GaussianMixture(n_components=2, max_iter=200, tol=1e-6,
                             random_state=0).fit(ds.array(x))
        sk = SkGM(n_components=2, max_iter=200, tol=1e-6, n_init=1,
                  random_state=0).fit(x)
        # both should reach (nearly) the same optimum on well-separated blobs
        assert gm.lower_bound_ == pytest.approx(sk.lower_bound_, rel=1e-3)

    def test_explicit_means_init(self, rng):
        x, _ = _blobs(rng, n=120, d=3, k=2)
        means0 = x[[0, 60]]
        gm = GaussianMixture(n_components=2, means_init=means0, max_iter=50,
                             random_state=0).fit(ds.array(x))
        assert gm.converged_

    def test_bad_cov_type(self, rng):
        with pytest.raises(ValueError):
            GaussianMixture(covariance_type="nope").fit(ds.array(rng.rand(10, 2)))


class TestScalers:
    def test_standard_scaler_vs_sklearn(self, rng):
        from sklearn.preprocessing import StandardScaler as SkSS
        x = rng.rand(50, 7).astype(np.float32) * 5
        a = ds.array(x, block_size=(9, 3))
        got = StandardScaler().fit_transform(a).collect()
        want = SkSS().fit_transform(x)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_standard_scaler_roundtrip(self, rng):
        x = rng.rand(30, 4).astype(np.float32)
        sc = StandardScaler()
        t = sc.fit_transform(ds.array(x))
        np.testing.assert_allclose(sc.inverse_transform(t).collect(), x,
                                   rtol=1e-3, atol=1e-4)

    def test_minmax_scaler(self, rng):
        from sklearn.preprocessing import MinMaxScaler as SkMM
        x = rng.randn(40, 5).astype(np.float32)
        got = MinMaxScaler().fit_transform(ds.array(x)).collect()
        want = SkMM().fit_transform(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_minmax_range(self, rng):
        x = rng.randn(40, 5).astype(np.float32)
        sc = MinMaxScaler(feature_range=(-1, 1))
        t = sc.fit_transform(ds.array(x)).collect()
        assert t.min() >= -1 - 1e-5 and t.max() <= 1 + 1e-5
        np.testing.assert_allclose(sc.inverse_transform(
            sc.transform(ds.array(x))).collect(), x, rtol=1e-3, atol=1e-4)


class TestLinearRegression:
    def test_vs_numpy_lstsq(self, rng):
        x = rng.rand(80, 6).astype(np.float32)
        w = rng.randn(6, 1).astype(np.float32)
        y = x @ w + 0.5 + 0.01 * rng.randn(80, 1).astype(np.float32)
        lr = LinearRegression().fit(ds.array(x), ds.array(y))
        xa = np.hstack([x, np.ones((80, 1), np.float32)])
        sol = np.linalg.lstsq(xa, y, rcond=None)[0]
        np.testing.assert_allclose(lr.coef_, sol[:-1], atol=1e-3)
        np.testing.assert_allclose(lr.intercept_, sol[-1], atol=1e-3)
        assert lr.score(ds.array(x), ds.array(y)) > 0.99

    def test_no_intercept(self, rng):
        x = rng.rand(50, 3).astype(np.float32)
        y = (x @ np.array([[1.0], [2.0], [3.0]], np.float32))
        lr = LinearRegression(fit_intercept=False).fit(ds.array(x), ds.array(y))
        np.testing.assert_allclose(lr.coef_.ravel(), [1, 2, 3], atol=1e-3)
        np.testing.assert_allclose(lr.intercept_, [0.0])

    def test_multioutput(self, rng):
        x = rng.rand(60, 4).astype(np.float32)
        w = rng.randn(4, 3).astype(np.float32)
        y = x @ w
        lr = LinearRegression(fit_intercept=False).fit(ds.array(x), ds.array(y))
        np.testing.assert_allclose(lr.coef_, w, atol=1e-3)
        pred = lr.predict(ds.array(x)).collect()
        np.testing.assert_allclose(pred, y, atol=1e-3)


class TestLasso:
    def test_sparse_recovery(self, rng):
        # y depends on 3 of 20 features; lasso should zero most others
        n, d = 200, 20
        x = rng.randn(n, d).astype(np.float32)
        w = np.zeros((d, 1), np.float32)
        w[[2, 7, 15]] = [[2.0], [-3.0], [1.5]]
        y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
        las = Lasso(lmbd=5.0, rho=1.0, max_iter=300, atol=1e-5, rtol=1e-4)
        las.fit(ds.array(x), ds.array(y))
        coef = las.coef_
        assert abs(coef[2] - 2.0) < 0.3
        assert abs(coef[7] + 3.0) < 0.3
        assert abs(coef[15] - 1.5) < 0.3
        others = np.delete(coef, [2, 7, 15])
        assert np.abs(others).max() < 0.15
        assert las.score(ds.array(x), ds.array(y)) > 0.95

    def test_vs_sklearn(self, rng):
        from sklearn.linear_model import Lasso as SkLasso
        n, d = 160, 8
        x = rng.randn(n, d).astype(np.float32)
        y = (x[:, :2] @ np.array([3.0, -2.0], np.float32)).reshape(-1, 1)
        alpha = 0.1
        las = Lasso(lmbd=alpha * n, rho=1.0, max_iter=500, atol=1e-6, rtol=1e-5)
        las.fit(ds.array(x), ds.array(y))
        sk = SkLasso(alpha=alpha).fit(x, y.ravel())
        np.testing.assert_allclose(las.coef_, sk.coef_, atol=0.05)


class TestADMM:
    def test_identity_prox_is_least_squares(self, rng):
        from dislib_tpu.optimization import ADMM
        x = rng.randn(64, 5).astype(np.float32)
        w = rng.randn(5).astype(np.float32)
        y = (x @ w).reshape(-1, 1)
        admm = ADMM(rho=1.0, max_iter=200, abstol=1e-6, reltol=1e-5)
        admm.fit(ds.array(x), ds.array(y))
        np.testing.assert_allclose(admm.z_, w, atol=1e-2)
        assert admm.converged_


class TestNeighbors:
    def test_vs_sklearn(self, rng):
        from sklearn.neighbors import NearestNeighbors as SkNN
        x = rng.rand(90, 5).astype(np.float32)
        q = rng.rand(17, 5).astype(np.float32)
        nn = NearestNeighbors(n_neighbors=4).fit(ds.array(x))
        d, i = nn.kneighbors(ds.array(q))
        sk = SkNN(n_neighbors=4, algorithm="brute").fit(x)
        sd, si = sk.kneighbors(q)
        np.testing.assert_allclose(d.collect(), sd, rtol=1e-3, atol=1e-4)
        np.testing.assert_array_equal(i.collect().astype(int), si)

    def test_self_query(self, rng):
        x = rng.rand(40, 3).astype(np.float32)
        nn = NearestNeighbors(n_neighbors=1).fit(ds.array(x))
        d, i = nn.kneighbors(ds.array(x))
        np.testing.assert_array_equal(i.collect().ravel().astype(int), np.arange(40))
        np.testing.assert_allclose(d.collect().ravel(), 0, atol=1e-3)

    def test_k_too_large(self, rng):
        nn = NearestNeighbors(n_neighbors=99).fit(ds.array(rng.rand(5, 2)))
        with pytest.raises(ValueError):
            nn.kneighbors(ds.array(rng.rand(3, 2)))


class TestKNNClassifier:
    def test_vs_sklearn(self, rng):
        from sklearn.neighbors import KNeighborsClassifier as SkKNN
        x, labels = _blobs(rng, n=150, d=4, k=3)
        q, _ = _blobs(rng, n=30, d=4, k=3)
        y = labels.astype(np.float32).reshape(-1, 1)
        knn = KNeighborsClassifier(n_neighbors=5).fit(ds.array(x), ds.array(y))
        got = knn.predict(ds.array(q)).collect().ravel()
        sk = SkKNN(n_neighbors=5).fit(x, labels)
        want = sk.predict(q)
        assert (got == want).mean() > 0.95
        assert knn.score(ds.array(x), ds.array(y)) > 0.95

    def test_distance_weights(self, rng):
        x, labels = _blobs(rng, n=90, d=3, k=3)
        y = labels.astype(np.float32).reshape(-1, 1)
        knn = KNeighborsClassifier(n_neighbors=3, weights="distance")
        knn.fit(ds.array(x), ds.array(y))
        assert knn.score(ds.array(x), ds.array(y)) == 1.0


class TestReviewRegressions:
    """Locks in fixes from code review."""

    def test_scaler_large_mean_variance(self, rng):
        # mean ~1e4, std ~1: one-pass E[x²]−μ² would cancel in float32
        x = (1e4 + rng.randn(200, 3)).astype(np.float32)
        sc = StandardScaler().fit(ds.array(x))
        np.testing.assert_allclose(sc.var_.collect().ravel(), x.var(axis=0),
                                   rtol=0.05)
        t = sc.transform(ds.array(x)).collect()
        assert abs(t.std() - 1.0) < 0.05

    def test_knn_k_exceeds_samples(self, rng):
        x = rng.rand(5, 3).astype(np.float32)
        y = np.zeros((5, 1), np.float32)
        knn = KNeighborsClassifier(n_neighbors=10).fit(ds.array(x), ds.array(y))
        with pytest.raises(ValueError):
            knn.predict(ds.array(x))

    def test_admm_rejects_multitarget(self, rng):
        from dislib_tpu.optimization import ADMM
        with pytest.raises(ValueError):
            ADMM().fit(ds.array(rng.rand(8, 2)), ds.array(rng.rand(8, 2)))

    def test_neighbors_indices_are_int(self, rng):
        nn = NearestNeighbors(n_neighbors=2).fit(ds.array(rng.rand(10, 2)))
        _, i = nn.kneighbors(ds.array(rng.rand(4, 2)))
        assert np.issubdtype(i.collect().dtype, np.integer)


class TestObservability:
    """SURVEY §6 metrics row: per-iteration history_ with
    len(history_) == n_iter_ on every iterative estimator."""

    def test_kmeans_history(self, rng):
        from dislib_tpu.cluster import KMeans
        x = ds.array(rng.rand(100, 4).astype(np.float32))
        km = KMeans(n_clusters=3, random_state=0, max_iter=7, tol=0.0).fit(x)
        assert len(km.history_) == km.n_iter_ == 7
        assert np.all(np.diff(km.history_) <= 1e-3)  # inertia non-increasing

    def test_gmm_history_and_score(self, rng):
        from dislib_tpu.cluster import GaussianMixture
        x = ds.array(np.vstack([rng.randn(60, 3) - 4,
                                rng.randn(60, 3) + 4]).astype(np.float32))
        gm = GaussianMixture(n_components=2, max_iter=6, tol=0.0,
                             random_state=0).fit(x)
        assert len(gm.history_) == gm.n_iter_
        assert gm.history_[-1] == pytest.approx(gm.lower_bound_, rel=1e-5)
        # score = mean log-likelihood, matches the final lower bound here
        assert gm.score(x) == pytest.approx(gm.lower_bound_, rel=1e-3)

    def test_admm_history(self, rng):
        from dislib_tpu.optimization import ADMM
        x = rng.rand(64, 5).astype(np.float32)
        y = (x @ rng.rand(5).astype(np.float32))[:, None]
        # a nontrivial prox (L1 soft threshold) keeps z ≠ x even on a
        # SINGLE row shard — identity-prox consensus with one shard is
        # exact from iteration 1 (history all zero, nothing to assert),
        # which is precisely what the 1-chip TPU suite runs
        from dislib_tpu.optimization.admm import soft_threshold
        est = ADMM(max_iter=20, z_prox=soft_threshold,
                   prox_kappa=0.05).fit(ds.array(x), ds.array(y))
        assert len(est.history_) == est.n_iter_
        assert np.all(np.isfinite(est.history_))
        assert est.history_[-1] < est.history_[0]  # residual decreases

    def test_als_history(self, rng):
        from dislib_tpu.recommendation import ALS
        ratings = (rng.rand(40, 25) * (rng.rand(40, 25) < 0.4)).astype(np.float32)
        als = ALS(n_f=4, max_iter=5, tol=0.0, random_state=0).fit(
            ds.array(ratings))
        assert len(als.history_) == als.n_iter_ == 5
        assert als.history_[-1] == pytest.approx(als.rmse_, rel=1e-5)

    def test_verbose_logs(self, rng, caplog):
        import logging
        from dislib_tpu.cluster import KMeans
        x = ds.array(rng.rand(50, 3).astype(np.float32))
        with caplog.at_level(logging.INFO, logger="dslib.kmeans"):
            KMeans(n_clusters=2, random_state=0, verbose=True).fit(x)
        assert any("inertia" in r.message for r in caplog.records)
