"""Multi-process ("multi-host") integration: the library's distributed
bootstrap, per-host byte-range ingest, and a cross-process KMeans fit —
run for real across 2 OS processes × 4 virtual CPU devices with gloo
collectives (SURVEY §3.7 / §5: the reference exercised its cross-node path
with COMPSs workers as local processes; this is the same trick for DCN).

Skipped automatically on the real-TPU suite run (single-chip axon tunnel
cannot host a 2-process gloo job)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

def _mp_cpu_supported():
    """Cross-process collectives on the CPU backend: older jaxlibs raise
    'Multiprocess computations aren't implemented on the CPU backend', so
    the gloo rig is version-gated (DSLIB_FORCE_MP_TESTS=1 overrides)."""
    if os.environ.get("DSLIB_FORCE_MP_TESTS") == "1":
        return True
    from dislib_tpu.runtime.xla_flags import _jaxlib_version
    v = _jaxlib_version()
    return v is not None and v >= (0, 6, 0)


pytestmark = [
    pytest.mark.skipif(os.environ.get("DSLIB_TEST_TPU") == "1",
                       reason="multi-process CPU rig only"),
    pytest.mark.skipif(not _mp_cpu_supported(),
                       reason="this jaxlib's CPU backend lacks "
                              "multiprocess collectives"),
]

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_kmeans_matches_single(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.rand(96, 5).astype(np.float32)
    csv = str(tmp_path / "data.csv")
    np.savetxt(csv, data, delimiter=",", fmt="%.6f")
    # same matrix for the npy / dense-svmlight shard-local loaders (the
    # worker loads all three collective-free and cross-checks them)
    parsed0 = np.loadtxt(csv, delimiter=",", dtype=np.float32, ndmin=2)
    np.save(csv + ".npy", parsed0)
    with open(csv + ".svm", "w") as f:
        for i, row in enumerate(parsed0):
            feats = " ".join(f"{j + 1}:{v:.6f}"
                             for j, v in enumerate(row) if v != 0)
            f.write(f"{i % 2} {feats}\n")
    out = str(tmp_path / "result.json")
    port = _free_port()

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.path.dirname(_HERE)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(_HERE, "mp_worker.py"),
         str(r), "2", str(port), csv, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(stdout.decode())
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{outs[i]}"

    with open(out) as f:
        got = json.load(f)
    # oracle: parse + fit in-process on the same data
    parsed = np.loadtxt(csv, delimiter=",", dtype=np.float32, ndmin=2)
    assert got["shape"] == [96, 5]
    np.testing.assert_allclose(got["checksum"], parsed.sum(), rtol=1e-5)

    centers = np.asarray(parsed[:3], np.float64)
    for _ in range(5):
        d = ((parsed[:, None, :] - centers[None]) ** 2).sum(-1)
        lab = d.argmin(1)
        centers = np.stack([
            parsed[lab == j].mean(0) if (lab == j).any() else centers[j]
            for j in range(3)])
    np.testing.assert_allclose(np.asarray(got["centers"]), centers,
                               rtol=2e-3, atol=2e-3)

    # tp / sp / ring results crossed the process boundary correctly
    np.testing.assert_allclose(got["gram_trace"],
                               np.trace(parsed @ parsed.T), rtol=1e-4)
    assert got["qr_err"] < 1e-3
    assert got["shuffle_ok"], "all-to-all shuffle lost/changed rows across hosts"
    dd = ((parsed[:, None, :] - parsed[None]) ** 2).sum(-1)
    k3 = np.sqrt(np.maximum(np.sort(dd, axis=1)[:, :3], 0.0))
    np.testing.assert_allclose(got["ring_d_sum"], k3.sum(), rtol=1e-3)

    # sparse tier across the process boundary: BCOO KMeans matched the
    # dense path in-worker, and the sharded sparse-fit kNN stream matches
    # the host oracle
    assert got["sparse_centers_close"], \
        "multi-host sparse KMeans diverged from the dense path"
    xsp = parsed.copy()
    xsp[xsp < 0.5] = 0.0
    dsp = ((parsed[:, None, :] - xsp[None]) ** 2).sum(-1)
    k3s = np.sqrt(np.maximum(np.sort(dsp, axis=1)[:, :3], 0.0))
    np.testing.assert_allclose(got["sparse_knn_sum"], k3s.sum(), rtol=1e-3)


def _run_ckfit(tmp_path, csv, tag, crash_after, mode, nprocs):
    """Launch one checkpointed-fit job: ``mode`` 'crashfit' (flat
    (n·4, 1) mesh) or 'grid' (2-D (nprocs, 2) process mesh)."""
    out = str(tmp_path / f"{tag}.json")
    ck = str(tmp_path / f"{tag}.ck.npz")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.path.dirname(_HERE)
    if crash_after:
        env["DSLIB_TEST_CRASH_AFTER_SAVES"] = str(crash_after)
    else:
        env.pop("DSLIB_TEST_CRASH_AFTER_SAVES", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(_HERE, "mp_worker.py"), mode,
         str(r), str(nprocs), str(port), csv, ck, out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(nprocs)]
    rcs, outs = [], []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        rcs.append(p.returncode)
        outs.append(stdout.decode())
    return rcs, outs, out, ck


def _run_grid(tmp_path, csv, tag, crash_after, nprocs=4):
    return _run_ckfit(tmp_path, csv, tag, crash_after, "grid", nprocs)


def test_four_process_grid_mesh_and_resume(tmp_path):
    """Round-5 (SURVEY §3.7 cross-slice row, §6 fault tolerance): 4 real
    processes on a 2-D PROCESS mesh (4 rows × 2 cols, one mesh row per
    process).  KMeans + collect + checkpoint-resume + all_to_all shuffle
    all cross the 4-way process boundary; centers oracle'd against an
    in-process NumPy Lloyd run, and the kill+resume run must land on the
    uninterrupted run's centers exactly."""
    rng = np.random.RandomState(2)
    data = rng.rand(96, 5).astype(np.float32)
    csv = str(tmp_path / "data.csv")
    np.savetxt(csv, data, delimiter=",", fmt="%.6f")
    parsed = np.loadtxt(csv, delimiter=",", dtype=np.float32, ndmin=2)

    # uninterrupted run
    rcs, outs, out_ok, _ = _run_grid(tmp_path, csv, "ok", crash_after=0)
    assert rcs == [0, 0, 0, 0], outs
    with open(out_ok) as f:
        oracle = json.load(f)
    assert oracle["n_iter"] == 12
    assert oracle["shape"] == [96, 5]
    assert oracle["shuffle_ok"], "4-way all_to_all shuffle lost rows"
    np.testing.assert_allclose(oracle["checksum"], parsed.sum(), rtol=1e-5)

    # NumPy Lloyd oracle (same init = first 3 rows, 12 iterations)
    centers = np.asarray(parsed[:3], np.float64)
    for _ in range(12):
        d = ((parsed[:, None, :] - centers[None]) ** 2).sum(-1)
        lab = d.argmin(1)
        centers = np.stack([
            parsed[lab == j].mean(0) if (lab == j).any() else centers[j]
            for j in range(3)])
    np.testing.assert_allclose(np.asarray(oracle["centers"]), centers,
                               rtol=2e-3, atol=2e-3)

    # whole-job death after the 2nd durable snapshot (6 of 12 iters)
    rcs, outs, out_crash, ck = _run_grid(tmp_path, csv, "crash",
                                         crash_after=2)
    assert rcs == [17, 17, 17, 17], outs
    assert os.path.exists(ck) and not os.path.exists(out_crash)

    # resume across all 4 processes → identical final centers
    rcs, outs, out_res, _ = _run_grid(tmp_path, csv, "crash", crash_after=0)
    assert rcs == [0, 0, 0, 0], outs
    with open(out_res) as f:
        resumed = json.load(f)
    assert resumed["n_iter"] == 12
    np.testing.assert_allclose(np.asarray(resumed["centers"]),
                               np.asarray(oracle["centers"]),
                               rtol=1e-5, atol=1e-6)


def _run_crashfit(tmp_path, csv, tag, crash_after):
    return _run_ckfit(tmp_path, csv, tag, crash_after, "crashfit", 2)


def test_kill_and_resume_equivalence(tmp_path):
    """SURVEY §6 failure-detection: the whole 2-process job dies abruptly
    after the 2nd durable snapshot; re-running the same launch resumes from
    the snapshot and must land on the uninterrupted run's centers."""
    rng = np.random.RandomState(1)
    data = rng.rand(96, 5).astype(np.float32)
    csv = str(tmp_path / "data.csv")
    np.savetxt(csv, data, delimiter=",", fmt="%.6f")

    # uninterrupted oracle (same chunking via the same checkpoint cadence)
    rcs, outs, out_ok, _ = _run_crashfit(tmp_path, csv, "ok", crash_after=0)
    assert rcs == [0, 0], outs
    with open(out_ok) as f:
        oracle = json.load(f)
    assert oracle["n_iter"] == 12

    # crash run: both ranks exit 17 after the 2nd snapshot (6 of 12 iters)
    rcs, outs, out_crash, ck = _run_crashfit(tmp_path, csv, "crash",
                                             crash_after=2)
    assert rcs == [17, 17], outs
    assert os.path.exists(ck) and not os.path.exists(out_crash)

    # resume: same launch, no crash env — continues from the snapshot
    rcs, outs, out_res, _ = _run_crashfit(tmp_path, csv, "crash",
                                          crash_after=0)
    assert rcs == [0, 0], outs
    with open(out_res) as f:
        resumed = json.load(f)
    assert resumed["n_iter"] == 12
    np.testing.assert_allclose(np.asarray(resumed["centers"]),
                               np.asarray(oracle["centers"]),
                               rtol=1e-5, atol=1e-6)
