"""Async snapshot offload (round-7 perf PR): `FitCheckpoint.save_async`
runs the device→host resolution + checksum + atomic write on a worker
thread, so the fit loop's next chunk dispatches while the previous
snapshot is still being written — PR 1 made these saves synchronous on
the hot path; this pins the overlap AND that every crash-consistency
property survived the move.
"""

import os
import threading
import time

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils.checkpoint import _load_verified


def _blobs(rng, n=210, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d)
                   for i in range(k)])
    return x.astype(np.float32)


class TestOverlap:
    def test_next_chunk_dispatches_while_write_in_flight(
            self, rng, tmp_path, monkeypatch):
        """The acceptance assertion: with a deliberately slow writer, the
        fit loop's next device chunk starts BEFORE the previous snapshot
        write finishes — `save` no longer blocks the loop."""
        import dislib_tpu.cluster.kmeans as km_mod
        events = []

        class SlowWrite(FitCheckpoint):
            def save(self, state):
                events.append(("write_start", time.monotonic()))
                time.sleep(0.25)            # slow disk stand-in
                super().save(state)
                events.append(("write_end", time.monotonic()))

        real_fit = km_mod._kmeans_fit

        def spying_fit(*args, **kwargs):
            events.append(("chunk_start", time.monotonic()))
            return real_fit(*args, **kwargs)

        monkeypatch.setattr(km_mod, "_kmeans_fit", spying_fit)
        x = ds.array(_blobs(rng))
        path = str(tmp_path / "km.npz")
        KMeans(n_clusters=3, max_iter=6, tol=0.0, random_state=0).fit(
            x, checkpoint=SlowWrite(path, every=2))

        writes = [(t, e) for e, t in events if e.startswith("write")]
        chunks = [t for e, t in events if e == "chunk_start"]
        assert len(chunks) == 3 and len(writes) == 6
        # some chunk must start inside a (write_start, write_end) window
        spans = list(zip(sorted(t for t, e in writes if e == "write_start"),
                         sorted(t for t, e in writes if e == "write_end")))
        overlapped = any(s < c < e for c in chunks for s, e in spans)
        assert overlapped, (
            f"no chunk dispatched during a snapshot write — the save "
            f"blocked the loop (events: {events})")
        # and the final snapshot still landed before fit returned
        snap = FitCheckpoint(path, every=2).load()
        assert int(snap["n_iter"]) == 6 and bool(snap["converged"]) is False

    def test_fit_result_identical_to_sync_saves(self, rng, tmp_path):
        """Offloading the write must not change the fit itself."""
        x_np = _blobs(rng)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        plain = KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0) \
            .fit(ds.array(x_np))
        ck = FitCheckpoint(str(tmp_path / "a.npz"), every=2)
        chunked = KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0) \
            .fit(ds.array(x_np), checkpoint=ck)
        np.testing.assert_allclose(chunked.centers_, plain.centers_,
                                   rtol=1e-5)
        assert chunked.n_iter_ == plain.n_iter_


class TestAsyncFetch:
    def test_resolves_and_caches(self):
        import jax.numpy as jnp
        from dislib_tpu.runtime import AsyncFetch, fetch
        x = jnp.arange(12.0).reshape(3, 4)
        h = fetch(x, blocking=False)
        assert isinstance(h, AsyncFetch)
        v = h.result()
        np.testing.assert_array_equal(v, np.arange(12.0).reshape(3, 4))
        assert h.result() is v               # cached ndarray

    def test_forces_lazy_ds_array(self, rng):
        from dislib_tpu.runtime import fetch
        x = rng.rand(8, 8).astype(np.float32)
        a = ds.array(x) * 2.0
        assert a.is_lazy
        h = fetch(a, blocking=False)
        assert not a.is_lazy                 # fetch is a force point
        np.testing.assert_allclose(h.result()[:8, :8], x * 2.0, rtol=1e-6)

    def test_retries_transient_failures(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        from dislib_tpu.runtime import fetch
        monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
        x = jnp.ones((4, 4))
        h = fetch(x, blocking=False)
        flaky = faults.FlakyCall(jax.device_get, failures=1)
        monkeypatch.setattr(jax, "device_get", flaky)
        np.testing.assert_array_equal(h.result(), np.ones((4, 4)))
        assert flaky.calls == 2              # one injected failure + retry


class TestAsyncSemantics:
    def test_writes_never_reorder(self, tmp_path):
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=2)
        ck.save_async({"v": np.asarray([1])})
        ck.save_async({"v": np.asarray([2])})
        ck.flush()
        assert int(ck.load()["v"][0]) == 2
        assert int(_load_verified(path + ".1")["v"][0]) == 1

    def test_write_failure_surfaces_at_flush(self, tmp_path):
        class Boom(FitCheckpoint):
            def save(self, state):
                raise OSError(28, "injected: no space left on device")

        ck = Boom(str(tmp_path / "b.npz"))
        ck.save_async({"v": np.asarray([1])})
        with pytest.raises(OSError, match="no space"):
            ck.flush()
        ck.save_async({"v": np.asarray([1])})   # next one re-arms cleanly
        with pytest.raises(OSError):
            ck.save_async({"v": np.asarray([2])})

    def test_load_and_delete_wait_for_pending(self, tmp_path):
        gate = threading.Event()

        class Gated(FitCheckpoint):
            def save(self, state):
                gate.wait(5.0)
                super().save(state)

        path = str(tmp_path / "g.npz")
        ck = Gated(path, keep=1)
        ck.save_async({"v": np.asarray([7])})
        assert not os.path.exists(path)      # still gated
        gate.set()
        assert int(ck.load()["v"][0]) == 7   # load flushed first
        ck.delete()
        assert not os.path.exists(path)

    def test_fault_callback_fires_on_worker(self, tmp_path):
        """`CallbackCheckpoint` semantics survive the offload: the callback
        runs right after the n-th snapshot reaches disk (now on the worker
        thread), before the next save_async can start."""
        fired = []
        ck = faults.CallbackCheckpoint(
            str(tmp_path / "c.npz"), after=2,
            callback=lambda: fired.append(os.path.exists(
                str(tmp_path / "c.npz"))))
        ck.save_async({"v": np.asarray([1])})
        ck.save_async({"v": np.asarray([2])})
        ck.flush()
        assert fired == [True]               # fired once, file on disk
