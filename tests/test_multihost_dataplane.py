"""Round-19 DCN data plane, single-process coverage: the hierarchical
``dcn`` rechunk schedule under mocked host maps (bit-equality grid +
analytic accounting), the sharded-bundle load barrier with a poisoned
shard, the coordination primitives (ranked exchange over the local and
file transports, typed timeout), the capacity ledger's last-coherent-wins
race, and the serving mesh's elastic shrink/grow between batches.

The ``DSLIB_MOCK_HOSTS=N`` overlay partitions this process's flat device
order into N contiguous fake hosts, so every protocol decision (host
blocks, coalesced message accounting, shard ownership) runs for real
without a second process; ``tools/run_multihost.sh`` is the two-REAL-
process proof of the same paths under ``jax.distributed``.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import dislib_tpu as ds
from dislib_tpu.ops import rechunk as rc
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.parallel import hosts as _hosts
from dislib_tpu.utils import profiling as _prof


@pytest.fixture
def mock_hosts(request):
    """Set DSLIB_MOCK_HOSTS for one test and restore it after."""
    def _set(n):
        os.environ["DSLIB_MOCK_HOSTS"] = str(n)
    prev = os.environ.get("DSLIB_MOCK_HOSTS")
    yield _set
    if prev is None:
        os.environ.pop("DSLIB_MOCK_HOSTS", None)
    else:
        os.environ["DSLIB_MOCK_HOSTS"] = prev


def _hier_data(src_shape, m, n):
    """A deterministic (m, n) array staged canonically on a src mesh."""
    _mesh.init(src_shape)
    src = _mesh.get_mesh()
    x = np.arange(m * n, dtype=np.float32).reshape(m, n) * 0.25 - 3.0
    pr, pc = src.shape[_mesh.ROWS], src.shape[_mesh.COLS]
    xp = np.zeros((-(-m // pr) * pr, -(-n // pc) * pc), np.float32)
    xp[:m, :n] = x
    return jax.device_put(xp, _mesh.data_sharding(src)), src


def _dst(src, shape):
    return Mesh(np.asarray(list(src.devices.flat)).reshape(shape),
                _mesh.AXIS_NAMES)


# --------------------------------------------------------------------------
# hierarchical rechunk: schedule x mesh bit-equality grid + accounting
# --------------------------------------------------------------------------

GRID = [
    # (mock hosts, src shape, dst shape) — every pair hierarchical under
    # the mock map: contiguous equal host blocks of whole rows both sides
    (2, (8, 1), (4, 2)),
    (2, (4, 2), (2, 4)),
    (2, (2, 4), (8, 1)),
    (4, (8, 1), (4, 2)),
    (4, (4, 2), (8, 1)),
]


@pytest.mark.parametrize("mock,src_shape,dst_shape", GRID)
@pytest.mark.parametrize("overlap", ["seq", "db"])
def test_dcn_bit_equal_grid(mock_hosts, mock, src_shape, dst_shape,
                            overlap):
    """dcn == panels bit-for-bit across host counts, mesh pairs, and both
    overlap variants — a reshard is pure data movement."""
    mock_hosts(mock)
    m, n = 50, 21                     # pads misalign between the shapes
    data, src = _hier_data(src_shape, m, n)
    dst = _dst(src, dst_shape)
    assert rc.dcn_supported(data, dst)
    out = rc.dcn_rechunk(data, (m, n), dst, overlap=overlap)
    ref, sched = rc.reshard(data, (m, n), dst, schedule="panels")
    assert sched == "panels"
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("mock,src_shape,dst_shape", GRID)
def test_dcn_accounting_invariants(mock_hosts, mock, src_shape,
                                   dst_shape):
    """The analytic claims behind the schedule: coalesced messages are
    O(hosts) per step, bytes match the rows-that-change-host floor
    exactly (no write amplification), and the hierarchical total never
    exceeds the flat exchange's O(panels) message count."""
    mock_hosts(mock)
    data, src = _hier_data(src_shape, 50, 21)
    dst = _dst(src, dst_shape)
    acct = rc.dcn_accounting(data, (50, 21), dst)
    assert acct["hosts"] == mock
    assert acct["messages_per_step_max"] <= acct["hosts"] - 1
    assert acct["dcn_bytes_moved"] == acct["deviceput_bytes"]
    assert acct["dcn_messages"] <= acct["flat_messages"]


def test_dcn_routing_and_counter(mock_hosts):
    """Auto-routing picks dcn exactly when the mesh is multi-host (and
    the run is counted); a single-host mesh keeps the flat exchange, and
    the sparse router downgrades dcn to panels (no hierarchical sparse
    tier yet)."""
    mock_hosts(4)
    data, src = _hier_data((8, 1), 50, 21)
    dst = _dst(src, (4, 2))
    assert rc.pick_schedule(data, dst) == "dcn"
    _prof.reset_counters()
    out, sched = rc.reshard(data, (50, 21), dst, schedule="auto")
    assert sched == "dcn"
    assert sum(v for k, v in _prof.schedule_counters().items()
               if k.startswith("rechunk_dcn:")) == 1
    ref, _ = rc.reshard(data, (50, 21), dst, schedule="panels")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # explicit dcn on sparse downgrades before any layout inspection
    assert rc.pick_sparse_schedule(None, None, "dcn") == "panels"

    os.environ["DSLIB_MOCK_HOSTS"] = "1"
    data1, src1 = _hier_data((8, 1), 50, 21)
    assert rc.pick_schedule(data1, _dst(src1, (4, 2))) == "panels"


def test_dcn_explicit_on_unsupported_layout_raises(mock_hosts):
    """schedule='dcn' on a mesh whose rows span hosts is a loud error,
    not a silent downgrade."""
    mock_hosts(4)
    data, src = _hier_data((8, 1), 50, 21)
    bad = _dst(src, (2, 4))           # 4 devices/row over 4 hosts
    with pytest.raises(ValueError, match="contiguous equal host blocks"):
        rc.reshard(data, (50, 21), bad, schedule="dcn")


def test_host_map_helpers(mock_hosts):
    """The mock overlay partitions flat device order contiguously; the
    block decomposition feeds the dcn schedule."""
    mock_hosts(4)
    _mesh.init((8, 1))
    mesh = _mesh.get_mesh()
    assert _hosts.n_hosts(mesh) == 4
    blocks = _hosts.host_blocks(mesh)
    assert blocks is not None
    n_blocks, rows_per_block, block_hosts = blocks
    assert (n_blocks, rows_per_block) == (4, 2)
    assert list(block_hosts) == [0, 1, 2, 3]
    mock_hosts(3)                     # 3 does not divide 8 evenly
    assert _hosts.host_blocks(_mesh.get_mesh()) is None


# --------------------------------------------------------------------------
# sharded bundles: coordinated load barrier, poisoned-shard regression
# --------------------------------------------------------------------------

NF = 8


def _linreg_pipe():
    from dislib_tpu.serving import ServePipeline
    lr = ds.LinearRegression()
    lr.coef_ = np.ones((NF, 1), np.float32)
    lr.intercept_ = np.full(1, 5.0, np.float32)
    state = {"coef": lr.coef_, "intercept": lr.intercept_}
    return ServePipeline(lr, n_features=NF), state


def test_sharded_bundle_round_trip(tmp_path):
    """export_bundle(hosts=N) writes one executable shard per host plus
    a manifest; the barrier-gated load serves bit-correct predictions."""
    from dislib_tpu.serving import export_bundle, load_bundle
    pipe, state = _linreg_pipe()
    path = str(tmp_path / "model.dsb.npz")
    man = export_bundle(pipe, path, buckets=(1, 8), state=state, hosts=4)
    assert man["sharded"] and man["hosts"] == 4
    assert len(man["shard_crcs"]) == 4
    for r in range(4):
        assert os.path.exists(f"{path}.shard{r}")
    _prof.reset_counters()
    lb = load_bundle(path)
    assert lb.hosts == 4 and lb.host == 0 and not lb.fallback
    x = np.random.RandomState(0).rand(5, NF).astype(np.float32)
    np.testing.assert_allclose(lb.pipeline.predict_bucket(x, 8),
                               x @ state["coef"] + 5.0, atol=1e-5)
    assert _prof.resilience_counters().get("bundle_barrier_ok") == 1


def test_poisoned_shard_aborts_every_host(tmp_path):
    """One corrupt per-host shard -> the SAME typed abort everywhere
    (zero hosts serve), naming the bad host; the abort is counted."""
    from dislib_tpu.runtime import BundleShardCorrupt
    from dislib_tpu.runtime.bundle_io import shard_path
    from dislib_tpu.serving import export_bundle, load_bundle
    pipe, state = _linreg_pipe()
    path = str(tmp_path / "model.dsb.npz")
    export_bundle(pipe, path, buckets=(1,), state=state, hosts=4)
    with open(shard_path(path, 2), "r+b") as f:
        f.seek(100)
        f.write(b"\xde\xad\xbe\xef")
    _prof.reset_counters()
    with pytest.raises(BundleShardCorrupt) as ei:
        load_bundle(path)
    assert ei.value.host == 2
    assert _prof.resilience_counters().get("bundle_barrier_abort") == 1
    assert not _prof.resilience_counters().get("bundle_barrier_ok")


def test_sharded_bundle_mesh_contract_mismatch(tmp_path):
    """A manifest whose mesh contract disagrees with THIS runtime's
    device split refuses to serve executables (state-only fallback path
    stays available through build=)."""
    from dislib_tpu.runtime import BundleIncompatible
    from dislib_tpu.serving import export_bundle, load_bundle
    pipe, state = _linreg_pipe()
    path = str(tmp_path / "model.dsb.npz")
    with pytest.raises((BundleIncompatible, ValueError)):
        export_bundle(pipe, path, buckets=(1,), state=state, hosts=3)


# --------------------------------------------------------------------------
# coordination: ranked exchange, typed timeout, capacity-ledger race
# --------------------------------------------------------------------------

def test_local_exchange_across_threads():
    from dislib_tpu.runtime.coord import LocalCoordinator
    co = LocalCoordinator()
    out = {}

    def worker(r):
        out[r] = co.exchange("grid", r, {"rank": r}, n=3, timeout=10.0)

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    for r in range(3):
        assert out[r] == {0: {"rank": 0}, 1: {"rank": 1}, 2: {"rank": 2}}


def test_file_exchange_and_typed_timeout(tmp_path):
    from dislib_tpu.runtime.coord import (CoordinationTimeout,
                                          FileCoordinator)
    co = FileCoordinator(str(tmp_path))
    co.post("ex", 0, [1, 2])
    co.post("ex", 1, [3])
    got = co.exchange("ex", 0, [1, 2], n=2, timeout=5.0)
    assert got == {0: [1, 2], 1: [3]}
    with pytest.raises(CoordinationTimeout) as ei:
        co.exchange("lonely", 0, "x", n=3, timeout=0.1)
    assert set(ei.value.missing) == {1, 2}


def test_capacity_ledger_last_coherent_wins(tmp_path):
    """Two racing writers, one reader: every read is either a coherent
    published record or an explicit no-statement (None) — never a torn
    mix; the final state is the last coherent publish."""
    from dislib_tpu.runtime.coord import CapacityLedger
    path = str(tmp_path / "cap.ledger")
    ledger = CapacityLedger(path)
    stop = threading.Event()
    bad_reads = []

    def reader():
        while not stop.is_set():
            target, epoch = ledger.read()
            if target is not None and target not in (2, 4, 8):
                bad_reads.append((target, epoch))

    def writer(vals):
        for v in vals:
            ledger.publish(v, writer=f"w{v}")

    rt = threading.Thread(target=reader)
    rt.start()
    w1 = threading.Thread(target=writer, args=([2, 4] * 25,))
    w2 = threading.Thread(target=writer, args=([8, 4] * 25,))
    w1.start(); w2.start(); w1.join(); w2.join()
    stop.set(); rt.join()
    assert not bad_reads
    target, epoch = ledger.read()
    assert target in (2, 4, 8) and epoch >= 1

    # a torn/garbage file is an explicit no-statement, not a crash
    with open(path, "w") as f:
        f.write('{"epoch": 3, "target":')
    assert ledger.read() == (None, 0)


def test_capacity_env_precedence(tmp_path, monkeypatch):
    """request_capacity (process override) wins over the ledger; with no
    override the ledger speaks; clear_capacity republishes None."""
    from dislib_tpu.runtime import (capacity_target, clear_capacity,
                                    request_capacity)
    from dislib_tpu.runtime.coord import CapacityLedger
    path = str(tmp_path / "cap.ledger")
    monkeypatch.setenv("DSLIB_CAPACITY_LEDGER", path)
    try:
        request_capacity(4)
        assert capacity_target() == 4
        # the override also published, so a ledger-only consumer agrees
        assert CapacityLedger(path).read()[0] == 4
    finally:
        clear_capacity()
    assert capacity_target() is None


# --------------------------------------------------------------------------
# serving: elastic capacity re-layout between batches (ROADMAP 3(c))
# --------------------------------------------------------------------------

def test_predict_server_elastic_shrink_grow():
    from dislib_tpu.serving import PredictServer, ServePipeline
    from dislib_tpu.runtime import clear_capacity, request_capacity
    pipe, state = _linreg_pipe()
    calls = []

    def hook(m):
        calls.append(None if m is None else _mesh.mesh_shape(m))
        return None

    x = np.random.RandomState(0).rand(4, NF).astype(np.float32)
    exp = x @ state["coef"] + 5.0
    _prof.reset_counters()
    srv = PredictServer(pipeline=pipe, buckets=(1, 8), elastic=hook,
                        capacity_poll_s=0.01)
    try:
        with srv:
            np.testing.assert_allclose(srv.predict(x), exp, atol=1e-5)
            request_capacity(4)
            t0 = time.time()
            while srv.stats()["mesh_resizes"] < 1 and time.time() - t0 < 30:
                time.sleep(0.02)
            assert srv.stats()["mesh_resizes"] == 1
            assert _mesh.mesh_shape(_mesh.get_mesh()) == (4, 1)
            np.testing.assert_allclose(srv.predict(x), exp, atol=1e-5)
            request_capacity(8)
            t0 = time.time()
            while srv.stats()["mesh_resizes"] < 2 and time.time() - t0 < 30:
                time.sleep(0.02)
            assert srv.stats()["mesh_resizes"] == 2
            assert _mesh.mesh_shape(_mesh.get_mesh()) == (8, 1)
            np.testing.assert_allclose(srv.predict(x), exp, atol=1e-5)
    finally:
        clear_capacity()
    # hook saw: pre-switch drain, new mesh, pre-switch drain, new mesh
    assert calls == [None, (4, 1), None, (8, 1)]
    res = _prof.resilience_counters()
    assert res.get("serve_mesh_shrinks") == 1
    assert res.get("serve_mesh_grows") == 1


def test_predict_server_elastic_excludes_pool():
    from dislib_tpu.serving import PredictServer
    with pytest.raises(ValueError, match="elastic"):
        PredictServer(pool=object(), buckets=(1,),
                      elastic=lambda m: None)


def test_predict_server_elastic_true_is_the_hookless_spelling():
    """``elastic=True`` (no rebind hook) must serve AND resize — a
    non-callable leaking into the worker thread would raise TypeError
    there, killing serving and stranding every queued future (found
    driving the surface, round 19)."""
    from dislib_tpu.serving import PredictServer, ServePipeline  # noqa: F401
    from dislib_tpu.runtime import clear_capacity, request_capacity
    pipe, state = _linreg_pipe()
    x = np.random.RandomState(1).rand(4, NF).astype(np.float32)
    exp = x @ state["coef"] + 5.0
    srv = PredictServer(pipeline=pipe, buckets=(1, 8), elastic=True,
                        capacity_poll_s=0.01)
    try:
        with srv:
            np.testing.assert_allclose(srv.predict(x), exp, atol=1e-5)
            request_capacity(4)
            t0 = time.time()
            while srv.stats()["mesh_resizes"] < 1 and time.time() - t0 < 30:
                time.sleep(0.02)
            assert srv.stats()["mesh_resizes"] == 1
            assert _mesh.mesh_shape(_mesh.get_mesh()) == (4, 1)
            np.testing.assert_allclose(srv.predict(x), exp, atol=1e-5)
    finally:
        clear_capacity()
    # elastic=False is plain disabled — legal even in pool mode
    assert PredictServer(pool=None, pipeline=pipe, buckets=(1,),
                         elastic=False)._elastic is None
