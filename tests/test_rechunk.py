"""On-device collective rechunk (round-11 perf PR, ROADMAP item 4).

Five pillars:

1. **Bit-equivalence vs the host path** — every schedule (fused/xla,
   panels, deviceput) over a (block_size × mesh-pair × dtype) grid,
   float64/x64 included, must reproduce the `runtime.repad_rows` host
   oracle EXACTLY (a reshard is pure data movement: zero rounding), and
   leave the new pad region exactly zero.
2. **Poisoned-pad regression** — a backing whose pad tail was corrupted
   upstream comes out of ANY moving schedule with pads re-zeroed (the
   round-10 `grow_canvas` discipline, extended to resharding).
3. **Elastic resume** — on-device state re-pads for a new mesh through
   the same primitive (`repad_rows` device route ≡ host route, bit for
   bit), and a checkpointed fit resumes onto a different mesh unchanged.
4. **Dispatch/transfer counters** — a mid-chain rechunk adds ZERO
   dispatches to a fused chain; the panel exchange is ONE dispatch; a
   mismatched-block PCA → KMeans (and scaler → CSVM) stage boundary
   costs ZERO host transfers (counter-asserted AND jax.transfer_guard).
5. **Ingest guard** — estimators accept arrays laid out under another
   mesh (`ensure_canonical` re-lays out on device).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import dislib_tpu as ds
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as prof
from conftest import skip_unless_devices


def _host_repad_oracle(x, logical, pshape):
    """The reference reshard: crop to logical, zero-fill to the target
    padded canvas — what `runtime.repad_rows` does on host, per axis."""
    from dislib_tpu.runtime import repad_rows
    out = repad_rows(np.asarray(x)[: logical[0], : logical[1]],
                     logical[0], pshape[0], axis=0)
    return repad_rows(out, logical[1], pshape[1], axis=1)


def _mk(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.randint(-50, 50, size=shape).astype(dtype)
    return rng.rand(*shape).astype(dtype)


# ---------------------------------------------------------------------------
# 1. bit-equivalence grid
# ---------------------------------------------------------------------------

class TestEquivalenceGrid:
    MESH_PAIRS = [
        ((4, 2), (2, 4)),     # 2-D relayout, same 8 devices (panels)
        ((8, 1), (4, 2)),     # 1-D -> 2-D, same devices (panels)
        ((2, 2), (8, 1)),     # 4 -> 8 devices (deviceput fallback)
        ((8, 1), (2, 1)),     # 8 -> 2 devices (shrink)
    ]

    @pytest.mark.parametrize("src,dst", MESH_PAIRS)
    @pytest.mark.parametrize("blocks", [(7, 3), (64, 64)])
    @pytest.mark.parametrize("dtype", [np.float32, np.int32])
    def test_cross_mesh_bit_equal(self, src, dst, blocks, dtype):
        skip_unless_devices(8)
        shape = (50, 12)
        x = _mk(shape, dtype)
        ds.init(src)
        a = ds.array(x, block_size=(9, 5), dtype=dtype).force()
        ds.init(dst)
        out = ds.rechunk(a, blocks)
        pshape = tuple(-(-s // _mesh.pad_quantum()) * _mesh.pad_quantum()
                       for s in shape)
        full = np.asarray(out.force()._data)
        assert full.shape == pshape
        np.testing.assert_array_equal(full,
                                      _host_repad_oracle(np.asarray(a._data),
                                                         shape, pshape))
        # oversized hints clamp to the logical shape (ds.array contract)
        assert out.block_size == tuple(min(b, s)
                                       for b, s in zip(blocks, shape))
        assert out._data.sharding == _mesh.data_sharding()

    @pytest.mark.parametrize("schedule", ["panels", "xla", "deviceput"])
    def test_explicit_schedules_agree(self, schedule):
        skip_unless_devices(8)
        shape = (37, 10)
        x = _mk(shape, np.float32, seed=3)
        ds.init((4, 2))
        a = ds.array(x).force()
        ds.init((2, 4))
        out = ds.rechunk(a, schedule=schedule).force()
        np.testing.assert_array_equal(out.collect(), x)
        full = np.asarray(out._data)
        assert np.all(full[shape[0]:] == 0)
        assert np.all(full[:, shape[1]:] == 0)

    @pytest.mark.parametrize("panels", [1, 2, 8])
    def test_panel_count_is_a_tuning_knob_not_semantics(self, panels):
        skip_unless_devices(8)
        shape = (48, 16)
        x = _mk(shape, np.float32, seed=4)
        ds.init((4, 2))
        a = ds.array(x).force()
        ds.init((8, 1))
        out = ds.rechunk(a, schedule="panels", panels=panels)
        np.testing.assert_array_equal(out.collect(), x)

    def test_f64_x64_mode(self):
        skip_unless_devices(8)
        with jax.enable_x64(True):
            shape = (21, 9)
            x = _mk(shape, np.float64, seed=5)
            ds.init((4, 2))
            a = ds.array(x, dtype=np.float64).force()
            assert a.dtype == np.float64
            ds.init((2, 4))
            out = ds.rechunk(a)
            assert out.dtype == np.float64
            np.testing.assert_array_equal(out.collect(), x)

    def test_same_mesh_is_metadata_only(self):
        x = _mk((20, 8), np.float32)
        a = ds.array(x, block_size=(6, 4)).force()
        b = ds.rechunk(a, (5, 2))
        assert b._concrete is a._concrete          # zero data movement
        assert b.block_size == (5, 2)
        c = a.rechunk((3, 3))                      # method parity
        assert c._concrete is a._concrete and c.block_size == (3, 3)

    def test_sparse_array_accepted_since_round_14(self):
        """The PR-6 typed rejection is GONE: SparseArray routes through
        the sparse schedules (tests/test_spmm.py owns the equivalence
        grid; this pins the entry accepting it at all)."""
        from dislib_tpu.data.sparse import SparseArray
        import scipy.sparse as sp
        mat = sp.random(8, 8, 0.5, format="csr", random_state=0)
        s = SparseArray.from_scipy(mat)
        out = ds.rechunk(s)
        assert isinstance(out, SparseArray)
        np.testing.assert_allclose(out.collect().toarray(), mat.toarray())


# ---------------------------------------------------------------------------
# 2. poisoned-pad regression
# ---------------------------------------------------------------------------

class TestPoisonedPad:
    def _poisoned(self, shape=(20, 6)):
        x = _mk(shape, np.float32, seed=7)
        a = ds.array(x).force()
        bad = a._data.at[shape[0]:, :].set(jnp.nan) \
                     .at[:, shape[1]:].set(jnp.inf)
        from dislib_tpu.data.array import Array
        return Array(bad, shape), x

    def test_fused_requantize_rezeroes(self):
        a, x = self._poisoned()
        out = ds.rechunk(a, schedule="xla").force()
        full = np.asarray(out._data)
        np.testing.assert_array_equal(full[:20, :6], x)
        assert np.all(full[20:] == 0) and np.all(full[:, 6:] == 0)

    def test_panel_exchange_rezeroes(self):
        skip_unless_devices(8)
        ds.init((4, 2))
        a, x = self._poisoned()
        ds.init((2, 4))
        out = ds.rechunk(a, schedule="panels")
        full = np.asarray(out._data)
        np.testing.assert_array_equal(full[:20, :6], x)
        assert np.all(full[20:] == 0) and np.all(full[:, 6:] == 0)

    def test_deviceput_rezeroes(self):
        skip_unless_devices(8)
        ds.init((2, 2))
        a, x = self._poisoned()
        ds.init((8, 1))
        out = ds.rechunk(a, schedule="deviceput")
        full = np.asarray(out._data)
        np.testing.assert_array_equal(full[:20, :6], x)
        assert np.all(full[20:] == 0) and np.all(full[:, 6:] == 0)


# ---------------------------------------------------------------------------
# 3. elastic resume
# ---------------------------------------------------------------------------

class TestElasticOnDevice:
    def test_repad_rows_device_route_equals_host_route(self):
        skip_unless_devices(8)
        from dislib_tpu.runtime import repad_rows
        ds.init((8, 1))
        state = ds.random_array((30, 16), random_state=0).force()._data
        dev = repad_rows(state, 30, 40)
        host = repad_rows(np.asarray(state), 30, 40)
        assert isinstance(dev, jax.Array)          # stayed on device
        np.testing.assert_array_equal(np.asarray(dev), host)
        # axis=1, and the validation contract matches the host path's
        dev1 = repad_rows(state.T, 30, 33, axis=1)
        np.testing.assert_array_equal(np.asarray(dev1),
                                      repad_rows(np.asarray(state).T, 30, 33,
                                                 axis=1))
        with pytest.raises(ValueError, match="stale or foreign"):
            repad_rows(state, 100, 120)
        with pytest.raises(ValueError, match="smaller than the logical"):
            repad_rows(state, 30, 20)

    def test_on_device_state_reshards_for_new_mesh(self):
        """The elastic scenario the host path can't serve without a
        round trip: live device state at a mesh change."""
        skip_unless_devices(8)
        ds.init((8, 1))
        a = ds.random_array((40, 12), random_state=1).force()
        ref = a.collect()
        prof.reset_counters()
        ds.init((2, 2))
        out = ds.rechunk(a)
        np.testing.assert_array_equal(out.collect(), ref)
        assert prof.transfer_count() == 1          # only the final collect

    def test_checkpointed_fit_resumes_on_different_mesh(self, tmp_path):
        skip_unless_devices(8)
        from dislib_tpu.utils.checkpoint import FitCheckpoint
        x = _mk((64, 6), np.float32, seed=9)
        ds.init((8, 1))
        ref = ds.cluster.KMeans(n_clusters=3, max_iter=8, random_state=0) \
            .fit(ds.array(x)).centers_
        ckpt = FitCheckpoint(str(tmp_path / "km"), every=4)
        km = ds.cluster.KMeans(n_clusters=3, max_iter=4, random_state=0)
        km.fit(ds.array(x), checkpoint=ckpt)       # first 4 iterations
        ds.init((2, 2))                            # elastic mesh change
        km2 = ds.cluster.KMeans(n_clusters=3, max_iter=8, random_state=0)
        km2.fit(ds.array(x), checkpoint=FitCheckpoint(str(tmp_path / "km"),
                                                      every=4))
        np.testing.assert_allclose(km2.centers_, ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# 4. dispatch / transfer counters
# ---------------------------------------------------------------------------

class TestCounters:
    def test_mid_chain_rechunk_costs_zero_extra_dispatches(self):
        """schedule="xla" pins the claim for a REAL rechunk node on the
        graph; the auto metadata fast-path (same pshape → shared expr,
        no node at all) is asserted separately — both forms of "zero
        extra dispatches", the second vacuously (review-found: the gate
        must not rely on the vacuous form alone)."""
        x = _mk((32, 8), np.float32, seed=11)
        a = ds.array(x).force()
        prof.reset_counters()
        y = (a * 2.0 - 1.0)
        y = ds.rechunk(y, (16, 4), schedule="xla")   # a genuine node
        assert y.is_lazy
        y = (y + 0.5).T
        y.force()
        assert prof.dispatch_count() == 1, prof.counters()
        np.testing.assert_allclose(y.collect(), ((x * 2.0 - 1.0) + 0.5).T,
                                   rtol=1e-6)
        # auto fast-path: block-hint-only rechunk shares the pending
        # expression (no node, no force, no dispatch)
        prof.reset_counters()
        z = ds.rechunk(a * 2.0, (16, 4))
        assert z.is_lazy and prof.dispatch_count() == 0

    def test_ensure_canonical_requantizes_stale_lazy_chain(self):
        """Review-found repro: a lazy chain built under an old quantum
        must not reach a shard_map kernel with its stale canvas — the
        ingest guard appends the fused requantize node."""
        skip_unless_devices(8)
        ds.init((4, 2))                    # quantum 4
        x = _mk((12, 12), np.float32, seed=16)
        a = ds.array(x).force()
        c = a * 2.0                        # lazy, canvas (12, 12)
        ds.init((8, 1))                    # quantum 8
        cc = ds.ensure_canonical(c)
        assert cc.is_lazy                  # still on the fusion graph
        assert cc._pshape == (16, 16)
        np.testing.assert_allclose(cc.collect(), x * 2.0, rtol=1e-6)
        full = np.asarray(cc._data)
        assert np.all(full[12:] == 0) and np.all(full[:, 12:] == 0)

    def test_summa_accepts_stale_lazy_operands(self):
        """The deleted post-force repad guard's job, now done by
        ensure_canonical: SUMMA over a LAZY chain whose canvas was built
        under an older quantum (10 under (2,1); the (4,2) grid needs 12)
        must requantize instead of crashing the shard_map row/col
        split (review-found repro)."""
        skip_unless_devices(8)
        ds.init((4, 2))                    # quantum 4 → (12, 12) canvas
        x = _mk((12, 12), np.float32, seed=17)
        a = ds.array(x).force()
        c = a * 2.0                        # lazy, stale (12, 12) canvas
        ds.init((8, 1))                    # quantum 8: 12 % 8 != 0; same
        b = ds.array(x)                    # device SET (a lazy chain can
        # only force onto the devices its leaves live on — a device-SET
        # change with a pending chain is a pre-existing fusion-layer
        # limit, unchanged by this PR: force before re-initing the mesh)
        out = ds.matmul(c, b, algorithm="summa")
        np.testing.assert_allclose(out.collect(), (x * 2.0) @ x,
                                   rtol=1e-4, atol=1e-4)

    def test_panel_exchange_is_one_dispatch(self):
        skip_unless_devices(8)
        ds.init((4, 2))
        a = ds.random_array((48, 16), random_state=2).force()
        ds.init((2, 4))
        ds.rechunk(a, schedule="panels")           # warm/compile
        b = ds.rechunk(a.copy(), schedule="panels")  # cached program
        prof.reset_counters()
        ds.rechunk(a, schedule="panels")
        assert prof.dispatch_count() == 1, prof.counters()
        assert prof.transfer_count() == 0
        del b

    def test_rechunk_fuses_into_estimator_predict(self):
        """A rechunk between a scaler and a predict kernel still yields
        the serving contract: ONE dispatch end to end."""
        x = _mk((40, 6), np.float32, seed=12)
        a = ds.array(x).force()
        km = ds.cluster.KMeans(n_clusters=3, max_iter=3, random_state=0)
        km.fit(a)
        sc = ds.preprocessing.StandardScaler().fit(a)
        km.predict(ds.rechunk(sc.transform(a), (8, 6))).force()  # warm
        prof.reset_counters()
        km.predict(ds.rechunk(sc.transform(a), (8, 6))).force()
        assert prof.dispatch_count() == 1, prof.counters()


class TestPipelineStageBoundaries:
    """The acceptance rows: mismatched block sizes between stages cost
    ZERO host transfers at the boundary — counter-asserted and enforced
    by jax's own transfer guard around the boundary region."""

    def test_pca_to_kmeans_zero_host_transfers(self):
        skip_unless_devices(8)
        ds.init((4, 2))
        x = _mk((96, 16), np.float32, seed=13)
        a = ds.array(x, block_size=(90, 16))       # stage-1 block size
        pca = ds.PCA(n_components=8).fit(a)
        prof.reset_counters()
        with jax.transfer_guard("disallow"):
            t = pca.transform(a)                   # inherits matmul blocks
            t2 = ds.rechunk(t, (32, 8))            # stage-2 block size
            t2.force()
        assert prof.transfer_count() == 0, prof.counters()
        km = ds.cluster.KMeans(n_clusters=4, max_iter=3, random_state=0)
        km.fit(t2)                                 # stage 2 runs fine
        assert km.centers_.shape == (4, 8)

    def test_scaler_to_csvm_zero_host_transfers(self):
        skip_unless_devices(8)
        ds.init((4, 2))
        rng = np.random.RandomState(14)
        x = np.vstack([rng.randn(40, 5) + 2, rng.randn(40, 5) - 2]) \
            .astype(np.float32)
        y = np.r_[np.ones(40), np.zeros(40)].astype(np.float32)
        a = ds.array(x, block_size=(33, 5))
        sc = ds.preprocessing.StandardScaler().fit(a)
        sc.transform(a).force()    # warm: builds the scaler's device-side
        prof.reset_counters()      # scale cache (a one-time scalar upload)
        with jax.transfer_guard("disallow"):
            t = ds.rechunk(sc.transform(a), (16, 5))
            t.force()
        assert prof.transfer_count() == 0, prof.counters()
        svm = ds.classification.CascadeSVM(max_iter=2, random_state=0)
        svm.fit(t, ds.array(y.reshape(-1, 1), block_size=(16, 1)))
        assert svm.score(t, ds.array(y.reshape(-1, 1))) > 0.8

    def test_cross_mesh_boundary_stays_on_device(self):
        """Stage-1 output computed under an OLD mesh feeds stage 2 after
        an elastic mesh change: the reshard is collective, not a host
        hop."""
        skip_unless_devices(8)
        ds.init((8, 1))
        x = _mk((64, 8), np.float32, seed=15)
        a = ds.array(x)
        sc = ds.preprocessing.StandardScaler().fit(a)
        t = sc.transform(a).force()
        ds.init((4, 2))
        prof.reset_counters()
        t2 = ds.rechunk(t, (16, 8))
        t2.force()
        assert prof.transfer_count() == 0, prof.counters()
        km = ds.cluster.KMeans(n_clusters=3, max_iter=3, random_state=0)
        km.fit(t2)
        assert np.isfinite(km.inertia_)


# ---------------------------------------------------------------------------
# 5. ingest guard
# ---------------------------------------------------------------------------

class TestEnsureCanonical:
    def test_noop_on_canonical(self):
        a = ds.random_array((24, 8), random_state=3).force()
        assert ds.ensure_canonical(a) is a

    def test_relayouts_foreign_backing(self):
        skip_unless_devices(8)
        ds.init((4, 2))
        a = ds.random_array((24, 8), random_state=4).force()
        ref = a.collect()
        ds.init((8, 1))
        b = ds.ensure_canonical(a)
        assert b is not a
        assert tuple(b._data.shape) == (24, 8)
        assert b._data.sharding == _mesh.data_sharding()
        np.testing.assert_array_equal(b.collect(), ref)

    def test_ring_estimator_accepts_foreign_mesh_input(self):
        """DBSCAN's ring tier shard_maps rows over the mesh — an input
        built under another mesh must re-lay out, not crash."""
        skip_unless_devices(8)
        rng = np.random.RandomState(5)
        x = np.vstack([rng.randn(30, 2), rng.randn(30, 2) + 10]) \
            .astype(np.float32)
        ds.init((4, 2))
        a = ds.array(x).force()
        ds.init((8, 1))
        labels = ds.cluster.DBSCAN(eps=2.0, min_samples=3).fit_predict(a)
        lab = labels.collect().ravel()
        assert len(set(lab[lab >= 0])) == 2
