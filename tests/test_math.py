"""Blocked-math tests (reference: test_matmul/test_kron/test_svd/test_qr/
test_tsqr/test_randomsvd/test_lanczos/test_pca — SURVEY.md §5 oracle pattern)."""

import os

import numpy as np
import pytest

import dislib_tpu as ds


class TestMatmul:
    @pytest.mark.parametrize("shapes", [((8, 8), (8, 8)), ((17, 5), (5, 9)),
                                        ((1, 7), (7, 1)), ((33, 65), (65, 12))])
    def test_matmul(self, rng, shapes):
        (m, k), (_, n) = shapes
        x, y = rng.rand(m, k), rng.rand(k, n)
        got = ds.matmul(ds.array(x), ds.array(y)).collect()
        np.testing.assert_allclose(got, x @ y, rtol=1e-4, atol=1e-5)

    def test_transposes(self, rng):
        x, y = rng.rand(12, 7), rng.rand(12, 9)
        got = ds.matmul(ds.array(x), ds.array(y), transpose_a=True).collect()
        np.testing.assert_allclose(got, x.T @ y, rtol=1e-4)
        x, y = rng.rand(7, 12), rng.rand(9, 12)
        got = ds.matmul(ds.array(x), ds.array(y), transpose_b=True).collect()
        np.testing.assert_allclose(got, x @ y.T, rtol=1e-4)
        got = ds.matmul(ds.array(x.T), ds.array(y), transpose_a=True,
                        transpose_b=True).collect()
        np.testing.assert_allclose(got, x @ y.T, rtol=1e-4)

    def test_operator(self, rng):
        x, y = rng.rand(6, 4), rng.rand(4, 5)
        np.testing.assert_allclose((ds.array(x) @ ds.array(y)).collect(), x @ y,
                                   rtol=1e-4)

    def test_mismatch_raises(self, rng):
        with pytest.raises(ValueError):
            ds.matmul(ds.array(rng.rand(3, 4)), ds.array(rng.rand(3, 4)))


class TestKron:
    def test_kron(self, rng):
        a, b = rng.rand(3, 4), rng.rand(5, 2)
        np.testing.assert_allclose(ds.kron(ds.array(a), ds.array(b)).collect(),
                                   np.kron(a, b), rtol=1e-5)

    def test_kron_irregular(self, rng):
        a, b = rng.rand(7, 3), rng.rand(2, 9)
        np.testing.assert_allclose(ds.kron(ds.array(a), ds.array(b)).collect(),
                                   np.kron(a, b), rtol=1e-5)

    def test_kron_large_product_stays_sharded(self, rng):
        """VERDICT r2 #8: an 8192x8192 product (256 MB f32) — far past a
        single virtual device's plausible share — computes with each device
        holding only its output shard plus the (small) operands."""
        a = ds.array(rng.rand(512, 512).astype(np.float32))
        b = ds.array(rng.rand(16, 16).astype(np.float32))
        c = ds.kron(a, b)
        assert c.shape == (8192, 8192)
        total = 8192 * 8192 * 4
        ndev = len({s.device for s in c._data.addressable_shards})
        for s in c._data.addressable_shards:
            assert s.data.nbytes <= total // ndev
        # spot-check values without materialising np.kron on host
        ah, bh = a.collect(), b.collect()
        got = np.asarray(c._data[1000:1002, 2000:2004])
        want = np.stack([
            [ah[r // 16, cc // 16] * bh[r % 16, cc % 16]
             for cc in range(2000, 2004)] for r in range(1000, 1002)])
        np.testing.assert_allclose(got, want, rtol=1e-5)
        # global invariant: sum(kron(a,b)) == sum(a)·sum(b)
        np.testing.assert_allclose(
            float(c.sum(axis=None).collect()[0, 0]),
            float(ah.sum()) * float(bh.sum()), rtol=1e-3)


class TestQR:
    @pytest.mark.parametrize("shape", [(16, 16), (20, 8), (9, 9)])
    def test_full(self, rng, shape):
        x = rng.rand(*shape)
        q, r = ds.qr(ds.array(x), mode="full")
        qc, rc = q.collect(), r.collect()
        assert qc.shape == (shape[0], shape[0])
        np.testing.assert_allclose(qc @ rc, x, atol=1e-4)
        np.testing.assert_allclose(qc.T @ qc, np.eye(shape[0]), atol=1e-4)
        np.testing.assert_allclose(np.tril(rc[:, :shape[1]], -1), 0, atol=1e-5)

    def test_economic(self, rng):
        x = rng.rand(20, 6)
        q, r = ds.qr(ds.array(x), mode="economic")
        assert q.collect().shape == (20, 6)
        assert r.collect().shape == (6, 6)
        np.testing.assert_allclose(q.collect() @ r.collect(), x, atol=1e-4)

    def test_r_mode(self, rng):
        x = rng.rand(10, 4)
        r = ds.qr(ds.array(x), mode="r").collect()
        rn = np.linalg.qr(x, mode="r")
        np.testing.assert_allclose(np.abs(r), np.abs(rn), atol=1e-4)

    def test_bad_mode(self, rng):
        with pytest.raises(ValueError):
            ds.qr(ds.array(rng.rand(4, 4)), mode="zzz")


class TestBlockedQR:
    """The distributed panel-loop path (VERDICT r1 #5): tsQR panels +
    sharded trailing GEMMs, full operand never gathered."""

    @pytest.mark.parametrize("shape", [(256, 130), (300, 97), (192, 64)])
    def test_invariants_irregular(self, rng, shape, monkeypatch):
        import importlib
        qr_mod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qr_mod, "_PANEL", 32)
        x = rng.rand(*shape).astype(np.float32)
        q, r = ds.qr(ds.array(x, block_size=(64, 32)), mode="economic")
        qc, rc = q.collect(), r.collect()
        assert qc.shape == shape and rc.shape == (shape[1], shape[1])
        np.testing.assert_allclose(qc @ rc, x, atol=1e-3)
        np.testing.assert_allclose(qc.T @ qc, np.eye(shape[1]), atol=1e-3)
        np.testing.assert_allclose(np.tril(rc, -1), 0, atol=1e-4)

    @pytest.mark.parametrize("shape", [(256, 64), (320, 40)])
    def test_full_mode_distributed(self, rng, shape, monkeypatch):
        """VERDICT r2 #5: mode='full' runs the panel loop + random-completion
        complement at blocked sizes — Q (m, m) orthonormal, QR == A."""
        import importlib
        qr_mod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qr_mod, "_PANEL", 32)
        m, n = shape
        x = rng.rand(m, n).astype(np.float32)
        q, r = ds.qr(ds.array(x), mode="full")
        qc, rc = q.collect(), r.collect()
        assert qc.shape == (m, m) and rc.shape == (m, n)
        np.testing.assert_allclose(qc @ rc, x, atol=1e-3)
        np.testing.assert_allclose(qc.T @ qc, np.eye(m), atol=1e-3)
        np.testing.assert_allclose(np.tril(rc[:n, :n], -1), 0, atol=1e-4)
        assert np.allclose(rc[n:], 0)

    def test_r_mode_matches_numpy(self, rng, monkeypatch):
        import importlib
        qr_mod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qr_mod, "_PANEL", 32)
        x = rng.rand(256, 80).astype(np.float32)
        r = ds.qr(ds.array(x), mode="r").collect()
        rn = np.linalg.qr(x, mode="r")
        np.testing.assert_allclose(np.abs(r), np.abs(rn), atol=1e-3)

    def test_never_gathers_full_operand(self, rng):
        """Compiled-HLO assertion: on a multi-device rows mesh, no
        all-gather materialises the full (mp, n_pad) operand."""
        import jax
        import jax.numpy as jnp
        from dislib_tpu.math.qr import _qr_blocked
        from dislib_tpu.parallel import mesh as _mesh
        mesh = _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        if p == 1:
            pytest.skip("needs a multi-device rows axis")
        mp, n = 2048 * p, 1024
        ap = jax.device_put(jnp.zeros((mp, n), jnp.float32),
                            _mesh.row_sharding())
        compiled = _qr_blocked.lower(ap, (mp, n), mesh, p, 256,
                                     cholqr=False).compile()
        hlo = compiled.as_text()
        full_elems = (mp * n)
        import re
        for m_ in re.finditer(r"all-gather[^\n]*f32\[([\d,]+)\]", hlo):
            dims = [int(d) for d in m_.group(1).split(",")]
            elems = 1
            for d in dims:
                elems *= d
            assert elems < full_elems, \
                f"all-gather of {dims} covers the full operand"


class TestTSQR:
    @pytest.mark.parametrize("shape", [(64, 8), (100, 13), (8, 8), (1000, 3)])
    def test_reduced(self, rng, shape):
        x = rng.rand(*shape)
        q, r = ds.tsqr(ds.array(x))
        qc, rc = q.collect(), r.collect()
        assert qc.shape == shape and rc.shape == (shape[1], shape[1])
        np.testing.assert_allclose(qc @ rc, x, atol=1e-4)
        np.testing.assert_allclose(qc.T @ qc, np.eye(shape[1]), atol=1e-4)

    def test_r_mode(self, rng):
        x = rng.rand(64, 4)
        r = ds.tsqr(ds.array(x), mode="r").collect()
        # R unique up to row signs
        rn = np.linalg.qr(x, mode="r")
        np.testing.assert_allclose(np.abs(r), np.abs(rn), atol=1e-4)

    def test_wide_raises(self, rng):
        with pytest.raises(ValueError):
            ds.tsqr(ds.array(rng.rand(4, 8)))

    def test_local_tree_path(self, rng):
        # shard rows (512/8 = 64) ≥ 16·n with power-of-two divisibility, so
        # _local_tsqr actually recurses (s > 1) instead of degrading to one
        # flat QR — pin the batched-tree path's invariants
        from dislib_tpu.decomposition.tsqr import _split_count
        assert _split_count(512, 2) > 1            # tree engaged at this shape
        x = rng.rand(512, 2)
        q, r = ds.tsqr(ds.array(x))
        qc, rc = q.collect(), r.collect()
        np.testing.assert_allclose(qc @ rc, x, atol=1e-4)
        np.testing.assert_allclose(qc.T @ qc, np.eye(2), atol=1e-4)
        assert np.allclose(rc, np.triu(rc))


class TestSVD:
    @pytest.mark.parametrize("shape", [(16, 8), (30, 30), (50, 7)])
    def test_svd(self, rng, shape):
        x = rng.rand(*shape)
        u, s, v = ds.svd(ds.array(x))
        uc, sc, vc = u.collect(), s.collect().ravel(), v.collect()
        sn = np.linalg.svd(x, compute_uv=False)
        np.testing.assert_allclose(sc, sn, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(uc * sc @ vc.T, x, atol=1e-3)
        np.testing.assert_allclose(uc.T @ uc, np.eye(shape[1]), atol=1e-3)
        np.testing.assert_allclose(vc.T @ vc, np.eye(shape[1]), atol=1e-3)

    def test_values_only(self, rng):
        x = rng.rand(12, 6)
        s = ds.svd(ds.array(x), compute_uv=False).collect().ravel()
        np.testing.assert_allclose(s, np.linalg.svd(x, compute_uv=False),
                                   rtol=1e-3, atol=1e-4)


class TestRandomSVD:
    def test_low_rank_recovery(self, rng):
        # rank-5 matrix: randomized SVD should nail the spectrum
        a = rng.rand(60, 5) @ rng.rand(5, 40)
        u, s, v = ds.random_svd(ds.array(a), nsv=5, random_state=0)
        sn = np.linalg.svd(a, compute_uv=False)[:5]
        np.testing.assert_allclose(s.collect().ravel(), sn, rtol=1e-3)
        np.testing.assert_allclose((u.collect() * s.collect().ravel()) @ v.collect().T,
                                   a, atol=1e-2)

    def test_irregular_shape(self, rng):
        # rows/cols not multiples of the device count or pad quantum
        a = rng.rand(61, 6) @ rng.rand(6, 37)
        u, s, v = ds.random_svd(ds.array(a), nsv=6, random_state=3)
        sn = np.linalg.svd(a, compute_uv=False)[:6]
        np.testing.assert_allclose(s.collect().ravel(), sn, rtol=1e-3)
        np.testing.assert_allclose((u.collect() * s.collect().ravel()) @ v.collect().T,
                                   a, atol=1e-2)

    def test_fused_matches_composed(self, rng):
        # the m >= sketch fast path is a single jitted program; the m < sketch
        # case runs the original host-composed stages.  Same seed → same
        # Gaussian test matrix → the two paths must agree on the (converged)
        # spectrum and subspace reconstruction.
        a = rng.rand(80, 5) @ rng.rand(5, 30)
        u1, s1, v1 = ds.random_svd(ds.array(a), nsv=5, random_state=7)

        from dislib_tpu.data.array import Array

        class _View(Array):  # fails the `type(a) is Array` fast-path gate
            pass

        composed = ds.array(a)
        composed.__class__ = _View
        u2, s2, v2 = ds.random_svd(composed, nsv=5, random_state=7)
        np.testing.assert_allclose(s1.collect(), s2.collect(), rtol=1e-4)
        r1 = (u1.collect() * s1.collect().ravel()) @ v1.collect().T
        r2 = (u2.collect() * s2.collect().ravel()) @ v2.collect().T
        np.testing.assert_allclose(r1, r2, atol=1e-4)

    def test_wide_fallback(self, rng):
        # m < sketch exercises the composed path's economic-QR fallback
        a = rng.rand(8, 40)
        u, s, v = ds.random_svd(ds.array(a), nsv=4, oversample=10,
                                random_state=0)
        sn = np.linalg.svd(a, compute_uv=False)[:4]
        np.testing.assert_allclose(s.collect().ravel(), sn, rtol=1e-2)


class TestLanczosSVD:
    def test_spectrum(self, rng):
        x = rng.rand(40, 20)
        _, s, _ = ds.lanczos_svd(ds.array(x), k=4)
        sn = np.linalg.svd(x, compute_uv=False)[:4]
        np.testing.assert_allclose(s.collect().ravel(), sn, rtol=1e-2)


class TestPCA:
    def test_vs_sklearn(self, rng):
        from sklearn.decomposition import PCA as SkPCA
        x = rng.rand(100, 10).astype(np.float32)
        p = ds.PCA(n_components=4).fit(ds.array(x))
        sk = SkPCA(n_components=4).fit(x)
        np.testing.assert_allclose(p.explained_variance_.collect().ravel(),
                                   sk.explained_variance_, rtol=1e-3)
        np.testing.assert_allclose(np.abs(p.components_.collect()),
                                   np.abs(sk.components_), atol=1e-3)
        np.testing.assert_allclose(p.mean_.collect().ravel(), sk.mean_, rtol=1e-4)

    def test_transform_roundtrip(self, rng):
        x = rng.rand(50, 8).astype(np.float32)
        p = ds.PCA()  # all components
        t = p.fit_transform(ds.array(x))
        back = p.inverse_transform(t).collect()
        np.testing.assert_allclose(back, x, atol=1e-3)

    def test_svd_method(self, rng):
        x = rng.rand(60, 6).astype(np.float32)
        p = ds.PCA(n_components=3, method="svd").fit(ds.array(x))
        from sklearn.decomposition import PCA as SkPCA
        sk = SkPCA(n_components=3).fit(x)
        np.testing.assert_allclose(p.explained_variance_.collect().ravel(),
                                   sk.explained_variance_, rtol=1e-3)


class TestBlockJacobiSVD:
    def test_block_tier_matches_numpy(self, rng):
        # n >= 2*_JACOBI_BLOCK engages the block tier; include a ragged n
        # so the zero pad block exercises the NaN-proof off metric
        for (m, n) in [(300, 130), (200, 150)]:
            x = rng.rand(m, n).astype(np.float32)
            u, s, v = ds.svd(ds.array(x))
            uc, sc, vc = u.collect(), np.asarray(s.collect()).ravel(), v.collect()
            s_ref = np.linalg.svd(x, compute_uv=False)
            np.testing.assert_allclose(sc, s_ref, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(uc @ np.diag(sc) @ vc.T, x, atol=1e-3)
            np.testing.assert_allclose(uc.T @ uc, np.eye(n), atol=1e-3)
            np.testing.assert_allclose(vc.T @ vc, np.eye(n), atol=1e-3)

    def test_block_tier_engaged(self):
        from dislib_tpu.math.base import _JACOBI_BLOCK
        assert 130 >= 2 * _JACOBI_BLOCK  # shapes above actually take the tier

    def test_block_tier_ill_conditioned(self, rng):
        """6-decade geometric spectrum: errors stay at the f32 floor
        relative to sigma_max, orthogonality at machine precision, no NaN
        (the QR+small-SVD pair solve is conditioning-independent)."""
        m, n = 600, 192
        u0, _ = np.linalg.qr(rng.standard_normal((m, n)))
        v0, _ = np.linalg.qr(rng.standard_normal((n, n)))
        sv = np.logspace(3, -3, n).astype(np.float32)
        x = ((u0 * sv) @ v0.T).astype(np.float32)
        u, s, v = ds.svd(ds.array(x))
        sc = np.asarray(s.collect()).ravel()
        s_ref = np.linalg.svd(x, compute_uv=False)
        assert not np.isnan(sc).any()
        assert np.abs(sc - s_ref).max() / s_ref[0] < 1e-4
        uc, vc = u.collect(), v.collect()
        np.testing.assert_allclose(uc.T @ uc, np.eye(n), atol=1e-4)
        np.testing.assert_allclose(vc.T @ vc, np.eye(n), atol=1e-4)


class TestCholQR2:
    """Round-4 TPU fast path: CholeskyQR2 local factorisation (forced via
    DSLIB_TSQR_CHOLQR=1 on the rig — the auto policy enables it on TPU)."""

    def _force(self, monkeypatch):
        monkeypatch.setenv("DSLIB_TSQR_CHOLQR", "1")

    def test_tsqr_cholqr_matches_oracle(self, rng, monkeypatch):
        self._force(monkeypatch)
        x = rng.standard_normal((1024, 32)).astype(np.float32)
        q, r = ds.tsqr(ds.array(x, block_size=(128, 32)))
        qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
        np.testing.assert_allclose(qh @ rh, x, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(qh.T @ qh, np.eye(32), atol=5e-5)
        # R upper triangular
        assert np.allclose(rh, np.triu(rh), atol=1e-6)

    def test_cholqr_breakdown_falls_back_exact(self, rng, monkeypatch):
        """Numerically singular columns break the Gram Cholesky; the
        in-program fallback must deliver tree-QR accuracy anyway."""
        self._force(monkeypatch)
        base = rng.standard_normal((512, 8)).astype(np.float32)
        x = np.hstack([base, base + 1e-8 * rng.standard_normal((512, 8))
                       .astype(np.float32)]).astype(np.float32)
        q, r = ds.tsqr(ds.array(x, block_size=(64, 16)))
        qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
        np.testing.assert_allclose(qh @ rh, x, rtol=1e-3, atol=1e-3)
        # orthogonality of the RANGE part still holds to tree-QR quality
        assert np.abs(qh.T @ qh - np.eye(16)).max() < 1e-2

    @pytest.mark.skipif(os.environ.get("DSLIB_TEST_TPU") != "1",
                        reason="breakdown band is an MXU-rounding property "
                               "— meaningful on the real chip only")
    def test_cholqr_breakdown_band_on_chip(self, rng, monkeypatch):
        """Round-5 (VERDICT #3): probe the cond(A) band around u^(-1/2)
        under the actual MXU rounding the `precise`-scoped Gram gets on
        chip.  Sweep cond 1e2 → 1e8 with forced cholqr: the quality gate's
        `ok` must hold at benign cond, the fallback MUST fire by 1e6, and
        end-to-end orthogonality stays < 1e-3 at every cond (lose speed,
        never accuracy)."""
        self._force(monkeypatch)
        import jax
        from dislib_tpu.decomposition.tsqr import _cholqr2
        from dislib_tpu.ops.base import precise
        m, n = 4096, 128
        u0, _ = np.linalg.qr(rng.standard_normal((m, n)))
        v0, _ = np.linalg.qr(rng.standard_normal((n, n)))
        gate = jax.jit(precise(_cholqr2))
        oks = {}
        for cond in (1e2, 1e4, 1e6, 1e8):
            spec = np.logspace(0, -np.log10(cond), n).astype(np.float32)
            x = ((u0 * spec) @ v0.T).astype(np.float32)
            _, _, ok = gate(x)
            oks[cond] = bool(ok)
            q, r = ds.tsqr(ds.array(x, block_size=(512, n)))
            qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
            ortho = np.abs(qh.T @ qh - np.eye(n)).max()
            assert ortho < 1e-3, f"cond={cond:g}: orthogonality {ortho}"
            assert np.abs(qh @ rh - x).max() < 1e-3 * spec[0], \
                f"cond={cond:g}: reconstruction"
        assert oks[1e2], f"quality gate refused a benign matrix: {oks}"
        assert not oks[1e6] and not oks[1e8], \
            f"fallback did not fire in the breakdown band: {oks}"

    def test_randomsvd_and_blocked_qr_with_cholqr(self, rng, monkeypatch):
        self._force(monkeypatch)
        from dislib_tpu.decomposition import random_svd
        # decaying spectrum: randomized SVD is only accurate when the tail
        # is well separated (a flat gaussian spectrum is ~5% off for ANY
        # local-QR flavor — verified identical with the tree path)
        u0, _ = np.linalg.qr(rng.standard_normal((512, 64)))
        v0, _ = np.linalg.qr(rng.standard_normal((64, 64)))
        spec = (2.0 ** -np.arange(64)).astype(np.float32) * 100
        x = (u0 * spec) @ v0.T
        x = x.astype(np.float32)
        u, s, v = random_svd(ds.array(x, block_size=(64, 64)), iters=2,
                             nsv=8, oversample=8, random_state=0)
        s_ref = np.linalg.svd(x, compute_uv=False)
        np.testing.assert_allclose(np.asarray(s.collect()).ravel()[:8],
                                   s_ref[:8], rtol=1e-2)
        # force the BLOCKED qr path (panel loop + cholqr local factors):
        # the default _PANEL (256) would route 64 columns to the
        # replicated fallback kernel, skipping the integration under test
        import importlib
        qr_mod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qr_mod, "_PANEL", 16)
        qf, rf = ds.qr(ds.array(x, block_size=(64, 64)))
        np.testing.assert_allclose(
            np.asarray(qf.collect()) @ np.asarray(rf.collect()), x,
            rtol=1e-3, atol=1e-3)


def test_randomsvd_smoke_gate_margin(rng):
    """Regression pin for the bench_randomsvd smoke gate (round-8 satellite).

    The pre-round-8 gate drew a FLAT Gaussian spectrum: with oversample=10
    the device path and the numpy proxy each carry ~6% subspace error and —
    because they draw different test matrices Ω (jax vs numpy RNG) — differ
    from EACH OTHER by up to ~1.5%, flaking a 1% gate (reproduced back to
    PR 1 on this rig).  bench.py now scales columns by 0.95^j, the decaying
    spectrum truncated SVD is actually for; this test replays the exact
    smoke-config comparison and demands ≥2x margin under the 1% gate so a
    regression (in the data recipe OR the sketching path) fails here first."""
    import bench
    from dislib_tpu.decomposition import random_svd
    m, n, nsv, iters = 1024, 128, 16, 2
    r0 = np.random.RandomState(0)
    x = (r0.standard_normal((m, n)) * 0.95 ** np.arange(n)).astype(np.float32)
    _, s_proxy, _ = bench._numpy_random_svd(x, nsv + 10, iters)
    a = ds.array(x, block_size=(m // 8, n))
    _, s, _ = random_svd(a, iters=iters, nsv=nsv, oversample=10,
                         random_state=0)
    s_dev = np.asarray(s.collect()).ravel()[:16]
    rel = np.max(np.abs(s_dev - s_proxy[:16]) / s_proxy[:16])
    assert rel < 5e-3, (
        f"smoke-gate margin regressed: dev-vs-proxy rel err {rel:.4f} "
        "(gate is 1e-2; this pin demands >=2x headroom)")
    # and the gate itself must hold against the EXACT spectrum too — the
    # proxy agreeing with the device path is necessary but not sufficient
    s_ref = np.linalg.svd(x, compute_uv=False)[:16]
    np.testing.assert_allclose(s_dev, s_ref, rtol=1e-2)
