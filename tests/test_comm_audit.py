"""Communication audits for the north-star fit programs (SURVEY §3.7).

The SPMD memory contract behind every scale claim: a fit over row-sharded
data reduces small statistics (psum → all-reduce of (k, n)-sized tensors)
but NEVER all-gathers the (m, n) operand onto one device.  The reference
holds this by construction (per-block tasks + arity-tree merges of
partials); here it must be pinned, because one misplaced sharding
constraint would make XLA "helpfully" gather — correct results, broken
memory scaling, invisible to oracle tests.  Same technique as
test_math.py's QR gather audit: compile at a sharded shape and inspect the
HLO's collectives.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.parallel import mesh as _mesh


def _collective_sizes(hlo, op):
    """Per-instruction result element counts of every `op` in the HLO text.

    HLO instructions read ``%name = <shape(s)> op(...)`` — the result shape
    PRECEDES the op keyword (JAX often renames the instruction, e.g.
    ``%ppermute.9 = f32[128,16] collective-permute(...)``), so the parse
    anchors on the ``op(`` call and sums the shape tokens between ``=`` and
    it (tuple-shaped collectives contribute all their element counts).
    ``-start`` async variants (TPU latency-hiding scheduler) are matched
    too; their result tuple aliases the SOURCE buffer next to the
    destination (plus u32 context scalars), so summing it would double the
    true volume — for those the largest single shape token (= the
    destination; for all-gather-start the gathered output is the largest)
    is counted instead."""
    sizes = []
    for line in hlo.splitlines():
        m_ = re.search(r"=\s+(.*?)\b" + op + r"(-start)?\(", line)
        if not m_:
            continue
        toks = []
        for dims in re.findall(r"\w+\[([\d,]*)\]", m_.group(1)):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            toks.append(n)
        elems = (max(toks) if m_.group(2) else sum(toks)) if toks else 0
        if elems:
            sizes.append(elems)
    return sizes


def _assert_no_operand_gather(hlo, full_elems):
    for op in ("all-gather", "all-to-all"):
        for elems in _collective_sizes(hlo, op):
            assert elems < full_elems, \
                f"{op} of {elems} elems covers the full {full_elems} operand"


class TestFitCommAudit:
    M, N = 4096, 32

    def _sharded(self, rng):
        # collectives only exist on a multi-device rows axis (the on-chip
        # run has ONE device — same skip as the QR gather audit)
        if _mesh.get_mesh().shape[_mesh.ROWS] < 2:
            pytest.skip("needs a multi-device rows axis")
        x = rng.rand(self.M, self.N).astype(np.float32)
        return ds.array(x, block_size=(self.M // 8, self.N)), x

    def test_kmeans_fit_never_gathers_data(self, rng):
        from dislib_tpu.cluster.kmeans import _kmeans_fit
        a, x = self._sharded(rng)
        c0 = jnp.asarray(x[:4])
        hlo = _kmeans_fit.lower(a._data, a.shape, c0, 3, 0.0,
                                fast=False).compile().as_text()
        _assert_no_operand_gather(hlo, self.M * self.N)
        # the psum of per-cluster (Σx, count) partials must be there — the
        # reference's arity-tree merge, as an all-reduce over 'rows'
        assert "all-reduce" in hlo

    def test_gmm_fit_never_gathers_data(self, rng):
        from dislib_tpu.cluster.gm import _gm_fit
        a, x = self._sharded(rng)
        resp0 = jnp.ones((a._data.shape[0], 3), jnp.float32) / 3.0
        hlo = _gm_fit.lower(a._data, a.shape, resp0, "full", 1e-6, 0.0,
                            3).compile().as_text()
        # responsibilities are (m, k) row-sharded state — also never gathered
        _assert_no_operand_gather(hlo, self.M * 3)
        _assert_no_operand_gather(hlo, self.M * self.N)
        assert "all-reduce" in hlo

    def test_kmeans_per_device_memory_scales(self, rng):
        """memory_analysis: per-device temporaries stay ~O(m/p · (n + k)),
        nowhere near a replicated (m, n) copy of the operand."""
        from dislib_tpu.cluster.kmeans import _kmeans_fit
        a, x = self._sharded(rng)
        c0 = jnp.asarray(x[:4])
        mem = _kmeans_fit.lower(a._data, a.shape, c0, 3, 0.0,
                                fast=False).compile().memory_analysis()
        if mem is None:
            pytest.skip("backend reports no memory analysis")
        full = self.M * self.N * 4
        assert mem.temp_size_in_bytes < full, \
            f"per-device temp {mem.temp_size_in_bytes} >= full operand {full}"


def _needs_multirow():
    if _mesh.get_mesh().shape[_mesh.ROWS] < 2:
        pytest.skip("needs a multi-device rows axis")


class TestMatmul2DMeshAudit:
    """The SPMD partitioner's schedule for the 2-D-sharded GEMM.

    Oracle tests prove the matmul's VALUES; nothing before round 4 proved
    the partitioner doesn't win them by all-gathering a full operand per
    device — a decision that would survive every correctness test and only
    surface as a perf/memory collapse on real multi-chip hardware (round-3
    verdict weak #5).  A SUMMA-plausible schedule moves contraction-dim
    panels: every collective must be strictly smaller than a full operand.
    """

    DIM = 512

    def test_2d_mesh_matmul_collectives_subfull(self, rng):
        import dislib_tpu as ds_
        from dislib_tpu.math.base import _matmul_kernel
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        ds_.init((4, 2))
        try:
            x = rng.rand(self.DIM, self.DIM).astype(np.float32)
            a = ds_.array(x, block_size=(self.DIM // 4, self.DIM // 2))
            from dislib_tpu.ops import precision as px
            hlo = _matmul_kernel.lower(a._data, a._data, False, False,
                                       a.shape, a.shape,
                                       px.FLOAT32).compile().as_text()
            full = self.DIM * self.DIM
            for op in ("all-gather", "all-to-all", "collective-permute"):
                for elems in _collective_sizes(hlo, op):
                    assert elems < full, \
                        f"{op} of {elems} elems = a full operand replicated"
            # and the schedule must actually communicate on a 2-D mesh —
            # a silent full-replication of inputs would show zero collectives
            assert any(_collective_sizes(hlo, op) or (op in hlo)
                       for op in ("all-gather", "collective-permute",
                                  "all-reduce")), \
                "no collectives at all — operands were not sharded"
        finally:
            ds_.init()

    def test_2d_mesh_matmul_memory_scales(self, rng):
        import dislib_tpu as ds_
        from dislib_tpu.math.base import _matmul_kernel
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        ds_.init((4, 2))
        try:
            x = rng.rand(self.DIM, self.DIM).astype(np.float32)
            a = ds_.array(x, block_size=(self.DIM // 4, self.DIM // 2))
            from dislib_tpu.ops import precision as px
            mem = _matmul_kernel.lower(a._data, a._data, False, False,
                                       a.shape, a.shape,
                                       px.FLOAT32).compile().memory_analysis()
            if mem is None:
                pytest.skip("backend reports no memory analysis")
            full = self.DIM * self.DIM * 4
            # per-device working set is the gathered contraction panels
            # (m·k/cols + k·n/rows ≈ 0.75 operands at this square shape on
            # a 4×2 mesh) plus the output shard — the contract is that it
            # stays strictly below replicating BOTH operands, which is what
            # a partitioner bailing out of SUMMA would do
            assert mem.temp_size_in_bytes < 2 * full, \
                f"per-device temp {mem.temp_size_in_bytes} >= both " \
                f"operands ({2 * full}) — partitioner replicated the GEMM"
        finally:
            ds_.init()


class TestShuffleCommAudit:
    """The all-to-all shuffle moves each row once: exchange buffers are
    O(shard · slack), never a gathered copy of the operand."""

    M, N = 2048, 16

    def test_shuffle_alltoall_volume(self, rng):
        _needs_multirow()
        from dislib_tpu.utils.base import _routing, _shuffle_exchange
        mesh = _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        x = rng.rand(self.M, self.N).astype(np.float32)
        a = ds.array(x, block_size=(self.M // p, self.N))
        m_loc = a._data.shape[0] // p
        perm = rng.permutation(self.M)
        send_idx, dst_idx = _routing(perm, m_loc, p)
        hlo = _shuffle_exchange.lower(
            a._data, jnp.asarray(send_idx), jnp.asarray(dst_idx), mesh,
            p).compile().as_text()
        full = a._data.shape[0] * a._data.shape[1]
        cap = send_idx.shape[-1]
        sizes = _collective_sizes(hlo, "all-to-all")
        assert sizes, "shuffle compiled without an all-to-all"
        for elems in sizes:
            # per-device exchange buffer: (p, cap, n) — one shard + the
            # bucket-imbalance slack of a random permutation, o(operand)
            assert elems <= p * cap * a._data.shape[1], \
                f"all-to-all of {elems} elems exceeds the routing plan"
            assert elems < full, \
                f"all-to-all of {elems} elems covers the operand ({full})"
        _assert_no_operand_gather(hlo, full)


class TestSparseStagingCommAudit:
    """The round-4 sparse staging paths: CSVM's ELL node solves and the
    sparse-fit kNN stream must not smuggle operand-sized collectives in."""

    def test_csvm_ell_level_no_operand_collectives(self, rng):
        """A cascade level over ELL staging is node-local batched work —
        any operand-scale collective means the partitioner replicated or
        regathered the staging buffers."""
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.classification.csvm import _solve_level_ell
        m, n = 512, 32
        xs = sp.random(m, n, density=0.1, random_state=42,
                       dtype=np.float32).tocsr()
        sa = SparseArray.from_scipy(xs)
        ev, ec = sa.ell()
        yv = jnp.asarray(np.where(rng.rand(m) > 0.5, 1.0, -1.0)
                         .astype(np.float32))
        nodes = jnp.asarray(np.arange(m).reshape(4, m // 4))
        # audit BOTH solver policies — the fista trace adds momentum
        # carries that must stay node-local too
        for solver in ("pg", "fista"):
            hlo = _solve_level_ell.lower(ev, ec, yv, nodes, 1.0, n, "rbf",
                                         1.0 / n, solver) \
                .compile().as_text()
            _assert_no_operand_gather(hlo, m * n)
            for elems in _collective_sizes(hlo, "all-reduce"):
                assert elems < m * n

    def test_sparse_knn_no_query_gather(self, rng):
        """Dense queries over a sparse fit stream: the query operand and
        the running top-k stay row-sharded; the only replicated tensors
        are the bounded O(chunk·n) windows."""
        _needs_multirow()
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.neighbors import NearestNeighbors
        from dislib_tpu.neighbors.base import (_kneighbors_sparse_sharded_q,
                                               _CHUNK)
        mq, mf, n, k = 4096, 600, 16, 3
        f = SparseArray.from_scipy(sp.random(mf, n, density=0.1,
                                             random_state=0,
                                             dtype=np.float32).tocsr())
        q = ds.array(rng.rand(mq, n).astype(np.float32),
                     block_size=(mq // 8, n))
        chunk = min(_CHUNK, mf)
        hlo = _kneighbors_sparse_sharded_q.lower(
            q._data, *f.row_steps(chunk), n=n, mq=mq, mf=mf, k=k,
            chunk=chunk, mesh=_mesh.get_mesh()).compile().as_text()
        _assert_no_operand_gather(hlo, mq * n)
        for op in ("all-gather", "all-to-all", "collective-permute"):
            for elems in _collective_sizes(hlo, op):
                assert elems < mq * n, \
                    f"{op} of {elems} elems covers the query operand"
        # and the result must actually be correct at this sharded shape
        nn = NearestNeighbors(n_neighbors=k).fit(f)
        d, i = nn.kneighbors(q)
        xd = f.collect().toarray()
        qd = np.asarray(q.collect())
        ref = np.sqrt(np.maximum(
            (qd * qd).sum(1)[:, None] - 2 * qd @ xd.T
            + (xd * xd).sum(1)[None], 0.0))
        np.testing.assert_allclose(np.sort(np.asarray(d.collect()), axis=1),
                                   np.sort(np.sort(ref, axis=1)[:, :k],
                                           axis=1), rtol=1e-4, atol=1e-4)


    def test_sparse_query_knn_no_gather(self, rng):
        """Sparse queries (round-4b): per-shard local BCOO from
        sharded_rows + replicated windows — no operand-scale collective."""
        _needs_multirow()
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.neighbors import NearestNeighbors
        from dislib_tpu.neighbors.base import (_kneighbors_sparse_sharded_sq,
                                               _CHUNK)
        mq, mf, n, k = 2048, 500, 16, 3
        q = SparseArray.from_scipy(sp.random(mq, n, density=0.15,
                                             random_state=1,
                                             dtype=np.float32).tocsr())
        f = SparseArray.from_scipy(sp.random(mf, n, density=0.1,
                                             random_state=0,
                                             dtype=np.float32).tocsr())
        mesh = _mesh.get_mesh()
        chunk = min(_CHUNK, mf)
        qdat, qlr, qcol, qrsq = q.sharded_rows(mesh)
        hlo = _kneighbors_sparse_sharded_sq.lower(
            qdat, qlr, qcol, qrsq, *f.row_steps(chunk), None, n=n, mq=mq,
            mf=mf, k=k, chunk=chunk, mesh=mesh).compile().as_text()
        _assert_no_operand_gather(hlo, mq * n)
        for op in ("all-gather", "all-to-all", "collective-permute"):
            for elems in _collective_sizes(hlo, op):
                assert elems < mq * n, \
                    f"{op} of {elems} elems covers the query operand"
        # oracle at the sharded shape, both fit kinds
        qd = q.collect().toarray()
        for fit in (f, ds.array(f.collect().toarray())):
            d, i = NearestNeighbors(n_neighbors=k).fit(fit).kneighbors(q)
            xd = f.collect().toarray()
            ref = np.sqrt(np.maximum(
                (qd * qd).sum(1)[:, None] - 2 * qd @ xd.T
                + (xd * xd).sum(1)[None], 0.0))
            np.testing.assert_allclose(
                np.asarray(d.collect()), np.sort(ref, axis=1)[:, :k],
                rtol=1e-4, atol=1e-4)


class TestRingKnnCommAudit:
    """Ring kNN rotates one fitted SHARD per hop (ppermute); the fitted set
    never materialises on one device."""

    M, N, K = 1024, 16, 5

    def test_ring_ppermute_volume(self, rng):
        _needs_multirow()
        from dislib_tpu.ops.ring import ring_kneighbors
        mesh = _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        x = rng.rand(self.M, self.N).astype(np.float32)
        a = ds.array(x, block_size=(self.M // p, self.N))
        hlo = ring_kneighbors.lower(a._data, a._data, mesh, self.K,
                                    self.M).compile().as_text()
        shard = (a._data.shape[0] // p) * a._data.shape[1]
        full = a._data.shape[0] * a._data.shape[1]
        sizes = _collective_sizes(hlo, "collective-permute")
        assert sizes, "ring compiled without a collective-permute"
        for elems in sizes:
            assert elems <= shard, \
                f"ppermute of {elems} elems exceeds one fitted shard ({shard})"
        _assert_no_operand_gather(hlo, full)
