"""Communication audits for the north-star fit programs (SURVEY §3.7).

The SPMD memory contract behind every scale claim: a fit over row-sharded
data reduces small statistics (psum → all-reduce of (k, n)-sized tensors)
but NEVER all-gathers the (m, n) operand onto one device.  The reference
holds this by construction (per-block tasks + arity-tree merges of
partials); here it must be pinned, because one misplaced sharding
constraint would make XLA "helpfully" gather — correct results, broken
memory scaling, invisible to oracle tests.  Same technique as
test_math.py's QR gather audit: compile at a sharded shape and inspect the
HLO's collectives.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.parallel import mesh as _mesh


def _collective_sizes(hlo, op):
    """Element counts of every `op` collective in the HLO text."""
    sizes = []
    for m_ in re.finditer(op + r"[^\n]*?f32\[([\d,]*)\]", hlo):
        dims = [int(d) for d in m_.group(1).split(",") if d]
        elems = 1
        for d in dims:
            elems *= d
        sizes.append(elems)
    return sizes


def _assert_no_operand_gather(hlo, full_elems):
    for op in ("all-gather", "all-to-all"):
        for elems in _collective_sizes(hlo, op):
            assert elems < full_elems, \
                f"{op} of {elems} elems covers the full {full_elems} operand"


class TestFitCommAudit:
    M, N = 4096, 32

    def _sharded(self, rng):
        # collectives only exist on a multi-device rows axis (the on-chip
        # run has ONE device — same skip as the QR gather audit)
        if _mesh.get_mesh().shape[_mesh.ROWS] < 2:
            pytest.skip("needs a multi-device rows axis")
        x = rng.rand(self.M, self.N).astype(np.float32)
        return ds.array(x, block_size=(self.M // 8, self.N)), x

    def test_kmeans_fit_never_gathers_data(self, rng):
        from dislib_tpu.cluster.kmeans import _kmeans_fit
        a, x = self._sharded(rng)
        c0 = jnp.asarray(x[:4])
        hlo = _kmeans_fit.lower(a._data, a.shape, c0, 3, 0.0,
                                fast=False).compile().as_text()
        _assert_no_operand_gather(hlo, self.M * self.N)
        # the psum of per-cluster (Σx, count) partials must be there — the
        # reference's arity-tree merge, as an all-reduce over 'rows'
        assert "all-reduce" in hlo

    def test_gmm_fit_never_gathers_data(self, rng):
        from dislib_tpu.cluster.gm import _gm_fit
        a, x = self._sharded(rng)
        resp0 = jnp.ones((a._data.shape[0], 3), jnp.float32) / 3.0
        hlo = _gm_fit.lower(a._data, a.shape, resp0, "full", 1e-6, 0.0,
                            3).compile().as_text()
        # responsibilities are (m, k) row-sharded state — also never gathered
        _assert_no_operand_gather(hlo, self.M * 3)
        _assert_no_operand_gather(hlo, self.M * self.N)
        assert "all-reduce" in hlo

    def test_kmeans_per_device_memory_scales(self, rng):
        """memory_analysis: per-device temporaries stay ~O(m/p · (n + k)),
        nowhere near a replicated (m, n) copy of the operand."""
        from dislib_tpu.cluster.kmeans import _kmeans_fit
        a, x = self._sharded(rng)
        c0 = jnp.asarray(x[:4])
        mem = _kmeans_fit.lower(a._data, a.shape, c0, 3, 0.0,
                                fast=False).compile().memory_analysis()
        if mem is None:
            pytest.skip("backend reports no memory analysis")
        full = self.M * self.N * 4
        assert mem.temp_size_in_bytes < full, \
            f"per-device temp {mem.temp_size_in_bytes} >= full operand {full}"
