"""Host-sync lint (round-7 fusion PR satellite, the `test_xla_flags_policy`
pattern): estimator iteration loops must not read device values back to
host except through the blessed boundaries — `runtime.fetch` (retried,
async-capable, a fusion force point) or an explicit `force()`.

The per-dispatch host RTT on this rig is ~70 ms (BENCH_local_r05): ONE
stray `jax.device_get` / `float(device_scalar)` / `np.asarray(device_val)`
inside a fit loop reintroduces a per-iteration sync and silently costs
5-500x on chip.  This lint makes that a CPU test failure instead.

Policy, enforced by AST scan of the estimator packages:

1. inside any `for`/`while` loop, the raw sync spellings — `.device_get`,
   `np.asarray`, `.collect()`, `.block_until_ready()`, `float(<non-const>)`
   — are flagged; `fetch`/`_fetch` never is (it IS the blessed boundary);
2. flagged sites must be on the explicit allowlist below.  Every entry is
   a CHUNK-boundary loop (one sync per k-iteration device chunk, next to
   its snapshot) or the deliberately host-orchestrated irregular tier
   (cascade merges, async-trial collection) — NOT a per-iteration sync.
   Adding a new site means consciously extending the list with a reason.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ESTIMATOR_DIRS = (
    "dislib_tpu/cluster",
    "dislib_tpu/classification",
    "dislib_tpu/recommendation",
    "dislib_tpu/trees",
    "dislib_tpu/regression",
    "dislib_tpu/decomposition",
    "dislib_tpu/neighbors",
    "dislib_tpu/optimization",
    "dislib_tpu/model_selection",
    # round-9: the serving hot path — ONE fetch per served batch is the
    # whole design; a stray per-request sync here is the regression the
    # lint exists for
    "dislib_tpu/serving",
    # round-13: the overlap/panel kernels (summa, rechunk, ring, tiled,
    # overlap, pallas_kernels) — a host sync inside a panel loop would
    # serialize the very schedule the overlap PR exists to pipeline
    "dislib_tpu/ops",
    # round-18: the IVF retrieval tier — every list length is
    # host-computed at build; a device sync deciding a shape in the
    # search path would kill the one-dispatch contract
    "dislib_tpu/retrieval",
)

# single FILES scanned alongside the dirs — round-14: the sparse storage
# layer hosts the sharded buffers every sparse fast path consumes; a
# stray in-loop sync there would serialize every consumer at once.  (Its
# siblings io.py/array.py are host ingest/parsing by design.)
EXTRA_FILES = ("dislib_tpu/data/sparse.py",)

# (file, enclosing function) pairs allowed to host-sync inside a loop,
# each with the reason it is a boundary and not a per-iteration sync.
ALLOWLIST = {
    # (round-12: the chunked fit loops moved onto runtime.fitloop's
    # ChunkedFitLoop — their boundary syncs are the driver's now, and the
    # kmeans/gm/als fit() entries are gone: the lint's desired end state.
    # The estimator `step` closures sync only their chunk's convergence
    # scalars, OUTSIDE any estimator-file loop, except the cascade below.)
    # cascade SVM: the irregular tier — level merges are host-planned by
    # design (SURVEY §3.3), one sync per cascade level inside step()'s
    # level loop, never per solver iteration (those run in
    # lax.while_loop on device)
    ("dislib_tpu/classification/csvm.py", "step"),
    ("dislib_tpu/classification/csvm.py", "_merge_level"),
    ("dislib_tpu/classification/csvm.py", "k_of"),
    # (_solve_level_batched left the list in round-17: its batch loop now
    # pipelines through ops/overlap.host_pipeline — the blocking reads
    # live in the shared discipline, not in an estimator-file loop.)
    # async-trial grid search: block_until_ready/float AFTER every trial
    # of a fold is dispatched — the protocol's single collection point
    ("dislib_tpu/model_selection/search.py", "_block_tree"),
    ("dislib_tpu/model_selection/search.py", "_dispatch_fold"),
    ("dislib_tpu/model_selection/search.py", "fit"),
    # serving AOT warmup: one sync per BUCKET at warm time (adoption /
    # server start), never on the request path — the hot path's only
    # sync is the blessed runtime.fetch inside predict_bucket
    ("dislib_tpu/serving/cache.py", "warm"),
    # round-15 bundle EXPORT: one sync per operand leaf while serializing
    # the compiled ladder to disk — offline deployment packaging by
    # definition; the bundle's serve path (BundlePipeline.predict_bucket)
    # syncs only through the blessed runtime.fetch
    ("dislib_tpu/serving/bundle.py", "export_bundle"),
    # round-19 split export_bundle into the shared AOT-capture loop and
    # the sharded-fleet writer — the SAME offline packaging boundary as
    # the export_bundle entry above, one sync per leaf/state value at
    # export time, never on the serve path
    ("dislib_tpu/serving/bundle.py", "_capture_entries"),
    ("dislib_tpu/serving/bundle.py", "_export_sharded"),
}

_RAW_SYNC_ATTRS = ("device_get", "collect", "block_until_ready")


def _sync_calls(loop_node):
    """Raw host-sync spellings inside one loop body."""
    hits = []
    for sub in ast.walk(loop_node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            if f.attr in _RAW_SYNC_ATTRS:
                hits.append(f.attr)
            elif f.attr == "asarray" and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy"):
                hits.append("np.asarray")
        elif isinstance(f, ast.Name):
            if f.id == "float" and sub.args \
                    and not isinstance(sub.args[0], ast.Constant):
                hits.append("float")
    return hits


def _scan(path):
    """Yield (function_name, lineno, syncs) for every loop with raw syncs."""
    tree = ast.parse(open(path, encoding="utf-8").read())

    def walk(node, fname):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from walk(child, child.name)
            else:
                if isinstance(child, (ast.For, ast.While)):
                    syncs = _sync_calls(child)
                    if syncs:
                        yield fname, child.lineno, sorted(set(syncs))
                yield from walk(child, fname)

    yield from walk(tree, "<module>")


def _estimator_files():
    for d in ESTIMATOR_DIRS:
        full = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full)):
            if fn.endswith(".py"):
                yield f"{d}/{fn}", os.path.join(full, fn)
    for rel in EXTRA_FILES:
        yield rel, os.path.join(REPO, rel)


def test_no_unblessed_host_syncs_in_estimator_loops():
    offenders = []
    for rel, full in _estimator_files():
        for fname, lineno, syncs in _scan(full):
            if (rel, fname) not in ALLOWLIST:
                offenders.append(f"{rel}:{lineno} in {fname}(): {syncs}")
    assert not offenders, (
        "raw host syncs inside estimator iteration loops — route them "
        "through runtime.fetch (or force()) at a chunk boundary, or "
        "consciously extend the lint allowlist with a reason:\n  "
        + "\n  ".join(offenders))


# ---------------------------------------------------------------------------
# round-11 rechunk PR: host-numpy RESHARDING lint.  Estimator/pipeline
# code may not re-pad / re-lay out array data through host numpy —
# resharding flows through `ds.rechunk` (on-device collective) or
# `runtime.repad_rows` (the blessed elastic boundary, which itself
# routes device inputs on-device).  `np.pad` is the telltale spelling of
# a host reshard; the AST scan covers WHOLE files (not just loops),
# because a single one-shot host re-pad of a sharded operand still
# gathers the array through the host.
# ---------------------------------------------------------------------------

# (file, enclosing function) pairs allowed to np.pad, each a HOST-side
# ingest/serialization boundary, never a device-array reshard:
RESHARD_ALLOWLIST = {
    # cascade labels arrive host-side by design (SURVEY §3.3) and are
    # padded BEFORE first device_put — ingest, not a reshard
    ("dislib_tpu/classification/csvm.py", "fit"),
    # adoption packs ragged per-level host copies into the model's host
    # attrs (post-device_get serialization, not a layout move)
    ("dislib_tpu/trees/decision_tree.py", "_pack"),
    # elastic snapshot restore: re-pads the VERIFIED HOST snapshot state
    # to this mesh's pad width before its first device_put — the blessed
    # resize boundary itself (ingest of host bytes, not a device-array
    # gather); the density/greedy carries are integer label vectors, so
    # repad_rows' float row machinery does not apply
    ("dislib_tpu/cluster/daura.py", "restore"),
    ("dislib_tpu/cluster/dbscan.py", "restore"),
    # elastic rebind (round 14): re-pads the HOST ±1 label vector kept
    # from fit ingest to the resized mesh's pad width before device_put —
    # ingest-side twin of the restore() entries above
    ("dislib_tpu/classification/csvm.py", "rebind"),
}


def _np_pad_calls(path):
    """(enclosing_function, lineno) of every np.pad/numpy.pad call."""
    tree = ast.parse(open(path, encoding="utf-8").read())

    def walk(node, fname):
        for child in ast.iter_child_nodes(node):
            cname = fname
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cname = child.name
            if isinstance(child, ast.Call) \
                    and isinstance(child.func, ast.Attribute) \
                    and child.func.attr == "pad" \
                    and isinstance(child.func.value, ast.Name) \
                    and child.func.value.id in ("np", "numpy"):
                yield fname, child.lineno
            yield from walk(child, cname)

    yield from walk(tree, "<module>")


def test_no_host_numpy_resharding_in_estimators():
    offenders = []
    for rel, full in _estimator_files():
        for fname, lineno in _np_pad_calls(full):
            if (rel, fname) not in RESHARD_ALLOWLIST:
                offenders.append(f"{rel}:{lineno} in {fname}()")
    assert not offenders, (
        "host-numpy resharding (np.pad) in estimator/pipeline code — "
        "reshard through ds.rechunk (on-device collective) or "
        "runtime.repad_rows (elastic boundary), or consciously extend "
        "RESHARD_ALLOWLIST with a reason:\n  " + "\n  ".join(offenders))


def test_reshard_allowlist_entries_still_exist():
    live = set()
    for rel, full in _estimator_files():
        for fname, _ in _np_pad_calls(full):
            live.add((rel, fname))
    dead = {site for site in RESHARD_ALLOWLIST if site not in live}
    assert not dead, f"reshard allowlist entries match no code: {dead}"


def test_allowlist_entries_still_exist():
    """A refactor that renames or removes an allowlisted loop must prune
    the list — dead entries would quietly bless future regressions."""
    live = set()
    for rel, full in _estimator_files():
        for fname, _, _ in _scan(full):
            live.add((rel, fname))
    dead = {site for site in ALLOWLIST if site not in live}
    assert not dead, f"allowlist entries no longer match any code: {dead}"
