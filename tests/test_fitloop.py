"""The unified chunk-fit driver (round-12 tentpole): escalation-ladder
schedule semantics, the tier-targeted ``FaultAtTier`` injector, the
resilience counters (host-side — zero extra dispatches, asserted against
the dispatch counters), the fit ``info`` surface, and the PINNED
elastic-tier scenario: a fault that defeats the retry AND remediation
tiers escalates to the mesh-shrink tier, the fit resumes on half the
devices, and the healed model equals the unfaulted oracle.

Shapes mirror ``tests/test_health.py`` so the fit kernels compile once
per suite, not once per file.
"""

import numpy as np
import pytest

import jax

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.runtime import NumericalDivergence
from dislib_tpu.runtime.fitloop import (ChunkedFitLoop, ChunkOutcome,
                                        EscalationLadder, LoopState, TIERS)
from dislib_tpu.runtime.health import HealthPolicy, Verdict
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils import profiling as prof


def _blobs(rng, n=198, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


def _kmeans_setup(rng):
    x_np = _blobs(rng)
    init = np.ascontiguousarray(x_np[[0, 70, 140]])
    kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
    return ds.array(x_np), kw


# ---------------------------------------------------------------------------
# ladder schedule semantics
# ---------------------------------------------------------------------------

class TestLadderSchedule:
    def _ladder(self, elastic_ok=True, **pol):
        g = HealthPolicy(**pol).make_guard("t", checkpoint=object())
        return EscalationLadder(g, elastic_ok=elastic_ok)

    def test_default_budget_schedule_is_retry_then_remediate(self):
        # max_restarts=2 default: exactly the pre-extraction budget —
        # two rollbacks then the typed raise, tiers deciding WHAT each does
        assert self._ladder().schedule == ["retry", "remediate"]

    def test_elastic_rungs_are_last_and_opt_in(self):
        assert self._ladder(max_restarts=3, elastic_attempts=1).schedule \
            == ["retry", "remediate", "elastic"]
        assert self._ladder(max_restarts=3).schedule \
            == ["retry", "remediate", "remediate"]
        # no elastic hook (elastic_ok=False): the rung is never offered
        assert self._ladder(elastic_ok=False, max_restarts=3,
                            elastic_attempts=1).schedule \
            == ["retry", "remediate", "remediate"]

    def test_escalation_walks_the_schedule_and_raises_at_budget(self):
        lad = self._ladder(max_restarts=3, elastic_attempts=1,
                           action="halve")
        bad = Verdict(False, guard="nonfinite")
        e1, e2, e3 = (lad.escalate(bad) for _ in range(3))
        assert [e.tier for e in (e1, e2, e3)] == list(TIERS)
        assert (e1.attempt, e2.attempt, e3.attempt) == (1, 2, 3)
        # tier-adjusted remediation: plain retry tiers never damp/perturb,
        # the remediate tier applies the policy action from ITS first rung
        assert e1.remediation.damping == 1.0
        assert e2.remediation.damping == 2.0
        assert e3.remediation.damping == 1.0
        with pytest.raises(NumericalDivergence, match="max_restarts"):
            lad.escalate(bad)

    def test_escalations_feed_the_resilience_counters(self):
        prof.reset_counters()
        lad = self._ladder(max_restarts=3, elastic_attempts=1)
        bad = Verdict(False, guard="nonfinite")
        for _ in range(3):
            lad.escalate(bad)
        r = prof.resilience_counters()
        assert r["rollbacks"] == 3 and r["chunk_retries"] == 1
        assert r["escalations_retry"] == 1
        assert r["escalations_remediate"] == 1
        assert r["escalations_elastic"] == 1


# ---------------------------------------------------------------------------
# deferred commit: estimator-side syncs stay BEHIND the watchdogged check
# ---------------------------------------------------------------------------

class TestDeferredCommit:
    def test_commit_thunk_runs_only_after_a_passing_verdict(self, tmp_path):
        """A step whose successor state is a CALLABLE must see it invoked
        only for chunks whose verdict passed: the convergence-scalar
        syncs inside it therefore sit behind the watchdogged hvec read (a
        hung kernel trips `WatchdogTimeout` at the check, never blocks in
        estimator code), and a faulted chunk's side effects never run —
        the review-found watchdog-coverage regression, pinned."""
        calls = {"steps": 0, "commits": 0}
        ck = FitCheckpoint(str(tmp_path / "d.npz"), every=1)
        loop = ChunkedFitLoop("t", checkpoint=ck, max_iter=3, chunk_iters=1,
                              health=faults.TripAtChunk(at_chunk=2, times=1))

        def init(rem):
            return LoopState(())

        def restore(snap, rem):
            return LoopState((), it=int(snap["it"]))

        def step(st, chunk):
            calls["steps"] += 1

            def commit():
                calls["commits"] += 1
                return LoopState((), st.it + 1, False)

            return ChunkOutcome(commit,
                                host_values={"v": np.asarray([1.0])})

        st = loop.run(init=init, step=step, restore=restore,
                      snapshot=lambda st: {"it": st.it})
        assert st.it == 3
        assert calls["steps"] == 4, "one chunk re-ran after the rollback"
        assert calls["commits"] == 3, \
            "a faulted chunk's deferred commit must never run"


# ---------------------------------------------------------------------------
# FaultAtTier: defeats exactly N tiers
# ---------------------------------------------------------------------------

class TestFaultAtTier:
    def test_tier0_heals_on_first_plain_retry(self, rng, tmp_path):
        x, kw = _kmeans_setup(rng)
        full = KMeans(**kw).fit(x)
        pol = faults.FaultAtTier(tiers=0, at_chunk=2)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert pol.fired == 1 and pol.healed
        assert res.fit_info_["escalations"] == \
            {"retry": 1, "remediate": 0, "elastic": 0}
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_tier1_defeats_retry_heals_on_remediation(self, rng, tmp_path):
        x, kw = _kmeans_setup(rng)
        full = KMeans(**kw).fit(x)
        pol = faults.FaultAtTier(tiers=1, at_chunk=2)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert pol.fired == 2 and pol.healed
        assert res.fit_info_["escalations"] == \
            {"retry": 1, "remediate": 1, "elastic": 0}
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_whole_ladder_defeated_raises_typed(self, rng, tmp_path):
        x, kw = _kmeans_setup(rng)
        pol = faults.FaultAtTier(tiers=3, at_chunk=2, max_restarts=2)
        with pytest.raises(NumericalDivergence, match="max_restarts"):
            KMeans(**kw).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"),
                                            every=2),
                health=pol)
        assert pol.fired == 3 and not pol.healed


# ---------------------------------------------------------------------------
# the PINNED elastic-tier scenario (acceptance): a fault that defeats
# retry AND remediation escalates to the mesh-shrink tier; the fit
# resumes on half the devices and equals the unfaulted oracle
# ---------------------------------------------------------------------------

class TestElasticTier:
    def test_mesh_shrink_resume_equals_unfaulted_oracle(self, rng,
                                                        tmp_path):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init((8, 1), devices=jax.devices()[:8])
        x, kw = _kmeans_setup(rng)
        full = KMeans(**kw).fit(x)

        ds.init((8, 1), devices=jax.devices()[:8])
        pol = faults.FaultAtTier(tiers=2, at_chunk=2, max_restarts=3,
                                 elastic_attempts=1)
        prof.reset_counters()
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        # the ladder actually reached the elastic tier and shrank the mesh
        assert pol.healed and pol.fired == 3
        assert res.fit_info_["mesh_shrinks"] == 1
        assert res.fit_info_["escalations"]["elastic"] == 1
        assert ds.get_mesh().shape["rows"] == 4, \
            "elastic tier must halve the mesh's row axis"
        assert prof.resilience_counters()["mesh_shrinks"] == 1
        # the resumed model equals the unfaulted oracle
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_,
                                   rtol=1e-4, atol=1e-5)

    def test_unshrinkable_mesh_degrades_to_plain_retry(self, rng, tmp_path):
        ds.init((1, 1), devices=jax.devices()[:1])
        x, kw = _kmeans_setup(rng)
        full = KMeans(**kw).fit(x)
        pol = faults.FaultAtTier(tiers=2, at_chunk=2, max_restarts=3,
                                 elastic_attempts=1)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        # the elastic rung still runs (heals the tier-targeted fault) but
        # cannot shrink a single-row mesh — deterministic degradation
        assert pol.healed and res.fit_info_["mesh_shrinks"] == 0
        assert ds.get_mesh().shape["rows"] == 1
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)


# ---------------------------------------------------------------------------
# bidirectional elasticity: capacity-driven shrink AND grow-back
# ---------------------------------------------------------------------------

class TestGrowBack:
    def test_capacity_oscillation_heals_and_matches_oracle(self, rng,
                                                           tmp_path):
        """Capacity dips to half the mesh after the first snapshot and
        returns after the second: the fit shrinks, then GROWS BACK to the
        home mesh, and lands bit-for-bit on the unfaulted oracle.
        Capacity resizes are re-layouts from a committed snapshot — NOT
        failures — so they must not consume rollbacks or escalations."""
        from conftest import skip_unless_devices
        from dislib_tpu.runtime.preemption import clear_capacity
        skip_unless_devices(8)
        ds.init((8, 1), devices=jax.devices()[:8])
        x, kw = _kmeans_setup(rng)
        full = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "o.npz"), every=2))

        ds.init((8, 1), devices=jax.devices()[:8])
        pol = faults.CapacityAtSave({1: 4, 2: 8})
        prof.reset_counters()
        try:
            res = KMeans(**kw).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "c.npz"),
                                            every=2),
                health=pol)
        finally:
            clear_capacity()
        assert res.fit_info_["mesh_shrinks"] == 1
        assert res.fit_info_["mesh_grows"] == 1
        assert ds.get_mesh().shape["rows"] == 8, \
            "grow-back must restore the home mesh"
        r = prof.resilience_counters()
        assert r["mesh_shrinks"] == 1 and r["mesh_grows"] == 1
        assert "rollbacks" not in r and "escalations_elastic" not in r, \
            "a capacity resize is not a failure and spends no budget"
        # the oscillated fit equals the unfaulted oracle bit-for-bit
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_array_equal(res.centers_, full.centers_)

    def test_grow_attempts_budget_caps_grow_backs(self, rng, tmp_path):
        """grow_attempts=0 pins the fit to the shrunk mesh: the shrink
        still happens (capacity drops are always honored) but the
        grow-back is declined."""
        from conftest import skip_unless_devices
        from dislib_tpu.runtime.preemption import clear_capacity
        skip_unless_devices(8)
        ds.init((8, 1), devices=jax.devices()[:8])
        x, kw = _kmeans_setup(rng)
        pol = faults.CapacityAtSave({1: 4, 2: 8}, grow_attempts=0)
        try:
            res = KMeans(**kw).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "g.npz"),
                                            every=2),
                health=pol)
        finally:
            clear_capacity()
        assert res.fit_info_["mesh_shrinks"] == 1
        assert res.fit_info_["mesh_grows"] == 0
        assert ds.get_mesh().shape["rows"] == 4


# ---------------------------------------------------------------------------
# counters: populated by a healed fit, at zero extra dispatches
# ---------------------------------------------------------------------------

class TestResilienceCounters:
    def test_healed_fit_counts_and_costs_only_the_retried_chunk(
            self, rng, tmp_path):
        x, kw = _kmeans_setup(rng)
        ck = FitCheckpoint(str(tmp_path / "warm.npz"), every=2)
        KMeans(**kw).fit(x, checkpoint=ck)          # warm the compile caches
        ck.delete()

        prof.reset_counters()
        KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "ref.npz"), every=2))
        clean = prof.counters()
        assert prof.resilience_counters() == {}, \
            "an unfaulted fit must not count resilience events"

        prof.reset_counters()
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "f.npz"), every=2),
            health=faults.NaNAtChunk(at_chunk=3))
        faulted = prof.counters()
        r = faulted["resilience"]
        assert r["rollbacks"] == 1 and r["chunk_retries"] == 1
        assert r["escalations_retry"] == 1
        assert res.fit_info_["rollbacks"] == 1
        # the counters are host-side integers: the ONLY extra device work
        # of the healed fit is the one re-run chunk
        assert faulted["dispatch_by"]["kmeans_fit"] == \
            clean["dispatch_by"]["kmeans_fit"] + 1

    def test_watchdog_trips_are_counted(self, rng, tmp_path, monkeypatch):
        monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
        x, kw = _kmeans_setup(rng)
        prof.reset_counters()
        pol = faults.HangAtChunk(at_chunk=2, hang_s=0.4, deadline_s=0.05,
                                 times=1)
        KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert prof.resilience_counters()["watchdog_trips"] == 1
