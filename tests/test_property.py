"""Property-based hardening of the ds-array core (hypothesis).

The reference's most bug-catching tests are irregular-shape slicing and
mixed elementwise/reduction cases (SURVEY §5); here hypothesis drives the
same surface with randomized shapes, block sizes, slices and fancy indices
against the NumPy oracle.  Deadlines are disabled (first jit trace of a new
shape dominates wall time).

Round-8 satellite: on rigs WITHOUT the hypothesis package (it lives in the
``dev`` extra) the tier no longer skips silently — `_hypothesis_lite`
supplies deterministic seeded sampling for the same properties at a
smaller example budget (no shrinking; install hypothesis for the full
search)."""

import numpy as np
import pytest  # noqa: F401 — fixture plumbing

try:
    from hypothesis import given, settings, strategies as st
    _LITE = False
except ImportError:
    from _hypothesis_lite import given, settings, strategies as st
    _LITE = True

import dislib_tpu as ds  # noqa: E402

# On the real chip every example pays the ~69 ms tunnel dispatch RTT, so
# 25 examples x ~10 dispatches x 9 properties blows the suite-runner's
# 900 s per-file budget (round-5: rc 124 on-chip).  The TPU run keeps the
# same properties at sample size 5 — the hardware-rounding check — while
# the CPU rig keeps the full search.
import os

# lite tier runs the TPU smoke budget: it is the always-on smoke pass of
# this tier (tier-1 wall-clock is budgeted), not the full search
_N = 5 if os.environ.get("DSLIB_TEST_TPU") == "1" else (5 if _LITE else 25)
_settings = settings(max_examples=_N, deadline=None)


@st.composite
def arr_and_block(draw):
    m = draw(st.integers(1, 40))
    n = draw(st.integers(1, 17))
    br = draw(st.integers(1, 40))
    bc = draw(st.integers(1, 17))
    seed = draw(st.integers(0, 2**16))
    data = np.random.RandomState(seed).standard_normal((m, n)) \
        .astype(np.float32)
    return data, (br, bc)


@given(arr_and_block())
@_settings
def test_roundtrip_and_reductions(ab):
    data, bs = ab
    x = ds.array(data, block_size=bs)
    np.testing.assert_allclose(np.asarray(x.collect()), data, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(x.sum(axis=0).collect()).ravel(),
                               data.sum(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(x.mean(axis=1).collect()).ravel(),
                               data.mean(1), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(x.min(axis=0).collect()).ravel(),
                               data.min(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(x.max(axis=1).collect()).ravel(),
                               data.max(1), rtol=1e-5, atol=1e-6)


@given(arr_and_block(), st.data())
@_settings
def test_slicing_matches_numpy(ab, payload):
    data, bs = ab
    m, n = data.shape
    x = ds.array(data, block_size=bs)
    r0 = payload.draw(st.integers(0, m - 1))
    r1 = payload.draw(st.integers(r0 + 1, m))
    c0 = payload.draw(st.integers(0, n - 1))
    c1 = payload.draw(st.integers(c0 + 1, n))
    got = np.asarray(x[r0:r1, c0:c1].collect())
    np.testing.assert_allclose(got, data[r0:r1, c0:c1], rtol=1e-6)
    # fancy row indexing
    k = payload.draw(st.integers(1, m))
    idx = payload.draw(st.lists(st.integers(0, m - 1), min_size=k,
                                max_size=k))
    got = np.asarray(x[idx, :].collect())
    np.testing.assert_allclose(got, data[idx, :], rtol=1e-6)


@given(arr_and_block(), st.integers(0, 2**16))
@_settings
def test_elementwise_and_transpose(ab, seed2):
    data, bs = ab
    other = np.random.RandomState(seed2).standard_normal(data.shape) \
        .astype(np.float32)
    x = ds.array(data, block_size=bs)
    y = ds.array(other, block_size=bs)
    np.testing.assert_allclose(np.asarray((x + y).collect()), data + other,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray((x * y).collect()), data * other,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray((x - y).collect()), data - other,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(x.T.collect()), data.T, rtol=1e-6)
    # transpose round-trip keeps the pad-and-mask invariant intact
    np.testing.assert_allclose(np.asarray(x.T.T.collect()), data, rtol=1e-6)


@given(st.integers(0, 2**16), st.integers(5, 30), st.integers(3, 12))
@_settings
def test_sparse_roundtrip_and_ops(seed, m, n):
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    rng = np.random.RandomState(seed)
    dense = rng.rand(m, n).astype(np.float32)
    dense[dense < 0.6] = 0.0
    xs = SparseArray.from_scipy(sp.csr_matrix(dense))
    np.testing.assert_allclose(np.asarray(xs.collect().toarray()), dense,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(xs.sum(axis=0).collect()).ravel(),
                               dense.sum(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(xs.square().collect().toarray()), dense ** 2, rtol=1e-5)
    got = (xs + xs._scaled(-1.0)).collect().toarray()
    np.testing.assert_allclose(got, np.zeros_like(dense), atol=1e-6)
    np.testing.assert_allclose(np.asarray(xs.T.collect().toarray()), dense.T,
                               rtol=1e-6)


@given(st.integers(0, 2**16), st.integers(1, 400), st.integers(1, 64),
       st.floats(0.02, 0.9))
@_settings
def test_row_steps_invariants(seed, m, chunk, density):
    """row_steps (kNN sparse streaming) invariants for arbitrary sparsity
    patterns: steps partition [0, m) in order, every step respects the row
    cap, every nonzero lands exactly once with correct local coordinates,
    and the rectangle memory stays within the documented budget bound."""
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    rng = np.random.RandomState(seed)
    dense = (rng.rand(m, 8) < density).astype(np.float32) * rng.rand(m, 8)
    xs = SparseArray.from_scipy(sp.csr_matrix(dense))
    data, lrows, cols, row_off, rows_in = (np.asarray(a) for a in
                                           xs.row_steps(chunk))
    # partition: contiguous, ordered, covers all m rows exactly once
    covered = 0
    for ro, rc in zip(row_off, rows_in):
        assert ro == covered
        assert 0 <= rc <= chunk
        covered += int(rc)
    assert covered == m
    # reconstruction: scatter every step back and compare
    rebuilt = np.zeros_like(dense)
    for s in range(data.shape[0]):
        np.add.at(rebuilt, (row_off[s] + lrows[s], cols[s]), data[s])
        assert (lrows[s] < max(1, rows_in[s])).all()
    np.testing.assert_allclose(rebuilt, dense, rtol=1e-6)
    # memory bound: the per-step nnz budget itself obeys the documented
    # formula (4x the average chunk's nonzeros, floored at 64 and at the
    # densest single row) — a regression to budget = O(densest chunk)
    # would fail this
    row_nnz = (dense != 0).sum(axis=1)
    want = max(64, 4 * int(np.ceil(xs.nnz * chunk / max(m, 1))),
               int(row_nnz.max(initial=1)))
    assert data.shape[1] <= want


@given(st.integers(0, 2**16), st.integers(1, 9), st.integers(1, 8))
@_settings
def test_tsqr_invariants(seed, n, mult):
    """QᵀQ≈I and QR≈A across tall shapes, including ones that engage the
    batched-tree local QR (rows ≫ n) and ones that pad shards (rows < p·n)."""
    m = n * mult * 8 + (seed % 7)           # sometimes ragged vs the mesh
    if m < n:
        m = n
    x = np.random.RandomState(seed).standard_normal((m, n)).astype(np.float32)
    q, r = ds.tsqr(ds.array(x))
    qc, rc = q.collect(), r.collect()
    assert qc.shape == (m, n) and rc.shape == (n, n)
    np.testing.assert_allclose(qc @ rc, x, atol=5e-4 * max(1, np.abs(x).max()))
    np.testing.assert_allclose(qc.T @ qc, np.eye(n), atol=5e-4)
    assert np.allclose(rc, np.triu(rc))


@given(st.integers(0, 2**16), st.integers(1, 60), st.integers(1, 12),
       st.floats(0.05, 0.9))
@_settings
def test_ell_invariants(seed, m, n, density):
    """ELL buffers densify back to the exact matrix (round-4 CSVM staging
    representation), padding entries contribute nothing, and the budget
    guard trips exactly on the padded byte size."""
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.classification.csvm import _ell_rows_dense
    import jax.numpy as jnp
    mat = sp.random(m, n, density=density, random_state=seed,
                    dtype=np.float32).tocsr()
    sa = SparseArray.from_scipy(mat)
    ell = sa.ell()
    assert ell is not None
    ev, ec = ell
    assert ev.shape == ec.shape and ev.shape[0] == m
    dense = np.asarray(_ell_rows_dense(ev, ec, jnp.arange(m), n))
    np.testing.assert_allclose(dense, mat.toarray(), rtol=1e-6, atol=1e-7)
    # row-nnz bound: r is exactly the max row nnz (no silent inflation)
    row_nnz = np.diff(mat.indptr)
    assert ev.shape[1] == max(1, int(row_nnz.max(initial=1)))
    # budget guard: one byte below the need → fallback (fresh object: the
    # cache also re-checks, but this pins the fresh-build path)
    need = m * ev.shape[1] * 8
    sa2 = SparseArray.from_scipy(mat)
    assert sa2.ell(budget=need - 1) is None
    assert sa2.ell(budget=need) is not None


@given(st.integers(0, 2**16), st.integers(1, 60), st.integers(1, 12),
       st.floats(0.05, 0.9))
@_settings
def test_sharded_rows_invariants(seed, m, n, density):
    """The rectangular row-sharded representation reconstructs the exact
    matrix: every nonzero lands in its shard's bucket at its local row,
    padding entries are zero-valued, and rowsq matches per-row ‖·‖²."""
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.parallel import mesh as _mesh
    mat = sp.random(m, n, density=density, random_state=seed,
                    dtype=np.float32).tocsr()
    sa = SparseArray.from_scipy(mat)
    mesh = _mesh.get_mesh()
    p = mesh.shape[_mesh.ROWS]
    data, lrows, cols, rowsq = (np.asarray(a) for a in sa.sharded_rows())
    m_local = -(-m // p)
    rebuilt = np.zeros((p * m_local, n), np.float32)
    for s in range(p):
        np.add.at(rebuilt, (s * m_local + lrows[s], cols[s]), data[s])
    np.testing.assert_allclose(rebuilt[:m], mat.toarray(), rtol=1e-6,
                               atol=1e-7)
    assert not rebuilt[m:].any(), "padding rows carry mass"
    dense = mat.toarray()
    np.testing.assert_allclose(
        rowsq.reshape(-1)[: m], (dense * dense).sum(1), rtol=1e-5,
        atol=1e-6)
