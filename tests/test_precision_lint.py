"""Precision-policy lint (round-10 satellite; the xla-flags / host-sync
lint pattern): library kernels under ``dislib_tpu/{math,ops,decomposition}``
may not hardcode GEMM compute dtypes or precision — every such decision
routes through the ONE policy module, ``dislib_tpu/ops/precision.py``
(:func:`resolve` / :func:`to_compute` / :func:`f32` / :func:`pdot` /
:func:`precise`), so "what precision does this kernel run at" is a
one-module audit instead of a per-kernel archaeology dig, and the
``DSLIB_MATMUL_PRECISION`` env knob can never be silently bypassed.

Flagged spellings, by AST scan:

1. ``x.astype(<float dtype literal>)`` — e.g. ``astype(jnp.float32)``,
   ``astype(np.bfloat16)``, ``astype("float32")``.  Deriving a dtype from
   a VALUE (``astype(u.dtype)``, mask casts) is fine — that is layout
   plumbing, not a precision decision.
2. any call of ``default_matmul_precision`` — the trace-scope lives in
   the policy module's ``precise`` only.
3. a literal string ``precision=`` keyword on any call — policies thread
   as resolved objects / variables, never as scattered string constants.

The policy module itself is the single allowed site.  Adding a new site
means consciously extending ALLOW with a reason, the host-sync-lint
contract.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KERNEL_DIRS = (
    "dislib_tpu/math",
    "dislib_tpu/ops",
    "dislib_tpu/decomposition",
    # round-14: the sparse fast path spells its own contractions (the
    # fold-in peinsum/pdot, the SpMM gather/segment contraction) — its
    # homes may not hardcode compute dtypes either
    "dislib_tpu/recommendation",
    # round-17: the forest's histogram loop became a routed kernel (XLA
    # scatter / Pallas one-hot GEMM) — its home must route every compute
    # dtype through ops/precision like the other kernel tiers
    "dislib_tpu/trees",
    # round-18: the IVF search kernel spells its own distance
    # contractions (centroid GEMM, probed-list einsum) — routed through
    # ops/precision like every other kernel tier
    "dislib_tpu/retrieval",
)

# single FILES scanned alongside the dirs (their siblings are host
# ingest/serialization code whose float casts are dtype policy, not
# kernel compute decisions)
KERNEL_FILES = (
    "dislib_tpu/data/sparse.py",
    "dislib_tpu/serving/sparse.py",
)

# the ONE module allowed to spell compute dtypes / precision literals
ALLOW = {
    "dislib_tpu/ops/precision.py",
}

_FLOAT_DTYPE_NAMES = {"float32", "float64", "float16", "bfloat16"}


def _is_float_dtype_literal(node):
    """True for jnp.float32 / np.bfloat16 / jax.numpy.float16-style
    attribute chains and 'float32'-style string constants."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _FLOAT_DTYPE_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _FLOAT_DTYPE_NAMES
    return False


def _scan(path):
    tree = ast.parse(open(path, encoding="utf-8").read())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype" and node.args:
            if _is_float_dtype_literal(node.args[0]):
                yield node.lineno, "astype(<hardcoded float dtype>)"
        name = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else None)
        if name == "default_matmul_precision":
            yield node.lineno, "default_matmul_precision(...)"
        for kw in node.keywords:
            if kw.arg == "precision" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                yield node.lineno, f"precision={kw.value.value!r} literal"


def _kernel_files():
    for d in KERNEL_DIRS:
        full = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full)):
            if fn.endswith(".py"):
                yield f"{d}/{fn}", os.path.join(full, fn)
    for rel in KERNEL_FILES:
        yield rel, os.path.join(REPO, rel)


def test_no_hardcoded_compute_dtypes_in_kernels():
    offenders = []
    for rel, full in _kernel_files():
        if rel in ALLOW:
            continue
        for lineno, what in _scan(full):
            offenders.append(f"{rel}:{lineno}: {what}")
    assert not offenders, (
        "hardcoded compute dtype / precision in library kernels — route "
        "through dislib_tpu/ops/precision (resolve/to_compute/f32/pdot/"
        "precise), or consciously extend the lint ALLOW with a reason:\n  "
        + "\n  ".join(offenders))


def test_policy_module_is_the_one_scope_site():
    """The f32-faithful trace scope (default_matmul_precision) must exist
    in the policy module — if a refactor moves it, the lint's premise
    (one audited site) needs re-establishing, not silently dropping."""
    path = os.path.join(REPO, "dislib_tpu/ops/precision.py")
    hits = [what for _, what in _scan(path)
            if "default_matmul_precision" in what]
    assert hits, "ops/precision.py no longer hosts the matmul scope"


def test_overlap_kernel_files_are_in_the_scanned_set():
    """Round-13 pin: the overlap-schedule kernels (incl. the Pallas
    fallback, which spells its own dot) must stay inside this lint's
    scanned set — a refactor that moves them out would let a new kernel
    hardcode compute dtypes unnoticed."""
    scanned = {rel for rel, _ in _kernel_files()}
    for f in ("dislib_tpu/ops/overlap.py", "dislib_tpu/ops/summa.py",
              "dislib_tpu/ops/rechunk.py", "dislib_tpu/ops/ring.py",
              "dislib_tpu/ops/tiled.py",
              "dislib_tpu/ops/pallas_kernels.py",
              # round-14 sparse fast path
              "dislib_tpu/ops/spmm.py",
              "dislib_tpu/recommendation/als.py",
              "dislib_tpu/data/sparse.py",
              "dislib_tpu/serving/sparse.py",
              # round-18 retrieval tier
              "dislib_tpu/retrieval/ivf.py",
              "dislib_tpu/retrieval/serving.py"):
        assert f in scanned, f"{f} escaped the precision lint"


def test_public_entries_expose_precision_kwarg():
    """The paper-scale surface must actually accept the policy: matmul,
    qr, polar, svd, tsqr, random_svd, lanczos_svd take ``precision=``
    and PCA takes it as a constructor param — an entry dropping the
    kwarg would orphan the env knob for that path."""
    import inspect
    import dislib_tpu as ds
    for fn in (ds.matmul, ds.qr, ds.polar, ds.svd, ds.tsqr, ds.random_svd,
               ds.lanczos_svd):
        assert "precision" in inspect.signature(fn).parameters, fn
    assert "precision" in inspect.signature(ds.PCA.__init__).parameters
