"""DBSCAN + Daura tests (reference: test_dbscan.py, test_daura.py —
SURVEY.md §5 oracle pattern: compare vs sklearn / NumPy closed form,
labels permutation-equivalent)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import DBSCAN, Daura


def _moons_like(rng, n=200):
    """Two well-separated dense rings + far-away noise points."""
    t = rng.rand(n // 2) * 2 * np.pi
    c1 = np.c_[np.cos(t), np.sin(t)] + 0.05 * rng.randn(n // 2, 2)
    c2 = np.c_[np.cos(t) + 6.0, np.sin(t)] + 0.05 * rng.randn(n // 2, 2)
    noise = rng.rand(6, 2) * 2 + np.array([2.5, 4.0])
    return np.vstack([c1, c2, noise]).astype(np.float32)


def _canon(labels):
    """Canonical form: relabel clusters by first occurrence (noise stays -1)."""
    out = np.full_like(labels, -1)
    nxt = 0
    seen = {}
    for i, v in enumerate(labels):
        if v == -1:
            continue
        if v not in seen:
            seen[v] = nxt
            nxt += 1
        out[i] = seen[v]
    return out


class TestDBSCAN:
    def test_vs_sklearn(self, rng):
        from sklearn.cluster import DBSCAN as SkDBSCAN
        x = _moons_like(rng)
        mine = DBSCAN(eps=0.4, min_samples=5).fit(ds.array(x))
        sk = SkDBSCAN(eps=0.4, min_samples=5).fit(x)
        assert mine.n_clusters_ == len(set(sk.labels_) - {-1})
        # noise sets identical; core-point partitions permutation-equivalent
        assert np.array_equal(mine.labels_ == -1, sk.labels_ == -1)
        core = np.zeros(len(x), bool)
        core[sk.core_sample_indices_] = True
        assert np.array_equal(_canon(np.where(core, mine.labels_, -1)),
                              _canon(np.where(core, sk.labels_, -1)))
        assert np.array_equal(np.sort(mine.core_sample_indices_),
                              np.sort(sk.core_sample_indices_))

    def test_fit_predict_matches_labels(self, rng):
        x = _moons_like(rng, n=80)
        est = DBSCAN(eps=0.4, min_samples=4)
        lab = est.fit_predict(ds.array(x)).collect().ravel().astype(int)
        assert np.array_equal(lab, est.labels_)

    def test_all_noise(self, rng):
        x = (rng.rand(20, 3) * 100).astype(np.float32)
        est = DBSCAN(eps=1e-3, min_samples=3).fit(ds.array(x))
        assert est.n_clusters_ == 0
        assert np.all(est.labels_ == -1)

    def test_single_cluster(self, rng):
        x = (rng.randn(30, 2) * 0.01).astype(np.float32)
        est = DBSCAN(eps=1.0, min_samples=3).fit(ds.array(x))
        assert est.n_clusters_ == 1
        assert np.all(est.labels_ == 0)

    def test_chain_cluster(self, rng):
        # a long 1-D chain: worst case for label propagation depth
        x = np.c_[np.arange(64) * 0.5, np.zeros(64)].astype(np.float32)
        est = DBSCAN(eps=0.6, min_samples=2).fit(ds.array(x))
        assert est.n_clusters_ == 1
        assert np.all(est.labels_ == 0)


def _np_daura(x, cutoff, n_atoms):
    """NumPy oracle: greedy GROMOS clustering."""
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1) / n_atoms
    adj = d2 <= cutoff ** 2
    active = np.ones(len(x), bool)
    labels = np.full(len(x), -1)
    medoids = []
    cid = 0
    while active.any():
        counts = np.where(active, (adj & active[None, :]).sum(1), -1)
        med = int(np.argmax(counts))
        members = adj[med] & active
        labels[members] = cid
        medoids.append(med)
        active &= ~members
        cid += 1
    return labels, medoids


class TestDaura:
    def test_vs_numpy_oracle(self, rng):
        n_atoms = 4
        x = (rng.randn(40, 3 * n_atoms) * 2).astype(np.float32)
        cutoff = 3.0
        est = Daura(cutoff=cutoff).fit(ds.array(x))
        ref_labels, ref_medoids = _np_daura(x, cutoff, n_atoms)
        assert np.array_equal(est.labels_, ref_labels)
        assert [c[0] for c in est.clusters_] == ref_medoids

    def test_cluster_membership(self, rng):
        n_atoms = 2
        # two tight bundles of frames
        a = rng.randn(1, 6) + np.zeros((10, 6))
        b = rng.randn(1, 6) + 50 + np.zeros((8, 6))
        x = (np.vstack([a, b]) + 0.01 * rng.randn(18, 6)).astype(np.float32)
        est = Daura(cutoff=1.0).fit(ds.array(x))
        assert len(est.clusters_) == 2
        assert {tuple(sorted(c)) for c in est.clusters_} == \
            {tuple(range(10)), tuple(range(10, 18))}

    def test_bad_shape(self, rng):
        with pytest.raises(ValueError):
            Daura().fit(ds.array(rng.rand(5, 7)))
