"""Pallas fused-E-step equivalence tests (SURVEY.md §8: Pallas only where
XLA fusion falls short — the fused kernel must be a drop-in for the XLA
path).  Runs the SAME kernel in interpreter mode on the 8-device CPU mesh;
the real-TPU path is exercised by bench.py and the TPU test run."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster.kmeans import _kmeans_fit, _kmeans_fit_fused
from dislib_tpu.parallel import mesh as _mesh


@pytest.mark.parametrize("m,n,k", [(64, 8, 3), (100, 5, 4)])
def test_fused_fit_matches_xla_path(rng, m, n, k):
    x = ds.array((rng.rand(m, n) * 5).astype(np.float32))
    import jax.numpy as jnp
    centers0 = jnp.asarray(np.ascontiguousarray(
        x.collect()[rng.choice(m, k, replace=False)]))
    ref_c, ref_it, ref_inertia, ref_shift = _kmeans_fit(
        x._data, x.shape, centers0, 10, 1e-6)
    fus_c, fus_it, fus_inertia, fus_shift = _kmeans_fit_fused(
        x._data, x.shape, centers0, 10, 1e-6, _mesh.get_mesh(),
        interpret=True)
    assert int(fus_it) == int(ref_it)
    np.testing.assert_allclose(np.asarray(fus_c), np.asarray(ref_c),
                               rtol=1e-4, atol=1e-5)
    assert float(fus_inertia) == pytest.approx(float(ref_inertia), rel=1e-4)


def test_fused_estep_partial_tile(rng):
    """Row count not divisible by the tile/mesh quantum: padded rows must
    carry weight zero."""
    m, n, k = 72, 6, 2          # 72 rows over 8 shards = 9 per shard
    x = ds.array((rng.rand(m, n) + 1).astype(np.float32))
    import jax.numpy as jnp
    centers0 = jnp.asarray(np.ascontiguousarray(x.collect()[[0, 40]]))
    ref = _kmeans_fit(x._data, x.shape, centers0, 5, 0.0)
    fus = _kmeans_fit_fused(x._data, x.shape, centers0, 5, 0.0,
                            _mesh.get_mesh(), interpret=True)
    np.testing.assert_allclose(np.asarray(fus[0]), np.asarray(ref[0]),
                               rtol=1e-4, atol=1e-5)
