"""Serving layer (round-9 tentpole): padded batch buckets, one-dispatch
pipelines, the micro-batching server, and checkpoint hot-swap through the
adoption gate.

Compile-budget note (tier-1 discipline, see ROADMAP): every jitted
program in this file uses ONE feature width (8), ONE bucket ladder
(1/8/64) and module-cached fitted models, so the serving programs
compile once for the whole file.
"""

import ast
import os
import threading
import time
import warnings

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.runtime import AdoptionRejected, adopt_latest, \
    generation_token
from dislib_tpu.serving import (ModelPool, PredictServer, ProgramCache,
                                ServePipeline, bucket_for, bucket_ladder,
                                split_rows)
from dislib_tpu.serving.buckets import BucketTemplate
from dislib_tpu.utils import profiling as prof
from dislib_tpu.utils.checkpoint import FitCheckpoint
from dislib_tpu.utils.faults import corrupt_snapshot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BUCKETS = (1, 8, 64)
NF = 8

_ctx = {}


def ctx():
    """Module-cached data + fitted models (one compile set per file)."""
    if not _ctx:
        rng = np.random.RandomState(7)
        x = rng.rand(200, NF).astype(np.float32)
        a = ds.array(x)
        _ctx["x"] = x
        _ctx["a"] = a
        _ctx["scaler"] = ds.StandardScaler().fit(a)
        _ctx["km"] = ds.KMeans(n_clusters=3, max_iter=4,
                               random_state=0).fit(a)
    return _ctx


def _linreg_state(g):
    """Generation g of the hot-swap test model: ŷ = x @ 1 + g, so a
    response's value − row-sum identifies EXACTLY which generation
    computed it (the torn-handoff oracle)."""
    return {"coef": np.ones((NF, 1), np.float32),
            "intercept": np.full(1, float(g), np.float32)}


def _build_linreg(state):
    lr = ds.LinearRegression()
    lr.coef_ = np.asarray(state["coef"], np.float32)
    lr.intercept_ = np.asarray(state["intercept"], np.float32)
    return ServePipeline(lr, n_features=NF)


def _gen_of(values, rows):
    """Recover the generation a response was computed by (see
    `_linreg_state`); float32 exact for small integers."""
    g = np.unique(np.round(values.ravel() - rows.sum(axis=1), 3))
    assert len(g) == 1, f"response mixes generations: {g}"
    return float(g[0])


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_ladder_default_and_env(self, monkeypatch):
        assert bucket_ladder((64, 1, 8, 8)) == (1, 8, 64)
        monkeypatch.setenv("DSLIB_SERVE_BUCKETS", "4, 32")
        assert bucket_ladder() == (4, 32)
        monkeypatch.delenv("DSLIB_SERVE_BUCKETS")
        assert bucket_ladder()[0] >= 1

    def test_ladder_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            bucket_ladder((0, 8))

    def test_bucket_for(self):
        assert bucket_for(1, BUCKETS) == 1
        assert bucket_for(2, BUCKETS) == 8
        assert bucket_for(8, BUCKETS) == 8
        assert bucket_for(64, BUCKETS) == 64
        assert bucket_for(65, BUCKETS) is None

    def test_split_rows(self):
        assert split_rows(5, BUCKETS) == [5]
        assert split_rows(64, BUCKETS) == [64]
        assert split_rows(150, BUCKETS) == [64, 64, 22]

    def test_template_rezeroes_only_dirty_rows(self):
        t = BucketTemplate((8, 4))
        t.fill(np.ones((5, 4), np.float32) * 3.0)
        buf = t.fill(np.ones((2, 4), np.float32))
        assert np.all(buf[:2] == 1.0)
        assert np.all(buf[2:] == 0.0)       # rows 2:5 were dirty

    def test_template_rejects_oversize(self):
        with pytest.raises(ValueError):
            BucketTemplate((8, 4)).fill(np.ones((9, 4), np.float32))


# ---------------------------------------------------------------------------
# one-dispatch predict pipelines
# ---------------------------------------------------------------------------

class TestOneDispatchPipelines:
    def test_scaler_kmeans_chain_is_one_dispatch(self):
        c = ctx()
        pred = c["km"].predict(c["scaler"].transform(c["a"]))
        assert pred.is_lazy                  # nothing dispatched yet
        pred.force()                         # warm/compile
        prof.reset_counters()
        c["km"].predict(c["scaler"].transform(c["a"])).force()
        assert prof.dispatch_count() == 1
        assert prof.counters()["dispatch_by"] == {"fused_chain": 1}

    def test_warm_predict_adds_zero_traces(self):
        c = ctx()
        c["km"].predict(c["scaler"].transform(c["a"])).force()
        t0 = prof.trace_count()
        c["km"].predict(c["scaler"].transform(c["a"])).force()
        assert prof.trace_count() == t0

    def test_fused_chain_matches_eager(self, monkeypatch):
        c = ctx()
        got = c["km"].predict(c["scaler"].transform(c["a"])).collect()
        monkeypatch.setenv("DSLIB_EAGER", "1")
        eager = c["km"].predict(c["scaler"].transform(c["a"]))
        assert not eager.is_lazy
        np.testing.assert_array_equal(got, eager.collect())

    def test_bucket_predict_matches_direct(self):
        c = ctx()
        pipe = ServePipeline(c["km"], transforms=(c["scaler"],),
                             n_features=NF)
        rows = c["x"][:5]
        direct = c["km"].predict(
            c["scaler"].transform(ds.array(rows))).collect()
        np.testing.assert_array_equal(pipe.predict_bucket(rows, 8), direct)

    def test_bucket_hot_path_is_one_dispatch_zero_traces(self):
        c = ctx()
        pipe = ServePipeline(c["km"], transforms=(c["scaler"],),
                             n_features=NF)
        pipe.predict_bucket(c["x"][:3], 8)   # warm
        prof.reset_counters()
        t0 = prof.trace_count()
        pipe.predict_bucket(c["x"][10:14], 8)
        assert prof.dispatch_count() == 1
        assert prof.trace_count() == t0

    def test_generation_swap_costs_zero_traces(self):
        """Two model generations of identical shapes share one compiled
        executable per bucket — the hot-swap no-recompile invariant."""
        c = ctx()
        km2 = ds.KMeans(n_clusters=3, max_iter=4, random_state=1) \
            .fit(c["a"])
        pipe1 = ServePipeline(c["km"], transforms=(c["scaler"],),
                              n_features=NF)
        pipe2 = ServePipeline(km2, transforms=(c["scaler"],),
                              n_features=NF)
        pipe1.predict_bucket(c["x"][:3], 8)  # warm generation 1
        t0 = prof.trace_count()
        pipe2.predict_bucket(c["x"][:3], 8)  # generation 2: cache hit
        assert prof.trace_count() == t0

    def test_pipeline_rejects_bad_requests(self):
        c = ctx()
        pipe = ServePipeline(c["km"], transforms=(c["scaler"],),
                             n_features=NF)
        with pytest.raises(ValueError, match="features"):
            pipe.predict_bucket(np.ones((2, NF + 1), np.float32), 8)
        with pytest.raises(ValueError, match="exceed"):
            pipe.predict_bucket(np.ones((9, NF), np.float32), 8)

    def test_infers_feature_width(self):
        c = ctx()
        assert ServePipeline(c["km"]).n_features == NF
        assert ServePipeline(c["km"],
                             transforms=(c["scaler"],)).n_features == NF

    def test_program_cache_ledger(self):
        c = ctx()
        pipe = ServePipeline(c["km"], n_features=NF)
        cache = ProgramCache()
        out = cache.warm(pipe, "g0", BUCKETS)
        assert np.all(np.isfinite(out))
        assert len(cache) == len(BUCKETS)
        assert cache.is_warm("g0", 8) and not cache.is_warm("g1", 8)
        cache.rekey("g0", "g1")
        assert cache.is_warm("g1", 8) and not cache.is_warm("g0", 8)
        # rekey evicts superseded generations — the ledger is bounded by
        # one live generation however many adoptions a pool performs
        cache.warm(pipe, "warming", BUCKETS)
        cache.rekey("warming", "g2")
        assert len(cache) == len(BUCKETS)
        assert cache.is_warm("g2", 8) and not cache.is_warm("g1", 8)


# ---------------------------------------------------------------------------
# the micro-batching server
# ---------------------------------------------------------------------------

def _km_server(deadline_ms=5):
    c = ctx()
    pipe = ServePipeline(c["km"], transforms=(c["scaler"],), n_features=NF)
    return PredictServer(pipeline=pipe, buckets=BUCKETS,
                         deadline_ms=deadline_ms)


class TestPredictServer:
    def test_single_request_flushes_on_deadline(self):
        c = ctx()
        with _km_server(deadline_ms=5) as srv:
            r = srv.submit(c["x"][0]).result(timeout=30)
            assert r.values.shape == (1, 1)
            assert srv.stats()["batches"] == 1

    def test_burst_coalesces_one_dispatch_per_batch(self):
        c = ctx()
        with _km_server(deadline_ms=10) as srv:
            futs = [srv.submit(c["x"][i:i + 2]) for i in range(0, 80, 2)]
            outs = [f.result(timeout=30) for f in futs]
            st = srv.stats()
        assert st["requests"] == 40 and st["rows"] == 80
        assert st["batches"] < st["requests"]     # coalescing happened
        assert st["dispatches_per_batch_max"] == 1
        ref = c["km"].predict(
            c["scaler"].transform(c["a"])).collect().ravel()
        for i, o in zip(range(0, 80, 2), outs):
            np.testing.assert_array_equal(o.values.ravel(), ref[i:i + 2])

    def test_oversize_request_splits_across_buckets(self):
        c = ctx()
        with _km_server() as srv:
            r = srv.submit(c["x"][:150]).result(timeout=30)
            st = srv.stats()
        assert r.values.shape == (150, 1)
        # 150 rows over (1, 8, 64): three pieces, one dispatch each
        assert st["dispatches_per_batch_max"] == len(split_rows(150, BUCKETS))

    def test_bad_request_fails_its_future_not_the_server(self):
        c = ctx()
        with _km_server() as srv:
            bad = srv.submit(np.ones((2, NF + 3), np.float32))
            with pytest.raises(ValueError):
                bad.result(timeout=30)
            good = srv.submit(c["x"][:2]).result(timeout=30)
            assert good.values.shape == (2, 1)

    def test_bad_request_does_not_poison_its_cobatched_peers(self):
        """A malformed request coalesced into the same deadline window
        as valid ones must fail ITS future only."""
        c = ctx()
        with _km_server(deadline_ms=50) as srv:
            good1 = srv.submit(c["x"][:2])
            bad = srv.submit(np.ones((2, NF + 3), np.float32))
            good2 = srv.submit(c["x"][2:4])
            with pytest.raises(ValueError, match="features"):
                bad.result(timeout=30)
            assert good1.result(timeout=30).values.shape == (2, 1)
            assert good2.result(timeout=30).values.shape == (2, 1)

    def test_submit_outside_lifecycle_raises(self):
        srv = _km_server()
        with pytest.raises(RuntimeError):
            srv.submit(np.ones((1, NF), np.float32))
        with srv:
            pass
        with pytest.raises(RuntimeError):
            srv.submit(np.ones((1, NF), np.float32))

    def test_queue_backpressure_rejects_not_oom(self):
        """A client outrunning the device hits a typed queue-full error
        instead of growing the queue without bound; already-accepted
        requests still drain at stop()."""
        c = ctx()
        pipe = ServePipeline(c["km"], transforms=(c["scaler"],),
                             n_features=NF)
        srv = PredictServer(pipeline=pipe, buckets=BUCKETS,
                            deadline_ms=2000, max_queue_rows=4)
        with srv:
            futs = [srv.submit(c["x"][i:i + 2]) for i in (0, 2)]
            with pytest.raises(RuntimeError, match="queue full"):
                srv.submit(c["x"][:1])
        for f in futs:                      # stop() drained the queue
            assert f.result(timeout=10).values.shape == (2, 1)

    def test_pool_server_bucket_mismatch_rejected(self, tmp_path):
        """A served bucket the pool never warms/health-gates would pay a
        hot-path compile and dodge the adoption gate — constructor error."""
        pool = ModelPool(FitCheckpoint(str(tmp_path / "g.npz"), keep=2),
                         _build_linreg, buckets=(1, 8))
        with pytest.raises(ValueError, match="warmed ladder"):
            PredictServer(pool=pool, buckets=(1, 8, 64))
        PredictServer(pool=pool, buckets=(1,))      # subset is fine

    def test_predict_leaf_cache_stable_across_methods(self):
        """predict ↔ predict_proba alternate different leaf tuples; the
        device cache must hold one entry per tuple, not thrash (a thrash
        re-uploads the whole model per call)."""
        c = ctx()
        y = ds.array((c["x"][:, 0] > 0.5).astype(np.float32)[:, None])
        rf = ds.RandomForestClassifier(n_estimators=2, max_depth=3,
                                       random_state=0).fit(c["a"], y)
        rf.predict(c["a"]).force()
        rf.predict_proba(c["a"]).force()
        leaves_a = rf._predict_leaves(rf._edges, rf._feats, rf._tbins,
                                      rf._leaves)
        rf.predict(c["a"]).force()                  # alternation...
        leaves_b = rf._predict_leaves(rf._edges, rf._feats, rf._tbins,
                                      rf._leaves)
        assert all(a is b for a, b in zip(leaves_a, leaves_b)), \
            "leaf cache thrashed across method alternation"

    def test_stats_shape(self):
        with _km_server() as srv:
            srv.predict(np.zeros((2, NF), np.float32))
            st = srv.stats()
        for key in ("p50_ms", "p99_ms", "requests", "rows", "batches",
                    "dispatches_per_batch_max", "queue_depth"):
            assert key in st


# ---------------------------------------------------------------------------
# checkpoint hot-swap through the adoption gate
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_adopt_latest_gates_and_tokens(self, tmp_path):
        path = str(tmp_path / "gen.npz")
        writer = FitCheckpoint(path, keep=2)
        reader = FitCheckpoint(path, keep=2)
        assert generation_token(reader) is None
        assert adopt_latest(reader, _build_linreg) is None
        writer.save(_linreg_state(1))
        ad = adopt_latest(reader, _build_linreg,
                          probe=lambda p: p.predict_bucket(
                              np.zeros((1, NF), np.float32), 1))
        assert ad is not None
        # same generation again: no-op
        assert adopt_latest(reader, _build_linreg,
                            last_token=ad.token) is None
        writer.save(_linreg_state(2))
        ad2 = adopt_latest(reader, _build_linreg, last_token=ad.token)
        assert ad2 is not None and ad2.token != ad.token
        assert float(ad2.state["intercept"][0]) == 2.0

    def test_unhealthy_generation_raises_typed(self, tmp_path):
        path = str(tmp_path / "gen.npz")
        writer = FitCheckpoint(path, keep=2)
        writer.save({"coef": np.full((NF, 1), np.nan, np.float32),
                     "intercept": np.zeros(1, np.float32)})
        with pytest.raises(AdoptionRejected):
            adopt_latest(FitCheckpoint(path, keep=2), _build_linreg,
                         probe=lambda p: p.predict_bucket(
                             np.zeros((1, NF), np.float32), 1))

    def test_nan_state_rejected_even_behind_integer_labels(self, tmp_path):
        """The probe alone is blind to NaN parameters when predict emits
        int labels (argmin over all-NaN distances is a finite int32) —
        the STATE gate must refuse the generation anyway."""
        path = str(tmp_path / "gen.npz")
        FitCheckpoint(path, keep=2).save(
            {"centers": np.full((3, NF), np.nan, np.float32)})

        def build(state):
            km = ds.KMeans(n_clusters=3)
            km.centers_ = np.asarray(state["centers"], np.float32)
            return ServePipeline(km, n_features=NF)

        probe = lambda p: p.predict_bucket(  # noqa: E731
            np.zeros((1, NF), np.float32), 1)
        out = probe(build({"centers": np.full((3, NF), np.nan,
                                              np.float32)}))
        assert np.all(np.isfinite(out))      # the blindness being tested
        with pytest.raises(AdoptionRejected, match="non-finite state"):
            adopt_latest(FitCheckpoint(path, keep=2), build, probe=probe)

    def test_writer_rotation_never_yields_torn_state(self, tmp_path):
        """Satellite 3: a writer rotating keep=2 generations at full speed
        while a reader adopt-loops — every adoption must observe a
        complete, internally-consistent generation (the per-response
        oracle: coef all-ones AND an integer intercept the writer
        actually wrote)."""
        path = str(tmp_path / "gen.npz")
        writer = FitCheckpoint(path, keep=2)
        reader = FitCheckpoint(path, keep=2)
        n_gens = 25
        stop = threading.Event()

        def write():
            for g in range(1, n_gens + 1):
                writer.save(_linreg_state(g))
            stop.set()

        t = threading.Thread(target=write)
        t.start()
        seen = []
        last = None
        try:
            while not stop.is_set() or not seen:
                ad = adopt_latest(reader, _build_linreg, last_token=last)
                if ad is None:
                    continue
                last = ad.token
                assert np.array_equal(ad.state["coef"],
                                      np.ones((NF, 1), np.float32)), \
                    "torn generation: coef not the written value"
                g = float(ad.state["intercept"][0])
                assert g == int(g) and 1 <= g <= n_gens, \
                    f"torn generation: intercept {g}"
                seen.append(g)
        finally:
            t.join()
        assert seen == sorted(seen), "adoptions went backwards"

    def test_live_reader_never_misreads_rotation_as_corruption(
            self, tmp_path):
        """Verify-drive regression: a reader polling a LIVE checkpoint
        can hit the rotation gap (path renamed away between exists() and
        open()).  That transient FileNotFoundError must read as "try the
        next generation", NOT as corruption — the corrupt-fallback
        warning path would misdiagnose (and its cleanup could delete a
        racing writer's brand-new generation)."""
        path = str(tmp_path / "gen.npz")
        w = FitCheckpoint(path, keep=2)
        w.save(_linreg_state(1))
        reader = FitCheckpoint(path, keep=2)
        stop = threading.Event()

        def churn():
            g = 2
            while not stop.is_set():
                w.save(_linreg_state(g))
                g += 1

        t = threading.Thread(target=churn)
        t.start()
        try:
            with warnings.catch_warnings():
                # ANY corrupt-fallback warning under pure rotation churn
                # is the misdiagnosis this test pins
                warnings.simplefilter("error", RuntimeWarning)
                end = time.time() + 1.5
                while time.time() < end:
                    state = reader.load()
                    assert state is not None
                    g = float(state["intercept"][0])
                    assert g == int(g) and g >= 1
        finally:
            stop.set()
            t.join()

    def test_pool_swaps_skips_unhealthy_and_survives_corruption(
            self, tmp_path):
        path = str(tmp_path / "gen.npz")
        writer = FitCheckpoint(path, keep=2)
        pool = ModelPool(FitCheckpoint(path, keep=2), _build_linreg,
                         buckets=BUCKETS, poll_interval_s=0.0)
        writer.save(_linreg_state(1))
        assert pool.poll(force=True)
        rows = ctx()["x"][:4]

        def served_gen():
            _, pipe = pool.current()
            return _gen_of(pipe.predict_bucket(rows, 8), rows)

        assert served_gen() == 1.0
        # unhealthy generation: health gate refuses, old gen stays live
        writer.save({"coef": np.full((NF, 1), np.nan, np.float32),
                     "intercept": np.zeros(1, np.float32)})
        assert not pool.poll(force=True)
        assert pool.rejections == 1 and served_gen() == 1.0
        # a rejected token is remembered — no re-gating storm
        assert not pool.poll(force=True)
        assert pool.rejections == 1
        # a good successor adopts
        writer.save(_linreg_state(3))
        assert pool.poll(force=True)
        assert served_gen() == 3.0
        # corrupt the newest file (PR-1 injector): the verified load falls
        # back to the previous good generation instead of serving garbage
        with pytest.warns(RuntimeWarning):
            writer.save(_linreg_state(4))
            corrupt_snapshot(path)
            pool.poll(force=True)
        g = served_gen()
        assert g in (3.0, 4.0) and g == int(g)   # SOME complete generation
        assert np.all(np.isfinite(pool.current()[1].predict_bucket(rows, 8)))

    def test_server_over_pool_serves_across_swaps(self, tmp_path):
        path = str(tmp_path / "gen.npz")
        writer = FitCheckpoint(path, keep=2)
        writer.save(_linreg_state(1))
        pool = ModelPool(FitCheckpoint(path, keep=2), _build_linreg,
                         buckets=BUCKETS, poll_interval_s=0.0)
        rows = ctx()["x"][:4]
        with PredictServer(pool=pool, deadline_ms=1) as srv:
            r1 = srv.submit(rows).result(timeout=30)
            assert _gen_of(r1.values, rows) == 1.0
            writer.save(_linreg_state(2))
            deadline = time.time() + 30
            while time.time() < deadline:
                r = srv.submit(rows).result(timeout=30)
                assert _gen_of(r.values, rows) in (1.0, 2.0)
                if r.generation != r1.generation:
                    break
                time.sleep(0.005)
            assert r.generation != r1.generation, "swap never served"
            assert _gen_of(r.values, rows) == 2.0
            assert srv.stats()["swaps"] == 2    # initial adoption + swap


# ---------------------------------------------------------------------------
# adoption-gate lint: serving may only reach checkpoints via the gate
# ---------------------------------------------------------------------------

SERVING_DIR = "dislib_tpu/serving"
ADOPTION = "dislib_tpu/runtime/adoption.py"

# raw snapshot-read spellings forbidden anywhere under serving/ — every
# model read must flow through runtime.adoption.adopt_latest (checksum
# verify + health-gated warmup), the read-side analog of the PR-3
# "writes go through guard.save_async" lint
_FORBIDDEN_ATTR_CALLS = ("load",)
_FORBIDDEN_NP_CALLS = ("load", "savez")


def _serving_files():
    d = os.path.join(REPO, SERVING_DIR)
    for fn in sorted(os.listdir(d)):
        if fn.endswith(".py"):
            yield f"{SERVING_DIR}/{fn}", os.path.join(d, fn)


class TestAdoptionGateLint:
    def test_serving_never_reads_snapshots_directly(self):
        offenders = []
        for rel, full in _serving_files():
            tree = ast.parse(open(full, encoding="utf-8").read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in _FORBIDDEN_ATTR_CALLS:
                        offenders.append(f"{rel}:{node.lineno}: .{f.attr}()")
                    elif isinstance(f.value, ast.Name) \
                            and f.value.id in ("np", "numpy", "zipfile") \
                            and f.attr in _FORBIDDEN_NP_CALLS:
                        offenders.append(
                            f"{rel}:{node.lineno}: {f.value.id}.{f.attr}()")
                elif isinstance(f, ast.Name) and f.id == "open":
                    offenders.append(f"{rel}:{node.lineno}: open()")
        assert not offenders, (
            "serving code reading checkpoint/model state around the "
            "adoption gate — route it through runtime.adoption."
            "adopt_latest:\n  " + "\n  ".join(offenders))

    def test_serving_imports_the_gate(self):
        src = open(os.path.join(REPO, SERVING_DIR, "hotswap.py"),
                   encoding="utf-8").read()
        assert "adopt_latest" in src, \
            "hotswap no longer routes through runtime.adoption"

    def test_lint_covers_round15_serving_files(self):
        """The round-15 files carry the highest-stakes byte handling in
        the package (serialized executables, embedded model state) — the
        directory scan must keep seeing them, or the no-raw-IO lint above
        silently stops protecting exactly where it matters most."""
        scanned = {os.path.basename(rel) for rel, _ in _serving_files()}
        assert {"bundle.py", "router.py"} <= scanned

    def test_bundle_routes_bytes_and_state_through_blessed_seams(self):
        """bundle.py may only touch artifact bytes through the
        runtime.bundle_io seam (write_bundle/read_bundle — atomic,
        checksum-verified) and checkpoint state through adopt_latest —
        the round-15 extension of the adoption-gate discipline."""
        src = open(os.path.join(REPO, SERVING_DIR, "bundle.py"),
                   encoding="utf-8").read()
        for seam in ("write_bundle", "read_bundle", "adopt_latest"):
            assert seam in src, \
                f"serving/bundle.py no longer routes through {seam}"

    def test_adoption_module_uses_verified_load_and_probe_gate(self):
        """The gate itself must (1) read via checkpoint.load() — the
        checksum-verified, fallback-capable reader — and (2) judge the
        probe output through the health layer before returning."""
        tree = ast.parse(open(os.path.join(REPO, ADOPTION),
                              encoding="utf-8").read())
        fn = next(n for n in ast.walk(tree)
                  if isinstance(n, ast.FunctionDef)
                  and n.name == "adopt_latest")
        calls = [n.func for n in ast.walk(fn) if isinstance(n, ast.Call)]
        attrs = {f.attr for f in calls if isinstance(f, ast.Attribute)}
        assert "load" in attrs, "adopt_latest no longer calls " \
            "checkpoint.load() (the verified reader)"
        assert "check_host" in attrs, "adopt_latest dropped the health " \
            "gate on the warmup probe"
        # and no raw np.load / _load_verified bypass
        names = {f.attr for f in calls if isinstance(f, ast.Attribute)
                 and isinstance(f.value, ast.Name)
                 and f.value.id in ("np", "numpy")}
        assert "load" not in names
