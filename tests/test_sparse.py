"""Sparse ds-array tests (reference: sparse CSR block variants across
test_array/test_kmeans — SURVEY.md §5 "sparse/dense variants ... catch the
most bugs"; §8 sparse-support decision record in data/sparse.py)."""

import numpy as np
import pytest
import scipy.sparse as sp

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.data.sparse import SparseArray


def _rand_csr(rng, m=40, n=12, density=0.2):
    return sp.random(m, n, density=density, format="csr",
                     random_state=rng, dtype=np.float32)


class TestSparseArray:
    def test_roundtrip_collect(self, rng):
        mat = _rand_csr(rng)
        a = SparseArray.from_scipy(mat)
        got = a.collect()
        assert sp.issparse(got)
        np.testing.assert_allclose(got.toarray(), mat.toarray(), rtol=1e-6)
        assert a.nnz == mat.nnz
        assert a.shape == mat.shape

    def test_to_dense_matches(self, rng):
        mat = _rand_csr(rng)
        dense = SparseArray.from_scipy(mat).to_dense()
        np.testing.assert_allclose(dense.collect(), mat.toarray(), rtol=1e-6)

    def test_matmul_dense_oracle(self, rng):
        mat = _rand_csr(rng, m=30, n=10)
        rhs = rng.rand(10, 7).astype(np.float32)
        out = SparseArray.from_scipy(mat) @ ds.array(rhs)
        np.testing.assert_allclose(out.collect(), mat.toarray() @ rhs,
                                   rtol=1e-4, atol=1e-5)

    def test_matmul_shape_mismatch(self, rng):
        with pytest.raises(ValueError):
            SparseArray.from_scipy(_rand_csr(rng, m=5, n=3)) @ np.ones((4, 2))

    def test_transpose(self, rng):
        mat = _rand_csr(rng, m=9, n=5)
        t = SparseArray.from_scipy(mat).T
        assert t.shape == (5, 9)
        np.testing.assert_allclose(t.collect().toarray(), mat.toarray().T,
                                   rtol=1e-6)

    def test_sums_and_means(self, rng):
        mat = _rand_csr(rng, m=15, n=6)
        a = SparseArray.from_scipy(mat)
        dense = mat.toarray()
        np.testing.assert_allclose(a.sum(axis=0).collect().ravel(),
                                   dense.sum(axis=0), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(a.sum(axis=1).collect().ravel(),
                                   dense.sum(axis=1), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(a.mean(axis=None).collect().ravel(),
                                   [dense.mean()], rtol=1e-5)

    def test_row_norms(self, rng):
        mat = _rand_csr(rng, m=12, n=8)
        got = np.asarray(SparseArray.from_scipy(mat).row_norms_sq())
        np.testing.assert_allclose(got, (mat.toarray() ** 2).sum(axis=1),
                                   rtol=1e-5, atol=1e-6)


class TestSparseKMeans:
    def test_sparse_fit_matches_dense(self, rng):
        # block-structured sparse blobs
        dense = np.zeros((90, 10), np.float32)
        dense[:45, :5] = rng.rand(45, 5) + 2
        dense[45:, 5:] = rng.rand(45, 5) + 2
        init = np.ascontiguousarray(dense[[0, 60]])
        km_d = KMeans(n_clusters=2, init=init, max_iter=20).fit(ds.array(dense))
        km_s = KMeans(n_clusters=2, init=init, max_iter=20).fit(
            SparseArray.from_scipy(sp.csr_matrix(dense)))
        np.testing.assert_allclose(km_s.centers_, km_d.centers_,
                                   rtol=1e-4, atol=1e-5)
        assert km_s.n_iter_ == km_d.n_iter_
        assert km_s.inertia_ == pytest.approx(km_d.inertia_, rel=1e-4)

    def test_sparse_predict_and_random_init(self, rng):
        dense = np.zeros((60, 8), np.float32)
        dense[:30, :4] = rng.rand(30, 4) + 3
        dense[30:, 4:] = rng.rand(30, 4) + 3
        sx = SparseArray.from_scipy(sp.csr_matrix(dense))
        km = KMeans(n_clusters=2, random_state=0, max_iter=20).fit(sx)
        labels = km.predict(sx).collect().ravel().astype(int)
        assert len(np.unique(labels[:30])) == 1
        assert len(np.unique(labels[30:])) == 1
        assert labels[0] != labels[-1]
        assert km.score(sx) <= 0.0


class TestSvmlightSparse:
    def test_loader_returns_sparse(self, tmp_path):
        path = str(tmp_path / "data.svm")
        with open(path, "w") as f:
            f.write("1 1:0.5 3:2.0\n0 2:1.5\n1 1:1.0 2:0.5 3:0.25\n")
        x, y = ds.load_svmlight_file(path, n_features=3, store_sparse=True)
        assert isinstance(x, SparseArray)
        got = x.collect().toarray()
        want = np.array([[0.5, 0, 2.0], [0, 1.5, 0], [1.0, 0.5, 0.25]],
                        np.float32)
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(y.collect().ravel(), [1, 0, 1])


class TestShardedRows:
    def test_spmm_equivalence(self, rng):
        """sharded_rows buffers reproduce x @ B and x.T @ C exactly."""
        import jax.numpy as jnp
        import scipy.sparse as sp
        from dislib_tpu.parallel import mesh as _mesh
        dense = (rng.rand(37, 9) * (rng.rand(37, 9) < 0.3)).astype(np.float32)
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        data, lrows, cols, rowsq = xs.sharded_rows()
        p, m_local = rowsq.shape
        # reconstruct the dense matrix from the sharded buffers
        rec = np.zeros((p * m_local, 9), np.float32)
        d, lr, cc = (np.asarray(a) for a in (data, lrows, cols))
        for s in range(p):
            np.add.at(rec[s * m_local:(s + 1) * m_local], (lr[s], cc[s]), d[s])
        np.testing.assert_allclose(rec[:37], dense, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(rowsq).reshape(-1)[:37], (dense ** 2).sum(1), rtol=1e-5)

    def test_sparse_kmeans_matches_dense_on_mesh(self, rng):
        """Oracle equality dense vs sharded-sparse path on the multi-device
        mesh (SURVEY §8 hard part 2 done-criterion)."""
        import scipy.sparse as sp
        dense = (rng.rand(200, 6) * (rng.rand(200, 6) < 0.4)).astype(np.float32)
        init = dense[:3].copy()
        km_d = KMeans(n_clusters=3, init=init, max_iter=15, tol=0.0).fit(
            ds.array(dense))
        km_s = KMeans(n_clusters=3, init=init, max_iter=15, tol=0.0).fit(
            SparseArray.from_scipy(sp.csr_matrix(dense)))
        np.testing.assert_allclose(km_s.centers_, km_d.centers_,
                                   rtol=1e-3, atol=1e-3)
        assert abs(km_s.inertia_ - km_d.inertia_) / km_d.inertia_ < 1e-3


class TestSparseElementwise:
    def test_scalar_ops_stay_sparse(self, rng):
        import scipy.sparse as sp
        dense = (rng.rand(10, 5) * (rng.rand(10, 5) < 0.5)).astype(np.float32)
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        out = (xs * 2.0) / 4.0
        assert isinstance(out, SparseArray)
        np.testing.assert_allclose(out.collect().toarray(), dense / 2.0,
                                   rtol=1e-6)
        neg = -xs
        np.testing.assert_allclose(neg.collect().toarray(), -dense, rtol=1e-6)

    def test_sparse_add_sub(self, rng):
        import scipy.sparse as sp
        a = (rng.rand(8, 4) * (rng.rand(8, 4) < 0.5)).astype(np.float32)
        b = (rng.rand(8, 4) * (rng.rand(8, 4) < 0.5)).astype(np.float32)
        sa = SparseArray.from_scipy(sp.csr_matrix(a))
        sb = SparseArray.from_scipy(sp.csr_matrix(b))
        tot = sa + sb
        assert isinstance(tot, SparseArray)
        np.testing.assert_allclose(tot.collect().toarray(), a + b, rtol=1e-6)
        diff = sa - sb
        np.testing.assert_allclose(diff.collect().toarray(), a - b, rtol=1e-6)

    def test_shape_mismatch_raises(self, rng):
        import scipy.sparse as sp
        sa = SparseArray.from_scipy(sp.csr_matrix(np.eye(4, dtype=np.float32)))
        sb = SparseArray.from_scipy(sp.csr_matrix(np.eye(5, dtype=np.float32)))
        with pytest.raises(ValueError):
            sa + sb


class TestSparseScaler:
    """StandardScaler sparse awareness (SURVEY §3.3: no centering of
    sparse; scale without densifying)."""

    def _data(self):
        rng = np.random.RandomState(7)
        dense = rng.rand(60, 9).astype(np.float32)
        dense[dense < 0.6] = 0.0
        return dense

    def test_sparse_scaler_matches_dense(self):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.preprocessing import StandardScaler
        dense = self._data()
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        xd = ds.array(dense, block_size=(16, 9))

        s_sp = StandardScaler(with_mean=False).fit(xs)
        s_d = StandardScaler(with_mean=False).fit(xd)
        np.testing.assert_allclose(np.asarray(s_sp.var_.collect()),
                                   np.asarray(s_d.var_.collect()),
                                   rtol=1e-4, atol=1e-5)
        t_sp = s_sp.transform(xs)
        t_d = s_d.transform(xd)
        out = t_sp.collect()
        out = out.toarray() if hasattr(out, "toarray") else np.asarray(out)
        np.testing.assert_allclose(out, np.asarray(t_d.collect()),
                                   rtol=1e-4, atol=1e-5)
        # round trip
        back = s_sp.inverse_transform(t_sp).collect()
        back = back.toarray() if hasattr(back, "toarray") else np.asarray(back)
        np.testing.assert_allclose(back, dense, rtol=1e-4, atol=1e-5)

    def test_sparse_centering_raises(self):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.preprocessing import StandardScaler, MinMaxScaler
        xs = SparseArray.from_scipy(sp.csr_matrix(self._data()))
        with pytest.raises(ValueError):
            StandardScaler(with_mean=True).fit(xs)
        with pytest.raises(TypeError):
            MinMaxScaler().fit(xs)


class TestSparseKNN:
    """VERDICT r2 #6: sparse-native NearestNeighbors — cross-terms via spmm /
    bounded dense windows, no whole-matrix densification — plus the densify
    budget guard on the `_data` escape hatch."""

    def _data(self, m=150, n=12, seed=3):
        rng = np.random.RandomState(seed)
        dense = rng.rand(m, n).astype(np.float32)
        dense[dense < 0.7] = 0.0
        return dense

    def test_sparse_fit_sparse_query_matches_dense(self, monkeypatch):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.neighbors import NearestNeighbors
        import dislib_tpu.neighbors.base as nb
        monkeypatch.setattr(nb, "_CHUNK", 32)    # force multi-chunk streaming
        dense = self._data()
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        # guard armed: ANY full densification would raise
        monkeypatch.setenv("DSLIB_SPARSE_DENSIFY_BUDGET", "1")
        d_sp, i_sp = NearestNeighbors(n_neighbors=4).fit(xs).kneighbors(xs)
        monkeypatch.delenv("DSLIB_SPARSE_DENSIFY_BUDGET")
        xd = ds.array(dense)
        d_d, i_d = NearestNeighbors(n_neighbors=4).fit(xd).kneighbors(xd)
        # atol 2e-3: the dense oracle's own GEMM cancellation noise is
        # ~5e-4 on self-distances (the sparse path is exactly 0 there)
        np.testing.assert_allclose(np.asarray(d_sp.collect()),
                                   np.asarray(d_d.collect()),
                                   rtol=1e-3, atol=2e-3)
        np.testing.assert_array_equal(np.asarray(i_sp.collect()),
                                      np.asarray(i_d.collect()))

    def test_mixed_sparse_dense(self, monkeypatch):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.neighbors import NearestNeighbors
        dense = self._data(m=80)
        q = self._data(m=20, seed=5)
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        xd, qd = ds.array(dense), ds.array(q)
        d_ref, i_ref = NearestNeighbors(n_neighbors=3).fit(xd).kneighbors(qd)
        # sparse fit, dense query
        d1, i1 = NearestNeighbors(n_neighbors=3).fit(xs).kneighbors(qd)
        np.testing.assert_array_equal(np.asarray(i1.collect()),
                                      np.asarray(i_ref.collect()))
        # dense fit, sparse query
        qs = SparseArray.from_scipy(sp.csr_matrix(q))
        d2, i2 = NearestNeighbors(n_neighbors=3).fit(xd).kneighbors(qs)
        np.testing.assert_array_equal(np.asarray(i2.collect()),
                                      np.asarray(i_ref.collect()))
        np.testing.assert_allclose(np.asarray(d1.collect()),
                                   np.asarray(d_ref.collect()),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(d2.collect()),
                                   np.asarray(d_ref.collect()),
                                   rtol=1e-3, atol=1e-4)

    def test_densify_guard_trips_and_opts_out(self, monkeypatch):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        xs = SparseArray.from_scipy(sp.csr_matrix(self._data()))
        monkeypatch.setenv("DSLIB_SPARSE_DENSIFY_BUDGET", "1")
        with pytest.raises(MemoryError, match="DSLIB_SPARSE_DENSIFY_BUDGET"):
            xs._data
        # raising the budget opts out
        monkeypatch.setenv("DSLIB_SPARSE_DENSIFY_BUDGET", str(1 << 30))
        assert xs._data.shape[0] >= 150

    def test_sparse_knn_classifier_no_densify(self, monkeypatch):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.classification import KNeighborsClassifier
        rng = np.random.RandomState(0)
        dense = np.vstack([rng.rand(40, 8), rng.rand(40, 8) + 2.0]) \
            .astype(np.float32)
        dense[dense < 0.5] = 0.0
        y = np.r_[np.zeros(40), np.ones(40)].astype(np.float32)[:, None]
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        monkeypatch.setenv("DSLIB_SPARSE_DENSIFY_BUDGET", "1")
        est = KNeighborsClassifier(n_neighbors=3).fit(xs, ds.array(y))
        pred = est.predict(xs).collect().ravel()
        acc_async = float(est._score_async((xs,), xs, ds.array(y)))
        monkeypatch.delenv("DSLIB_SPARSE_DENSIFY_BUDGET")
        xd = ds.array(dense)
        ref = KNeighborsClassifier(n_neighbors=3).fit(xd, ds.array(y))
        np.testing.assert_array_equal(pred, ref.predict(xd).collect().ravel())
        assert np.isclose(acc_async, ref.score(xd, ds.array(y)), rtol=1e-6)

    def test_row_steps_bounded_under_skew(self):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        # one pathologically dense row block amid near-empty rows
        rng = np.random.RandomState(1)
        m, n = 5000, 64
        rows = np.r_[np.full(20000, 7), rng.randint(0, m, 500)]
        cols = rng.randint(0, n, rows.shape[0])
        mat = sp.csr_matrix((np.ones(rows.shape[0], np.float32),
                             (rows, cols)), shape=(m, n))
        xs = SparseArray.from_scipy(mat)
        data, lrows, colb, row_off, rows_in = xs.row_steps(1024)
        total_alloc = data.size
        nnz = xs.nnz
        # rectangles stay within a small factor of the actual triplets
        assert total_alloc <= 6 * nnz + 10 * data.shape[1]
        # steps partition all m rows exactly once
        spans = sorted(zip(np.asarray(row_off), np.asarray(rows_in)))
        assert spans[0][0] == 0
        covered = 0
        for ro, rc in spans:
            assert ro == covered
            covered += int(rc)
        assert covered == m


class TestSparseIndexingAndMeta:
    def test_getitem_matches_dense_oracle(self, rng):
        import scipy.sparse as sp
        x = sp.random(80, 10, density=0.3, random_state=0,
                      dtype=np.float32).tocsr()
        xs = SparseArray.from_scipy(x)
        d = ds.array(np.asarray(x.todense()))
        for key in [(slice(3, 40), slice(None)), ([5, 2, 9], slice(1, 7)),
                    (np.arange(80) % 3 == 0, slice(None, None, 2)), 7]:
            got = np.asarray(xs[key].collect().todense())
            np.testing.assert_allclose(got, d[key].collect())

    def test_kfold_and_search_over_sparse(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.model_selection import KFold, GridSearchCV
        from dislib_tpu.cluster import KMeans
        x = sp.random(80, 10, density=0.3, random_state=0,
                      dtype=np.float32).tocsr()
        xs = SparseArray.from_scipy(x)
        folds = list(KFold(n_splits=3).split(xs))
        assert all(isinstance(f[0], SparseArray) for f in folds)
        assert sum(f[2].shape[0] for f in folds) == 80
        gs = GridSearchCV(KMeans(random_state=0, max_iter=3),
                          {"n_clusters": [2, 3]}, cv=2, refit=False).fit(xs)
        assert np.isfinite(gs.best_score_)

    def test_shuffle_and_split_stay_sparse(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.utils import shuffle, train_test_split
        x = sp.random(60, 8, density=0.3, random_state=1,
                      dtype=np.float32).tocsr()
        xs = SparseArray.from_scipy(x)
        xsh = shuffle(xs, random_state=1)
        assert isinstance(xsh, SparseArray)
        a = np.asarray(x.todense())
        b = np.asarray(xsh.collect().todense())
        assert sorted(map(tuple, a.tolist())) == sorted(map(tuple, b.tolist()))
        tr, te = train_test_split(xs, test_size=0.25, random_state=2)
        assert isinstance(tr, SparseArray) and tr.shape == (45, 8)
        assert te.shape == (15, 8)


# ---------------------------------------------------------------------------
# round-17 leg 3: CSVM/kNN staging built on-device from the sharded rep
# ---------------------------------------------------------------------------

class TestDeviceStaging:
    """A sharded-backed SparseArray stages its consumer views (the CSVM
    ELL buffers, the kNN row-step rectangles) ON DEVICE from the sharded
    primaries — transfer-guard-pinned, bit-equal to the legacy host
    staging, and with zero BCOO/host-triplet materialisations on the
    estimator fit paths."""

    def _pair(self, rng, m=300, n=48, density=0.07):
        """(host-backed, sharded-only) views of the same matrix."""
        from dislib_tpu.parallel import mesh as _mesh
        mat = sp.random(m, n, density=density, random_state=rng,
                        format="csr", dtype=np.float32)
        xs_host = SparseArray.from_scipy(mat)
        rep = SparseArray.from_scipy(mat).sharded(_mesh.get_mesh())
        return xs_host, SparseArray(sharded=rep)

    def test_staging_is_transfer_free_and_bit_equal(self, rng):
        import jax
        from dislib_tpu.utils import profiling as prof
        m = 300
        xs_host, xs = self._pair(rng, m=m)
        t0 = prof.transfer_count()
        with jax.transfer_guard("disallow"):
            ell_d = xs.ell()
            rs_d = xs.row_steps(64)
        assert prof.transfer_count() == t0
        # ELL: device buffers carry the padded row tail; rows past m are
        # all-zero and the first m are BIT-equal to the host staging
        vh, ch = (np.asarray(a) for a in xs_host.ell())
        vd, cd = (np.asarray(a) for a in ell_d)
        assert vd.shape[1] == vh.shape[1]
        np.testing.assert_array_equal(vd[:m], vh)
        np.testing.assert_array_equal(cd[:m], ch)
        assert not vd[m:].any() and not cd[m:].any()
        # row-steps: same greedy plan math from the same row_nnz metadata
        # → all five buffers bit-identical
        for a, b, name in zip(rs_d, xs_host.row_steps(64),
                              ("data", "lrows", "cols", "row_off",
                               "rows_in")):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
        assert xs._bcoo_val is None     # staging never built a BCOO

    def test_csvm_fit_zero_bcoo_and_same_svs(self, rng):
        from dislib_tpu.classification.csvm import CascadeSVM
        m = 300
        xs_host, xs = self._pair(rng, m=m)
        y = (rng.rand(m) > 0.5).astype(np.float32)
        ya = ds.array(y.reshape(-1, 1))
        kw = dict(cascade_arity=2, max_iter=2, c=1.0, gamma=0.1)
        clf = CascadeSVM(**kw).fit(xs, ya)
        assert xs._bcoo_val is None, "CSVM fit materialised the BCOO"
        clf_h = CascadeSVM(**kw).fit(xs_host, ya)
        np.testing.assert_array_equal(np.sort(clf._sv_idx),
                                      np.sort(clf_h._sv_idx))

    def test_knn_fit_query_zero_bcoo_and_equal(self, rng):
        from dislib_tpu.neighbors import NearestNeighbors
        xs_host, xs = self._pair(rng)
        d1, i1 = NearestNeighbors(n_neighbors=3).fit(xs).kneighbors(xs)
        assert xs._bcoo_val is None, "kNN materialised the BCOO"
        d2, i2 = NearestNeighbors(n_neighbors=3).fit(xs_host) \
            .kneighbors(xs_host)
        np.testing.assert_array_equal(np.asarray(i1.collect()),
                                      np.asarray(i2.collect()))
        np.testing.assert_allclose(np.asarray(d1.collect()),
                                   np.asarray(d2.collect()), atol=1e-6)

    def test_ell_budget_exceeded_still_falls_back(self, rng, monkeypatch):
        """A sharded rep whose ELL canvas would blow the byte budget
        returns None from ell() — the CSVM host-CSR fallback's contract
        (k_of) stays reachable."""
        _, xs = self._pair(rng, m=80, n=16, density=0.3)
        monkeypatch.setenv("DSLIB_SPARSE_ELL_BUDGET", "256")
        assert xs.ell() is None
