"""Health-guard lint (round-8 robustness PR, the `test_host_sync_lint`
pattern): every chunked fit loop must (1) register a runtime health guard,
(2) actually judge each chunk with it, and (3) route every snapshot write
through the guard's gate — a direct ``checkpoint.save_async`` would let an
unhealthy chunk rotate the last GOOD generation out of the checkpoint,
which is exactly the corruption mode the health layer exists to prevent.

Enforced by AST scan so a new estimator (or a refactor of an existing
one) cannot silently ship an unguarded loop: add the loop to the registry
and wire the guard, or consciously change this lint with a reason.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every chunked fit loop in the library: (file, function) — the function
# must build a guard (`_health.guard(...)`), judge chunks
# (`guard.check(...)` / `guard.check_host(...)`), and gate writes
# (`guard.save_async(...)`)
CHUNKED_FIT_LOOPS = {
    ("dislib_tpu/cluster/kmeans.py", "fit"),
    ("dislib_tpu/cluster/gm.py", "fit"),
    ("dislib_tpu/recommendation/als.py", "fit"),
    ("dislib_tpu/classification/csvm.py", "fit"),
    ("dislib_tpu/trees/decision_tree.py", "_grow_forest"),
    ("dislib_tpu/cluster/dbscan.py", "_fit_checkpointed"),
    ("dislib_tpu/cluster/daura.py", "_fit_checkpointed"),
}

ESTIMATOR_DIRS = (
    "dislib_tpu/cluster",
    "dislib_tpu/classification",
    "dislib_tpu/recommendation",
    "dislib_tpu/trees",
    "dislib_tpu/regression",
    "dislib_tpu/decomposition",
    "dislib_tpu/neighbors",
    "dislib_tpu/optimization",
    "dislib_tpu/model_selection",
)


def _functions(path):
    tree = ast.parse(open(path, encoding="utf-8").read())
    out = {}

    def walk(node, prefix=""):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(child.name, child)
                walk(child, child.name)
            else:
                walk(child, prefix)

    walk(tree)
    return out


def _calls(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _attr_call(call, attr):
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr == attr


def _receiver_name(call):
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


def test_every_chunked_fit_loop_registers_a_guard_and_checks_chunks():
    missing = []
    for rel, fname in sorted(CHUNKED_FIT_LOOPS):
        fns = _functions(os.path.join(REPO, rel))
        fn = fns.get(fname)
        if fn is None:
            missing.append(f"{rel}: function {fname}() no longer exists — "
                           "update the lint registry")
            continue
        calls = list(_calls(fn))
        registers = any(
            (_attr_call(c, "guard") and _receiver_name(c) == "_health")
            or _attr_call(c, "make_guard")
            for c in calls)
        # dbscan/daura build the guard in fit() and pass it down — accept
        # a `guard` parameter as registration for those
        takes_param = any(a.arg == "guard" for a in fn.args.args)
        if not (registers or takes_param):
            missing.append(f"{rel}:{fname}() never registers a health "
                           "guard (_health.guard(...))")
        checks = any(_attr_call(c, "check") or _attr_call(c, "check_host")
                     for c in calls
                     if _receiver_name(c) in ("guard", "self"))
        if not checks:
            missing.append(f"{rel}:{fname}() never judges a chunk "
                           "(guard.check / guard.check_host)")
    assert not missing, (
        "chunked fit loops without a wired health guard:\n  "
        + "\n  ".join(missing))


def test_snapshot_writes_are_gated_on_the_guard():
    """No estimator file may write a snapshot around the guard: every
    ``save_async`` call must be the guard's own gate, and blocking
    ``checkpoint.save`` must not appear at all."""
    offenders = []
    for d in ESTIMATOR_DIRS:
        full_dir = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full_dir)):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(full_dir, fn)
            tree = ast.parse(open(path, encoding="utf-8").read())
            for call in _calls(tree):
                if _attr_call(call, "save_async") and \
                        _receiver_name(call) != "guard":
                    offenders.append(
                        f"{d}/{fn}:{call.lineno}: ungated "
                        f"{_receiver_name(call)}.save_async(...)")
                if _attr_call(call, "save") and \
                        _receiver_name(call) in ("checkpoint", "ck"):
                    offenders.append(
                        f"{d}/{fn}:{call.lineno}: ungated checkpoint.save")
    assert not offenders, (
        "snapshot writes that bypass the health gate (route them through "
        "guard.save_async so a bad chunk can never rotate out the last "
        "good generation):\n  " + "\n  ".join(offenders))


def test_registry_entries_still_exist():
    """A refactor that renames a registered loop must update the registry
    — dead entries would quietly bless future unguarded loops."""
    dead = []
    for rel, fname in sorted(CHUNKED_FIT_LOOPS):
        path = os.path.join(REPO, rel)
        if not os.path.exists(path) or fname not in _functions(path):
            dead.append(f"{rel}:{fname}")
    assert not dead, f"lint registry entries no longer match code: {dead}"
