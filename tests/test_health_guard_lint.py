"""Fit-loop driver lint (round-12 robustness PR, retargeted from the
round-8 guard lint): the per-chunk resilience protocol — guard
registration, admit, health checks, verdict-gated snapshot writes,
rollback, preemption polls — lives in ONE place,
``dislib_tpu.runtime.fitloop.ChunkedFitLoop``.  Estimator code that
hand-rolls any piece of it is a lint failure:

1. every chunked fit loop in the registry must actually drive its chunks
   through ``ChunkedFitLoop`` (``run``/``run_one``);
2. estimator code may not call the protocol primitives directly —
   ``save_async``/``checkpoint.save`` (an ungated write could rotate the
   last GOOD generation away), ``remediate``/``admit``/``check``/
   ``check_host`` (a private rollback block bypasses the escalation
   ladder and its shared budget), ``checkpoint.load`` (rollback targets
   belong to the driver), or the preemption polls (a hand-rolled chunk
   boundary).  Exceptions live in the allowlist WITH a reason, and a
   dead allowlist entry is itself a failure;
3. the streaming recipe stays honest: ``MiniBatchKMeans.partial_fit``
   (the zero-bespoke-resilience acceptance estimator) is registry-bound
   like the seven ported loops.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every chunked fit loop in the library: (file, function) — the function
# must instantiate ChunkedFitLoop and call .run(...) / .run_one(...)
CHUNKED_FIT_LOOPS = {
    ("dislib_tpu/cluster/kmeans.py", "fit"),
    ("dislib_tpu/cluster/minibatch.py", "partial_fit"),
    ("dislib_tpu/cluster/gm.py", "fit"),
    ("dislib_tpu/recommendation/als.py", "fit"),
    ("dislib_tpu/classification/csvm.py", "fit"),
    ("dislib_tpu/trees/decision_tree.py", "_grow_forest"),
    ("dislib_tpu/cluster/dbscan.py", "_fit_checkpointed"),
    ("dislib_tpu/cluster/daura.py", "_fit_checkpointed"),
}

ESTIMATOR_DIRS = (
    "dislib_tpu/cluster",
    "dislib_tpu/classification",
    "dislib_tpu/recommendation",
    "dislib_tpu/trees",
    "dislib_tpu/regression",
    "dislib_tpu/decomposition",
    "dislib_tpu/neighbors",
    "dislib_tpu/optimization",
    "dislib_tpu/model_selection",
)

# (file, attr) -> reason.  Every entry must still occur in the file
# (dead entries would quietly bless future hand-rolled loops).
ALLOWLIST = {
    ("dislib_tpu/trees/decision_tree.py", "check"):
        "adoption-time health gate: _adopt_forest judges the grown "
        "forest's fused leaf hvec at its first host materialisation — "
        "there is no loop left to roll back, so the driver cannot own "
        "this check",
}

# protocol primitives the driver owns.  attr -> receiver restriction
# (None = any receiver; a tuple restricts to those receiver names so
# generic verbs like `load` don't false-positive on np.load)
FORBIDDEN_CALLS = {
    "save_async": None,
    "remediate": None,
    "admit": None,
    "check_host": None,
    "check": ("guard", "g"),
    "save": ("checkpoint", "ck"),
    "load": ("checkpoint", "ck"),
    "raise_if_preempted": None,
    "preemption_requested": None,
}


def _functions(path):
    tree = ast.parse(open(path, encoding="utf-8").read())
    out = {}

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.setdefault(child.name, child)
            walk(child)

    walk(tree)
    return out


def _calls(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _call_name(call):
    """(attr_or_func_name, receiver_name_or_None)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = f.value.id if isinstance(f.value, ast.Name) else None
        return f.attr, recv
    if isinstance(f, ast.Name):
        return f.id, None
    return None, None


def test_every_chunked_fit_loop_runs_on_the_driver():
    missing = []
    for rel, fname in sorted(CHUNKED_FIT_LOOPS):
        fns = _functions(os.path.join(REPO, rel))
        fn = fns.get(fname)
        if fn is None:
            missing.append(f"{rel}: function {fname}() no longer exists — "
                           "update the lint registry")
            continue
        calls = [_call_name(c) for c in _calls(fn)]
        builds = any(n == "ChunkedFitLoop" for n, _ in calls)
        runs = any(n in ("run", "run_one") for n, _ in calls)
        if not builds:
            missing.append(f"{rel}:{fname}() never instantiates "
                           "ChunkedFitLoop — chunked fits must run on the "
                           "driver, not a hand-rolled loop")
        if not runs:
            missing.append(f"{rel}:{fname}() never calls the driver's "
                           "run()/run_one()")
    assert not missing, (
        "chunked fit loops not driven by runtime.fitloop.ChunkedFitLoop:"
        "\n  " + "\n  ".join(missing))


def test_no_hand_rolled_resilience_protocol_in_estimator_code():
    """The five copy-pasted rollback blocks this lint replaced must never
    grow back: any protocol-primitive call in estimator code fails."""
    offenders = []
    seen_allowed = set()
    for d in ESTIMATOR_DIRS:
        full_dir = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full_dir)):
            if not fn.endswith(".py"):
                continue
            rel = f"{d}/{fn}"
            tree = ast.parse(
                open(os.path.join(full_dir, fn), encoding="utf-8").read())
            for call in _calls(tree):
                name, recv = _call_name(call)
                if name not in FORBIDDEN_CALLS:
                    continue
                recv_limit = FORBIDDEN_CALLS[name]
                if recv_limit is not None and recv not in recv_limit:
                    continue
                if (rel, name) in ALLOWLIST:
                    seen_allowed.add((rel, name))
                    continue
                offenders.append(
                    f"{rel}:{call.lineno}: {recv or ''}"
                    f"{'.' if recv else ''}{name}(...) — the fit-loop "
                    "driver owns this protocol step")
    assert not offenders, (
        "hand-rolled resilience protocol in estimator code (route it "
        "through ChunkedFitLoop):\n  " + "\n  ".join(offenders))
    dead = set(ALLOWLIST) - seen_allowed
    assert not dead, (
        f"allowlist entries no longer match any call: {sorted(dead)} — "
        "remove them so they can't bless future hand-rolled loops")


def test_snapshot_validation_owned_by_the_rollback_funnel():
    """Round 19 collapsed the five copy-pasted snapshot-compatibility
    blocks (kmeans/minibatch/gm centers-vs-data, ALS's two factor-state
    raises) into ``ChunkGuard.rollback(expect=...)`` →
    ``health.check_snapshot`` — estimators now DECLARE the contract via
    ``ChunkedFitLoop(snapshot_expect=...)``.  An estimator spelling the
    "stale or foreign" message itself has grown a private validation
    block back; the funnel owns that raise."""
    offenders = []
    for d in ESTIMATOR_DIRS:
        full_dir = os.path.join(REPO, d)
        for fn in sorted(os.listdir(full_dir)):
            if not fn.endswith(".py"):
                continue
            rel = f"{d}/{fn}"
            tree = ast.parse(
                open(os.path.join(full_dir, fn), encoding="utf-8").read())
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and "stale or foreign" in node.value:
                    offenders.append(
                        f"{rel}:{node.lineno}: inline 'stale or foreign' "
                        "message — declare snapshot_expect and let "
                        "ChunkGuard.rollback raise it")
    assert not offenders, (
        "hand-rolled snapshot validation in estimator code (declare it "
        "via ChunkedFitLoop(snapshot_expect=...)):\n  "
        + "\n  ".join(offenders))


def test_registry_entries_still_exist():
    """A refactor that renames a registered loop must update the registry
    — dead entries would quietly bless future unguarded loops."""
    dead = []
    for rel, fname in sorted(CHUNKED_FIT_LOOPS):
        path = os.path.join(REPO, rel)
        if not os.path.exists(path) or fname not in _functions(path):
            dead.append(f"{rel}:{fname}")
    assert not dead, f"lint registry entries no longer match code: {dead}"
