"""Worker script for the multi-process (multi-"host") integration test.

Launched by tests/test_multiprocess.py as N separate processes, each with 4
virtual CPU devices — the DCN analog of the reference's COMPSs
workers-as-local-processes CI rig (SURVEY §5): process boundaries are real,
collectives cross them via gloo, and the library's own distributed
bootstrap (`dislib_tpu.parallel.distributed.initialize`) does the wiring.

Each worker: joins the job → builds the global mesh → per-host byte-range
text ingest → KMeans fit → rank 0 writes centers + ingest checksum to
`out_path`.
"""

import json
import os
import sys


def _bootstrap(rank, nprocs, port, csv_path):
    """Shared worker bring-up: join the job, build the mesh, ingest.
    Returns (ds, x, xs_host) — xs_host from the ONE collect allgather."""
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dislib_tpu.parallel import distributed
    distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nprocs, process_id=rank)
    assert jax.process_count() == nprocs
    import numpy as np
    import dislib_tpu as ds
    ds.init((jax.device_count(), 1))        # rows axis spans the "DCN"
    # per-host SHARD-LOCAL ingest: each process parses only its row slab
    # and must neither run a collective nor materialise the full array
    # (SURVEY §4.1; round-2 VERDICT missing #3).  Instrumented: any
    # process_allgather during the load fails the job.
    from jax.experimental import multihost_utils as _mh
    calls = {"n": 0}
    real_ag = _mh.process_allgather

    def counting_ag(*a, **k):
        calls["n"] += 1
        return real_ag(*a, **k)

    _mh.process_allgather = counting_ag
    x = ds.load_txt_file(csv_path, block_size=(16, 5))
    if os.path.exists(csv_path + ".npy"):
        xn = ds.load_npy_file(csv_path + ".npy")
        xsv, _ = ds.load_svmlight_file(csv_path + ".svm", n_features=5,
                                       store_sparse=False)
    else:
        xn = xsv = None
    _mh.process_allgather = real_ag
    assert calls["n"] == 0, "ingest ran a collective — not shard-local"
    # addressable shards cover exactly this rank's contiguous row slab
    M = x._data.shape[0]
    imap = x._data.sharding.devices_indices_map(x._data.shape)
    spans = sorted(idx[0].indices(M)[:2]
                   for d, idx in imap.items()
                   if d.process_index == jax.process_index())
    slab = M // nprocs
    assert spans[0][0] == rank * slab, (spans, rank, slab)
    assert max(s[1] for s in spans) == (rank + 1) * slab, (spans, rank, slab)
    assert not x._data.is_fully_addressable
    xs_host = np.asarray(x.collect())
    if xn is not None:
        np.testing.assert_allclose(np.asarray(xn.collect()), xs_host,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(xsv.collect()), xs_host,
                                   atol=2e-6)
    return ds, x, xs_host


def crashfit_main():
    """Fault-injection mode (SURVEY §6 failure-detection row): all ranks
    run a checkpointed KMeans fit; with DSLIB_TEST_CRASH_AFTER_SAVES=k set,
    the whole job hard-dies (os._exit) right after the k-th durable
    snapshot — the recoverable mid-job host-death scenario.  Re-running the
    same command resumes from the snapshot and writes final centers."""
    rank = int(sys.argv[2])
    nprocs = int(sys.argv[3])
    port = sys.argv[4]
    csv_path = sys.argv[5]
    ck_path = sys.argv[6]
    out_path = sys.argv[7]

    import numpy as np
    from dislib_tpu.utils import checkpoint as ckm

    crash_after = int(os.environ.get("DSLIB_TEST_CRASH_AFTER_SAVES", "0"))
    if crash_after:
        real_save = ckm.FitCheckpoint.save
        state = {"n": 0}

        def dying_save(self, payload):
            real_save(self, payload)
            state["n"] += 1
            if state["n"] >= crash_after:
                os._exit(17)          # abrupt host death, snapshot durable
        ckm.FitCheckpoint.save = dying_save

    _, x, xs_host = _bootstrap(rank, nprocs, port, csv_path)
    from dislib_tpu.cluster import KMeans
    km = KMeans(n_clusters=3, init=xs_host[:3].copy(), max_iter=12, tol=0.0)
    km.fit(x, checkpoint=ckm.FitCheckpoint(ck_path, every=3))
    centers = np.asarray(km.centers_)
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"centers": centers.tolist(),
                       "n_iter": int(km.n_iter_)}, f)
    print(f"crashfit worker {rank} done", flush=True)


def main():
    if sys.argv[1] == "crashfit":
        crashfit_main()
        return
    rank = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    csv_path = sys.argv[4]
    out_path = sys.argv[5]

    import numpy as np
    ds, x, xs_host = _bootstrap(rank, nprocs, port, csv_path)
    from dislib_tpu.cluster import KMeans

    km = KMeans(n_clusters=3, init=xs_host[:3].copy(), max_iter=5, tol=0.0)
    km.fit(x)

    # tp: 2-D-sharded GEMM across the process boundary
    c = ds.matmul(x, x, transpose_b=True)
    gram_trace = float(np.trace(np.asarray(c.collect())))

    # sp analog: shard_map tsQR (all_gather(R) rides the cross-process axis)
    q, r = ds.tsqr(x)
    qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
    qr_err = float(np.abs(qh @ rh - xs_host).max())

    # ring schedule: ppermute rotation crosses the process boundary
    from dislib_tpu.neighbors import NearestNeighbors
    d_ring, _ = NearestNeighbors(n_neighbors=3, ring=True).fit(x) \
        .kneighbors(x)
    ring_d = np.asarray(d_ring.collect())

    # all-to-all: the global shuffle exchange crosses the process boundary
    # (row content must be preserved exactly, just reordered)
    from dislib_tpu.utils import shuffle
    xsh = np.asarray(shuffle(x, random_state=7).collect())
    shuffle_ok = sorted(map(tuple, xsh.tolist())) == \
        sorted(map(tuple, xs_host.tolist()))

    # sparse tier crosses the process boundary too (round 4): row-sharded
    # BCOO KMeans (shard_map segment-sum E-step + psum over the DCN axis)
    # vs the dense path on the same matrix, and the sharded sparse-fit
    # kNN stream with dense queries
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    xsp_host = xs_host.copy()
    xsp_host[xsp_host < 0.5] = 0.0
    s_arr = SparseArray.from_scipy(sp.csr_matrix(xsp_host))
    km_sp = KMeans(n_clusters=3, init=xsp_host[:3].copy(), max_iter=3,
                   tol=0.0).fit(s_arr)
    km_dn = KMeans(n_clusters=3, init=xsp_host[:3].copy(), max_iter=3,
                   tol=0.0).fit(ds.array(xsp_host, block_size=(16, 5)))
    sparse_centers_close = bool(np.allclose(km_sp.centers_, km_dn.centers_,
                                            rtol=1e-3, atol=1e-3))
    d_sp, _ = NearestNeighbors(n_neighbors=3).fit(s_arr).kneighbors(x)
    sparse_knn_sum = float(np.asarray(d_sp.collect()).sum())

    # SPMD discipline: EVERY rank runs the same collectives in the same
    # order (collect() is a process_allgather) — only the file write is
    # rank-conditional
    centers = np.asarray(km.centers_)
    checksum = float(xs_host.sum())
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"centers": centers.tolist(),
                       "checksum": checksum,
                       "shape": list(x.shape),
                       "gram_trace": gram_trace,
                       "qr_err": qr_err,
                       "shuffle_ok": bool(shuffle_ok),
                       "ring_d_sum": float(ring_d.sum()),
                       "sparse_centers_close": sparse_centers_close,
                       "sparse_knn_sum": sparse_knn_sum}, f)
    print(f"worker {rank} done", flush=True)


if __name__ == "__main__":
    main()
