"""Worker script for the multi-process (multi-"host") integration test.

Launched by tests/test_multiprocess.py as N separate processes, each with 4
virtual CPU devices — the DCN analog of the reference's COMPSs
workers-as-local-processes CI rig (SURVEY §5): process boundaries are real,
collectives cross them via gloo, and the library's own distributed
bootstrap (`dislib_tpu.parallel.distributed.initialize`) does the wiring.

Each worker: joins the job → builds the global mesh → per-host byte-range
text ingest → KMeans fit → rank 0 writes centers + ingest checksum to
`out_path`.
"""

import json
import os
import sys


def _bootstrap(rank, nprocs, port, csv_path, devs_per_proc=4, mesh=None):
    """Shared worker bring-up: join the job, build the mesh, ingest.
    Returns (ds, x, xs_host) — xs_host from the ONE collect allgather.

    ``mesh`` (rows, cols): default (global_devices, 1) puts every device
    on the cross-process rows axis; the grid mode passes (nprocs,
    devs_per_proc) — a true 2-D PROCESS mesh where each process owns one
    mesh row (rows = DCN analog, cols = intra-host)."""
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={devs_per_proc}"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dislib_tpu.parallel import distributed
    distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nprocs, process_id=rank)
    assert jax.process_count() == nprocs
    import numpy as np
    import dislib_tpu as ds
    ds.init(mesh or (jax.device_count(), 1))  # rows axis spans the "DCN"
    # per-host SHARD-LOCAL ingest: each process parses only its row slab
    # and must neither run a collective nor materialise the full array
    # (SURVEY §4.1; round-2 VERDICT missing #3).  Instrumented: any
    # process_allgather during the load fails the job.
    from jax.experimental import multihost_utils as _mh
    calls = {"n": 0}
    real_ag = _mh.process_allgather

    def counting_ag(*a, **k):
        calls["n"] += 1
        return real_ag(*a, **k)

    _mh.process_allgather = counting_ag
    x = ds.load_txt_file(csv_path, block_size=(16, 5))
    if os.path.exists(csv_path + ".npy"):
        xn = ds.load_npy_file(csv_path + ".npy")
        xsv, _ = ds.load_svmlight_file(csv_path + ".svm", n_features=5,
                                       store_sparse=False)
    else:
        xn = xsv = None
    _mh.process_allgather = real_ag
    assert calls["n"] == 0, "ingest ran a collective — not shard-local"
    # addressable shards cover exactly this rank's contiguous row slab
    M = x._data.shape[0]
    imap = x._data.sharding.devices_indices_map(x._data.shape)
    spans = sorted(idx[0].indices(M)[:2]
                   for d, idx in imap.items()
                   if d.process_index == jax.process_index())
    slab = M // nprocs
    assert spans[0][0] == rank * slab, (spans, rank, slab)
    assert max(s[1] for s in spans) == (rank + 1) * slab, (spans, rank, slab)
    assert not x._data.is_fully_addressable
    xs_host = np.asarray(x.collect())
    if xn is not None:
        np.testing.assert_allclose(np.asarray(xn.collect()), xs_host,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(xsv.collect()), xs_host,
                                   atol=2e-6)
    return ds, x, xs_host


def _arm_crash_saves():
    """DSLIB_TEST_CRASH_AFTER_SAVES=k: the whole job hard-dies (os._exit)
    right after the k-th durable snapshot — the recoverable mid-job
    host-death scenario (SURVEY §6 failure-detection row)."""
    from dislib_tpu.utils import checkpoint as ckm
    crash_after = int(os.environ.get("DSLIB_TEST_CRASH_AFTER_SAVES", "0"))
    if crash_after:
        real_save = ckm.FitCheckpoint.save
        state = {"n": 0}

        def dying_save(self, payload):
            real_save(self, payload)
            state["n"] += 1
            if state["n"] >= crash_after:
                os._exit(17)          # abrupt host death, snapshot durable
        ckm.FitCheckpoint.save = dying_save


def crashfit_main():
    """Fault-injection mode: all ranks run a checkpointed KMeans fit with
    optional crash-after-k-saves; re-running the same command resumes from
    the snapshot and writes final centers."""
    rank = int(sys.argv[2])
    nprocs = int(sys.argv[3])
    port = sys.argv[4]
    csv_path = sys.argv[5]
    ck_path = sys.argv[6]
    out_path = sys.argv[7]

    import numpy as np
    from dislib_tpu.utils import checkpoint as ckm

    _arm_crash_saves()
    _, x, xs_host = _bootstrap(rank, nprocs, port, csv_path)
    km = _ck_fit(x, xs_host, ck_path)
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"centers": np.asarray(km.centers_).tolist(),
                       "n_iter": int(km.n_iter_)}, f)
    print(f"crashfit worker {rank} done", flush=True)


def _ck_fit(x, xs_host, ck_path):
    """The one checkpointed-fit recipe both fault-injection modes run:
    12 Lloyd iterations, init = first 3 rows, snapshot every 3 — cadence
    changes apply to crashfit and grid together."""
    from dislib_tpu.cluster import KMeans
    from dislib_tpu.utils import checkpoint as ckm
    km = KMeans(n_clusters=3, init=xs_host[:3].copy(), max_iter=12, tol=0.0)
    return km.fit(x, checkpoint=ckm.FitCheckpoint(ck_path, every=3))


def grid_main():
    """Round-5 4-process 2-D PROCESS-mesh mode (SURVEY §3.7 cross-slice /
    hierarchical row): mesh (nprocs, 2) with 2 virtual devices per
    process — every process owns exactly one mesh ROW, so the rows axis
    is a pure DCN analog (all row-axis collectives cross process
    boundaries) while cols is intra-host.  Runs: shard-local ingest,
    checkpointed KMeans fit (with optional crash-after-k-saves), a global
    all_to_all shuffle across the boundary, and collect."""
    rank = int(sys.argv[2])
    nprocs = int(sys.argv[3])
    port = sys.argv[4]
    csv_path = sys.argv[5]
    ck_path = sys.argv[6]
    out_path = sys.argv[7]

    import numpy as np

    _arm_crash_saves()
    ds, x, xs_host = _bootstrap(rank, nprocs, port, csv_path,
                                devs_per_proc=2, mesh=(nprocs, 2))
    import jax
    from dislib_tpu.parallel import mesh as _mesh
    m = _mesh.get_mesh()
    assert dict(zip(m.axis_names, m.devices.shape)) == \
        {"rows": nprocs, "cols": 2}
    # one mesh row == one process (the 2-D process-mesh contract)
    my_rows = {np.argwhere(m.devices == d)[0][0]
               for d in jax.local_devices()}
    assert len(my_rows) == 1, f"process spans mesh rows {my_rows}"

    km = _ck_fit(x, xs_host, ck_path)

    # 2-D collective mix: the Gram GEMM partitions over BOTH axes — cols
    # collectives stay intra-process, the rows reduction crosses all 4
    gram_trace = float(np.trace(np.asarray(
        ds.matmul(x, x, transpose_b=True).collect())))
    assert abs(gram_trace - float((xs_host * xs_host).sum())) \
        <= 1e-4 * max(1.0, abs(gram_trace)), f"rank {rank}: gram trace"

    from dislib_tpu.utils import shuffle
    xsh = np.asarray(shuffle(x, random_state=7).collect())
    # asserted on EVERY rank (nonzero exit), not just recorded by rank 0:
    # a gloo bug corrupting only a non-zero rank's gather must fail the job
    shuffle_ok = sorted(map(tuple, xsh.tolist())) == \
        sorted(map(tuple, xs_host.tolist()))
    assert shuffle_ok, f"rank {rank}: shuffle lost/changed rows"

    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"centers": np.asarray(km.centers_).tolist(),
                       "n_iter": int(km.n_iter_),
                       "checksum": float(xs_host.sum()),
                       "shape": list(x.shape),
                       "shuffle_ok": bool(shuffle_ok)}, f)
    print(f"grid worker {rank} done", flush=True)


def main():
    if sys.argv[1] == "crashfit":
        crashfit_main()
        return
    if sys.argv[1] == "grid":
        grid_main()
        return
    rank = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    csv_path = sys.argv[4]
    out_path = sys.argv[5]

    import numpy as np
    ds, x, xs_host = _bootstrap(rank, nprocs, port, csv_path)
    from dislib_tpu.cluster import KMeans

    km = KMeans(n_clusters=3, init=xs_host[:3].copy(), max_iter=5, tol=0.0)
    km.fit(x)

    # tp: 2-D-sharded GEMM across the process boundary
    c = ds.matmul(x, x, transpose_b=True)
    gram_trace = float(np.trace(np.asarray(c.collect())))

    # sp analog: shard_map tsQR (all_gather(R) rides the cross-process axis)
    q, r = ds.tsqr(x)
    qh, rh = np.asarray(q.collect()), np.asarray(r.collect())
    qr_err = float(np.abs(qh @ rh - xs_host).max())

    # ring schedule: ppermute rotation crosses the process boundary
    from dislib_tpu.neighbors import NearestNeighbors
    d_ring, _ = NearestNeighbors(n_neighbors=3, ring=True).fit(x) \
        .kneighbors(x)
    ring_d = np.asarray(d_ring.collect())

    # all-to-all: the global shuffle exchange crosses the process boundary
    # (row content must be preserved exactly, just reordered)
    from dislib_tpu.utils import shuffle
    xsh = np.asarray(shuffle(x, random_state=7).collect())
    shuffle_ok = sorted(map(tuple, xsh.tolist())) == \
        sorted(map(tuple, xs_host.tolist()))

    # sparse tier crosses the process boundary too (round 4): row-sharded
    # BCOO KMeans (shard_map segment-sum E-step + psum over the DCN axis)
    # vs the dense path on the same matrix, and the sharded sparse-fit
    # kNN stream with dense queries
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    xsp_host = xs_host.copy()
    xsp_host[xsp_host < 0.5] = 0.0
    s_arr = SparseArray.from_scipy(sp.csr_matrix(xsp_host))
    km_sp = KMeans(n_clusters=3, init=xsp_host[:3].copy(), max_iter=3,
                   tol=0.0).fit(s_arr)
    km_dn = KMeans(n_clusters=3, init=xsp_host[:3].copy(), max_iter=3,
                   tol=0.0).fit(ds.array(xsp_host, block_size=(16, 5)))
    sparse_centers_close = bool(np.allclose(km_sp.centers_, km_dn.centers_,
                                            rtol=1e-3, atol=1e-3))
    d_sp, _ = NearestNeighbors(n_neighbors=3).fit(s_arr).kneighbors(x)
    sparse_knn_sum = float(np.asarray(d_sp.collect()).sum())

    # SPMD discipline: EVERY rank runs the same collectives in the same
    # order (collect() is a process_allgather) — only the file write is
    # rank-conditional
    centers = np.asarray(km.centers_)
    checksum = float(xs_host.sum())
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"centers": centers.tolist(),
                       "checksum": checksum,
                       "shape": list(x.shape),
                       "gram_trace": gram_trace,
                       "qr_err": qr_err,
                       "shuffle_ok": bool(shuffle_ok),
                       "ring_d_sum": float(ring_d.sum()),
                       "sparse_centers_close": sparse_centers_close,
                       "sparse_knn_sum": sparse_knn_sum}, f)
    print(f"worker {rank} done", flush=True)


if __name__ == "__main__":
    main()
