"""Chaos MATRIX (round-12 satellite): every chunked estimator × every
numerical/liveness fault injector, including the tier-targeted
``FaultAtTier`` that defeats exactly N escalation-ladder tiers.  The one
invariant every cell must satisfy is the driver's contract: the fit
either HEALS (finite model) or raises a TYPED diagnostic — never a hang,
never a silently corrupt model.

The full matrix is `slow` (run via ``tools/chaos_soak.sh --matrix``,
which appends the machine-readable ``CHAOS_MATRIX_SUMMARY`` line — per
cell verdicts + the process resilience counters — to the local bench
JSONL).  A 2-estimator smoke subset rides tier-1, shapes mirroring
``tests/test_health.py`` so its kernels are suite-wide cache hits.

``DSLIB_MATRIX_SEED`` (default 0) seeds the data draws, so a failing
cell reproduces from the printed seed + cell name alone.  Cells that
shrink the mesh (the elastic tier) re-init the default mesh afterwards.
"""

import json
import os
import warnings

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import (DBSCAN, Daura, GaussianMixture, KMeans,
                                MiniBatchKMeans)
from dislib_tpu.classification import CascadeSVM
from dislib_tpu.recommendation import ALS
from dislib_tpu.runtime import (NumericalDivergence, Preempted,
                                WatchdogTimeout, clear_preemption)
from dislib_tpu.trees import RandomForestClassifier
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils import profiling as prof
from dislib_tpu.utils.checkpoint import SnapshotCorrupt

TYPED = (Preempted, NumericalDivergence, WatchdogTimeout, SnapshotCorrupt)


def _blobs(rng, n=198, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


def _sparse(x_np):
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    m = x_np.copy()
    m[m < np.median(m)] = 0.0
    return SparseArray.from_scipy(sp.csr_matrix(m))


# name -> rng -> (fit(checkpoint, health) -> estimator, model_of)
def _estimators():
    def kmeans(rng, sparse=False):
        x_np = _blobs(rng)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        x = _sparse(x_np) if sparse else ds.array(x_np)
        kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
        return (lambda ck, pol: KMeans(**kw).fit(x, checkpoint=ck,
                                                 health=pol),
                lambda e: e.centers_)

    def minibatch(rng):
        x = ds.array(_blobs(rng, n=192))
        return (lambda ck, pol: MiniBatchKMeans(
                    n_clusters=3, batch_size=64, random_state=0).fit(
                        x, checkpoint=ck, health=pol),
                lambda e: e.centers_)

    def gmm(rng):
        x = ds.array(_blobs(rng, n=150, d=3, k=2))
        kw = dict(n_components=2, max_iter=12, tol=0.0, random_state=0)
        return (lambda ck, pol: GaussianMixture(**kw).fit(x, checkpoint=ck,
                                                          health=pol),
                lambda e: e.means_)

    def als(rng, sparse=False):
        u, v = rng.rand(30, 4), rng.rand(20, 4)
        r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)
        x = _sparse(r) if sparse else ds.array(r)
        kw = dict(n_f=4, max_iter=8, tol=1e-9, random_state=0)
        return (lambda ck, pol: ALS(**kw).fit(x, checkpoint=ck, health=pol),
                lambda e: e.users_)

    def csvm(rng):
        n = 120
        xh = np.vstack([rng.randn(n // 2, 4) - 2,
                        rng.randn(n // 2, 4) + 2]).astype(np.float32)
        yh = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
        sh = rng.permutation(n)
        x, y = ds.array(xh[sh]), ds.array(yh[sh].reshape(-1, 1))
        kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
                  check_convergence=False, max_iter=4)
        return (lambda ck, pol: CascadeSVM(**kw).fit(x, y, checkpoint=ck,
                                                     health=pol),
                lambda e: e._sv_alpha)

    def forest(rng):
        n, k = 240, 3
        centers = rng.rand(k, 6) * 8
        xh = np.vstack([centers[i] + 0.4 * rng.randn(n // k, 6)
                        for i in range(k)]).astype(np.float32)
        yh = np.repeat(np.arange(k), n // k).astype(np.float32)
        p = rng.permutation(n)
        x, y = ds.array(xh[p]), ds.array(yh[p].reshape(-1, 1))
        kw = dict(n_estimators=4, max_depth=6, random_state=7)
        return (lambda ck, pol: RandomForestClassifier(**kw).fit(
                    x, y, checkpoint=ck, health=pol),
                lambda e: np.asarray(e.predict(x).collect()))

    def ivf(rng):
        # round-20 satellite: the retrieval tier rides the matrix — the
        # coarse-quantizer build is a chunked KMeans fit (so every
        # injector lands mid-BUILD), and the model readout is a SEARCH,
        # which must auto-rebind onto whatever mesh the elastic rung
        # left behind (capacity shrink mid-fit/mid-search heals)
        from dislib_tpu.retrieval import IVFIndex
        x_np = _blobs(rng)

        def fit(ck, pol):
            ix = IVFIndex(n_lists=3, nprobe=3, kmeans_max_iter=12,
                          random_state=0)
            return ix.fit(ds.array(x_np), checkpoint=ck, health=pol)

        def readout(e):
            # restore the full mesh FIRST: when the elastic rung shrank
            # the build, this search runs on a mesh the striped buffers
            # were not laid out for — it must transparently re-stripe
            # (never refuse, never tear)
            ds.init()
            dist, _ = e.search(x_np[:8], k=3)
            return np.asarray(dist.collect())

        return fit, readout

    def dbscan(rng):
        x = ds.array(rng.rand(60, 3).astype(np.float32))
        return (lambda ck, pol: DBSCAN(eps=0.5, min_samples=3).fit(
                    x, checkpoint=ck, health=pol),
                lambda e: e.labels_)

    def daura(rng):
        # cutoff tight enough that extraction spans several chunks —
        # a single-chunk fit would end before at_chunk=2 arms and every
        # daura cell would pass vacuously
        x = ds.array(rng.rand(40, 6).astype(np.float32))
        return (lambda ck, pol: Daura(cutoff=0.35).fit(x, checkpoint=ck,
                                                       health=pol),
                lambda e: e.labels_)

    return {
        "kmeans": kmeans,
        "kmeans_sparse": lambda rng: kmeans(rng, sparse=True),
        "minibatch_kmeans": minibatch,
        "gmm": gmm,
        "als": als,
        "als_sparse": lambda rng: als(rng, sparse=True),
        "csvm": csvm,
        "forest": forest,
        "dbscan": dbscan,
        "daura": daura,
        "ivf": ivf,
    }


INJECTORS = {
    "nan": lambda: faults.NaNAtChunk(at_chunk=2),
    "ramp": lambda: faults.DivergenceRamp(at_chunk=2, repeat=False,
                                          grow_limit=1e3),
    "hang": lambda: faults.HangAtChunk(at_chunk=2, hang_s=0.3,
                                       deadline_s=0.05, times=1),
    "trip": lambda: faults.TripAtChunk(at_chunk=2),
    # defeats retry; healed by policy remediation
    "tier1": lambda: faults.FaultAtTier(tiers=1, at_chunk=2),
    # defeats retry AND remediation; healed only by the elastic
    # mesh-shrink tier — round 16: EVERY chunked estimator carries the
    # rebind hook now, so no tier2 cell is allowed to type
    "tier2": lambda: faults.FaultAtTier(tiers=2, at_chunk=2,
                                        max_restarts=3, elastic_attempts=1),
    # defeats the whole ladder; must type, never hang
    "tier3": lambda: faults.FaultAtTier(tiers=3, at_chunk=2,
                                        max_restarts=2),
}


def _run_cell(est_name, inj_name, tmp_path, seed):
    """One matrix cell.  Returns its verdict record; raises on a contract
    violation (silent non-finite model)."""
    ds.init()                   # fresh default mesh (elastic cells shrink it)
    clear_preemption()
    fit, model_of = _estimators()[est_name](np.random.RandomState(seed))
    pol = INJECTORS[inj_name]()
    ck = FitCheckpoint(str(tmp_path / f"{est_name}-{inj_name}.npz"), every=2)
    cell = {"cell": f"{est_name}x{inj_name}"}
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            est = fit(ck, pol)
    except TYPED as e:
        cell["outcome"] = f"typed:{type(e).__name__}"
    else:
        model = np.asarray(model_of(est), np.float64)
        assert np.isfinite(model).all(), \
            f"{cell['cell']} seed {seed}: SILENT NON-FINITE MODEL"
        cell["outcome"] = "healed"
        info = getattr(est, "fit_info_", None)
        if info:
            cell["rollbacks"] = info["rollbacks"]
            cell["mesh_shrinks"] = info["mesh_shrinks"]
            cell["mesh_grows"] = info.get("mesh_grows", 0)
    finally:
        clear_preemption()
        ds.init()
    cell["fired"] = int(getattr(pol, "fired", getattr(pol, "stalls", 0)))
    return cell


@pytest.mark.slow
def test_chaos_matrix_full(tmp_path, monkeypatch):
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    seed = int(os.environ.get("DSLIB_MATRIX_SEED", "0"))
    cells = {}
    healed = typed = 0
    for est_name in _estimators():
        for inj_name in INJECTORS:
            cell = _run_cell(est_name, inj_name, tmp_path, seed)
            cells[cell.pop("cell")] = cell
            if cell["outcome"] == "healed":
                healed += 1
            else:
                typed += 1
    summary = {"metric": "chaos_matrix", "seed": seed,
               "healed": healed, "typed": typed,
               "cells": cells,
               "resilience": prof.resilience_counters()}
    print("CHAOS_MATRIX_SUMMARY " + json.dumps(summary))
    # heal-or-type on EVERY cell is asserted inside _run_cell; the
    # ladder's top tier must actually have been exercised somewhere
    assert healed + typed == len(_estimators()) * len(INJECTORS)
    assert any(c.get("mesh_shrinks") for c in cells.values()), \
        "no cell escalated to the elastic mesh-shrink tier"
    # round 16: every chunked estimator carries a rebind hook, so the
    # elastic rung HEALS everywhere — a typed tier2 cell is a regression
    bad = [k for k, c in cells.items()
           if k.endswith("xtier2") and c["outcome"] != "healed"]
    assert not bad, f"elastic rung failed to heal: {bad}"


def test_chaos_matrix_smoke(tmp_path, monkeypatch):
    """Tier-1 subset: 2 estimators (the reference chunked fit and the
    zero-bespoke-resilience streaming one) × {carry poison, ladder
    escalation} — the contract stays pinned without the slow sweep."""
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")
    seed = int(os.environ.get("DSLIB_MATRIX_SEED", "0"))
    for est_name, inj_name in (("kmeans", "nan"), ("kmeans", "tier1"),
                               ("minibatch_kmeans", "nan"),
                               ("minibatch_kmeans", "hang")):
        cell = _run_cell(est_name, inj_name, tmp_path, seed)
        assert cell["outcome"] == "healed", cell
        assert cell["fired"] >= 1, f"{cell}: fault was never injected"
