"""ALS tests (reference: tests/test_als.py — SURVEY.md §5 oracle pattern:
NumPy closed-form oracle + invariants on small ratings matrices)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.recommendation import ALS


def _ratings(rng, m=40, n=25, n_f=3, density=0.4):
    """Low-rank ground truth with observed mask; ratings in [1, 5]."""
    u = rng.rand(m, n_f)
    v = rng.rand(n, n_f)
    full = u @ v.T
    full = 1.0 + 4.0 * (full - full.min()) / (full.max() - full.min())
    mask = rng.rand(m, n) < density
    # every row/col needs at least one rating
    mask[np.arange(m), rng.randint(0, n, m)] = True
    mask[rng.randint(0, m, n), np.arange(n)] = True
    return (full * mask).astype(np.float32), full.astype(np.float32), mask


def _numpy_als_iter(r, mask, u, v, lam):
    """Oracle: one full ALS sweep, per-row normal equations (Zhou et al.)."""
    f = v.shape[1]
    for (rr, mm, src, dst) in ((r, mask, v, u), (r.T, mask.T, u, None)):
        out = np.zeros((rr.shape[0], f), rr.dtype)
        for i in range(rr.shape[0]):
            obs = mm[i].astype(bool)
            vo = src[obs]
            a = vo.T @ vo + lam * max(obs.sum(), 1) * np.eye(f, dtype=rr.dtype)
            out[i] = np.linalg.solve(a, vo.T @ rr[i, obs])
        if dst is None:
            v = out
        else:
            u = out
    return u, v


class TestALS:
    def test_reconstructs_low_rank(self, rng):
        r, full, mask = _ratings(rng)
        als = ALS(n_f=3, lambda_=0.01, tol=1e-6, max_iter=100,
                  random_state=0).fit(ds.array(r))
        pred = als.users_ @ als.items_.T
        err = np.abs((pred - r)[mask]).mean()
        assert err < 0.1
        assert als.converged_
        assert als.rmse_ < 0.1

    def test_matches_numpy_oracle_one_sweep(self, rng):
        """One device sweep == the per-row normal-equation oracle, given the
        same starting factors (wired through init seeding equivalence is not
        possible, so run from the device's own first-sweep factors)."""
        r, _, mask = _ratings(rng, m=20, n=12)
        als = ALS(n_f=2, lambda_=0.1, tol=-1.0, max_iter=1,
                  random_state=0).fit(ds.array(r))
        # feed the device result through ONE oracle sweep: a fixed point of
        # the oracle must (approximately) be reproduced after convergence
        als2 = ALS(n_f=2, lambda_=0.1, tol=1e-7, max_iter=200,
                   random_state=0).fit(ds.array(r))
        u2, v2 = _numpy_als_iter(r, mask, als2.users_, als2.items_, 0.1)
        np.testing.assert_allclose(u2, als2.users_, rtol=1e-2, atol=1e-2)
        np.testing.assert_allclose(v2, als2.items_, rtol=1e-2, atol=1e-2)
        del als

    def test_heldout_test_convergence(self, rng):
        r, full, mask = _ratings(rng)
        test = np.where(~mask, full, 0.0).astype(np.float32)
        test[test != 0] *= (np.random.RandomState(1).rand((test != 0).sum()) < 0.3)
        als = ALS(n_f=3, lambda_=0.02, tol=1e-5, max_iter=80,
                  random_state=0).fit(ds.array(r), test=test)
        assert np.isfinite(als.rmse_)
        assert als.n_iter_ <= 80

    def test_predict_user(self, rng):
        r, _, _ = _ratings(rng, m=15, n=10)
        als = ALS(n_f=2, max_iter=20, random_state=0).fit(ds.array(r))
        p = als.predict_user(3)
        assert p.shape == (10,)
        np.testing.assert_allclose(p, als.users_[3] @ als.items_.T, rtol=1e-6)
        with pytest.raises(IndexError):
            als.predict_user(15)

    def test_irregular_blocks_and_mesh(self, rng):
        """Irregular logical shape (prime dims) exercises padding masks."""
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        r, _, mask = _ratings(rng, m=37, n=23)
        ds.init((4, 2))
        als = ALS(n_f=2, lambda_=0.05, max_iter=40, random_state=0)
        als.fit(ds.array(r, block_size=(10, 10)))
        assert als.users_.shape == (37, 2)
        assert als.items_.shape == (23, 2)
        pred = als.users_ @ als.items_.T
        assert np.abs((pred - r)[mask]).mean() < 0.5

    def test_save_load_roundtrip(self, rng, tmp_path):
        r, _, _ = _ratings(rng, m=15, n=10)
        als = ALS(n_f=2, max_iter=10, random_state=0).fit(ds.array(r))
        path = str(tmp_path / "als.json")
        ds.save_model(als, path)
        loaded = ds.load_model(path)
        np.testing.assert_allclose(loaded.users_, als.users_)
        np.testing.assert_allclose(loaded.items_, als.items_)


class TestSparseALS:
    """True sparse ALS path: segment-sum normal equations over triplets."""

    def _ratings(self):
        rng = np.random.RandomState(11)
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        full = u @ v.T
        mask = rng.rand(30, 20) < 0.4
        return np.where(mask, full, 0.0).astype(np.float32)

    def test_sparse_fit_reconstructs(self):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.recommendation import ALS

        r = self._ratings()
        xs = SparseArray.from_scipy(sp.csr_matrix(r))
        als = ALS(n_f=4, lambda_=0.002, max_iter=40, tol=1e-7, random_state=0)
        als.fit(xs)
        assert als.users_.shape == (30, 4)
        assert als.items_.shape == (20, 4)
        assert als.rmse_ < 0.05                       # low-rank data: near-exact
        assert len(als.history_) == als.n_iter_
        pred = als.users_ @ als.items_.T
        obs = r != 0
        np.testing.assert_allclose(pred[obs], r[obs], atol=0.2)
        # predict_user parity
        np.testing.assert_allclose(als.predict_user(3), pred[3], rtol=1e-6)

    def test_sparse_matches_dense_quality(self):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.recommendation import ALS

        r = self._ratings()
        xs = SparseArray.from_scipy(sp.csr_matrix(r))
        xd = ds.array(r, block_size=(16, 20))
        a_sp = ALS(n_f=4, max_iter=25, tol=1e-6, random_state=0).fit(xs)
        a_d = ALS(n_f=4, max_iter=25, tol=1e-6, random_state=0).fit(xd)
        # different init layouts → compare converged quality, not factors
        assert a_sp.rmse_ < max(2 * a_d.rmse_, 0.05)

    def test_sparse_checkpoint_resume(self, tmp_path):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.recommendation import ALS
        from dislib_tpu.utils.checkpoint import FitCheckpoint

        r = self._ratings()
        xs = SparseArray.from_scipy(sp.csr_matrix(r))
        p = str(tmp_path / "als.npz")
        a1 = ALS(n_f=4, max_iter=12, tol=0.0, random_state=0)
        a1.fit(xs, checkpoint=FitCheckpoint(p, every=5))
        a2 = ALS(n_f=4, max_iter=12, tol=0.0, random_state=0).fit(xs)
        np.testing.assert_allclose(a1.users_, a2.users_, rtol=2e-2, atol=2e-3)
        assert a1.n_iter_ == a2.n_iter_ == 12
