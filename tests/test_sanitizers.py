"""Sanitizer modes (SURVEY.md §6 race-detection row; VERDICT r1 #9).

The library's collective-correctness sanitizer — `shard_map`
replication checking (`check_vma=True`) — is permanently ON in every
shard_map (tsqr, ADMM, sparse KMeans), so the whole suite exercises it.
This file adds the two CI sanitizer modes the reference's runtime-level
checks map to:

- `jax.debug_nans`: any NaN materialising in a fit raises immediately
  (the analog of the runtime's failed-task surfacing);
- `jax.disable_jit`: the same device code runs op-by-op in eager mode —
  catches tracing-only assumptions (shapes, dtypes, Python control flow).

Kept to small shapes so the no-jit paths stay fast.
"""

import numpy as np
import jax
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans, GaussianMixture
from dislib_tpu.optimization import ADMM


@pytest.fixture
def small(rng):
    return ds.array(rng.rand(48, 4).astype(np.float32), block_size=(8, 4))


class TestDebugNans:
    def test_kmeans_fit_clean(self, rng, small):
        with jax.debug_nans(True):
            km = KMeans(n_clusters=2, random_state=0, max_iter=3).fit(small)
        assert np.isfinite(km.centers_).all()

    def test_gmm_fit_clean(self, rng, small):
        with jax.debug_nans(True):
            gm = GaussianMixture(n_components=2, max_iter=3,
                                 random_state=0).fit(small)
        assert np.isfinite(gm.lower_bound_)

    def test_nan_input_is_caught(self, rng):
        bad = rng.rand(16, 3).astype(np.float32)
        bad[3, 1] = np.nan
        with jax.debug_nans(True):
            with pytest.raises(Exception, match="[Nn]a[Nn]"):
                KMeans(n_clusters=2, random_state=0, max_iter=2).fit(
                    ds.array(bad))

    def test_tsqr_clean(self, rng):
        x = ds.array(rng.rand(64, 6).astype(np.float32))
        with jax.debug_nans(True):
            q, r = ds.tsqr(x)
            assert np.isfinite(q.collect()).all()


class TestNoJit:
    def test_kmeans_no_jit_matches_jit(self, rng, small):
        init = np.asarray(small.collect()[:2])
        jit_km = KMeans(n_clusters=2, init=init, max_iter=3, tol=0.0).fit(small)
        with jax.disable_jit():
            eager_km = KMeans(n_clusters=2, init=init, max_iter=3,
                              tol=0.0).fit(small)
        np.testing.assert_allclose(eager_km.centers_, jit_km.centers_,
                                   rtol=1e-5, atol=1e-6)

    def test_admm_no_jit(self, rng):
        x = rng.rand(32, 3).astype(np.float32)
        y = (x @ np.ones(3, np.float32))[:, None]
        with jax.disable_jit():
            est = ADMM(max_iter=5).fit(ds.array(x), ds.array(y))
        assert len(est.history_) == est.n_iter_ == 5

    def test_matmul_no_jit(self, rng):
        a, b = rng.rand(9, 5), rng.rand(5, 7)
        with jax.disable_jit():
            got = ds.matmul(ds.array(a), ds.array(b)).collect()
        np.testing.assert_allclose(got, a @ b, rtol=1e-4)


class TestRingSanitizers:
    """The ppermute ring paths under the same two CI sanitizer modes."""

    def test_ring_knn_debug_nans(self, rng):
        x = ds.array(rng.rand(40, 4).astype(np.float32), block_size=(8, 4))
        from dislib_tpu.neighbors import NearestNeighbors
        with jax.debug_nans(True):
            d, i = NearestNeighbors(n_neighbors=3, ring=True).fit(x) \
                .kneighbors(x)
        assert np.isfinite(np.asarray(d.collect())).all()

    def test_ring_dbscan_no_jit(self, rng, monkeypatch):
        from dislib_tpu.cluster import dbscan as dbm
        # ring size 2 (not the full 8-virtual-device mesh): under
        # `disable_jit` every ring step is hundreds of EAGER multi-device
        # collective dispatches, and the 8-shard variant of this test
        # alone cost ~128 s of the 870 s tier-1 budget (round-8
        # measurement).  What this sanitizer checks — eager/traced
        # semantic equivalence of the ring passes — is hop-count
        # independent; the full-mesh multi-hop ring under jit is covered
        # by test_ring.py::test_ring_dbscan_matches_dense.  2 shards keep
        # the rotation + wraparound + cross-shard propagation paths live
        # at ~1/5 of the wall clock (and degrade gracefully to the old
        # behavior on single-device rigs).
        p = min(2, len(jax.devices()))
        ds.init((p, 1), devices=jax.devices()[:p])
        pts = np.vstack([rng.randn(12, 3) * 0.05,
                         rng.randn(12, 3) * 0.05 + 3]).astype(np.float32)
        x = ds.array(pts, block_size=(8, 3))
        ref = dbm.DBSCAN(eps=0.5, min_samples=3).fit(x).labels_  # dense path
        monkeypatch.setattr(dbm, "_RING", True)
        with jax.disable_jit():
            got = dbm.DBSCAN(eps=0.5, min_samples=3).fit(x).labels_
        np.testing.assert_array_equal(got, ref)


class TestRound3Paths:
    """Sanitizer coverage for the round-3 additions: sparse kNN streaming,
    the distributed full-QR assembly, and the forest async score kernel."""

    def test_sparse_knn_debug_nans(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        from dislib_tpu.neighbors import NearestNeighbors
        dense = rng.rand(40, 6).astype(np.float32)
        dense[dense < 0.6] = 0.0
        xs = SparseArray.from_scipy(sp.csr_matrix(dense))
        with jax.debug_nans(True):
            d, i = NearestNeighbors(n_neighbors=3).fit(xs).kneighbors(xs)
            assert np.isfinite(np.asarray(d.collect())).all()

    def test_full_qr_no_jit_matches_jit(self, rng, monkeypatch):
        import importlib
        qr_mod = importlib.import_module("dislib_tpu.math.qr")
        monkeypatch.setattr(qr_mod, "_PANEL", 8)
        x = rng.rand(64, 16).astype(np.float32)
        q1, r1 = ds.qr(ds.array(x), mode="full")
        with jax.disable_jit():
            q2, r2 = ds.qr(ds.array(x), mode="full")
        np.testing.assert_allclose(np.asarray(q1.collect()),
                                   np.asarray(q2.collect()),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(r1.collect()),
                                   np.asarray(r2.collect()),
                                   rtol=1e-4, atol=1e-4)

    def test_forest_async_score_debug_nans(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        x = rng.rand(60, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32)[:, None]
        xa, ya = ds.array(x), ds.array(y)
        with jax.debug_nans(True):
            est = RandomForestClassifier(n_estimators=3, random_state=0)
            st = est._fit_async(xa, ya)
            assert np.isfinite(float(est._score_async(st, xa, ya)))
