"""Test rig: 8 virtual CPU devices — the analog of the reference's
"COMPSs workers as local processes" CI trick (SURVEY.md §5).

The suite runs on the CPU platform with 8 virtual devices so every sharding /
collective path executes for real.  Set ``DSLIB_TEST_TPU=1`` to run the same
tests unmodified on the real TPU backend instead (SURVEY §5 implication (c)).

XLA_FLAGS must be set before the first backend initialisation; the platform
override must happen before any jax computation (this file is imported by
pytest ahead of all test modules).
"""

import os

_ON_TPU = os.environ.get("DSLIB_TEST_TPU") == "1"

if not _ON_TPU:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test starts from the default (n_devices, 1) mesh unless it sets its own."""
    import dislib_tpu as ds
    ds.init()
    yield


# Every jitted executable holds LLVM JIT code pages, and one long pytest
# process compiles ~thousands of programs; on this rig the suite's memory
# MAP count reaches the kernel's vm.max_map_count ceiling (default 65530)
# around the late test files, at which point an mmap failure inside a
# compile SEGFAULTS the whole run (observed 2026-08-04 at test_trees,
# reproducible at the PR-4 HEAD — an environment regression, not a code
# one).  Relief valve: when the process's map count crosses the
# threshold, drop jax's executable caches — the affected late files
# recompile their own programs (they share little with earlier files),
# which costs seconds, not the suite.
_MAP_RELIEF_THRESHOLD = int(os.environ.get("DSLIB_TEST_MAP_RELIEF", "45000"))


@pytest.fixture(autouse=True, scope="module")
def _jit_map_pressure_relief():
    try:
        n_maps = sum(1 for _ in open("/proc/self/maps"))
    except OSError:          # non-Linux: no ceiling to manage
        n_maps = 0
    if _MAP_RELIEF_THRESHOLD and n_maps > _MAP_RELIEF_THRESHOLD:
        import warnings
        warnings.warn(
            f"conftest: {n_maps} memory maps — clearing jax caches to stay "
            "under vm.max_map_count (see conftest note)", ResourceWarning)
        jax.clear_caches()
    yield


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def skip_unless_devices(n):
    """Skip on rigs with fewer than n devices — the single-chip TPU suite
    run can't host the multi-device mesh-shape tests."""
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices (single-chip TPU suite run)")
