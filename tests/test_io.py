"""I/O loader tests (reference: data loaders, SURVEY.md §3.1)."""

import os

import numpy as np
import pytest

import dislib_tpu as ds


class TestTxt:
    def test_roundtrip(self, rng, tmp_path):
        x = rng.rand(12, 5)
        path = os.path.join(tmp_path, "x.csv")
        np.savetxt(path, x, delimiter=",")
        a = ds.load_txt_file(path, block_size=(4, 5))
        np.testing.assert_allclose(a.collect(), x.astype(np.float32), rtol=1e-6)
        out = os.path.join(tmp_path, "y.csv")
        ds.save_txt(a, out)
        np.testing.assert_allclose(np.loadtxt(out, delimiter=","), x, rtol=1e-5)

    def test_save_per_block(self, rng, tmp_path):
        x = rng.rand(10, 3)
        a = ds.array(x, block_size=(4, 3))
        out = os.path.join(tmp_path, "blocks")
        ds.save_txt(a, out, merge_rows=False)
        parts = [np.loadtxt(os.path.join(out, str(i)), delimiter=",", ndmin=2)
                 for i in range(3)]
        np.testing.assert_allclose(np.vstack(parts), x, rtol=1e-5)


class TestNpy:
    def test_load(self, rng, tmp_path):
        x = rng.rand(8, 6).astype(np.float32)
        path = os.path.join(tmp_path, "x.npy")
        np.save(path, x)
        a = ds.load_npy_file(path, block_size=(3, 3))
        np.testing.assert_allclose(a.collect(), x)


class TestSvmlight:
    def test_load(self, tmp_path):
        path = os.path.join(tmp_path, "data.svm")
        with open(path, "w") as f:
            f.write("1 1:0.5 3:1.5\n")
            f.write("-1 2:2.0\n")
            f.write("1 1:1.0 2:1.0 3:1.0\n")
        x, y = ds.load_svmlight_file(path, block_size=(2, 3), n_features=3,
                                     store_sparse=False)
        want = np.array([[0.5, 0, 1.5], [0, 2.0, 0], [1, 1, 1]], np.float32)
        np.testing.assert_allclose(x.collect(), want)
        np.testing.assert_allclose(y.collect().ravel(), [1, -1, 1])

    def test_load_sparse(self, tmp_path):
        import scipy.sparse as sp
        path = os.path.join(tmp_path, "data.svm")
        with open(path, "w") as f:
            f.write("0 1:1.0\n0 2:1.0\n")
        x, _ = ds.load_svmlight_file(path, n_features=2, store_sparse=True)
        got = x.collect()
        assert sp.issparse(got)
        np.testing.assert_allclose(got.toarray(), np.eye(2, dtype=np.float32))


class TestMdcrd:
    def test_load(self, tmp_path):
        # 2 frames, 2 atoms → 6 coords/frame, AMBER fixed-width 8.3f, 10/line
        path = os.path.join(tmp_path, "traj.mdcrd")
        coords = [float(i) / 10 for i in range(12)]
        with open(path, "w") as f:
            f.write("test trajectory\n")
            for i in range(0, 12, 10):
                line = "".join(f"{c:8.3f}" for c in coords[i:i + 10])
                f.write(line + "\n")
        a = ds.load_mdcrd_file(path, n_atoms=2)
        assert a.shape == (2, 6)
        np.testing.assert_allclose(a.collect().ravel(), coords, atol=1e-3)


class TestRowSlabIngest:
    """Per-host shard-local ingest (SURVEY §4.1, VERDICT r2 missing #3):
    the line-offset table must index rows exactly — any partition of
    [0, m) into row slabs reconstructs the file, order-preserving."""

    @pytest.mark.parametrize("pcount", [1, 2, 3, 7, 16])
    def test_row_slabs_partition_exactly(self, rng, tmp_path, pcount):
        from dislib_tpu.data.io import _parse_rows, _scan_line_offsets
        x = rng.rand(53, 4).astype(np.float32)
        path = tmp_path / "rows.csv"
        np.savetxt(path, x, delimiter=",")
        starts, fsize = _scan_line_offsets(str(path))
        m = len(starts)
        assert m == 53
        bounds = [m * i // pcount for i in range(pcount + 1)]
        parts = [_parse_rows(str(path), starts, fsize, bounds[i],
                             bounds[i + 1], ",", np.float32, 4)
                 for i in range(pcount)]
        got = np.concatenate([p for p in parts if p.size], axis=0)
        np.testing.assert_allclose(got, x, rtol=1e-5)

    def test_no_trailing_newline(self, rng, tmp_path):
        from dislib_tpu.data.io import _parse_rows, _scan_line_offsets
        path = tmp_path / "nonl.csv"
        with open(path, "w") as f:
            f.write("1.0,2.0\n3.0,4.0")          # no trailing newline
        starts, fsize = _scan_line_offsets(str(path))
        assert len(starts) == 2
        got = _parse_rows(str(path), starts, fsize, 0, 2, ",", np.float32, 2)
        np.testing.assert_allclose(got, [[1, 2], [3, 4]])

    def test_empty_slab(self, rng, tmp_path):
        from dislib_tpu.data.io import _parse_rows, _scan_line_offsets
        x = rng.rand(3, 2).astype(np.float32)
        path = tmp_path / "tiny.csv"
        np.savetxt(path, x, delimiter=",")
        starts, fsize = _scan_line_offsets(str(path))
        got = _parse_rows(str(path), starts, fsize, 3, 3, ",", np.float32, 2)
        assert got.shape == (0, 2)


class TestDtypePolicy:
    """VERDICT r2 #7: explicit dtype= through constructors/loaders; silent
    f64→f32 narrowing warns once."""

    def test_f64_narrowing_warns(self, rng):
        with pytest.warns(UserWarning, match="narrowing it to float32"):
            a = ds.array(rng.rand(4, 3))          # rng.rand is float64
        assert a.dtype == np.float32

    def test_explicit_f32_silences(self, rng):
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            a = ds.array(rng.rand(4, 3), dtype=np.float32)
        assert a.dtype == np.float32

    def test_f64_without_x64_raises(self, rng):
        with pytest.raises(ValueError, match="x64"):
            ds.array(rng.rand(4, 3), dtype=np.float64)

    def test_f64_with_x64_roundtrips(self, rng):
        import jax
        with jax.enable_x64(True):
            a = ds.array(rng.rand(4, 3), dtype=np.float64)
            got = a.collect()
        assert got.dtype == np.float64

    def test_loader_dtype_param(self, rng, tmp_path):
        import warnings
        x = rng.rand(6, 3)
        path = os.path.join(tmp_path, "x.npy")
        np.save(path, x)                           # float64 on disk
        with pytest.warns(UserWarning, match="narrowing"):
            a = ds.load_npy_file(path)
        assert a.dtype == np.float32
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            b = ds.load_npy_file(path, dtype=np.float32)
        assert b.dtype == np.float32


class TestMultiprocGuards:
    """Multi-process ingest error paths, exercised single-host by
    monkeypatching process_count (the slab logic is identical; only the
    process→shard mapping collapses to one host)."""

    def _force_multiproc(self, monkeypatch):
        import jax
        monkeypatch.setattr(jax, "process_count", lambda: 2)

    def test_blank_line_raises_everywhere(self, rng, tmp_path, monkeypatch):
        self._force_multiproc(monkeypatch)
        path = os.path.join(tmp_path, "b.csv")
        with open(path, "w") as f:
            f.write("1.0,2.0\n\n3.0,4.0\n")
        with pytest.raises(ValueError, match="blank lines"):
            ds.load_txt_file(path)

    def test_comment_first_line_raises(self, rng, tmp_path, monkeypatch):
        self._force_multiproc(monkeypatch)
        path = os.path.join(tmp_path, "c.csv")
        with open(path, "w") as f:
            f.write("# header\n1.0,2.0\n")
        with pytest.raises(ValueError, match="single-process"):
            ds.load_txt_file(path)

    def test_ragged_width_raises(self, rng, tmp_path, monkeypatch):
        self._force_multiproc(monkeypatch)
        path = os.path.join(tmp_path, "r.csv")
        with open(path, "w") as f:
            f.write("1.0,2.0,3.0\n")
            f.write("1.0,2.0\n" * 5)          # uniform but != first line
        with pytest.raises(ValueError):
            ds.load_txt_file(path)

    def test_clean_file_loads_through_multiproc_path(self, rng, tmp_path,
                                                     monkeypatch):
        self._force_multiproc(monkeypatch)
        x = rng.rand(12, 3).astype(np.float32)
        path = os.path.join(tmp_path, "ok.csv")
        np.savetxt(path, x, delimiter=",")
        a = ds.load_txt_file(path)
        np.testing.assert_allclose(a.collect(), x, rtol=1e-5)
