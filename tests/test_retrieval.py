"""Round-18 tentpole: the IVF-ANN retrieval tier.

- the recall oracle: ``nprobe = n_lists`` through the SAME fused program
  is the exact kneighbors result, checked against a numpy brute-force
  oracle over a (dtype incl. x64-f64 × overlap schedule) grid;
- the pad discipline: sentinel slots are provably non-load-bearing (the
  poisoned-slot regression fills them with 1e30 garbage per schedule and
  demands bit-equal results), empty lists and unfillable slots carry the
  documented (−1, +inf) contract, db/seq schedules are bit-equal;
- the one-dispatch contract: a search is ONE profiled dispatch with zero
  warm retraces, schedule routing observable via the counters;
- serving: ``RetrievalPipeline`` through the ``PredictServer`` bucket
  ladder and ``ModelRouter`` tenancy unchanged; ``export_bundle`` /
  ``load_bundle`` answer ``[ids | scores]`` in a FRESH subprocess with
  zero traces;
- the round-18 satellites: the on-device ``pack_sparse_rows`` encode,
  the sparse fold-in bundle capture, and the latency-budget admission
  control (``DeadlineShed``) riding the server's learned cost model.
"""

import os
import subprocess
import sys
from collections import deque

import numpy as np
import pytest

import jax

import dislib_tpu as ds
from dislib_tpu.retrieval import IVFIndex, RetrievalPipeline
from dislib_tpu.utils import profiling as prof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, D, NLIST, K, MQ = 256, 16, 8, 4, 8


@pytest.fixture
def rng():
    return np.random.RandomState(7)


def _mesh_now():
    from dislib_tpu.parallel import mesh as _mesh
    return _mesh.get_mesh()


def _crafted(rng, n=N, d=D, nlist=NLIST, dtype=np.float32, empty=(),
             **kw):
    """Build an index through the layout seam ``_build`` — crafted
    labels/centroids, no KMeans run (fast, and the only way to force
    empty lists or an x64 catalog deterministically)."""
    x = rng.randn(n, d).astype(dtype)
    live = [l for l in range(nlist) if l not in set(empty)]
    labels = np.asarray(live)[rng.randint(0, len(live), n)]
    cents = np.zeros((nlist, d), dtype)
    for l in live:
        m = labels == l
        if m.any():
            cents[l] = x[m].mean(axis=0)
    ix = IVFIndex(n_lists=nlist, **kw)._build(x, labels, cents)
    return ix, x


def _oracle(q, x, k):
    d2 = ((q[:, None, :].astype(np.float64)
           - x[None, :, :].astype(np.float64)) ** 2).sum(-1)
    idx = np.argsort(d2, axis=1, kind="stable")[:, :k]
    return np.sqrt(np.take_along_axis(d2, idx, axis=1)), idx


def _recall(found, true):
    return np.mean([len(set(found[i]) & set(true[i])) / true.shape[1]
                    for i in range(true.shape[0])])


# ---------------------------------------------------------------------------
# the recall oracle: exact at full probe, over the dtype × schedule grid
# ---------------------------------------------------------------------------

class TestRecallOracle:
    @pytest.mark.parametrize("sched", ["db", "seq"])
    @pytest.mark.parametrize("xdtype", ["float32", "float64"])
    def test_full_probe_matches_brute_force(self, rng, sched, xdtype):
        """nprobe = n_lists scans every entry exactly once across the
        ring steps — the exact kneighbors result through the SAME fused
        program, for f32 and (under x64) f64 catalogs."""
        x64 = xdtype == "float64"
        ctx = jax.enable_x64(True) if x64 else _null_ctx()
        with ctx:
            ix, x = _crafted(rng, dtype=np.dtype(xdtype))
            q = x[:MQ]
            dist, idx = ix.search(ds.array(q, dtype=np.dtype(xdtype)),
                                  k=K, nprobe=NLIST, overlap=sched)
            dh, ih = dist.collect(), idx.collect()
        od, oi = _oracle(q, x, K)
        assert _recall(ih, oi) == 1.0
        # the q²−2qf+f² form loses ~sqrt(eps·‖x‖²) near zero (the ring
        # kernel's own formulation) — tolerances account for it
        np.testing.assert_allclose(dh, od, atol=1e-4 if x64 else 2e-2)
        assert dh.dtype == np.dtype(xdtype)

    def test_nprobe_one_on_separated_blobs(self, rng):
        """Well-separated blobs with exact blob centroids: a catalog
        query's own list IS the nearest centroid, so nprobe=1 already
        returns the query itself at rank 0."""
        centers = rng.randn(NLIST, D).astype(np.float32) * 50
        labels = rng.randint(0, NLIST, N)
        x = (centers[labels] + rng.randn(N, D)).astype(np.float32)
        ix = IVFIndex(n_lists=NLIST)._build(x, labels, centers)
        dist, idx = ix.search(ds.array(x[:MQ]), k=1, nprobe=1)
        np.testing.assert_array_equal(idx.collect().ravel(),
                                      np.arange(MQ))

    def test_partial_probe_recall_dials_up(self, rng):
        """More probes → recall can only improve, reaching 1 at nlist."""
        ix, x = _crafted(rng)
        q = x[:MQ]
        _, oi = _oracle(q, x, K)
        last = 0.0
        for nprobe in (1, 4, NLIST):
            _, idx = ix.search(ds.array(q), k=K, nprobe=nprobe)
            r = _recall(idx.collect(), oi)
            assert r >= last - 1e-9
            last = r
        assert last == 1.0


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


# ---------------------------------------------------------------------------
# the pad discipline: schedules bit-equal, pads non-load-bearing, edges
# ---------------------------------------------------------------------------

class TestPadDiscipline:
    def test_db_seq_bit_equal(self, rng):
        ix, x = _crafted(rng)
        q = ds.array(x[:MQ])
        outs = {}
        for sched in ("db", "seq"):
            dist, idx = ix.search(q, k=K, nprobe=3, overlap=sched)
            outs[sched] = (dist.collect(), idx.collect())
        np.testing.assert_array_equal(outs["db"][0], outs["seq"][0])
        np.testing.assert_array_equal(outs["db"][1], outs["seq"][1])

    @pytest.mark.parametrize("sched", ["db", "seq"])
    def test_poisoned_pad_slots_change_nothing(self, rng, sched):
        """Fill every sentinel slot (id < 0) with 1e30 garbage in the
        vector, norm, AND id buffers — search must be bit-equal: the
        slot<count ∧ id≥0 mask is the only thing keeping pads out."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dislib_tpu.parallel import mesh as _mesh
        ix, x = _crafted(rng)
        q = ds.array(x[:MQ])
        clean = [a.collect() for a in ix.search(q, k=K, nprobe=NLIST,
                                                overlap=sched)]
        ids_h = np.asarray(ix._ids)
        pad = ids_h < 0
        assert pad.any()        # the quantum guarantees sentinel slots
        vecs_h = np.asarray(ix._vecs).copy()
        vsq_h = np.asarray(ix._vsq).copy()
        vecs_h[pad] = 1e30
        vsq_h[pad] = 1e30
        ids_p = ids_h.copy()
        ids_p[pad] = -999
        mesh = _mesh.get_mesh()
        ix._vecs = jax.device_put(vecs_h, _mesh.data_sharding(mesh))
        ix._ids = jax.device_put(ids_p, NamedSharding(mesh, P(_mesh.ROWS)))
        ix._vsq = jax.device_put(vsq_h, NamedSharding(mesh, P(_mesh.ROWS)))
        poisoned = [a.collect() for a in ix.search(q, k=K, nprobe=NLIST,
                                                   overlap=sched)]
        np.testing.assert_array_equal(clean[0], poisoned[0])
        np.testing.assert_array_equal(clean[1], poisoned[1])

    def test_empty_lists_and_unfillable_slots(self, rng):
        """Half the lists empty: full-probe search still exact; a tiny
        catalog with k > n_items carries the documented sentinel contract
        (id −1, distance +inf) in the unfillable slots."""
        ix, x = _crafted(rng, empty=(1, 3, 5, 7))
        q = x[:MQ]
        dist, idx = ix.search(ds.array(q), k=K, nprobe=NLIST)
        _, oi = _oracle(q, x, K)
        assert _recall(idx.collect(), oi) == 1.0

        tiny = rng.randn(3, D).astype(np.float32)
        ixt = IVFIndex(n_lists=2)._build(tiny, np.zeros(3, np.int64),
                                         np.zeros((2, D), np.float32))
        dist, idx = ixt.search(ds.array(tiny[:2]), k=8, nprobe=2)
        dh, ih = dist.collect(), idx.collect()
        assert (ih[:, 3:] == -1).all()
        assert np.isinf(dh[:, 3:]).all()
        assert (ih[:, :3] >= 0).all() and np.isfinite(dh[:, :3]).all()

    def test_pad_waste_report_and_quantum_knob(self, rng, monkeypatch):
        ix, _ = _crafted(rng)
        w = ix.pad_waste
        assert w["entries"] == N and w["quantum"] == 8
        assert w["buffer_rows"] >= N and 0.0 <= w["waste_frac"] < 1.0
        assert w["entries"] + w["list_pad_entries"] \
            + w["balance_pad_rows"] == w["buffer_rows"]
        assert sum(w["per_shard_entries"]) == N
        monkeypatch.setenv("DSLIB_IVF_LIST_QUANTUM", "16")
        ix16, _ = _crafted(rng)
        assert ix16.pad_waste["quantum"] == 16
        assert ix16.pad_waste["cap"] % 16 == 0
        # explicit arg beats the env
        ix4, _ = _crafted(rng, list_quantum=4)
        assert ix4.pad_waste["quantum"] == 4

    def test_mesh_change_heals_or_demands_refit(self, rng):
        """Round 20: a mesh change under a fitted index auto-heals —
        search re-stripes from the retained host layout inputs (counted
        ``retrieval_rebinds``) and keeps its full-probe exactness.  Only
        an index whose host inputs were dropped still raises the typed
        refit demand."""
        ix, x = _crafted(rng)
        q = x[:MQ]
        _, oi = _oracle(q, x, K)
        ds.init((4, 2))
        prof.reset_counters()
        _, idx = ix.search(ds.array(q), k=K, nprobe=NLIST)
        assert _recall(idx.collect(), oi) == 1.0
        assert prof.resilience_counters().get("retrieval_rebinds") == 1
        assert ix._fitted_mesh == (4, 2)
        # host inputs dropped → the pre-round-20 typed demand survives
        ix._items_h = None
        ds.init((8, 1))
        with pytest.raises(RuntimeError, match="refit"):
            ix.search(ds.array(q), k=K)

    def test_unfitted_and_bad_inputs_are_typed(self, rng):
        with pytest.raises(RuntimeError, match="not fitted"):
            IVFIndex().search(np.zeros((1, 4)))
        ix, x = _crafted(rng)
        with pytest.raises(ValueError, match="features"):
            ix.search(np.zeros((2, D + 1), np.float32))
        with pytest.raises(ValueError, match="k must be"):
            ix.search(x[:2], k=0)
        with pytest.raises(ValueError, match="labels"):
            IVFIndex(n_lists=2)._build(x[:4], np.array([0, 1, 2, 0]),
                                       np.zeros((2, D)))


# ---------------------------------------------------------------------------
# the one-dispatch contract
# ---------------------------------------------------------------------------

class TestDispatchContract:
    def test_search_is_one_dispatch_zero_warm_retraces(self, rng):
        ix, x = _crafted(rng)
        q = ds.array(x[:MQ])
        ix.search(q, k=K, nprobe=3)             # compile
        prof.reset_counters()
        dist, idx = ix.search(q, k=K, nprobe=3)
        dist.collect(), idx.collect()
        c = prof.counters()
        assert c["dispatch_by"].get("ivf_search") == 1
        assert c["traces"] == 0
        assert prof.schedule_counters().get("ivf_search:db", 0) >= 1

    def test_schedule_router_is_observable(self, rng, monkeypatch):
        ix, x = _crafted(rng)
        monkeypatch.setenv("DSLIB_OVERLAP", "seq")
        before = prof.schedule_counters().get("ivf_search:seq", 0)
        ix.search(ds.array(x[:MQ]), k=K, nprobe=2)
        assert prof.schedule_counters()["ivf_search:seq"] == before + 1


# ---------------------------------------------------------------------------
# fit: the KMeans quantizer path
# ---------------------------------------------------------------------------

class TestFit:
    def test_fit_builds_from_kmeans_and_searches(self, rng):
        centers = rng.randn(4, D).astype(np.float32) * 20
        x = (centers[rng.randint(0, 4, 128)]
             + rng.randn(128, D)).astype(np.float32)
        ix = IVFIndex(n_lists=4, kmeans_max_iter=5, random_state=0).fit(x)
        assert ix.quantizer_ is not None and ix.n_lists_ == 4
        assert ix.n_items == 128 and ix.d == D
        dist, idx = ix.search(ds.array(x[:MQ]), k=1, nprobe=4)
        np.testing.assert_array_equal(idx.collect().ravel(),
                                      np.arange(MQ))

    def test_default_nlist_is_sqrt_heuristic(self, rng):
        x = rng.randn(64, D).astype(np.float32)
        ix = IVFIndex(kmeans_max_iter=2, random_state=0).fit(x)
        assert ix.n_lists_ == 8


# ---------------------------------------------------------------------------
# serving: bucket ladder, tenancy, and the deployment bundle
# ---------------------------------------------------------------------------

_FRESH_PROCESS_SCRIPT = """
import os, sys, json
import numpy as np
import dislib_tpu as ds
ds.init()
from dislib_tpu.serving import load_bundle
from dislib_tpu.utils import profiling as prof
lb = load_bundle(sys.argv[1])
rows = np.asarray(json.loads(sys.argv[2]), np.float32)
t0 = prof.trace_count()
outs = {b: lb.pipeline.predict_bucket(rows, b).tolist()
        for b in lb.buckets}
print(json.dumps({"traces": prof.trace_count() - t0,
                  "fallback": lb.fallback, "outs": outs}))
"""


class TestRetrievalServing:
    def test_pipeline_through_server_ladder(self, rng):
        from dislib_tpu.serving import PredictServer
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=NLIST)
        q = x[:5]
        dist, idx = ix.search(ds.array(q), k=K, nprobe=NLIST)
        want = np.concatenate([idx.collect().astype(np.float32),
                               dist.collect()], axis=1)
        with PredictServer(pipeline=pipe, buckets=(1, 8)) as srv:
            out = srv.predict(q)
            stats = srv.stats()
        assert stats["dispatches_per_batch_max"] == 1
        assert out.shape == (5, 2 * K)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

    def test_pipeline_rebind_through_data_rebind(self, rng):
        """Round-20 elastic rebind: ``fitloop.data_rebind`` delegates to
        a holder exposing ``rebind_mesh`` — the pipeline re-stripes the
        index onto the new mesh and drops its quantum-shaped bucket
        canvases, and the re-striped serve answers match the pre-resize
        ones on the surviving device set."""
        from dislib_tpu.runtime.fitloop import data_rebind
        ds.init((8, 1))
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=NLIST)
        q = x[:MQ]
        before = pipe.predict_bucket(q, 8)
        assert pipe._templates           # canvases built on the old mesh
        ds.init((4, 2))                  # the elastic rung's resize
        hook = data_rebind({"x": pipe})
        prof.reset_counters()
        hook(None)                       # pre-switch force phase: no-op
        assert prof.resilience_counters().get("retrieval_rebinds") is None
        hook(_mesh_now())
        assert prof.resilience_counters().get("retrieval_rebinds") == 1
        assert ix._fitted_mesh == (4, 2)
        assert not pipe._templates       # stale canvases dropped
        after = pipe.predict_bucket(q, 8)
        # full probe on both meshes: identical retrieved sets; distances
        # agree to the kernel's near-zero cancellation tolerance (the
        # q²−2qf+f² form — same bound as the recall oracle above)
        np.testing.assert_array_equal(before[:, :K], after[:, :K])
        np.testing.assert_allclose(before[:, K:], after[:, K:], atol=2e-2)
        # a second hook on an unchanged mesh is a no-op
        hook(_mesh_now())
        assert prof.resilience_counters().get("retrieval_rebinds") == 1

    def test_serve_path_heals_after_external_mesh_move(self, rng):
        """Round-20 regression (found by the multi-host soak): when the
        mesh moves UNDER a serving pipeline — a co-resident fit loop
        resizing on a capacity event, no elastic hook wired — the next
        ``predict_bucket`` must heal end-to-end: the index auto-rebinds
        in ``_check_fitted`` AND the quantum-shaped bucket canvases
        follow.  A canvas cached for the old pad staged queries into the
        wrong shape and every subsequent request tore on a dot_general
        mismatch."""
        ds.init((8, 1))
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=NLIST)
        q = x[:MQ]
        before = pipe.predict_bucket(q, 8)
        assert pipe._templates
        ds.init((4, 2))                  # external resize, nobody told us
        prof.reset_counters()
        after = pipe.predict_bucket(q, 8)    # must not tear
        assert prof.resilience_counters().get("retrieval_rebinds") == 1
        assert ix._fitted_mesh == (4, 2)
        np.testing.assert_array_equal(before[:, :K], after[:, :K])
        np.testing.assert_allclose(before[:, K:], after[:, K:], atol=2e-2)

    def test_router_tenancy_composes(self, rng):
        from dislib_tpu.serving import ModelRouter, PredictServer
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=2)
        srv = PredictServer(pipeline=pipe, buckets=(8,), name="retr")
        r = ModelRouter()
        r.add_tenant("acme", srv, quota_rows=64)
        with r:
            out = r.predict(x[:3], "acme")
            st = r.stats()
        assert out.shape == (3, 2 * K)
        assert st["acme"]["serving"]["requests"] == 1
        assert st["acme"]["deadline_shed"] == 0

    def test_bundle_roundtrip_fresh_subprocess(self, rng, tmp_path):
        """The headline cold-start claim: a process that never saw the
        index serves [ids|scores] off the bundle with ZERO traces."""
        import json
        from dislib_tpu.serving import export_bundle
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=NLIST)
        q = x[:4]
        live = pipe.predict_bucket(q, 8)
        path = str(tmp_path / "retr.bundle")
        export_bundle(pipe, path, buckets=(8,))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        out = subprocess.run(
            [sys.executable, "-c", _FRESH_PROCESS_SCRIPT, path,
             json.dumps(q.tolist())],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        res = json.loads(out.stdout.strip().splitlines()[-1])
        assert res["traces"] == 0 and not res["fallback"]
        np.testing.assert_array_equal(
            np.asarray(res["outs"]["8"], np.float32), live)

    def test_id_ceiling_is_guarded(self, rng):
        ix, _ = _crafted(rng)
        ix.n_items = 1 << 24            # simulate a too-large catalog
        with pytest.raises(ValueError, match="2\\^24"):
            RetrievalPipeline(ix)


# ---------------------------------------------------------------------------
# satellite: the on-device sparse request encode + fold-in bundle
# ---------------------------------------------------------------------------

class TestSparsePackOnDevice:
    def test_device_pack_matches_host_path_bit_for_bit(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.serving import pack_sparse_rows
        dense = np.where(rng.rand(6, 40) < 0.15,
                         rng.randn(6, 40), 0.0).astype(np.float32)
        prof.reset_counters()
        a = pack_sparse_rows(dense, nse_cap=8)
        c = prof.counters()
        assert c["dispatch_by"].get("pack_sparse_rows") == 1
        assert c["transfers"] == 1      # counts packed into the payload
        b = pack_sparse_rows(sp.csr_matrix(dense), nse_cap=8)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.float32 and a.shape == (6, 16)

    def test_device_pack_error_parity(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.serving import pack_sparse_rows
        full = np.ones((2, 12), np.float32)
        msgs = []
        for req in (full, sp.csr_matrix(full)):
            with pytest.raises(ValueError) as e:
                pack_sparse_rows(req, nse_cap=4)
            msgs.append(str(e.value))
        assert msgs[0] == msgs[1]
        # out-of-range ids stay typed on the device path too
        bad = np.zeros((1, 8), np.float32)
        bad[0, 6] = 1.0
        with pytest.raises(ValueError, match="out of range"):
            pack_sparse_rows(bad, nse_cap=4, n_items=5)

    def test_cap_wider_than_catalog(self, rng):
        import scipy.sparse as sp
        from dislib_tpu.serving import pack_sparse_rows
        small = np.zeros((2, 3), np.float32)
        small[0, 1] = 2.5
        a = pack_sparse_rows(small, nse_cap=8)
        b = pack_sparse_rows(sp.csr_matrix(small), nse_cap=8)
        np.testing.assert_array_equal(a, b)


def _tiny_als(rng):
    import scipy.sparse as sp
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.recommendation import ALS
    u = rng.rand(30, 4).astype(np.float32)
    v = rng.rand(20, 4).astype(np.float32)
    r = np.where(rng.rand(30, 20) < 0.4, u @ v.T, 0.0).astype(np.float32)
    return ALS(n_f=4, lambda_=0.002, max_iter=5, tol=1e-7,
               random_state=0).fit(SparseArray.from_scipy(sp.csr_matrix(r)))


class TestSparseFoldInBundle:
    @pytest.mark.parametrize("top_n", [None, 3])
    def test_bundle_matches_live_serving(self, rng, tmp_path, top_n):
        from dislib_tpu.serving import (SparseFoldInPipeline,
                                        export_bundle, load_bundle)
        als = _tiny_als(rng)
        pipe = SparseFoldInPipeline(als, nse_cap=16, top_n=top_n)
        packed = pipe.pack(np.where(rng.rand(5, 20) < 0.4, 1.0, 0.0)
                           .astype(np.float32))
        live = pipe.predict_bucket(packed, 8)
        path = str(tmp_path / f"foldin_{top_n}.bundle")
        export_bundle(pipe, path, buckets=(8,))
        lb = load_bundle(path)
        assert not lb.fallback
        prof.reset_counters()
        out = lb.pipeline.predict_bucket(packed, 8)
        assert prof.counters()["traces"] == 0
        np.testing.assert_allclose(out, live, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# satellite: latency-budget admission control
# ---------------------------------------------------------------------------

class TestDeadlineShed:
    def _served(self, rng):
        from dislib_tpu.serving import PredictServer
        ix, x = _crafted(rng)
        pipe = RetrievalPipeline(ix, k=K, nprobe=2)
        return PredictServer(pipeline=pipe, buckets=(8,),
                             name="dl"), x

    def test_cost_model_learns_from_serving(self, rng):
        srv, x = self._served(rng)
        with srv:
            for _ in range(3):
                srv.predict(x[:2])
            costs = srv.bucket_cost()
            stats = srv.stats()
        assert 8 in costs and costs[8] > 0.0
        assert stats["bucket_cost_ms"][8] > 0.0
        assert srv.predict_latency(2) is not None

    def test_no_shed_on_ignorance(self, rng):
        """A cold server has no cost model — the budget must admit, not
        guess."""
        from dislib_tpu.serving import ModelRouter
        srv, x = self._served(rng)
        r = ModelRouter(deadline_ms=0.001)
        r.add_tenant("acme", srv)
        with r:
            assert srv.predict_latency(2) is None
            out = r.predict(x[:2], "acme")
        assert out.shape == (2, 2 * K)

    def test_predicted_miss_sheds_typed_and_counted(self, rng):
        from dislib_tpu.serving import DeadlineShed, ModelRouter
        srv, x = self._served(rng)
        r = ModelRouter(deadline_ms=5)
        r.add_tenant("acme", srv)
        with r:
            # seed the learned model with measured-looking 10 s walls
            with srv._cv:
                srv._bucket_wall[8] = deque([10.0, 10.0, 10.0])
            with pytest.raises(DeadlineShed) as e:
                r.submit(x[:2], "acme")
            st = r.stats()
            out = None
            # the budget gone → the same request is admitted again
            r2 = ModelRouter(deadline_ms=None)
            r2.add_tenant("acme", srv)
            out = r2.predict(x[:2], "acme")
        assert e.value.tenant == "acme"
        assert e.value.predicted_ms > e.value.deadline_ms == 5.0
        assert st["acme"]["deadline_shed"] == 1
        assert st["acme"]["inflight_rows"] == 0     # reservation released
        assert out is not None

    def test_env_knob_sets_the_budget(self, rng, monkeypatch):
        from dislib_tpu.serving import ModelRouter
        monkeypatch.setenv("DSLIB_DEADLINE_MS", "250")
        assert ModelRouter().deadline_s == 0.25
        monkeypatch.delenv("DSLIB_DEADLINE_MS")
        assert ModelRouter().deadline_s is None
