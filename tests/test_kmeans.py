"""KMeans tests (reference: tests/test_kmeans.py; oracle = sklearn KMeans on
the same data/init, SURVEY.md §5)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans


def _blobs(rng, n=300, d=4, k=3, spread=0.15):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + spread * rng.randn(n // k, d) for i in range(k)])
    labels = np.repeat(np.arange(k), n // k)
    return x.astype(np.float32), labels, centers.astype(np.float32)


class TestKMeans:
    def test_converges_on_blobs(self, rng):
        x, true_labels, _ = _blobs(rng)
        km = KMeans(n_clusters=3, max_iter=50, tol=1e-6, random_state=0)
        labels = km.fit_predict(ds.array(x)).collect().ravel().astype(int)
        # clustering equals ground truth up to label permutation
        for c in range(3):
            assert len(np.unique(labels[true_labels == c])) == 1
        assert km.n_iter_ <= 50
        assert km.inertia_ > 0

    def test_vs_sklearn_same_init(self, rng):
        from sklearn.cluster import KMeans as SkKMeans
        x, _, _ = _blobs(rng, n=240, d=5, k=4)
        init = x[rng.choice(len(x), 4, replace=False)]
        km = KMeans(n_clusters=4, init=init.copy(), max_iter=30, tol=0.0)
        km.fit(ds.array(x))
        sk = SkKMeans(n_clusters=4, init=init.copy(), n_init=1, max_iter=30,
                      tol=0.0, algorithm="lloyd").fit(x)
        # same init + Lloyd's ⇒ same final centers (order preserved)
        np.testing.assert_allclose(km.centers_, sk.cluster_centers_, atol=1e-3)
        np.testing.assert_allclose(km.inertia_, sk.inertia_, rtol=1e-4)

    def test_predict_matches_assignment(self, rng):
        x, _, _ = _blobs(rng, n=120)
        a = ds.array(x)
        km = KMeans(n_clusters=3, max_iter=20, random_state=1).fit(a)
        labels = km.predict(a).collect().ravel().astype(int)
        d = ((x[:, None, :] - km.centers_[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_deterministic_with_seed(self, rng):
        x, _, _ = _blobs(rng)
        a = ds.array(x)
        c1 = KMeans(n_clusters=3, random_state=5).fit(a).centers_
        c2 = KMeans(n_clusters=3, random_state=5).fit(a).centers_
        np.testing.assert_array_equal(c1, c2)

    def test_score_is_negative_inertia(self, rng):
        x, _, _ = _blobs(rng, n=90)
        a = ds.array(x)
        km = KMeans(n_clusters=3, max_iter=20, random_state=2).fit(a)
        assert km.score(a) == pytest.approx(-km.inertia_, rel=1e-4)

    def test_explicit_init_bad_shape(self, rng):
        with pytest.raises(ValueError):
            KMeans(n_clusters=3, init=np.zeros((2, 2))).fit(ds.array(rng.rand(10, 4)))

    def test_irregular_rows(self, rng):
        # row count not divisible by mesh: padded rows must not perturb centers
        x, _, _ = _blobs(rng, n=231, d=3, k=3)
        x = x[:231]
        init = x[:3]
        km = KMeans(n_clusters=3, init=init.copy(), max_iter=10, tol=0.0)
        km.fit(ds.array(x))
        from sklearn.cluster import KMeans as SkKMeans
        sk = SkKMeans(n_clusters=3, init=init.copy(), n_init=1, max_iter=10,
                      tol=0.0, algorithm="lloyd").fit(x)
        np.testing.assert_allclose(km.centers_, sk.cluster_centers_, atol=1e-3)


def test_fast_distance_flag_matches(monkeypatch):
    """DSLIB_KMEANS_FAST_DISTANCE stores the E-step operand as bfloat16 —
    the same input rounding the TPU MXU applies at default precision, so
    the CPU rig now exercises the fast path's true numerics.  Gate mirrors
    bench.py's: centers within bf16 tolerance, inertia within 0.1%."""
    import dislib_tpu as ds
    from dislib_tpu.cluster import KMeans

    rng = np.random.RandomState(5)
    data = rng.rand(200, 6).astype(np.float32)
    x = ds.array(data, block_size=(32, 6))
    init = data[:4].copy()
    km_ref = KMeans(n_clusters=4, init=init, max_iter=7, tol=0.0).fit(x)
    km_fast = KMeans(n_clusters=4, init=init, max_iter=7, tol=0.0,
                     fast_distance=True).fit(x)
    monkeypatch.setenv("DSLIB_KMEANS_FAST_DISTANCE", "1")
    km_env = KMeans(n_clusters=4, init=init, max_iter=7, tol=0.0).fit(x)
    np.testing.assert_allclose(km_env.centers_, km_fast.centers_, rtol=1e-6)
    np.testing.assert_allclose(km_fast.centers_, km_ref.centers_,
                               rtol=2e-2, atol=2e-2)
    # 7 iterations on 200 points: a few bf16 boundary flips can drift the
    # trajectory to a nearby local optimum — gate on objective QUALITY (1%);
    # the tight 0.1% single-iteration gate lives in bench.py at m=1M
    np.testing.assert_allclose(km_fast.inertia_, km_ref.inertia_, rtol=1e-2)
