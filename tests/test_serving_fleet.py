"""Round-15 tentpole: AOT deployment bundles + the multi-tenant router.

- bundle lifecycle: export → load in a warm process is bit-equal across
  the WHOLE ladder with zero retraces (trace-counter-pinned), and a
  truly fresh process proves the cold-start claim end-to-end in a
  subprocess; damage and incompatibility fail typed-and-loud.
- ladder validation: ``DSLIB_SERVE_BUCKETS`` rejects out-of-order /
  duplicate / non-integer / non-positive ladders at parse time.
- tenancy: per-tenant latency/shed observability on the server, quota
  admission on the router shedding only the offender, hash-deterministic
  canary splits, and health-gated promotion.

Compile-budget note (tier-1 discipline): ONE feature width (8), ONE
ladder (1, 8, 64), module-cached fitted models and ONE module-cached
exported bundle — export pays the ladder's compiles once for the file.
"""

import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.runtime import BundleIncompatible
from dislib_tpu.serving import (BucketLadderError, BundlePipeline,
                                ModelPool, ModelRouter, PredictServer,
                                QueueFull, ServePipeline,
                                TenantQuotaExceeded, bucket_ladder,
                                export_bundle, load_bundle)
from dislib_tpu.serving import bundle as bundle_mod
from dislib_tpu.utils import profiling as prof
from dislib_tpu.utils.checkpoint import FitCheckpoint, SnapshotCorrupt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BUCKETS = (1, 8, 64)
NF = 8

_ctx = {}


def _linreg(intercept: float) -> ServePipeline:
    lr = ds.LinearRegression()
    lr.coef_ = np.ones((NF, 1), np.float32)
    lr.intercept_ = np.full(1, float(intercept), np.float32)
    return ServePipeline(lr, n_features=NF)


def ctx(tmp_factory=None):
    """Module-cached pipeline + ONE exported bundle (the export pays the
    per-bucket lower+compile once for the whole file)."""
    if not _ctx:
        _ctx["pipe"] = _linreg(5.0)
        _ctx["state"] = {"coef": _ctx["pipe"].model.coef_,
                         "intercept": _ctx["pipe"].model.intercept_}
        path = str(tmp_factory.mktemp("bundle") / "model.dsb.npz")
        _ctx["manifest"] = export_bundle(_ctx["pipe"], path,
                                         buckets=BUCKETS,
                                         state=_ctx["state"])
        _ctx["path"] = path
        _ctx["rng"] = np.random.RandomState(3)
    return _ctx


@pytest.fixture(scope="module")
def bundle_ctx(tmp_path_factory):
    return ctx(tmp_path_factory)


# ---------------------------------------------------------------------------
# satellite: strict DSLIB_SERVE_BUCKETS validation
# ---------------------------------------------------------------------------

class TestLadderValidation:
    @pytest.mark.parametrize("env,fragment", [
        ("512,64", "strictly increasing"),
        ("8,8,64", "strictly increasing"),
        ("4,banana", "not an integer"),
        ("0,8", "not positive"),
        ("-1", "not positive"),
        (",,", "no buckets"),
    ])
    def test_env_ladder_rejected_at_parse_time(self, monkeypatch, env,
                                               fragment):
        monkeypatch.setenv("DSLIB_SERVE_BUCKETS", env)
        with pytest.raises(BucketLadderError) as ei:
            bucket_ladder()
        # the deployment postmortem needs the offending value verbatim
        assert env in str(ei.value) and fragment in str(ei.value)

    def test_env_ladder_accepts_valid(self, monkeypatch):
        monkeypatch.setenv("DSLIB_SERVE_BUCKETS", " 4 , 32 ,512 ")
        assert bucket_ladder() == (4, 32, 512)

    def test_typed_error_is_a_valueerror(self):
        # pre-round-15 callers catching ValueError keep working
        assert issubclass(BucketLadderError, ValueError)

    def test_programmatic_ladders_still_normalise(self):
        # a Python-literal ladder is the caller's own code — legacy
        # sort/dedupe normalisation stays
        assert bucket_ladder((64, 1, 8, 8)) == (1, 8, 64)


# ---------------------------------------------------------------------------
# bundle lifecycle
# ---------------------------------------------------------------------------

class TestBundleLifecycle:
    def test_roundtrip_bit_equal_across_whole_ladder(self, bundle_ctx):
        c = bundle_ctx
        lb = load_bundle(c["path"])
        assert not lb.fallback
        assert isinstance(lb.pipeline, BundlePipeline)
        assert lb.buckets == BUCKETS
        for b in BUCKETS:
            rows = c["rng"].rand(min(b, 7), NF).astype(np.float32)
            np.testing.assert_array_equal(
                lb.pipeline.predict_bucket(rows, b),
                c["pipe"].predict_bucket(rows, b))

    def test_load_and_serve_add_zero_traces(self, bundle_ctx):
        c = bundle_ctx
        t0 = prof.trace_count()
        lb = load_bundle(c["path"])
        for b in BUCKETS:
            lb.pipeline.predict_bucket(
                c["rng"].rand(1, NF).astype(np.float32), b)
        assert prof.trace_count() == t0, \
            "bundle load or serve retraced — the cold-start win is gone"

    def test_bundle_dispatches_are_counted(self, bundle_ctx):
        c = bundle_ctx
        lb = load_bundle(c["path"])
        prof.reset_counters()
        lb.pipeline.predict_bucket(np.ones((3, NF), np.float32), 8)
        assert prof.counters()["dispatch_by"].get("bundle_exec") == 1

    def test_embedded_state_roundtrips(self, bundle_ctx):
        c = bundle_ctx
        lb = load_bundle(c["path"])
        assert sorted(lb.state) == ["coef", "intercept"]
        np.testing.assert_array_equal(lb.state["coef"], c["state"]["coef"])

    def test_truncation_is_typed_and_loud(self, bundle_ctx, tmp_path):
        data = open(bundle_ctx["path"], "rb").read()
        bad = tmp_path / "trunc.npz"
        bad.write_bytes(data[: len(data) // 2])
        with pytest.raises(SnapshotCorrupt):
            load_bundle(str(bad))

    def test_bit_corruption_is_typed_and_loud(self, bundle_ctx, tmp_path):
        data = bytearray(open(bundle_ctx["path"], "rb").read())
        data[len(data) // 2] ^= 0xFF
        bad = tmp_path / "flip.npz"
        bad.write_bytes(bytes(data))
        with pytest.raises(SnapshotCorrupt):
            load_bundle(str(bad))

    def test_foreign_file_is_typed(self, tmp_path):
        alien = tmp_path / "alien.npz"
        np.savez(alien, x=np.ones(3))
        with pytest.raises(SnapshotCorrupt):
            load_bundle(str(alien))

    def test_fingerprint_mismatch_refuses_cleanly(self, bundle_ctx,
                                                  monkeypatch):
        real = bundle_mod.runtime_fingerprint()

        def other():
            fp = dict(real)
            fp["jaxlib"] = "99.0.0"
            fp["n_devices"] = 1024
            return fp

        monkeypatch.setattr(bundle_mod, "runtime_fingerprint", other)
        with pytest.raises(BundleIncompatible) as ei:
            load_bundle(bundle_ctx["path"])
        # both fingerprints ride the error for the postmortem
        assert ei.value.expected["jaxlib"] == real["jaxlib"]
        assert ei.value.found["jaxlib"] == "99.0.0"
        assert "jaxlib" in str(ei.value)

    def test_fingerprint_mismatch_falls_back_loudly_with_build(
            self, bundle_ctx, monkeypatch):
        monkeypatch.setattr(
            bundle_mod, "runtime_fingerprint",
            lambda: {**bundle_ctx["manifest"]["fingerprint"],
                     "platform": "definitely-not-this"})

        def build(state):
            lr = ds.LinearRegression()
            lr.coef_ = np.asarray(state["coef"], np.float32)
            lr.intercept_ = np.asarray(state["intercept"], np.float32)
            return ServePipeline(lr, n_features=NF)

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            lb = load_bundle(bundle_ctx["path"], build=build)
        assert lb.fallback
        assert any("cold-start protection is LOST" in str(x.message)
                   for x in w)
        rows = np.ones((2, NF), np.float32)
        np.testing.assert_array_equal(
            lb.pipeline.predict_bucket(rows, 8),
            bundle_ctx["pipe"].predict_bucket(rows, 8))

    def test_export_via_checkpoint_routes_through_the_gate(
            self, bundle_ctx, tmp_path):
        ckpt = FitCheckpoint(str(tmp_path / "ck"), keep=2)
        ckpt.save(bundle_ctx["state"])
        path = str(tmp_path / "ck.dsb.npz")
        export_bundle(bundle_ctx["pipe"], path, buckets=(1,),
                      checkpoint=ckpt)
        lb = load_bundle(path)
        np.testing.assert_array_equal(lb.state["coef"],
                                      bundle_ctx["state"]["coef"])

    def test_export_empty_checkpoint_refuses(self, bundle_ctx, tmp_path):
        ckpt = FitCheckpoint(str(tmp_path / "empty"), keep=2)
        with pytest.raises(ValueError, match="no generation"):
            export_bundle(bundle_ctx["pipe"], str(tmp_path / "x.npz"),
                          buckets=(1,), checkpoint=ckpt)

    def test_bundle_pipeline_rejects_bad_requests(self, bundle_ctx):
        lb = load_bundle(bundle_ctx["path"])
        with pytest.raises(ValueError, match="not in the bundle"):
            lb.pipeline.predict_bucket(np.ones((2, NF), np.float32), 16)
        with pytest.raises(ValueError, match="features"):
            lb.pipeline.predict_bucket(np.ones((2, NF + 1), np.float32), 8)
        with pytest.raises(ValueError, match="exceed bucket"):
            lb.pipeline.predict_bucket(np.ones((9, NF), np.float32), 8)

    def test_serves_through_predict_server(self, bundle_ctx):
        lb = load_bundle(bundle_ctx["path"])
        with PredictServer(pipeline=lb.pipeline, buckets=BUCKETS,
                           name="bundle-srv") as srv:
            rows = np.ones((3, NF), np.float32)
            np.testing.assert_array_equal(
                srv.predict(rows),
                bundle_ctx["pipe"].predict_bucket(rows, 8))


_FRESH_PROCESS_SCRIPT = """
import os, sys, json
import numpy as np
import dislib_tpu as ds
ds.init()
from dislib_tpu.serving import load_bundle
from dislib_tpu.utils import profiling as prof
lb = load_bundle(sys.argv[1])
t0 = prof.trace_count()
outs = {b: lb.pipeline.predict_bucket(
            np.ones((min(b, 4), lb.pipeline.n_features), np.float32), b
        ).tolist() for b in lb.buckets}
print(json.dumps({"traces": prof.trace_count() - t0,
                  "fallback": lb.fallback, "outs": outs}))
"""


class TestBundleFreshProcess:
    def test_fresh_process_serves_with_zero_traces(self, bundle_ctx):
        """The actual cold-start claim: a process that has never seen
        the model serves the whole ladder off the bundle without a
        single trace."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags +
                                " --xla_force_host_platform_device_count"
                                "=8").strip()
        out = subprocess.run(
            [sys.executable, "-c", _FRESH_PROCESS_SCRIPT,
             bundle_ctx["path"]],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        import json
        res = json.loads(out.stdout.strip().splitlines()[-1])
        assert res["traces"] == 0 and not res["fallback"]
        for b in BUCKETS:
            rows = np.ones((min(b, 4), NF), np.float32)
            np.testing.assert_array_equal(
                np.asarray(res["outs"][str(b)], np.float32),
                bundle_ctx["pipe"].predict_bucket(rows, b))


# ---------------------------------------------------------------------------
# satellite: per-tenant server observability + typed backpressure
# ---------------------------------------------------------------------------

class TestTenantStats:
    def test_per_tenant_percentiles_and_shed(self, bundle_ctx):
        with PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                           name="tsrv") as srv:
            for t in ("acme", "globex"):
                for _ in range(4):
                    srv.predict(np.ones((2, NF), np.float32), tenant=t)
            st = srv.stats()
        assert st["shed"] == 0
        for t in ("acme", "globex"):
            ten = st["tenants"][t]
            assert ten["requests"] == 4 and ten["shed"] == 0
            assert ten["p50_ms"] is not None
            assert ten["p50_ms"] <= ten["p95_ms"] <= ten["p99_ms"]
        assert st["p95_ms"] is not None    # overall window grew p95 too

    def test_queue_full_is_typed_and_tenant_attributed(self, bundle_ctx):
        srv = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                            max_queue_rows=4, name="tiny")
        srv.start()
        try:
            # stall the worker by never letting it win the deadline race:
            # fill the queue within one deadline window
            srv.deadline_s = 5.0
            srv.submit(np.ones((4, NF), np.float32), tenant="acme")
            with pytest.raises(QueueFull) as ei:
                srv.submit(np.ones((1, NF), np.float32), tenant="acme")
            assert ei.value.tenant == "acme"
            assert isinstance(ei.value, RuntimeError)   # legacy catch
            st = srv.stats()
            assert st["shed"] == 1
            assert st["tenants"]["acme"]["shed"] == 1
        finally:
            srv.deadline_s = 0.001
            srv.stop()


# ---------------------------------------------------------------------------
# multi-tenant router
# ---------------------------------------------------------------------------

class TestModelRouter:
    def test_n_tenants_one_ladder_zero_extra_traces(self, bundle_ctx):
        """The executable-sharing claim: tenants 2..N on an
        already-warmed shared server cost ZERO additional compiles."""
        srv = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                            name="shared")
        r = ModelRouter()
        for t in ("a", "b", "c"):
            r.add_tenant(t, srv)
        with r:
            t0 = prof.trace_count()
            for t in ("a", "b", "c"):
                for k in (1, 3, 8):
                    r.predict(np.ones((k, NF), np.float32), t)
            assert prof.trace_count() == t0
            st = r.stats()
        assert all(st[t]["serving"]["requests"] == 3 for t in "abc")

    def test_quota_sheds_only_the_offender(self, bundle_ctx):
        srv = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                            name="quota")
        r = ModelRouter()
        r.add_tenant("noisy", srv, quota_rows=4)
        r.add_tenant("quiet", srv)
        with r:
            # the worker computes its flush window once per batch: 1 s is
            # long enough to keep noisy's rows in flight for the quota
            # check, short enough not to stall the suite
            srv.deadline_s = 1.0
            f1 = r.submit(np.ones((4, NF), np.float32), "noisy")
            with pytest.raises(TenantQuotaExceeded) as ei:
                r.submit(np.ones((1, NF), np.float32), "noisy")
            assert ei.value.tenant == "noisy"
            assert ei.value.quota_rows == 4
            # the neighbour is untouched — same instant, same server
            f2 = r.submit(np.ones((2, NF), np.float32), "quiet")
            srv.deadline_s = 0.001
            assert f1.result(timeout=30).values.shape == (4, 1)
            assert f2.result(timeout=30).values.shape == (2, 1)
            assert r.stats()["noisy"]["quota_shed"] == 1
            assert r.stats()["quiet"]["quota_shed"] == 0

    def test_quota_releases_on_completion(self, bundle_ctx):
        srv = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                            name="rel")
        r = ModelRouter()
        r.add_tenant("t", srv, quota_rows=4)
        with r:
            for _ in range(5):      # serially: quota frees every time
                r.predict(np.ones((4, NF), np.float32), "t")
            assert r.stats()["t"]["inflight_rows"] == 0

    def test_canary_split_is_deterministic_and_reaches_both_arms(
            self, bundle_ctx):
        s1 = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                           name="primary")
        s2 = PredictServer(pipeline=_linreg(6.0), buckets=BUCKETS,
                           name="canary")
        r = ModelRouter()
        r.add_tenant("t", s1)
        r.set_canary("t", s2, fraction=0.5)
        rows = np.ones((1, NF), np.float32)
        labels = {}
        for i in range(32):
            _, label = r.route("t", rows, key=f"user{i}")
            labels[f"user{i}"] = label
        assert set(labels.values()) == {"t", "t:canary"}
        for i in range(32):     # same key → same arm, always
            _, label = r.route("t", rows, key=f"user{i}")
            assert label == labels[f"user{i}"]

    def test_canary_promote_and_generation_oracle(self, bundle_ctx):
        s1 = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                           name="gen5")
        s2 = PredictServer(pipeline=_linreg(6.0), buckets=BUCKETS,
                           name="gen6")
        r = ModelRouter()
        r.add_tenant("t", s1)
        rows = np.ones((1, NF), np.float32)
        with r:
            r.set_canary("t", s2, fraction=0.5)     # starts s2 too
            seen = set()
            for i in range(32):
                v = r.predict(rows, "t", key=f"user{i}")
                seen.add(float(v.ravel()[0]) - NF)  # intercept = gen
            assert seen == {5.0, 6.0}   # both generations really served
            r.promote("t")
            for i in range(16):
                v = r.predict(rows, "t", key=f"user{i}")
                assert float(v.ravel()[0]) - NF == 6.0
            assert r.stats()["t"]["promotions"] == 1

    def test_promote_refuses_unadopted_pool_canary(self, bundle_ctx,
                                                   tmp_path):
        s1 = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                           name="ok")
        pool = ModelPool(FitCheckpoint(str(tmp_path / "never"), keep=2),
                         build=lambda s: _linreg(0.0), buckets=BUCKETS)
        s2 = PredictServer(pool=pool, name="hollow")
        r = ModelRouter()
        r.add_tenant("t", s1)
        r._tenants["t"].canary = s2     # bypass set_canary's start
        r._tenants["t"].canary_fraction = 0.5
        with pytest.raises(RuntimeError, match="adoption gate"):
            r.promote("t")
        assert r._tenants["t"].server is s1     # traffic stayed put

    def test_abort_canary_restores_primary(self, bundle_ctx):
        s1 = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS,
                           name="p")
        s2 = PredictServer(pipeline=_linreg(6.0), buckets=BUCKETS,
                           name="c")
        r = ModelRouter()
        r.add_tenant("t", s1)
        r.set_canary("t", s2, fraction=1.0)
        rows = np.ones((1, NF), np.float32)
        assert r.route("t", rows, key="k")[1] == "t:canary"
        r.abort_canary("t")
        assert r.route("t", rows, key="k")[1] == "t"

    def test_unknown_tenant_and_duplicates_are_typed(self, bundle_ctx):
        srv = PredictServer(pipeline=bundle_ctx["pipe"], buckets=BUCKETS)
        r = ModelRouter()
        r.add_tenant("t", srv)
        with pytest.raises(ValueError, match="already registered"):
            r.add_tenant("t", srv)
        with pytest.raises(KeyError, match="unknown tenant"):
            r.submit(np.ones((1, NF), np.float32), "ghost")
        with pytest.raises(TypeError, match="PredictServer"):
            r.add_tenant("u", object())
