"""XLA_FLAGS hygiene lint + the version gate for the collective-timeout
flags (round-6 satellite: the class of bug where an unsupported flag is
injected at import — XLA fatally aborts on unknown flags — must not
recur).

Policy, enforced by scanning the repo's Python sources:

1. the XLA:CPU collective-timeout flag NAMES may be spelled only in
   ``dislib_tpu/runtime/xla_flags.py`` (the one guarded, version-gated
   injection site) — nowhere else, so nothing can reintroduce an
   unguarded injection;
2. ``os.environ["XLA_FLAGS"]`` mutation is allowed only in that module
   plus a short allowlist of test/example bootstrap sites, and those
   sites may set only the universally-supported device-count flag.
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the one module allowed to spell the timeout flag names
GUARDED_SITE = "dislib_tpu/runtime/xla_flags.py"

# bootstrap sites that may mutate XLA_FLAGS directly — each must touch
# ONLY the device-count flag (asserted below); everything else routes
# through runtime.xla_flags
MUTATION_ALLOWLIST = {
    GUARDED_SITE,
    "tests/conftest.py",
    "tests/mp_worker.py",
    "examples/multihost_launch.py",
    # bench smoke children fake a 2-D mesh for the SUMMA tier with
    # virtual host devices (the conftest bootstrap, applied pre-import
    # in the per-config subprocess); device-count flag only
    "bench.py",
    # round-19 two-process dryrun worker: each rank bootstraps 2 virtual
    # CPU devices pre-import (the mp_worker precedent); device-count
    # flag only
    "tools/mh_dryrun.py",
}

_MUTATION = re.compile(
    r"""(environ\s*\[\s*['"]XLA_FLAGS['"]\s*\]\s*=
         |environ\.setdefault\(\s*['"]XLA_FLAGS
         |putenv\(\s*['"]XLA_FLAGS)""", re.VERBOSE)
_TIMEOUT_FLAG = re.compile(r"xla_cpu_collective_call")


def _py_files():
    for root, dirs, files in os.walk(REPO):
        dirs[:] = [d for d in dirs
                   if not d.startswith(".") and d != "__pycache__"]
        for f in files:
            if f.endswith(".py"):
                full = os.path.join(root, f)
                yield os.path.relpath(full, REPO).replace(os.sep, "/"), full


def test_timeout_flag_names_confined_to_guarded_site():
    offenders = []
    for rel, full in _py_files():
        if rel in (GUARDED_SITE, "tests/test_xla_flags_policy.py"):
            continue
        with open(full, encoding="utf-8", errors="replace") as f:
            if _TIMEOUT_FLAG.search(f.read()):
                offenders.append(rel)
    assert not offenders, (
        "the XLA:CPU collective-timeout flags may only be injected by the "
        f"version-gated {GUARDED_SITE} (jaxlib builds that predate them "
        f"abort on unknown flags); found the names in: {offenders}")


def test_xla_flags_mutation_only_at_allowed_sites():
    offenders, allowlisted = [], []
    for rel, full in _py_files():
        if rel == "tests/test_xla_flags_policy.py":
            continue  # this file quotes the forbidden pattern in asserts
        with open(full, encoding="utf-8", errors="replace") as f:
            src = f.read()
        if not _MUTATION.search(src):
            continue
        if rel not in MUTATION_ALLOWLIST:
            offenders.append(rel)
        elif rel != GUARDED_SITE:
            allowlisted.append((rel, src))
    assert not offenders, (
        "XLA_FLAGS mutation outside the allowed sites — route it through "
        f"dislib_tpu.runtime.xla_flags instead: {offenders}")
    for rel, src in allowlisted:
        # bootstrap sites may only set the device-count flag
        flags = set(re.findall(r"--(xla_\w+)", src))
        assert flags <= {"xla_force_host_platform_device_count"}, (
            f"{rel} sets XLA flags other than the device-count bootstrap "
            f"flag ({flags}) — use dislib_tpu.runtime.xla_flags")


class TestVersionGate:
    def test_gate_matches_this_jaxlib(self):
        """On the pinned CI jaxlib (0.4.x) the flags are unsupported and
        must NOT be in this process's XLA_FLAGS; on a jaxlib past the
        threshold the gate opens."""
        from dislib_tpu.runtime import xla_flags as xf
        v = xf._jaxlib_version()
        assert v is not None
        if os.environ.get("DSLIB_XLA_CPU_TIMEOUT_FLAGS") in ("0", "1"):
            pytest.skip("gate explicitly forced via env")
        expect = v >= xf._MIN_JAXLIB_FOR_TIMEOUT_FLAGS
        assert xf.cpu_collective_timeout_flags_supported() == expect
        if not expect:
            assert "xla_cpu_collective_call" not in \
                os.environ.get("XLA_FLAGS", ""), \
                "unsupported timeout flags leaked into XLA_FLAGS"

    def test_force_enable_and_disable(self, monkeypatch):
        from dislib_tpu.runtime import xla_flags as xf
        monkeypatch.setenv("DSLIB_XLA_CPU_TIMEOUT_FLAGS", "1")
        monkeypatch.setenv("XLA_FLAGS", "")
        assert xf.cpu_collective_timeout_flags_supported()
        assert xf.inject_cpu_collective_timeouts()
        flags = os.environ["XLA_FLAGS"]
        assert "terminate_timeout_seconds=600" in flags
        assert "warn_stuck_timeout_seconds=60" in flags
        # idempotent: a second injection appends nothing
        assert xf.inject_cpu_collective_timeouts()
        assert os.environ["XLA_FLAGS"] == flags
        monkeypatch.setenv("DSLIB_XLA_CPU_TIMEOUT_FLAGS", "0")
        monkeypatch.setenv("XLA_FLAGS", "")
        assert not xf.inject_cpu_collective_timeouts()
        assert os.environ["XLA_FLAGS"] == ""

    def test_user_value_wins(self, monkeypatch):
        from dislib_tpu.runtime import xla_flags as xf
        monkeypatch.setenv("DSLIB_XLA_CPU_TIMEOUT_FLAGS", "1")
        monkeypatch.setenv(
            "XLA_FLAGS",
            "--xla_cpu_collective_call_terminate_timeout_seconds=99")
        xf.inject_cpu_collective_timeouts()
        assert "terminate_timeout_seconds=99" in os.environ["XLA_FLAGS"]
        assert "terminate_timeout_seconds=600" not in os.environ["XLA_FLAGS"]

    def test_device_count_helper(self, monkeypatch):
        from dislib_tpu.runtime import xla_flags as xf
        monkeypatch.setenv("XLA_FLAGS", "")
        xf.force_host_platform_device_count(6)
        assert os.environ["XLA_FLAGS"] == \
            "--xla_force_host_platform_device_count=6"
        xf.force_host_platform_device_count(8)   # existing value wins
        assert "=6" in os.environ["XLA_FLAGS"]
