"""Preemption-safe elastic runtime, end to end (SURVEY §6 "Failure
detection / elastic recovery"), driven by the deterministic fault-injection
harness (`dislib_tpu.utils.faults`):

- SIGTERM (or the `DSLIB_PREEMPTION_FILE` sentinel) mid-fit → snapshot
  written at the chunk boundary → clean `Preempted` → resume reproduces
  the uninterrupted fit;
- crash-consistent snapshots: checksum + rotation; a corrupt/truncated/
  foreign newest generation falls back to the previous one (or raises a
  CLEAR error when nothing good remains);
- elastic resume: a checkpoint written on an 8-device mesh restores onto
  a 4-device (or 2-D) mesh with identical final centers/factors;
- the `Retry` policy: transient-vs-fatal classification, deterministic
  backoff, deadline — and its wiring into the ingest loaders, the
  multi-host join, and the host↔device fetch boundary.

Every fault fires on a fixed schedule (save counts, byte positions, call
counts) — no timers, no RNG — so the suite is bit-deterministic on the
8-virtual-device CPU rig.
"""

import builtins
import os
import signal

import jax
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import GaussianMixture, KMeans
from dislib_tpu.recommendation import ALS
from dislib_tpu.runtime import (Preempted, PreemptionWatcher, Retry,
                                clear_preemption, is_transient_error,
                                preemption_requested, repad_rows,
                                request_preemption, retry_call)
from dislib_tpu.utils import FitCheckpoint, faults
from dislib_tpu.utils.checkpoint import SnapshotCorrupt


@pytest.fixture(autouse=True)
def _clean_preemption(monkeypatch):
    """Every test starts and ends with the preemption flag down and no
    sentinel file configured — preemption state must never leak."""
    monkeypatch.delenv("DSLIB_PREEMPTION_FILE", raising=False)
    clear_preemption()
    yield
    clear_preemption()


@pytest.fixture
def fast_retry(monkeypatch):
    """Zero backoff so retry tests don't sleep."""
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")


def _blobs(rng, n=200, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


# ---------------------------------------------------------------------------
# preemption watcher
# ---------------------------------------------------------------------------

class TestPreemptionWatcher:
    def test_sigterm_sets_flag_and_handler_restores(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionWatcher((signal.SIGTERM,)):
            assert not preemption_requested()
            faults.sigterm_self()
            assert preemption_requested()
        assert signal.getsignal(signal.SIGTERM) == before

    def test_sentinel_file_polls(self, tmp_path, monkeypatch):
        flag = tmp_path / "drain"
        monkeypatch.setenv("DSLIB_PREEMPTION_FILE", str(flag))
        assert not preemption_requested()
        flag.touch()
        assert preemption_requested()
        # sticky: the flag stays up even after the file goes away
        flag.unlink()
        assert preemption_requested()
        clear_preemption()
        assert not preemption_requested()

    def test_uncheckpointed_fit_ignores_preemption(self, rng):
        # nothing to snapshot → nothing to raise; the flag is only honoured
        # by checkpointed chunk loops
        request_preemption()
        x = ds.array(_blobs(rng, n=60))
        km = KMeans(n_clusters=2, random_state=0, max_iter=3).fit(x)
        assert np.isfinite(km.centers_).all()

    def test_kmeans_sigterm_snapshot_resume_equals_full(self, rng, tmp_path):
        """The acceptance path: SIGTERM mid-fit → snapshot written → clean
        Preempted → resume reproduces the uninterrupted fit."""
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        full = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(x)

        path = str(tmp_path / "km.npz")
        with PreemptionWatcher((signal.SIGTERM,)):
            with pytest.raises(Preempted) as exc:
                KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                    x, checkpoint=faults.SigtermAtNthSave(path, every=2,
                                                          after=2))
        assert exc.value.checkpoint_path == path
        assert os.path.exists(path), "Preempted raised without a snapshot"
        clear_preemption()

        res = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=2))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_gmm_sentinel_file_snapshot_resume(self, rng, tmp_path,
                                               monkeypatch):
        x = ds.array(_blobs(rng, n=150, d=3, k=2))
        # tol=0: EM never converges early, so the preemption lands with
        # work left — deterministic across rigs
        kw = dict(n_components=2, max_iter=12, tol=0.0, random_state=0)
        full = GaussianMixture(**kw).fit(x)
        flag = tmp_path / "drain"
        monkeypatch.setenv("DSLIB_PREEMPTION_FILE", str(flag))
        path = str(tmp_path / "gm.npz")
        ck = faults.CallbackCheckpoint(path, every=4, after=1,
                                       callback=flag.touch)
        with pytest.raises(Preempted):
            GaussianMixture(**kw).fit(x, checkpoint=ck)
        monkeypatch.delenv("DSLIB_PREEMPTION_FILE")
        clear_preemption()
        res = GaussianMixture(**kw).fit(
            x, checkpoint=FitCheckpoint(path, every=4))
        assert res.n_iter_ == full.n_iter_
        assert res.lower_bound_ == pytest.approx(full.lower_bound_, rel=1e-4)

    def test_csvm_preempt_off_boundary_snapshots_then_resumes(self, rng,
                                                              tmp_path):
        from dislib_tpu.classification import CascadeSVM
        n = 120
        xh = np.vstack([rng.randn(n // 2, 4) - 2,
                        rng.randn(n // 2, 4) + 2]).astype(np.float32)
        yh = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
        sh = rng.permutation(n)
        x, y = ds.array(xh[sh]), ds.array(yh[sh].reshape(-1, 1))
        kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
                  check_convergence=False)
        full = CascadeSVM(max_iter=4, **kw).fit(x, y)

        path = str(tmp_path / "csvm.npz")
        # every=10 puts NO periodic snapshot inside a 4-iteration fit — the
        # preemption path must write its own off-boundary snapshot
        request_preemption()
        with pytest.raises(Preempted):
            CascadeSVM(max_iter=4, **kw).fit(
                x, y, checkpoint=FitCheckpoint(path, every=10))
        assert os.path.exists(path)
        clear_preemption()
        res = CascadeSVM(max_iter=4, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=10))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_array_equal(res._sv_idx, full._sv_idx)
        np.testing.assert_allclose(res._sv_alpha, full._sv_alpha, rtol=1e-5)

    def test_forest_preempt_between_levels_resumes_identical(self, rng,
                                                             tmp_path):
        from dislib_tpu.trees import RandomForestClassifier
        n, k = 240, 3
        centers = rng.rand(k, 6) * 8
        xh = np.vstack([centers[i] + 0.4 * rng.randn(n // k, 6)
                        for i in range(k)]).astype(np.float32)
        yh = np.repeat(np.arange(k), n // k).astype(np.float32)
        p = rng.permutation(n)
        x, y = ds.array(xh[p]), ds.array(yh[p].reshape(-1, 1))
        kw = dict(n_estimators=4, max_depth=6, random_state=7)
        full = RandomForestClassifier(**kw).fit(x, y)

        path = str(tmp_path / "rf.npz")
        # snapshot every 2 levels; preemption requested right after the
        # first snapshot → raise at the NEXT level boundary, off-schedule
        ck = faults.CallbackCheckpoint(path, every=2, after=1,
                                       callback=request_preemption)
        with pytest.raises(Preempted):
            RandomForestClassifier(**kw).fit(x, y, checkpoint=ck)
        clear_preemption()
        res = RandomForestClassifier(**kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=2))
        np.testing.assert_array_equal(res.predict(x).collect(),
                                      full.predict(x).collect())


# ---------------------------------------------------------------------------
# crash-consistent snapshots: checksum, rotation, fallback
# ---------------------------------------------------------------------------

class TestSnapshotIntegrity:
    def test_rotation_keeps_last_k(self, tmp_path):
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=2)
        for i in range(5):
            ck.save({"gen": np.asarray([i])})
        files = sorted(os.listdir(tmp_path))
        assert files == ["s.npz", "s.npz.1"]
        assert int(ck.load()["gen"][0]) == 4
        assert int(
            np.load(path + ".1", allow_pickle=False)["gen"][0]) == 3
        ck.delete()
        assert os.listdir(tmp_path) == [] and ck.load() is None

    @pytest.mark.parametrize("mode", ["flip", "truncate", "foreign"])
    def test_corrupt_newest_falls_back_to_previous(self, tmp_path, mode):
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=2)
        ck.save({"gen": np.asarray([0]), "a": np.arange(64.0)})
        ck.save({"gen": np.asarray([1]), "a": np.arange(64.0) * 2})
        faults.corrupt_snapshot(path, mode=mode)
        with pytest.warns(RuntimeWarning, match="falling back"):
            state = ck.load()
        assert int(state["gen"][0]) == 0
        # the corrupt newest generation is purged on fallback, so the next
        # save can never rotate it over the good one — a crash mid-save
        # must still leave the good generation on disk
        assert not os.path.exists(path)
        ck.save({"gen": np.asarray([2])})
        assert int(np.load(path + ".1",
                           allow_pickle=False)["gen"][0]) == 0

    @pytest.mark.parametrize("mode,match", [
        ("flip", "checksum|truncated or corrupt"),
        ("truncate", "truncated or corrupt"),
        ("foreign", "integrity record"),
    ])
    def test_all_generations_bad_raises_clear_error(self, tmp_path, mode,
                                                    match):
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=1)
        ck.save({"a": np.arange(64.0)})
        faults.corrupt_snapshot(path, mode=mode)
        # the per-generation diagnosis is specific...
        from dislib_tpu.utils.checkpoint import _load_verified
        with pytest.raises(SnapshotCorrupt, match=match):
            _load_verified(path)
        # ...and the aggregate load() error says what to do about it
        with pytest.raises(SnapshotCorrupt, match="delete the file"):
            ck.load()

    def test_missing_newest_uses_older_generation(self, tmp_path):
        # crash window between the rotation renames: path gone, path.1 good
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=2)
        ck.save({"gen": np.asarray([0])})
        ck.save({"gen": np.asarray([1])})
        os.remove(path)
        assert int(ck.load()["gen"][0]) == 0

    def test_failed_save_leaks_no_staging_file(self, tmp_path, monkeypatch):
        path = str(tmp_path / "s.npz")
        ck = FitCheckpoint(path, every=1, keep=2)
        ck.save({"a": np.arange(4)})

        def boom(*a, **k):
            raise OSError(5, "injected write failure")
        monkeypatch.setattr(np, "savez", boom)
        with pytest.raises(OSError):
            ck.save({"a": np.arange(8)})
        monkeypatch.undo()
        assert sorted(os.listdir(tmp_path)) == ["s.npz"], \
            "mkstemp staging file leaked on a failed save"
        assert np.array_equal(ck.load()["a"], np.arange(4)), \
            "failed save clobbered the previous snapshot"

    def test_reserved_key_refused(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "s.npz"))
        with pytest.raises(ValueError, match="reserved"):
            ck.save({"_dslib_crc32": np.zeros(1)})

    def test_bad_keep_refused(self, tmp_path):
        with pytest.raises(ValueError, match="keep"):
            FitCheckpoint(str(tmp_path / "s.npz"), keep=0)

    def test_kmeans_resumes_from_older_generation_after_corruption(
            self, rng, tmp_path):
        """Acceptance: corrupt newest snapshot → fallback to the previous
        generation → the resumed fit still lands on the uninterrupted
        result (it just redoes one chunk)."""
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        full = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(x)

        path = str(tmp_path / "km.npz")
        KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=3, keep=2))
        assert os.path.exists(path) and os.path.exists(path + ".1")
        faults.corrupt_snapshot(path, mode="truncate")
        with pytest.warns(RuntimeWarning, match="falling back"):
            res = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                x, checkpoint=FitCheckpoint(path, every=3, keep=2))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)


# ---------------------------------------------------------------------------
# elastic resume: restore onto a different mesh
# ---------------------------------------------------------------------------

class TestElasticResume:
    def test_repad_rows_unit(self):
        a = np.arange(12.0).reshape(6, 2)
        out = repad_rows(a, 4, 8)
        assert out.shape == (8, 2)
        np.testing.assert_array_equal(out[:4], a[:4])
        assert (out[4:] == 0).all()
        np.testing.assert_array_equal(repad_rows(a, 6, 6), a)
        out = repad_rows(a.T, 4, 5, axis=1)
        assert out.shape == (2, 5) and (out[:, 4:] == 0).all()
        with pytest.raises(ValueError, match="stale or foreign"):
            repad_rows(a, 10, 12)
        with pytest.raises(ValueError, match="smaller than the logical"):
            repad_rows(a, 4, 2)

    def test_kmeans_8dev_checkpoint_resumes_on_4dev(self, rng, tmp_path):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        devs = jax.devices()
        x_np = _blobs(rng)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])

        ds.init((8, 1), devices=devs[:8])
        x8 = ds.array(x_np)
        full = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(x8)
        path = str(tmp_path / "km.npz")
        KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0).fit(
            x8, checkpoint=FitCheckpoint(path, every=3))

        ds.init((4, 1), devices=devs[:4])       # half the fleet survives
        x4 = ds.array(x_np)
        res = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
            x4, checkpoint=FitCheckpoint(path, every=3))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_,
                                   rtol=1e-4, atol=1e-5)

    def test_als_8dev_checkpoint_resumes_on_2x2(self, rng, tmp_path):
        """Dense ALS stores mesh-PADDED factors — the elastic path re-pads
        them for the restoring mesh (8×1 quantum 8 → 2×2 quantum 2)."""
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        devs = jax.devices()
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)

        ds.init((8, 1), devices=devs[:8])
        x8 = ds.array(r)
        full = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(x8)
        path = str(tmp_path / "als.npz")
        ALS(n_f=4, max_iter=6, tol=1e-7, random_state=0).fit(
            x8, checkpoint=FitCheckpoint(path, every=3))

        ds.init((2, 2), devices=devs[:4])       # different COUNT and SHAPE
        x4 = ds.array(r)
        res = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(
            x4, checkpoint=FitCheckpoint(path, every=3))
        assert res.rmse_ == pytest.approx(full.rmse_, abs=1e-4)
        np.testing.assert_allclose(res.users_, full.users_,
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(res.items_, full.items_,
                                   rtol=1e-3, atol=1e-4)

    def test_forest_8dev_checkpoint_resumes_on_4dev(self, rng, tmp_path):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        from dislib_tpu.trees import RandomForestClassifier
        devs = jax.devices()
        n, k = 240, 3
        centers = rng.rand(k, 6) * 8
        xh = np.vstack([centers[i] + 0.4 * rng.randn(n // k, 6)
                        for i in range(k)]).astype(np.float32)
        yh = np.repeat(np.arange(k), n // k).astype(np.float32).reshape(-1, 1)
        kw = dict(n_estimators=4, max_depth=6, random_state=7)

        ds.init((8, 1), devices=devs[:8])
        x8, y8 = ds.array(xh), ds.array(yh)
        full = RandomForestClassifier(**kw).fit(x8, y8)
        path = str(tmp_path / "rf.npz")
        ck = faults.CallbackCheckpoint(path, every=2, after=1,
                                       callback=request_preemption)
        with pytest.raises(Preempted):
            RandomForestClassifier(**kw).fit(x8, y8, checkpoint=ck)
        clear_preemption()

        ds.init((4, 1), devices=devs[:4])
        x4, y4 = ds.array(xh), ds.array(yh)
        res = RandomForestClassifier(**kw).fit(
            x4, y4, checkpoint=FitCheckpoint(path, every=2))
        np.testing.assert_array_equal(res.predict(x4).collect(),
                                      full.predict(x8).collect())

    def test_als_stale_snapshot_still_refused(self, rng, tmp_path):
        x = ds.array((rng.rand(30, 20) * (rng.rand(30, 20) < 0.6))
                     .astype(np.float32))
        path = str(tmp_path / "als.npz")
        ALS(n_f=4, max_iter=4, random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=2))
        other = ds.array((rng.rand(24, 20) * (rng.rand(24, 20) < 0.6))
                         .astype(np.float32))
        with pytest.raises(ValueError, match="stale or foreign"):
            ALS(n_f=4, max_iter=4, random_state=0).fit(
                other, checkpoint=FitCheckpoint(path, every=2))
        with pytest.raises(ValueError, match="stale or foreign"):
            ALS(n_f=8, max_iter=4, random_state=0).fit(
                x, checkpoint=FitCheckpoint(path, every=2))


# ---------------------------------------------------------------------------
# the Retry policy
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_transient_retries_then_succeeds(self):
        flaky = faults.FlakyCall(lambda: 42, failures=2)
        assert Retry(attempts=5, backoff=0, jitter=0).call(flaky) == 42
        assert flaky.calls == 3

    def test_fatal_not_retried(self):
        flaky = faults.FlakyCall(lambda: 42, failures=3,
                                 exc_factory=lambda: ValueError("bad shape"))
        with pytest.raises(ValueError):
            Retry(attempts=5, backoff=0).call(flaky)
        assert flaky.calls == 1

    def test_attempts_exhausted_reraises_last(self):
        flaky = faults.FlakyCall(lambda: 42, failures=10)
        with pytest.raises(ConnectionResetError):
            Retry(attempts=3, backoff=0).call(flaky)
        assert flaky.calls == 3

    def test_backoff_schedule_deterministic(self):
        delays = []

        def run(seed):
            delays.clear()
            flaky = faults.FlakyCall(lambda: 0, failures=3)
            Retry(attempts=4, backoff=0.5, jitter=0.25, seed=seed,
                  sleep=delays.append).call(flaky)
            return list(delays)
        a, b = run(7), run(7)
        assert a == b and len(a) == 3, "seeded jitter must be reproducible"
        # exponential base under the jitter envelope
        assert 0.5 <= a[0] <= 0.625 and 1.0 <= a[1] <= 1.25 \
            and 2.0 <= a[2] <= 2.5
        assert run(8) != a, "different seed, different jitter"

    def test_deadline_stops_retrying(self):
        slept = []
        flaky = faults.FlakyCall(lambda: 0, failures=10)
        with pytest.raises(ConnectionResetError):
            Retry(attempts=10, backoff=10.0, jitter=0, deadline=5.0,
                  sleep=slept.append).call(flaky)
        assert flaky.calls == 1 and slept == [], \
            "a sleep that would overrun the deadline must not happen"

    def test_classifier_override(self):
        flaky = faults.FlakyCall(lambda: 42, failures=1,
                                 exc_factory=lambda: ValueError("flaky"))
        got = Retry(attempts=3, backoff=0,
                    classify=lambda e: isinstance(e, ValueError)).call(flaky)
        assert got == 42 and flaky.calls == 2

    def test_default_classification(self):
        assert is_transient_error(
            RuntimeError("UNAVAILABLE: failed to connect to all addresses"))
        assert is_transient_error(RuntimeError("Deadline Exceeded"))
        assert is_transient_error(OSError(5, "I/O error"))
        assert is_transient_error(ConnectionResetError())
        assert not is_transient_error(FileNotFoundError("gone"))
        assert not is_transient_error(ValueError("shape mismatch"))
        assert not is_transient_error(RuntimeError("singular matrix"))
        assert not is_transient_error(Preempted("draining"))
        assert not is_transient_error(KeyboardInterrupt())

    def test_from_env_overrides(self, monkeypatch):
        monkeypatch.setenv("DSLIB_RETRY_ATTEMPTS", "7")
        monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0.125")
        monkeypatch.setenv("DSLIB_RETRY_DEADLINE", "9.5")
        r = Retry.from_env(attempts=2)
        assert r.attempts == 7 and r.backoff == 0.125 and r.deadline == 9.5

    def test_retry_call_convenience(self, fast_retry):
        flaky = faults.FlakyCall(lambda: "ok", failures=1)
        assert retry_call(flaky) == "ok"
        assert flaky.calls == 2

    def test_bad_attempts(self):
        with pytest.raises(ValueError):
            Retry(attempts=0)


# ---------------------------------------------------------------------------
# Retry wiring: ingest IO, multi-host join, host↔device fetch
# ---------------------------------------------------------------------------

class TestRetryWiring:
    def test_load_txt_survives_flaky_reads(self, rng, tmp_path, monkeypatch,
                                           fast_retry):
        x = rng.rand(16, 3).astype(np.float32)
        p = str(tmp_path / "a.csv")
        np.savetxt(p, x, delimiter=",")
        flaky = faults.FlakyOpen(p, failures=2)
        monkeypatch.setattr(builtins, "open", flaky)
        got = ds.load_txt_file(p)
        assert flaky.fails == 2
        np.testing.assert_allclose(np.asarray(got.collect()), x, rtol=1e-5)

    def test_load_txt_persistent_failure_raises(self, rng, tmp_path,
                                                monkeypatch, fast_retry):
        p = str(tmp_path / "a.csv")
        np.savetxt(p, rng.rand(4, 2), delimiter=",")
        flaky = faults.FlakyOpen(p, failures=100)
        monkeypatch.setattr(builtins, "open", flaky)
        with pytest.raises(OSError, match="injected flaky read"):
            ds.load_txt_file(p)
        assert flaky.fails == 3, "default IO policy is 3 attempts"

    def test_load_missing_file_fails_fast(self, tmp_path, fast_retry):
        # FileNotFoundError is fatal — one attempt, no backoff burned
        with pytest.raises(FileNotFoundError):
            ds.load_npy_file(str(tmp_path / "nope.npy"))

    def test_distributed_initialize_retries_coordinator(self, monkeypatch,
                                                        fast_retry):
        from dislib_tpu.parallel import distributed
        flaky = faults.FlakyCall(
            lambda **kw: None, failures=2,
            exc_factory=lambda: RuntimeError(
                "UNAVAILABLE: failed to connect to all addresses"))
        monkeypatch.setattr(jax.distributed, "initialize", flaky)
        monkeypatch.setattr(distributed, "_initialized", False)
        distributed.initialize(coordinator_address="127.0.0.1:1",
                               num_processes=1, process_id=0)
        assert flaky.calls == 3
        assert distributed.is_initialized()

    def test_distributed_initialize_fatal_config_error(self, monkeypatch,
                                                       fast_retry):
        from dislib_tpu.parallel import distributed
        flaky = faults.FlakyCall(
            lambda **kw: None, failures=5,
            exc_factory=lambda: ValueError("process_id must be set"))
        monkeypatch.setattr(jax.distributed, "initialize", flaky)
        monkeypatch.setattr(distributed, "_initialized", False)
        with pytest.raises(ValueError):
            distributed.initialize(coordinator_address="127.0.0.1:1",
                                   num_processes=2, process_id=0)
        assert flaky.calls == 1 and not distributed.is_initialized()

    def test_fetch_retries_device_get(self, monkeypatch, fast_retry):
        from dislib_tpu import runtime
        real = jax.device_get
        flaky = faults.FlakyCall(real, failures=1)
        monkeypatch.setattr(jax, "device_get", flaky)
        out = runtime.fetch(np.arange(3.0))
        np.testing.assert_array_equal(out, np.arange(3.0))
        assert flaky.calls == 2
