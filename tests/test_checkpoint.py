"""Mid-fit checkpoint / kill+resume fault-injection tests (SURVEY.md §6
"Failure detection / elastic recovery": kill a fit mid-way, resume from the
snapshot, assert equivalence with an uninterrupted fit).

The "kill" is simulated by running a fit whose max_iter stops it mid-way
(the snapshot is what a preempted job would have on disk), then resuming
with a fresh estimator pointed at the same checkpoint."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans, GaussianMixture
from dislib_tpu.recommendation import ALS
from dislib_tpu.utils import FitCheckpoint


def _blobs(rng, n=200, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


class TestFitCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "s.npz"), every=2)
        assert ck.load() is None
        ck.save({"a": np.arange(5), "n": 3})
        st = ck.load()
        assert np.array_equal(st["a"], np.arange(5)) and int(st["n"]) == 3
        ck.delete()
        assert ck.load() is None

    def test_bad_every(self, tmp_path):
        with pytest.raises(ValueError):
            FitCheckpoint(str(tmp_path / "s.npz"), every=0)


class TestKillResume:
    def test_kmeans_resume_equals_full(self, rng, tmp_path):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        full = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(x)

        path = str(tmp_path / "km.npz")
        # "killed" run: stops after 6 iterations, snapshot on disk
        KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        # resume to completion with a fresh estimator
        res = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_kmeans_checkpointed_equals_plain(self, rng, tmp_path):
        x_np = _blobs(rng, n=120)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 40, 80]])
        plain = KMeans(n_clusters=3, init=init, max_iter=10, tol=1e-4).fit(x)
        ck = KMeans(n_clusters=3, init=init, max_iter=10, tol=1e-4).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k2.npz"), every=2))
        np.testing.assert_allclose(ck.centers_, plain.centers_, rtol=1e-5)

    def test_gmm_resume_converges_same(self, rng, tmp_path):
        x = ds.array(_blobs(rng, n=150, d=3, k=2))
        full = GaussianMixture(n_components=2, max_iter=40, tol=1e-6,
                               random_state=0).fit(x)
        path = str(tmp_path / "gm.npz")
        GaussianMixture(n_components=2, max_iter=10, tol=1e-6,
                        random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=5))
        res = GaussianMixture(n_components=2, max_iter=40, tol=1e-6,
                              random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=5))
        assert res.converged_
        assert res.lower_bound_ == pytest.approx(full.lower_bound_, rel=1e-4)
        np.testing.assert_allclose(np.sort(res.means_, axis=0),
                                   np.sort(full.means_, axis=0), atol=1e-2)

    def test_als_resume_converges_same(self, rng, tmp_path):
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = (u @ v.T) * (rng.rand(30, 20) < 0.6)
        x = ds.array(r.astype(np.float32))
        full = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(x)
        path = str(tmp_path / "als.npz")
        ALS(n_f=4, max_iter=6, tol=1e-7, random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        res = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        assert res.rmse_ == pytest.approx(full.rmse_, abs=1e-4)


class TestProfiling:
    def test_annotate_and_op_graph(self, rng):
        import jax.numpy as jnp
        from dislib_tpu.utils import annotate, op_graph
        with annotate("phase"):
            pass
        txt = op_graph(lambda a: a @ a, jnp.ones((8, 8)))
        assert "dot" in txt or "fusion" in txt

    def test_trace_writes_files(self, rng, tmp_path):
        import jax.numpy as jnp
        from dislib_tpu.utils import trace
        d = str(tmp_path / "tb")
        with trace(d):
            (jnp.ones((16, 16)) @ jnp.ones((16, 16))).block_until_ready()
        import os
        found = [f for _, _, fs in os.walk(d) for f in fs]
        assert found, "profiler wrote no trace files"


class TestCSVMCheckpoint:
    """Round-3 widening: CascadeSVM global-iteration snapshot/resume."""

    def _data(self, rng, n=120):
        x = np.vstack([rng.randn(n // 2, 4) - 2,
                       rng.randn(n // 2, 4) + 2]).astype(np.float32)
        y = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
        sh = rng.permutation(n)
        return x[sh], y[sh].reshape(-1, 1)

    def test_csvm_resume_equals_full(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng)
        x, y = ds.array(xh), ds.array(yh)
        kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
                  check_convergence=False)
        full = CascadeSVM(max_iter=4, **kw).fit(x, y)

        path = str(tmp_path / "csvm.npz")
        CascadeSVM(max_iter=2, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        res = CascadeSVM(max_iter=4, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_array_equal(res._sv_idx, full._sv_idx)
        np.testing.assert_allclose(res._sv_alpha, full._sv_alpha, rtol=1e-5)
        np.testing.assert_allclose(res.decision_function(x).collect(),
                                   full.decision_function(x).collect(),
                                   rtol=1e-4, atol=1e-5)

    def test_csvm_resume_of_converged_fit(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        x, y = ds.array(xh), ds.array(yh)
        path = str(tmp_path / "csvm2.npz")
        kw = dict(cascade_arity=2, kernel="linear", check_convergence=True,
                  tol=1e-2)
        first = CascadeSVM(max_iter=8, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert first.converged_
        again = CascadeSVM(max_iter=8, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert again.converged_
        np.testing.assert_array_equal(again._sv_idx, first._sv_idx)

    def test_csvm_stale_checkpoint_raises(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        path = str(tmp_path / "csvm3.npz")
        CascadeSVM(max_iter=1, check_convergence=False).fit(
            ds.array(xh), ds.array(yh),
            checkpoint=FitCheckpoint(path, every=1))
        xs, ys = self._data(rng, n=40)
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, check_convergence=False).fit(
                ds.array(xs), ds.array(ys),
                checkpoint=FitCheckpoint(path, every=1))
        # same data shape but different hyperparameters must refuse too
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, c=100.0, check_convergence=False).fit(
                ds.array(xh), ds.array(yh),
                checkpoint=FitCheckpoint(path, every=1))
        # same shape AND hyperparameters but different data content too
        xo, yo = self._data(np.random.RandomState(99), n=80)
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, check_convergence=False).fit(
                ds.array(xo), ds.array(yo),
                checkpoint=FitCheckpoint(path, every=1))

    def test_csvm_resume_without_convergence_check_runs_on(self, rng,
                                                           tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        x, y = ds.array(xh), ds.array(yh)
        path = str(tmp_path / "csvm4.npz")
        kw = dict(cascade_arity=2, kernel="linear")
        first = CascadeSVM(max_iter=8, check_convergence=True, tol=1e-2,
                           **kw).fit(x, y,
                                     checkpoint=FitCheckpoint(path, every=1))
        assert first.converged_ and first.n_iter_ < 8
        # converged snapshot + check_convergence=False → keep iterating
        more = CascadeSVM(max_iter=first.n_iter_ + 2,
                          check_convergence=False, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert more.n_iter_ == first.n_iter_ + 2
        assert not more.converged_
