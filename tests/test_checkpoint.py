"""Mid-fit checkpoint / kill+resume fault-injection tests (SURVEY.md §6
"Failure detection / elastic recovery": kill a fit mid-way, resume from the
snapshot, assert equivalence with an uninterrupted fit).

The "kill" is simulated by running a fit whose max_iter stops it mid-way
(the snapshot is what a preempted job would have on disk), then resuming
with a fresh estimator pointed at the same checkpoint."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans, GaussianMixture
from dislib_tpu.recommendation import ALS
from dislib_tpu.utils import FitCheckpoint


def _blobs(rng, n=200, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


class TestFitCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "s.npz"), every=2)
        assert ck.load() is None
        ck.save({"a": np.arange(5), "n": 3})
        st = ck.load()
        assert np.array_equal(st["a"], np.arange(5)) and int(st["n"]) == 3
        ck.delete()
        assert ck.load() is None

    def test_bad_every(self, tmp_path):
        with pytest.raises(ValueError):
            FitCheckpoint(str(tmp_path / "s.npz"), every=0)

    def test_digest_version_messages(self, rng):
        """The validate_snapshot refusal message distinguishes an
        OLD-FORMAT digest (v1: unversioned, shorter — 'different library
        version') from a same-version mismatch ('stale or foreign'),
        including the cross-estimator length-mismatch case which must NOT
        claim a version change."""
        import jax.numpy as jnp
        from dislib_tpu.utils.checkpoint import (data_digest,
                                                 validate_snapshot)
        xp = jnp.asarray(rng.rand(100, 3), jnp.float32)
        fp = np.asarray([1.0])
        digest = data_digest(xp)               # v2: [version, sum, wsum]
        # v1-style snapshot: same sums, no version element
        with pytest.raises(ValueError, match="different library version"):
            validate_snapshot({"fp": fp, "digest": digest[1:]}, fp, digest)
        # cross-estimator: v2 with-stats (5 elts) vs v2 without (3 elts)
        d_stats = data_digest(xp, stats=rng.rand(100, 2))
        with pytest.raises(ValueError, match="stale or foreign"):
            validate_snapshot({"fp": fp, "digest": digest}, fp, d_stats)
        # empty digest array must not crash the heuristic
        with pytest.raises(ValueError, match="different library version"):
            validate_snapshot({"fp": fp, "digest": np.zeros(0)}, fp, digest)
        # matching v2 snapshot passes
        validate_snapshot({"fp": fp, "digest": digest}, fp, digest)


class TestKillResume:
    def test_kmeans_resume_equals_full(self, rng, tmp_path):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        full = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(x)

        path = str(tmp_path / "km.npz")
        # "killed" run: stops after 6 iterations, snapshot on disk
        KMeans(n_clusters=3, init=init, max_iter=6, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        # resume to completion with a fresh estimator
        res = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_kmeans_checkpointed_equals_plain(self, rng, tmp_path):
        x_np = _blobs(rng, n=120)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 40, 80]])
        plain = KMeans(n_clusters=3, init=init, max_iter=10, tol=1e-4).fit(x)
        ck = KMeans(n_clusters=3, init=init, max_iter=10, tol=1e-4).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k2.npz"), every=2))
        np.testing.assert_allclose(ck.centers_, plain.centers_, rtol=1e-5)

    def test_gmm_resume_converges_same(self, rng, tmp_path):
        x = ds.array(_blobs(rng, n=150, d=3, k=2))
        full = GaussianMixture(n_components=2, max_iter=40, tol=1e-6,
                               random_state=0).fit(x)
        path = str(tmp_path / "gm.npz")
        GaussianMixture(n_components=2, max_iter=10, tol=1e-6,
                        random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=5))
        res = GaussianMixture(n_components=2, max_iter=40, tol=1e-6,
                              random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=5))
        assert res.converged_
        assert res.lower_bound_ == pytest.approx(full.lower_bound_, rel=1e-4)
        np.testing.assert_allclose(np.sort(res.means_, axis=0),
                                   np.sort(full.means_, axis=0), atol=1e-2)

    def test_als_resume_converges_same(self, rng, tmp_path):
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = (u @ v.T) * (rng.rand(30, 20) < 0.6)
        x = ds.array(r.astype(np.float32))
        full = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(x)
        path = str(tmp_path / "als.npz")
        ALS(n_f=4, max_iter=6, tol=1e-7, random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        res = ALS(n_f=4, max_iter=20, tol=1e-7, random_state=0).fit(
            x, checkpoint=FitCheckpoint(path, every=3))
        assert res.rmse_ == pytest.approx(full.rmse_, abs=1e-4)


class TestProfiling:
    def test_annotate_and_op_graph(self, rng):
        import jax.numpy as jnp
        from dislib_tpu.utils import annotate, op_graph
        with annotate("phase"):
            pass
        txt = op_graph(lambda a: a @ a, jnp.ones((8, 8)))
        assert "dot" in txt or "fusion" in txt

    def test_trace_writes_files(self, rng, tmp_path):
        import jax.numpy as jnp
        from dislib_tpu.utils import trace
        d = str(tmp_path / "tb")
        with trace(d):
            (jnp.ones((16, 16)) @ jnp.ones((16, 16))).block_until_ready()
        import os
        found = [f for _, _, fs in os.walk(d) for f in fs]
        assert found, "profiler wrote no trace files"


class TestCSVMCheckpoint:
    """Round-3 widening: CascadeSVM global-iteration snapshot/resume."""

    def _data(self, rng, n=120):
        x = np.vstack([rng.randn(n // 2, 4) - 2,
                       rng.randn(n // 2, 4) + 2]).astype(np.float32)
        y = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
        sh = rng.permutation(n)
        return x[sh], y[sh].reshape(-1, 1)

    def test_csvm_resume_equals_full(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng)
        x, y = ds.array(xh), ds.array(yh)
        kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
                  check_convergence=False)
        full = CascadeSVM(max_iter=4, **kw).fit(x, y)

        path = str(tmp_path / "csvm.npz")
        CascadeSVM(max_iter=2, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        res = CascadeSVM(max_iter=4, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_array_equal(res._sv_idx, full._sv_idx)
        np.testing.assert_allclose(res._sv_alpha, full._sv_alpha, rtol=1e-5)
        np.testing.assert_allclose(res.decision_function(x).collect(),
                                   full.decision_function(x).collect(),
                                   rtol=1e-4, atol=1e-5)

    def test_csvm_resume_of_converged_fit(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        x, y = ds.array(xh), ds.array(yh)
        path = str(tmp_path / "csvm2.npz")
        kw = dict(cascade_arity=2, kernel="linear", check_convergence=True,
                  tol=1e-2)
        first = CascadeSVM(max_iter=8, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert first.converged_
        again = CascadeSVM(max_iter=8, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert again.converged_
        np.testing.assert_array_equal(again._sv_idx, first._sv_idx)

    def test_csvm_stale_checkpoint_raises(self, rng, tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        path = str(tmp_path / "csvm3.npz")
        CascadeSVM(max_iter=1, check_convergence=False).fit(
            ds.array(xh), ds.array(yh),
            checkpoint=FitCheckpoint(path, every=1))
        xs, ys = self._data(rng, n=40)
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, check_convergence=False).fit(
                ds.array(xs), ds.array(ys),
                checkpoint=FitCheckpoint(path, every=1))
        # same data shape but different hyperparameters must refuse too
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, c=100.0, check_convergence=False).fit(
                ds.array(xh), ds.array(yh),
                checkpoint=FitCheckpoint(path, every=1))
        # same shape AND hyperparameters but different data content too
        xo, yo = self._data(np.random.RandomState(99), n=80)
        with pytest.raises(ValueError, match="stale or foreign"):
            CascadeSVM(max_iter=2, check_convergence=False).fit(
                ds.array(xo), ds.array(yo),
                checkpoint=FitCheckpoint(path, every=1))

    def test_csvm_resume_without_convergence_check_runs_on(self, rng,
                                                           tmp_path):
        from dislib_tpu.classification import CascadeSVM
        xh, yh = self._data(rng, n=80)
        x, y = ds.array(xh), ds.array(yh)
        path = str(tmp_path / "csvm4.npz")
        kw = dict(cascade_arity=2, kernel="linear")
        first = CascadeSVM(max_iter=8, check_convergence=True, tol=1e-2,
                           **kw).fit(x, y,
                                     checkpoint=FitCheckpoint(path, every=1))
        assert first.converged_ and first.n_iter_ < 8
        # converged snapshot + check_convergence=False → keep iterating
        more = CascadeSVM(max_iter=first.n_iter_ + 2,
                          check_convergence=False, **kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=1))
        assert more.n_iter_ == first.n_iter_ + 2
        assert not more.converged_


class _KillAfter(FitCheckpoint):
    """Fault injection: dies (KeyboardInterrupt) right AFTER the n-th
    snapshot hits disk — the state a preempted job leaves behind."""

    def __init__(self, path, every=1, kill_after=1):
        super().__init__(path, every=every)
        self._left = kill_after

    def save(self, state):
        super().save(state)
        self._left -= 1
        if self._left == 0:
            raise KeyboardInterrupt("injected kill after snapshot")


class TestForestCheckpoint:
    """Round-4 widening: per-LEVEL snapshots of level-synchronous forest
    growth (verdict #7)."""

    def _data(self, rng, n=240, d=6, k=3):
        centers = rng.rand(k, d) * 8
        x = np.vstack([centers[i] + 0.4 * rng.randn(n // k, d)
                       for i in range(k)]).astype(np.float32)
        y = np.repeat(np.arange(k), n // k).astype(np.float32)
        p = rng.permutation(n)
        return x[p], y[p].reshape(-1, 1)

    def test_forest_kill_resume_equals_full(self, rng, tmp_path):
        from dislib_tpu.trees import RandomForestClassifier
        xh, yh = self._data(rng)
        x, y = ds.array(xh), ds.array(yh)
        kw = dict(n_estimators=4, max_depth=6, random_state=7)
        full = RandomForestClassifier(**kw).fit(x, y)

        path = str(tmp_path / "rf.npz")
        with pytest.raises(KeyboardInterrupt):
            RandomForestClassifier(**kw).fit(
                x, y, checkpoint=_KillAfter(path, every=2, kill_after=1))
        import os
        assert os.path.exists(path), "kill landed before any snapshot"
        res = RandomForestClassifier(**kw).fit(
            x, y, checkpoint=FitCheckpoint(path, every=2))
        np.testing.assert_array_equal(np.asarray(res._feats),
                                      np.asarray(full._feats))
        np.testing.assert_allclose(np.asarray(res._tbins),
                                   np.asarray(full._tbins), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(res._leaves),
                                   np.asarray(full._leaves), rtol=1e-5)
        np.testing.assert_array_equal(res.predict(x).collect(),
                                      full.predict(x).collect())

    def test_forest_regressor_checkpointed_equals_plain(self, rng, tmp_path):
        from dislib_tpu.trees import RandomForestRegressor
        xh, _ = self._data(rng, n=180)
        yh = (xh[:, 0] * 2 - xh[:, 1]).astype(np.float32).reshape(-1, 1)
        x, y = ds.array(xh), ds.array(yh)
        kw = dict(n_estimators=3, max_depth=5, random_state=3)
        plain = RandomForestRegressor(**kw).fit(x, y)
        ck = RandomForestRegressor(**kw).fit(
            x, y, checkpoint=FitCheckpoint(str(tmp_path / "rfr.npz"),
                                           every=1))
        np.testing.assert_allclose(ck.predict(x).collect(),
                                   plain.predict(x).collect(), rtol=1e-5)

    def test_forest_stale_checkpoint_raises(self, rng, tmp_path):
        from dislib_tpu.trees import RandomForestClassifier
        xh, yh = self._data(rng, n=120)
        path = str(tmp_path / "rf2.npz")
        with pytest.raises(KeyboardInterrupt):
            RandomForestClassifier(n_estimators=3, random_state=0).fit(
                ds.array(xh), ds.array(yh),
                checkpoint=_KillAfter(path, every=1, kill_after=1))
        xo, yo = self._data(np.random.RandomState(5), n=120)
        with pytest.raises(ValueError, match="stale or foreign"):
            RandomForestClassifier(n_estimators=3, random_state=0).fit(
                ds.array(xo), ds.array(yo),
                checkpoint=FitCheckpoint(path, every=1))


class TestTiledPassCheckpoint:
    """Round-4 widening: per-pass snapshots of the tiled quadratic
    estimators (verdict #7) — DBSCAN propagation rounds, Daura cluster
    extractions."""

    def _blobs3(self, rng, n=90):
        c = np.asarray([[0, 0], [6, 6], [12, 0]], np.float32)
        x = np.vstack([c[i] + 0.3 * rng.randn(n // 3, 2) for i in range(3)])
        return x.astype(np.float32)

    def test_dbscan_kill_resume_equals_plain(self, rng, tmp_path):
        from dislib_tpu.cluster import DBSCAN
        x = ds.array(self._blobs3(rng))
        plain = DBSCAN(eps=1.0, min_samples=4).fit(x)

        path = str(tmp_path / "db.npz")
        with pytest.raises(KeyboardInterrupt):
            DBSCAN(eps=1.0, min_samples=4).fit(
                x, checkpoint=_KillAfter(path, every=1, kill_after=1))
        res = DBSCAN(eps=1.0, min_samples=4).fit(
            x, checkpoint=FitCheckpoint(path, every=1))
        np.testing.assert_array_equal(res.labels_, plain.labels_)
        np.testing.assert_array_equal(res.core_sample_indices_,
                                      plain.core_sample_indices_)
        assert res.n_clusters_ == plain.n_clusters_ == 3

    def test_daura_kill_resume_equals_plain(self, rng, tmp_path):
        from dislib_tpu.cluster import Daura
        x = ds.array(self._blobs3(rng, n=60))   # 2 cols is not 3*n_atoms
        xx = ds.array(np.hstack([np.asarray(x.collect())] * 3))  # 6 = 3*2
        plain = Daura(cutoff=2.0).fit(xx)

        path = str(tmp_path / "da.npz")
        with pytest.raises(KeyboardInterrupt):
            Daura(cutoff=2.0).fit(
                xx, checkpoint=_KillAfter(path, every=1, kill_after=1))
        res = Daura(cutoff=2.0).fit(
            xx, checkpoint=FitCheckpoint(path, every=1))
        np.testing.assert_array_equal(res.labels_, plain.labels_)
        assert len(res.clusters_) == len(plain.clusters_)
        for a, b in zip(res.clusters_, plain.clusters_):
            np.testing.assert_array_equal(a, b)

    def test_dbscan_stale_checkpoint_raises(self, rng, tmp_path):
        from dislib_tpu.cluster import DBSCAN
        x = ds.array(self._blobs3(rng))
        path = str(tmp_path / "db2.npz")
        with pytest.raises(KeyboardInterrupt):
            DBSCAN(eps=1.0, min_samples=4).fit(
                x, checkpoint=_KillAfter(path, every=1, kill_after=1))
        with pytest.raises(ValueError, match="stale or foreign"):
            DBSCAN(eps=2.0, min_samples=4).fit(
                x, checkpoint=FitCheckpoint(path, every=1))

    def test_forest_changed_seed_or_features_raises(self, rng, tmp_path):
        from dislib_tpu.trees import RandomForestClassifier
        xh = np.vstack([rng.rand(60, 4), rng.rand(60, 4) + 3]) \
            .astype(np.float32)
        yh = np.repeat([0.0, 1.0], 60).astype(np.float32).reshape(-1, 1)
        x, y = ds.array(xh), ds.array(yh)
        path = str(tmp_path / "rf3.npz")
        with pytest.raises(KeyboardInterrupt):
            RandomForestClassifier(n_estimators=3, random_state=7).fit(
                x, y, checkpoint=_KillAfter(path, every=1, kill_after=1))
        with pytest.raises(ValueError, match="stale or foreign"):
            RandomForestClassifier(n_estimators=3, random_state=8).fit(
                x, y, checkpoint=FitCheckpoint(path, every=1))
        with pytest.raises(ValueError, match="stale or foreign"):
            RandomForestClassifier(n_estimators=3, random_state=7,
                                   try_features="third").fit(
                x, y, checkpoint=FitCheckpoint(path, every=1))

    def test_foreign_npz_raises_not_keyerror(self, rng, tmp_path):
        """A snapshot from a DIFFERENT estimator (missing fp/digest keys)
        must refuse with the ValueError, not crash with KeyError."""
        from dislib_tpu.cluster import DBSCAN
        path = str(tmp_path / "foreign.npz")
        FitCheckpoint(path).save({"centers": np.ones((3, 2))})
        x = ds.array(self._blobs3(rng))
        with pytest.raises(ValueError, match="stale or foreign"):
            DBSCAN(eps=1.0, min_samples=4).fit(
                x, checkpoint=FitCheckpoint(path, every=1))

    def test_dbscan_ring_tier_kill_resume(self, rng, tmp_path, monkeypatch):
        """Checkpointing composes with the ring (multi-device) tier: the
        chunked fit follows the same tier policy as the plain fit."""
        from dislib_tpu.cluster import DBSCAN
        from dislib_tpu.cluster import dbscan as dbscan_mod
        monkeypatch.setattr(dbscan_mod, "_RING", True)
        x = ds.array(self._blobs3(rng))
        plain = DBSCAN(eps=1.0, min_samples=4).fit(x)
        path = str(tmp_path / "dbr.npz")
        with pytest.raises(KeyboardInterrupt):
            DBSCAN(eps=1.0, min_samples=4).fit(
                x, checkpoint=_KillAfter(path, every=1, kill_after=1))
        res = DBSCAN(eps=1.0, min_samples=4).fit(
            x, checkpoint=FitCheckpoint(path, every=1))
        np.testing.assert_array_equal(res.labels_, plain.labels_)
        assert res.n_clusters_ == plain.n_clusters_ == 3

    def test_daura_ring_tier_kill_resume(self, rng, tmp_path, monkeypatch):
        from dislib_tpu.cluster import Daura
        from dislib_tpu.cluster import daura as daura_mod
        monkeypatch.setattr(daura_mod, "_RING", True)
        xx = ds.array(np.hstack([self._blobs3(rng, n=60)] * 3))
        plain = Daura(cutoff=2.0).fit(xx)
        path = str(tmp_path / "dar.npz")
        with pytest.raises(KeyboardInterrupt):
            Daura(cutoff=2.0).fit(
                xx, checkpoint=_KillAfter(path, every=1, kill_after=1))
        res = Daura(cutoff=2.0).fit(
            xx, checkpoint=FitCheckpoint(path, every=1))
        np.testing.assert_array_equal(res.labels_, plain.labels_)

    def test_tier_mismatch_resumes(self, rng, tmp_path, monkeypatch):
        """Round 16: a snapshot written on one tier RESUMES on the other —
        the greedy/propagation state stores frame ids with a sentinel the
        restore re-bases, so pad widths are no longer fingerprinted (a
        mesh resize changes the pad width mid-fit; a refusal here would
        make every elastic resume a typed failure)."""
        from dislib_tpu.cluster import DBSCAN
        from dislib_tpu.cluster import dbscan as dbscan_mod
        x = ds.array(self._blobs3(rng))
        plain = DBSCAN(eps=1.0, min_samples=4).fit(x)
        path = str(tmp_path / "dbt.npz")
        with pytest.raises(KeyboardInterrupt):
            DBSCAN(eps=1.0, min_samples=4).fit(     # tiled-tier snapshot
                x, checkpoint=_KillAfter(path, every=1, kill_after=1))
        monkeypatch.setattr(dbscan_mod, "_RING", True)
        res = DBSCAN(eps=1.0, min_samples=4).fit(   # ring-tier resume
            x, checkpoint=FitCheckpoint(path, every=1))
        np.testing.assert_array_equal(res.labels_, plain.labels_)
        np.testing.assert_array_equal(res.core_sample_indices_,
                                      plain.core_sample_indices_)
