"""Round-20 multi-host survival, tier-1 coverage: heartbeat leases and
the attributed ``RankDead``, epoch fencing of a stale rejoiner, the
death → capacity → rejoin healing flow (counters asserted), the barrier
deadline's typed abort on EVERY surviving rank, the retry-then-escalate
classification (``CoordinationTimeout`` transient, ``RankDead`` fatal),
torn coordination files surviving as TRANSIENT, the serving fleet's
shard drain, and the round-20 fault injectors themselves.

Everything lease-related runs on a MOCKED clock (``Membership`` takes
injectable ``clock``/``sleep``), so expiry scenarios are instant and
bit-reproducible — the real-process, real-SIGKILL versions of these
scenarios live in ``tools/mh_dryrun.py --chaos``.
"""

import json
import signal
import threading
import time

import numpy as np
import pytest

from dislib_tpu.runtime.coord import (CoordinationTimeout, FileCoordinator,
                                      LeaseKeeper, LocalCoordinator,
                                      Membership, RankDead, TornCoordFile,
                                      barrier_timeout, lease_seconds,
                                      resilient_exchange, set_membership)
from dislib_tpu.runtime.retry import is_transient_error
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.faults import KillRankAt, LeaseExpiry, TornCoordWrite

LEASE_MS = 2000


class FakeClock:
    """Injectable wall clock: ``sleep`` advances it, nothing waits."""

    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)

    def sleep(self, dt):
        self.t += float(dt)


def _member(rank, n, co, clock, **kw):
    kw.setdefault("lease_ms", LEASE_MS)
    kw.setdefault("devices", 2)
    kw.setdefault("heal_capacity", False)
    return Membership(rank, n, coord=co, clock=clock, sleep=clock.sleep,
                      **kw)


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def co():
    return LocalCoordinator()


# ---------------------------------------------------------------------------
# leases: expiry → attributed RankDead
# ---------------------------------------------------------------------------

class TestLeases:
    def test_expiry_is_attributed(self, clock, co):
        m0 = _member(0, 3, co, clock)
        m1 = _member(1, 3, co, clock)
        assert m0.join() == 1
        assert m1.join() == 1
        assert m0.dead() == []          # fresh fleet
        last = clock.t
        clock.advance(LEASE_MS / 1000.0 + 0.5)
        m0.heartbeat()                  # self stays fresh
        # rank 2 NEVER joined: missing, not dead — only a lease that
        # stopped renewing is evidence of death
        assert m0.dead() == [(1, last, 1)]
        with pytest.raises(RankDead) as ei:
            m0.raise_if_dead()
        e = ei.value
        assert (e.rank, e.last_seen, e.epoch) == (1, last, 1)
        assert e.missing == (1,)
        assert isinstance(e, CoordinationTimeout)   # old handlers catch
        assert "rank 1 is dead" in str(e)

    def test_heartbeat_keeps_the_lease_alive(self, clock, co):
        m0, m1 = _member(0, 2, co, clock), _member(1, 2, co, clock)
        m0.join(), m1.join()
        for _ in range(5):
            clock.advance(LEASE_MS / 1000.0 * 0.6)
            m1.heartbeat()
            assert m0.dead() == []

    def test_env_knobs_parse(self, monkeypatch):
        assert lease_seconds() == 2.0
        monkeypatch.setenv("DSLIB_COORD_LEASE_MS", "500")
        assert lease_seconds() == 0.5
        monkeypatch.setenv("DSLIB_COORD_LEASE_MS", "junk")
        assert lease_seconds() == 2.0   # never a crash
        assert barrier_timeout() == 30.0
        monkeypatch.setenv("DSLIB_BARRIER_TIMEOUT", "1.5")
        assert barrier_timeout() == 1.5


# ---------------------------------------------------------------------------
# epoch fencing: a restarted rank's stale posts can never satisfy a
# post-restart barrier
# ---------------------------------------------------------------------------

class TestEpochFencing:
    def test_stale_rejoiner_is_fenced(self, clock, co):
        m0, m1 = _member(0, 2, co, clock), _member(1, 2, co, clock)
        m0.join()
        assert m1.join() == 1
        m1.post("result", "pre-crash")
        assert m0.gather("result") == {1: "pre-crash"}
        # rank 1 dies and restarts: join() bumps PAST the prior lease's
        # epoch, so the pre-crash post is fenced out of every gather
        m1b = _member(1, 2, co, clock)
        assert m1b.join() == 2
        assert m0.gather("result") == {}
        m1b.post("result", "post-restart")
        assert m0.gather("result") == {1: "post-restart"}

    def test_fenced_exchange_death_vs_timeout(self, clock, co):
        m0, m1 = _member(0, 2, co, clock), _member(1, 2, co, clock)
        m0.join(), m1.join()
        # peer's lease expires while we wait → RankDead long before the
        # exchange deadline (the mocked clock proves no timeout burn)
        clock.advance(LEASE_MS / 1000.0 + 0.5)
        m0.heartbeat()
        t0 = clock.t
        with pytest.raises(RankDead):
            m0.exchange("step", 1, timeout=3600.0)
        assert clock.t - t0 < 1.0
        # fresh peer that simply never posts → plain CoordinationTimeout
        # at the deadline, missing ranks attributed
        m0b = _member(0, 2, co, clock, lease_ms=10 ** 7)
        m1b = _member(1, 2, co, clock, lease_ms=10 ** 7)
        m0b.join(), m1b.join()
        with pytest.raises(CoordinationTimeout) as ei:
            m0b.exchange("step2", 1, timeout=2.0)
        assert ei.value.missing == (1,)
        assert not isinstance(ei.value, RankDead)

    def test_transport_exchanges_are_death_aware(self, clock, tmp_path):
        """With a process-global membership registered, the RAW
        coordinator exchange (the path every barrier in the library
        takes) aborts with RankDead instead of burning its timeout."""
        for co in (LocalCoordinator(), FileCoordinator(str(tmp_path))):
            m0, m1 = _member(0, 2, co, clock), _member(1, 2, co, clock)
            m0.join(), m1.join()
            clock.advance(LEASE_MS / 1000.0 + 0.5)
            m0.heartbeat()
            set_membership(m0)
            try:
                t0 = time.monotonic()
                with pytest.raises(RankDead):
                    co.exchange("barrier", 0, "vote", 2, timeout=30.0)
                assert time.monotonic() - t0 < 5.0
            finally:
                set_membership(None)


# ---------------------------------------------------------------------------
# degradation policy: transient → retry, RankDead → escalate immediately
# ---------------------------------------------------------------------------

class _FlakyCoord:
    def __init__(self, fails, exc):
        self.calls = 0
        self.fails = int(fails)
        self.exc = exc

    def exchange(self, name, rank, value, n, timeout=30.0):
        self.calls += 1
        if self.calls <= self.fails:
            raise self.exc
        return {r: value for r in range(int(n))}


class TestRetryClassification:
    def test_is_transient(self):
        assert is_transient_error(CoordinationTimeout("slow peer", [1]))
        assert is_transient_error(TornCoordFile("/x.json", "crc"))
        assert not is_transient_error(RankDead(1, 0.0, 1))

    def test_resilient_exchange_retries_transient(self):
        co = _FlakyCoord(1, CoordinationTimeout("slow", [1]))
        out = resilient_exchange(co, "x", 0, 7, 2, timeout=1.0)
        assert out == {0: 7, 1: 7}
        assert co.calls == 2            # one retry, then through

    def test_resilient_exchange_escalates_rank_dead(self):
        co = _FlakyCoord(99, RankDead(1, 0.0, 1))
        with pytest.raises(RankDead):
            resilient_exchange(co, "x", 0, 7, 2, timeout=1.0)
        assert co.calls == 1            # fatal: no retry burned

    def test_budget_is_split_not_multiplied(self):
        seen = []

        class _Co:
            def exchange(self, name, rank, value, n, timeout=30.0):
                seen.append(timeout)
                raise CoordinationTimeout("slow", [1])

        with pytest.raises(CoordinationTimeout):
            resilient_exchange(_Co(), "x", 0, 7, 2, timeout=1.0)
        assert sum(seen) <= 1.0 + 1e-9  # deadline holds across attempts


# ---------------------------------------------------------------------------
# torn coordination files: TRANSIENT, retried, counted — never fatal
# ---------------------------------------------------------------------------

class TestTornCoordFiles:
    def test_torn_write_degrades_to_missing_and_heals(self, tmp_path):
        co = FileCoordinator(str(tmp_path))
        torn = TornCoordWrite(co, failures=1)
        _prof.reset_counters()
        torn.post("vote", 0, {"a": 1})
        assert (torn.calls, torn.fails) == (1, 1)
        # one verification attempt sees the typed transient
        with pytest.raises(TornCoordFile):
            co._read_once(co._path("vote", 0))
        # the production read retries, then degrades to "missing"
        assert co.peek("vote", 0) is None
        assert _prof.resilience_counters().get("coord_torn_reads") == 1
        # the writer's clean re-post (the atomic path) heals in place
        torn.post("vote", 0, {"a": 1})
        assert co.peek("vote", 0) == {"a": 1}

    def test_crc_roundtrip_and_bare_back_compat(self, tmp_path):
        co = FileCoordinator(str(tmp_path))
        co.post("x", 0, {"nested": [1, 2, "three"]})
        assert co.peek("x", 0) == {"nested": [1, 2, "three"]}
        # a pre-round-20 bare payload (no CRC envelope) still reads
        with open(co._path("x", 1), "w") as f:
            json.dump(5, f)
        assert co.peek("x", 1) == 5

    def test_racing_writer_heals_within_the_retry_budget(self, tmp_path):
        """The tear the CRC exists for: a reader that catches a torn
        file while the writer is still alive sees the clean re-post
        within its retry budget — no counter, no missing rank."""
        co = FileCoordinator(str(tmp_path))
        TornCoordWrite(co, failures=1).post("v", 0, "payload")
        reads = {"n": 0}
        real = co._read_once

        def healing_read(path):
            reads["n"] += 1
            if reads["n"] == 2:         # between attempts: writer re-posts
                co.post("v", 0, "payload")
            return real(path)

        co._read_once = healing_read
        _prof.reset_counters()
        assert co.peek("v", 0) == "payload"
        assert _prof.resilience_counters().get("coord_torn_reads") is None


# ---------------------------------------------------------------------------
# death → capacity → rejoin: the healing flow, counters asserted
# ---------------------------------------------------------------------------

class TestDeathToCapacity:
    def test_poll_publishes_shrunk_target_then_heals(self, clock, co):
        from dislib_tpu.runtime import capacity_target, clear_capacity
        m0 = _member(0, 2, co, clock, devices=8, heal_capacity=True)
        m1 = _member(1, 2, co, clock)
        m0.join(), m1.join()
        last = clock.t
        _prof.reset_counters()
        try:
            assert m0.poll() == []
            clock.advance(LEASE_MS / 1000.0 + 1.0)
            m0.heartbeat()
            assert m0.poll() == [("death", 1, last)]
            # shrunk per-host target: 8 devices · 1 live // 2 ranks
            assert capacity_target() == 4
            assert m0.stats()["dead_ranks"] == [1]
            assert _prof.resilience_counters().get("rank_deaths") == 1
            assert m0.poll() == []      # idempotent per lease epoch
            # the restarted rank rejoins under a bumped epoch
            m1b = _member(1, 2, co, clock)
            assert m1b.join() == 2
            assert m0.poll() == [("rejoin", 1, 2)]
            assert capacity_target() is None    # whole fleet back
            assert m0.stats()["dead_ranks"] == []
            assert _prof.resilience_counters().get("rank_rejoins") == 1
        finally:
            clear_capacity()

    def test_lease_keeper_gate_drives_a_flap(self, clock, co):
        """A LeaseExpiry-gated keeper skips exactly the scheduled beats:
        peers observe death, then the rejoin when beating resumes."""
        m0 = _member(0, 2, co, clock)
        m1 = _member(1, 2, co, clock)
        m0.join(), m1.join()
        gate = LeaseExpiry(after=1, beats=2)
        keeper = LeaseKeeper(m1, watch=False, gate=gate)
        _prof.reset_counters()
        assert keeper.step() == []      # beat 1: renews
        clock.advance(LEASE_MS / 1000.0 + 0.5)
        m0.heartbeat()
        keeper.step()                   # beat 2: GATED — lease expires
        assert [e[0] for e in m0.poll()] == ["death"]
        keeper.step()                   # beat 3: still gated
        assert m0.poll() == []
        keeper.step()                   # beat 4: resumes → fresh lease
        assert [e[0] for e in m0.poll()] == ["rejoin"]
        assert gate.calls == 4
        r = _prof.resilience_counters()
        assert (r.get("rank_deaths"), r.get("rank_rejoins")) == (1, 1)

    def test_lease_keeper_thread_never_hangs(self, co):
        """The real daemon keeper (real clock, short lease): renews while
        running, stops promptly, and its death is observed by a peer."""
        m0 = Membership(0, 2, coord=co, lease_ms=400, devices=2,
                        heal_capacity=False)
        m1 = Membership(1, 2, coord=co, lease_ms=400, devices=2,
                        heal_capacity=False)
        m0.join(), m1.join()
        keeper = LeaseKeeper(m1, interval_s=0.05, watch=False)
        keeper.start()
        try:
            time.sleep(0.6)             # > lease: only renewals keep it
            assert m0.dead() == []
        finally:
            keeper.stop()
        assert not keeper.is_alive()
        deadline = time.monotonic() + 10.0
        while not m0.dead():
            assert time.monotonic() < deadline, "lease never expired"
            time.sleep(0.02)
        assert m0.dead()[0][0] == 1


class TestHeadHome:
    """Pressure lifted → head home: the rejoin heal CLEARS the capacity
    target rather than publishing a bigger level, so a capacity-shrunk
    fit/server must treat None-after-shrink as 'grow back toward the
    home mesh' (an elastic-tier remediation shrink stays sticky)."""

    def test_fit_heads_home_when_pressure_lifts(self, tmp_path):
        import dislib_tpu as ds
        from dislib_tpu.cluster import KMeans
        from dislib_tpu.parallel import mesh as _mesh
        from dislib_tpu.runtime import clear_capacity, request_capacity
        from dislib_tpu.utils import FitCheckpoint, faults
        ds.init((8, 1))
        rng = np.random.RandomState(0)
        centers = rng.rand(3, 4) * 10
        x_np = np.vstack([centers[i] + 0.3 * rng.randn(66, 4)
                          for i in range(3)]).astype(np.float32)
        kw = dict(n_clusters=3,
                  init=np.ascontiguousarray(x_np[[0, 70, 140]]),
                  max_iter=12, tol=0.0)
        oracle = KMeans(**kw).fit(
            ds.array(x_np),
            checkpoint=FitCheckpoint(str(tmp_path / "o.npz"), every=2))
        try:
            request_capacity(4)         # a host died before the fit
            ck = faults.CallbackCheckpoint(
                str(tmp_path / "h.npz"), every=2, after=2,
                callback=clear_capacity)    # ...and rejoins mid-fit
            est = KMeans(**kw).fit(ds.array(x_np), checkpoint=ck)
        finally:
            clear_capacity()
            ds.init()
        info = est.fit_info_
        assert (info["mesh_shrinks"], info["mesh_grows"]) == (1, 1)
        np.testing.assert_allclose(est.centers_, oracle.centers_,
                                   rtol=1e-5, atol=1e-6)

    def test_server_heads_home_when_pressure_lifts(self):
        import dislib_tpu as ds
        from dislib_tpu.parallel import mesh as _mesh
        from dislib_tpu.runtime import clear_capacity, request_capacity
        from dislib_tpu.serving import PredictServer, ServePipeline
        ds.init((8, 1))
        lr = ds.LinearRegression()
        lr.coef_ = np.ones((4, 1), np.float32)
        lr.intercept_ = np.zeros(1, np.float32)
        pipe = ServePipeline(lr, n_features=4)
        x = np.ones((2, 4), np.float32)
        _prof.reset_counters()

        def _resized(srv, n, what):
            deadline = time.monotonic() + 30.0
            while srv.stats()["mesh_resizes"] < n:
                assert time.monotonic() < deadline, f"{what} never landed"
                time.sleep(0.02)

        srv = PredictServer(pipeline=pipe, buckets=(1, 4), elastic=True,
                            capacity_poll_s=0.01, name="headhome")
        try:
            with srv:
                assert srv.predict(x).shape == (2, 1)
                request_capacity(4)
                _resized(srv, 1, "shrink")
                assert _mesh.mesh_shape(_mesh.get_mesh()) == (4, 1)
                clear_capacity()        # pressure lifts — NO grow target
                _resized(srv, 2, "head-home grow")
                assert _mesh.mesh_shape(_mesh.get_mesh()) == (8, 1)
                assert srv.predict(x).shape == (2, 1)
        finally:
            clear_capacity()
            ds.init()
        r = _prof.resilience_counters()
        assert r.get("serve_mesh_shrinks") == 1
        assert r.get("serve_mesh_grows") == 1


# ---------------------------------------------------------------------------
# the load barrier: one dead host aborts ALL hosts typed — never a hang
# ---------------------------------------------------------------------------

class TestBarrierAbort:
    def test_typed_abort_on_every_surviving_rank(self, co):
        from dislib_tpu.serving.bundle import _barrier_exchange
        _prof.reset_counters()
        errs, done = {}, []

        def run(rank):
            try:
                _barrier_exchange(co, "bundle-load:m", rank, {"ok": 1},
                                  3, 0.6, "m.dsb.npz")
            except CoordinationTimeout as e:
                errs[rank] = e
            done.append(rank)

        ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30.0)        # rank 2 never arrives
        assert sorted(done) == [0, 1]   # zero hangs
        assert sorted(errs) == [0, 1]   # BOTH survivors abort...
        for e in errs.values():         # ...typed and attributed
            assert "load barrier ABORTED" in str(e)
            assert "zero hosts serve" in str(e)
            assert 2 in e.missing
        assert _prof.resilience_counters()["bundle_barrier_abort"] == 2


# ---------------------------------------------------------------------------
# serving: a dead peer's shard drains instead of serving torn results
# ---------------------------------------------------------------------------

class TestShardDrain:
    def _pipe(self):
        import dislib_tpu as ds
        from dislib_tpu.serving import ServePipeline
        lr = ds.LinearRegression()
        lr.coef_ = np.ones((4, 1), np.float32)
        lr.intercept_ = np.zeros(1, np.float32)
        return ServePipeline(lr, n_features=4)

    def _await(self, srv, draining, what):
        deadline = time.monotonic() + 30.0
        while srv.stats()["draining"] != draining:
            assert time.monotonic() < deadline, f"{what} never observed"
            time.sleep(0.02)

    def test_drain_and_resume(self, clock, co):
        from dislib_tpu.serving import PredictServer, ShardDrained
        m0, m1 = _member(0, 2, co, clock), _member(1, 2, co, clock)
        m0.join(), m1.join()
        _prof.reset_counters()
        srv = PredictServer(pipeline=self._pipe(), buckets=(1, 4),
                            membership=m0, name="drainer")
        srv.start()
        try:
            q = np.ones((2, 4), np.float32)
            assert srv.predict(q).shape == (2, 1)   # healthy fleet
            clock.advance(LEASE_MS / 1000.0 + 1.0)
            m0.heartbeat()              # peer 1's lease expires
            self._await(srv, True, "drain")
            with pytest.raises(ShardDrained) as ei:
                srv.submit(q)
            assert ei.value.rank == 1
            st = srv.stats()
            assert st["shard_drains"] == 1 and st["draining"]
            assert _prof.resilience_counters()["serve_shard_drains"] == 1
            m1.heartbeat()              # the peer comes back
            self._await(srv, False, "resume")
            assert srv.predict(q).shape == (2, 1)   # serving resumes
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# the injectors themselves
# ---------------------------------------------------------------------------

class TestInjectors:
    def test_kill_rank_at_schedule(self):
        kills = []
        inj = KillRankAt(at_call=3, pid=4242,
                         kill=lambda pid, sig: kills.append((pid, sig)))
        for _ in range(5):
            inj("any", seam="args")
        assert (inj.calls, inj.fired) == (5, 1)
        assert kills == [(4242, signal.SIGKILL)]

    def test_kill_rank_at_defaults_to_self(self):
        kills = []
        inj = KillRankAt(kill=lambda pid, sig: kills.append((pid, sig)))
        inj()
        import os
        assert kills == [(os.getpid(), signal.SIGKILL)]

    def test_lease_expiry_window(self):
        gate = LeaseExpiry(after=2, beats=3)
        assert [gate() for _ in range(8)] == [True, True, False, False,
                                              False, True, True, True]
        assert gate.calls == 8

    def test_torn_coord_write_narrows_by_name(self, tmp_path):
        co = FileCoordinator(str(tmp_path))
        torn = TornCoordWrite(co, failures=2, name="victim")
        torn.post("healthy", 0, "ok")
        assert co.peek("healthy", 0) == "ok"    # untouched exchange
        torn.post("victim", 0, "gone")
        assert co.peek("victim", 0) is None     # torn on the final path
        assert (torn.calls, torn.fails) == (2, 1)
        # non-post methods pass through untouched
        assert torn.peek("healthy", 0) == "ok"
