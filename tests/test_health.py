"""Self-healing fit loops, end to end (round-8 robustness PR), driven by
the deterministic numerical/liveness fault injectors in
``dislib_tpu.utils.faults``:

- **fused guards** — every chunk kernel emits a health vector inside its
  existing dispatch; the zero-extra-dispatch claim is asserted with the
  round-7 ``dispatch_count`` counters;
- **rollback-to-last-good** — NaN injected into a chunk's carry rolls the
  fit back to the last good snapshot generation (writes are gated on
  healthy chunks) and, under the default 'retry' action, the healed fit
  lands on the SAME model as an unfaulted run — for every estimator that
  carries float state (KMeans, GMM, ALS, forest; the cascade SVM's
  host-side state uses the forced-trip injector);
- **typed diagnostics, never silent bad models** — without a checkpoint
  (or with the budget exhausted / 'raise' policy / non-finite input data)
  the fit raises ``NumericalDivergence`` carrying estimator, iteration,
  guard, and offending-carry coordinates; DBSCAN/Daura raise it on
  non-finite input instead of silently emitting an all-noise clustering;
- **chunk watchdog** — a hung force point trips ``WatchdogTimeout``,
  escalates through the PR-1 ``Retry`` policy, and either self-heals or
  aborts cleanly;
- **ingest quarantine** — loaders isolate non-finite rows into a
  ``QuarantineReport`` instead of poisoning blocks.

Every fault fires on an exact chunk index — no timers (the hang injector
sleeps a fixed interval but FIRES deterministically), no RNG — so the
suite reproduces on any rig.
"""

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import DBSCAN, Daura, GaussianMixture, KMeans
from dislib_tpu.recommendation import ALS
from dislib_tpu.runtime import (HealthPolicy, NumericalDivergence,
                                WatchdogTimeout)
from dislib_tpu.runtime import health as health_mod
from dislib_tpu.utils import FitCheckpoint, faults

pytestmark = pytest.mark.filterwarnings(
    "ignore:.*invalid value encountered.*")


@pytest.fixture
def fast_retry(monkeypatch):
    monkeypatch.setenv("DSLIB_RETRY_BACKOFF", "0")


def _blobs(rng, n=198, d=4, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.3 * rng.randn(n // k, d) for i in range(k)])
    return x.astype(np.float32)


# ---------------------------------------------------------------------------
# the health vector + guard classification (unit tier)
# ---------------------------------------------------------------------------

class TestHealthVec:
    def test_layout_and_nonfinite_coords(self):
        @jax.jit
        def k(c, hist):
            return health_mod.health_vec(carries=(c,), hist=hist, n_done=3)

        c = jnp.asarray([[1.0, 2.0], [np.nan, 4.0]])
        hist = jnp.asarray([5.0, 4.0, 6.0, 0.0])   # rise 2.0 inside n_done
        h = np.asarray(k(c, hist))
        assert len(h) == health_mod.HEALTH_BASE_LEN + 2
        g = health_mod.guard("t")
        v = g.check(h, carry_names=("centers",), carry_shapes=((2, 2),))
        assert not v.ok and v.guard == "nonfinite" and v.recoverable
        info = v.detail["carries"]["centers"]
        assert info["count"] == 1 and info["coords"] == (1, 0)

    def test_monotone_and_growth_guards_are_opt_in(self):
        @jax.jit
        def k(c, hist):
            return health_mod.health_vec(carries=(c,), hist=hist)

        h = np.asarray(k(jnp.full((2, 2), 50.0),
                         jnp.asarray([1.0, 3.0])))   # rises, |carry|=50
        assert health_mod.guard("t").check(h).ok, \
            "default policy must trip on nonfinite only"
        pol = HealthPolicy(monotone_rtol=0.1)
        v = pol.make_guard("t").check(h)
        assert not v.ok and v.guard == "divergence"
        pol = HealthPolicy(grow_limit=10.0)
        v = pol.make_guard("t").check(h)
        assert not v.ok and v.guard == "norm-growth"

    def test_loss_nonfinite_trips_even_with_clean_carries(self):
        # a transient blow-up can wash out of a self-correcting carry
        # (Lloyd's M-step recomputes centers from data) yet poison the
        # trajectory — the loss history is the witness
        @jax.jit
        def k(c, hist):
            return health_mod.health_vec(carries=(c,), hist=hist, n_done=2)

        h = np.asarray(k(jnp.ones((2, 2)), jnp.asarray([np.nan, 1.0])))
        v = health_mod.guard("t").check(h)
        assert not v.ok and v.guard == "nonfinite"
        assert v.detail["loss_nonfinite"] == 1

    def test_input_nonfinite_is_not_recoverable(self):
        @jax.jit
        def k(x):
            return health_mod.health_vec(inputs=(x,))

        h = np.asarray(k(jnp.asarray([[np.inf, 1.0]])))
        g = health_mod.guard("t", checkpoint=object())
        v = g.check(h)
        assert not v.ok and v.guard == "input-nonfinite" and not v.recoverable
        with pytest.raises(NumericalDivergence, match="quarantine"):
            g.remediate(v)

    def test_cross_chunk_monotone_jump_trips(self):
        """A loss jump landing exactly on a chunk boundary — invisible to
        the in-chunk diffs, and at every=1 the ONLY signal — must trip
        the armed monotone guard via the host-side loss carry-over."""
        @jax.jit
        def k(hist):
            return health_mod.health_vec(hist=hist, n_done=1)

        g = HealthPolicy(monotone_rtol=0.1).make_guard("t")
        assert g.check(np.asarray(k(jnp.asarray([5.0])))).ok
        assert g.check(np.asarray(k(jnp.asarray([4.0])))).ok  # fell: fine
        v = g.check(np.asarray(k(jnp.asarray([9.0]))))        # jumped
        assert not v.ok and v.guard == "divergence"
        # remediate drops the reference: the re-run chunk is not judged
        # against the pre-rollback trajectory
        g.checkpoint = object()
        g.remediate(v)
        assert g.check(np.asarray(k(jnp.asarray([9.0])))).ok

    def test_increasing_metric_mode(self):
        @jax.jit
        def k(hist):
            return health_mod.health_vec(hist=hist, increasing=True)

        h = np.asarray(k(jnp.asarray([2.0, 1.0])))  # fell: violation 1.0
        v = HealthPolicy(monotone_rtol=0.1).make_guard("t").check(h)
        assert not v.ok and v.guard == "divergence"

    def test_remediation_schedule_and_budget(self):
        pol = HealthPolicy(action="halve", max_restarts=2)
        g = pol.make_guard("t", checkpoint=object())
        bad = health_mod.Verdict(False, guard="nonfinite")
        r1, r2 = g.remediate(bad), g.remediate(bad)
        assert (r1.attempt, r2.attempt) == (1, 2)
        assert (r1.damping, r2.damping) == (2.0, 4.0)
        with pytest.raises(NumericalDivergence, match="max_restarts"):
            g.remediate(bad)

    def test_reseed_perturb_is_deterministic_and_action_scoped(self):
        arr = np.ones((3, 2), np.float32)
        r = health_mod.Remediation(1, "reseed", seed=7)
        out1, out2 = r.perturb(arr), r.perturb(arr)
        np.testing.assert_array_equal(out1, out2)
        assert not np.array_equal(out1, arr)
        np.testing.assert_array_equal(
            health_mod.Remediation(1, "retry", seed=7).perturb(arr), arr)

    def test_policy_env_defaults(self, monkeypatch):
        monkeypatch.setenv("DSLIB_HEALTH_ACTION", "raise")
        monkeypatch.setenv("DSLIB_HEALTH_MAX_RESTARTS", "5")
        monkeypatch.setenv("DSLIB_CHUNK_DEADLINE_S", "1.5")
        monkeypatch.setenv("DSLIB_HEALTH_GROW_LIMIT", "1e6")
        pol = HealthPolicy()
        assert (pol.action, pol.max_restarts, pol.deadline_s,
                pol.grow_limit) == ("raise", 5, 1.5, 1e6)
        monkeypatch.setenv("DSLIB_HEALTH", "0")
        assert not HealthPolicy().enabled
        g = HealthPolicy().make_guard("t")
        assert g.check(np.asarray([9.0] * 8)).ok, "disabled guard admits all"

    def test_save_gate_blocks_unhealthy_state(self, tmp_path):
        ck = FitCheckpoint(str(tmp_path / "s.npz"), every=1)
        g = health_mod.guard("t", checkpoint=ck)
        g.check_host({"w": np.asarray([1.0])})
        g.save_async(ck, {"gen": np.asarray([0])})
        ck.flush()
        g.check_host({"w": np.asarray([np.nan])})
        assert g.save_async(ck, {"gen": np.asarray([1])}) is None
        ck.flush()
        assert int(ck.load()["gen"][0]) == 0, \
            "unhealthy state rotated over the good generation"


# ---------------------------------------------------------------------------
# chunk watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_deadline_trips_typed_timeout(self):
        import time as _t

        class Slow:
            def result(self):
                _t.sleep(0.3)
                return np.zeros(health_mod.HEALTH_BASE_LEN)

        # first_deadline_s pinned: this is the fresh guard's first check,
        # which otherwise gets the 10x compile grace
        g = HealthPolicy(deadline_s=0.05,
                         first_deadline_s=0.05).make_guard("t")
        with pytest.raises(WatchdogTimeout, match="force point"):
            g._watched_resolve(Slow())

    def test_first_check_gets_compile_grace(self, fast_retry, monkeypatch):
        """The guard's FIRST force point usually blocks on XLA compile —
        it gets the (default 10x) grace deadline; steady-state checks get
        the tight one."""
        import time as _t

        from dislib_tpu.runtime.elastic import AsyncFetch

        class Slow(AsyncFetch):
            def __init__(self):
                pass

            def result(self):
                _t.sleep(0.2)
                return np.zeros(health_mod.HEALTH_BASE_LEN)

        monkeypatch.setenv("DSLIB_RETRY_ATTEMPTS", "1")
        pol = HealthPolicy(deadline_s=0.05)
        assert pol.first_deadline_s == pytest.approx(0.5)
        g = pol.make_guard("t")
        assert g.check(Slow()).ok            # first: grace covers 0.2s
        with pytest.raises(WatchdogTimeout):
            g.check(Slow())                  # second: tight deadline

    def test_watchdog_timeout_is_retry_transient(self):
        from dislib_tpu.runtime import is_transient_error
        assert is_transient_error(WatchdogTimeout("hung"))

    def test_hang_escalates_through_retry_then_heals(self, rng, tmp_path,
                                                     fast_retry):
        x = ds.array(_blobs(rng))
        init = np.ascontiguousarray(_blobs(rng)[[0, 70, 140]])
        # max_iter matches the rollback tests so the jitted fit kernels
        # (static max_iter/chunk) are cache hits, not fresh compiles
        kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
        full = KMeans(**kw).fit(x)
        pol = faults.HangAtChunk(at_chunk=2, hang_s=0.4, deadline_s=0.05,
                                 times=1)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert pol.stalls == 1, "hang was never injected"
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)

    def test_hang_exhaustion_aborts_cleanly(self, rng, tmp_path, fast_retry,
                                            monkeypatch):
        monkeypatch.setenv("DSLIB_RETRY_ATTEMPTS", "2")
        x = ds.array(_blobs(rng))
        init = np.ascontiguousarray(_blobs(rng)[[0, 70, 140]])
        with pytest.raises(WatchdogTimeout):
            KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
                health=faults.HangAtChunk(at_chunk=1, hang_s=0.4,
                                          deadline_s=0.05, times=10))


# ---------------------------------------------------------------------------
# rollback-under-fault: NaN at chunk k → heal == unfaulted (acceptance)
# ---------------------------------------------------------------------------

class TestRollbackUnderFault:
    def test_kmeans_nan_at_chunk_heals_to_unfaulted_model(self, rng,
                                                          tmp_path):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
        full = KMeans(**kw).fit(x)
        pol = faults.NaNAtChunk(at_chunk=3)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
            health=pol)
        assert pol.fired == 1, "fault was never injected"
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)
        assert len(res.history_) == full.n_iter_, \
            "rollback left stale history entries"
        assert np.isfinite(res.history_).all()

    def test_gmm_nan_in_means_heals_to_unfaulted_model(self, rng, tmp_path):
        # shapes and static args mirror test_resilience's GMM drill so the
        # _gm_fit compiles (keyed on shape/cov_type/max_iter) are shared
        # across the two files instead of paid twice
        x = ds.array(_blobs(rng, n=150, d=3, k=2))
        kw = dict(n_components=2, max_iter=12, tol=0.0, random_state=0)
        full = GaussianMixture(**kw).fit(x)
        pol = faults.NaNAtChunk(at_chunk=2, where=1)     # poison means
        res = GaussianMixture(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "g.npz"), every=4),
            health=pol)
        assert pol.fired == 1
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.means_, full.means_, rtol=1e-5)
        assert res.lower_bound_ == pytest.approx(full.lower_bound_, rel=1e-6)

    def test_als_nan_in_factors_heals_to_unfaulted_model(self, rng,
                                                         tmp_path):
        u = rng.rand(30, 4).astype(np.float32)
        v = rng.rand(20, 4).astype(np.float32)
        r = ((u @ v.T) * (rng.rand(30, 20) < 0.6)).astype(np.float32)
        x = ds.array(r)
        kw = dict(n_f=4, max_iter=8, tol=1e-9, random_state=0)
        # checkpointed reference: both fits then use ONLY the every=2
        # chunk compile of _als_fit (shared with test_resilience's ALS
        # drills) — an unfaulted checkpointed run is the same model
        full = ALS(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "ref.npz"), every=2))
        pol = faults.NaNAtChunk(at_chunk=2)
        res = ALS(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "a.npz"), every=2),
            health=pol)
        assert pol.fired == 1
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_allclose(res.users_, full.users_,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(res.items_, full.items_,
                                   rtol=1e-4, atol=1e-5)

    def _forest_data(self, rng):
        # one shared shape across BOTH forest tests AND test_resilience's
        # forest drills: the level kernels compile per (n_trees,
        # depth-level, padded-m) static config, so shape alignment means
        # the whole suite pays each compile once
        n, k = 240, 3
        centers = rng.rand(k, 6) * 8
        xh = np.vstack([centers[i] + 0.4 * rng.randn(n // k, 6)
                        for i in range(k)]).astype(np.float32)
        yh = np.repeat(np.arange(k), n // k).astype(np.float32)
        p = rng.permutation(n)
        return ds.array(xh[p]), ds.array(yh[p].reshape(-1, 1))

    _forest_kw = dict(n_estimators=4, max_depth=6, random_state=7)

    def test_forest_nan_in_weights_heals_to_unfaulted_model(self, rng,
                                                            tmp_path):
        from dislib_tpu.trees import RandomForestClassifier
        x, y = self._forest_data(rng)
        full = RandomForestClassifier(**self._forest_kw).fit(x, y)
        pol = faults.NaNAtChunk(at_chunk=3)              # poison w at level 3
        res = RandomForestClassifier(**self._forest_kw).fit(
            x, y, checkpoint=FitCheckpoint(str(tmp_path / "f.npz"), every=2),
            health=pol)
        assert pol.fired == 1
        np.testing.assert_array_equal(res.predict(x).collect(),
                                      full.predict(x).collect())

    def test_csvm_forced_trip_rolls_back_to_unfaulted_model(self, rng,
                                                            tmp_path):
        from dislib_tpu.classification import CascadeSVM
        n = 120
        xh = np.vstack([rng.randn(n // 2, 4) - 2,
                        rng.randn(n // 2, 4) + 2]).astype(np.float32)
        yh = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
        sh = rng.permutation(n)
        x, y = ds.array(xh[sh]), ds.array(yh[sh].reshape(-1, 1))
        # config mirrors test_resilience's CSVM drill (same rng fixture →
        # same data → same cascade node shapes → shared solve compiles)
        kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
                  check_convergence=False)
        full = CascadeSVM(max_iter=4, **kw).fit(x, y)
        pol = faults.TripAtChunk(at_chunk=2)
        res = CascadeSVM(max_iter=4, **kw).fit(
            x, y, checkpoint=FitCheckpoint(str(tmp_path / "c.npz"), every=1),
            health=pol)
        assert pol.fired == 1
        assert res.n_iter_ == full.n_iter_
        np.testing.assert_array_equal(res._sv_idx, full._sv_idx)
        np.testing.assert_allclose(res._sv_alpha, full._sv_alpha, rtol=1e-5)

    def test_no_checkpoint_raises_typed_diagnostic(self, rng):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        with pytest.raises(NumericalDivergence) as exc:
            KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                x, health=faults.NaNAtChunk(at_chunk=1))
        e = exc.value
        assert e.estimator == "kmeans" and e.guard == "nonfinite"
        assert e.iteration is not None and "hvec" in e.detail

    def test_restart_budget_exhaustion_raises(self, rng, tmp_path):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        pol = faults.TripAtChunk(at_chunk=2, times=10, max_restarts=2)
        with pytest.raises(NumericalDivergence, match="max_restarts"):
            KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
                health=pol)
        assert pol.fired == 3, "2 restarts + the final raise = 3 trips"

    def test_raise_action_skips_remediation(self, rng, tmp_path):
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        pol = faults.NaNAtChunk(at_chunk=2, action="raise")
        with pytest.raises(NumericalDivergence, match="'raise'"):
            KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
                x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2),
                health=pol)

    def test_forest_unchecked_nan_raises_at_adoption(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        x, y = self._forest_data(rng)    # same shapes: kernels cache-hit
        with pytest.raises(NumericalDivergence, match="adoption"):
            RandomForestClassifier(**self._forest_kw).fit(
                x, y, health=faults.NaNAtChunk(at_chunk=1))

    def test_dbscan_nonfinite_input_raises_not_all_noise(self, rng,
                                                         tmp_path):
        xb = rng.rand(60, 3).astype(np.float32)
        xb[7, 1] = np.nan
        with pytest.raises(NumericalDivergence) as exc:
            DBSCAN(eps=0.5, min_samples=3).fit(ds.array(xb))
        assert exc.value.guard == "input-nonfinite"
        with pytest.raises(NumericalDivergence):
            DBSCAN(eps=0.5, min_samples=3).fit(
                ds.array(xb),
                checkpoint=FitCheckpoint(str(tmp_path / "d.npz"), every=2))

    def test_daura_nonfinite_input_raises(self, rng, tmp_path):
        xt = rng.rand(40, 6).astype(np.float32)
        xt[5, 2] = np.inf
        with pytest.raises(NumericalDivergence) as exc:
            Daura(cutoff=0.8).fit(ds.array(xt))
        assert exc.value.guard == "input-nonfinite"
        with pytest.raises(NumericalDivergence):
            Daura(cutoff=0.8).fit(
                ds.array(xt),
                checkpoint=FitCheckpoint(str(tmp_path / "d.npz"), every=2))

    def test_gated_writes_never_rotate_out_the_good_generation(self, rng,
                                                               tmp_path):
        """With keep=1 a single bad write would DESTROY the only good
        generation — the gate must make the faulted fit still heal."""
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        kw = dict(n_clusters=3, init=init, max_iter=12, tol=0.0)
        full = KMeans(**kw).fit(x)
        res = KMeans(**kw).fit(
            x, checkpoint=FitCheckpoint(str(tmp_path / "k.npz"), every=2,
                                        keep=1),
            health=faults.NaNAtChunk(at_chunk=3))
        np.testing.assert_allclose(res.centers_, full.centers_, rtol=1e-5)


# ---------------------------------------------------------------------------
# zero extra dispatches (acceptance: fused guards are free)
# ---------------------------------------------------------------------------

class TestZeroDispatchGuard:
    def test_kmeans_chunked_fit_dispatch_count_is_chunks_only(self, rng,
                                                              tmp_path,
                                                              monkeypatch):
        from dislib_tpu.utils import profiling as prof
        x_np = _blobs(rng)
        x = ds.array(x_np)
        init = np.ascontiguousarray(x_np[[0, 70, 140]])
        kw = dict(n_clusters=3, init=init, max_iter=6, tol=0.0)

        def run(tag):
            ck = FitCheckpoint(str(tmp_path / f"{tag}.npz"), every=2)
            KMeans(**kw).fit(x, checkpoint=ck)          # warm the caches
            ck.delete()
            prof.reset_counters()
            ck = FitCheckpoint(str(tmp_path / f"{tag}2.npz"), every=2)
            KMeans(**kw).fit(x, checkpoint=ck)
            return prof.counters()

        with_guard = run("on")
        # 6 iters / every=2 → 3 chunks → exactly 3 kmeans_fit dispatches,
        # health vector included in each
        assert with_guard["dispatch_by"].get("kmeans_fit") == 3
        monkeypatch.setenv("DSLIB_HEALTH", "0")
        without = run("off")
        assert with_guard["dispatches"] == without["dispatches"], (
            "the health guard added device dispatches: "
            f"{with_guard['dispatch_by']} vs {without['dispatch_by']}")


# ---------------------------------------------------------------------------
# ingest quarantine
# ---------------------------------------------------------------------------

class TestIngestQuarantine:
    def _csv(self, tmp_path, x):
        p = str(tmp_path / "q.csv")
        np.savetxt(p, x, delimiter=",")
        return p

    def test_txt_loader_isolates_nonfinite_rows(self, rng, tmp_path):
        x = rng.rand(12, 3).astype(np.float32)
        x[3, 1], x[9, 0] = np.nan, np.inf
        p = self._csv(tmp_path, x)
        with pytest.warns(RuntimeWarning, match="quarantined 2"):
            got = ds.load_txt_file(p)
        assert got.shape == (10, 3)
        rep = got.quarantine_
        assert rep is not None and rep.n_quarantined == 2
        assert rep.rows.tolist() == [3, 9] and rep.n_loaded == 10
        assert not np.isfinite(rep.values).all()
        assert ds.last_quarantine_report() is rep
        np.testing.assert_allclose(np.asarray(got.collect()),
                                   x[np.isfinite(x).all(axis=1)], rtol=1e-5)

    def test_keep_mask_realigns_a_row_paired_file(self, rng, tmp_path):
        x = rng.rand(10, 3).astype(np.float32)
        x[4, 0] = np.nan
        y = np.arange(10, dtype=np.float32).reshape(-1, 1)
        px, py = str(tmp_path / "x.csv"), str(tmp_path / "y.csv")
        np.savetxt(px, x, delimiter=",")
        np.savetxt(py, y, delimiter=",")
        with pytest.warns(RuntimeWarning, match="keep_mask"):
            gx = ds.load_txt_file(px)
        gy = ds.load_txt_file(py)          # clean file: nothing dropped
        mask = gx.quarantine_.keep_mask
        assert mask.shape == (10,) and not mask[4]
        aligned = np.asarray(gy.collect()).ravel()[mask]
        np.testing.assert_array_equal(aligned,
                                      y.ravel()[np.isfinite(x).all(axis=1)])
        assert gx.shape[0] == aligned.shape[0]

    def test_opt_out_loads_raw(self, rng, tmp_path, monkeypatch):
        x = rng.rand(6, 2).astype(np.float32)
        x[1, 0] = np.nan
        p = self._csv(tmp_path, x)
        got = ds.load_txt_file(p, quarantine=False)
        assert got.shape == (6, 2) and got.quarantine_ is None
        monkeypatch.setenv("DSLIB_QUARANTINE", "0")
        got = ds.load_txt_file(p)
        assert got.shape == (6, 2) and got.quarantine_ is None

    def test_npy_loader_quarantines(self, rng, tmp_path):
        x = rng.rand(8, 3).astype(np.float32)
        x[2, 2] = np.nan
        p = str(tmp_path / "q.npy")
        np.save(p, x)
        with pytest.warns(RuntimeWarning, match="quarantined 1"):
            got = ds.load_npy_file(p)
        assert got.shape == (7, 3) and got.quarantine_.rows.tolist() == [2]

    def test_svmlight_quarantine_keeps_labels_aligned(self, tmp_path):
        p = str(tmp_path / "q.svm")
        with open(p, "w") as f:
            f.write("1 1:0.5 3:0.25\n-1 2:nan\n1 1:2.0\n-1 2:1.0\n")
        with pytest.warns(RuntimeWarning, match="quarantined 1"):
            x, y = ds.load_svmlight_file(p)
        assert x.shape[0] == 3
        np.testing.assert_array_equal(
            np.asarray(y.collect()).ravel(), [1, 1, -1])
        assert x.quarantine_.rows.tolist() == [1]

    def test_mdcrd_quarantines_frames_before_copy_first(self, rng,
                                                        tmp_path):
        fr = rng.rand(4, 6).astype(np.float32)
        fr[1, 2] = np.nan
        p = str(tmp_path / "t.mdcrd")
        with open(p, "w") as f:
            f.write("title\n")
            for v in fr.ravel():
                f.write(f"{v:8.3f}")
            f.write("\n")
        with pytest.warns(RuntimeWarning, match="quarantined 1"):
            got = ds.load_mdcrd_file(p, n_atoms=2, copy_first=True)
        # 3 clean frames + the duplicated (clean) first frame
        assert got.shape == (4, 6)
        assert np.isfinite(np.asarray(got.collect())).all()

    def test_all_rows_bad_is_a_clear_error(self, tmp_path):
        x = np.full((3, 2), np.nan, np.float32)
        p = self._csv(tmp_path, x)
        with pytest.warns(RuntimeWarning), \
                pytest.raises(ValueError, match="nothing left to load"):
            ds.load_txt_file(p)

    def test_quarantined_load_fits_clean(self, rng, tmp_path):
        """End to end: a poisoned file, quarantined at ingest, fits to a
        finite model — the failure mode the guards would otherwise catch
        mid-fit never materialises."""
        x = _blobs(rng, n=90, d=3)
        x[11] = np.nan
        p = self._csv(tmp_path, x)
        with pytest.warns(RuntimeWarning):
            got = ds.load_txt_file(p)
        km = KMeans(n_clusters=3, random_state=0, max_iter=5).fit(got)
        assert np.isfinite(km.centers_).all()
        assert np.isfinite(km.inertia_)
