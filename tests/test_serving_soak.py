"""Serving soak (slow tier, `tools/serving_soak.sh`): a sustained
concurrent request stream across live generation hot-swaps.

Invariants asserted over the whole run (the round-9 acceptance bar):

- zero failed requests — every submitted request resolves to a response;
- zero TORN responses — each response decodes to exactly ONE generation
  the writer actually wrote (the linear-model oracle: ŷ − Σx == g);
- zero stale-after-adoption responses — per client, the served
  generation never goes backwards;
- ≥ 2 swaps observed under load, one-dispatch warm batches throughout,
  and a mid-stream corruption of the newest generation file neither
  fails a request nor serves garbage.

Knobs: DSLIB_SOAK_GENS (default 6), DSLIB_SOAK_CLIENTS (3),
DSLIB_SOAK_SECONDS (6).
"""

import os
import threading
import time

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.serving import ModelPool, PredictServer, ServePipeline
from dislib_tpu.utils.checkpoint import FitCheckpoint
from dislib_tpu.utils.faults import corrupt_snapshot

NF = 8
BUCKETS = (1, 8, 64)


def _state(g):
    return {"coef": np.ones((NF, 1), np.float32),
            "intercept": np.full(1, float(g), np.float32)}


def _build(state):
    lr = ds.LinearRegression()
    lr.coef_ = np.asarray(state["coef"], np.float32)
    lr.intercept_ = np.asarray(state["intercept"], np.float32)
    return ServePipeline(lr, n_features=NF)


@pytest.mark.slow
def test_serving_soak_across_hot_swaps(tmp_path):
    n_gens = int(os.environ.get("DSLIB_SOAK_GENS", "6"))
    n_clients = int(os.environ.get("DSLIB_SOAK_CLIENTS", "3"))
    seconds = float(os.environ.get("DSLIB_SOAK_SECONDS", "6"))
    path = str(tmp_path / "gen.npz")
    writer = FitCheckpoint(path, keep=2)
    writer.save(_state(1))
    pool = ModelPool(FitCheckpoint(path, keep=2), _build,
                     buckets=BUCKETS, poll_interval_s=0.02)
    rng = np.random.RandomState(0)
    x = rng.rand(4096, NF).astype(np.float32)
    written = [1.0]
    stop = threading.Event()
    errors = []

    def trainer():
        """Rotate generations (keep=2) under the live stream; one of the
        rotations is immediately corrupted — the PR-1 injector — so the
        soak also covers the verified-load fallback path."""
        gap = seconds / (n_gens + 1)
        for g in range(2, n_gens + 2):
            if stop.wait(gap):
                return
            writer.save(_state(g))
            written.append(float(g))
            if g == 3:
                corrupt_snapshot(path)

    def client(cid, srv, seen):
        crng = np.random.RandomState(cid)
        last_gen_val = 0.0
        while not stop.is_set():
            k = int(crng.randint(1, 9))
            start = int(crng.randint(0, len(x) - k))
            rows = x[start:start + k]
            try:
                r = srv.submit(rows).result(timeout=60)
            except Exception as e:  # noqa: BLE001 — any failure fails soak
                errors.append(f"client {cid}: {type(e).__name__}: {e}")
                return
            vals = np.round(r.values.ravel() - rows.sum(axis=1), 3)
            gens = np.unique(vals)
            if len(gens) != 1:
                errors.append(f"client {cid}: TORN response {gens}")
                return
            g = float(gens[0])
            if g != int(g):
                errors.append(f"client {cid}: non-generation value {g}")
                return
            if g < last_gen_val:
                errors.append(f"client {cid}: stale after adoption "
                              f"({g} after {last_gen_val})")
                return
            last_gen_val = g
            seen.add(g)

    with PredictServer(pool=pool, deadline_ms=2) as srv:
        seen_sets = [set() for _ in range(n_clients)]
        threads = [threading.Thread(target=client, args=(i, srv, s))
                   for i, s in enumerate(seen_sets)]
        tr = threading.Thread(target=trainer)
        for t in threads:
            t.start()
        tr.start()
        time.sleep(seconds)
        stop.set()
        tr.join()
        for t in threads:
            t.join()
        stats = srv.stats()

    assert not errors, "soak failures:\n  " + "\n  ".join(errors)
    seen = set().union(*seen_sets)
    assert seen <= set(written), f"served generations {seen} " \
        f"never written {written}"
    assert pool.adoptions >= 3, (  # initial + >=2 swaps under load
        f"only {pool.adoptions} adoptions in {seconds}s "
        f"(stats: {stats}, pool: {pool.stats()})")
    assert len(seen) >= 3, f"request stream only saw generations {seen}"
    assert stats["dispatches_per_batch_max"] == 1, stats
    assert stats["requests"] > 50, stats
