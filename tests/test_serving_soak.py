"""Serving soak (slow tier, `tools/serving_soak.sh`): a sustained
concurrent request stream across live generation hot-swaps.

Invariants asserted over the whole run (the round-9 acceptance bar):

- zero failed requests — every submitted request resolves to a response;
- zero TORN responses — each response decodes to exactly ONE generation
  the writer actually wrote (the linear-model oracle: ŷ − Σx == g);
- zero stale-after-adoption responses — per client, the served
  generation never goes backwards;
- ≥ 2 swaps observed under load, one-dispatch warm batches throughout,
  and a mid-stream corruption of the newest generation file neither
  fails a request nor serves garbage.

Round 15 adds the FLEET soak (``tools/serving_soak.sh --fleet``): three
tenants with distinct models on one ModelRouter under mixed-shape load,
one tenant taking a mid-stream canary that is promoted under fire — the
oracle encodes (tenant, generation) into every prediction, so a single
cross-tenant routing mistake or a torn promotion is a decoded wrong
number, not a vibe.

Knobs: DSLIB_SOAK_GENS (default 6), DSLIB_SOAK_CLIENTS (3),
DSLIB_SOAK_SECONDS (6).
"""

import os
import threading
import time

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.serving import (ModelPool, ModelRouter, PredictServer,
                                ServePipeline)
from dislib_tpu.utils.checkpoint import FitCheckpoint
from dislib_tpu.utils.faults import corrupt_snapshot

NF = 8
BUCKETS = (1, 8, 64)


def _state(g):
    return {"coef": np.ones((NF, 1), np.float32),
            "intercept": np.full(1, float(g), np.float32)}


def _build(state):
    lr = ds.LinearRegression()
    lr.coef_ = np.asarray(state["coef"], np.float32)
    lr.intercept_ = np.asarray(state["intercept"], np.float32)
    return ServePipeline(lr, n_features=NF)


@pytest.mark.slow
def test_serving_soak_across_hot_swaps(tmp_path):
    n_gens = int(os.environ.get("DSLIB_SOAK_GENS", "6"))
    n_clients = int(os.environ.get("DSLIB_SOAK_CLIENTS", "3"))
    seconds = float(os.environ.get("DSLIB_SOAK_SECONDS", "6"))
    path = str(tmp_path / "gen.npz")
    writer = FitCheckpoint(path, keep=2)
    writer.save(_state(1))
    pool = ModelPool(FitCheckpoint(path, keep=2), _build,
                     buckets=BUCKETS, poll_interval_s=0.02)
    rng = np.random.RandomState(0)
    x = rng.rand(4096, NF).astype(np.float32)
    written = [1.0]
    stop = threading.Event()
    errors = []

    def trainer():
        """Rotate generations (keep=2) under the live stream; one of the
        rotations is immediately corrupted — the PR-1 injector — so the
        soak also covers the verified-load fallback path."""
        gap = seconds / (n_gens + 1)
        for g in range(2, n_gens + 2):
            if stop.wait(gap):
                return
            writer.save(_state(g))
            written.append(float(g))
            if g == 3:
                corrupt_snapshot(path)

    def client(cid, srv, seen):
        crng = np.random.RandomState(cid)
        last_gen_val = 0.0
        while not stop.is_set():
            k = int(crng.randint(1, 9))
            start = int(crng.randint(0, len(x) - k))
            rows = x[start:start + k]
            try:
                r = srv.submit(rows).result(timeout=60)
            except Exception as e:  # noqa: BLE001 — any failure fails soak
                errors.append(f"client {cid}: {type(e).__name__}: {e}")
                return
            vals = np.round(r.values.ravel() - rows.sum(axis=1), 3)
            gens = np.unique(vals)
            if len(gens) != 1:
                errors.append(f"client {cid}: TORN response {gens}")
                return
            g = float(gens[0])
            if g != int(g):
                errors.append(f"client {cid}: non-generation value {g}")
                return
            if g < last_gen_val:
                errors.append(f"client {cid}: stale after adoption "
                              f"({g} after {last_gen_val})")
                return
            last_gen_val = g
            seen.add(g)

    with PredictServer(pool=pool, deadline_ms=2) as srv:
        seen_sets = [set() for _ in range(n_clients)]
        threads = [threading.Thread(target=client, args=(i, srv, s))
                   for i, s in enumerate(seen_sets)]
        tr = threading.Thread(target=trainer)
        for t in threads:
            t.start()
        tr.start()
        time.sleep(seconds)
        stop.set()
        tr.join()
        for t in threads:
            t.join()
        stats = srv.stats()

    assert not errors, "soak failures:\n  " + "\n  ".join(errors)
    seen = set().union(*seen_sets)
    assert seen <= set(written), f"served generations {seen} " \
        f"never written {written}"
    assert pool.adoptions >= 3, (  # initial + >=2 swaps under load
        f"only {pool.adoptions} adoptions in {seconds}s "
        f"(stats: {stats}, pool: {pool.stats()})")
    assert len(seen) >= 3, f"request stream only saw generations {seen}"
    assert stats["dispatches_per_batch_max"] == 1, stats
    assert stats["requests"] > 50, stats


# ---------------------------------------------------------------------------
# round-15 fleet soak: multi-tenant router under mixed-shape fire with a
# mid-stream canary promotion
# ---------------------------------------------------------------------------

def _tenant_pipe(tenant_idx: int, gen: int) -> ServePipeline:
    """ŷ = Σx + 1000·(tenant_idx+1) + gen: the decoded intercept names
    BOTH who should have answered and which generation did — one routing
    mistake anywhere in the fleet is a wrong thousands digit."""
    lr = ds.LinearRegression()
    lr.coef_ = np.ones((NF, 1), np.float32)
    lr.intercept_ = np.full(1, 1000.0 * (tenant_idx + 1) + gen,
                            np.float32)
    return ServePipeline(lr, n_features=NF)


@pytest.mark.slow
def test_fleet_soak_three_tenants_canary_promotion():
    seconds = float(os.environ.get("DSLIB_SOAK_SECONDS", "6"))
    tenants = ("alpha", "beta", "gamma")
    servers = {t: PredictServer(pipeline=_tenant_pipe(i, 1),
                                buckets=BUCKETS, name=f"{t}-gen1")
               for i, t in enumerate(tenants)}
    canary = PredictServer(pipeline=_tenant_pipe(1, 2), buckets=BUCKETS,
                           name="beta-gen2")
    router = ModelRouter(name="fleet")
    for t in tenants:
        router.add_tenant(t, servers[t], quota_rows=4096)
    stop = threading.Event()
    promoted = threading.Event()
    errors = []
    shapes = (1, 3, 8, 20, 64)          # mixed, all within the ladder
    gens_seen = {t: set() for t in tenants}

    def client(cid, tenant, tenant_idx):
        crng = np.random.RandomState(cid)
        base = 1000.0 * (tenant_idx + 1)
        i = 0
        while not stop.is_set():
            i += 1
            k = int(shapes[crng.randint(0, len(shapes))])
            rows = crng.rand(k, NF).astype(np.float32)
            sent_after_promote = promoted.is_set()
            try:
                r = router.submit(rows, tenant,
                                  key=f"{tenant}:{cid}:{i}").result(
                                      timeout=60)
            except Exception as e:  # noqa: BLE001 — any failure fails soak
                errors.append(f"{tenant}/{cid}: {type(e).__name__}: {e}")
                return
            vals = np.round(r.values.ravel() - rows.sum(axis=1), 3)
            decoded = np.unique(vals)
            if len(decoded) != 1:
                errors.append(f"{tenant}/{cid}: TORN response {decoded}")
                return
            g = float(decoded[0]) - base
            if g not in (1.0, 2.0):     # wrong tenant's model answered
                errors.append(f"{tenant}/{cid}: cross-tenant leak — "
                              f"decoded {decoded[0]} (base {base})")
                return
            if g == 2.0 and tenant != "beta":
                errors.append(f"{tenant}/{cid}: canary generation leaked "
                              "outside beta")
                return
            if sent_after_promote and tenant == "beta" and g != 2.0:
                errors.append(f"beta/{cid}: generation 1 served after "
                              "promotion")
                return
            gens_seen[tenant].add(g)

    with router:
        # fleet-wide dispatch accounting: the per-batch deltas inside
        # each server cross-inflate when four servers dispatch
        # concurrently in one process (documented in stats()), so the
        # one-dispatch-per-batch invariant is asserted GLOBALLY below —
        # total fused dispatches == total batches (+ canary warmup)
        from dislib_tpu.utils import profiling as prof
        prof.reset_counters()
        threads = [threading.Thread(target=client, args=(17 * i + j, t, i))
                   for i, t in enumerate(tenants) for j in range(2)]
        for th in threads:
            th.start()
        time.sleep(seconds / 3)
        router.set_canary("beta", canary, fraction=0.5)
        time.sleep(seconds / 3)
        router.promote("beta")
        promoted.set()
        time.sleep(seconds / 3)
        stop.set()
        for th in threads:
            th.join()
        rstats = router.stats()
        sstats = {t: servers[t].stats() for t in tenants}
        cstats = canary.stats()
        fused_dispatches = prof.counters()["dispatch_by"].get(
            "fused_chain", 0)

    assert not errors, "fleet soak failures:\n  " + "\n  ".join(errors)
    # every tenant served, from its own model only
    for t in tenants:
        assert gens_seen[t], f"tenant {t} never served"
    # the canary really took traffic before AND kept it after promotion
    assert gens_seen["beta"] == {1.0, 2.0}, gens_seen["beta"]
    assert rstats["beta"]["promotions"] == 1
    assert cstats["tenants"]["beta:canary"]["requests"] > 0
    assert cstats["tenants"]["beta"]["requests"] > 0    # post-promote
    # one fused dispatch per batch ACROSS THE FLEET: every served batch
    # on all four servers costs exactly one fused dispatch, plus the
    # canary's mid-stream warmup (one dispatch per ladder bucket)
    total_batches = sum(s["batches"] for s in sstats.values()) \
        + cstats["batches"]
    assert fused_dispatches == total_batches + len(BUCKETS), (
        fused_dispatches, total_batches, sstats, cstats)
    # the server-side tenant labels never bled across servers
    for t in tenants:
        foreign = set(sstats[t]["tenants"]) - {t}
        assert not foreign, f"{t}'s server saw foreign tenants {foreign}"
    assert set(cstats["tenants"]) <= {"beta", "beta:canary"}
    # nobody was shed (quotas generous, queues never filled)
    assert all(rstats[t]["quota_shed"] == 0 for t in tenants), rstats
    assert all(s["shed"] == 0 for s in sstats.values())
