"""Bench orchestrator tests (BASELINE.md measurement rules; round-2 VERDICT
weak #7): the parent must survive a wedged config (skip-and-continue), abort
after two consecutive timeouts, and fail fast when the backend probe dies.

These spawn the real ``bench.py`` parent with the fake-hang test hook; no
config body runs, so they are cheap."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout=120):
    env = {**os.environ, "BENCH_SMOKE": "1", "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "", **env_extra}
    return subprocess.run([sys.executable, BENCH], env=env, timeout=timeout,
                          capture_output=True, text=True)


def _lines(out):
    return [json.loads(ln) for ln in out.strip().splitlines() if ln.strip()]


class TestBenchOrchestrator:
    def test_skip_and_continue_then_abort_on_second_timeout(self):
        # hang the first two configs (dispatch_rtt, kmeans_smoke) so no
        # config body ever really runs — keeps the test cheap/deterministic
        res = _run({"DSLIB_BENCH_FAKE_HANG": "dispatch_rtt,kmeans_smoke",
                    "DSLIB_BENCH_CONFIG_S": "5"})
        assert res.returncode == 2
        lines = _lines(res.stdout)
        errs = [l for l in lines if l.get("error")]
        # first hang: skipped-and-continuing; second: abort
        assert any("skipped, continuing" in l["error"] for l in errs)
        assert lines[-1]["metric"] == "abort"
        assert "two consecutive" in lines[-1]["error"]

    def test_probe_failure_is_fast_and_recorded(self):
        res = _run({"JAX_PLATFORMS": "bogus_platform",
                    "DSLIB_BENCH_PROBE_S": "30"})
        assert res.returncode == 2
        lines = _lines(res.stdout)
        assert lines[0]["metric"] == "backend_init"
        assert "probe failed" in lines[0]["error"]

    def test_probe_failure_emits_stale_fallback(self):
        """Round-5 (r4 VERDICT weak #8): a wedged/failed probe re-emits the
        last green local capture marked stale — rc stays 2 for the driver,
        but the artifact is informative instead of one error line.

        Round-9 satellite (ROADMAP item 5 follow-up — BENCH_r05.json's
        stale chip rows read like fresh evidence): the fallback must ALSO
        lead with an explicit ``stale_carryover`` record, mark every
        replayed row ``stale_carryover: true``, and shout on stderr."""
        res = _run({"JAX_PLATFORMS": "bogus_platform",
                    "DSLIB_BENCH_PROBE_S": "30"})
        assert res.returncode == 2
        lines = _lines(res.stdout)
        stale = [l for l in lines if l.get("stale")]
        # the leading top-level flag record precedes every replayed row
        flags = [i for i, l in enumerate(lines)
                 if l.get("metric") == "stale_carryover"]
        assert flags, "no leading stale_carryover record"
        assert lines[flags[0]]["stale_carryover"] is True
        assert all(i > flags[0] for i, l in enumerate(lines)
                   if l.get("stale"))
        assert all(l.get("stale_carryover") for l in stale)
        assert "STALE CARRYOVER" in res.stderr
        # round-10 satellite: carryover provenance — the leading record
        # NAMES every replayed metric, each row is explicitly non-fresh,
        # and stale_origin survives multi-hop replays (a replayed replay
        # keeps the capture its number was actually measured in)
        assert lines[flags[0]]["metrics"] == [l["metric"] for l in stale]
        assert all(l.get("fresh") is False for l in stale)
        assert all(l.get("stale_origin", "").startswith("BENCH_local_r")
                   for l in stale)
        # BENCH_local_r05.jsonl is committed in-repo, so the fallback has
        # a capture to replay; every replayed row is flagged + attributed
        assert stale, "no stale fallback rows emitted"
        assert all(l.get("stale_source", "").startswith("BENCH_local_r")
                   for l in stale)
        assert all(not l.get("error") for l in stale)
        # ...and fill_baseline must REFUSE to treat stale rows as measured
        # — run against a COPY of BASELINE.md (FILL_BASELINE_PATH hook):
        # mutating the checked-in file would risk wiping it if this test
        # process is SIGKILLed before a restore
        import re
        import shutil
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            name = os.path.join(td, "rows.jsonl")
            with open(name, "w") as f:
                for l in lines:
                    f.write(json.dumps(l) + "\n")
            md = os.path.join(td, "BASELINE.md")
            shutil.copy(os.path.join(REPO, "BASELINE.md"), md)
            out = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              "fill_baseline.py"), name],
                capture_output=True, text=True, cwd=REPO,
                env={**os.environ, "FILL_BASELINE_PATH": md})
            m = re.search(r"updated with (\d+) measured rows", out.stdout)
            assert m, f"fill_baseline failed: {out.stdout} {out.stderr}"
            assert m.group(1) == "0", out.stdout
