"""Model-selection tests (reference: test_model_selection — SURVEY.md §3.4)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.classification import KNeighborsClassifier
from dislib_tpu.model_selection import KFold, GridSearchCV, RandomizedSearchCV


def _blobs(rng, n=120, d=3, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.2 * rng.randn(n // k, d) for i in range(k)])
    y = np.repeat(np.arange(k), n // k).astype(np.float32)
    return x.astype(np.float32), y.reshape(-1, 1)


class TestKFold:
    def test_partition(self, rng):
        x, y = _blobs(rng, n=90)
        folds = list(KFold(n_splits=3).split(ds.array(x), ds.array(y)))
        assert len(folds) == 3
        test_rows = np.vstack([f[2].collect() for f in folds])
        assert test_rows.shape == x.shape
        # every original row appears exactly once across test folds
        assert len(np.unique(test_rows @ rng.rand(3).astype(np.float32))) >= 85

    def test_sizes(self, rng):
        x, _ = _blobs(rng, n=90)
        for xt, _, xv, _ in KFold(n_splits=4).split(ds.array(x)):
            assert xt.shape[0] + xv.shape[0] == 90
            assert xv.shape[0] in (22, 23)

    def test_shuffle_deterministic(self, rng):
        x, _ = _blobs(rng, n=60)
        f1 = [f[2].collect() for f in KFold(3, shuffle=True, random_state=0).split(ds.array(x))]
        f2 = [f[2].collect() for f in KFold(3, shuffle=True, random_state=0).split(ds.array(x))]
        for a, b in zip(f1, f2):
            np.testing.assert_array_equal(a, b)

    def test_bad_n_splits(self, rng):
        x, _ = _blobs(rng, n=30)
        with pytest.raises(ValueError):
            list(KFold(n_splits=1).split(ds.array(x)))


class TestGridSearchCV:
    def test_finds_best_k(self, rng):
        x, y = _blobs(rng, n=120, k=3)
        perm = rng.permutation(len(x))
        x, y = x[perm], y[perm]
        gs = GridSearchCV(KNeighborsClassifier(),
                          {"n_neighbors": [1, 3, 5]},
                          cv=KFold(n_splits=3, shuffle=True, random_state=0))
        gs.fit(ds.array(x), ds.array(y))
        assert set(gs.cv_results_.keys()) >= {"params", "mean_test_score",
                                              "std_test_score", "rank_test_score"}
        assert len(gs.cv_results_["params"]) == 3
        assert gs.best_score_ > 0.9
        assert gs.best_estimator_.score(ds.array(x), ds.array(y)) > 0.9
        assert gs.predict(ds.array(x)).shape == (120, 1)

    def test_unsupervised_estimator(self, rng):
        x, _ = _blobs(rng, n=90, k=3)
        gs = GridSearchCV(KMeans(random_state=0, max_iter=20),
                          {"n_clusters": [2, 3]}, cv=3)
        gs.fit(ds.array(x))
        assert len(gs.cv_results_["params"]) == 2
        assert hasattr(gs, "best_params_")

    def test_multi_grid(self, rng):
        x, y = _blobs(rng, n=60)
        gs = GridSearchCV(KNeighborsClassifier(),
                          [{"n_neighbors": [1, 3]},
                           {"n_neighbors": [5], "weights": ["distance"]}],
                          cv=2)
        gs.fit(ds.array(x), ds.array(y))
        assert len(gs.cv_results_["params"]) == 3


class TestRandomizedSearchCV:
    def test_samples_n_iter(self, rng):
        x, y = _blobs(rng, n=60)
        rs = RandomizedSearchCV(KNeighborsClassifier(),
                                {"n_neighbors": [1, 2, 3, 4, 5]},
                                n_iter=4, random_state=0,
                                cv=KFold(n_splits=2, shuffle=True, random_state=0))
        rs.fit(ds.array(x), ds.array(y))
        assert len(rs.cv_results_["params"]) == 4
        assert rs.best_score_ > 0.8

    def test_scipy_distribution(self, rng):
        from scipy.stats import randint
        x, y = _blobs(rng, n=60)
        rs = RandomizedSearchCV(KNeighborsClassifier(),
                                {"n_neighbors": randint(1, 6)},
                                n_iter=3, cv=2, random_state=1)
        rs.fit(ds.array(x), ds.array(y))
        ks = [p["n_neighbors"] for p in rs.cv_results_["params"]]
        assert all(1 <= k < 6 for k in ks)


class TestAsyncDispatch:
    """SURVEY §4.5 concurrency contract: all candidate fits dispatch before
    any score is read, and the async path is score-identical to serial."""

    def test_async_matches_serial(self, rng, monkeypatch):
        from dislib_tpu.base import BaseEstimator
        x = ds.array(rng.rand(120, 4).astype(np.float32), block_size=(30, 4))
        grid = {"n_clusters": [2, 3, 4], "random_state": [0]}
        fast = GridSearchCV(KMeans(random_state=0), grid, cv=3, refit=False)
        fast.fit(x)
        # force every estimator onto the synchronous fallback
        monkeypatch.setattr(KMeans, "_fit_async", BaseEstimator._fit_async)
        monkeypatch.setattr(KMeans, "_score_async", BaseEstimator._score_async)
        slow = GridSearchCV(KMeans(random_state=0), grid, cv=3, refit=False)
        slow.fit(x)
        np.testing.assert_allclose(fast.cv_results_["mean_test_score"],
                                   slow.cv_results_["mean_test_score"],
                                   rtol=1e-5)
        assert fast.best_params_ == slow.best_params_

    def test_all_fits_dispatch_before_any_score(self, rng, monkeypatch):
        events = []
        orig_fit, orig_score = KMeans._fit_async, KMeans._score_async

        def spy_fit(self, x, y=None):
            events.append("fit")
            return orig_fit(self, x, y)

        def spy_score(self, state, x, y=None):
            events.append("score")
            return orig_score(self, state, x, y)

        monkeypatch.setattr(KMeans, "_fit_async", spy_fit)
        monkeypatch.setattr(KMeans, "_score_async", spy_score)
        x = ds.array(rng.rand(90, 3).astype(np.float32))
        GridSearchCV(KMeans(random_state=0, max_iter=3),
                     {"n_clusters": [2, 3, 4]}, cv=2, refit=False).fit(x)
        # per fold: 3 fits then 3 scores — never interleaved
        assert events == ["fit"] * 3 + ["score"] * 3 + ["fit"] * 3 + ["score"] * 3


class TestAsyncAdoption:
    """Round-3 widening of the §4.5 contract: GMM / LinearRegression /
    Lasso / ALS dispatch async, and the silent fallback is logged."""

    def test_gmm_trials_dispatch_before_any_host_read(self, rng, monkeypatch):
        import jax
        from dislib_tpu.cluster import GaussianMixture
        events = []
        real_get = jax.device_get
        orig_fit = GaussianMixture._fit_async

        def spy_get(v):
            events.append("host_read")
            return real_get(v)

        def spy_fit(self, x, y=None):
            events.append("fit")
            state = orig_fit(self, x, y)
            assert state is not None, "GMM must be truly async, not fallback"
            return state

        monkeypatch.setattr(jax, "device_get", spy_get)
        monkeypatch.setattr(GaussianMixture, "_fit_async", spy_fit)
        x = ds.array(rng.rand(80, 3).astype(np.float32))
        GridSearchCV(GaussianMixture(max_iter=5, random_state=0),
                     {"n_components": [2, 3]}, cv=2, refit=False).fit(x)
        # 2 candidates × 2 folds dispatch; no device_get may interleave —
        # the whole fit (incl. the KMeans init) stays on device
        assert events == ["fit", "fit"] * 2

    def test_gmm_async_matches_serial(self, rng, monkeypatch):
        from dislib_tpu.base import BaseEstimator
        from dislib_tpu.cluster import GaussianMixture
        x = ds.array(rng.rand(90, 3).astype(np.float32))
        grid = {"n_components": [2, 3]}
        fast = GridSearchCV(GaussianMixture(max_iter=10, random_state=0),
                            grid, cv=2, refit=False)
        fast.fit(x)
        monkeypatch.setattr(GaussianMixture, "_fit_async",
                            BaseEstimator._fit_async)
        monkeypatch.setattr(GaussianMixture, "_score_async",
                            BaseEstimator._score_async)
        slow = GridSearchCV(GaussianMixture(max_iter=10, random_state=0),
                            grid, cv=2, refit=False)
        slow.fit(x)
        np.testing.assert_allclose(fast.cv_results_["mean_test_score"],
                                   slow.cv_results_["mean_test_score"],
                                   rtol=1e-4)

    def test_linreg_async_matches_serial(self, rng):
        from dislib_tpu.regression import LinearRegression
        x = rng.rand(80, 3).astype(np.float32)
        y = (x @ np.array([1.0, -2.0, 0.5]) + 0.3).astype(np.float32)[:, None]
        grid = {"fit_intercept": [True, False]}
        fast = GridSearchCV(LinearRegression(),
                            grid, cv=KFold(n_splits=2), refit=False)
        fast.fit(ds.array(x), ds.array(y))
        # serial oracle: plain fit + score per (candidate, fold)
        want = []
        for fi in grid["fit_intercept"]:
            scores = []
            for xt, yt, xv, yv in KFold(n_splits=2).split(ds.array(x),
                                                          ds.array(y)):
                est = LinearRegression(fit_intercept=fi).fit(xt, yt)
                scores.append(est.score(xv, yv))
            want.append(np.mean(scores))
        np.testing.assert_allclose(fast.cv_results_["mean_test_score"],
                                   want, rtol=1e-4)
        assert fast.best_params_ == {"fit_intercept": True}

    def test_lasso_async_score_matches_sync(self, rng):
        from dislib_tpu.regression import Lasso
        x = rng.rand(60, 4).astype(np.float32)
        y = (x @ np.array([2.0, 0.0, -1.0, 0.0]) + 0.1
             * rng.randn(60)).astype(np.float32)[:, None]
        xa, ya = ds.array(x), ds.array(y)
        est = Lasso(lmbd=0.1, max_iter=50)
        state = est._fit_async(xa, ya)
        dev_score = float(est._score_async(state, xa, ya))
        est._fit_finalize(state)
        assert np.isclose(dev_score, est.score(xa, ya), rtol=1e-4)

    def test_als_async_matches_sync(self, rng):
        from dislib_tpu.recommendation import ALS
        r = rng.rand(24, 12).astype(np.float32)
        r[rng.rand(24, 12) > 0.4] = 0.0
        xa = ds.array(r)
        sync = ALS(n_f=3, max_iter=8, random_state=0).fit(xa)
        a = ALS(n_f=3, max_iter=8, random_state=0)
        a._fit_finalize(a._fit_async(xa))
        np.testing.assert_allclose(a.users_, sync.users_, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(a.items_, sync.items_, rtol=1e-4, atol=1e-5)
        assert a.n_iter_ == sync.n_iter_

    def test_knn_async_score_matches_sync(self, rng):
        x, y = _blobs(rng, n=90, k=3)
        perm = rng.permutation(len(x))
        xa, ya = ds.array(x[perm]), ds.array(y[perm])
        est = KNeighborsClassifier(n_neighbors=3)
        state = est._fit_async(xa, ya)
        dev = float(est._score_async(state, xa, ya))
        assert np.isclose(dev, est.score(xa, ya), rtol=1e-6)

    def test_knn_async_unseen_labels_never_correct(self, rng):
        x, y = _blobs(rng, n=60, k=2)
        est = KNeighborsClassifier(n_neighbors=1)
        state = est._fit_async(ds.array(x), ds.array(y))
        y_unseen = ds.array(np.full_like(y, 99.0))
        assert float(est._score_async(state, ds.array(x), y_unseen)) == 0.0

    def test_folds_pipeline_two_deep(self, rng, monkeypatch):
        """Fold f's host reads happen only after fold f+1's dispatch —
        the submit-before-wait contract across folds, memory-bounded.
        Forced ON (auto disables it on the cpu backend rig)."""
        import dislib_tpu.model_selection.search as search_mod
        monkeypatch.setattr(search_mod, "_PIPELINE_FOLDS", True)
        events = []
        orig_fit, orig_score = KMeans._fit_async, KMeans._score_async

        class _ReadLogged:
            def __init__(self, v):
                self.v = v

            def __float__(self):
                events.append("read")
                return float(self.v)

        def spy_fit(self, x, y=None):
            events.append("fit")
            return orig_fit(self, x, y)

        def spy_score(self, state, x, y=None):
            return _ReadLogged(orig_score(self, state, x, y))

        monkeypatch.setattr(KMeans, "_fit_async", spy_fit)
        monkeypatch.setattr(KMeans, "_score_async", spy_score)
        x = ds.array(rng.rand(90, 3).astype(np.float32))
        GridSearchCV(KMeans(random_state=0, max_iter=3),
                     {"n_clusters": [2, 3]}, cv=3, refit=False).fit(x)
        # 3 folds × 2 candidates: fold0 fits, fold1 fits, fold0 reads,
        # fold2 fits, fold1 reads, fold2 reads
        assert events == (["fit"] * 2 + ["fit"] * 2 + ["read"] * 2
                          + ["fit"] * 2 + ["read"] * 2 + ["read"] * 2)

    def test_forest_async_matches_sync(self, rng):
        from dislib_tpu.trees import (RandomForestClassifier,
                                      RandomForestRegressor)
        x, y = _blobs(rng, n=90, k=3)
        perm = rng.permutation(len(x))
        xa, ya = ds.array(x[perm]), ds.array(y[perm])
        est = RandomForestClassifier(n_estimators=4, random_state=0)
        state = est._fit_async(xa, ya)
        dev = float(est._score_async(state, xa, ya))
        est._fit_finalize(state)
        assert np.isclose(dev, est.score(xa, ya), rtol=1e-6)
        # same-seed sync fit lands on identical trees
        sync = RandomForestClassifier(n_estimators=4, random_state=0) \
            .fit(xa, ya)
        np.testing.assert_array_equal(est._feats, sync._feats)

        xr = rng.rand(80, 3).astype(np.float32)
        yr = (xr @ np.array([1.0, -2.0, 0.5])).astype(np.float32)[:, None]
        reg = RandomForestRegressor(n_estimators=4, random_state=0)
        st = reg._fit_async(ds.array(xr), ds.array(yr))
        dev_r2 = float(reg._score_async(st, ds.array(xr), ds.array(yr)))
        reg._fit_finalize(st)
        assert np.isclose(dev_r2, reg.score(ds.array(xr), ds.array(yr)),
                          rtol=1e-4, atol=1e-5)

    def test_forest_grid_search_async_dispatch(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        x, y = _blobs(rng, n=90, k=3)
        perm = rng.permutation(len(x))
        gs = GridSearchCV(RandomForestClassifier(random_state=0),
                          {"n_estimators": [2, 4]}, cv=2, refit=False)
        gs.fit(ds.array(x[perm]), ds.array(y[perm]))
        assert len(gs.cv_results_["params"]) == 2
        assert gs.best_score_ > 0.8

    def test_fallback_notice_logged_once(self, rng, caplog):
        import logging
        from dislib_tpu.base import BaseEstimator
        import dislib_tpu.base as base_mod

        class _NoAsync(BaseEstimator):
            def __init__(self, a=1):
                self.a = a

            def fit(self, x, y=None):
                self.done_ = True
                return self

            def score(self, x, y=None):
                return float(self.a)

        base_mod._ASYNC_FALLBACK_NOTICED.discard("_NoAsync")
        x, _ = _blobs(rng, n=60)
        with caplog.at_level(logging.INFO, logger="dslib.search"):
            GridSearchCV(_NoAsync(), {"a": [1, 2]},
                         cv=2, refit=False).fit(ds.array(x))
        notices = [r for r in caplog.records
                   if "does not implement _fit_async" in r.message]
        assert len(notices) == 1


class TestScorerStrings:
    def test_accuracy_scorer(self, rng):
        x = np.vstack([rng.randn(30, 2) - 3, rng.randn(30, 2) + 3]).astype(np.float32)
        y = np.r_[np.zeros(30), np.ones(30)].astype(np.float32)
        sh = rng.permutation(60)
        xa, ya = ds.array(x[sh]), ds.array(y[sh][:, None])
        gs = GridSearchCV(KNeighborsClassifier(), {"n_neighbors": [1, 3]},
                          cv=2, scoring="accuracy", refit=False)
        gs.fit(xa, ya)
        assert gs.best_score_ > 0.9

    def test_r2_scorer(self, rng):
        from dislib_tpu.regression import LinearRegression
        x = rng.rand(80, 3).astype(np.float32)
        y = (x @ np.array([1.0, -2.0, 0.5]) + 0.3).astype(np.float32)
        gs = GridSearchCV(LinearRegression(), {"fit_intercept": [True, False]},
                          cv=2, scoring="r2", refit=False)
        gs.fit(ds.array(x), ds.array(y[:, None]))
        assert gs.best_score_ > 0.99
        assert gs.best_params_ == {"fit_intercept": True}

    def test_unknown_scorer_raises(self, rng):
        x = ds.array(rng.rand(20, 2))
        with pytest.raises(ValueError, match="unknown scorer"):
            GridSearchCV(KMeans(), {"n_clusters": [2]}, cv=2,
                         scoring="zzz").fit(x)


class TestAsyncProtocolFallbacks:
    def test_default_score_async_finalizes_first(self, rng):
        """An estimator with _fit_async but no custom _score_async must be
        scored FITTED — the base fallback materialises the handle."""
        from dislib_tpu.base import BaseEstimator

        class AsyncOnly(BaseEstimator):
            def __init__(self, a=1):
                self.a = a

            def fit(self, x, y=None):
                self._fit_finalize(self._fit_async(x, y))
                return self

            def _fit_async(self, x, y=None):
                return {"val": float(self.a)}

            def _fit_finalize(self, state):
                if state is not None:
                    self.val_ = state["val"]

            def score(self, x, y=None):
                return self.val_          # raises if not finalised

        x = ds.array(rng.rand(30, 3).astype(np.float32))
        gs = GridSearchCV(AsyncOnly(), {"a": [1, 2]}, cv=2, refit=False)
        gs.fit(x)
        assert gs.best_params_ == {"a": 2}


class TestPipelinedDispatchOrder:
    """Proof of the §4.5 submit-all-before-wait contract on the PIPELINED
    branch (the TPU policy — round-3 verdict weak #3: on the cpu rig the
    auto policy deliberately serializes, so until round 4 the pipelined
    path's ordering was exercised nowhere).

    A tracing KMeans logs every `_fit_async` / `_score_async` dispatch and
    every host read (via a __float__ shim around the device score).  The
    invariant pinned: when the j-th host read happens, at least
    min(n_folds, j//n_cand + 2) folds' worth of trials must ALREADY be
    dispatched — i.e. fold f's scores are only read after fold f+1 is
    fully in flight.  Any blocking read re-entering the dispatch loop
    (per-trial, per-candidate, or per-fold serialization) breaks it.
    """

    def test_every_dispatch_precedes_first_read(self, rng):
        from dislib_tpu.model_selection import search as search_mod

        events = []

        class TracingScalar:
            def __init__(self, v):
                self.v = v

            def __float__(self):
                events.append(("host_read",))
                return float(self.v)

        class TracingKMeans(KMeans):
            def _fit_async(self, x, y=None):
                events.append(("fit_dispatch",))
                return super()._fit_async(x, y)

            def _score_async(self, state, x, y=None):
                events.append(("score_dispatch",))
                return TracingScalar(super()._score_async(state, x, y))

        x, _ = _blobs(rng, n=96, k=3)
        n_cand, n_folds = 3, 3
        old = search_mod._PIPELINE_FOLDS
        search_mod._PIPELINE_FOLDS = True      # force the TPU policy
        try:
            gs = GridSearchCV(TracingKMeans(random_state=0, max_iter=5),
                              {"n_clusters": [2, 3, 4]}, cv=n_folds,
                              refit=False)
            gs.fit(ds.array(x))
        finally:
            search_mod._PIPELINE_FOLDS = old

        fits = reads = 0
        for ev in events:
            if ev[0] == "fit_dispatch":
                fits += 1
            elif ev[0] == "host_read":
                need = min(n_folds, reads // n_cand + 2) * n_cand
                assert fits >= need, \
                    f"host read #{reads} after only {fits} fit dispatches " \
                    f"(need {need}): a blocking read re-entered the " \
                    "dispatch loop"
                reads += 1
        assert fits == n_cand * n_folds and reads == n_cand * n_folds

    def test_serialized_order_would_fail_invariant(self):
        """The invariant is sharp: the cpu throttle's read-each-fold order
        violates it (meta-test that the assertion can actually fail)."""
        n_cand, n_folds = 3, 3
        serialized = (["fit_dispatch"] * n_cand + ["host_read"] * n_cand) \
            * n_folds
        fits = reads = 0
        violated = False
        for ev in serialized:
            if ev == "fit_dispatch":
                fits += 1
            else:
                if fits < min(n_folds, reads // n_cand + 2) * n_cand:
                    violated = True
                reads += 1
        assert violated
