"""Model-selection tests (reference: test_model_selection — SURVEY.md §3.4)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.classification import KNeighborsClassifier
from dislib_tpu.model_selection import KFold, GridSearchCV, RandomizedSearchCV


def _blobs(rng, n=120, d=3, k=3):
    centers = rng.rand(k, d) * 10
    x = np.vstack([centers[i] + 0.2 * rng.randn(n // k, d) for i in range(k)])
    y = np.repeat(np.arange(k), n // k).astype(np.float32)
    return x.astype(np.float32), y.reshape(-1, 1)


class TestKFold:
    def test_partition(self, rng):
        x, y = _blobs(rng, n=90)
        folds = list(KFold(n_splits=3).split(ds.array(x), ds.array(y)))
        assert len(folds) == 3
        test_rows = np.vstack([f[2].collect() for f in folds])
        assert test_rows.shape == x.shape
        # every original row appears exactly once across test folds
        assert len(np.unique(test_rows @ rng.rand(3).astype(np.float32))) >= 85

    def test_sizes(self, rng):
        x, _ = _blobs(rng, n=90)
        for xt, _, xv, _ in KFold(n_splits=4).split(ds.array(x)):
            assert xt.shape[0] + xv.shape[0] == 90
            assert xv.shape[0] in (22, 23)

    def test_shuffle_deterministic(self, rng):
        x, _ = _blobs(rng, n=60)
        f1 = [f[2].collect() for f in KFold(3, shuffle=True, random_state=0).split(ds.array(x))]
        f2 = [f[2].collect() for f in KFold(3, shuffle=True, random_state=0).split(ds.array(x))]
        for a, b in zip(f1, f2):
            np.testing.assert_array_equal(a, b)

    def test_bad_n_splits(self, rng):
        x, _ = _blobs(rng, n=30)
        with pytest.raises(ValueError):
            list(KFold(n_splits=1).split(ds.array(x)))


class TestGridSearchCV:
    def test_finds_best_k(self, rng):
        x, y = _blobs(rng, n=120, k=3)
        perm = rng.permutation(len(x))
        x, y = x[perm], y[perm]
        gs = GridSearchCV(KNeighborsClassifier(),
                          {"n_neighbors": [1, 3, 5]},
                          cv=KFold(n_splits=3, shuffle=True, random_state=0))
        gs.fit(ds.array(x), ds.array(y))
        assert set(gs.cv_results_.keys()) >= {"params", "mean_test_score",
                                              "std_test_score", "rank_test_score"}
        assert len(gs.cv_results_["params"]) == 3
        assert gs.best_score_ > 0.9
        assert gs.best_estimator_.score(ds.array(x), ds.array(y)) > 0.9
        assert gs.predict(ds.array(x)).shape == (120, 1)

    def test_unsupervised_estimator(self, rng):
        x, _ = _blobs(rng, n=90, k=3)
        gs = GridSearchCV(KMeans(random_state=0, max_iter=20),
                          {"n_clusters": [2, 3]}, cv=3)
        gs.fit(ds.array(x))
        assert len(gs.cv_results_["params"]) == 2
        assert hasattr(gs, "best_params_")

    def test_multi_grid(self, rng):
        x, y = _blobs(rng, n=60)
        gs = GridSearchCV(KNeighborsClassifier(),
                          [{"n_neighbors": [1, 3]},
                           {"n_neighbors": [5], "weights": ["distance"]}],
                          cv=2)
        gs.fit(ds.array(x), ds.array(y))
        assert len(gs.cv_results_["params"]) == 3


class TestRandomizedSearchCV:
    def test_samples_n_iter(self, rng):
        x, y = _blobs(rng, n=60)
        rs = RandomizedSearchCV(KNeighborsClassifier(),
                                {"n_neighbors": [1, 2, 3, 4, 5]},
                                n_iter=4, random_state=0,
                                cv=KFold(n_splits=2, shuffle=True, random_state=0))
        rs.fit(ds.array(x), ds.array(y))
        assert len(rs.cv_results_["params"]) == 4
        assert rs.best_score_ > 0.8

    def test_scipy_distribution(self, rng):
        from scipy.stats import randint
        x, y = _blobs(rng, n=60)
        rs = RandomizedSearchCV(KNeighborsClassifier(),
                                {"n_neighbors": randint(1, 6)},
                                n_iter=3, cv=2, random_state=1)
        rs.fit(ds.array(x), ds.array(y))
        ks = [p["n_neighbors"] for p in rs.cv_results_["params"]]
        assert all(1 <= k < 6 for k in ks)
