"""Minimal, dependency-free stand-in for the slice of `hypothesis` the
property tier uses (round-8 satellite: the env-gated property tests must
RUN on a rig without the package instead of silently skipping).

Semantics: deterministic seeded random sampling — every example's RNG is
seeded from (test qualname, example index), so failures reproduce
bit-identically and a plain re-run replays the exact same examples.  No
shrinking, no example database, no deadline handling: when the real
`hypothesis` is installed (the ``dev`` extra in pyproject.toml),
``tests/test_property.py`` prefers it automatically and gains the full
search.  Covered API: ``given``, ``settings(max_examples, deadline)``,
``strategies.integers/floats/lists/composite/data``.
"""

from __future__ import annotations

import functools
import types
import zlib

import numpy as np

__all__ = ["given", "settings", "strategies"]


class _Strategy:
    def __init__(self, sample):
        self._sample = sample

    def sample(self, rng):
        return self._sample(rng)


def _integers(lo, hi):
    lo, hi = int(lo), int(hi)
    if hi < lo:           # hypothesis raises too; fail loudly, not silently
        raise ValueError(f"integers({lo}, {hi}): empty range")
    return _Strategy(lambda rng: int(rng.randint(lo, hi + 1)))


def _floats(lo, hi):
    lo, hi = float(lo), float(hi)
    return _Strategy(lambda rng: float(lo + (hi - lo) * rng.random_sample()))


def _lists(elem, min_size=0, max_size=None):
    max_size = (min_size + 10) if max_size is None else max_size

    def sample(rng):
        k = int(rng.randint(int(min_size), int(max_size) + 1))
        return [elem.sample(rng) for _ in range(k)]
    return _Strategy(sample)


def _composite(fn):
    """``@st.composite`` — the wrapped function receives ``draw`` first."""
    def build(*args, **kwargs):
        return _Strategy(
            lambda rng: fn(lambda st: st.sample(rng), *args, **kwargs))
    return build


class _DataObject:
    """``st.data()`` value: mid-test draws share the example's RNG."""

    def __init__(self, rng):
        self._rng = rng

    def draw(self, st, label=None):
        return st.sample(self._rng)


def _data():
    return _Strategy(_DataObject)


strategies = types.SimpleNamespace(
    integers=_integers, floats=_floats, lists=_lists, composite=_composite,
    data=_data)


def settings(max_examples=20, deadline=None, **_ignored):
    """Decorator recording the example budget (deadline is accepted and
    ignored — the lite runner never times out an example)."""
    def deco(fn):
        fn._hl_max_examples = int(max_examples)
        return fn
    return deco


def given(*strats):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # read the budget at CALL time, checking the wrapper first:
            # @settings above @given tags the wrapper, below tags fn —
            # hypothesis allows both orders and so must the shim
            n = int(getattr(wrapper, "_hl_max_examples",
                            getattr(fn, "_hl_max_examples", 20)))
            for ex in range(n):
                tag = f"{fn.__module__}.{fn.__qualname__}:{ex}"
                rng = np.random.RandomState(
                    zlib.crc32(tag.encode()) & 0xFFFFFFFF)
                vals = [s.sample(rng) for s in strats]
                try:
                    fn(*args, *vals, **kwargs)
                except Exception as e:  # noqa: BLE001 — annotate + re-raise
                    e.args = ((f"[hypothesis-lite example {ex}/{n}, "
                               f"drawn args: {vals!r}] {e.args[0] if e.args else ''}",)
                              + e.args[1:])
                    raise
        # pytest must not see the strategy-filled parameters as fixtures:
        # hide the wrapped signature (hypothesis does the same)
        del wrapper.__wrapped__
        import inspect
        wrapper.__signature__ = inspect.Signature()
        return wrapper
    return deco
