"""CascadeSVM tests (reference: test_csvm.py — SURVEY.md §5 oracle pattern:
accuracy vs sklearn SVC on the same data, convergence behavior, both
kernels)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.classification import CascadeSVM


def _two_blobs(rng, n=200, d=4, sep=4.0):
    a = rng.randn(n // 2, d).astype(np.float32)
    b = (rng.randn(n // 2, d) + sep).astype(np.float32)
    x = np.vstack([a, b])
    y = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
    p = rng.permutation(n)
    return x[p], y[p]


class TestCascadeSVM:
    @pytest.mark.parametrize("kernel", ["rbf", "linear"])
    def test_separable_blobs(self, rng, kernel):
        x, y = _two_blobs(rng)
        est = CascadeSVM(kernel=kernel, c=1.0, max_iter=5, random_state=0)
        est.fit(ds.array(x, block_size=(50, 4)), ds.array(y[:, None]))
        assert est.score(ds.array(x), ds.array(y[:, None])) >= 0.98
        assert est.support_vectors_count_ >= 2

    @pytest.mark.parametrize("kernel", ["rbf", "linear"])
    def test_accuracy_vs_sklearn(self, rng, kernel):
        from sklearn.svm import SVC
        x, y = _two_blobs(rng, n=160, d=3, sep=2.0)   # overlapping-ish
        est = CascadeSVM(kernel=kernel, c=1.0, max_iter=6, tol=1e-4,
                         random_state=0)
        est.fit(ds.array(x, block_size=(40, 3)), ds.array(y[:, None]))
        mine = est.score(ds.array(x), ds.array(y[:, None]))
        gamma = 1.0 / x.shape[1] if kernel == "rbf" else "scale"
        sk = SVC(kernel=kernel, C=1.0, gamma=gamma).fit(x, y).score(x, y)
        # K+1 bias augmentation ≠ libsvm's exact intercept: allow small slack
        assert mine >= sk - 0.05

    @pytest.mark.parametrize("kernel", ["rbf", "linear"])
    def test_fista_solver_matches_pg(self, rng, kernel, monkeypatch):
        """Round-5 solver policy (DSLIB_CSVM_SOLVER): accelerated PG must
        land on the same model as plain PG — same fixed point, same
        stopping rule, only the sequential-step count differs.  Pinned on
        dense AND on the objective/convergence surface."""
        x, y = _two_blobs(rng, n=160, d=3, sep=2.0)
        xa = ds.array(x, block_size=(40, 3))
        ya = ds.array(y[:, None])
        monkeypatch.setenv("DSLIB_CSVM_SOLVER", "pg")
        pg = CascadeSVM(kernel=kernel, c=1.0, max_iter=4, tol=1e-4,
                        random_state=0).fit(xa, ya)
        monkeypatch.setenv("DSLIB_CSVM_SOLVER", "fista")
        fi = CascadeSVM(kernel=kernel, c=1.0, max_iter=4, tol=1e-4,
                        random_state=0).fit(xa, ya)
        # near-total prediction agreement (not bit-exact: a decision value
        # near zero may legally flip between two optimizers stopped by a
        # step rule, so demand ≥ 99% rather than flake on numerics drift)
        agree = np.mean(np.asarray(pg.predict(xa).collect())
                        == np.asarray(fi.predict(xa).collect()))
        assert agree >= 0.99, f"solver prediction agreement {agree}"
        # decision surfaces agree to solver tolerance: identical
        # predictions/score are the pinned contract above; VALUES may
        # drift ~10% where plain PG hits its 500-step cap short of the
        # optimum FISTA reaches (PG's 1/k rate on an ill-conditioned Q) —
        # bound the drift without demanding sub-optimizer agreement
        pd_ = np.asarray(pg.decision_function(xa).collect()).ravel()
        fd_ = np.asarray(fi.decision_function(xa).collect()).ravel()
        rel = np.abs(pd_ - fd_) / np.maximum(np.abs(pd_), 1.0)
        assert np.quantile(rel, 0.95) < 0.2, np.sort(rel)[-5:]

    def test_decision_function_sign(self, rng):
        x, y = _two_blobs(rng, n=100, d=2)
        est = CascadeSVM(max_iter=3, random_state=0)
        est.fit(ds.array(x), ds.array(y[:, None]))
        dec = est.decision_function(ds.array(x)).collect().ravel()
        pred = est.predict(ds.array(x)).collect().ravel()
        assert np.array_equal(pred == est.classes_[1], dec > 0)

    def test_converges_and_reports(self, rng):
        x, y = _two_blobs(rng, n=120, d=3)
        est = CascadeSVM(max_iter=10, tol=1e-2, check_convergence=True,
                         random_state=0)
        est.fit(ds.array(x, block_size=(30, 3)), ds.array(y[:, None]))
        assert est.converged_
        assert est.n_iter_ <= 10

    def test_original_labels_preserved(self, rng):
        x, y = _two_blobs(rng, n=80, d=2)
        y_named = np.where(y > 0, 7.0, -3.0).astype(np.float32)
        est = CascadeSVM(max_iter=3, random_state=0)
        est.fit(ds.array(x), ds.array(y_named[:, None]))
        pred = est.predict(ds.array(x)).collect().ravel()
        assert set(np.unique(pred)) <= {-3.0, 7.0}
        assert np.array_equal(est.classes_, [-3.0, 7.0])

    def test_not_fitted_raises(self, rng):
        with pytest.raises(RuntimeError):
            CascadeSVM().decision_function(ds.array(rng.rand(4, 2)))

    def test_bad_kernel_and_multiclass(self, rng):
        x = ds.array(rng.rand(12, 2))
        y3 = ds.array(np.arange(12.0)[:, None] % 3)
        with pytest.raises(ValueError):
            CascadeSVM(kernel="poly").fit(x, y3)
        with pytest.raises(ValueError):
            CascadeSVM().fit(x, y3)


class TestSolveBatching:
    def test_batched_solve_is_invariant(self, rng, monkeypatch):
        """A tiny solve budget forces one-node batches; the cascade must
        produce the identical model (same partitions, same math)."""
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        x = rng.rand(120, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32).reshape(-1, 1)
        xa, ya = ds.array(x, block_size=(16, 4)), ds.array(y, block_size=(16, 1))
        ref = CascadeSVM(kernel="rbf", max_iter=2, random_state=0).fit(xa, ya)
        monkeypatch.setenv("DSLIB_CSVM_SOLVE_BUDGET", "1")
        batched = CascadeSVM(kernel="rbf", max_iter=2, random_state=0).fit(xa, ya)
        assert batched.support_vectors_count_ == ref.support_vectors_count_
        np.testing.assert_array_equal(batched._sv_idx, ref._sv_idx)
        np.testing.assert_allclose(batched._sv_alpha, ref._sv_alpha, rtol=1e-6)

    def test_default_blocks_partition_is_bounded(self, rng, monkeypatch):
        """With the mesh-default block size (m/p rows), level-0 partitions
        must still be capped — the accidental-quadratic-Gram guard."""
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.classification import csvm as csvm_mod
        monkeypatch.setenv("DSLIB_CSVM_MAX_PARTITION", "32")
        x = rng.rand(400, 4).astype(np.float32)
        y = (x[:, 0] + x[:, 1] > 1.0).astype(np.float32).reshape(-1, 1)
        xa, ya = ds.array(x), ds.array(y)   # default blocks: 400/8 = 50 > 32
        seen = []
        real = csvm_mod._solve_level_batched

        def spy(xv, yv, nodes, *a, **k):
            seen.append(nodes.shape)
            return real(xv, yv, nodes, *a, **k)

        monkeypatch.setattr(csvm_mod, "_solve_level_batched", spy)
        model = CascadeSVM(kernel="linear", max_iter=1).fit(xa, ya)
        assert seen[0][1] <= 64, f"level-0 cap {seen[0][1]} not bounded"
        assert model.score(xa, ya) > 0.9


class TestSparseNative:
    def _blobs(self, rng, m=240, nf=40):
        x = np.zeros((m, nf), np.float32)
        half = m // 2
        for i in range(m):
            feats = rng.choice(nf // 2, 4, replace=False) \
                + (0 if i < half else nf // 2)
            x[i, feats] = 1.0 + rng.rand(4).astype(np.float32)
        y = np.r_[np.zeros(half), np.ones(half)].astype(np.float32)
        p = rng.permutation(m)
        return x[p], y[p]

    @pytest.mark.parametrize("kern", ["rbf", "linear"])
    def test_matches_dense_path(self, rng, kern):
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.data.sparse import SparseArray
        x, yv = self._blobs(rng)
        xd = ds.array(x, block_size=(48, x.shape[1]))
        xs = SparseArray.from_scipy(sp.csr_matrix(x),
                                    block_size=(48, x.shape[1]))
        ya = ds.array(yv.reshape(-1, 1))
        md = CascadeSVM(kernel=kern, max_iter=2,
                        check_convergence=False).fit(xd, ya)
        ms = CascadeSVM(kernel=kern, max_iter=2,
                        check_convergence=False).fit(xs, ya)
        np.testing.assert_array_equal(ms.predict(xs).collect(),
                                      md.predict(xd).collect())
        # a fitted-on-sparse model also classifies dense queries (and
        # vice versa) identically
        np.testing.assert_array_equal(ms.predict(xd).collect(),
                                      ms.predict(xs).collect())
        assert ms.score(xs, ya) == 1.0

    def test_never_densifies(self, rng, monkeypatch):
        """Fit + predict on SparseArray must not touch the dense escape
        hatch at all (the whole point of the sparse-native path)."""
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.data import sparse as sparse_mod
        x, yv = self._blobs(rng, m=120)
        xs = sparse_mod.SparseArray.from_scipy(sp.csr_matrix(x))
        ya = ds.array(yv.reshape(-1, 1))

        def boom(self):
            raise AssertionError("sparse CSVM touched the dense escape hatch")

        monkeypatch.setattr(sparse_mod.SparseArray, "_data", property(boom))
        model = CascadeSVM(kernel="rbf", max_iter=1).fit(xs, ya)
        assert model.predict(xs).collect().shape == (120, 1)

    def test_ell_staging_is_default_and_device_resident(self, rng,
                                                        monkeypatch):
        """The sparse fit must go through the device ELL staging (round-4):
        no host kernel product — `_host_gram` never called."""
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.classification import csvm as csvm_mod
        from dislib_tpu.data.sparse import SparseArray
        x, yv = self._blobs(rng, m=120)
        xs = SparseArray.from_scipy(sp.csr_matrix(x))
        ya = ds.array(yv.reshape(-1, 1))

        def boom(*a, **k):
            raise AssertionError("sparse CSVM staged a host-CSR sub-Gram "
                                 "on the ELL path")

        monkeypatch.setattr(csvm_mod, "_host_gram", boom)
        model = CascadeSVM(kernel="rbf", max_iter=1).fit(xs, ya)
        assert model.score(xs, ya) > 0.9

    def test_ell_budget_fallback_matches(self, rng, monkeypatch):
        """Past the ELL byte budget (row-nnz skew guard) the fit falls back
        to host-CSR staging and lands on the same model."""
        import scipy.sparse as sp
        import dislib_tpu as ds
        from dislib_tpu.classification import CascadeSVM
        from dislib_tpu.data.sparse import SparseArray
        x, yv = self._blobs(rng, m=120)
        ya = ds.array(yv.reshape(-1, 1))

        xs1 = SparseArray.from_scipy(sp.csr_matrix(x))
        m1 = CascadeSVM(kernel="rbf", max_iter=2,
                        check_convergence=False).fit(xs1, ya)
        monkeypatch.setenv("DSLIB_SPARSE_ELL_BUDGET", "16")
        xs2 = SparseArray.from_scipy(sp.csr_matrix(x))
        assert xs2.ell() is None            # the guard actually tripped
        m2 = CascadeSVM(kernel="rbf", max_iter=2,
                        check_convergence=False).fit(xs2, ya)
        np.testing.assert_array_equal(m1.predict(xs1).collect(),
                                      m2.predict(xs1).collect())
        # the two stagings compute the same Gram through different float
        # paths (device scatter+GEMM vs scipy spGEMM) — borderline alphas
        # at the 1e-8 SV threshold may flip, so the SV sets are compared
        # up to a small symmetric difference, with identical predictions
        # already pinned above
        diff = set(m1._sv_idx.tolist()) ^ set(m2._sv_idx.tolist())
        assert len(diff) <= max(3, len(m1._sv_idx) // 50), \
            f"SV sets diverge by {len(diff)} vectors"


def test_raised_refit_does_not_poison_the_previous_model(rng, tmp_path):
    """A refit that ends in a typed raise (budget spent, no rollback
    target) must leave the previously fitted attributes untouched — the
    per-iteration SV updates are deferred behind the health verdict
    (review-found, pinned)."""
    import numpy as np
    import pytest
    import dislib_tpu as ds
    from dislib_tpu.classification import CascadeSVM
    from dislib_tpu.runtime import NumericalDivergence
    from dislib_tpu.utils import faults

    n = 120
    xh = np.vstack([rng.randn(n // 2, 4) - 2,
                    rng.randn(n // 2, 4) + 2]).astype(np.float32)
    yh = np.r_[np.zeros(n // 2), np.ones(n // 2)].astype(np.float32)
    sh = rng.permutation(n)
    x, y = ds.array(xh[sh]), ds.array(yh[sh].reshape(-1, 1))
    kw = dict(cascade_arity=2, c=1.0, kernel="rbf", gamma=0.3,
              check_convergence=False, max_iter=4)
    est = CascadeSVM(**kw).fit(x, y)
    alpha0, idx0 = est._sv_alpha.copy(), est._sv_idx.copy()
    with pytest.raises(NumericalDivergence):
        est.fit(x, y, health=faults.TripAtChunk(at_chunk=1, times=10))
    np.testing.assert_array_equal(est._sv_alpha, alpha0)
    np.testing.assert_array_equal(est._sv_idx, idx0)
    assert np.isfinite(est.decision_function(x).collect()).all()
